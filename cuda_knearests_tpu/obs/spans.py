"""Structured span tracer: one event schema for every timing in the engine.

The repo's timing was fragmented before this layer existed: ``dispatch``
counted syncs beside the solve, bench rows carried wall stamps, the serve
tier kept ad-hoc latency lists, and ``jax.profiler`` traces showed
anonymous jit regions.  This module is the one vocabulary they all speak:

* :func:`span` -- a nested, attributed timing region with a STABLE event
  schema (:data:`SCHEMA`): name, wall-anchored t0, dur_ms, nesting depth +
  parent, (pid, process job tag), thread, optional ``trace_id``, attrs.
* **near-zero cost when disabled**: tracing is off unless a sink is
  registered (or the caller forces a span for its own timing).  The
  disabled fast path allocates NOTHING -- ``span()`` returns one shared
  no-op singleton -- so instrumenting a hot path costs one truthiness
  check on :data:`_sinks` plus a call.
* **sinks** are plain callables fed one finished-event dict each; the
  flight recorder (obs/recorder.py), the in-memory :class:`Collector`,
  and the :class:`JsonlSink` trace spill are all sinks.  A sink that
  raises is ignored: observability must never take the engine down.
* **cross-process stitching**: every event carries ``pid`` and the
  process ``job`` tag (:func:`set_process_tag` -- supervisor workers and
  fleet replicas tag themselves), timestamps are anchored to the wall
  clock, and obs/export.py merges per-process ``.jsonl`` spills into one
  Chrome-trace timeline loadable in Perfetto.
* ``trace_id`` rides a request end to end: the serve wire carries it,
  the daemon stamps it on queue/execute spans, and the reply echoes it --
  which is what lets fleet bench rows decompose p99 into
  queue/dispatch/device components (DESIGN.md section 19).

No jax import: infrastructure (watchdog, supervisor, worker entry) must
be able to arm tracing before any backend exists.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: Event schema version (the ``v`` key of every event); bump on any key
#: change -- obs/export.py and the flight-recorder consumers key on it.
SCHEMA = 1

# Wall anchor: perf_counter gives monotonic durations, the anchor maps its
# axis onto wall-clock seconds so events from different processes land on
# one mergeable timeline.
_ANCHOR_WALL = time.time()
_ANCHOR_PERF = time.perf_counter()

_lock = threading.Lock()
_sinks: List[Callable[[dict], None]] = []   # empty == tracing disabled
_tls = threading.local()
_proc_tag: Dict[str, Any] = {"job": ""}


def now() -> float:
    """The tracer's clock (``perf_counter``): the ONE sanctioned timing
    source for code the bare-timing lint rule covers (serve/, runtime/)."""
    return time.perf_counter()


def wall(t_perf: float) -> float:
    """Wall-clock seconds of a :func:`now` timestamp (the cross-process
    merge axis)."""
    return _ANCHOR_WALL + (t_perf - _ANCHOR_PERF)


def enabled() -> bool:
    return bool(_sinks)


def add_sink(sink: Callable[[dict], None]) -> None:
    with _lock:
        if sink not in _sinks:
            _sinks.append(sink)


def remove_sink(sink: Callable[[dict], None]) -> None:
    with _lock:
        if sink in _sinks:
            _sinks.remove(sink)


def set_process_tag(job: str) -> None:
    """Tag every subsequent event of THIS process with a job label --
    supervisor workers use ``worker:<label>``, fleet replica children
    ``replica:<pid>`` -- the (pid, job) pair export.py renders as the
    Perfetto process name."""
    _proc_tag["job"] = str(job)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def set_trace_id(trace_id: Optional[str]) -> None:
    """Thread-local default ``trace_id`` for spans that don't carry an
    explicit one (the serve request lifecycle sets it per request)."""
    _tls.trace_id = trace_id


def current_trace_id() -> Optional[str]:
    return getattr(_tls, "trace_id", None)


def _feed(event: dict) -> None:
    for sink in list(_sinks):
        try:
            sink(event)
        except Exception:  # noqa: BLE001 -- a broken sink must never take the engine down; tracing is best-effort by contract
            pass


class _NullSpan:
    """The disabled fast path: one shared, allocation-free no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self

    t0 = 0.0
    t1 = 0.0

    @property
    def dur_ms(self) -> float:
        return 0.0


_NULL = _NullSpan()


class Span:
    """One live span (use via ``with``).  After exit, ``t0``/``t1``/
    ``dur_ms`` stay readable -- the serve decomposition reads them even
    when no sink is listening (``force=True``)."""

    __slots__ = ("name", "attrs", "trace_id", "t0", "t1", "_parent",
                 "_depth")

    def __init__(self, name: str, attrs: Dict[str, Any],
                 trace_id: Optional[str]):
        self.name = name
        self.attrs = attrs
        self.trace_id = trace_id
        self.t0 = 0.0
        self.t1 = 0.0
        self._parent = ""
        self._depth = 0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3

    def __enter__(self) -> "Span":
        st = _stack()
        self._parent = st[-1] if st else ""
        self._depth = len(st)
        st.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        self.t1 = time.perf_counter()
        st = _stack()
        if st and st[-1] == self.name:
            st.pop()
        if et is not None:
            self.attrs["error"] = et.__name__
        if _sinks:
            _feed(self._event())
        return False

    def _event(self) -> dict:
        return {"v": SCHEMA, "kind": "span", "name": self.name,
                "t0": wall(self.t0), "dur_ms": round(self.dur_ms, 6),
                "depth": self._depth, "parent": self._parent,
                "pid": os.getpid(), "job": _proc_tag["job"],
                "tid": threading.current_thread().name,
                "trace_id": (self.trace_id if self.trace_id is not None
                             else current_trace_id()),
                "attrs": self.attrs}


def span(name: str, force: bool = False, trace_id: Optional[str] = None,
         **attrs):
    """Open a span.  Disabled (no sinks) and unforced: returns the shared
    no-op singleton -- no allocation, no timing.  ``force=True`` times the
    region regardless (the serve decomposition's always-on stopwatch),
    feeding sinks only when some are registered."""
    if not _sinks and not force:
        return _NULL
    return Span(name, attrs, trace_id)


def emit(name: str, t0: float, t1: float, trace_id: Optional[str] = None,
         **attrs) -> None:
    """Record a RETROSPECTIVE span from two :func:`now` timestamps -- for
    intervals that cannot be a ``with`` block (a request's queue wait ends
    inside the executor, not where it began).  No-op when disabled."""
    if not _sinks:
        return
    _feed({"v": SCHEMA, "kind": "span", "name": name, "t0": wall(t0),
           "dur_ms": round((t1 - t0) * 1e3, 6),
           "depth": len(_stack()), "parent": "", "pid": os.getpid(),
           "job": _proc_tag["job"],
           "tid": threading.current_thread().name,
           "trace_id": trace_id, "attrs": attrs})


def event(name: str, trace_id: Optional[str] = None, **attrs) -> None:
    """Record an instant event (dur 0).  No-op when disabled."""
    if not _sinks:
        return
    t = time.perf_counter()
    _feed({"v": SCHEMA, "kind": "event", "name": name, "t0": wall(t),
           "dur_ms": 0.0, "depth": len(_stack()), "parent": "",
           "pid": os.getpid(), "job": _proc_tag["job"],
           "tid": threading.current_thread().name,
           "trace_id": trace_id, "attrs": attrs})


class Collector:
    """In-memory sink: appends every event to ``self.events``."""

    def __init__(self) -> None:
        self.events: List[dict] = []

    def __call__(self, ev: dict) -> None:
        self.events.append(ev)


class capture:
    """``with capture() as events:`` -- collect every event inside the
    block (the obs smoke and the tests run solves under this)."""

    def __enter__(self) -> List[dict]:
        self._col = Collector()
        add_sink(self._col)
        return self._col.events

    def __exit__(self, *exc) -> None:
        remove_sink(self._col)


class JsonlSink:
    """File sink: one JSON line per event, flushed per line so the spill
    survives a SIGKILL (the data is in the kernel after flush).  This is
    the per-process trace file obs/export.py merges."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def __call__(self, ev: dict) -> None:
        self._f.write(json.dumps(ev) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            remove_sink(self)
            self._f.close()
        except Exception:  # noqa: BLE001 -- closing a trace sink is best-effort teardown
            pass


def start_file_trace(path: str) -> JsonlSink:
    """Open + register a :class:`JsonlSink`; returns it (call ``close()``
    to stop)."""
    sink = JsonlSink(path)
    add_sink(sink)
    return sink


def start_file_trace_from_env(tag: str = "") -> Optional[JsonlSink]:
    """When ``KNTPU_TRACE_DIR`` is set, start spilling this process's
    spans into ``<dir>/trace_<tag>_<pid>.jsonl`` (the export-mergeable
    naming).  Workers and the serve/bench mains call this so one exported
    env var turns on whole-run tracing across every child."""
    d = os.environ.get("KNTPU_TRACE_DIR", "")
    if not d:
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "-"
                   for c in (tag or "proc"))
    return start_file_trace(
        os.path.join(d, f"trace_{safe}_{os.getpid()}.jsonl"))


def validate_event(ev: dict) -> Optional[str]:
    """Schema check of one event dict: returns None when well-formed,
    else a one-line reason (the obs smoke gates on this)."""
    required = ("v", "kind", "name", "t0", "dur_ms", "depth", "parent",
                "pid", "job", "tid", "trace_id", "attrs")
    for key in required:
        if key not in ev:
            return f"missing key {key!r}"
    if ev["v"] != SCHEMA:
        return f"schema version {ev['v']!r} != {SCHEMA}"
    if ev["kind"] not in ("span", "event", "metrics"):
        return f"unknown kind {ev['kind']!r}"
    if not isinstance(ev["name"], str) or not ev["name"]:
        return "empty name"
    if not isinstance(ev["dur_ms"], (int, float)) or ev["dur_ms"] < 0:
        return f"negative dur_ms {ev['dur_ms']!r}"
    if not isinstance(ev["depth"], int) or ev["depth"] < 0:
        return f"bad depth {ev['depth']!r}"
    if not isinstance(ev["attrs"], dict):
        return "attrs not a dict"
    return None
