"""kntpu-trace: unified observability (DESIGN.md section 19).

Four pieces, one event vocabulary:

* :mod:`.spans`   -- structured span tracer: nested, attributed timing
  regions with a stable schema, near-zero cost when disabled, stitched
  across processes by (pid, job) tags and wall-anchored timestamps.
* :mod:`.metrics` -- counters/gauges/fixed-bucket histograms and the one
  unified snapshot (``metrics`` wire command, ``--metrics-jsonl``).
* :mod:`.recorder` -- the flight recorder: a bounded ring of recent
  spans + metric deltas, spilled line-flushed so a SIGKILLed worker's
  last milliseconds land in the failure artifact.
* :mod:`.export`  -- merge per-process trace spills into one Chrome
  trace-event JSON (Perfetto-loadable).
* :mod:`.device` / :mod:`.attribution` (kntpu-scope, DESIGN.md
  section 20) -- programmatic ``jax.profiler`` capture scoped to a
  solve window, device-event attribution to spans/scopes/signatures,
  and the measured-HBM verdict.  NOT imported here: ``device`` touches
  jax lazily and both load on demand, preserving this package's
  import-before-any-backend contract.

``python -m cuda_knearests_tpu.obs`` runs the CPU smoke: capture a 20k
solve trace, validate the schema, bound the disabled-mode overhead, and
write the merged Perfetto trace + a metrics snapshot as artifacts.

The package imports no jax: infrastructure (watchdog, worker entry,
supervisor) arms tracing before any backend exists.
"""

from . import metrics, recorder, spans
from .metrics import REGISTRY, Histogram, metrics_snapshot
from .recorder import FLIGHT
from .spans import capture, emit, event, set_process_tag, span

__all__ = [
    "FLIGHT",
    "Histogram",
    "REGISTRY",
    "capture",
    "emit",
    "event",
    "metrics",
    "metrics_snapshot",
    "recorder",
    "set_process_tag",
    "span",
    "spans",
]
