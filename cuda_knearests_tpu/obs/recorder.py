"""Flight recorder: the last milliseconds of a dying process, bounded.

A SIGKILLed worker (libtpu abort, the supervisor's row timeout, the
failover drill's deliberate kill) used to leave nothing but an exit code
and a stderr tail.  The recorder keeps a BOUNDED in-memory ring of recent
span events plus metric deltas and -- when armed with a spill path --
mirrors every event to a line-flushed ``.jsonl`` file, so the evidence
survives even a kill the process never sees:

* the ring feeds the watchdog's stall artifact (utils/watchdog.py dumps
  ``FLIGHT.dump()`` next to the faulthandler tracebacks), and
* the spill feeds the supervisor: on any worker failure it reads the
  file's tail into ``FailureRecord.flight_tail``, so a crash-injected
  bench row's failure artifact reconstructs the killed worker's last
  >= 32 spans (the ISSUE 13 acceptance pin, tests/test_obs.py).

Fault injection: :meth:`FlightRecorder.kill_after_events` arms a
deterministic mid-flight SIGKILL after the N-th recorded event -- the
``KNTPU_FAULT=abort-after:<label>:<n>`` hook (runtime/worker.py), which is
how the spill-survives-SIGKILL property is tested without hardware.

No jax import (armed by the worker entry before any backend exists).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import IO, Deque, List, Optional

from . import spans as _spans

#: Default ring capacity (events).  Generous for "last milliseconds":
#: a serve batch emits ~3 spans, so 256 events cover ~85 batches.
DEFAULT_CAPACITY = 256

#: Spill-path env var: the supervisor points each worker attempt at its
#: own file, then harvests the tail on failure.
FLIGHT_FILE_ENV = "KNTPU_FLIGHT_FILE"


class FlightRecorder:
    """Bounded ring of recent events; optionally spilled to a jsonl file
    (line-flushed: survives SIGKILL).  Registers itself as a spans sink
    when armed, so every span/event in the process lands here."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self.events: Deque[dict] = deque(maxlen=self.capacity)
        self.recorded = 0
        self.tag = ""
        self.armed = False
        self._lock = threading.Lock()
        self._spill: Optional[IO[str]] = None
        self._spill_path: Optional[str] = None
        self._kill_after: Optional[int] = None
        self._metric_base: dict = {}

    # -- lifecycle ----------------------------------------------------------

    def arm(self, tag: str = "", spill_path: Optional[str] = None,
            capacity: Optional[int] = None) -> "FlightRecorder":
        """Start recording (idempotent): register as a spans sink, open
        the spill file when given one, and drop a ``recorder.arm``
        marker event so even an immediately-wedged process leaves at
        least one record."""
        with self._lock:
            self.tag = tag or self.tag
            if capacity and capacity != self.capacity:
                self.capacity = int(capacity)
                self.events = deque(self.events, maxlen=self.capacity)
            if spill_path and spill_path != self._spill_path:
                if self._spill is not None:
                    try:
                        self._spill.close()
                    except OSError:
                        pass
                d = os.path.dirname(spill_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._spill = open(spill_path, "a", encoding="utf-8")
                self._spill_path = spill_path
            self.armed = True
        _spans.add_sink(self)
        self._metric_base = self._dispatch_counters()
        self.record({"v": _spans.SCHEMA, "kind": "event",
                     "name": "recorder.arm", "t0": time.time(),
                     "dur_ms": 0.0, "depth": 0, "parent": "",
                     "pid": os.getpid(), "job": tag, "tid": "main",
                     "trace_id": None, "attrs": {"tag": tag}})
        return self

    def disarm(self) -> None:
        _spans.remove_sink(self)
        with self._lock:
            self.armed = False
            if self._spill is not None:
                try:
                    self._spill.close()
                except OSError:
                    pass
                self._spill = None
                self._spill_path = None

    # -- recording ----------------------------------------------------------

    def __call__(self, event: dict) -> None:
        self.record(event)

    def record(self, event: dict) -> None:
        kill = False
        with self._lock:
            if not self.armed:
                return
            self.events.append(event)
            self.recorded += 1
            if self._spill is not None:
                try:
                    self._spill.write(json.dumps(event) + "\n")
                    self._spill.flush()
                except (OSError, TypeError, ValueError):
                    pass          # spill is best-effort; the ring survives
            kill = (self._kill_after is not None
                    and self.recorded >= self._kill_after)
        if kill:
            # the abort-after fault: die exactly as hard as libtpu would
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    @staticmethod
    def _dispatch_counters() -> dict:
        try:
            from ..runtime import dispatch as _dispatch

            return dict(_dispatch.stats_dict())
        except Exception:  # noqa: BLE001 -- the recorder must work before/without the dispatch layer
            return {}

    def metric_delta(self) -> dict:
        """Record (and return) the dispatch-counter delta since the last
        call -- the ``spans+metric deltas`` half of the ring's contract.
        Cheap; phase boundaries and the watchdog trip path call it."""
        now_c = self._dispatch_counters()
        delta = {k: now_c.get(k, 0) - self._metric_base.get(k, 0)
                 for k in now_c}
        self._metric_base = now_c
        ev = {"v": _spans.SCHEMA, "kind": "metrics",
              "name": "dispatch.delta", "t0": time.time(), "dur_ms": 0.0,
              "depth": 0, "parent": "", "pid": os.getpid(),
              "job": self.tag, "tid": "main", "trace_id": None,
              "attrs": delta}
        self.record(ev)
        return ev

    def kill_after_events(self, n: int) -> None:
        """Arm the deterministic mid-flight SIGKILL (fault injection):
        the process dies upon recording its ``n``-th event, counted from
        process start."""
        with self._lock:
            self._kill_after = max(1, int(n))

    # -- reading ------------------------------------------------------------

    def tail(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            evs = list(self.events)
        return evs if n is None else evs[-int(n):]

    def dump(self) -> dict:
        """The crash-artifact document: ring tail + drop accounting +
        one final metric delta."""
        with self._lock:
            dropped = max(0, self.recorded - len(self.events))
        return {"v": _spans.SCHEMA, "tag": self.tag, "pid": os.getpid(),
                "recorded": self.recorded, "dropped": dropped,
                "events": self.tail()}


#: The process-wide recorder (one per process by design: the (pid, tag)
#: pair identifies it across the merged artifact).
FLIGHT = FlightRecorder()


def arm(tag: str = "", spill_path: Optional[str] = None,
        capacity: Optional[int] = None) -> FlightRecorder:
    """Arm the process-wide recorder.  ``spill_path`` defaults to the
    supervisor-provided ``KNTPU_FLIGHT_FILE`` env var."""
    if spill_path is None:
        spill_path = os.environ.get(FLIGHT_FILE_ENV) or None
    return FLIGHT.arm(tag=tag, spill_path=spill_path, capacity=capacity)


def read_spill_tail(path: str, n: int = 64) -> List[dict]:
    """Last ``n`` well-formed events of a spill file (the supervisor's
    harvest on worker failure).  Missing/corrupt files yield []."""
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return []
    out: List[dict] = []
    for line in lines[-int(n):]:
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue          # a half-written final line (killed mid-write)
        if isinstance(ev, dict):
            out.append(ev)
    return out
