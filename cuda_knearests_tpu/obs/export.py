"""Trace export: merge per-process span spills into one Perfetto timeline.

Every process that traces (the bench driver, each supervised worker, each
fleet replica child) spills its events to its own
``trace_<tag>_<pid>.jsonl`` (spans.start_file_trace_from_env).  This
module stitches them:

* :func:`load_jsonl` -- one spill file -> validated event list.
* :func:`merge` -- many files -> one time-sorted event list (events
  already carry (pid, job), so nothing needs rewriting).
* :func:`to_chrome` -- events -> a Chrome trace-event JSON document
  (``traceEvents`` with complete 'X' events + 'M' process-name metadata),
  loadable in Perfetto / chrome://tracing.

CLI: ``python -m cuda_knearests_tpu.obs.export --dir TRACEDIR --out
trace.json`` (also reachable via ``python -m cuda_knearests_tpu.obs
--export ...``); prints a one-line JSON summary.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

from . import spans as _spans


def load_jsonl(path: str) -> List[dict]:
    """Events of one spill file; malformed lines are skipped (a killed
    writer may leave a torn final line), schema-invalid events too."""
    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(ev, dict) \
                        and _spans.validate_event(ev) is None:
                    out.append(ev)
    except OSError:
        return []
    return out


def merge(paths: Iterable[str]) -> List[dict]:
    """One time-sorted event list across all files (the wall-anchored
    ``t0`` is the shared axis)."""
    events: List[dict] = []
    for p in paths:
        events.extend(load_jsonl(p))
    events.sort(key=lambda ev: ev.get("t0", 0.0))
    return events


def trace_files(trace_dir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(trace_dir, "trace_*.jsonl")))


def to_chrome(events: List[dict]) -> dict:
    """Chrome trace-event form: complete ('X') events on a microsecond
    axis rebased to the earliest event, one process-name metadata record
    per (pid, job)."""
    t_base = min((ev["t0"] for ev in events), default=0.0)
    out: List[dict] = []
    seen_procs: Dict[int, str] = {}
    for ev in events:
        pid = int(ev.get("pid", 0))
        job = str(ev.get("job", "") or "")
        if pid not in seen_procs:
            seen_procs[pid] = job
            out.append({"ph": "M", "name": "process_name", "pid": pid,
                        "tid": 0,
                        "args": {"name": job or f"pid {pid}"}})
        args = dict(ev.get("attrs") or {})
        if ev.get("trace_id") is not None:
            args["trace_id"] = ev["trace_id"]
        out.append({
            "name": ev["name"],
            "ph": "X" if ev.get("kind") == "span" else "i",
            "ts": round((ev["t0"] - t_base) * 1e6, 3),
            "dur": round(float(ev.get("dur_ms", 0.0)) * 1e3, 3),
            "pid": pid,
            "tid": str(ev.get("tid", "main")),
            "args": args,
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_dir(trace_dir: str, out_path: str) -> dict:
    """Merge every spill under ``trace_dir`` into ``out_path`` (Chrome
    JSON); returns a summary dict."""
    files = trace_files(trace_dir)
    events = merge(files)
    chrome = to_chrome(events)
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(chrome, f)
    return {"trace_dir": trace_dir, "files": len(files),
            "events": len(events),
            "pids": len({ev.get("pid") for ev in events}),
            "out": out_path}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuda_knearests_tpu.obs.export",
        description=__doc__.splitlines()[0])
    ap.add_argument("--dir", required=True,
                    help="directory of trace_*.jsonl spills "
                         "(KNTPU_TRACE_DIR)")
    ap.add_argument("--out", default=None,
                    help="merged Chrome-trace output path (default "
                         "<dir>/trace_merged.json)")
    args = ap.parse_args(argv)
    out = args.out or os.path.join(args.dir, "trace_merged.json")
    summary = export_dir(args.dir, out)
    print(json.dumps(summary), flush=True)
    return 0 if summary["files"] else 1


if __name__ == "__main__":
    sys.exit(main())
