"""Device-event attribution: captured profiler events -> signatures/spans.

``obs/device.py`` captures a ``jax.profiler`` trace scoped to one solve
window.  This module is the pure-parsing half (no jax import -- the
capture product is a gzipped Chrome trace-event document, and parsing it
must work in processes that never initialize a backend):

* :func:`load_chrome_trace` / :func:`chrome_events` -- read the capture.
* :func:`rebase` -- map the profiler's private microsecond axis onto the
  span tracer's wall-clock axis via the capture-anchor annotation
  (``kntpu.capture:<id>``) whose host-side wall time the capturer
  recorded, and classify every event:

    - ``exec``   -- an executable/op event (carries ``hlo_module`` /
      ``hlo_op`` args): actual device compute, the thing we attribute.
    - ``scope``  -- a ``profiling.annotate`` named region (the
      ``kntpu:*`` scopes the routes already emit).
    - ``anchor`` -- the capture window annotation itself.
    - ``other``  -- profiler plumbing (ignored by attribution).

* :data:`MODULE_REGISTRY` / :func:`register_executable` -- the
  hlo-module -> executable-signature join: ``runtime.dispatch``'s
  ExecutableCache registers every AOT build here (module name, cache-key
  label, compile wall seconds, ``cost_analysis()`` flops/bytes), so a
  captured ``hlo_module`` resolves to the executable signature that
  compiled it.
* :func:`attribute` -- mount each exec event into the host span
  timeline: the innermost host span containing the event's midpoint
  (deepest, then latest-started -- unique by span nesting), plus the
  innermost named scope and the registry signature.  Events no span
  covers come back as ``unattributed`` -- the capture harness asserts
  that count is ZERO (its umbrella window span guarantees coverage).
* :func:`decomposition` -- the ``device_time_decomposition`` bench
  stamp: device ms by module / scope / span, with per-module compile
  seconds and achieved GFLOP/s where the registry knows the cost.
* :func:`mount` / :func:`write_spill` -- re-express attributed events in
  the span event schema (obs/spans.py) so ``obs/export.py`` merges them
  into ONE host+device Perfetto timeline (device events ride a
  ``device:*`` thread lane of the capturing process).
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from . import spans as _spans

#: Prefix of the capture-window anchor annotation (obs/device.py opens a
#: ``TraceAnnotation(CAPTURE_PREFIX + capture_id)`` around the window and
#: records its host wall time -- the affine clock join).
CAPTURE_PREFIX = "kntpu.capture:"

#: Prefix of the engine's named profiler scopes (utils/profiling.annotate
#: call sites: ``kntpu:adaptive-solve``, ``kntpu:halo-exchange``, ...).
SCOPE_PREFIX = "kntpu:"

#: Safety margin (seconds) when window-filtering exec events: profiler
#: event close timestamps can trail the anchor exit by scheduler noise.
WINDOW_EPS_S = 0.050


# -- executable registry (the compile-observability join) ---------------------

_REG_LOCK = threading.Lock()
#: hlo module name -> {"module", "label", "compile_s", "flops",
#: "bytes_accessed"}: fed by ExecutableCache.get_or_build at compile time,
#: read by attribution when a captured event carries that module name.
MODULE_REGISTRY: Dict[str, dict] = {}


def register_executable(module: Optional[str], label: str = "",
                        compile_s: Optional[float] = None,
                        flops: Optional[float] = None,
                        bytes_accessed: Optional[float] = None) -> None:
    """Record one compiled executable's identity + cost census.  Keyed by
    the XLA module name because that is exactly what captured device
    events carry (``args.hlo_module``)."""
    if not module:
        return
    with _REG_LOCK:
        ent = MODULE_REGISTRY.setdefault(str(module), {"module": str(module)})
        if label:
            ent["label"] = str(label)
        if compile_s is not None:
            ent["compile_s"] = round(float(compile_s), 6)
        if flops is not None:
            ent["flops"] = float(flops)
        if bytes_accessed is not None:
            ent["bytes_accessed"] = float(bytes_accessed)


def executable_info(module: Optional[str]) -> Optional[dict]:
    if not module:
        return None
    with _REG_LOCK:
        ent = MODULE_REGISTRY.get(module)
        return dict(ent) if ent is not None else None


# -- capture parsing ----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DeviceEvent:
    """One captured profiler event, rebased onto the span wall axis."""

    name: str
    t0: float          # wall seconds (same axis as span events' ``t0``)
    dur_ms: float
    pid: int
    tid: str
    kind: str          # 'exec' | 'scope' | 'anchor' | 'other'
    hlo_module: Optional[str] = None
    hlo_op: Optional[str] = None

    @property
    def t1(self) -> float:
        return self.t0 + self.dur_ms / 1e3

    @property
    def midpoint(self) -> float:
        return self.t0 + self.dur_ms / 2e3


def load_chrome_trace(path: str) -> dict:
    """A capture's Chrome trace-event document (gzipped or plain JSON)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:  # type: ignore[operator]
        return json.loads(f.read().decode("utf-8"))


def chrome_events(doc: dict) -> List[dict]:
    """The complete ('X') events of a Chrome trace document."""
    return [ev for ev in doc.get("traceEvents", [])
            if isinstance(ev, dict) and ev.get("ph") == "X"]


def _full_name(raw: dict) -> str:
    """An event's full name: the trace exporter splits ``prefix:rest``
    annotation names into (category, short name) and parks the original
    under ``args.long_name`` -- the ``kntpu:*`` scopes and the capture
    anchor must match either spelling."""
    args = raw.get("args") or {}
    return str(args.get("long_name") or raw.get("name", ""))


#: Host-side launch events ("PjitFunction(<fn>)") recorded by the
#: profiler's python tracer: the launch-order join maps the module they
#: dispatch ("jit_<fn>") onto the named scope the launch ran under, so
#: ASYNC executions -- compute landing after the dispatching scope closed
#: -- still attribute to the scope that launched them.
_LAUNCH_PREFIX = "PjitFunction("


def _classify(raw: dict) -> Tuple[str, Optional[str], Optional[str]]:
    args = raw.get("args") or {}
    name = _full_name(raw)
    if name.startswith(CAPTURE_PREFIX):
        return "anchor", None, None
    module = args.get("hlo_module")
    if module:
        return "exec", str(module), (str(args["hlo_op"])
                                     if args.get("hlo_op") else None)
    if name.startswith(SCOPE_PREFIX):
        return "scope", None, None
    if name.startswith(_LAUNCH_PREFIX):
        return "launch", None, None
    return "other", None, None


def rebase(raw_events: List[dict], anchor_wall: float,
           capture_id: str) -> Tuple[List[DeviceEvent], int]:
    """(window events on the wall axis, count dropped as outside-window).

    The anchor annotation ``kntpu.capture:<capture_id>`` appears in the
    capture at its own profiler timestamp; the capturer recorded the host
    wall clock at the instant it opened that annotation.  The offset
    between the two joins the axes (one shared host clock family -- the
    drift over a solve window is far below event durations).  Exec/scope
    events whose midpoint falls outside the anchor interval (work from
    before the window that the profiler session still saw) are dropped
    and counted, never silently attributed.
    """
    anchor_name = CAPTURE_PREFIX + capture_id
    anchor = next((ev for ev in raw_events
                   if _full_name(ev) == anchor_name), None)
    if anchor is None:
        raise ValueError(
            f"capture anchor {anchor_name!r} not found in the trace "
            f"({len(raw_events)} events): the profiler did not record "
            f"the window annotation")
    a_ts = float(anchor["ts"])
    a_dur_s = float(anchor.get("dur", 0.0)) / 1e6
    lo = anchor_wall - WINDOW_EPS_S
    hi = anchor_wall + a_dur_s + WINDOW_EPS_S
    out: List[DeviceEvent] = []
    outside = 0
    for raw in raw_events:
        kind, module, op = _classify(raw)
        t0 = anchor_wall + (float(raw.get("ts", 0.0)) - a_ts) / 1e6
        dur_ms = float(raw.get("dur", 0.0)) / 1e3
        ev = DeviceEvent(name=_full_name(raw), t0=t0,
                         dur_ms=dur_ms, pid=int(raw.get("pid", 0)),
                         tid=str(raw.get("tid", "")), kind=kind,
                         hlo_module=module, hlo_op=op)
        if kind in ("exec", "scope", "launch") \
                and not (lo <= ev.midpoint <= hi):
            outside += 1
            continue
        out.append(ev)
    return out, outside


# -- attribution --------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Attribution:
    """One exec event mounted into the host timeline."""

    event: DeviceEvent
    span_name: str
    span_depth: int
    trace_id: Optional[str]
    scope: Optional[str]          # innermost kntpu:* named region
    signature: Optional[dict]     # MODULE_REGISTRY entry (or None)


def attribute(events: List[DeviceEvent], host_events: List[dict]
              ) -> Tuple[List[Attribution], List[DeviceEvent]]:
    """Mount every exec event into the host span timeline.

    Host spans are finished span-schema event dicts (an obs/spans
    Collector's output).  Each exec event lands in the innermost host
    span containing its midpoint -- deepest nesting level, then latest
    start, which is unique because same-thread spans strictly nest and
    the capture harness's umbrella window span covers the whole window.
    Returns (attributed, unattributed); the capture harness asserts the
    second list is EMPTY.
    """
    spans = [e for e in host_events
             if e.get("kind") == "span"
             and isinstance(e.get("t0"), (int, float))]
    scopes = [e for e in events if e.kind == "scope"]
    # launch-order join: a host "PjitFunction(<fn>)" event inside a named
    # scope dispatched module "jit_<fn>" -- compute for that module
    # attributes to the scope even when it executes AFTER the scope
    # closed (async dispatch: the host moves on to block in fetch while
    # the executor runs the program)
    launch_map: Dict[str, str] = {}
    for ev in events:
        if ev.kind != "launch":
            continue
        fn = ev.name[len(_LAUNCH_PREFIX):].rstrip(")")
        enclosing = [sc for sc in scopes
                     if sc.t0 <= ev.midpoint <= sc.t1]
        if fn and enclosing:
            launch_map.setdefault(
                "jit_" + fn,
                min(enclosing, key=lambda sc: sc.dur_ms).name)
    attributed: List[Attribution] = []
    unattributed: List[DeviceEvent] = []
    for ev in events:
        if ev.kind != "exec":
            continue
        mid = ev.midpoint
        cands = [s for s in spans
                 if s["t0"] <= mid <= s["t0"] + s["dur_ms"] / 1e3]
        if not cands:
            unattributed.append(ev)
            continue
        best = max(cands, key=lambda s: (s["depth"], s["t0"]))
        enclosing = [sc for sc in scopes if sc.t0 <= mid <= sc.t1]
        scope = (min(enclosing, key=lambda sc: sc.dur_ms).name
                 if enclosing else launch_map.get(ev.hlo_module or ""))
        attributed.append(Attribution(
            event=ev, span_name=str(best["name"]),
            span_depth=int(best["depth"]),
            trace_id=best.get("trace_id"), scope=scope,
            signature=executable_info(ev.hlo_module)))
    return attributed, unattributed


def _top(acc: Dict[str, float], cap: int) -> Dict[str, float]:
    """Largest ``cap`` buckets (ms, rounded), the tail folded into
    ``"...other"`` -- bench rows must stay bounded however many modules a
    big solve executes."""
    items = sorted(acc.items(), key=lambda kv: -kv[1])
    out = {k: round(v, 4) for k, v in items[:cap]}
    rest = sum(v for _, v in items[cap:])
    if rest > 0:
        out["...other"] = round(rest, 4)
    return out


def decomposition(attributed: List[Attribution],
                  unattributed: List[DeviceEvent],
                  cap: int = 12,
                  events: Optional[List[DeviceEvent]] = None) -> dict:
    """The ``device_time_decomposition`` stamp: measured device ms by
    executable module, named scope, and host span, plus per-module
    compile/cost provenance where the ExecutableCache registered it.

    ``events`` (the full window event list) lets per-module achieved
    GFLOP/s account for REPEATED executions: exec events are per-op, so
    execution counts come from the host ``PjitFunction(<fn>)`` launch
    events -- a module launched N times in the window did N times its
    cost census's flops.  Without launch evidence the count defaults to
    1 and the figure is a lower bound."""
    by_module: Dict[str, float] = {}
    by_scope: Dict[str, float] = {}
    by_span: Dict[str, float] = {}
    modules: Dict[str, dict] = {}
    total = 0.0
    for a in attributed:
        ms = a.event.dur_ms
        total += ms
        mod = a.event.hlo_module or "<unknown-module>"
        by_module[mod] = by_module.get(mod, 0.0) + ms
        by_scope[a.scope or "<no-scope>"] = \
            by_scope.get(a.scope or "<no-scope>", 0.0) + ms
        by_span[a.span_name] = by_span.get(a.span_name, 0.0) + ms
        if a.signature and mod not in modules:
            modules[mod] = {k: a.signature[k] for k in
                            ("label", "compile_s", "flops",
                             "bytes_accessed") if k in a.signature}
    launches: Dict[str, int] = {}
    for ev in events or []:
        if ev.kind == "launch":
            fn = ev.name[len(_LAUNCH_PREFIX):].rstrip(")")
            if fn:
                launches["jit_" + fn] = launches.get("jit_" + fn, 0) + 1
    for mod, info in modules.items():
        ms = by_module.get(mod, 0.0)
        n_exec = max(1, launches.get(mod, 0))
        if ms > 0 and isinstance(info.get("flops"), (int, float)):
            info["executions"] = n_exec
            info["achieved_gflops"] = round(
                info["flops"] * n_exec / (ms / 1e3) / 1e9, 3)
    return {
        "device_total_ms": round(total, 4),
        "events": len(attributed),
        "unattributed": len(unattributed),
        "by_module": _top(by_module, cap),
        "by_scope": _top(by_scope, cap),
        "by_span": _top(by_span, cap),
        **({"modules": modules} if modules else {}),
    }


# -- mounting into the merged timeline ---------------------------------------

def mount(attributed: List[Attribution], job: str = "device") -> List[dict]:
    """Attributed device events as span-schema event dicts: one child
    span per exec event, parented under the host span it attributed to,
    on a ``device:*`` thread lane of THIS process -- obs/export.py merges
    them into the same Perfetto timeline as the host spans with zero
    special-casing (they validate against the same schema)."""
    out = []
    for a in attributed:
        ev = a.event
        attrs: dict = {}
        if ev.hlo_module:
            attrs["hlo_module"] = ev.hlo_module
        if ev.hlo_op:
            attrs["hlo_op"] = ev.hlo_op
        if a.scope:
            attrs["scope"] = a.scope
        if a.signature and a.signature.get("label"):
            attrs["signature"] = a.signature["label"]
        out.append({"v": _spans.SCHEMA, "kind": "span", "name": ev.name,
                    "t0": ev.t0, "dur_ms": round(ev.dur_ms, 6),
                    "depth": a.span_depth + 1, "parent": a.span_name,
                    "pid": os.getpid(), "job": job,
                    "tid": f"device:{ev.tid}", "trace_id": a.trace_id,
                    "attrs": attrs})
    return out


def write_spill(events: List[dict], path: str) -> str:
    """Append span-schema events to a ``trace_*.jsonl`` spill (the shape
    obs/export.py globs), creating directories as needed."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return path
