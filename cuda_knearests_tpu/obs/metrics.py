"""Metrics registry: bounded counters/gauges/histograms + one snapshot.

Replaces the scattered accounting the repo grew route by route --
DispatchStats beside the solve, ExecutableCache hit counters, DRR deficit
stamps, admission refusals, halo/ICI byte counts, watchdog stall trips,
and (worst) the load generators' unbounded Python latency lists -- with
three primitives and one unified snapshot:

* :class:`Counter` / :class:`Gauge` -- what you expect, thread-safe.
* :class:`Histogram` -- FIXED geometric buckets with exact count/sum/
  min/max and interpolated percentiles.  O(1) memory at any request
  count: an open-loop session at sustained QPS observes every latency
  into ~100 ints instead of growing a list forever (arXiv 1512.02831's
  queue-depth/latency trade-off is only measurable if measuring it
  doesn't OOM the measurer).
* :class:`MetricsRegistry` / :data:`REGISTRY` -- the process-wide name ->
  instrument table, plus pluggable *providers* (callables returning a
  dict) so subsystem-owned counters (dispatch, executable cache) join the
  snapshot without being rewritten.  The fleet autoscaler registers its
  sensor set this way (provider ``fleet_autoscale``: per-SLO-class queue
  depth, occupancy EWMA, windowed p999, refusal rate, ladder position --
  DESIGN.md section 24), so the policy's inputs are inspectable through
  the same ``metrics`` wire op that serves everything else.
* :func:`metrics_snapshot` -- the one document: registry + dispatch
  counters + executable-cache counters, schema-stamped.  The serve wire's
  ``metrics`` command and the ``--metrics-jsonl`` periodic emitter both
  return exactly this (DESIGN.md section 19).

No jax import (the watchdog increments a counter from its trip path,
which must stay importable before any backend exists).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional, Sequence

#: Snapshot schema version (the ``v`` key); bump on any key change.
SCHEMA = 1


def _geometric_bounds(lo: float, hi: float, n: int) -> tuple:
    ratio = (hi / lo) ** (1.0 / (n - 1))
    return tuple(lo * ratio ** i for i in range(n))


#: Default latency ladder: 0.05 ms .. 120 s over 96 geometric buckets
#: (~17% bucket width -> interpolated percentiles within a few percent).
DEFAULT_MS_BUCKETS = _geometric_bounds(0.05, 120_000.0, 96)


class Counter:
    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += int(n)

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


class Gauge:
    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v: float = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Fixed-bucket histogram with exact extrema and interpolated
    percentiles.  Values at or below ``bounds[0]`` land in bucket 0,
    beyond ``bounds[-1]`` in the overflow bucket (whose percentile
    interpolation is clamped by the exact observed max)."""

    __slots__ = ("name", "bounds", "counts", "count", "total", "vmin",
                 "vmax", "_lock")

    def __init__(self, name: str = "", bounds: Sequence[float] = ()):
        self.name = name
        self.bounds = tuple(bounds) or DEFAULT_MS_BUCKETS
        self.counts = [0] * (len(self.bounds) + 1)   # +1 overflow
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if self.vmin is None or v < self.vmin:
                self.vmin = v
            if self.vmax is None or v > self.vmax:
                self.vmax = v
            lo, hi = 0, len(self.bounds)
            while lo < hi:                       # first bound >= v
                mid = (lo + hi) // 2
                if self.bounds[mid] < v:
                    lo = mid + 1
                else:
                    hi = mid
            self.counts[lo] += 1

    def percentile(self, q: float) -> Optional[float]:
        """Interpolated q-quantile (q in [0, 1]); None when empty."""
        with self._lock:
            if not self.count or self.vmin is None or self.vmax is None:
                return None
            rank = q * self.count
            cum = 0.0
            for i, c in enumerate(self.counts):
                if not c:
                    continue
                if cum + c >= rank:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = (self.bounds[i] if i < len(self.bounds)
                          else self.vmax)
                    frac = (rank - cum) / c
                    v = lo + (hi - lo) * max(0.0, min(1.0, frac))
                    return float(min(max(v, self.vmin), self.vmax))
                cum += c
            return float(self.vmax)

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        out = {"count": count, "sum": round(total, 6),
               "min": vmin, "max": vmax}
        for label, q in (("p50", 0.5), ("p99", 0.99), ("p999", 0.999)):
            p = self.percentile(q)
            out[label] = round(p, 6) if p is not None else None
        return out


def percentile_fields(hist: Histogram, digits: int = 3) -> dict:
    """{"p50": .., "p99": ..} rounded -- the bench-row stamp form."""
    out = {}
    for label, q in (("p50", 0.5), ("p99", 0.99)):
        p = hist.percentile(q)
        out[label] = round(p, digits) if p is not None else None
    return out


class MetricsRegistry:
    """Process-wide name -> instrument table + snapshot providers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._providers: Dict[str, Callable[[], dict]] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def histogram(self, name: str,
                  bounds: Sequence[float] = ()) -> Histogram:
        with self._lock:
            if name not in self._hists:
                self._hists[name] = Histogram(name, bounds)
            return self._hists[name]

    def register_provider(self, name: str,
                          fn: Callable[[], dict]) -> None:
        """Attach a subsystem's own counters to the snapshot: ``fn``
        returns a plain dict, merged under ``providers.<name>`` at
        snapshot time.  A provider that raises reports its error instead
        of killing the snapshot."""
        with self._lock:
            self._providers[name] = fn

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
            providers = dict(self._providers)
        out = {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.snapshot()
                           for n, h in sorted(hists.items())},
        }
        provided = {}
        for name, fn in sorted(providers.items()):
            try:
                provided[name] = fn()
            except Exception as e:  # noqa: BLE001 -- one broken provider must not kill the whole snapshot; its error IS the datum
                provided[name] = {"error": f"{type(e).__name__}: {e}"}
        out["providers"] = provided
        return out


#: The process-wide registry (daemons, the watchdog, and the loadgens all
#: write here; the `metrics` wire command reads it).
REGISTRY = MetricsRegistry()


def metrics_snapshot() -> dict:
    """The unified metrics document: registry instruments + providers +
    the dispatch/executable-cache counters that predate this layer.
    Stable top-level schema (``v``, ``ts``, ``pid``, ``counters``,
    ``gauges``, ``histograms``, ``providers``, ``dispatch``,
    ``exec_cache``), pinned by tests/test_obs.py."""
    out = {"v": SCHEMA, "ts": round(time.time(), 6), "pid": os.getpid(),
           **REGISTRY.snapshot()}
    try:
        from ..runtime import dispatch as _dispatch

        out["dispatch"] = _dispatch.stats_dict()
        out["exec_cache"] = _dispatch.EXEC_CACHE.stats_dict()
        # tuned-plan store counters (PR 14): hit rate of the persisted
        # measured-cost plans, so --metrics-jsonl and fleet summaries
        # report it without a dispatch.tuned_plan_stats() side channel
        out["tuned_plans"] = _dispatch.tuned_plan_stats()
    except Exception as e:  # noqa: BLE001 -- the snapshot must land even if the dispatch layer is mid-teardown
        out["dispatch"] = {"error": f"{type(e).__name__}: {e}"}
        out["exec_cache"] = {}
        out["tuned_plans"] = {}
    return out


class JsonlEmitter(threading.Thread):
    """Periodic snapshot emitter: one JSON line per period to ``path``
    (the ``--metrics-jsonl`` flag of the serve/fleet mains).  Daemon
    thread; ``stop()`` writes one final snapshot so short sessions still
    produce at least one line."""

    def __init__(self, path: str, period_s: float = 1.0,
                 snapshot_fn: Optional[Callable[[], dict]] = None):
        super().__init__(daemon=True, name="kntpu-metrics-emitter")
        self.path = path
        self.period_s = max(0.05, float(period_s))
        self.snapshot_fn = snapshot_fn or metrics_snapshot
        self._halt = threading.Event()  # NOT _stop: Thread.join() calls a private self._stop() internally
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def _emit(self) -> None:
        try:
            snap = self.snapshot_fn()
        except Exception as e:  # noqa: BLE001 -- a failed snapshot becomes an error line, never a dead emitter
            snap = {"v": SCHEMA, "error": f"{type(e).__name__}: {e}"}
        with self._lock:
            if self._f.closed:        # stop() already closed the file
                return
            self._f.write(json.dumps(snap) + "\n")
            self._f.flush()

    def run(self) -> None:
        while not self._halt.wait(self.period_s):
            self._emit()

    def stop(self) -> None:
        """Final snapshot + close.  Joins the emitter thread first so a
        mid-_emit run never races the close (and the closed-file guard
        in _emit covers a stop() racing an unjoinable caller)."""
        self._halt.set()
        if self.is_alive():
            self.join(timeout=10.0)
        self._emit()                  # final snapshot (short sessions)
        with self._lock:
            self._f.close()


def watchdog_stall_tripped(tag: str) -> None:
    """The watchdog's trip path: count the stall where every other
    counter lives (called from utils/watchdog.py right before exit)."""
    REGISTRY.counter("watchdog.stalls").inc()
    REGISTRY.gauge("watchdog.last_stall_ts").set(time.time())
    _ = tag  # the tag rides the flight-recorder event, not the counter
