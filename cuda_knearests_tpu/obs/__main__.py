"""``python -m cuda_knearests_tpu.obs`` -- the observability CPU smoke.

One bounded, chip-free gate (scripts/check.sh + CI), staged
(``--stage all|host|device``):

1. **Trace capture** (host stage): solve the 20k fixture with tracing
   enabled (collector + per-process jsonl spill), then VALIDATE -- every
   event passes the schema check, the instrumented seams all appear
   (``knn.prepare`` / ``knn.solve`` / ``dispatch.fetch``), and the
   dispatch child spans nest INSIDE the solve span tree (depth > 0), so
   sync counters land in the timeline rather than beside it.
2. **Disabled-overhead bound** (host stage): measure the disabled
   ``span()`` fast path directly (per-call cost over a tight loop),
   scale it by the span count one traced solve actually emits, and
   assert the implied per-solve overhead is under ``--overhead-pct``
   (default 2%) of the measured solve time.  Deterministic: bounds the
   machinery itself, not two noisy wall-clock runs against each other.
3. **Device capture round trip** (device stage, kntpu-scope): capture
   one solve under the REAL ``jax.profiler`` via obs/device.py, then
   assert the full pipeline -- >= 1 executable event captured, every one
   attributed to exactly one host span (unattributed count ZERO), the
   measured-HBM verdict true against the engine's own model, mounted
   device events schema-valid and exported into the SAME merged
   timeline as the host spans.  The capture-disabled fast path (the
   only cost bench rows pay when capture is off) is bounded under
   ``--overhead-pct`` like the span fast path.
4. **Artifacts**: the merged host+device Chrome trace
   (Perfetto-loadable) and one metrics snapshot line land in
   ``--out-dir`` -- CI uploads them.

Exit 0 iff every check passes; one JSON summary line either way.
``KNTPU_OBS_N`` scales the fixture for constrained runners.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def _overhead_per_call_s(calls: int = 200_000) -> float:
    """Measured cost of one DISABLED span() call (enter+exit included)."""
    from . import spans as _spans

    assert not _spans.enabled()
    t0 = time.perf_counter()
    for _ in range(calls):
        with _spans.span("overhead.probe"):
            pass
    return (time.perf_counter() - t0) / calls


def _capture_disabled_cost_s(calls: int = 200_000) -> float:
    """Measured cost of the capture-off fast path (the only thing a
    bench row pays when BENCH_DEVICE_CAPTURE=0): one env check."""
    from . import device as _device

    t0 = time.perf_counter()
    for _ in range(calls):
        _device.bench_capture_enabled()
    return (time.perf_counter() - t0) / calls


def _device_stage(args, points, summary: dict,
                  failures: List[str]) -> None:
    """The kntpu-scope round trip (DESIGN.md section 20): capture one
    solve under the real profiler, attribute, reconcile HBM, mount the
    device lane into the export dir, and bound the capture-off cost."""
    import jax

    from .. import KnnConfig, KnnProblem
    from . import attribution as _attr
    from . import device as _device
    from . import spans as _spans

    problem = KnnProblem.prepare(points, KnnConfig(k=8))

    def run():
        res = problem.solve()
        jax.block_until_ready((res.neighbors, res.dists_sq,
                               res.certified))

    run()  # warmup: the capture measures a steady-state solve
    try:
        report = _device.profile_window(
            run, trace_id="obs-smoke",
            hbm_model_bytes=_device.problem_hbm_model(problem))
    except Exception as e:  # noqa: BLE001 -- the smoke's verdict IS the failure list
        failures.append(f"device capture failed: {type(e).__name__}: {e}")
        return
    summary.update(
        device_events=len(report.attributed),
        device_unattributed=len(report.unattributed),
        device_outside_window=report.outside_window,
        device_total_ms=report.decomposition["device_total_ms"],
        hbm_model_ok=report.hbm["hbm_model_ok"],
        hbm_measured_source=report.hbm["hbm_measured_source"])
    if not report.attributed:
        failures.append("device capture attributed zero executable "
                        "events (the profiler recorded nothing)")
    if report.unattributed:
        failures.append(
            f"{len(report.unattributed)} device events attributed to NO "
            f"host span (first: "
            f"{report.unattributed[0].name!r})")
    if report.hbm["hbm_model_ok"] is not True:
        failures.append(f"hbm_model_ok failed: {report.hbm}")
    mounted_bad = [ev for ev in report.mounted
                   if _spans.validate_event(ev) is not None]
    if mounted_bad:
        failures.append(f"{len(mounted_bad)} mounted device events "
                        f"violate the span schema")
    scopes = set(report.decomposition["by_scope"])
    if not any(s.startswith(_attr.SCOPE_PREFIX) for s in scopes):
        failures.append(f"no kntpu:* named scope in the decomposition "
                        f"(got {sorted(scopes)})")
    # the device lane joins the SAME merged timeline as the host spans
    _attr.write_spill(report.mounted, os.path.join(
        args.out_dir, f"trace_obs-device_{os.getpid()}.jsonl"))
    # capture-off fast-path bound (like the PR 12 disabled-span gate).
    # Denominator: the captured window's OWN measured duration (the
    # umbrella span) -- the host stage's solve_s does not exist in the
    # standalone `--stage device` invocation CI runs, and a fictitious
    # denominator would make the bound vacuous.
    window = [e for e in report.host_events
              if e.get("name") == _device.WINDOW_SPAN]
    solve_s = (window[0]["dur_ms"] / 1e3 if window else 0.0)
    if solve_s <= 0:
        failures.append("capture window span missing from the host "
                        "events: no denominator for the overhead bound")
        return
    per_call = _capture_disabled_cost_s()
    off_pct = 100.0 * per_call / solve_s
    summary.update(device_window_s=round(solve_s, 4),
                   capture_off_ns_per_check=round(per_call * 1e9, 1),
                   capture_off_overhead_pct=round(off_pct, 6))
    if off_pct >= args.overhead_pct:
        failures.append(f"capture-off overhead {off_pct:.4f}% >= "
                        f"{args.overhead_pct}% bound")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuda_knearests_tpu.obs",
        description=__doc__.splitlines()[0])
    ap.add_argument("--out-dir", default="obs_artifacts",
                    help="artifact directory (merged trace + metrics "
                         "snapshot; default ./obs_artifacts)")
    ap.add_argument("--n", type=int,
                    default=int(os.environ.get("KNTPU_OBS_N", "20000")),
                    help="fixture size (default 20000; KNTPU_OBS_N "
                         "overrides)")
    ap.add_argument("--overhead-pct", type=float, default=2.0,
                    help="disabled-mode overhead bound, percent of one "
                         "solve (default 2.0)")
    ap.add_argument("--stage", choices=("all", "host", "device"),
                    default="all",
                    help="which smoke stages to run (check.sh gates the "
                         "host and device stages as separate lines)")
    args = ap.parse_args(argv)

    from ..utils.platform import enable_compile_cache, honor_jax_platforms_env

    honor_jax_platforms_env()
    enable_compile_cache()

    from .. import KnnConfig, KnnProblem
    from ..io import generate_uniform
    from . import export as _export
    from . import metrics as _metrics
    from . import spans as _spans

    os.makedirs(args.out_dir, exist_ok=True)
    _spans.set_process_tag("obs-smoke")
    failures: List[str] = []
    summary: dict = {"config": "obs smoke", "n": args.n}

    points = generate_uniform(args.n, seed=5)
    queries = generate_uniform(max(256, args.n // 16), seed=6)

    if args.stage in ("all", "host"):
        # 1. traced solve: collector + spill, then schema/seam validation
        sink = _spans.start_file_trace(os.path.join(
            args.out_dir, f"trace_obs-smoke_{os.getpid()}.jsonl"))
        with _spans.capture() as events:
            problem = KnnProblem.prepare(points, KnnConfig(k=8))
            problem.solve()
            problem.query(queries)
        sink.close()
        bad = [(ev.get("name"), why) for ev in events
               if (why := _spans.validate_event(ev)) is not None]
        if bad:
            failures.append(f"schema violations: {bad[:5]}")
        names = {ev["name"] for ev in events}
        for need in ("knn.prepare", "knn.solve", "knn.query",
                     "dispatch.fetch"):
            if need not in names:
                failures.append(f"missing expected span {need!r}")
        nested_fetch = [ev for ev in events
                        if ev["name"] == "dispatch.fetch"
                        and ev["depth"] > 0]
        if not nested_fetch:
            failures.append("dispatch.fetch spans did not nest inside the "
                            "solve span tree")
        summary["events"] = len(events)
        solve_events = [ev for ev in events if ev["name"] == "knn.solve"]
        solve_s = (solve_events[0]["dur_ms"] / 1e3 if solve_events else 0.0)

        # 2. disabled-overhead bound (the near-zero-cost contract)
        spans_per_solve = sum(1 for ev in events)
        per_call = _overhead_per_call_s()
        overhead_pct = (100.0 * spans_per_solve * per_call / solve_s
                        if solve_s > 0 else 0.0)
        summary.update(spans_per_solve=spans_per_solve,
                       disabled_ns_per_span=round(per_call * 1e9, 1),
                       solve_s=round(solve_s, 4),
                       disabled_overhead_pct=round(overhead_pct, 4))
        if overhead_pct >= args.overhead_pct:
            failures.append(
                f"disabled-mode overhead {overhead_pct:.3f}% >= "
                f"{args.overhead_pct}% bound")

        # 3. metrics registry sanity + snapshot artifact
        _metrics.REGISTRY.counter("obs.smoke_runs").inc()
        hist = _metrics.Histogram("obs.probe_ms")
        for v in (1.0, 2.0, 4.0, 8.0):
            hist.observe(v)
        if hist.snapshot()["count"] != 4 or hist.percentile(0.5) is None:
            failures.append("histogram self-check failed")
        snap = _metrics.metrics_snapshot()
        for key in ("v", "ts", "counters", "histograms", "dispatch",
                    "exec_cache"):
            if key not in snap:
                failures.append(f"metrics snapshot missing {key!r}")
        with open(os.path.join(args.out_dir, "metrics.jsonl"), "a",
                  encoding="utf-8") as f:
            f.write(json.dumps(snap) + "\n")

    if args.stage in ("all", "device"):
        _device_stage(args, points, summary, failures)

    # 4. merged Perfetto trace artifact
    exp = _export.export_dir(
        args.out_dir, os.path.join(args.out_dir, "trace_merged.json"))
    summary["trace_events"] = exp["events"]
    if exp["events"] == 0:
        failures.append("merged trace is empty")
    with open(os.path.join(args.out_dir, "trace_merged.json"),
              encoding="utf-8") as f:
        if not json.load(f).get("traceEvents"):
            failures.append("merged trace has no traceEvents")

    summary["ok"] = not failures
    if failures:
        summary["failures"] = failures
    print(json.dumps(summary), flush=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
