"""kntpu-scope: programmatic device-time capture scoped to a solve window.

PR 12's span tracer sees only the host -- "device time" was wall clock
around a blocking ``dispatch.fetch``.  This module closes the gap with
three measured quantities per window, all exercised end-to-end on the CPU
backend profiler (tier-1) so the hardware path is proven before a chip
ever appears:

* **Device-time attribution** -- :func:`profile_window` runs a callable
  under ``jax.profiler`` capture with a wall-anchored window annotation,
  parses the capture (obs/attribution.py), and attributes every
  executable event to the host span timeline + the ``kntpu:*`` named
  scopes + the ExecutableCache signature registry.  Zero unattributed
  executions is an asserted property, not a hope: the harness holds an
  umbrella window span open for the whole capture.
* **Measured-HBM validation** -- :class:`HbmSampler` samples device
  memory through the window (``jax.Device.memory_stats()`` where the
  backend reports it; the summed ``jax.live_arrays()`` footprint on the
  CPU fallback) and :func:`hbm_fields` reconciles the window's measured
  growth against the engine's own model (``hbm_bytes_estimate`` /
  ``chip_hbm_model``) into a typed ``hbm_model_ok`` verdict: the model
  must DOMINATE the measured growth within :data:`HBM_MODEL_HEADROOM`
  (a systematic underestimate -- the preflight blessing a would-OOM
  launch -- fails the verdict, and scripts/bench_diff.py gates on the
  flip).
* **One merged timeline** -- attributed device events are re-expressed
  in the span event schema and spilled beside the host spans
  (``KNTPU_TRACE_DIR``), so ``obs/export.py`` emits one host+device
  Perfetto trace with no special cases.

``bench.py`` rows stamp :func:`bench_capture_fields` (one extra captured
solve after the timed runs -- the measurement itself stays uncaptured);
``scripts/tpu_watch.py --capture`` drives the whole ladder in one
command.  Everything jax-flavored imports lazily: the obs package must
stay importable before any backend exists.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import shutil
import tempfile
import threading
import uuid
from typing import Callable, List, Optional

from . import attribution as _attr
from . import spans as _spans

#: The umbrella span the harness holds open for the whole window -- the
#: fallback attribution target that makes zero-unattributed a guarantee.
WINDOW_SPAN = "obs.capture_window"

#: The model-dominates-measurement slack: the preflight budgets 80% of
#: the device limit to one launch (pallas_solve._HBM_BUDGET_FRACTION),
#: i.e. it reserves 1.25x headroom for XLA temporaries -- the verdict
#: grants the measurement the same factor before calling the model an
#: underestimate.
HBM_MODEL_HEADROOM = 1.25


class CaptureError(RuntimeError):
    """A device capture could not run or produced no parseable trace."""


# one capture per process at a time: jax.profiler sessions do not nest
_ACTIVE = threading.Lock()


def bench_capture_enabled() -> bool:
    """The bench-row gate: BENCH_DEVICE_CAPTURE=0 disables the extra
    captured solve entirely (this check is the only cost of 'off')."""
    return os.environ.get("BENCH_DEVICE_CAPTURE", "1") != "0"


def _trace_file(log_dir: str) -> str:
    """The capture's Chrome trace file (the profiler writes one run dir
    per session under ``plugins/profile/<stamp>/``)."""
    for pattern in ("*.trace.json.gz", "perfetto_trace.json.gz"):
        cands = sorted(glob.glob(os.path.join(
            log_dir, "plugins", "profile", "*", pattern)))
        if cands:
            return cands[-1]
    raise CaptureError(
        f"no Chrome trace under {log_dir!r}: the profiler session "
        f"produced no parseable capture on this backend")


# -- measured HBM -------------------------------------------------------------

class HbmSampler(threading.Thread):
    """Samples device-memory footprint through a window: floor (first
    sample), peak, and the source of truth -- ``memory_stats`` where the
    backend reports ``bytes_in_use`` (TPU), else the summed ``nbytes``
    of all live ``jax.Array`` buffers (the CPU backend reports no
    allocator stats; live buffers are the measurable device footprint
    there).  ``start()``/``stop()`` take one synchronous sample each, so
    floor and peak exist even if the thread never gets scheduled."""

    def __init__(self, period_s: float = 0.004):
        super().__init__(daemon=True, name="kntpu-hbm-sampler")
        self.period_s = max(0.001, float(period_s))
        self._halt = threading.Event()
        self.floor: Optional[int] = None
        self.peak: int = 0
        self.samples = 0
        self.source = "unavailable"

    @staticmethod
    def _read() -> "tuple[int, str]":
        import jax

        try:
            stats = jax.devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 -- some backends raise instead of returning None
            stats = None
        if stats and "bytes_in_use" in stats:
            return int(stats["bytes_in_use"]), "memory_stats"
        try:
            return (int(sum(int(a.nbytes) for a in jax.live_arrays())),
                    "live_arrays")
        except Exception:  # noqa: BLE001 -- a backend without live-array introspection measures nothing, not an error
            return 0, "unavailable"

    def _sample(self) -> None:
        v, src = self._read()
        self.samples += 1
        self.source = src
        if self.floor is None:
            self.floor = v
        self.peak = max(self.peak, v)

    def run(self) -> None:
        while not self._halt.wait(self.period_s):
            self._sample()

    def start(self) -> None:  # type: ignore[override]
        self._sample()                  # synchronous floor sample
        super().start()

    def stop(self) -> "HbmSampler":
        self._halt.set()
        if self.is_alive():
            self.join(timeout=5.0)
        self._sample()                  # synchronous closing sample
        return self

    def result(self) -> dict:
        return {"peak": int(self.peak), "floor": int(self.floor or 0),
                "samples": int(self.samples), "source": self.source}


def hbm_fields(sample: dict, model_bytes: Optional[int]) -> dict:
    """The measured-HBM bench stamp + the typed ``hbm_model_ok`` verdict.

    Law: the window's measured growth (``peak - floor`` -- ambient
    residency from before the window subtracts out) must not exceed the
    engine's modeled footprint times :data:`HBM_MODEL_HEADROOM`.  A
    systematic underestimate is exactly the failure the preflight model
    must never have: it would bless launches that OOM the chip.  Engines
    with no device plan to model (the oracle backend answers on the
    host) have nothing to reconcile: the verdict is vacuously true and
    says so."""
    peak, floor = int(sample["peak"]), int(sample["floor"])
    delta = max(0, peak - floor)
    out = {
        "hbm_measured_peak": peak,
        "hbm_measured_floor": floor,
        "hbm_window_delta_bytes": delta,
        "hbm_measured_source": sample["source"],
        "hbm_samples": int(sample["samples"]),
        "hbm_model_bytes": (int(model_bytes)
                            if model_bytes is not None else None),
        "hbm_model_headroom": HBM_MODEL_HEADROOM,
    }
    if model_bytes is None:
        out["hbm_model_ok"] = True
        out["hbm_model_note"] = ("no device-plan model for this engine "
                                 "(host-native route): nothing to "
                                 "reconcile")
        return out
    out["hbm_model_ok"] = bool(delta <= model_bytes * HBM_MODEL_HEADROOM)
    if not out["hbm_model_ok"]:
        out["hbm_model_verdict"] = (
            f"systematic underestimate: window grew {delta} bytes > "
            f"model {int(model_bytes)} * {HBM_MODEL_HEADROOM} -- the "
            f"preflight model would bless a launch this size")
    return out


def problem_hbm_model(problem) -> Optional[int]:
    """The engine's own modeled device footprint for one solve of a
    prepared single-chip KnnProblem: the launch-scale HBM model
    (``pallas_solve.hbm_bytes_estimate``) summed over the plan's classes,
    plus the assembled result rows.  None when the engine has no device
    plan (oracle backend) -- the measured-HBM verdict is then vacuous."""
    cfg = problem.config
    if cfg.backend == "oracle":
        return None
    from ..ops.pallas_solve import hbm_bytes_estimate

    k = int(cfg.k)
    total = 0
    if getattr(problem, "aplan", None) is not None:
        for cp in problem.aplan.classes:
            total += hbm_bytes_estimate(cp.qcap_pad, cp.ccap, k, cp.n_sc)
        n = int(problem.aplan.n_points)
    elif getattr(problem, "pack", None) is not None:
        pack = problem.pack
        total += hbm_bytes_estimate(pack.qx.shape[2], pack.cx.shape[2], k,
                                    pack.qx.shape[0])
        n = int(pack.inv_flat.shape[0])
    elif getattr(problem, "plan", None) is not None:
        plan = problem.plan
        total += hbm_bytes_estimate(plan.qcap, plan.ccap, k,
                                    plan.n_chunks * plan.batch)
        n = int(problem.grid.n_points)
    else:
        return None
    total += 2 * 4 * n * k  # assembled (n, k) ids + d2 result rows
    return int(total)


# -- the capture window -------------------------------------------------------

@dataclasses.dataclass
class WindowReport:
    """Everything one captured window measured."""

    capture_id: str
    ret: object                      # the callable's return value
    host_events: List[dict]          # span-schema events from the window
    device_events: List[_attr.DeviceEvent]
    attributed: List[_attr.Attribution]
    unattributed: List[_attr.DeviceEvent]
    outside_window: int
    decomposition: dict
    hbm: dict
    mounted: List[dict]              # span-schema device events (export)
    trace_path: Optional[str] = None  # kept only with keep_log_dir

    def fields(self) -> dict:
        """The bench-row stamp form."""
        return {"device_time_decomposition": self.decomposition,
                **self.hbm}


def profile_window(fn: Callable[[], object], *,
                   trace_id: Optional[str] = None,
                   hbm_model_bytes: Optional[int] = None,
                   log_dir: Optional[str] = None,
                   keep_log_dir: bool = False,
                   host_tracer_level: int = 1,
                   sample_period_s: float = 0.004,
                   job: str = "device") -> WindowReport:
    """Run ``fn`` under a scoped profiler capture and return the parsed,
    attributed, HBM-reconciled report.

    The window is: profiler session -> capture-anchor annotation (whose
    host wall time joins the clock axes) -> umbrella span -> ``fn`` ->
    block until all dispatched work completes.  ``host_tracer_level=1``
    records explicit annotations but not Python frames -- device/op
    events come from the backend tracer regardless, and a bench capture
    must not drown in interpreter noise.  Raises :class:`CaptureError`
    when a capture is already active in this process or the backend
    produced no parseable trace."""
    import jax

    if not _ACTIVE.acquire(blocking=False):
        raise CaptureError("another device capture is active in this "
                           "process (profiler sessions do not nest)")
    own_dir = log_dir is None
    try:
        log_dir = log_dir or tempfile.mkdtemp(prefix="kntpu-devcap-")
        capture_id = uuid.uuid4().hex[:10]
        anchor_name = _attr.CAPTURE_PREFIX + capture_id
        col = _spans.Collector()
        _spans.add_sink(col)
        sampler = HbmSampler(sample_period_s)
        sampler.start()
        try:
            options = None
            try:  # ProfileOptions moved across jax versions; optional
                options = jax.profiler.ProfileOptions()
                options.host_tracer_level = host_tracer_level
            except Exception:  # noqa: BLE001 -- absent options only lose the tracer-level tweak
                options = None
            ctx = (jax.profiler.trace(log_dir, profiler_options=options)
                   if options is not None else jax.profiler.trace(log_dir))
            with ctx:
                anchor_wall = _spans.wall(_spans.now())
                with jax.profiler.TraceAnnotation(anchor_name), \
                        _spans.span(WINDOW_SPAN, force=True,
                                    trace_id=trace_id,
                                    capture_id=capture_id):
                    ret = fn()
                    # trailing async work must land inside the window
                    (jax.device_put(0.0) + 0).block_until_ready()
        finally:
            sampler.stop()
            _spans.remove_sink(col)
        trace_path = _trace_file(log_dir)
        doc = _attr.load_chrome_trace(trace_path)
        events, outside = _attr.rebase(_attr.chrome_events(doc),
                                       anchor_wall, capture_id)
        host = [e for e in col.events if e.get("kind") == "span"]
        attributed, unattributed = _attr.attribute(events, host)
        report = WindowReport(
            capture_id=capture_id, ret=ret, host_events=host,
            device_events=events, attributed=attributed,
            unattributed=unattributed, outside_window=outside,
            decomposition=_attr.decomposition(attributed, unattributed,
                                              events=events),
            hbm=hbm_fields(sampler.result(), hbm_model_bytes),
            mounted=_attr.mount(attributed, job=job),
            trace_path=trace_path if keep_log_dir else None)
        return report
    finally:
        _ACTIVE.release()
        if own_dir and not keep_log_dir and log_dir:
            shutil.rmtree(log_dir, ignore_errors=True)


def spill_mounted_from_env(report: WindowReport, tag: str = "") -> Optional[str]:
    """When ``KNTPU_TRACE_DIR`` is set (whole-run tracing), spill the
    window's mounted device events beside the host span spills so the
    merged export shows the device lane -- same env contract as
    ``spans.start_file_trace_from_env``."""
    d = os.environ.get("KNTPU_TRACE_DIR", "")
    if not d or not report.mounted:
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "-"
                   for c in (tag or "device"))
    return _attr.write_spill(report.mounted, os.path.join(
        d, f"trace_{safe}-dev_{os.getpid()}.jsonl"))


def bench_capture_fields(fn: Callable[[], object], *,
                         hbm_model_bytes: Optional[int] = None,
                         trace_id: Optional[str] = None,
                         tag: str = "bench") -> dict:
    """One captured window as bench-row fields; a capture failure stamps
    a typed error field and NEVER kills the row -- observability must not
    take the bench down."""
    try:
        report = profile_window(fn, trace_id=trace_id,
                                hbm_model_bytes=hbm_model_bytes)
        spill_mounted_from_env(report, tag=tag)
        return report.fields()
    except Exception as e:  # noqa: BLE001 -- a failed capture is a typed stamp, never a dead bench row
        return {"device_capture_error": f"{type(e).__name__}: {e}"}


def bench_capture_or_skip(fn: Callable[[], object], *,
                          hbm_model_bytes: Optional[int] = None,
                          trace_id: Optional[str] = None,
                          tag: str = "bench",
                          solve_s: Optional[float] = None) -> dict:
    """The ONE enabled/skip contract every bench row shares: capture
    unless BENCH_DEVICE_CAPTURE=0 opts out or the measured ``solve_s``
    exceeds the BENCH_DEVICE_CAPTURE_MAX_S wall guard (default 180 s --
    the extra captured solve must not starve a wall budget).  Both
    skips stamp ``device_capture_skipped``, never silent: the capture
    harness's verdict distinguishes an opt-out from a missing
    decomposition by exactly this stamp."""
    if not bench_capture_enabled():
        return {"device_capture_skipped": "BENCH_DEVICE_CAPTURE=0"}
    max_s = float(os.environ.get("BENCH_DEVICE_CAPTURE_MAX_S", "180"))
    if solve_s is not None and solve_s > max_s:
        return {"device_capture_skipped":
                f"solve_s {solve_s:.1f} > BENCH_DEVICE_CAPTURE_MAX_S "
                f"{max_s:g}"}
    return bench_capture_fields(fn, hbm_model_bytes=hbm_model_bytes,
                                trace_id=trace_id, tag=tag)
