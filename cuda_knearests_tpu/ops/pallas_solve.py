"""Pallas TPU kernel for the supercell kNN solve (the hot path).

Reference parity (C4, /root/reference/knearests.cu:93-148): the reference's CUDA
search kernel keeps a per-thread k-max-heap in block shared memory while scanning
ring candidates.  The XLA path (solve.py) replaces the heap with ``lax.top_k``,
but XLA lowers that to a full stable sort of the (batch, Q, C) distance tensor --
the sort, not the distance arithmetic, dominates the solve and spills multi-GB
temporaries to HBM.

This kernel is the VMEM-native redesign: one Pallas program per supercell

  1. loads the supercell's padded query block (Q, 3) and per-axis candidate
     lane blocks (1, C) into VMEM,
  2. computes the full (Q, C) squared-distance tile on the VPU with the same
     x,y,z accumulation order as the reference (knearests.cu:125),
  3. extracts the k nearest by k unrolled min-and-mask passes over the
     VMEM-resident tile (the shared-memory-heap analog: O(k*C) VPU work, zero
     HBM traffic for the distance tile),
  4. writes ascending (k, Q) distances and stored-point ids.

The candidate/query *indexing* (CSR slot packing and coordinate gathers) is
static per problem, so it lives in :class:`PallasPack`, built once at prepare
time -- the analog of the reference precomputing its offset tables in
kn_prepare (knearests.cu:254-300) so kn_solve is one kernel launch.  Steady-
state solve = kernel + certificate + un-pad scatter, nothing else.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .gridhash import GridHash
from .solve import (KnnResult, SolvePlan, _margin_sq, build_plan, pack_cells)
from .topk import INVALID_ID

# Sentinels for padded query/candidate id lanes.  Distinct negatives so a padded
# query never "self-excludes" a padded candidate.
_PAD_Q = -2
_PAD_C = -3

_BIG_ID = 2**31 - 1

# Per-program VMEM budget (bytes) for choosing this path.  v5e has 128 MiB
# of VMEM; 32 MiB leaves headroom for Mosaic's own double-buffering and was
# validated by the round-5 on-chip A/B running ccap-10368 tiles (~24 MB by
# this estimate) cleanly.  Oversized QUERY axes no longer disqualify the
# kernel at all -- pick_qsub splits the query block across grid steps while
# the candidate block stays resident -- so this budget effectively gates on
# the candidate-axis footprint.
_VMEM_BUDGET = 32 * 1024 * 1024

# k above which the extraction loop is rolled (fori_loop) instead of unrolled.
_UNROLL_K_MAX = 64


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("qx", "qy", "qz", "cx", "cy", "cz", "qid3", "cid3",
                 "q_idx", "q_ok", "lo", "hi", "inv_flat", "inv_sc", "tgt"),
    meta_fields=("qcap", "ccap", "s_total"),
)
@dataclasses.dataclass(frozen=True)
class PallasPack:
    """Static per-problem kernel inputs: packed CSR slots + gathered coords.

    qx/qy/qz: (S, 1, qcap) f32 query coords, one lane block per axis (pad
              slots garbage).  Per-axis like the candidates: a (S, qcap, 3)
              block would put 3 on the TPU lane axis and pad it to 128 --
              a measured 42.7x HBM expansion that OOMed the 10M-point
              single-chip solve (2 x 7.63 GB of padding for 183 MB of data).
    cx/cy/cz: (S, 1, ccap) f32 candidate coords, one lane block per axis.
    qid3:     (S, 1, qcap) i32 stored-point id per query slot (_PAD_Q pads).
    cid3:     (S, 1, ccap) i32 stored-point id per candidate slot (_PAD_C pads).
    q_idx/q_ok: (S, qcap) stored-point index per slot / slot validity.
    lo/hi:    (S, 3) f32 dilated-box corners for the completeness certificate.
    inv_flat: (n,) i32 -- the inverse of the q_idx partition: stored point r
              lives in flat slot inv_flat[r] of the (S*qcap) slot axis.  The
              epilogue is therefore one row *gather* per output (TPU-fast)
              instead of the (S*qcap)-row scatter it replaced (scatter was
              ~45% of round-1 solve time, DESIGN.md section 2).
    inv_sc:   (n,) i32 -- inv_flat // qcap (the owning supercell per point).
    tgt:      (S*qcap,) i32 -- the FORWARD slot map for the scatter
              epilogue: flat slot s writes output row tgt[s]; pad slots
              carry the sentinel n and are dropped.  Built from the same
              safe-index pass as inv_flat so the two directions cannot
              drift apart (the ClassPlan.tgt rule).
    """

    qx: jax.Array
    qy: jax.Array
    qz: jax.Array
    cx: jax.Array
    cy: jax.Array
    cz: jax.Array
    qid3: jax.Array
    cid3: jax.Array
    q_idx: jax.Array
    q_ok: jax.Array
    lo: jax.Array
    hi: jax.Array
    inv_flat: jax.Array
    inv_sc: jax.Array
    tgt: jax.Array
    qcap: int
    ccap: int
    s_total: int


def _kernel(qx_ref, qy_ref, qz_ref, cx_ref, cy_ref, cz_ref, qid_ref, cid_ref,
            out_d_ref, out_i_ref, *, k: int, exclude_self: bool):
    """One supercell: per-axis (1,Q) query x (1,C) candidate lane blocks ->
    ascending (k,Q) best distances and stored-point ids.

    Padded candidate lanes carry garbage coordinates; they are masked here by
    their _PAD_C id (cheaper than a FAR-coordinate fill pass over HBM).  The
    k-pass min-and-mask is the reference heap's functional twin: pass i finds
    the i-th nearest and masks it out of the tile.  The winner's id is
    extracted by a masked min over the candidate-id lanes, so value ties
    resolve to the lowest stored-point id, exactly like a stable sort over
    ids (slot order is irrelevant -- _pack_inputs may interleave it).
    """
    d2 = None
    # same x,y,z accumulation order as knearests.cu:125
    for q_ref, c_ref in ((qx_ref, cx_ref), (qy_ref, cy_ref), (qz_ref, cz_ref)):
        qa = q_ref[0, 0, :].reshape(-1, 1)    # (Q, 1)
        ca = c_ref[0, 0, :].reshape(1, -1)    # (1, C)
        diff = qa - ca
        d2 = diff * diff if d2 is None else d2 + diff * diff
    ci = cid_ref[0, 0, :].reshape(1, -1)
    drop = ci == _PAD_C
    if exclude_self:
        # skip self by storage index (knearests.cu:123): coordinate duplicates
        # of the query are still reported.
        qi = qid_ref[0, 0, :].reshape(-1, 1)
        drop = drop | (qi == ci)
    d2 = jnp.where(drop, jnp.inf, d2)
    if k <= _UNROLL_K_MAX:
        for i in range(k):
            m = jnp.min(d2, axis=1)
            sel = d2 == m[:, None]
            bid = jnp.min(jnp.where(sel, ci, _BIG_ID), axis=1)
            out_d_ref[0, i, :] = m
            out_i_ref[0, i, :] = bid
            if i + 1 < k:
                d2 = jnp.where(sel & (ci == bid[:, None]), jnp.inf, d2)
    else:
        # large k: rolled loop keeps compile time bounded (unrolling 100+
        # min-and-mask passes blows up Mosaic compilation)
        def body(i, d2):
            m = jnp.min(d2, axis=1)
            sel = d2 == m[:, None]
            bid = jnp.min(jnp.where(sel, ci, _BIG_ID), axis=1)
            out_d_ref[0, pl.ds(i, 1), :] = m.reshape(1, -1)
            out_i_ref[0, pl.ds(i, 1), :] = bid.reshape(1, -1)
            return jnp.where(sel & (ci == bid[:, None]), jnp.inf, d2)

        jax.lax.fori_loop(0, k, body, d2)


def _kernel_blocked(qx_ref, qy_ref, qz_ref, cx_ref, cy_ref, cz_ref, qid_ref,
                    cid_ref, out_d_ref, out_i_ref, pool_d_ref, pool_i_ref,
                    rem_ref, *, k: int, m: int, exclude_self: bool):
    """Blocked two-stage top-k (config.kernel='blocked').

    Stage 1 walks the candidate lanes one 128-lane block at a time: each
    block's (Q, 128) squared-distance tile is computed from the coordinate
    lane blocks on the spot (same x,y,z accumulation order as
    knearests.cu:125) and reduced to its ascending top-m by m min-and-mask
    passes while it lives in registers -- the full (Q, C) distance tile is
    never materialized, so VMEM traffic drops from O(k*C) tile sweeps to one
    coordinate read per block plus the (Q, G*m) survivor pool.

    Stage 2 runs the classic k-pass min-and-mask on the survivor pool.
    Exactness: every candidate a block did NOT keep is >= that block's
    smallest REMAINING value (``rem``, computed after the m-th extraction's
    mask; inf when the block kept everything it had).  The result can
    therefore be wrong only if some rem is strictly below the selected k-th
    value t -- a hidden candidate could land in (rem, t).  Such rows get
    their k-th distance NaN'd, which fails the completeness certificate in
    every epilogue (NaN <= margin is false even for an infinite margin), so
    they resolve through the standard exact fallback.  Pack-time slot
    interleaving (_pack_inputs) spreads spatially-adjacent candidates across
    blocks to keep that event rare.

    Tie semantics: winners are chosen by minimum stored-point id among
    value ties, like the kpass kernel.  A hidden candidate exactly tying t
    (rem == t) does NOT flag: the reported distances are still the true k
    smallest, and the id set may differ from a full scan only inside exact
    ties at the k-th distance -- id flips inside exact ties are accepted
    throughout this framework (differential tests compare tie-aware).

    Layout: candidate refs arrive as (1, G, 128) -- one SUBLANE row per
    128-lane block -- so block g is a dynamic-sublane slice
    (``c_ref[0, pl.ds(g, 1), :]``), the indexing pattern Mosaic supports
    with a traced g.  The flat (1, 1, G*128) layout the kpass kernel uses
    would need a dynamic *lane* offset in the rolled stage-1 path, which
    the TPU's rigid 128-lane tiling does not (pallas_guide.md "Tiling
    Constraints"; every documented pl.ds example indexes sublanes).
    """
    n_blocks = cx_ref.shape[1]
    qa = [r[0, 0, :].reshape(-1, 1) for r in (qx_ref, qy_ref, qz_ref)]
    qi = qid_ref[0, 0, :].reshape(-1, 1) if exclude_self else None

    def block_topm(g):
        """One block's ascending top-m + its smallest remaining value, all
        sublane-major ((m, Q) kept, (1, Q) rem) so the rolled path can
        dynamic-update rows (sublane offsets everywhere; lane offsets are
        always static)."""
        sl = pl.ds(g, 1)
        d2b = None
        for q_col, c_ref in zip(qa, (cx_ref, cy_ref, cz_ref)):
            cb = c_ref[0, sl, :].reshape(1, -1)
            diff = q_col - cb
            d2b = diff * diff if d2b is None else d2b + diff * diff
        cib = cid_ref[0, sl, :].reshape(1, -1)
        drop = cib == _PAD_C
        if exclude_self:
            drop = drop | (qi == cib)
        d2b = jnp.where(drop, jnp.inf, d2b)
        kd, ki = [], []
        for j in range(m):
            mv = jnp.min(d2b, axis=1)
            sel = d2b == mv[:, None]
            bid = jnp.min(jnp.where(sel, cib, _BIG_ID), axis=1)
            kd.append(mv)
            ki.append(bid)
            d2b = jnp.where(sel & (cib == bid[:, None]), jnp.inf, d2b)
        # smallest value the block did NOT keep (inf when it kept all it
        # had) -- the exact lower bound on anything hidden in this block
        return (jnp.stack(kd, axis=0), jnp.stack(ki, axis=0),
                jnp.min(d2b, axis=1).reshape(1, -1))

    # Mosaic compile cost scales with unrolled op count; the kpass kernel
    # rolls above _UNROLL_K_MAX passes for the same reason.  Stage 1 is
    # n_blocks*m extraction passes: unroll small schedules (registers, no
    # scratch traffic); roll big ones over the block index, landing each
    # block's rows in the VMEM scratch pool via ref stores at a dynamic
    # SUBLANE offset (the documented pl.ds store pattern -- a traced-offset
    # dynamic_update_slice on a loop-carried value is not).
    if n_blocks * m + k <= _UNROLL_K_MAX:
        blocks = [block_topm(g) for g in range(n_blocks)]
        pool_d = jnp.concatenate([b[0] for b in blocks], axis=0)  # (G*m, Q)
        pool_i = jnp.concatenate([b[1] for b in blocks], axis=0)
        rem = jnp.concatenate([b[2] for b in blocks], axis=0)     # (G, Q)
    else:
        def s1_body(g, _):
            kd, ki, r = block_topm(g)
            pool_d_ref[pl.ds(g * m, m), :] = kd
            pool_i_ref[pl.ds(g * m, m), :] = ki
            rem_ref[pl.ds(g, 1), :] = r
            return 0

        jax.lax.fori_loop(0, n_blocks, s1_body, 0)
        pool_d = pool_d_ref[:, :]
        pool_i = pool_i_ref[:, :]
        rem = rem_ref[:, :]

    def extract(pool_d):
        mv = jnp.min(pool_d, axis=0)                              # (Q,)
        sel = pool_d == mv[None, :]
        bid = jnp.min(jnp.where(sel, pool_i, _BIG_ID), axis=0)
        masked = jnp.where(sel & (pool_i == bid[None, :]), jnp.inf, pool_d)
        return mv, bid, masked

    if k <= _UNROLL_K_MAX:
        t = None
        for i in range(k):
            mv, bid, masked = extract(pool_d)
            out_i_ref[0, i, :] = bid
            if i + 1 < k:
                out_d_ref[0, i, :] = mv
                pool_d = masked
            else:
                t = mv
    else:
        def s2_body(i, pool_d):
            mv, bid, masked = extract(pool_d)
            out_d_ref[0, pl.ds(i, 1), :] = mv.reshape(1, -1)
            out_i_ref[0, pl.ds(i, 1), :] = bid.reshape(1, -1)
            return masked

        pool_d = jax.lax.fori_loop(0, k - 1, s2_body, pool_d)
        t, bid, _ = extract(pool_d)
        out_i_ref[0, k - 1, :] = bid
    # Deficit certificate: hidden candidates in block g are >= rem[g] (the
    # smallest value that block did not keep; inf when it kept everything),
    # so the result can be wrong only if some rem < t strictly -- a hidden
    # value could then land in (rem, t).  rem == t hides at most exact ties
    # at the k-th distance (see docstring); rem == inf never flags, so
    # blocks holding <= m real candidates and fully-padded blocks certify
    # through the normal margin check.  Flagged rows get NaN at k-1, fail
    # every certificate, and resolve via the exact fallback.
    deficit = jnp.any(rem < t[None, :], axis=0)
    out_d_ref[0, k - 1, :] = jnp.where(deficit, jnp.nan, t)


def vmem_bytes_estimate(qcap: int, ccap: int, k: int,
                        row_out: bool = False) -> int:
    """Rough per-program VMEM need: d2 tile + in/out blocks (f32/i32 = 4B),
    with lane/sublane padding accounted.  ``row_out`` models the scatter
    epilogue's row-major (Q, k) output blocks (queries on sublanes, k padded
    to the 128-lane tile) instead of the gather layout's (k, Q) blocks."""
    q_pad = -(-qcap // 128) * 128
    k_pad = -(-k // 8) * 8
    tile = q_pad * ccap                       # d2 (+ the masked copy is fused)
    # 3 coord + 1 id block per side, each a (1, 1, N) VMEM tile occupying
    # 8 sublanes x N lanes
    inputs = 4 * 8 * q_pad + 4 * 8 * ccap
    if row_out:
        outputs = 2 * q_pad * (-(-k // 128) * 128)
    else:
        outputs = 2 * k_pad * q_pad
    return 4 * (2 * tile + inputs + outputs)


def pallas_fits(qcap: int, ccap: int, k: int, row_out: bool = False) -> bool:
    return vmem_bytes_estimate(qcap, ccap, k, row_out) <= _VMEM_BUDGET


def pick_qsub(qcap: int, ccap: int, k: int, row_out: bool = False) -> int:
    """Largest per-grid-step query-block width for a (qcap, ccap) class.

    Returns qcap itself when the full tile fits VMEM; otherwise the widest
    128-multiple divisor of the 128-rounded qcap whose (qsub, ccap) tile
    fits (the kernel then grids over query sub-blocks while the candidate
    block stays resident -- see _pallas_topk); 0 when even a 128-wide query
    block does not fit, i.e. the CANDIDATE axis alone blows the budget and
    the class must stream.  This is what routes dense-blob classes (huge
    qcap from thousands of coincident queries) onto the kernel instead of
    the streamed scan."""
    qcap = -(-qcap // 128) * 128
    lanes = qcap // 128
    best = 0
    for d in range(1, lanes + 1):
        if lanes % d:
            continue
        qsub = 128 * d
        if pallas_fits(qsub, ccap, k, row_out):
            best = qsub
    return best


def hbm_bytes_estimate(qcap: int, ccap: int, k: int, s_total: int,
                       row_out: bool = False) -> int:
    """Modeled HBM footprint (bytes) of one kernel launch: the PallasPack's
    per-supercell coordinate/id lane blocks and slot maps, plus the kernel's
    output buffers.  The VMEM estimate above gates what one *program* holds;
    this gates what the whole launch allocates -- the quantity that actually
    OOMs a device when a dense class's ccap explodes (the r5 clustered crash
    was a launch-scale failure, not a per-program one).  Deliberately a
    slight overestimate (pad slots counted, per-axis lane blocks at full
    width): preflight must refuse marginal launches, not bless them."""
    q_pad = -(-qcap // 128) * 128
    # qx/qy/qz/qid3 (q side) + cx/cy/cz/cid3 (c side), 4B each, per supercell
    pack = s_total * 4 * (4 * q_pad + 4 * ccap)
    pack += s_total * 4 * 2 * q_pad               # q_idx + q_ok
    if row_out:
        # row-major ((n_blk+1)*qsub, k) dists + ids, k padded to lanes
        out = 2 * 4 * (s_total * q_pad + q_pad) * (-(-k // 128) * 128)
    else:
        out = 2 * 4 * s_total * k * q_pad          # raw (S, k, Q) d + i
    return pack + out


_HBM_BUDGET_ENV = "KNTPU_HBM_BUDGET_BYTES"
# Fraction of the device's reported bytes_limit the preflight will commit to
# one launch: headroom for the grid CSR, the result buffers the epilogue
# scatters into, and XLA's own temporaries.
_HBM_BUDGET_FRACTION = 0.8


def hbm_budget_bytes(cfg=None) -> int | None:
    """The HBM budget one launch must fit, or None for unbounded.

    Resolution order: an explicit ``KnnConfig.hbm_budget_bytes`` wins, then
    the ``KNTPU_HBM_BUDGET_BYTES`` env knob (<= 0 means unbounded -- the
    escape hatch), then 80% of the device's reported ``bytes_limit``.  Hosts
    whose backend reports no limit (CPU fallback) run unbounded: the OS can
    page, and refusing launches there would fail workloads that succeed."""
    explicit = getattr(cfg, "hbm_budget_bytes", None) if cfg is not None \
        else None
    if explicit is not None:
        return int(explicit) if explicit > 0 else None
    raw = os.environ.get(_HBM_BUDGET_ENV)
    if raw is not None:
        try:
            v = int(float(raw))  # OverflowError: 'inf' means unbounded too
        except (ValueError, OverflowError):
            print(f"ignoring malformed {_HBM_BUDGET_ENV}={raw!r}",
                  file=sys.stderr, flush=True)
            return None
        return v if v > 0 else None
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        return int(limit * _HBM_BUDGET_FRACTION) if limit > 0 else None
    except Exception:  # noqa: BLE001 -- no stats = no bound, never an error
        return None


def preflight_launch(qcap: int, ccap: int, k: int, s_total: int, *,
                     row_out: bool = False, site: str = "pallas",
                     budget: int | None = None) -> None:
    """HBM+VMEM preflight for a kernel launch: raise a structured
    :class:`LaunchBudgetError` (``kind == 'oom'``) BEFORE any grid is built
    when the launch cannot fit, instead of letting Mosaic/libtpu discover it
    mid-flight and wedge or kill the worker (the r5 clustered-input crash
    mode).  VMEM: the candidate axis must fit a 128-wide query block
    (pick_qsub > 0 -- wider query blocks only split further).  HBM: the
    modeled launch footprint must fit ``budget`` when one is known.  Callers
    that can demote (adaptive class routing) check :func:`hbm_fits` /
    :func:`pick_qsub` instead of calling this."""
    from ..utils.memory import LaunchBudgetError

    if pick_qsub(qcap, ccap, k, row_out) == 0:
        raise LaunchBudgetError(
            f"{site}: candidate axis ccap={ccap} (k={k}) exceeds the "
            f"{_VMEM_BUDGET} byte VMEM budget even at a 128-wide query "
            f"block; use a smaller config.supercell, backend='xla', or the "
            f"streamed route",
            requested=vmem_bytes_estimate(128, ccap, k, row_out),
            budget=_VMEM_BUDGET, site=site)
    if budget is not None:
        need = hbm_bytes_estimate(qcap, ccap, k, s_total, row_out)
        if need > budget:
            raise LaunchBudgetError(
                f"{site}: modeled launch footprint {need} bytes "
                f"(qcap={qcap}, ccap={ccap}, k={k}, supercells={s_total}) "
                f"exceeds the {budget} byte HBM budget; shard the problem, "
                f"lower config.supercell, or raise "
                f"config.hbm_budget_bytes / {_HBM_BUDGET_ENV}",
                requested=need, budget=budget, site=site)


def hbm_fits(qcap: int, ccap: int, k: int, s_total: int,
             row_out: bool = False, budget: int | None = None) -> bool:
    """True iff the modeled launch footprint fits ``budget`` (always True
    when unbounded).  The demotion predicate: adaptive class routing keys on
    this to stream a would-OOM class instead of refusing the whole solve."""
    return (budget is None
            or hbm_bytes_estimate(qcap, ccap, k, s_total, row_out) <= budget)


def launch_row_out(qcap: int, ccap: int, k: int, kernel: str,
                   epilogue: str) -> bool:
    """True iff this launch will actually take the row-major scatter path.
    Mirrors _topk_rows_or_transpose's gate exactly (kpass body only, row-out
    tile must fit VMEM -- ineligible scatter launches fall back to the
    gather kernel + XLA transpose).  The preflight/demotion callers MUST
    model the same layout the launch will allocate: the row-out output
    blocks pad k to 128 lanes, up to ~12.8x the gather layout's at k=10, so
    modeling the wrong layout either blesses a launch that OOMs or refuses
    a config the fallback would have solved."""
    return (epilogue == "scatter" and kernel == "kpass"
            and pick_qsub(qcap, ccap, k, row_out=True) > 0)


def _check_qcap(qcap: int) -> None:
    """qcap must be lane-aligned BEFORE the grid is built: pick_qsub
    128-rounds internally, so an unaligned qcap (say 100) would get qsub=128
    and a silently EMPTY grid (n_q = 100 // 128 == 0) whose output buffers
    come back uninitialized with no error (ADVICE r5).  Every production
    caller pads to 128 in its pack; this guard keeps the contract loud."""
    if qcap % 128 != 0:
        raise ValueError(
            f"qcap={qcap} is not a multiple of 128 (the TPU lane width): an "
            f"unaligned qcap would build an empty or misaligned kernel grid "
            f"with uninitialized outputs; pad the query capacity to 128 "
            f"(see _pack_inputs)")


def _kernel_rows(off_ref, qx_ref, qy_ref, qz_ref, cx_ref, cy_ref, cz_ref,
                 qid_ref, cid_ref, out_d_ref, out_i_ref, *, k: int,
                 exclude_self: bool):
    """Row-major twin of :func:`_kernel` for the scatter epilogue: the same
    k-pass min-and-mask, but the per-pass (Q,) winners accumulate into a
    (Q, k) tile that is written to the output block in one store -- the
    lane->sublane transpose the gather epilogue paid as a separate HBM pass
    (adaptive._rows2d) happens here on VMEM-resident registers instead.

    ``off_ref`` is the scalar-prefetched destination-block map (consumed by
    the output index map in _pallas_topk_rows, not read here): program
    (b, j) lands its rows at output row-block off[b*n_q + j], so fully
    padded sub-blocks route to a sink block and their write-back is skipped.
    """
    del off_ref  # consumed by the output BlockSpec index map
    d2 = None
    # same x,y,z accumulation order as knearests.cu:125
    for q_ref, c_ref in ((qx_ref, cx_ref), (qy_ref, cy_ref), (qz_ref, cz_ref)):
        qa = q_ref[0, 0, :].reshape(-1, 1)    # (Q, 1)
        ca = c_ref[0, 0, :].reshape(1, -1)    # (1, C)
        diff = qa - ca
        d2 = diff * diff if d2 is None else d2 + diff * diff
    ci = cid_ref[0, 0, :].reshape(1, -1)
    drop = ci == _PAD_C
    if exclude_self:
        qi = qid_ref[0, 0, :].reshape(-1, 1)
        drop = drop | (qi == ci)
    d2 = jnp.where(drop, jnp.inf, d2)
    q = d2.shape[0]
    if k <= _UNROLL_K_MAX:
        kd, ki = [], []
        for i in range(k):
            m = jnp.min(d2, axis=1)
            sel = d2 == m[:, None]
            bid = jnp.min(jnp.where(sel, ci, _BIG_ID), axis=1)
            kd.append(m)
            ki.append(bid)
            if i + 1 < k:
                d2 = jnp.where(sel & (ci == bid[:, None]), jnp.inf, d2)
        out_d_ref[:, :] = jnp.stack(kd, axis=1)
        out_i_ref[:, :] = jnp.stack(ki, axis=1)
    else:
        # large k: rolled loop (compile-time bound, like _kernel).  The
        # neighbor axis is on LANES here, where dynamic offsets are not
        # supported -- each pass lands its column through an iota mask on
        # loop-carried (Q, k) accumulators instead of a pl.ds store.
        lane_i = jax.lax.broadcasted_iota(jnp.int32, (q, k), 1)

        def body(i, carry):
            d2, acc_d, acc_i = carry
            m = jnp.min(d2, axis=1)
            sel = d2 == m[:, None]
            bid = jnp.min(jnp.where(sel, ci, _BIG_ID), axis=1)
            hit = lane_i == i
            acc_d = jnp.where(hit, m[:, None], acc_d)
            acc_i = jnp.where(hit, bid[:, None], acc_i)
            return (jnp.where(sel & (ci == bid[:, None]), jnp.inf, d2),
                    acc_d, acc_i)

        _, acc_d, acc_i = jax.lax.fori_loop(
            0, k, body, (d2, jnp.full((q, k), jnp.inf, jnp.float32),
                         jnp.full((q, k), _BIG_ID, jnp.int32)))
        out_d_ref[:, :] = acc_d
        out_i_ref[:, :] = acc_i


def _pallas_topk_rows(qx, qy, qz, cx, cy, cz, qid3, cid3, qcap: int,
                      ccap: int, k: int, exclude_self: bool, interpret: bool,
                      q_ok=None):
    """Scatter-epilogue launch: row-major ((S*qcap, k) dists, ids) straight
    from the kernel, no transpose pass and no raw (S, k, Q) intermediate.

    The output BlockSpec's index map is DATA-DEPENDENT: the per-program
    destination-block map (built here from ``q_ok`` when given) rides the
    scalar-prefetch channel (pltpu.PrefetchScalarGridSpec), so each program
    DMAs its (qsub, k) row block to a runtime-chosen offset.  Today the map
    encodes (supercell, query-sub-block) -> row block plus a sink block for
    fully padded sub-blocks (their rows are never read -- every consumer
    reads only valid slots through inv_flat/ClassPlan.tgt -- so skipping
    their write-back is free bandwidth); it is the hook per-class placement
    folds into.  Only the kpass extraction body exists in row-major form:
    the blocked kernel stays gather-layout (explicit-request-only since r5)
    and scatter-mode callers transpose its output in XLA instead."""
    _check_qcap(qcap)
    s_total = qx.shape[0]
    qsub = pick_qsub(qcap, ccap, k, row_out=True)
    if qsub == 0:
        # every production caller gates through _topk_rows_or_transpose;
        # launching the full tile here would just die later with an opaque
        # Mosaic VMEM error, so refuse loudly instead
        raise ValueError(
            f"row-out tile (qcap={qcap}, ccap={ccap}, k={k}) exceeds the "
            f"VMEM budget: gate on pick_qsub(row_out=True) and fall back "
            f"to the gather-layout launch (_topk_rows_or_transpose)")
    n_q = qcap // qsub
    n_blk = s_total * n_q
    if q_ok is not None:
        # sink fully-padded sub-blocks (block n_blk is the sink)
        blk_ok = q_ok.reshape(n_blk, qsub).any(axis=1)
        off = jnp.where(blk_ok, jnp.arange(n_blk, dtype=jnp.int32), n_blk)
    else:
        off = jnp.arange(n_blk, dtype=jnp.int32)
    q_spec = pl.BlockSpec((1, 1, qsub), lambda b, j, off: (b, 0, j))
    c_spec = pl.BlockSpec((1, 1, ccap), lambda b, j, off: (b, 0, 0))
    out_spec = pl.BlockSpec((qsub, k), lambda b, j, off: (off[b * n_q + j], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_total, n_q),
        in_specs=[q_spec, q_spec, q_spec, c_spec, c_spec, c_spec,
                  q_spec, c_spec],
        out_specs=[out_spec, out_spec],
    )
    out_d, out_i = pl.pallas_call(
        functools.partial(_kernel_rows, k=k, exclude_self=exclude_self),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(((n_blk + 1) * qsub, k), jnp.float32),
            jax.ShapeDtypeStruct(((n_blk + 1) * qsub, k), jnp.int32),
        ],
        interpret=interpret,
    )(off, qx, qy, qz, cx, cy, cz, qid3, cid3)
    # drop the sink block: rows [p*qsub, (p+1)*qsub) of the remainder are
    # program p = b*n_q + j, i.e. row-major (S*qcap, k) slot order
    return out_d[: s_total * qcap], out_i[: s_total * qcap]


def _pallas_topk(qx, qy, qz, cx, cy, cz, qid3, cid3, qcap: int, ccap: int,
                 k: int, exclude_self: bool, interpret: bool,
                 kernel: str = "kpass"):
    """Launch the kernel over a (supercell, query-sub-block) grid.  Returns
    ((S,k,Q) dists, (S,k,Q) ids) -- raw, untransposed.  ``kernel`` picks the
    extraction strategy ('kpass' | 'blocked', see config.KnnConfig.kernel);
    ineligible blocked shapes silently take the kpass body.

    When the full (qcap, ccap) tile exceeds the VMEM budget the query axis
    splits into qcap/qsub grid steps (pick_qsub): the candidate blocks'
    index map is constant over the inner axis, so Pallas keeps them
    resident across the sub-steps and only the (1, 1, qsub) query/output
    blocks move -- dense-blob classes (huge qcap) run on the kernel with no
    candidate re-fetch instead of demoting to the streamed scan."""
    from ..config import blocked_topm

    _check_qcap(qcap)
    s_total = qx.shape[0]
    qsub = pick_qsub(qcap, ccap, k)
    if qsub in (0, qcap):
        qsub = qcap  # ungated call (explicit backend='pallas'): full tile
    n_q = qcap // qsub
    m = blocked_topm(k, ccap) if kernel == "blocked" else 0
    if m and n_q > 1:
        # the blocked body's VMEM survivor-pool scratch is sized by the full
        # qcap; blocked shapes are only eligible un-split (it is explicit-
        # request-only anyway -- config.resolve_kernel)
        m = 0
    scratch_shapes = []
    if m:
        body = functools.partial(_kernel_blocked, k=k, m=m,
                                 exclude_self=exclude_self)
        # Candidates as (S, G, 128): one sublane row per lane block, so the
        # kernel's per-block access is a dynamic-SUBLANE slice (see
        # _kernel_blocked docstring).  HBM-side reshape only.
        g = ccap // 128
        cx, cy, cz = (a.reshape(s_total, g, 128) for a in (cx, cy, cz))
        cid3 = cid3.reshape(s_total, g, 128)
        c_spec = pl.BlockSpec((1, g, 128), lambda b, j: (b, 0, 0),
                              memory_space=pltpu.VMEM)
        # VMEM survivor pool for the rolled stage-1 path (unused but cheap
        # -- tens of KB -- when the unrolled path keeps it in registers)
        scratch_shapes = [pltpu.VMEM((g * m, qcap), jnp.float32),
                          pltpu.VMEM((g * m, qcap), jnp.int32),
                          pltpu.VMEM((g, qcap), jnp.float32)]
    else:
        body = functools.partial(_kernel, k=k, exclude_self=exclude_self)
        c_spec = pl.BlockSpec((1, 1, ccap), lambda b, j: (b, 0, 0),
                              memory_space=pltpu.VMEM)
    q_spec = pl.BlockSpec((1, 1, qsub), lambda b, j: (b, 0, j),
                          memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((1, k, qsub), lambda b, j: (b, 0, j),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        body,
        grid=(s_total, n_q),
        in_specs=[q_spec, q_spec, q_spec, c_spec, c_spec, c_spec,
                  q_spec, c_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((s_total, k, qcap), jnp.float32),
            jax.ShapeDtypeStruct((s_total, k, qcap), jnp.int32),
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(qx, qy, qz, cx, cy, cz, qid3, cid3)


def _topk_rows_or_transpose(qx, qy, qz, cx, cy, cz, qid3, cid3, qcap: int,
                            ccap: int, k: int, exclude_self: bool,
                            interpret: bool, q_ok, kernel: str = "kpass"):
    """Row-major ((S*qcap, k) dists, ids) for the scatter epilogue, behind
    the ONE eligibility gate every consumer shares: the scalar-prefetch
    row-major body exists only for the kpass extraction (`blocked` has no
    row-out twin) and only when the (qsub, k) row-out tile fits VMEM
    (pick_qsub row_out=True); ineligible launches keep the gather-layout
    kernel and transpose its raw (S, k, Q) output with XLA -- byte-identical
    either way, the transpose just isn't fused into the kernel."""
    if kernel == "kpass" and pick_qsub(qcap, ccap, k, row_out=True):
        return _pallas_topk_rows(qx, qy, qz, cx, cy, cz, qid3, cid3, qcap,
                                 ccap, k, exclude_self, interpret, q_ok=q_ok)
    out_d, out_i = _pallas_topk(qx, qy, qz, cx, cy, cz, qid3, cid3, qcap,
                                ccap, k, exclude_self, interpret, kernel)
    return (jnp.swapaxes(out_d, 1, 2).reshape(-1, k),
            jnp.swapaxes(out_i, 1, 2).reshape(-1, k))


def _pack_inputs(points: jax.Array, starts: jax.Array, counts: jax.Array,
                 own: jax.Array, cand: jax.Array, qcap: int, ccap: int):
    """Shared pack-and-gather block: CSR slot packing + coordinate/id blocks
    in kernel layout.  Single source of truth for the packing contract, used
    by build_pack (cached single-chip) and the adaptive class solvers.

    Returns (q_idx, q_ok, qx, qy, qz, cx, cy, cz, qid3, cid3) with qcap
    rounded to the output lane multiple (128)."""
    s_total = own.shape[0]
    qcap = -(-qcap // 128) * 128
    q_idx, q_ok = pack_cells(own, starts, counts, qcap)
    c_idx, c_ok = pack_cells(cand, starts, counts, ccap)
    g = ccap // 128
    if ccap % 128 == 0 and g > 1:
        # Interleave candidate slots across 128-lane blocks (slot r*G+g ->
        # lane g*128+r): CSR packing puts spatially-adjacent candidates in
        # adjacent slots, which would concentrate every query's near
        # neighbors into one or two lane blocks and make the blocked
        # kernel's per-block top-m overflow (deficit) routinely.  Round-robin
        # spreads them evenly.  Order-insensitive consumers (the kpass
        # kernel, tie-breaks by min id) are unaffected.
        c_idx = c_idx.reshape(s_total, 128, g).transpose(0, 2, 1).reshape(
            s_total, ccap)
        c_ok = c_ok.reshape(s_total, 128, g).transpose(0, 2, 1).reshape(
            s_total, ccap)
    # Pad rows keep garbage (point-0) coords on both sides: padded candidates
    # are masked inside the kernel by their _PAD_C id, and padded query rows
    # are dropped by the q_ok scatter in the epilogue -- no FAR fill passes.
    # Coordinates one axis at a time as (S, 1, cap) on BOTH sides: the slot
    # axis stays on the 128-lane dimension, so there is no transpose pass and
    # no 3-wide minor axis for the TPU tiler to pad 42.7x (see PallasPack).
    axes = points.T  # (3, n)
    qx, qy, qz = (jnp.take(axes[ax], q_idx, axis=0).reshape(s_total, 1, qcap)
                  for ax in range(3))
    cx, cy, cz = (jnp.take(axes[ax], c_idx, axis=0).reshape(s_total, 1, ccap)
                  for ax in range(3))
    qid3 = jnp.where(q_ok, q_idx, _PAD_Q).astype(jnp.int32).reshape(
        s_total, 1, qcap)
    cid3 = jnp.where(c_ok, c_idx, _PAD_C).astype(jnp.int32).reshape(
        s_total, 1, ccap)
    return q_idx, q_ok, qx, qy, qz, cx, cy, cz, qid3, cid3


@jax.jit
def build_pack(points: jax.Array, starts: jax.Array, counts: jax.Array,
               plan: SolvePlan) -> PallasPack:
    """Pack CSR slots and gather all kernel inputs (once per problem)."""
    s_total = plan.n_chunks * plan.batch
    own = plan.own_cells.reshape(s_total, -1)
    cand = plan.cand_cells.reshape(s_total, -1)
    q_idx, q_ok, qx, qy, qz, cx, cy, cz, qid3, cid3 = _pack_inputs(
        points, starts, counts, own, cand, plan.qcap, plan.ccap)
    # Invert the slot partition once at prepare time (every stored point owns
    # exactly one valid slot), so steady-state solves gather instead of
    # scatter.  This is the only scatter left, and it runs once per problem.
    n = points.shape[0]
    qcap = qx.shape[2]
    flat_ids = jnp.arange(s_total * qcap, dtype=jnp.int32)
    safe = jnp.where(q_ok.reshape(-1), q_idx.reshape(-1), n)
    inv_flat = jnp.zeros((n,), jnp.int32).at[safe].set(flat_ids, mode="drop")
    return PallasPack(
        qx=qx, qy=qy, qz=qz, cx=cx, cy=cy, cz=cz, qid3=qid3, cid3=cid3,
        q_idx=q_idx, q_ok=q_ok,
        lo=plan.box_lo.reshape(s_total, 3), hi=plan.box_hi.reshape(s_total, 3),
        inv_flat=inv_flat, inv_sc=inv_flat // qcap, tgt=safe,
        qcap=int(qcap), ccap=int(plan.ccap), s_total=int(s_total))


@functools.partial(jax.jit, static_argnames=("k", "exclude_self", "domain",
                                             "interpret", "kernel",
                                             "epilogue"))
def _solve_packed(pack: PallasPack, points: jax.Array, k: int,
                  exclude_self: bool, domain: float, interpret: bool = False,
                  kernel: str = "kpass", epilogue: str = "gather"):
    """Steady-state solve: kernel launch + un-pad + certificates.
    Returns ((n,k) ids, (n,k) d2, (n,) certified), sorted indexing.

    epilogue='gather': pack.inv_flat maps every output row to its kernel
    slot, sentinel fixups and the certificate run on the (n, k) result
    (smaller than the padded (S, Q, k) block), and the query coordinate of
    sorted row r is just points[r] -- no scatter, no padded-side compute.
    epilogue='scatter': the kernel itself emits row-major slot rows at
    scalar-prefetched block offsets (_pallas_topk_rows) and the valid rows
    scatter through the forward slot map into the final buffer -- no raw
    (S, k, Q) intermediate and no index composition.  Byte-identical.
    """
    n = points.shape[0]
    qcap = pack.qcap
    if epilogue == "scatter":
        rows_d, rows_i = _topk_rows_or_transpose(
            pack.qx, pack.qy, pack.qz, pack.cx, pack.cy, pack.cz,
            pack.qid3, pack.cid3, qcap, pack.ccap, k, exclude_self,
            interpret, pack.q_ok, kernel)
        row_d = jnp.full((n, k), jnp.inf, jnp.float32).at[pack.tgt].set(
            rows_d, mode="drop")
        row_i = jnp.full((n, k), INVALID_ID, jnp.int32).at[pack.tgt].set(
            rows_i, mode="drop")
    else:
        out_d, out_i = _pallas_topk(pack.qx, pack.qy, pack.qz,
                                    pack.cx, pack.cy, pack.cz,
                                    pack.qid3, pack.cid3, qcap, pack.ccap, k,
                                    exclude_self, interpret, kernel)

        # One gather straight from the kernel's raw (S, k, Q) layout: row r
        # is supercell inv_sc[r], query lane inv_flat[r] % qcap, neighbor i
        # at 1-D offset sc*k*qcap + i*qcap + lane.  Composing the index maps
        # kills the (S,k,Q)->(S*Q,k) transposes that used to precede the row
        # gather (VERDICT r3 weak #2: they survived in the hot path).
        if pack.s_total * k * qcap > 2**31 - 1:
            raise ValueError(
                f"raw kernel output exceeds int32 indexing "
                f"({pack.s_total * k * qcap} elements): shard the problem or "
                f"reduce k")  # wrapped indices would gather wrong-yet-certifiable rows
        lane = pack.inv_flat % qcap
        base = pack.inv_sc * (k * qcap) + lane             # (n,)
        idx = base[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :] * qcap
        row_d = jnp.take(out_d.reshape(-1), idx)           # (n, k) ascending
        row_i = jnp.take(out_i.reshape(-1), idx)
    # Certificate from the RAW k-th value, before sanitization: the blocked
    # kernel marks deficit rows with NaN there, and NaN <= margin is false
    # even for an infinite margin (inf would wrongly certify).
    raw_kth = row_d[:, k - 1]
    ok = jnp.isfinite(row_d)
    row_i = jnp.where(ok, row_i, INVALID_ID)
    row_d = jnp.where(ok, row_d, jnp.inf)

    lo = jnp.take(pack.lo, pack.inv_sc, axis=0)            # (n, 3)
    hi = jnp.take(pack.hi, pack.inv_sc, axis=0)
    cert = raw_kth <= _margin_sq(points[:, None, :], lo, hi, domain)[:, 0]
    return row_i, row_d, cert, jnp.sum(~cert, dtype=jnp.int32)


def solve_pallas(grid: GridHash, cfg, plan: SolvePlan | None = None,
                 pack: PallasPack | None = None) -> KnnResult:
    """Grid-accelerated all-points kNN via the fused Pallas kernel.  Same
    contract as solve.solve (sorted indexing, uncertified rows left for the
    api-level exact fallback).  Pass a prebuilt ``pack`` for steady-state
    repeat solves (api.KnnProblem caches one)."""
    from ..config import resolve_kernel

    if plan is None:
        plan = build_plan(grid, cfg)
    kernel = resolve_kernel(cfg.effective_kernel(), cfg.k, plan.ccap)
    epilogue = cfg.resolved_epilogue()
    # HBM+VMEM preflight: refuse a would-OOM launch with a structured
    # oom-kind error BEFORE any pack allocation or kernel grid exists --
    # the supervised driver records it as a FailureRecord row instead of
    # losing the process (DESIGN.md section 9).  Modeled at the layout the
    # launch will actually allocate (launch_row_out): a row-out-ineligible
    # scatter config falls back to the gather kernel, so it is gated -- and
    # HBM-modeled -- as gather, not refused.
    preflight_launch(plan.qcap, plan.ccap, cfg.k,
                     plan.n_chunks * plan.batch,
                     row_out=launch_row_out(plan.qcap, plan.ccap, cfg.k,
                                            kernel, epilogue),
                     site="solve_pallas", budget=hbm_budget_bytes(cfg))
    if pack is None:
        pack = build_pack(grid.points, grid.cell_starts, grid.cell_counts, plan)
    nbr, d2, cert, n_unc = _solve_packed(
        pack, grid.points, cfg.k, cfg.exclude_self, grid.domain,
        cfg.interpret, kernel, epilogue)
    return KnnResult(neighbors=nbr, dists_sq=d2, certified=cert,
                     uncert_count=n_unc)
