"""Deterministic uniform-grid spatial hash build (sort-based counting sort).

Reference parity (C2, /root/reference/knearests.cu:22-60,152-201): the reference
builds its grid with three CUDA kernels -- ``count`` (atomicAdd histogram),
``reserve`` (atomicAdd segment allocation, *nondeterministic* segment order), and
``store`` (atomicAdd scatter recording a permutation).  XLA has no global atomics
and does not need them: a single stable sort by cell id yields the same CSR layout
-- sorted points, segment starts, segment counts, and the sorted-position ->
original-index permutation -- fully deterministically (fixing the reference's
nondeterministic ``reserve`` ordering, knearests.cu:40-48, flagged in SURVEY.md
section 2.2).

Cell addressing: like the reference's ``cellFromPoint`` (knearests.cu:22-30),
points are assumed to lie in ``[0, domain]^3`` and indices are clamped to the
grid.  Linearization here is ``x + y*dim + z*dim^2`` -- x fastest, z slowest -- so
that z-slabs of cells are contiguous in the sorted point array, which is what the
sharded path slices along (parallel/sharded.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import DEFAULT_CELL_DENSITY, DOMAIN_SIZE, grid_dim_for


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("points", "permutation", "cell_starts", "cell_counts"),
    meta_fields=("dim", "domain"),
)
@dataclasses.dataclass(frozen=True)
class GridHash:
    """CSR grid layout (reference analog: kn_problem, /root/reference/knearests.h:3-16).

    Attributes:
      points: (n, 3) f32 -- points reordered by cell (ref: d_stored_points).
      permutation: (n,) i32 -- sorted position -> original index (ref: d_permutation).
      cell_starts: (dim^3,) i32 -- CSR segment start per cell (ref: d_ptrs).
      cell_counts: (dim^3,) i32 -- points per cell (ref: d_counters).
      dim: cells per axis (static; ref: kn_problem.dimx/y/z, always cubic).
      domain: side length of the point domain (static; ref hard-codes 1000).
    """

    points: jax.Array
    permutation: jax.Array
    cell_starts: jax.Array
    cell_counts: jax.Array
    dim: int
    domain: float

    @property
    def n_points(self) -> int:
        return self.points.shape[0]

    @property
    def n_cells(self) -> int:
        return self.dim ** 3


def cell_coords(points: jax.Array, dim: int, domain: float = DOMAIN_SIZE) -> jax.Array:
    """(n, 3) integer cell coordinates, clamped to the grid.

    Reference: cellFromPoint (/root/reference/knearests.cu:22-30) -- same
    floor-scale-clamp, but kept as per-axis (i, j, k) rather than immediately
    linearized, so ring traversal can clamp per axis instead of inheriting the
    reference's linearized-delta boundary wraparound (SURVEY.md section 2.2).
    """
    scaled = points * (dim / domain)
    return jnp.clip(scaled.astype(jnp.int32), 0, dim - 1)


def cell_coords_host(points: np.ndarray, dim: int,
                     domain: float = DOMAIN_SIZE) -> np.ndarray:
    """Host numpy twin of :func:`cell_coords` -- identical f32 scale and
    i32 floor-clamp, so host-side query bucketing agrees with the device
    grid bit-for-bit with NO device round trip (the query paths used to
    stage queries up and read coordinates back once per call)."""
    scaled = np.asarray(points, np.float32) * np.float32(dim / domain)
    return np.clip(scaled.astype(np.int32), 0, dim - 1)


def linearize(coords: jax.Array, dim: int) -> jax.Array:
    """Linear cell id with x fastest, z slowest: x + dim*(y + dim*z)."""
    return coords[..., 0] + dim * (coords[..., 1] + dim * coords[..., 2])


def cell_ids(points: jax.Array, dim: int, domain: float = DOMAIN_SIZE) -> jax.Array:
    return linearize(cell_coords(points, dim, domain), dim)


@functools.partial(jax.jit, static_argnames=("dim", "domain"))
def _build(points: jax.Array, dim: int, domain: float) -> GridHash:
    n = points.shape[0]
    ncells = dim ** 3
    cids = cell_ids(points, dim, domain)
    # Stable argsort keeps same-cell points in input order: deterministic, and the
    # permutation is exactly the reference's d_permutation contract (sorted
    # position -> original id, knearests.cu:51-60).
    order = jnp.argsort(cids, stable=True).astype(jnp.int32)
    sorted_points = jnp.take(points, order, axis=0)
    counts = jnp.zeros((ncells,), jnp.int32).at[cids].add(1)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix sum (deterministic
    # replacement for the reference's atomicAdd segment allocator, knearests.cu:40-48)
    return GridHash(points=sorted_points, permutation=order,
                    cell_starts=starts.astype(jnp.int32),
                    cell_counts=counts, dim=dim, domain=domain)


def build_grid(points: jax.Array, dim: int | None = None,
               density: float = DEFAULT_CELL_DENSITY,
               domain: float = DOMAIN_SIZE) -> GridHash:
    """Build the spatial hash (reference analog: kn_firstbuild via kn_prepare,
    /root/reference/knearests.cu:152-201,235-344).

    Host input goes through the checked staging helper (utils/memory.to_device,
    the gpuMallocNCopy analog): a failed H2D placement surfaces shape/dtype and
    the cause instead of a bare runtime error.  Device-resident input is used
    as-is.
    """
    if dim is None:
        dim = grid_dim_for(points.shape[0], density)
    if isinstance(points, jax.Array):
        staged = jnp.asarray(points, jnp.float32)
    else:
        from ..utils.memory import to_device

        staged = to_device(points, validate=False)  # validate_points upstream
    return _build(staged, dim=int(dim), domain=float(domain))


def delta_csr_host(points: np.ndarray, dim: int,
                   domain: float = DOMAIN_SIZE):
    """Host-side CSR layout of a DELTA point set on an existing grid's cell
    partition -- the incremental-update twin of :func:`_build`, run only
    over the mutated points (serve/delta.py, DESIGN.md section 13).

    The same count / reserve / scatter structure as the reference's three
    grid-build kernels (knearests.cu:22-60), in its deterministic sort-based
    form -- ``count`` = unique-cell occupancy counts, ``reserve`` =
    exclusive prefix sum, ``scatter`` = stable argsort by cell id -- held
    COMPACT: segments index by dirty-cell *position*, not cell id, so the
    cost is O(d log d) in the delta alone (never O(dim^3)) and a moving
    point cloud pays per-mutation cost proportional to its delta, not a
    full re-sort + device restage.

    Returns (order, dirty, starts, counts): ``order`` sorts delta points
    cell-major (stable); ``dirty`` the sorted unique cell ids the delta
    occupies (the dirty-cell overlay); ``starts``/``counts`` the CSR
    segment of each dirty cell within ``order`` (``order[starts[j] :
    starts[j] + counts[j]]`` are the delta rows in cell ``dirty[j]`` --
    what the overlay's pruned delta launch gathers its candidates
    through, serve/delta.py)."""
    coords = cell_coords_host(points, dim, domain)
    cids = coords[:, 0] + dim * (coords[:, 1] + dim * coords[:, 2])
    order = np.argsort(cids, kind="stable").astype(np.int32)
    dirty, counts = np.unique(cids, return_counts=True)
    counts = counts.astype(np.int32)
    starts = (np.cumsum(counts) - counts).astype(np.int32)
    return order, dirty.astype(np.int32), starts, counts


def cell_min_d2_host(queries: np.ndarray, cells: np.ndarray, dim: int,
                     domain: float = DOMAIN_SIZE) -> np.ndarray:
    """(m, c) lower bound on the squared distance from each query to any
    point inside each cell -- the dirty-cell pruning bound of the delta
    overlay (a delta launch is skipped when every query's bound to every
    dirty cell exceeds its current k-th distance).

    Conservative by construction: computed in f64 against the exact cell
    box [lo, hi], with the per-axis clamp max(lo - q, 0, q - hi).  A bound
    of 0 (query inside the cell) never prunes."""
    w = np.float64(domain) / dim  # kntpu-ok: wide-dtype -- conservative pruning bound computed in f64 on host, never staged
    cx = cells % dim
    cy = (cells // dim) % dim
    cz = cells // (dim * dim)
    lo = np.stack([cx, cy, cz], axis=-1).astype(np.float64) * w  # kntpu-ok: wide-dtype -- conservative pruning bound computed in f64 on host, never staged
    hi = lo + w
    q = np.asarray(queries, np.float64)[:, None, :]  # kntpu-ok: wide-dtype -- conservative pruning bound computed in f64 on host, never staged
    d = np.maximum(np.maximum(lo[None] - q, q - hi[None]), 0.0)
    return (d * d).sum(-1)


def unpermute_neighbors(grid: GridHash, neighbors_sorted: jax.Array,
                        fill: int = -1) -> jax.Array:
    """Translate a (n, k) neighbor table from sorted indexing to original ids.

    The reference's search kernel emits neighbor ids that index the *sorted*
    point array, and the caller untangles them with the permutation
    (/root/reference/test_knearests.cu:155-160:
    ``neighbors[perm[i]*K+j] = perm[knearests[i*K+j]]``).  Same contract here;
    `fill` (< 0) marks not-found slots (the reference uses UINT_MAX).
    """
    from .topk import INVALID_ID, translate_ids

    if grid.n_points == 0:
        # empty problem (degraded mode): nothing to translate, and a take
        # from the empty permutation would not broadcast
        return neighbors_sorted
    # the one shared sentinel-preserving translation (topk.translate_ids);
    # only a non-default fill needs the extra rewrite
    mapped = translate_ids(neighbors_sorted, grid.permutation)
    if fill != INVALID_ID:
        mapped = jnp.where(neighbors_sorted >= 0, mapped, fill)
    out = jnp.zeros_like(mapped)
    return out.at[grid.permutation].set(mapped)
