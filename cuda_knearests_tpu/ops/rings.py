"""Chebyshev ring traversal schedule with distance lower bounds.

Reference parity (C3, /root/reference/knearests.cu:254-300): the reference
precomputes, on the host, the linearized offsets of every cell in Chebyshev rings
0..Nmax-1 around a query cell, each ring carrying a conservative lower bound on
the squared distance from anywhere in the center cell to that ring
(``((ring-1) * cell_width)^2``, knearests.cu:278-279).  Ring-ordered traversal +
that bound gives the provable early exit (knearests.cu:116).

Differences from the reference (deliberate, SURVEY.md section 2.2):
  * Offsets are kept per-axis ``(di, dj, dk)`` instead of linearized deltas, so
    grid-boundary handling is an explicit clamp/mask rather than the reference's
    silent wraparound into adjacent rows/slabs (knearests.cu:119).
  * The schedule is a static device array usable inside ``lax.while_loop`` /
    Pallas grids, not a host loop artifact.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np


class RingSchedule(NamedTuple):
    """Static traversal schedule for rings 0..nmax-1.

    offsets:    (m, 3) i32 -- (di, dj, dk) per candidate cell, ring-major order.
    ring_of:    (m,) i32   -- Chebyshev ring of each offset.
    ring_start: (nmax+1,) i32 -- offsets of ring r live at [ring_start[r], ring_start[r+1]).
    """

    offsets: np.ndarray
    ring_of: np.ndarray
    ring_start: np.ndarray

    @property
    def nmax(self) -> int:
        return len(self.ring_start) - 1


def ring_schedule(nmax: int) -> RingSchedule:
    """All (2*nmax-1)^3 cell offsets around a center cell, ordered by ring.

    Ring 0 is the center cell itself; ring r (1 <= r < nmax) is the Chebyshev
    shell ``max(|di|,|dj|,|dk|) == r`` (reference loop at knearests.cu:263-287).
    Within a ring, order is lexicographic (deterministic).
    """
    if nmax < 1:
        raise ValueError("nmax must be >= 1")
    r = np.arange(-(nmax - 1), nmax, dtype=np.int32)
    di, dj, dk = np.meshgrid(r, r, r, indexing="ij")
    offs = np.stack([di.ravel(), dj.ravel(), dk.ravel()], axis=1)
    ring = np.abs(offs).max(axis=1).astype(np.int32)
    # stable sort by ring keeps lexicographic order within each shell
    order = np.argsort(ring, kind="stable")
    offs, ring = offs[order], ring[order]
    ring_start = np.searchsorted(ring, np.arange(nmax + 1), side="left").astype(np.int32)
    return RingSchedule(offsets=np.ascontiguousarray(offs),
                        ring_of=np.ascontiguousarray(ring),
                        ring_start=ring_start)


def ring_lower_bounds_sq(nmax: int, cell_width: float) -> np.ndarray:
    """(nmax,) f32 -- conservative min squared distance from any point in the
    center cell to any point of ring r.

    A point anywhere in the center cell is at least ``(r-1) * cell_width`` away
    from every cell of ring r (0 for rings 0 and 1) -- the same bound the
    reference uses (knearests.cu:278-279).  Non-decreasing in r by construction,
    which is what makes "kth_best < bound(r)" a valid stopping rule.
    """
    # f64 on purpose: the bound must stay conservative, so the arithmetic
    # runs at full host precision and rounds to f32 exactly once at the end
    r = np.arange(nmax, dtype=np.float64)  # kntpu-ok: wide-dtype -- single terminal round-off (see above)
    d = np.maximum(r - 1.0, 0.0) * cell_width
    return (d * d).astype(np.float32)


def box_margin_bound_sq(query: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                        domain: float) -> np.ndarray:
    """Squared distance from each query to the *complement* of box [lo, hi].

    Used to certify supercell-tiled results: every un-gathered point lies outside
    the dilated candidate box, hence at distance >= the query's margin to the box
    boundary.  Sides of the box at or beyond the domain boundary contribute no
    constraint (all points live in [0, domain]^3).  Pure-numpy twin of the jnp
    version in ops/solve.py, kept for tests.
    """
    margins = []
    for ax in range(3):
        m_lo = np.where(lo[..., ax] <= 0.0, np.inf, query[..., ax] - lo[..., ax])
        m_hi = np.where(hi[..., ax] >= domain, np.inf, hi[..., ax] - query[..., ax])
        margins.append(np.minimum(m_lo, m_hi))
    m = np.maximum(np.minimum.reduce(margins), 0.0)
    return np.where(np.isinf(m), np.inf, m * m)


def dilated_box(sc_coord: Tuple[int, int, int], supercell: int, radius: int,
                dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """Cell-coordinate bounds [lo, hi) of a supercell dilated by `radius` cells,
    clamped to the grid."""
    lo = np.maximum(np.asarray(sc_coord) * supercell - radius, 0)
    hi = np.minimum(np.asarray(sc_coord) * supercell + supercell + radius, dim)
    return lo.astype(np.int32), hi.astype(np.int32)


def summed_area_table(counts3: np.ndarray) -> np.ndarray:
    """(dz+1, dy+1, dx+1) i64 inclusive 3D prefix sums of per-cell counts --
    build once, query many boxes via box_sums(..., sat=...).  Accepts
    non-cubic windows (the sharded per-chip planner's z-slab case)."""
    dz, dy, dx = counts3.shape
    # i64 on purpose (and in the docstring contract): prefix sums reach the
    # total point count, which exceeds i32 at the >2B-point scale the
    # sharded roadmap targets -- host-only, never staged to a device
    sat = np.zeros((dz + 1, dy + 1, dx + 1), dtype=np.int64)  # kntpu-ok: wide-dtype -- population prefix sums (see above)
    sat[1:, 1:, 1:] = counts3.cumsum(0).cumsum(1).cumsum(2)
    return sat


def box_sums(counts3: np.ndarray, lo: np.ndarray, hi: np.ndarray,
             sat: np.ndarray | None = None) -> np.ndarray:
    """Sum of per-cell counts over boxes [lo, hi) via a 3D summed-area table.

    counts3 is (dz,dy,dx) indexed [z,y,x] (cubic or a z-slab window); lo/hi
    are (m,3) as (x,y,z).  Pass a precomputed ``sat`` (summed_area_table) when
    querying many box sets against the same grid.  The host-side occupancy
    primitive behind both the capacity planners (ops/solve.py,
    ops/adaptive.py) and ring_occupancy.
    """
    dz, dy, dx = counts3.shape
    if sat is None:
        sat = summed_area_table(counts3)
    dims = np.array([dx, dy, dz])
    lo = np.clip(lo, 0, dims)
    hi = np.clip(hi, 0, dims)
    x0, y0, z0 = lo[:, 0], lo[:, 1], lo[:, 2]
    x1, y1, z1 = hi[:, 0], hi[:, 1], hi[:, 2]
    s = (sat[z1, y1, x1] - sat[z0, y1, x1] - sat[z1, y0, x1] - sat[z1, y1, x0]
         + sat[z0, y0, x1] + sat[z0, y1, x0] + sat[z1, y0, x0] - sat[z0, y0, x0])
    return s


def ring_occupancy(counts3: np.ndarray, sc_coords: np.ndarray, supercell: int,
                   rmax: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-supercell cumulative point and cell counts of the dilation rings.

    The occupancy-resolved version of the reference's ring schedule: where the
    reference walks ring offsets one query at a time (knearests.cu:113-136),
    the TPU planner asks, per *supercell*, how many points (and how many
    in-grid cells) each dilation radius r = 0..rmax captures -- the signal the
    adaptive planner (ops/adaptive.py) turns into per-supercell radii.

    Returns (points_cum, cells_cum), both (num_sc, rmax+1) i64, where
    column r covers the box [sc*s - r, sc*s + s + r) clamped to the grid.
    """
    dim = counts3.shape[0]
    num_sc = sc_coords.shape[0]
    # i64 per the documented contract: cumulative point populations (see
    # summed_area_table -- same >i32 headroom rationale, host-only)
    pts = np.empty((num_sc, rmax + 1), np.int64)    # kntpu-ok: wide-dtype -- population sums (see above)
    cells = np.empty((num_sc, rmax + 1), np.int64)  # kntpu-ok: wide-dtype -- population sums (see above)
    base_lo = sc_coords * supercell
    base_hi = base_lo + supercell
    sat = summed_area_table(counts3)  # one build for all rmax+1 box queries
    for r in range(rmax + 1):
        lo = np.clip(base_lo - r, 0, dim)
        hi = np.clip(base_hi + r, 0, dim)
        pts[:, r] = box_sums(counts3, lo, hi, sat=sat)
        cells[:, r] = np.prod(hi - lo, axis=1)
    return pts, cells
