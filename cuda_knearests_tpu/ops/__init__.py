from . import gridhash, rings, solve, topk

__all__ = ["gridhash", "rings", "solve", "topk"]
