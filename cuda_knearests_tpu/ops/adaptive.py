"""Adaptive supercell capacities: per-supercell radii + size classes.

Reference parity (the adaptive character of C4): the reference's search kernel
grows each query's window ring by ring and stops *individually* when the ring
bound proves completeness (/root/reference/knearests.cu:113-136, early exit
:116) -- dense regions do little work, sparse regions walk farther.  Round 1's
planner replaced that with ONE global dilation radius and ONE global
(qcap, ccap) pair, measured as maxima over all supercells (ops/solve.py
global_schedule): on skewed data a single dense region inflates every tile,
trips the kernel's VMEM gate, and demotes the whole solve to the slow path.

This module restores the adaptivity at supercell granularity, TPU-style
(static shapes per *class* instead of divergence per query):

  1. **Per-supercell radius** from local ring occupancy
     (rings.ring_occupancy): each supercell gets the smallest dilation whose
     local point density says the k-th neighbor distance fits inside the
     certified margin -- the planner's version of the reference's per-query
     ring walk, decided on the host at prepare time.
  2. **Capacity classes**: supercells are grouped by radius and bucketed by
     candidate count, giving a handful of (radius, qcap, ccap) classes.  Each
     class launches its own fused Pallas kernel when its tile fits VMEM;
     classes that don't fit stream their candidates through a memory-bounded
     merge_topk scan instead of demoting everything.  Supercells with no
     queries are dropped entirely.
  3. One **gather epilogue** over the concatenated class outputs (the
     slot-partition inverse, as in pallas_solve.PallasPack.inv_flat).

Certificates and the exact brute-force fallback are unchanged -- radii only
tune how often certification succeeds, never correctness.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import KnnConfig, default_ring_radius
from ..obs import spans as _spans
from ..runtime import dispatch as _dispatch
from ..utils.profiling import annotate
from .gridhash import GridHash
from .rings import ring_occupancy
from .solve import (KnnResult, _boxes_grid, _box_cell_ids, _margin_sq,
                    _round_up, pack_cells)
from .topk import (INVALID_ID, init_topk, masked_topk, merge_topk,
                   translate_ids)


def select_radii(points_cum: np.ndarray, cells_cum: np.ndarray, k: int,
                 rmax: int) -> np.ndarray:
    """Smallest per-supercell dilation radius consistent with local density.

    For each supercell and candidate radius r, estimate the local density
    rho(r) = points/cell over the r-dilated box, convert it to the expected
    k-th neighbor distance in cell widths (the same model as
    config.default_ring_radius, but with *local* instead of global density),
    and accept the smallest r >= that estimate + 1 cell of slack.  Supercells
    whose neighborhood stays too sparse get rmax (their certificates still
    guard exactness; the brute fallback resolves any failures).
    """
    num_sc = points_cum.shape[0]
    radii = np.full((num_sc,), rmax, np.int32)
    unassigned = np.ones((num_sc,), bool)
    for r in range(1, rmax + 1):
        rho = points_cum[:, r] / np.maximum(cells_cum[:, r], 1)
        r_exp = np.cbrt(3.0 * k / (4.0 * math.pi * np.maximum(rho, 1e-12)))
        ok = unassigned & (r >= np.ceil(r_exp) + 1.0)
        radii[ok] = r
        unassigned &= ~ok
    return radii


# Dense-route ceiling: one (rows, qcap, ccap) f32 tile per scan step must
# stay within this budget or the class streams instead.
_DENSE_TILE_BYTES = 128 << 20


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("qx", "qy", "qz", "cx", "cy", "cz", "qid3", "cid3"),
    meta_fields=(),
)
@dataclasses.dataclass(frozen=True)
class ClassPack:
    """Prepacked kernel inputs for one pallas-routed class (the named twin of
    pallas_solve._pack_inputs' tail): per-axis (Sc, 1, qcap)/(Sc, 1, ccap)
    coordinate lane blocks + slot-id blocks.

    Reuse contract: these blocks are gathers of the *exact* points/starts/
    counts arrays passed to _prepack_kernel_inputs -- a consumer reusing them
    (e.g. _query_class's candidate half) must be solving against that same
    CSR.  Mixing a plan with re-gridded data would compute wrong neighbors
    that still certify; reuse sites assert the derivable half of the contract
    (block shapes vs the plan's caps) at trace time."""

    qx: jax.Array
    qy: jax.Array
    qz: jax.Array
    cx: jax.Array
    cy: jax.Array
    cz: jax.Array
    qid3: jax.Array
    cid3: jax.Array


@dataclasses.dataclass(frozen=True)
class ClassSpec:
    """Host-side description of one capacity class (all-static)."""

    rows: np.ndarray      # (Sc,) indices into the global supercell list
    radius: int
    qcap: int             # per-supercell query capacity (pre-lane-rounding)
    qcap_pad: int         # capacity as laid out by the class solver
    ccap: int
    route: str            # 'pallas' | 'dense' | 'streamed' | 'mxu'

    @property
    def use_pallas(self) -> bool:
        return self.route == "pallas"


def build_class_specs(own_n: np.ndarray, pts_cum: np.ndarray,
                      radii: np.ndarray, cfg: KnnConfig,
                      on_kernel_platform: bool) -> Tuple[ClassSpec, ...]:
    """Partition nonempty supercells into <= cfg.max_classes capacity classes.

    Grouped by radius, then split once at the 90th percentile of candidate
    count when the class maximum dwarfs it (the dense-cluster case); smallest
    classes merge (taking the larger radius) until the class budget holds.

    ``pts_cum`` is the full (num_sc, rmax+1) ring occupancy: every class's
    ccap is sized from the counts *at that class's final radius* -- sizing
    from a pre-merge radius would make pack_cells silently truncate
    candidates, returning wrong neighbors that still certify.

    Route policy: kernel platforms (TPU / interpret) run each class through
    the fused Pallas kernel when its tile fits VMEM and stream it otherwise;
    host platforms run a chunked dense masked-top-k (measured ~3.5x the
    streamed path's throughput on CPU -- XLA CPU's TopK is fast, the
    streaming merge's extra tile copies are not), streaming only tiles past
    the dense byte ceiling.  Under ``cfg.resolved_scorer() == 'mxu'``
    (DESIGN.md section 16) every class whose (qcap, ccap) score tile fits
    the MXU chunk budget routes through the blocked-matmul scorer instead
    (mxu.scorer.grid_class_topk -- pure XLA, platform-agnostic); oversized
    classes keep their elementwise route, exact and never silent.
    """
    from ..config import resolve_epilogue, resolve_kernel
    from .pallas_solve import (hbm_budget_bytes, hbm_fits, launch_row_out,
                               pick_qsub)

    hbm_budget = hbm_budget_bytes(cfg)

    def cand_at(rows: np.ndarray, radius: int) -> np.ndarray:
        return pts_cum[rows, radius]

    groups: list[Tuple[np.ndarray, int]] = []  # (rows, radius)
    nonempty = np.nonzero(own_n > 0)[0]
    for r in np.unique(radii[nonempty]):
        rows = nonempty[radii[nonempty] == r]
        cn = cand_at(rows, int(r))
        p90 = np.quantile(cn, 0.9) if rows.size > 8 else cn.max(initial=0)
        if rows.size > 8 and cn.max() > 2.0 * max(p90, 1.0):
            groups.append((rows[cn <= p90], int(r)))
            groups.append((rows[cn > p90], int(r)))
        else:
            groups.append((rows, int(r)))
    groups = [(rows, r) for rows, r in groups if rows.size]

    # merge smallest classes (by supercell count) until within budget; a merge
    # takes the larger radius, which only widens candidate boxes (still exact
    # because ccap below is re-measured at the merged radius)
    while len(groups) > max(1, int(cfg.max_classes)):
        groups.sort(key=lambda g: g[0].size)
        (rows_a, r_a), (rows_b, r_b) = groups[0], groups[1]
        groups = groups[2:] + [(np.concatenate([rows_a, rows_b]),
                                max(r_a, r_b))]

    scorer = cfg.resolved_scorer()

    def mk(rows: np.ndarray, radius: int) -> ClassSpec:
        qcap = _round_up(int(own_n[rows].max()), 8)
        ccap = _round_up(max(int(cand_at(rows, radius).max()), cfg.k), 128)
        qcap_pad = -(-qcap // 128) * 128
        if scorer == "mxu":
            from ..mxu.scorer import class_eligible

            if class_eligible(qcap, ccap):
                # the MXU class scorer packs at the dense qcap (8-aligned
                # sublanes; the matmul contraction needs no 128-lane query
                # axis) -- ineligible tiles fall through to the platform's
                # elementwise route below, exact and never silent
                return ClassSpec(rows=rows, radius=radius, qcap=qcap,
                                 qcap_pad=qcap, ccap=ccap, route="mxu")
        if on_kernel_platform:
            # oversized query axes no longer demote (pick_qsub grids over
            # query sub-blocks); a candidate axis too wide for VMEM at a
            # 128-wide query block streams, and so does a class whose
            # launch-scale pack would overflow the HBM budget (the
            # preflight's demotion arm: stream the one dense-blob class,
            # keep the kernel for the rest -- DESIGN.md section 9).  The
            # HBM model uses the layout this class's launch will actually
            # allocate: row-major output blocks (k padded to 128 lanes)
            # when the scatter path is taken, gather blocks otherwise.
            row_out = launch_row_out(
                qcap_pad, ccap, cfg.k,
                resolve_kernel(cfg.effective_kernel(), cfg.k, ccap),
                resolve_epilogue(cfg.epilogue, True))
            route = ("pallas" if pick_qsub(qcap_pad, ccap, cfg.k)
                     and hbm_fits(qcap_pad, ccap, cfg.k, rows.size,
                                  row_out=row_out, budget=hbm_budget)
                     else "streamed")
        else:
            route = ("dense" if qcap * ccap * 4 <= _DENSE_TILE_BYTES
                     else "streamed")
        return ClassSpec(rows=rows, radius=radius, qcap=qcap,
                         qcap_pad=qcap_pad if route == "pallas" else qcap,
                         ccap=ccap, route=route)

    return tuple(mk(rows, r) for rows, r in groups)


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("own", "cand", "lo", "hi", "pk", "tgt"),
    meta_fields=("radius", "qcap", "qcap_pad", "ccap", "route"),
)
@dataclasses.dataclass(frozen=True)
class ClassPlan:
    """Device-side schedule for one class: cell tables + certificate boxes.

    ``pk`` holds the prepacked kernel inputs (a ClassPack) for pallas-routed
    classes.  Packing is
    static per problem, so doing it at plan time keeps the steady-state solve
    to kernel + epilogue -- the same prepare/solve split that took the legacy
    path from 1879 ms to 317 ms (DESIGN.md section 2); measured on v5e, the
    in-solve re-pack cost the adaptive path 3.3x (708 ms vs 215 ms on the
    900k north star).  None = pack in-solve (dense/streamed routes; the
    sharded engine prepacks per chip in _chip_ready_state against the
    halo-extended arrays).

    ``tgt`` is the class's FORWARD row map for the scatter epilogue
    (config.epilogue): (Sc * qcap_pad,) i32 destination row in the final
    output per slot (sentinel = one-past-the-end, dropped by the scatter) --
    the inverse of this class's stretch of AdaptivePlan.inv_row, built by
    the same _class_inverse_update pass at prepare time.  None only on
    plans that predate the scatter epilogue (gather mode needs no forward
    map)."""

    own: jax.Array    # (Sc, s^3) i32, -1 pad
    cand: jax.Array   # (Sc, (s+2*radius)^3) i32, -1 pad
    lo: jax.Array     # (Sc, 3) f32 dilated-box corners (unclamped)
    hi: jax.Array
    radius: int
    qcap: int
    qcap_pad: int
    ccap: int
    route: str        # 'pallas' | 'dense' | 'streamed' | 'mxu'
    pk: "ClassPack | None" = None
    tgt: "jax.Array | None" = None

    @property
    def use_pallas(self) -> bool:
        return self.route == "pallas"

    @property
    def n_sc(self) -> int:
        return self.own.shape[0]


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("classes", "inv_row", "inv_box",
                 "class_of_sc", "row_of_sc"),
    meta_fields=("n_points",),
)
@dataclasses.dataclass(frozen=True)
class AdaptivePlan:
    """Class schedules + the global slot-partition inverse for the epilogue.

    inv_row:  (n,) i32 -- stored point r's k neighbors live in row
              inv_row[r] of the ROW-MAJOR (N_slots, k) concatenation of
              every class's solver output.  The row index is uniform across
              routes (row_off + sc * qcap + lane); the pallas route's raw
              (Sc, k, qcap) output is transposed to row-major in the
              epilogue first.  The earlier element-level inv_base/istride
              maps avoided that transpose, but the resulting strided
              ELEMENT gather of n*k indices dominated the solve (51.5% of
              the on-chip kpass north star, bench_runs/r5_tpu_phases.json)
              -- gather cost scales with index count, so transposing and
              gathering k-fold fewer CONTIGUOUS rows wins despite the
              extra data movement (A/B: scripts/epilogue_ab.py).
    inv_box:  (n,) i32 into the concatenation of per-class supercell axes
              (for the per-row lo/hi certificate gather).
    class_of_sc / row_of_sc: (n_sc_global,) i32 HOST numpy arrays -- which
              class each global supercell landed in (-1 = dropped/empty) and
              its row within that class's tables; external queries bucket
              through these (query_adaptive), so one planning pass serves
              both the self-solve and arbitrary-coordinate queries.  Host-
              resident on purpose: they are consumed only by host-side query
              bucketing, and the old device copies cost the query path one
              readback per call (the prepare-time hoist of DESIGN.md
              section 12).  The solve program takes (classes, inv_row,
              inv_box) explicitly, so these leaves never cross a jit
              boundary.
    """

    classes: Tuple[ClassPlan, ...]
    inv_row: jax.Array
    inv_box: jax.Array
    # HOST numpy on purpose (see docstring): still registered as pytree
    # data_fields (numpy is a legal leaf; meta fields must be hashable), so
    # never pass a whole AdaptivePlan across a jit boundary -- that would
    # silently re-upload these per call (the solve takes classes/inv_row/
    # inv_box explicitly for exactly this reason)
    class_of_sc: np.ndarray
    row_of_sc: np.ndarray
    n_points: int


def build_adaptive_plan(grid: GridHash, cfg: KnnConfig,
                        cell_counts_host: np.ndarray | None = None,
                        on_kernel_platform: bool | None = None,
                        abstract: bool = False) -> AdaptivePlan:
    """Host planning + one device pass to invert the slot partition.

    ``abstract=True`` swaps the two jitted prepare programs (the kernel-input
    prepack and the slot-partition inversion) for ``jax.eval_shape`` of the
    same functions: the returned plan carries ShapeDtypeStruct leaves for
    ``pk``/``tgt``/``inv_row``/``inv_box`` and nothing device-side ever runs
    -- the static contract checker (analysis/contracts.py) traces the solve
    routes against exactly the plan the real prepare would build, with zero
    program execution."""
    dim, s, k = grid.dim, cfg.supercell, cfg.k
    counts = (np.asarray(cell_counts_host) if cell_counts_host is not None
              else np.asarray(jax.device_get(grid.cell_counts)))
    counts3 = counts.reshape(dim, dim, dim)
    n_sc = -(-dim // s)
    sc = _boxes_grid(n_sc)

    if cfg.ring_radius is not None:
        rmax = max(1, int(cfg.ring_radius))
        radii_all = np.full((sc.shape[0],), rmax, np.int32)
        pts_cum, _ = ring_occupancy(counts3, sc, s, rmax)
    else:
        rmax = int(min(dim, max(6, 2 * default_ring_radius(k, cfg.density))))
        pts_cum, cells_cum = ring_occupancy(counts3, sc, s, rmax)
        radii_all = select_radii(pts_cum, cells_cum, k, rmax)

    own_n = pts_cum[:, 0]
    if on_kernel_platform is None:
        on_kernel_platform = (jax.devices()[0].platform == "tpu"
                              or cfg.interpret)
    specs = build_class_specs(own_n, pts_cum, radii_all, cfg,
                              on_kernel_platform)

    # one indirection swaps real prepare execution for abstract tracing --
    # the planning logic (specs, caps, routes) is shared either way.  The
    # static args ride a partial: eval_shape abstracts every direct argument
    # (an int would reach the jit as a tracer and fail the static hash)
    def run(f, *arrays, **static):
        g = functools.partial(f, **static)
        return jax.eval_shape(g, *arrays) if abstract else g(*arrays)
    w = grid.domain / dim
    classes = []
    class_of = np.full((sc.shape[0],), -1, np.int32)
    row_of = np.zeros((sc.shape[0],), np.int32)
    for ci, spec in enumerate(specs):
        class_of[spec.rows] = ci
        row_of[spec.rows] = np.arange(spec.rows.size, dtype=np.int32)
        sc_c = sc[spec.rows]
        own = _box_cell_ids(sc_c, 0, 0, s, dim)
        cand = _box_cell_ids(sc_c, -spec.radius, spec.radius, s, dim)
        lo = ((sc_c * s - spec.radius) * w).astype(np.float32)
        hi = ((sc_c * s + s + spec.radius) * w).astype(np.float32)
        # prepare-time staging, bounded by cfg.max_classes (<= 4) iterations
        cp = ClassPlan(
            own=jnp.asarray(own), cand=jnp.asarray(cand),    # kntpu-ok: jnp-in-loop -- prepare-time, <= max_classes tables
            lo=jnp.asarray(lo), hi=jnp.asarray(hi),          # kntpu-ok: jnp-in-loop -- prepare-time, <= max_classes tables
            radius=spec.radius, qcap=spec.qcap, qcap_pad=spec.qcap_pad,
            ccap=spec.ccap, route=spec.route)
        if spec.route == "pallas":
            cp = dataclasses.replace(cp, pk=run(
                _prepack_kernel_inputs, grid.points, grid.cell_starts,
                grid.cell_counts, cp.own, cp.cand,
                qcap=cp.qcap_pad, ccap=cp.ccap))
        classes.append(cp)

    inv_row, inv_box, tgts = run(
        _invert_partition, tuple(classes), grid.cell_starts,
        grid.cell_counts, n=grid.n_points)
    classes = [dataclasses.replace(cp, tgt=t)
               for cp, t in zip(classes, tgts)]
    return AdaptivePlan(classes=tuple(classes), inv_row=inv_row,
                        inv_box=inv_box,
                        class_of_sc=class_of,
                        row_of_sc=row_of, n_points=grid.n_points)


@functools.partial(jax.jit, static_argnames=("qcap", "ccap"))
def _prepack_kernel_inputs(points, starts, counts, own, cand,
                           qcap: int, ccap: int):
    """Once-per-problem slot packing + coordinate gathers for one class."""
    from .pallas_solve import _pack_inputs

    _, _, qx, qy, qz, cx, cy, cz, qid3, cid3 = _pack_inputs(
        points, starts, counts, own, cand, qcap, ccap)
    return ClassPack(qx=qx, qy=qy, qz=qz, cx=cx, cy=cy, cz=cz,
                     qid3=qid3, cid3=cid3)


def _class_inverse_update(inv_row, inv_box, cp: ClassPlan,
                          starts, counts, sentinel: int,
                          row_off: int, box_off: int):
    """Scatter one class's output-row map into the inversion arrays
    (shared by the single-chip and per-chip-sharded prepare paths).

    Row indices are uniform across routes -- row = row_off + sc*qcap + lane
    into the row-major (N_slots, k) concat of class outputs; the per-route
    layout difference (pallas emits (Sc, k, qcap), dense/streamed emit
    (Sc*qcap, k)) is handled by `_rows2d`'s per-class transpose in the
    epilogue instead of being encoded into element strides here (see
    AdaptivePlan.inv_row for the measured reason).  Returns the updated
    arrays, the advanced (row_off, box_off), and the class's FORWARD map
    ``tgt`` (slot -> destination row, ``sentinel`` where the slot is pad)
    for the scatter epilogue -- the same pack_cells pass feeds both
    directions, so the two maps cannot drift apart.
    """
    q_idx, q_ok = pack_cells(cp.own, starts, counts, cp.qcap_pad)
    qcap = cp.qcap_pad
    lane = jnp.broadcast_to(jnp.arange(qcap, dtype=jnp.int32)[None, :],
                            q_idx.shape)
    rows = jnp.broadcast_to(
        jnp.arange(cp.n_sc, dtype=jnp.int32)[:, None], q_idx.shape)
    safe = jnp.where(q_ok, q_idx, sentinel)
    inv_row = inv_row.at[safe].set(row_off + rows * qcap + lane, mode="drop")
    inv_box = inv_box.at[safe].set(box_off + rows, mode="drop")
    tgt = safe.reshape(-1).astype(jnp.int32)
    row_off += cp.n_sc * qcap
    box_off += cp.n_sc
    # past the int32 ceiling jnp.take's clip mode would return silently
    # wrong (yet certifiable) neighbors, so refuse loudly; row-unit
    # indices put that ceiling k-fold beyond the old element-unit maps
    if row_off > 2**31 - 1:
        raise ValueError(
            f"solver output exceeds int32 row indexing "
            f"({row_off} rows): shard the problem")
    return inv_row, inv_box, row_off, box_off, tgt


def _rows2d(flats_d, flats_i, classes, k: int):
    """Concat per-class raw solver outputs as row-major (N_slots, k) arrays
    (the epilogue's gather operand; see AdaptivePlan.inv_row).  pallas
    classes transpose their (Sc, k, qcap) kernel layout here -- one
    vectorized data movement instead of a per-element strided gather."""
    ds, is_ = [], []
    for cp, fd, fi in zip(classes, flats_d, flats_i):
        if cp.route == "pallas":
            d3 = fd.reshape(cp.n_sc, k, cp.qcap_pad)
            i3 = fi.reshape(cp.n_sc, k, cp.qcap_pad)
            ds.append(jnp.swapaxes(d3, 1, 2).reshape(-1, k))
            is_.append(jnp.swapaxes(i3, 1, 2).reshape(-1, k))
        else:
            ds.append(fd.reshape(-1, k))
            is_.append(fi.reshape(-1, k))
    return jnp.concatenate(ds, axis=0), jnp.concatenate(is_, axis=0)


@functools.partial(jax.jit, static_argnames=("n",))
def _invert_partition(classes: Tuple[ClassPlan, ...], starts: jax.Array,
                      counts: jax.Array, n: int):
    """One prepare-time scatter: stored point -> (output row, supercell
    row), plus the per-class forward maps for the scatter epilogue.  See
    AdaptivePlan.inv_row and ClassPlan.tgt."""
    inv_row = jnp.zeros((n,), jnp.int32)
    inv_box = jnp.zeros((n,), jnp.int32)
    row_off = 0
    box_off = 0
    tgts = []
    for cp in classes:
        inv_row, inv_box, row_off, box_off, tgt = (
            _class_inverse_update(inv_row, inv_box, cp,
                                  starts, counts, n, row_off, box_off))
        tgts.append(tgt)
    return inv_row, inv_box, tuple(tgts)


def _streamed_topk(points: jax.Array, starts: jax.Array, counts: jax.Array,
                   cand_cells: jax.Array, q: jax.Array, q_ok: jax.Array,
                   q_excl: jax.Array, k: int, ccap: int, tile: int):
    """Memory-bounded candidate streaming through merge_topk (the core shared
    by the self-solve streamed route and external queries).

    q: (Sc, qcap, 3) query blocks; q_ok validity; q_excl (Sc, qcap) stored
    index to exclude per slot (-2 = exclude nothing -- external queries).
    Peak temp is (rows_chunk, qcap, tile), independent of ccap, so no class
    can demote or OOM the solve.  Returns (Sc * qcap, k) flat dists/ids,
    ascending.
    """
    n_sc, qcap = q.shape[0], q.shape[1]
    c_pad = -(-ccap // tile) * tile
    c_idx, c_ok = pack_cells(cand_cells, starts, counts, c_pad)
    n_tiles = c_pad // tile
    # rows per scan step: bound the (rows, qcap, tile) temp to ~64 MB
    rows_chunk = max(1, min(n_sc, (64 << 20) // (qcap * tile * 4)))
    n_row_chunks = -(-n_sc // rows_chunk)
    rows_pad = n_row_chunks * rows_chunk

    def pad_rows(a):
        pad = rows_pad - a.shape[0]
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])
        return a.reshape((n_row_chunks, rows_chunk) + a.shape[1:])

    qs, qi, qo = pad_rows(q), pad_rows(q_excl), pad_rows(q_ok)
    ci = pad_rows(c_idx).reshape(n_row_chunks, rows_chunk, n_tiles, tile)
    co = pad_rows(c_ok).reshape(n_row_chunks, rows_chunk, n_tiles, tile)

    def row_step(_, inp):
        q_c, qi_c, qo_c, ci_c, co_c = inp

        def cand_step(carry, t_inp):
            best_d, best_i = carry
            ci_t, co_t = t_inp                               # (rows, tile)
            c = jnp.take(points, ci_t, axis=0)               # (rows, tile, 3)
            d2 = jnp.zeros((rows_chunk, qcap, tile), jnp.float32)
            for ax in range(3):
                diff = q_c[:, :, None, ax] - c[:, None, :, ax]
                d2 = d2 + diff * diff
            # exclusion by stored index; -2 sentinel never matches, so the
            # same arithmetic serves self-queries and external queries
            mask = (qo_c[:, :, None] & co_t[:, None, :]
                    & (ci_t[:, None, :] != qi_c[:, :, None]))
            ids = jnp.broadcast_to(ci_t[:, None, :], d2.shape)
            return merge_topk(best_d, best_i, d2, ids, mask), None

        init = init_topk((rows_chunk, qcap), k)
        (best_d, best_i), _ = jax.lax.scan(
            cand_step, init,
            (jnp.moveaxis(ci_c, 1, 0), jnp.moveaxis(co_c, 1, 0)))
        return None, (best_d, best_i)

    _, (out_d, out_i) = jax.lax.scan(row_step, None, (qs, qi, qo, ci, co))
    out_d = out_d.reshape(rows_pad * qcap, k)[: n_sc * qcap]
    out_i = out_i.reshape(rows_pad * qcap, k)[: n_sc * qcap]
    return out_d, out_i


def _dense_rows_chunk(n_sc: int, qcap: int, ccap: int) -> int:
    """Rows per dense scan step: bound the (rows, qcap, ccap) f32 tile."""
    return max(1, min(n_sc, (32 << 20) // (qcap * ccap * 4)))


def _pad_chunk(a, n_chunks: int, rows_chunk: int, fill=0):
    pad = n_chunks * rows_chunk - a.shape[0]
    if pad:
        a = jnp.concatenate([a, jnp.full((pad,) + a.shape[1:], fill, a.dtype)])
    return a.reshape((n_chunks, rows_chunk) + a.shape[1:])


def _dense_step(points, starts, counts, cand_c, q_c, qe_c, qo_c, k, ccap):
    """One dense chunk: in-step candidate pack + gather + tile + masked_topk.

    Candidate indices are packed INSIDE the scan step from the (small) cell
    tables -- prepacking the whole class's (Sc, ccap) index array and
    threading it through scan xs measured ~1.6x slower on CPU (the stacked
    arrays stream through the loop; the in-step pack recomputes them from
    kilobytes of cell ids).  ``qe_c=None`` = exclude nothing (external
    queries), compiled out of the mask."""
    ci_c, co_c = pack_cells(cand_c, starts, counts, ccap)
    c = jnp.take(points, ci_c, axis=0)                       # (rows, ccap, 3)
    d2 = jnp.zeros(q_c.shape[:2] + (ccap,), jnp.float32)
    for ax in range(3):
        diff = q_c[:, :, None, ax] - c[:, None, :, ax]
        d2 = d2 + diff * diff
    mask = qo_c[:, :, None] & co_c[:, None, :]
    if qe_c is not None:
        mask = mask & (ci_c[:, None, :] != qe_c[:, :, None])
    ids = jnp.broadcast_to(ci_c[:, None, :], d2.shape)
    return masked_topk(d2, ids, mask, k)


def _dense_self(points: jax.Array, starts: jax.Array, counts: jax.Array,
                own_cells: jax.Array, cand_cells: jax.Array, qcap: int,
                k: int, ccap: int, exclude_self: bool):
    """Dense self-solve: queries are the class's own stored points, packed
    in-step together with the candidates -- the host-platform route (XLA
    CPU's TopK is fast; the streaming merge's tile-multiple padding and extra
    copies are not).  Returns (Sc * qcap, k) flat dists/ids, ascending."""
    n_sc = own_cells.shape[0]
    rows_chunk = _dense_rows_chunk(n_sc, qcap, ccap)
    n_chunks = -(-n_sc // rows_chunk)

    def step(_, inp):
        own_c, cand_c = inp
        qi_c, qo_c = pack_cells(own_c, starts, counts, qcap)
        q_c = jnp.take(points, qi_c, axis=0)
        qe_c = qi_c if exclude_self else None
        return None, _dense_step(points, starts, counts, cand_c, q_c, qe_c,
                                 qo_c, k, ccap)

    _, (out_d, out_i) = jax.lax.scan(
        step, None, (_pad_chunk(own_cells, n_chunks, rows_chunk, -1),
                     _pad_chunk(cand_cells, n_chunks, rows_chunk, -1)))
    out_d = out_d.reshape(n_chunks * rows_chunk * qcap, k)[: n_sc * qcap]
    out_i = out_i.reshape(n_chunks * rows_chunk * qcap, k)[: n_sc * qcap]
    return out_d, out_i


def _dense_query_topk(points: jax.Array, starts: jax.Array, counts: jax.Array,
                      cand_cells: jax.Array, q: jax.Array, q_ok: jax.Array,
                      k: int, ccap: int):
    """Dense external-query solve: prebuilt query blocks, in-step candidate
    packing.  Same flat output contract as _dense_self."""
    n_sc, qcap = q.shape[0], q.shape[1]
    rows_chunk = _dense_rows_chunk(n_sc, qcap, ccap)
    n_chunks = -(-n_sc // rows_chunk)

    def step(_, inp):
        cand_c, q_c, qo_c = inp
        return None, _dense_step(points, starts, counts, cand_c, q_c, None,
                                 qo_c, k, ccap)

    _, (out_d, out_i) = jax.lax.scan(
        step, None, (_pad_chunk(cand_cells, n_chunks, rows_chunk, -1),
                     _pad_chunk(q, n_chunks, rows_chunk),
                     _pad_chunk(q_ok, n_chunks, rows_chunk)))
    out_d = out_d.reshape(n_chunks * rows_chunk * qcap, k)[: n_sc * qcap]
    out_i = out_i.reshape(n_chunks * rows_chunk * qcap, k)[: n_sc * qcap]
    return out_d, out_i


def _class_flat(points: jax.Array, starts: jax.Array, counts: jax.Array,
                cp: ClassPlan, k: int, exclude_self: bool, tile: int,
                interpret: bool, kernel: str = "kpass",
                recall_target: float = 1.0, precision: str = "f32"):
    """Route one class's self-solve to its solver.  Returns the solver's
    RAW output flattened 1-D (Sc * qcap_pad * k elements): pallas emits
    (Sc, k, qcap) order, dense/streamed/mxu emit (Sc*qcap, k) order -- the
    epilogue's `_rows2d` normalizes both to row-major before the one
    per-point row gather (AdaptivePlan.inv_row)."""
    if cp.route == "pallas":
        return _pallas_class(points, starts, counts, cp, k, exclude_self,
                             interpret, kernel)
    if cp.route == "mxu":
        from ..mxu.scorer import grid_class_topk

        fd, fi = grid_class_topk(points, starts, counts, cp.own, cp.cand,
                                 cp.qcap_pad, k, cp.ccap, exclude_self,
                                 recall_target, precision)
        return fd.reshape(-1), fi.reshape(-1)
    if cp.route == "dense":
        fd, fi = _dense_self(points, starts, counts, cp.own, cp.cand,
                             cp.qcap_pad, k, cp.ccap, exclude_self)
        return fd.reshape(-1), fi.reshape(-1)
    q_idx, q_ok = pack_cells(cp.own, starts, counts, cp.qcap_pad)
    q = jnp.take(points, q_idx, axis=0)                      # (Sc, qcap, 3)
    q_excl = q_idx if exclude_self else jnp.full_like(q_idx, -2)
    fd, fi = _streamed_topk(points, starts, counts, cp.cand, q, q_ok, q_excl,
                            k, cp.ccap, tile)
    return fd.reshape(-1), fi.reshape(-1)


def _class_kernel_inputs(points: jax.Array, starts: jax.Array,
                         counts: jax.Array, cp: ClassPlan):
    """One class's kernel input blocks: the prepacked ClassPack when the
    plan carries one, else an in-solve _pack_inputs pass.  Shared by the
    gather- and row-major (scatter-epilogue) launches."""
    from .pallas_solve import _pack_inputs

    if cp.pk is not None:
        pk = cp.pk
        # ClassPack reuse contract: blocks must match this plan's caps.
        # ValueError, not assert: this guard must survive `python -O` (a
        # mismatched pack would gather wrong-yet-certified neighbors)
        if pk.cx.shape != (cp.n_sc, 1, cp.ccap):
            raise ValueError(
                f"ClassPack/plan mismatch: pk blocks {pk.cx.shape} vs plan "
                f"(n_sc={cp.n_sc}, ccap={cp.ccap}); was this plan built "
                f"against a different grid?")
        return (pk.qx, pk.qy, pk.qz, pk.cx, pk.cy, pk.cz, pk.qid3, pk.cid3)
    _, _, qx, qy, qz, cx, cy, cz, qid3, cid3 = _pack_inputs(
        points, starts, counts, cp.own, cp.cand, cp.qcap_pad, cp.ccap)
    return (qx, qy, qz, cx, cy, cz, qid3, cid3)


def _pallas_class(points: jax.Array, starts: jax.Array, counts: jax.Array,
                  cp: ClassPlan, k: int, exclude_self: bool, interpret: bool,
                  kernel: str = "kpass"):
    """Fused-kernel class solver (the hot route).  Returns (Sc * qcap_pad, k)
    flat dists/ids, ascending -- same layout contract as _streamed_class."""
    from .pallas_solve import _pallas_topk

    qx, qy, qz, cx, cy, cz, qid3, cid3 = _class_kernel_inputs(
        points, starts, counts, cp)
    from ..config import resolve_kernel

    out_d, out_i = _pallas_topk(qx, qy, qz, cx, cy, cz, qid3, cid3,
                                cp.qcap_pad, cp.ccap, k, exclude_self,
                                interpret,
                                resolve_kernel(kernel, k, cp.ccap))
    # raw (Sc, k, qcap) layout, flattened -- the epilogue's _rows2d
    # transposes it to row-major before the per-point row gather
    return out_d.reshape(-1), out_i.reshape(-1)


def _class_rows(points: jax.Array, starts: jax.Array, counts: jax.Array,
                cp: ClassPlan, k: int, exclude_self: bool, tile: int,
                interpret: bool, kernel: str = "kpass",
                recall_target: float = 1.0, precision: str = "f32"):
    """One class's self-solve as ROW-MAJOR (Sc * qcap_pad, k) dists/ids --
    the scatter-epilogue twin of _class_flat.  pallas classes go through
    pallas_solve._topk_rows_or_transpose (the shared eligibility gate:
    scalar-prefetch row-major kernel when the resolved body is kpass and
    the row-out tile fits VMEM, gather launch + XLA transpose otherwise --
    byte-identical either way).  dense/streamed routes already emit
    row-major rows."""
    from ..config import resolve_kernel
    from .pallas_solve import _PAD_Q, _topk_rows_or_transpose

    if cp.route == "pallas":
        qx, qy, qz, cx, cy, cz, qid3, cid3 = _class_kernel_inputs(
            points, starts, counts, cp)
        q_ok = (qid3 != _PAD_Q).reshape(cp.n_sc, cp.qcap_pad)
        return _topk_rows_or_transpose(
            qx, qy, qz, cx, cy, cz, qid3, cid3, cp.qcap_pad, cp.ccap, k,
            exclude_self, interpret, q_ok, resolve_kernel(kernel, k, cp.ccap))
    fd, fi = _class_flat(points, starts, counts, cp, k, exclude_self, tile,
                         interpret, kernel, recall_target, precision)
    return fd.reshape(-1, k), fi.reshape(-1, k)


def _scatter_classes(points: jax.Array, starts: jax.Array, counts: jax.Array,
                     classes: Tuple[ClassPlan, ...], n_rows: int, k: int,
                     exclude_self: bool, tile: int, interpret: bool,
                     kernel: str = "kpass", recall_target: float = 1.0,
                     precision: str = "f32"):
    """Scatter epilogue: every class's row-major rows land in the final
    (n_rows, k) buffers through its prepare-time forward map (ClassPlan.tgt,
    pad slots -> dropped sentinel).  Replaces the gather epilogue's
    transpose + row-major concatenation + per-point row gather with direct
    placement -- there is no standalone epilogue program left to time
    (DESIGN.md section 2c).  Every stored point owns exactly one valid slot,
    so all n_rows rows are written and the init values never survive;
    byte-identity with the gather path is pinned by tests/test_epilogue.py.
    """
    out_d = jnp.full((n_rows, k), jnp.inf, jnp.float32)
    out_i = jnp.full((n_rows, k), INVALID_ID, jnp.int32)
    for cp in classes:
        if cp.tgt is None:  # pre-scatter plan (no forward map persisted)
            raise ValueError(
                "this plan predates the scatter epilogue (ClassPlan.tgt is "
                "None); rebuild it or use epilogue='gather'")
        rows_d, rows_i = _class_rows(points, starts, counts, cp, k,
                                     exclude_self, tile, interpret, kernel,
                                     recall_target, precision)
        out_d = out_d.at[cp.tgt].set(rows_d, mode="drop")
        out_i = out_i.at[cp.tgt].set(rows_i, mode="drop")
    return out_d, out_i


@functools.partial(jax.jit, static_argnames=("n", "k", "exclude_self",
                                             "domain", "interpret", "tile",
                                             "kernel", "epilogue",
                                             "recall_target", "precision"))
def _solve_adaptive(points: jax.Array, starts: jax.Array, counts: jax.Array,
                    classes: Tuple[ClassPlan, ...], inv_row: jax.Array,
                    inv_box: jax.Array, n: int, k: int, exclude_self: bool,
                    domain: float, interpret: bool, tile: int,
                    kernel: str = "kpass", epilogue: str = "gather",
                    recall_target: float = 1.0, precision: str = "f32"):
    """One program = the whole class-partitioned solve: every class launch,
    the device-resident (n, k) assembly, and the certificate -- the solve
    dispatches as ONE async call and syncs nowhere (api._finalize does the
    single batched readback).  Takes the plan's device pieces explicitly
    (classes / inv_row / inv_box) rather than the whole AdaptivePlan so the
    plan's host-resident query maps (class_of_sc / row_of_sc) never ride a
    jit boundary."""
    los = [cp.lo for cp in classes]
    his = [cp.hi for cp in classes]
    if epilogue == "scatter":
        row_d, row_i = _scatter_classes(
            points, starts, counts, classes, n, k,
            exclude_self, tile, interpret, kernel, recall_target, precision)
    else:
        flats_d, flats_i = [], []
        for cp in classes:
            fd, fi = _class_flat(points, starts, counts, cp, k, exclude_self,
                                 tile, interpret, kernel, recall_target,
                                 precision)
            flats_d.append(fd)
            flats_i.append(fi)
        all_d, all_i = _rows2d(flats_d, flats_i, classes, k)
        row_d = jnp.take(all_d, inv_row, axis=0)             # (n, k)
        row_i = jnp.take(all_i, inv_row, axis=0)
    # raw k-th BEFORE sanitization: blocked-kernel deficit rows carry NaN
    # there, and NaN <= margin is false even for an infinite margin
    raw_kth = row_d[:, k - 1]
    ok = jnp.isfinite(row_d)
    row_i = jnp.where(ok, row_i, INVALID_ID)
    row_d = jnp.where(ok, row_d, jnp.inf)
    lo = jnp.take(jnp.concatenate(los, axis=0), inv_box, axis=0)
    hi = jnp.take(jnp.concatenate(his, axis=0), inv_box, axis=0)
    cert = raw_kth <= _margin_sq(points[:, None, :], lo, hi,
                                 domain)[:, 0]
    return row_i, row_d, cert, jnp.sum(~cert, dtype=jnp.int32)


def solve_adaptive(grid: GridHash, cfg: KnnConfig,
                   plan: AdaptivePlan | None = None) -> KnnResult:
    """All-points kNN over the class-partitioned schedule.  Same contract as
    solve.solve (sorted indexing; uncertified rows resolved by the api-level
    exact fallback)."""
    if plan is None:
        plan = build_adaptive_plan(grid, cfg)
    # named profiler scope (utils/profiling.annotate): the whole class-
    # partitioned dispatch shows up as one labeled region in jax.profiler
    # traces instead of anonymous jit frames; the obs span carries the same
    # phase into the kntpu-trace timeline
    with _spans.span("solve.adaptive.launch", n=plan.n_points,
                     classes=len(plan.classes)), \
            annotate("kntpu:adaptive-solve"):
        nbr, d2, cert, n_unc = _solve_adaptive(
            grid.points, grid.cell_starts, grid.cell_counts, plan.classes,
            plan.inv_row, plan.inv_box, plan.n_points, cfg.k,
            cfg.exclude_self, grid.domain, cfg.interpret, cfg.stream_tile,
            cfg.effective_kernel(), cfg.resolved_epilogue(),
            float(cfg.recall_target), cfg.resolved_precision())
    return KnnResult(neighbors=nbr, dists_sq=d2, certified=cert,
                     uncert_count=n_unc)


# -- external queries through the class schedule ------------------------------

@functools.partial(jax.jit, static_argnames=("q2cap", "k", "route",
                                             "domain", "interpret", "tile",
                                             "kernel", "epilogue"))
def _query_class(points: jax.Array, starts: jax.Array, counts: jax.Array,
                 cp: ClassPlan, qsorted: jax.Array, rstarts: jax.Array,
                 rcounts: jax.Array, inv: jax.Array, rows_sel: jax.Array,
                 q2cap: int, k: int, route: str, domain: float,
                 interpret: bool, tile: int, ids_map: jax.Array | None = None,
                 kernel: str = "kpass", epilogue: str = "gather"):
    """One class's external-query launch: build the per-supercell query block
    from the row-bucketed queries, run the class solver (kernel or streamed),
    gather each query's row back, and certify against the class's dilated
    boxes.  Returns ((m_c, k) ids into sorted storage, (m_c, k) d2 ascending,
    (m_c,) certified)."""
    slots = jnp.arange(q2cap, dtype=jnp.int32)
    qs_idx = rstarts[:, None] + slots[None, :]               # (Sc, q2cap)
    qs_ok = slots[None, :] < rcounts[:, None]
    safe_qs = jnp.where(qs_ok, qs_idx, 0)
    if route == "pallas":
        from .pallas_solve import _PAD_C, _PAD_Q, _pallas_topk

        if cp.pk is not None:
            # candidate half of the class's prepacked self-solve inputs --
            # identical by construction (same cand table, same ccap); see
            # the ClassPack reuse contract (ValueError: survives `python -O`)
            if cp.pk.cx.shape != (cp.n_sc, 1, cp.ccap):
                raise ValueError(
                    f"ClassPack/plan mismatch: pk blocks {cp.pk.cx.shape} vs "
                    f"plan (n_sc={cp.n_sc}, ccap={cp.ccap}); was this plan "
                    f"built against a different grid?")
            cx, cy, cz, cid3 = cp.pk.cx, cp.pk.cy, cp.pk.cz, cp.pk.cid3
        else:
            # this pack skips _pack_inputs' slot interleave, which the
            # blocked kernel's per-block top-m depends on (without it, near
            # candidates concentrate in one block and deficits become
            # routine) -- force the order-insensitive kpass body here
            kernel = "kpass"
            c_idx, c_ok = pack_cells(cp.cand, starts, counts, cp.ccap)
            axes = points.T
            cx, cy, cz = (jnp.take(axes[ax], c_idx, axis=0)
                          .reshape(cp.n_sc, 1, cp.ccap) for ax in range(3))
            cid3 = jnp.where(c_ok, c_idx, _PAD_C).astype(jnp.int32).reshape(
                cp.n_sc, 1, cp.ccap)
        # per-axis query lane blocks, same layout rationale as _pack_inputs
        qaxes = qsorted.T
        qxq, qyq, qzq = (jnp.take(qaxes[ax], safe_qs, axis=0)
                         .reshape(cp.n_sc, 1, q2cap) for ax in range(3))
        from ..config import resolve_kernel
        from .pallas_solve import _topk_rows_or_transpose

        qid3 = jnp.full((cp.n_sc, 1, q2cap), _PAD_Q, jnp.int32)
        resolved = resolve_kernel(kernel, k, cp.ccap)
        if cp.n_sc * q2cap > 2**31 - 1:
            # ValueError, not assert: under `python -O` a wrapped int32
            # index would gather wrong-yet-certified neighbors
            raise ValueError(
                "query output exceeds int32 row indexing; reduce the query "
                "batch")
        if epilogue == "scatter":
            # shared eligibility gate: row-major kernel when possible,
            # gather launch + XLA transpose otherwise
            rows_d, rows_i = _topk_rows_or_transpose(
                qxq, qyq, qzq, cx, cy, cz, qid3, cid3, q2cap, cp.ccap, k,
                False, interpret, qs_ok, resolved)
        else:
            out_d, out_i = _pallas_topk(qxq, qyq, qzq, cx, cy, cz, qid3,
                                        cid3, q2cap, cp.ccap, k, False,
                                        interpret, resolved)
            # transpose the raw (Sc, k, q2cap) kernel layout to row-major and
            # gather whole rows -- same pattern as the self-solve epilogue
            # (_rows2d): element gathers of m*k strided indices lose to one
            # vectorized transpose + a contiguous row gather
            rows_d = jnp.swapaxes(out_d, 1, 2).reshape(-1, k)  # (Sc*q2cap, k)
            rows_i = jnp.swapaxes(out_i, 1, 2).reshape(-1, k)
        row_d = jnp.take(rows_d, inv, axis=0)                # (m_c, k)
        row_i = jnp.take(rows_i, inv, axis=0)
    elif route == "dense":
        q = jnp.take(qsorted, safe_qs, axis=0)
        flat_d, flat_i = _dense_query_topk(points, starts, counts, cp.cand,
                                           q, qs_ok, k, cp.ccap)
        row_d = jnp.take(flat_d, inv, axis=0)                # (m_c, k)
        row_i = jnp.take(flat_i, inv, axis=0)
    else:
        q = jnp.take(qsorted, safe_qs, axis=0)
        q_excl = jnp.full((cp.n_sc, q2cap), -2, jnp.int32)   # exclude nothing
        flat_d, flat_i = _streamed_topk(points, starts, counts, cp.cand,
                                        q, qs_ok, q_excl, k, cp.ccap, tile)
        row_d = jnp.take(flat_d, inv, axis=0)                # (m_c, k)
        row_i = jnp.take(flat_i, inv, axis=0)
    # raw k-th BEFORE sanitization (blocked-kernel deficit rows carry NaN)
    raw_kth = row_d[:, k - 1]
    ok = jnp.isfinite(row_d)
    row_i = jnp.where(ok, row_i, INVALID_ID)
    row_d = jnp.where(ok, row_d, jnp.inf)
    if ids_map is not None:
        # translate to final ids on device (the grid permutation, or the
        # sharded path's ext-index -> original-id block); readback O(m*k)
        row_i = translate_ids(row_i, ids_map)
    lo = jnp.take(cp.lo, rows_sel, axis=0)                   # (m_c, 3)
    hi = jnp.take(cp.hi, rows_sel, axis=0)
    cert = raw_kth <= _margin_sq(qsorted[:, None, :], lo, hi,
                                 domain)[:, 0]
    return row_i, row_d, cert


def launch_class_query(points, starts, counts, cp: ClassPlan,
                       queries_sel: np.ndarray, rows_sel: np.ndarray, k: int,
                       cfg: KnnConfig, domain: float, ids_map=None):
    """Bucket one class's queries by supercell row and launch _query_class.

    The shared front half of every external-query path (single-chip
    query_adaptive and the sharded per-chip query): sorts queries row-major,
    sizes the padded per-row capacity, re-gates the route against THIS query
    set (a kernel class whose inflated q2cap no longer fits VMEM drops to
    streamed; likewise a dense class past the dense byte ceiling), and builds
    the flat-slot inverse.  Returns (order, r_i, r_d, r_c): ``order`` sorts
    ``queries_sel`` row-major; the device results are in that order.
    """
    from .pallas_solve import pick_qsub

    order = np.argsort(rows_sel, kind="stable")
    rows_sorted = rows_sel[order]
    rcounts = np.bincount(rows_sorted, minlength=cp.n_sc).astype(np.int32)
    rstarts = np.concatenate([[0], np.cumsum(rcounts)[:-1]]).astype(np.int32)
    # i64 so the rows*q2cap+rank flat index is computed at full width and
    # range-checked (_query_class refuses > i32) BEFORE the i32 cast -- a
    # narrow intermediate would wrap first and skip the guard
    rank = np.arange(order.size, dtype=np.int64) - rstarts[rows_sorted]  # kntpu-ok: wide-dtype -- pre-guard index headroom (see above)
    max_q = int(rcounts.max())
    # kernel lanes need 128-multiples; the other routes take any pow2
    # (bounds recompiles across query sets)
    q2cap_pal = -(-max_q // 128) * 128
    route = cp.route
    if route == "mxu":
        # external queries keep the exact elementwise class solvers: the
        # grid-fed MXU scorer is a self-solve (queries ARE the class's own
        # stored points); arbitrary-coordinate MXU scoring is the brute
        # route's job (mxu.solve_general(queries=...), DESIGN.md s16)
        route = "dense"
    if route == "pallas" and not pick_qsub(q2cap_pal, cp.ccap, k):
        route = "streamed"
    q2cap = (q2cap_pal if route == "pallas"
             else 1 << max(3, (max_q - 1).bit_length()))
    if route == "dense" and q2cap * cp.ccap * 4 > _DENSE_TILE_BYTES:
        route = "streamed"  # query blob inflated the dense tile too
    inv = (rows_sorted * q2cap + rank).astype(np.int32)
    # counted async staging (runtime.dispatch): the uploads and the launch
    # dispatch back-to-back; nothing here blocks the host
    r_i, r_d, r_c = _query_class(
        points, starts, counts, cp,
        _dispatch.stage(queries_sel[order]), _dispatch.stage(rstarts),  # syncflow: query-class-stage
        _dispatch.stage(rcounts), _dispatch.stage(inv),  # syncflow: query-class-stage
        _dispatch.stage(rows_sorted.astype(np.int32)), q2cap, k,  # syncflow: query-class-stage
        route, domain, cfg.interpret, cfg.stream_tile, ids_map,
        cfg.effective_kernel(), cfg.resolved_epilogue())
    return order, r_i, r_d, r_c


@jax.jit
def _place_query_rows(out_i, out_d, cert, rows, r_i, r_d, r_c):
    """Device-resident assembly of one class's external-query rows into the
    final (m, k) buffers -- the query-side twin of _scatter_classes' forward-
    map placement.  The destination rows come from the host bucketing (the
    query set's analog of a prepare-time ClassPlan.tgt), but the contract is
    the same: per-class results never detour through host ``out[sel] = ...``
    assembly, and no class launch waits on another's readback."""
    return (out_i.at[rows].set(r_i), out_d.at[rows].set(r_d),
            cert.at[rows].set(r_c))


def query_adaptive(grid: GridHash, cfg: KnnConfig, plan: AdaptivePlan,
                   queries: np.ndarray, k: int,
                   fallback: str = "brute") -> Tuple[np.ndarray, np.ndarray]:
    """Arbitrary-coordinate kNN through the adaptive class schedule -- the
    external-query twin of solve_adaptive, reusing the one plan prepare()
    built (no legacy SolvePlan or PallasPack is ever materialized).

    Queries bucket by supercell on the HOST (numpy cell coords against the
    plan's prepare-time class maps -- no device round trip), inherit their
    supercell's class (radius, candidate box, route), and every class launch
    dispatches back-to-back with its results scattered into device-resident
    final (m, k) buffers (_place_query_rows).  The call then syncs ONCE on a
    batched readback of the assembled buffers; classless queries (empty
    supercells) and uncertified rows resolve exactly through the tiled
    brute-force pass behind at most one more batched fetch -- <= 2 host
    round trips total (DESIGN.md section 12).  Returns ((m, k) ids in
    ORIGINAL indexing, ascending; (m, k) squared distances), query order.
    """
    from .gridhash import cell_coords_host
    from .query import brute_force_by_coords

    queries = np.ascontiguousarray(queries, np.float32)
    m = queries.shape[0]
    if m == 0:
        return (np.empty((0, k), np.int32), np.empty((0, k), np.float32))
    coords = cell_coords_host(queries, grid.dim, grid.domain)
    s = cfg.supercell
    n_sc = -(-grid.dim // s)
    scc = coords // s
    # i64 linearization headroom: n_sc^3 passes i32 at dim/supercell ~1290,
    # inside the 10M+-point roadmap scale -- host-only, indexes host arrays
    sid = (scc[:, 0].astype(np.int64) + n_sc * (scc[:, 1].astype(np.int64)   # kntpu-ok: wide-dtype -- supercell-id headroom (see above)
           + n_sc * scc[:, 2].astype(np.int64)))                             # kntpu-ok: wide-dtype -- supercell-id headroom (see above)
    qcls, qrow = plan.class_of_sc[sid], plan.row_of_sc[sid]

    # device-resident final buffers: every class scatters into these, and
    # ids translate to ORIGINAL indexing on device (ids_map) so the one
    # readback below needs no host-side permutation fetch
    out_i = jnp.full((m, k), INVALID_ID, jnp.int32)
    out_d = jnp.full((m, k), jnp.inf, jnp.float32)
    cert = jnp.zeros((m,), bool)
    for ci, cp in enumerate(plan.classes):
        sel = np.nonzero(qcls == ci)[0]
        if sel.size == 0:
            continue
        # named profiler scope per class launch: jax.profiler traces show
        # which capacity class each dispatch belongs to
        with _spans.span("query.adaptive.class", cls=ci,
                         rows=int(sel.size)), \
                annotate(f"kntpu:adaptive-query-class{ci}"):
            order, r_i, r_d, r_c = launch_class_query(
                grid.points, grid.cell_starts, grid.cell_counts, cp,
                queries[sel], qrow[sel], k, cfg, grid.domain,
                ids_map=grid.permutation)
            rows = _dispatch.stage(sel[order].astype(np.int32))  # syncflow: adaptive-query-place-stage
            out_i, out_d, cert = _place_query_rows(out_i, out_d, cert,
                                                   rows, r_i, r_d, r_c)
    # the one sync: a single batched readback of the assembled buffers
    out_i, out_d, cert = _dispatch.fetch(out_i, out_d, cert)  # syncflow: adaptive-query-final

    # Exact resolve: classless queries (empty supercells) have no grid route,
    # so they are always brute-forced (their rows stay uncertified above);
    # uncertified class rows go through the same pass when the fallback is
    # enabled.  One more batched fetch, not a per-array readback storm.
    need = (qcls < 0) if fallback != "brute" else ~np.asarray(cert)
    if need.any():
        # writable copies only on the resolution branch (device_get hands
        # back read-only zero-copy views on the CPU backend)
        out_i, out_d = np.array(out_i), np.array(out_d)
        bad = np.nonzero(need)[0].astype(np.int32)
        b_i, b_d = brute_force_by_coords(
            grid.points, _dispatch.stage(queries[bad]), k,  # syncflow: adaptive-query-fallback-stage
            ids_map=grid.permutation)
        b_i, b_d = _dispatch.fetch(b_i, b_d)  # syncflow: adaptive-query-fallback
        out_i[bad] = b_i
        out_d[bad] = b_d
    # writable results on every path, like the legacy route's fresh buffers
    # (without the resolution branch the fetch hands back read-only
    # zero-copy views on the CPU backend)
    if not out_i.flags.writeable:
        out_i, out_d = np.array(out_i), np.array(out_d)
    return out_i, out_d
