"""All-points k-nearest-neighbor solve over the uniform grid (TPU-first).

Reference parity (C4, /root/reference/knearests.cu:93-148,348-392): the reference
launches one CUDA thread per query point, each walking precomputed Chebyshev ring
offsets with a shared-memory max-heap and a divergent per-thread early exit.

The TPU design replaces per-thread divergence with *supercell tiling*:

  1. Queries are grouped by supercell (a tile of ``s^3`` grid cells).  Every query
     in a supercell shares one candidate set -- the supercell dilated by the ring
     radius R -- so the candidate gather is amortized and the distance computation
     becomes a dense, static-shape ``(Q, C)`` tile that XLA maps onto the VPU/MXU.
  2. The reference's per-thread early-exit bound (knearests.cu:116) becomes a
     per-query *completeness certificate*: a query is certified iff its k-th
     neighbor distance is within its margin to the dilated box boundary, so every
     un-gathered point is provably farther.  The reference's racy "max visited
     ring" telemetry (SURVEY.md section 2.2) thus becomes an exact guarantee.
  3. Uncertified stragglers (typically <<1%) are resolved exactly by a tiled
     brute-force pass (api.py drives this).

All shapes are static per (dataset, config): capacities are measured on the host
from the grid occupancy at plan time, the analog of kn_prepare's host-side setup
(/root/reference/knearests.cu:235-344).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import KnnConfig
from .gridhash import GridHash
from .rings import box_sums as _box_sums  # C3 production wiring
from .topk import INVALID_ID, init_topk, masked_topk, merge_topk

_FAR = 1.0e30  # padding coordinate; squared distances to it dwarf any real pair


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("own_cells", "cand_cells", "box_lo", "box_hi"),
    meta_fields=("qcap", "ccap", "n_chunks", "batch"),
)
@dataclasses.dataclass(frozen=True)
class SolvePlan:
    """Static supercell schedule, built host-side at prepare time.

    own_cells:  (n_chunks, batch, s^3) i32 linear cell ids per supercell (-1 pad).
    cand_cells: (n_chunks, batch, (s+2R)^3) i32 dilated-box cell ids (-1 pad).
    box_lo/hi:  (n_chunks, batch, 3) f32 dilated-box corners in domain coordinates.
    qcap/ccap:  static per-supercell query / candidate capacities (measured maxima).
    """

    own_cells: jax.Array
    cand_cells: jax.Array
    box_lo: jax.Array
    box_hi: jax.Array
    qcap: int
    ccap: int
    n_chunks: int
    batch: int


@dataclasses.dataclass(frozen=True)
class KnnResult:
    """neighbors/dists are in *sorted* point indexing, like the reference's
    g_knearests output (knearests.cu:141-147); translate with
    gridhash.unpermute_neighbors.  ``certified`` marks queries whose result is
    proven complete by the box-margin bound."""

    neighbors: np.ndarray | jax.Array  # (n, k) i32, ascending by distance
    dists_sq: np.ndarray | jax.Array   # (n, k) f32
    certified: np.ndarray | jax.Array  # (n,) bool
    # 0-d i32 count of uncertified rows, computed INSIDE the solve program
    # when the producing path supports it: api._finalize then reads it in
    # the SAME batched fetch as the result arrays (one round trip total --
    # each eager dispatch is a round trip on remote-tunnel backends).  On
    # api-finalized results this is always populated: the PRE-resolution
    # count (rows the exact fallback had to resolve; certified is all-True
    # afterwards).  None = a raw solver result whose caller computes it.
    uncert_count: np.ndarray | jax.Array | None = None
    # Optional Voronoi plane feed (cluster/planes.py, DESIGN.md section
    # 14): (n, k, 4) f32 bisector planes [(nx, ny, nz), d] per neighbor,
    # rows in ORIGINAL point order (matching get_knearests_original), pad
    # slots the trivially-true half-space (n=0, d=inf).  Populated by
    # api._finalize when config.plane_feed is on (or lazily by
    # KnnProblem.get_planes()); None otherwise.
    planes: np.ndarray | None = None


def _boxes_grid(n_sc: int) -> np.ndarray:
    """(n_sc^3, 3) supercell integer coordinates, x fastest (matches linearize)."""
    r = np.arange(n_sc, dtype=np.int32)
    zz, yy, xx = np.meshgrid(r, r, r, indexing="ij")
    return np.stack([xx.ravel(), yy.ravel(), zz.ravel()], axis=1)


def _box_cell_ids(sc_coords: np.ndarray, lo_off: int, hi_off: int, s: int,
                  dim: int) -> np.ndarray:
    """Linear cell ids of box [sc*s+lo_off, sc*s+s+hi_off) per supercell, -1 where
    the box exceeds the grid.  Returns (num_sc, (s+hi_off-lo_off)^3) i32."""
    side = s + hi_off - lo_off
    offs = np.arange(lo_off, s + hi_off, dtype=np.int32)
    ax = sc_coords[:, :, None] * s + offs[None, None, :]      # (num_sc, 3, side)
    ok = (ax >= 0) & (ax < dim)
    axc = np.clip(ax, 0, dim - 1)
    x, y, z = axc[:, 0], axc[:, 1], axc[:, 2]                  # (num_sc, side)
    okx, oky, okz = ok[:, 0], ok[:, 1], ok[:, 2]
    lin = (x[:, None, None, :]
           + dim * y[:, None, :, None]
           + dim * dim * z[:, :, None, None])                  # (num_sc, side, side, side)
    valid = okx[:, None, None, :] & oky[:, None, :, None] & okz[:, :, None, None]
    out = np.where(valid, lin, -1).reshape(sc_coords.shape[0], side ** 3)
    return out.astype(np.int32)




def _round_up(x: int, m: int) -> int:
    return max(m, ((int(x) + m - 1) // m) * m)


def global_schedule(grid: GridHash, cfg: KnnConfig,
                    cell_counts_host: np.ndarray | None = None):
    """Host-side supercell schedule shared by the single-chip and sharded
    planners (analog of kn_prepare's table precomputation,
    /root/reference/knearests.cu:254-300, but per-axis and clamped -- no
    boundary wraparound).

    Returns (own_cells, cand_cells, box_lo, box_hi, qcap, ccap), all over the
    z-major global supercell grid.
    """
    dim, s = grid.dim, cfg.supercell
    radius = cfg.resolved_ring_radius()
    n_sc = -(-dim // s)
    sc = _boxes_grid(n_sc)
    num_sc = sc.shape[0]

    counts = (np.asarray(cell_counts_host) if cell_counts_host is not None
              else np.asarray(jax.device_get(grid.cell_counts)))
    counts3 = counts.reshape(dim, dim, dim)  # [z, y, x]

    own = _box_cell_ids(sc, 0, 0, s, dim)
    cand = _box_cell_ids(sc, -radius, radius, s, dim)

    own_n = _box_sums(counts3, sc * s, np.minimum(sc * s + s, dim))
    cand_n = _box_sums(counts3, sc * s - radius, sc * s + s + radius)
    qcap = _round_up(own_n.max() if num_sc else 1, 8)
    # lower-bound ccap by k so lax.top_k(k) is always legal even when the
    # candidate pool is smaller than k (k > n case: surplus slots stay masked
    # and come out as -1/inf sentinels)
    ccap = _round_up(max(cand_n.max() if num_sc else 1, cfg.k), 128)

    w = grid.domain / dim
    box_lo = ((sc * s - radius) * w).astype(np.float32)
    box_hi = ((sc * s + s + radius) * w).astype(np.float32)
    return own, cand, box_lo, box_hi, int(qcap), int(ccap)


def build_plan(grid: GridHash, cfg: KnnConfig,
               cell_counts_host: np.ndarray | None = None) -> SolvePlan:
    """Single-chip supercell plan: the global schedule, chunked for lax.scan."""
    own, cand, box_lo, box_hi, qcap, ccap = global_schedule(
        grid, cfg, cell_counts_host)
    num_sc = own.shape[0]
    batch = max(1, int(cfg.sc_batch))
    n_chunks = -(-num_sc // batch)
    pad = n_chunks * batch - num_sc

    def _pad(a: np.ndarray, fill) -> np.ndarray:
        if pad:
            a = np.concatenate([a, np.full((pad,) + a.shape[1:], fill, a.dtype)])
        return a.reshape(n_chunks, batch, *a.shape[1:])

    return SolvePlan(
        own_cells=jnp.asarray(_pad(own, -1)),
        cand_cells=jnp.asarray(_pad(cand, -1)),
        box_lo=jnp.asarray(_pad(box_lo, 0.0)),
        box_hi=jnp.asarray(_pad(box_hi, 0.0)),
        qcap=int(qcap), ccap=int(ccap), n_chunks=int(n_chunks), batch=int(batch),
    )


def pack_cells(cells: jax.Array, starts: jax.Array, counts: jax.Array,
               cap: int) -> Tuple[jax.Array, jax.Array]:
    """Dense-pack the points of a ragged cell list: CSR -> (B, cap) point indices.

    For each row (a supercell), slot t holds the t-th point across the row's
    cells in order.  This is a static-shape segmented gather -- the functional
    replacement for the reference's pointer-chasing over d_ptrs/d_counters in the
    search kernel's inner loop (knearests.cu:119-134).
    Returns (indices, valid) with indices clamped to 0 where invalid.
    """
    valid_cell = cells >= 0
    safe = jnp.where(valid_cell, cells, 0)
    cnt = jnp.where(valid_cell, jnp.take(counts, safe), 0)        # (B, M)
    cum = jnp.cumsum(cnt, axis=1)
    off = cum - cnt
    total = cum[:, -1]
    slots = jnp.arange(cap, dtype=cnt.dtype)
    # Platform-split bin search: 'compare_all' vectorizes as a fused
    # compare+reduce, ~14x faster than 'scan' on TPU -- but its (B, M, cap)
    # compare matrix is ~24x SLOWER than 'scan' on CPU (measured 1085 ms vs
    # 45 ms at B=1331, M=343, cap=1152), where it dominated the fallback
    # solve.  Resolved at trace time; only the measured-slow CPU backend
    # demotes to 'scan' -- accelerators keep the vectorized form.
    method = "scan" if jax.default_backend() == "cpu" else "compare_all"
    which = jax.vmap(lambda c: jnp.searchsorted(
        c, slots, side="right", method=method))(cum)
    which = jnp.clip(which, 0, cells.shape[1] - 1)
    # One (B, cap) gather of the per-cell slot->index adjustment (start - off)
    # instead of separate base/begin gathers: idx = slot + adj[which].
    adj = jnp.take(starts, safe) - off
    idx = slots[None, :] + jnp.take_along_axis(adj, which, axis=1)
    ok = slots[None, :] < total[:, None]
    return jnp.where(ok, idx, 0).astype(jnp.int32), ok


def _pair_d2(q: jax.Array, c: jax.Array, method: str) -> jax.Array:
    """(B, Q, 3) x (B, C, 3) -> (B, Q, C) squared distances.

    'diff' uses sum_axis (q-c)^2 with x,y,z accumulation order -- identical
    arithmetic to the reference kernel (knearests.cu:125) and the C++ oracle, so
    single-chip results are bit-comparable.  'dot' is the MXU form
    |q|^2+|c|^2-2qc (fast path; may reorder exact near-ties).
    """
    if method == "dot":
        qq = jnp.sum(q * q, axis=-1)
        cc = jnp.sum(c * c, axis=-1)
        qc = jnp.einsum("bqd,bcd->bqc", q, c,
                        preferred_element_type=jnp.float32)
        return qq[:, :, None] + cc[:, None, :] - 2.0 * qc
    d2 = jnp.zeros(q.shape[:2] + (c.shape[1],), jnp.float32)
    for ax in range(3):
        diff = q[:, :, None, ax] - c[:, None, :, ax]
        d2 = d2 + diff * diff
    return d2


def _margin_sq(q: jax.Array, lo: jax.Array, hi: jax.Array,
               domain: float) -> jax.Array:
    """Squared margin from each query to the complement of its dilated box.

    Box sides at/beyond the domain boundary are unconstraining (all points live
    in [0, domain]^3).  jnp twin of rings.box_margin_bound_sq.
    """
    m_lo = jnp.where(lo[:, None, :] <= 0.0, jnp.inf, q - lo[:, None, :])
    m_hi = jnp.where(hi[:, None, :] >= domain, jnp.inf, hi[:, None, :] - q)
    m = jnp.maximum(jnp.minimum(m_lo, m_hi).min(axis=-1), 0.0)
    return jnp.where(jnp.isinf(m), jnp.inf, m * m)


def chunk_best(points: jax.Array, starts: jax.Array, counts: jax.Array,
               own: jax.Array, cand: jax.Array, lo: jax.Array, hi: jax.Array,
               qcap: int, ccap: int, k: int, dist_method: str,
               exclude_self: bool, domain: float):
    """Score one batch of supercells: gather queries + candidates, dense
    distances, masked top-k, completeness certificates.

    The reusable core of both the single-chip scan below and the sharded path
    (parallel/sharded.py), which calls it on halo-extended local arrays.
    Returns (q_idx, q_valid, best_d, best_i, cert); q_idx/best_i index `points`.
    """
    q_idx, q_valid = pack_cells(own, starts, counts, qcap)
    c_idx, c_valid = pack_cells(cand, starts, counts, ccap)
    q = jnp.take(points, q_idx, axis=0)
    c = jnp.take(points, c_idx, axis=0)
    d2 = _pair_d2(q, c, dist_method)
    mask = q_valid[:, :, None] & c_valid[:, None, :]
    if exclude_self:
        # skip self by *storage index* (knearests.cu:123): coordinate
        # duplicates of the query are still reported.
        mask = mask & (c_idx[:, None, :] != q_idx[:, :, None])
    ids = jnp.broadcast_to(c_idx[:, None, :], d2.shape)
    best_d, best_i = masked_topk(d2, ids, mask, k)
    kth = best_d[..., -1]
    cert = q_valid & (kth <= _margin_sq(q, lo, hi, domain))
    return q_idx, q_valid, best_d, best_i, cert


@functools.partial(jax.jit, static_argnames=("k", "dist_method", "exclude_self",
                                             "domain"))
def _solve_planned(points: jax.Array, starts: jax.Array, counts: jax.Array,
                   plan: SolvePlan, k: int, dist_method: str, exclude_self: bool,
                   domain: float):
    n = points.shape[0]
    out_d = jnp.full((n, k), jnp.inf, jnp.float32)
    out_i = jnp.full((n, k), INVALID_ID, jnp.int32)
    out_cert = jnp.zeros((n,), bool)

    def step(carry, chunk):
        out_d, out_i, out_cert = carry
        own, cand, lo, hi = chunk
        q_idx, q_valid, best_d, best_i, cert = chunk_best(
            points, starts, counts, own, cand, lo, hi,
            plan.qcap, plan.ccap, k, dist_method, exclude_self, domain)
        safe = jnp.where(q_valid, q_idx, n)  # n = out of bounds -> dropped
        out_d = out_d.at[safe].set(best_d, mode="drop")
        out_i = out_i.at[safe].set(best_i, mode="drop")
        out_cert = out_cert.at[safe].set(cert, mode="drop")
        return (out_d, out_i, out_cert), None

    (out_d, out_i, out_cert), _ = jax.lax.scan(
        step, (out_d, out_i, out_cert),
        (plan.own_cells, plan.cand_cells, plan.box_lo, plan.box_hi))
    return out_i, out_d, out_cert, jnp.sum(~out_cert, dtype=jnp.int32)


def pick_backend(cfg: KnnConfig, qcap: int, ccap: int) -> str:
    """'pallas' or 'xla' for a tile of the given capacities -- the single
    backend policy, shared by the single-chip, sharded, and external-query
    paths.  'auto' picks the fused Pallas kernel on TPU whenever the tile
    fits the VMEM budget."""
    if cfg.backend != "auto":
        if cfg.backend == "pallas" and cfg.dist_method == "dot":
            raise ValueError(
                "backend='pallas' computes 'diff' distances only; use "
                "dist_method='diff' or backend='xla'")
        if cfg.backend == "oracle":
            # the oracle engine is handled entirely in api.KnnProblem; a
            # grid path asked to run it must refuse rather than silently
            # substitute the grid engine
            raise ValueError(
                "backend='oracle' is a single-chip host engine "
                "(api.KnnProblem); this path has no oracle route")
        return cfg.backend
    if cfg.dist_method == "dot":
        return "xla"  # the kernel has no 'dot' arithmetic; honor the request
    from .pallas_solve import pick_qsub  # local import: avoid cycle

    on_tpu = jax.devices()[0].platform == "tpu"
    if (on_tpu or cfg.interpret) and pick_qsub(qcap, ccap, cfg.k):
        return "pallas"  # oversized query axes split across grid steps
    return "xla"


def resolve_backend(cfg: KnnConfig, plan: SolvePlan) -> str:
    return pick_backend(cfg, plan.qcap, plan.ccap)


def prepare_pack(grid: GridHash, cfg: KnnConfig, plan: SolvePlan):
    """Build the static kernel-input pack when the resolved backend is pallas
    (for callers that cache it across repeat solves); None for the xla path."""
    if resolve_backend(cfg, plan) != "pallas":
        return None
    from ..config import resolve_kernel
    from .pallas_solve import (build_pack, hbm_budget_bytes,  # local import:
                               launch_row_out, preflight_launch)  # avoid cycle

    # refuse a would-OOM pack BEFORE allocating it: the pack itself is the
    # launch-scale HBM commitment the preflight models.  Same actual-layout
    # modeling as solve_pallas (launch_row_out), so a scatter-mode refusal
    # fires HERE -- before the pack allocation -- not after it.
    kernel = resolve_kernel(cfg.effective_kernel(), cfg.k, plan.ccap)
    preflight_launch(plan.qcap, plan.ccap, cfg.k,
                     plan.n_chunks * plan.batch,
                     row_out=launch_row_out(plan.qcap, plan.ccap, cfg.k,
                                            kernel, cfg.resolved_epilogue()),
                     site="prepare_pack", budget=hbm_budget_bytes(cfg))
    return build_pack(grid.points, grid.cell_starts, grid.cell_counts, plan)


def solve(grid: GridHash, cfg: KnnConfig, plan: SolvePlan | None = None,
          pack=None) -> KnnResult:
    """Grid-accelerated all-points kNN (reference analog: kn_solve,
    /root/reference/knearests.cu:348-392).  Results are in sorted indexing;
    uncertified queries are *not* fixed up here -- api.KnnProblem drives the
    exact fallback.  ``pack`` (from prepare_pack) skips input re-packing on
    the pallas backend."""
    if plan is None:
        plan = build_plan(grid, cfg)
    if resolve_backend(cfg, plan) == "pallas":
        from .pallas_solve import solve_pallas  # local import: avoid cycle

        return solve_pallas(grid, cfg, plan, pack)
    nbr, d2, cert, n_unc = _solve_planned(
        grid.points, grid.cell_starts, grid.cell_counts, plan, cfg.k,
        cfg.dist_method, cfg.exclude_self, grid.domain)
    return KnnResult(neighbors=nbr, dists_sq=d2, certified=cert,
                     uncert_count=n_unc)


@functools.partial(jax.jit, static_argnames=("k", "exclude_self", "tile"))
def brute_force_by_index(points: jax.Array, q_idx: jax.Array, k: int,
                         exclude_self: bool = True, tile: int = 8192):
    """Exact kNN for selected stored points against the full set, tiled.

    Streaming merge_topk over point tiles -- the exact-resolution path for
    uncertified queries and the small-n reference solver for tests.  q_idx may be
    padded with -1 (rows ignored).  Returns ((m, k) ids ascending, (m, k) d2) in
    sorted indexing.  Dimension-agnostic: ``points`` may be (n, d) for any
    d >= 1 (the brute/MXU route's general-d refinement tier rides this same
    path; at d=3 the traced program is unchanged).
    """
    n, dim = points.shape
    n_pad = -(-n // tile) * tile
    pts = jnp.concatenate(
        [points, jnp.full((n_pad - n, dim), _FAR, points.dtype)], axis=0)
    q_ok = q_idx >= 0
    q = jnp.take(points, jnp.where(q_ok, q_idx, 0), axis=0)

    ids_all = jnp.arange(n_pad, dtype=jnp.int32)

    def body(carry, inp):
        best_d, best_i = carry
        pts_t, ids_t = inp
        d2 = jnp.zeros((q.shape[0], tile), jnp.float32)
        for ax in range(dim):
            diff = q[:, None, ax] - pts_t[None, :, ax]
            d2 = d2 + diff * diff
        mask = (ids_t[None, :] < n)
        if exclude_self:
            mask = mask & (ids_t[None, :] != q_idx[:, None])
        ids_b = jnp.broadcast_to(ids_t[None, :], d2.shape)
        return merge_topk(best_d, best_i, d2, ids_b, mask), None

    init = init_topk((q.shape[0],), k)
    (best_d, best_i), _ = jax.lax.scan(
        body, init, (pts.reshape(-1, tile, dim), ids_all.reshape(-1, tile)))
    best_i = jnp.where(q_ok[:, None], best_i, INVALID_ID)
    best_d = jnp.where(q_ok[:, None], best_d, jnp.inf)
    return best_i, best_d
