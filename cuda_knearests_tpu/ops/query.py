"""Grid-accelerated kNN for arbitrary query points against a prepared problem.

The reference's GPU engine only answers the all-points self-query (every stored
point is its own query, kn_solve, /root/reference/knearests.cu:348-392); its CPU
oracle, however, takes arbitrary query coordinates
(/root/reference/kd_tree.cpp:168-205).  This module closes that asymmetry: any
(m, 3) query set in the engine domain is answered against the stored point set,
reusing the prepared problem's supercell schedule and candidate pack.

Design: queries are bucketed into the same supercell tiling as the stored
points -- a query in supercell b shares b's dilated candidate box, so the
cached PallasPack candidate blocks are reused verbatim.  Query-side packing is
trivial (sort by supercell id -> contiguous ranges), with the same per-query
completeness certificate and exact brute-force fallback as the self-query path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .gridhash import GridHash, cell_coords
from .solve import SolvePlan, _margin_sq, _round_up
from .topk import INVALID_ID, init_topk, merge_topk

_FAR = 1.0e30


def bucket_queries(queries: np.ndarray, grid: GridHash, supercell: int,
                   s_total: int):
    """Host-side query bucketing: sort queries by supercell id.

    Returns (order, sc_counts, sc_starts, q2cap, inv_flat, inv_sc): `order`
    sorts queries supercell-major (stable), `sc_counts`/`sc_starts` the
    per-supercell query count / exclusive prefix over the plan's flat
    supercell axis, `q2cap` the padded per-supercell capacity, and
    `inv_flat`/`inv_sc` the slot-partition inverse (sorted query row r lives
    in flat slot inv_flat[r]; its supercell is inv_sc[r]) -- the static map
    that makes the epilogue a gather, like PallasPack.inv_flat.
    """
    coords = np.asarray(jax.device_get(
        cell_coords(jnp.asarray(queries, jnp.float32), grid.dim, grid.domain)))
    n_sc = -(-grid.dim // supercell)
    sc = coords // supercell
    sid = sc[:, 0] + n_sc * (sc[:, 1] + n_sc * sc[:, 2])
    order = np.argsort(sid, kind="stable").astype(np.int32)
    sc_counts = np.bincount(sid, minlength=s_total).astype(np.int32)
    q2cap = _round_up(int(sc_counts.max()) if sc_counts.size else 1, 128)
    # i64 so sid*q2cap+rank is computed at full width before the final i32
    # cast (same pre-guard headroom rationale as adaptive.launch_class_query)
    starts = np.concatenate([[0], np.cumsum(sc_counts)[:-1]]).astype(np.int64)  # kntpu-ok: wide-dtype -- pre-cast index headroom (see above)
    sid_sorted = sid[order].astype(np.int64)                                    # kntpu-ok: wide-dtype -- pre-cast index headroom (see above)
    inv_flat = (sid_sorted * q2cap
                + (np.arange(order.size) - starts[sid_sorted])).astype(np.int32)
    return (order, sc_counts, starts.astype(np.int32), q2cap, inv_flat,
            sid_sorted.astype(np.int32))


@functools.partial(jax.jit, static_argnames=("q2cap", "k", "exclude_hint",
                                             "domain", "interpret",
                                             "epilogue"))
def _query_packed(queries_sorted: jax.Array, sc_starts: jax.Array,
                  sc_counts: jax.Array, inv_flat: jax.Array,
                  inv_sc: jax.Array, pack, plan: SolvePlan, q2cap: int,
                  k: int, exclude_hint: bool, domain: float,
                  interpret: bool = False, epilogue: str = "gather"):
    """Kernel launch over the plan's supercells with external query blocks.

    Returns ((m,k) ids in *sorted stored-point* indexing, (m,k) d2,
    (m,) certified), rows in *sorted query* order.  epilogue='gather' is the
    same transpose + row-gather epilogue as pallas_solve._solve_packed;
    'scatter' has the kernel emit row-major rows at scalar-prefetched block
    offsets (_pallas_topk_rows, empty supercells sink) so only the inv_flat
    row gather remains.  inv_flat/inv_sc un-pad the slot blocks either way.
    """
    from .pallas_solve import _PAD_Q, _pallas_topk, _topk_rows_or_transpose

    s_total = pack.s_total
    slots = jnp.arange(q2cap, dtype=jnp.int32)
    qs_idx = sc_starts[:, None] + slots[None, :]
    qs_ok = slots[None, :] < sc_counts[:, None]
    safe_qs = jnp.where(qs_ok, qs_idx, 0)
    # per-axis (S, 1, q2cap) lane blocks, like the pack's candidates -- a
    # (S, q2cap, 3) gather would put 3 on the TPU lane axis (42.7x padding)
    qaxes = queries_sorted.T
    qx, qy, qz = (jnp.take(qaxes[ax], safe_qs, axis=0)
                  .reshape(s_total, 1, q2cap) for ax in range(3))
    # exclude_self is by *stored index*; external queries have none, so the id
    # block is all-_PAD_Q and exclusion is compiled out.
    qid3 = jnp.full((s_total, 1, q2cap), _PAD_Q, jnp.int32)

    if epilogue == "scatter":
        # shared eligibility gate (kpass-only surface: this path never
        # resolves a blocked body, so only the VMEM check can fall back)
        flat_d, flat_i = _topk_rows_or_transpose(
            qx, qy, qz, pack.cx, pack.cy, pack.cz, qid3, pack.cid3,
            q2cap, pack.ccap, k, exclude_hint, interpret, qs_ok)
    else:
        out_d, out_i = _pallas_topk(qx, qy, qz, pack.cx, pack.cy, pack.cz,
                                    qid3, pack.cid3,
                                    q2cap, pack.ccap, k, exclude_hint,
                                    interpret)
        flat_d = out_d.transpose(0, 2, 1).reshape(-1, k)
        flat_i = out_i.transpose(0, 2, 1).reshape(-1, k)
    row_d = jnp.take(flat_d, inv_flat, axis=0)             # (m, k)
    row_i = jnp.take(flat_i, inv_flat, axis=0)
    ok = jnp.isfinite(row_d)
    row_i = jnp.where(ok, row_i, INVALID_ID)
    row_d = jnp.where(ok, row_d, jnp.inf)

    lo = jnp.take(plan.box_lo.reshape(s_total, 3), inv_sc, axis=0)
    hi = jnp.take(plan.box_hi.reshape(s_total, 3), inv_sc, axis=0)
    cert = row_d[:, k - 1] <= _margin_sq(queries_sorted[:, None, :], lo, hi,
                                         domain)[:, 0]
    return row_i, row_d, cert


@functools.partial(jax.jit, static_argnames=("k", "tile"))
def brute_force_by_coords(points: jax.Array, queries: jax.Array, k: int,
                          tile: int = 8192):
    """Exact kNN of explicit query coordinates against the full stored set,
    streaming merge_topk over point tiles (the external-query twin of
    solve.brute_force_by_index)."""
    n = points.shape[0]
    n_pad = -(-n // tile) * tile
    pts = jnp.concatenate(
        [points, jnp.full((n_pad - n, 3), _FAR, points.dtype)], axis=0)
    ids_all = jnp.arange(n_pad, dtype=jnp.int32)

    def body(carry, inp):
        best_d, best_i = carry
        pts_t, ids_t = inp
        d2 = jnp.zeros((queries.shape[0], tile), jnp.float32)
        for ax in range(3):
            diff = queries[:, None, ax] - pts_t[None, :, ax]
            d2 = d2 + diff * diff
        mask = ids_t[None, :] < n
        ids_b = jnp.broadcast_to(ids_t[None, :], d2.shape)
        return merge_topk(best_d, best_i, d2, ids_b, mask), None

    init = init_topk((queries.shape[0],), k)
    (best_d, best_i), _ = jax.lax.scan(
        body, init, (pts.reshape(-1, tile, 3), ids_all.reshape(-1, tile)))
    return best_i, best_d


def query_knn(grid: GridHash, plan: SolvePlan, pack, queries: np.ndarray,
              k: int, supercell: int, interpret: bool = False,
              fallback: str = "brute",
              epilogue: str = "gather") -> Tuple[np.ndarray, np.ndarray]:
    """Full external-query pipeline.  Returns ((m,k) neighbor ids in ORIGINAL
    point indexing, ascending; (m,k) squared distances), rows in query order.

    `k` must not exceed the k the plan's ring radius was sized for -- the
    completeness certificate is only as deep as the candidate dilation.
    """
    queries = np.ascontiguousarray(queries, np.float32)
    m = queries.shape[0]
    if m == 0:
        return (np.empty((0, k), np.int32), np.empty((0, k), np.float32))
    order, sc_counts, starts, q2cap, inv_flat, inv_sc = bucket_queries(
        queries, grid, supercell, plan.n_chunks * plan.batch)
    qs = jnp.asarray(queries[order])

    # Backend gate: the kernel tile must fit VMEM *with this query set's*
    # per-supercell capacity (clustered queries can exceed the stored-point
    # pack's budget), and backend='xla' configs never take the kernel.  The
    # safe route is exact tiled brute force over all queries.
    from .pallas_solve import pick_qsub

    use_kernel = pack is not None and pick_qsub(q2cap, pack.ccap, k) > 0
    if use_kernel:
        out_i, out_d, cert = _query_packed(
            qs, jnp.asarray(starts), jnp.asarray(sc_counts),
            jnp.asarray(inv_flat), jnp.asarray(inv_sc), pack, plan,
            q2cap, k, False, grid.domain, interpret, epilogue)
        out_i = np.asarray(jax.device_get(out_i))
        out_d = np.asarray(jax.device_get(out_d))
        cert = np.asarray(jax.device_get(cert))
    else:
        out_i = np.full((m, k), INVALID_ID, np.int32)
        out_d = np.full((m, k), np.inf, np.float32)
        cert = np.zeros((m,), bool)

    # Brute resolution: fallback for uncertified kernel rows, primary path
    # when the kernel was gated off (then it ignores fallback='none' -- it is
    # the only exact route, not a fallback).
    if not cert.all() and (fallback == "brute" or not use_kernel):
        bad = np.nonzero(~cert)[0].astype(np.int32)
        b_i, b_d = brute_force_by_coords(grid.points, qs[bad], k)
        out_i[bad] = np.asarray(b_i)
        out_d[bad] = np.asarray(b_d)

    # sorted stored-point ids -> original ids; sorted query rows -> input order
    perm = np.asarray(jax.device_get(grid.permutation))
    valid = out_i >= 0
    ids_orig = np.where(valid, perm[np.clip(out_i, 0, grid.n_points - 1)],
                        INVALID_ID)
    nbrs = np.empty_like(ids_orig)
    d2 = np.empty_like(out_d)
    nbrs[order] = ids_orig
    d2[order] = out_d
    return nbrs, d2
