"""Grid-accelerated kNN for arbitrary query points against a prepared problem.

The reference's GPU engine only answers the all-points self-query (every stored
point is its own query, kn_solve, /root/reference/knearests.cu:348-392); its CPU
oracle, however, takes arbitrary query coordinates
(/root/reference/kd_tree.cpp:168-205).  This module closes that asymmetry: any
(m, 3) query set in the engine domain is answered against the stored point set,
reusing the prepared problem's supercell schedule and candidate pack.

Design: queries are bucketed into the same supercell tiling as the stored
points -- a query in supercell b shares b's dilated candidate box, so the
cached PallasPack candidate blocks are reused verbatim.  Query-side packing is
trivial (sort by supercell id -> contiguous ranges), with the same per-query
completeness certificate and exact brute-force fallback as the self-query path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import dispatch as _dispatch
from .gridhash import GridHash, cell_coords_host
from .solve import SolvePlan, _margin_sq, _round_up
from .topk import INVALID_ID, init_topk, merge_topk, translate_ids

_FAR = 1.0e30


def bucket_queries(queries: np.ndarray, grid: GridHash, supercell: int,
                   s_total: int):
    """Host-side query bucketing: sort queries by supercell id.

    Pure numpy -- cell coordinates come from gridhash.cell_coords_host (the
    bit-identical host twin of the device mapping), so bucketing costs no
    device round trip (the old eager cell_coords staging+readback was one
    full round trip per query call).

    Returns (order, sc_counts, sc_starts, q2cap, inv_flat, inv_sc): `order`
    sorts queries supercell-major (stable), `sc_counts`/`sc_starts` the
    per-supercell query count / exclusive prefix over the plan's flat
    supercell axis, `q2cap` the padded per-supercell capacity, and
    `inv_flat`/`inv_sc` the slot-partition inverse (sorted query row r lives
    in flat slot inv_flat[r]; its supercell is inv_sc[r]) -- the static map
    that makes the epilogue a gather, like PallasPack.inv_flat.  The chunk
    pipeline re-derives inv_flat alone at a shared capacity via
    _inv_flat_at (it is the only q2cap-dependent output).
    """
    coords = cell_coords_host(queries, grid.dim, grid.domain)
    n_sc = -(-grid.dim // supercell)
    sc = coords // supercell
    sid = sc[:, 0] + n_sc * (sc[:, 1] + n_sc * sc[:, 2])
    order = np.argsort(sid, kind="stable").astype(np.int32)
    sc_counts = np.bincount(sid, minlength=s_total).astype(np.int32)
    q2cap = _round_up(int(sc_counts.max()) if sc_counts.size else 1, 128)
    # i64 so sid*q2cap+rank is computed at full width before the final i32
    # cast (same pre-guard headroom rationale as adaptive.launch_class_query)
    starts = np.concatenate([[0], np.cumsum(sc_counts)[:-1]]).astype(np.int64)  # kntpu-ok: wide-dtype -- pre-cast index headroom (see above)
    sid_sorted = sid[order].astype(np.int64)                                    # kntpu-ok: wide-dtype -- pre-cast index headroom (see above)
    inv_flat = (sid_sorted * q2cap
                + (np.arange(order.size) - starts[sid_sorted])).astype(np.int32)
    return (order, sc_counts, starts.astype(np.int32), q2cap, inv_flat,
            sid_sorted.astype(np.int32))


def _inv_flat_at(sc_starts: np.ndarray, inv_sc: np.ndarray,
                 q2cap: int) -> np.ndarray:
    """Recompute a bucketing's slot-partition inverse at a pinned capacity
    -- inv_flat is the ONLY q2cap-dependent output of bucket_queries, so
    the chunk pipeline pins every chunk to the shared capacity with one
    cheap indexed subtraction instead of a full re-bucket (argsort +
    bincount twice per chunk)."""
    # same pre-cast i64 headroom rationale as bucket_queries
    starts64 = sc_starts.astype(np.int64)                                       # kntpu-ok: wide-dtype -- pre-cast index headroom (see bucket_queries)
    sid64 = inv_sc.astype(np.int64)                                             # kntpu-ok: wide-dtype -- pre-cast index headroom (see bucket_queries)
    rank = np.arange(sid64.size) - starts64[sid64]
    return (sid64 * q2cap + rank).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("q2cap", "k", "exclude_hint",
                                             "domain", "interpret",
                                             "epilogue"))
def _query_packed(queries_sorted: jax.Array, sc_starts: jax.Array,
                  sc_counts: jax.Array, inv_flat: jax.Array,
                  inv_sc: jax.Array, pack, plan: SolvePlan, perm: jax.Array,
                  q2cap: int,
                  k: int, exclude_hint: bool, domain: float,
                  interpret: bool = False, epilogue: str = "gather"):
    """Kernel launch over the plan's supercells with external query blocks.

    Returns ((m,k) ids in ORIGINAL point indexing -- translated on device
    through ``perm`` so the caller needs no host-side permutation fetch --
    (m,k) d2, (m,) certified), rows in *sorted query* order.
    epilogue='gather' is the
    same transpose + row-gather epilogue as pallas_solve._solve_packed;
    'scatter' has the kernel emit row-major rows at scalar-prefetched block
    offsets (_pallas_topk_rows, empty supercells sink) so only the inv_flat
    row gather remains.  inv_flat/inv_sc un-pad the slot blocks either way.
    """
    from .pallas_solve import _PAD_Q, _pallas_topk, _topk_rows_or_transpose

    s_total = pack.s_total
    slots = jnp.arange(q2cap, dtype=jnp.int32)
    qs_idx = sc_starts[:, None] + slots[None, :]
    qs_ok = slots[None, :] < sc_counts[:, None]
    safe_qs = jnp.where(qs_ok, qs_idx, 0)
    # per-axis (S, 1, q2cap) lane blocks, like the pack's candidates -- a
    # (S, q2cap, 3) gather would put 3 on the TPU lane axis (42.7x padding)
    qaxes = queries_sorted.T
    qx, qy, qz = (jnp.take(qaxes[ax], safe_qs, axis=0)
                  .reshape(s_total, 1, q2cap) for ax in range(3))
    # exclude_self is by *stored index*; external queries have none, so the id
    # block is all-_PAD_Q and exclusion is compiled out.
    qid3 = jnp.full((s_total, 1, q2cap), _PAD_Q, jnp.int32)

    if epilogue == "scatter":
        # shared eligibility gate (kpass-only surface: this path never
        # resolves a blocked body, so only the VMEM check can fall back)
        flat_d, flat_i = _topk_rows_or_transpose(
            qx, qy, qz, pack.cx, pack.cy, pack.cz, qid3, pack.cid3,
            q2cap, pack.ccap, k, exclude_hint, interpret, qs_ok)
    else:
        out_d, out_i = _pallas_topk(qx, qy, qz, pack.cx, pack.cy, pack.cz,
                                    qid3, pack.cid3,
                                    q2cap, pack.ccap, k, exclude_hint,
                                    interpret)
        flat_d = out_d.transpose(0, 2, 1).reshape(-1, k)
        flat_i = out_i.transpose(0, 2, 1).reshape(-1, k)
    row_d = jnp.take(flat_d, inv_flat, axis=0)             # (m, k)
    row_i = jnp.take(flat_i, inv_flat, axis=0)
    ok = jnp.isfinite(row_d)
    row_i = jnp.where(ok, row_i, INVALID_ID)
    row_d = jnp.where(ok, row_d, jnp.inf)
    # sorted stored-point ids -> ORIGINAL ids on device: readback stays
    # O(m*k) and the caller never fetches the (n,) permutation
    row_i = translate_ids(row_i, perm)

    lo = jnp.take(plan.box_lo.reshape(s_total, 3), inv_sc, axis=0)
    hi = jnp.take(plan.box_hi.reshape(s_total, 3), inv_sc, axis=0)
    cert = row_d[:, k - 1] <= _margin_sq(queries_sorted[:, None, :], lo, hi,
                                         domain)[:, 0]
    return row_i, row_d, cert


@functools.partial(jax.jit, static_argnames=("k", "tile"))
def brute_force_by_coords(points: jax.Array, queries: jax.Array, k: int,
                          tile: int = 8192, ids_map: jax.Array | None = None):
    """Exact kNN of explicit query coordinates against the full stored set,
    streaming merge_topk over point tiles (the external-query twin of
    solve.brute_force_by_index).  ``ids_map`` (e.g. the grid permutation)
    translates result ids on device before readback, same contract as
    _query_class.  Dimension-agnostic like its twin: (n, d) points for any
    d (the traced program at d=3 is unchanged)."""
    n, dim = points.shape
    n_pad = -(-n // tile) * tile
    pts = jnp.concatenate(
        [points, jnp.full((n_pad - n, dim), _FAR, points.dtype)], axis=0)
    ids_all = jnp.arange(n_pad, dtype=jnp.int32)

    def body(carry, inp):
        best_d, best_i = carry
        pts_t, ids_t = inp
        d2 = jnp.zeros((queries.shape[0], tile), jnp.float32)
        for ax in range(dim):
            diff = queries[:, None, ax] - pts_t[None, :, ax]
            d2 = d2 + diff * diff
        mask = ids_t[None, :] < n
        ids_b = jnp.broadcast_to(ids_t[None, :], d2.shape)
        return merge_topk(best_d, best_i, d2, ids_b, mask), None

    init = init_topk((queries.shape[0],), k)
    (best_d, best_i), _ = jax.lax.scan(
        body, init, (pts.reshape(-1, tile, dim), ids_all.reshape(-1, tile)))
    if ids_map is not None:
        best_i = translate_ids(best_i, ids_map)
    return best_i, best_d


def launch_brute(points, queries, k: int, ids_map, tile: int = 8192,
                 base_key=None):
    """One brute-force launch through the executable-signature cache -- the
    host-platform twin of :func:`_launch_packed`.

    On kernel-less platforms the external-query route (and the serving
    daemon's capacity-bucketed batches, serve/engine -- whose zero-recompile
    steady state is asserted against exactly these cache counters) executes
    through this launch: the AOT ``lower().compile()`` product is keyed by
    the same :func:`~..runtime.dispatch.signature` census as the kernel
    route, so repeated same-shape batches reuse ONE compiled program.  A
    backend that cannot AOT-lower falls back to the plain jitted call
    (EXEC_CACHE disables itself, same contract as _launch_packed)."""
    args = (points, queries, ids_map)
    key = (("ops.query.brute_force_by_coords",) + tuple(base_key or ())
           + _dispatch.signature(args, k, tile))
    exe = _dispatch.EXEC_CACHE.get_or_build(
        key, lambda: brute_force_by_coords.lower(
            points, queries, k=k, tile=tile, ids_map=ids_map).compile())
    if exe is not None:
        return exe(points, queries, ids_map=ids_map)
    return brute_force_by_coords(points, queries, k, tile=tile,
                                 ids_map=ids_map)


def _launch_packed(qs, starts, sc_counts, inv_flat, inv_sc, pack, plan, perm,
                   q2cap: int, k: int, domain: float, interpret: bool,
                   epilogue: str, base_key=None):
    """One chunk's kernel launch through the executable-signature cache.

    The cache key is the recompile-key census (runtime.dispatch.signature,
    the same function the kntpu-check contract engine reports per route)
    over the launch's abstract arguments plus its statics, prefixed by the
    problem's prepare-time key -- so repeated problems (and repeated query
    chunks) with the same class-shape signature reuse ONE AOT-compiled
    executable instead of re-tracing.  A backend that cannot AOT-lower
    falls back to the plain jitted call (EXEC_CACHE disables itself)."""
    args = (qs, _dispatch.stage(starts), _dispatch.stage(sc_counts),  # syncflow: query-launch-stage
            _dispatch.stage(inv_flat), _dispatch.stage(inv_sc), pack, plan,  # syncflow: query-launch-stage
            perm)
    statics = dict(q2cap=q2cap, k=k, exclude_hint=False, domain=domain,
                   interpret=interpret, epilogue=epilogue)
    # the function identity leads the key: EXEC_CACHE is process-wide, and
    # two different launch functions with a coincidentally equal shape
    # census must never serve each other's executables
    key = (("ops.query._query_packed",) + tuple(base_key or ())
           + _dispatch.signature(args, *sorted(statics.items())))
    exe = _dispatch.EXEC_CACHE.get_or_build(
        key, lambda: _query_packed.lower(*args, **statics).compile())
    if exe is not None:
        return exe(*args)
    return _query_packed(*args, **statics)


def query_knn(grid: GridHash, plan: SolvePlan, pack, queries: np.ndarray,
              k: int, supercell: int, interpret: bool = False,
              fallback: str = "brute",
              epilogue: str = "gather", chunk: int | None = None,
              exec_key=None) -> Tuple[np.ndarray, np.ndarray]:
    """Full external-query pipeline.  Returns ((m,k) neighbor ids in ORIGINAL
    point indexing, ascending; (m,k) squared distances), rows in query order.

    One-sync contract (DESIGN.md section 12): bucketing is pure host numpy,
    every launch's inputs stage asynchronously, result ids translate to
    original indexing ON DEVICE, and the call blocks exactly once on a
    batched readback of every chunk's results -- plus at most one more fetch
    for the exact resolution of uncertified kernel rows.  With ``chunk`` set
    the queries split into fixed-size chunks whose uploads and launches
    dispatch back-to-back (chunk i+1 stages while chunk i computes -- the
    double buffer is the async dispatch queue itself), all chunks bucketed
    at ONE shared per-supercell capacity so they reuse one cached executable
    (``exec_key`` prefixes the cache key with the problem's prepare-time
    signature census).  Byte-identical to the single-shot path
    (tests/test_dispatch.py).

    `k` must not exceed the k the plan's ring radius was sized for -- the
    completeness certificate is only as deep as the candidate dilation.
    """
    queries = np.ascontiguousarray(queries, np.float32)
    m = queries.shape[0]
    if m == 0:
        return (np.empty((0, k), np.int32), np.empty((0, k), np.float32))
    s_total = plan.n_chunks * plan.batch
    step = m if not chunk else max(1, int(chunk))
    bounds = [(a, min(a + step, m)) for a in range(0, m, step)]
    buckets = [bucket_queries(queries[a:b], grid, supercell, s_total)
               for a, b in bounds]
    q2cap = max(bk[3] for bk in buckets)
    if len(bounds) > 1:
        # pin every chunk to the shared capacity -> one executable
        # signature; only inv_flat depends on q2cap, so this is one indexed
        # subtraction per chunk, not a re-bucket
        buckets = [(order, cnt, st, q2cap, _inv_flat_at(st, inv_sc, q2cap),
                    inv_sc)
                   for order, cnt, st, _q2, _inv, inv_sc in buckets]

    # Backend gate: the kernel tile must fit VMEM *with this query set's*
    # per-supercell capacity (clustered queries can exceed the stored-point
    # pack's budget), and backend='xla' configs never take the kernel.  The
    # safe route is exact tiled brute force over all queries.
    from .pallas_solve import pick_qsub

    use_kernel = pack is not None and pick_qsub(q2cap, pack.ccap, k) > 0

    # dispatch phase: no readback between chunks -- chunk i+1's staging
    # overlaps chunk i's compute on the async dispatch queue
    pending = []
    for (a, b), (order, sc_counts, starts, _q2, inv_flat, inv_sc) \
            in zip(bounds, buckets):
        qs = _dispatch.stage(queries[a:b][order])  # syncflow: query-chunk-stage
        if use_kernel:
            r_i, r_d, r_c = _launch_packed(
                qs, starts, sc_counts, inv_flat, inv_sc, pack, plan,
                grid.permutation, q2cap, k, grid.domain, interpret, epilogue,
                base_key=exec_key)
        else:
            r_i, r_d = launch_brute(grid.points, qs, k,
                                    ids_map=grid.permutation,
                                    base_key=exec_key)
            r_c = None  # exact by construction
        pending.append((r_i, r_d, r_c))

    # the one sync: a single batched readback of every chunk's results
    fetched = _dispatch.fetch(pending)  # syncflow: query-final

    nbrs = np.empty((m, k), np.int32)
    d2 = np.empty((m, k), np.float32)
    cert = np.ones((m,), bool)
    for (a, _b), (order, *_), (h_i, h_d, h_c) in zip(bounds, buckets,
                                                     fetched):
        rows = a + order  # sorted chunk row r belongs to input a + order[r]
        nbrs[rows] = h_i  # fetch() already landed host numpy -- no sync here
        d2[rows] = h_d
        if h_c is not None:
            cert[rows] = h_c

    # Brute resolution of uncertified kernel rows (the brute-primary path is
    # exact already): one more dispatch + batched fetch, never a sync storm.
    if use_kernel and not cert.all() and fallback == "brute":
        bad = np.nonzero(~cert)[0].astype(np.int32)
        b_i, b_d = brute_force_by_coords(
            grid.points, _dispatch.stage(queries[bad]), k,  # syncflow: query-fallback-stage
            ids_map=grid.permutation)
        b_i, b_d = _dispatch.fetch(b_i, b_d)  # syncflow: query-fallback
        nbrs[bad] = np.asarray(b_i)
        d2[bad] = np.asarray(b_d)
    return nbrs, d2
