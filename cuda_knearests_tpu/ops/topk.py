"""Masked and streaming top-k utilities (smallest-distance semantics).

Reference parity (C4's data structure): the reference maintains a bounded k-max-
heap per query thread in CUDA shared memory (heapify/heapsort,
/root/reference/knearests.cu:62-91,95-110) and heapsorts it into an ascending
neighbor list.  On TPU there is no per-thread mutable heap; the idiomatic
replacement is ``lax.top_k`` over (masked) candidate tiles, and a concat+top_k
*merge* for streaming candidates ring-by-ring or tile-by-tile.  Results come out
ascending (nearest first), matching the reference's post-heapsort output
(knearests.cu:141-147).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

INVALID_ID = -1  # "not found" sentinel (the reference uses UINT_MAX, knearests.cu:107)


def translate_ids(ids: jax.Array, ids_map: jax.Array) -> jax.Array:
    """Sentinel-preserving on-device id translation: valid entries (>= 0)
    gather through ``ids_map`` (e.g. sorted-storage index -> original id via
    the grid permutation, or the sharded path's ext-index -> original-id
    block); INVALID_ID rows stay INVALID_ID.  The ONE implementation every
    solve/query route uses, so the clip bound and sentinel handling can
    never drift between copies."""
    return jnp.where(
        ids >= 0,
        jnp.take(ids_map, jnp.clip(ids, 0, ids_map.shape[0] - 1)),
        INVALID_ID)


def masked_topk(d2: jax.Array, ids: jax.Array, mask: jax.Array,
                k: int) -> Tuple[jax.Array, jax.Array]:
    """Smallest-k over the last axis with a validity mask.

    Args:
      d2:   (..., m) squared distances.
      ids:  (..., m) candidate ids aligned with d2.
      mask: (..., m) True where the candidate is real.
      k:    static neighbor count.
    Returns:
      (dists, ids): (..., k) ascending; masked-out / missing slots get
      +inf / INVALID_ID.
    """
    d2 = jnp.where(mask, d2, jnp.inf)
    neg, slot = jax.lax.top_k(-d2, k)  # top_k is largest-k -> negate for smallest
    best_d = -neg
    best_i = jnp.take_along_axis(ids, slot, axis=-1)
    best_i = jnp.where(jnp.isfinite(best_d), best_i, INVALID_ID)
    return best_d, best_i


def merge_topk(best_d: jax.Array, best_i: jax.Array,
               new_d: jax.Array, new_i: jax.Array, new_mask: jax.Array,
               ) -> Tuple[jax.Array, jax.Array]:
    """Fold a fresh candidate tile into a running ascending top-k.

    The streaming analog of the reference's heap-root replace+sift
    (knearests.cu:127-133): concat the running best (..., k) with the new tile
    (..., t), take smallest-k of the union.  Used by the ring-streaming and
    brute-force-tiled paths.
    """
    k = best_d.shape[-1]
    d2 = jnp.concatenate([best_d, jnp.where(new_mask, new_d, jnp.inf)], axis=-1)
    ids = jnp.concatenate([best_i, new_i], axis=-1)
    neg, slot = jax.lax.top_k(-d2, k)
    out_d = -neg
    out_i = jnp.take_along_axis(ids, slot, axis=-1)
    out_i = jnp.where(jnp.isfinite(out_d), out_i, INVALID_ID)
    return out_d, out_i


def init_topk(batch_shape: Tuple[int, ...], k: int) -> Tuple[jax.Array, jax.Array]:
    """Empty running top-k state: +inf distances, INVALID_ID ids (the reference
    initializes its heap slots to FLT_MAX / UINT_MAX, knearests.cu:107-110)."""
    return (jnp.full(batch_shape + (k,), jnp.inf, jnp.float32),
            jnp.full(batch_shape + (k,), INVALID_ID, jnp.int32))
