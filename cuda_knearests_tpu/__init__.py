"""cuda_knearests_tpu: a TPU-native k-nearest-neighbors framework.

A ground-up JAX/XLA/Pallas redesign with the capabilities of
``ssloy/cuda_knearests`` (see SURVEY.md): uniform-grid spatial hash, supercell-
tiled kNN solve with provable completeness certificates, exact C++ kd-tree
oracle, and -- beyond the reference -- multi-chip grid-slab sharding with ICI
halo exchange.
"""

# Restore standard JAX_PLATFORMS semantics before anything touches a backend:
# some environments site-register an accelerator platform that overrides the
# env var and hangs backend init when the accelerator transport is down.
from .utils.platform import honor_jax_platforms_env as _honor

_honor()

from .api import KnnProblem, knn, load_problem, save_problem  # noqa: E402
from .config import DEFAULT_CELL_DENSITY, DEFAULT_K, DOMAIN_SIZE, KnnConfig
from .ops.gridhash import GridHash, build_grid, cell_coords, cell_ids, \
    unpermute_neighbors
from .ops.solve import KnnResult, brute_force_by_index, build_plan, solve

__version__ = "0.1.0"

__all__ = [
    "KnnProblem", "knn", "save_problem", "load_problem",
    "KnnConfig", "KnnResult", "GridHash",
    "build_grid", "build_plan", "solve", "brute_force_by_index",
    "cell_coords", "cell_ids", "unpermute_neighbors",
    "DOMAIN_SIZE", "DEFAULT_K", "DEFAULT_CELL_DENSITY",
]
