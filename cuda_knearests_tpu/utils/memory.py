"""Checked host<->device staging helpers.

Reference parity (C5/C10): the reference wraps every cudaMalloc/Memcpy/Memset in
checked helpers that report the failing site and abort
(/root/reference/knearests.cu:205-231), and tracks total device memory used --
with a ``bufsize +=`` accounting bug that inflates the stat
(/root/reference/knearests.cu:329,333,342).  JAX owns allocation, so the useful
equivalents are: validated H2D staging (`to_device`), D2H extraction
(`from_device`), and *correct* buffer-size accounting for diagnostics.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class DeviceMemoryError(RuntimeError):
    """Raised when staging fails validation (analog of the reference's
    print-and-exit in gpuMalloc*, knearests.cu:205-231 -- but recoverable).

    Root of the device-fault hierarchy: every subclass stamps a ``kind`` from
    the supervisor's failure taxonomy (runtime/supervisor.py) so retry policy
    can key on fault kind instead of string-matching messages.  The base class
    covers checked-invariant refusals (non-finite staging data and the like),
    which are deterministic -- retrying them is never useful."""

    kind = "assertion"


class TransportError(DeviceMemoryError):
    """A *transient* accelerator-transport fault: the backend RPC layer
    reported UNAVAILABLE / connection loss rather than a real allocation or
    validation failure.  This environment's tunneled TPU transport goes dark
    and comes back (VERDICT r5: dark from 03:56 UTC to session end), so these
    are the one fault kind worth bounded retry-with-backoff -- the supervisor
    retries ``kind == 'transport'`` and quarantines everything else."""

    kind = "transport"


class DeviceOOMError(DeviceMemoryError):
    """A *runtime* allocation exhaustion reported by the backend
    (RESOURCE_EXHAUSTED from device_put / execute).  Same taxonomy bucket as
    a preflight refusal (kind 'oom') but after the fact: the preflight's
    model missed, or the allocation was outside its scope.  Deterministic
    for a given launch -- never retried, the fix is a smaller launch."""

    kind = "oom"


class LaunchBudgetError(DeviceMemoryError):
    """A launch refused by the HBM/VMEM preflight BEFORE any kernel grid is
    built (ops/pallas_solve.preflight_launch): the modeled footprint exceeds
    the budget, so running it would OOM or wedge the device.  Structured so
    callers can demote (smaller tile, streamed route, xla backend) instead of
    dying: ``requested``/``budget`` are bytes, ``site`` names the launch."""

    kind = "oom"

    def __init__(self, message: str, *, requested: Optional[int] = None,
                 budget: Optional[int] = None, site: str = ""):
        super().__init__(message)
        self.requested = requested
        self.budget = budget
        self.site = site


# -- input-contract taxonomy --------------------------------------------------
#
# The typed refusal hierarchy for ILLEGAL INPUT (as opposed to device faults
# above): every class subclasses ValueError so pre-existing callers that
# catch ValueError keep working, and every class stamps kind='invalid-input'
# so the supervisor's FailureRecord / classify_fault_text treat a contract
# refusal as its own failure class -- deterministic, never retried, never
# quarantine-worthy beyond the offending input.  io.validate_or_raise is the
# single front door that raises these; the solve routes (api.KnnProblem,
# parallel.sharded, the external-query surface, cli) all enforce it.


class InputContractError(ValueError):
    """Root of the illegal-input taxonomy (the input twin of
    DeviceMemoryError).  Raised when an input violates the engine's
    documented contract -- see DESIGN.md section 11 for the legal-input
    table and the degraded modes that do NOT raise (k > n pads, zero-extent
    clouds normalize)."""

    kind = "invalid-input"


class InvalidShapeError(InputContractError):
    """Points/queries are not a well-formed (n, 3) numeric array."""


class NonFiniteInputError(InputContractError, DeviceMemoryError):
    """NaN/inf coordinates.  Also a DeviceMemoryError: the checked staging
    helper (to_device) historically raised the device taxonomy here, so both
    ``except ValueError`` and ``except DeviceMemoryError`` callers keep
    catching it -- but the kind stamp is 'invalid-input' (InputContractError
    precedes DeviceMemoryError in the MRO), because the fix is cleaning the
    input, not anything device-side."""

    kind = "invalid-input"


class DomainBoundsError(InputContractError):
    """Coordinates outside the [0, domain]^3 engine contract
    (/root/reference/knearests.cu:21); run io.normalize_points first."""


class DegenerateExtentError(InputContractError):
    """An operation that needs a bounding box got no points to take one
    from (normalize/bbox of an empty cloud).  NOT raised for zero-extent
    clouds: all-identical points normalize by centering (degraded mode)."""


class InvalidKError(InputContractError):
    """k (or a radius cap) is not a positive integer, or exceeds the
    prepared k that sized the candidate dilation.  k > n is NOT an error:
    rows pad -1/inf beyond the available neighbors (degraded mode)."""


class CorruptInputError(InputContractError):
    """An input file that does not parse to its own declared contract
    (e.g. an .xyz header whose count disagrees with the rows)."""


class InvalidConfigError(InputContractError):
    """A configuration combination the engine cannot honor (e.g. a sharded
    solve asked to run the single-chip oracle backend, or a ring radius
    thicker than the z-slab it must fit inside)."""


class InvalidRequestError(InputContractError):
    """A serving-stream request that violates the request contract
    (io.validate_request): unknown operation kind, a query/insert payload
    failing the points contract, delete ids out of range for the current
    cloud, or a request larger than the daemon's batch capacity.  The
    daemon REFUSES the request with this typed taxonomy (wire error model,
    DESIGN.md section 13) instead of letting it crash a batch."""


class UnknownTenantError(InvalidRequestError):
    """A fleet request addressed a tenant the front door does not serve
    (serve/fleet, DESIGN.md section 17).  Deterministic caller error: the
    tenant field is part of the wire contract, and routing a request to a
    'nearest' tenant instead of refusing would silently answer it against
    the wrong point cloud."""


class OverQuotaError(InvalidRequestError):
    """A fleet request exceeded its tenant's token-bucket admission quota
    (serve/fleet/admission.py).  Typed refusal rather than silent queueing:
    over-quota load must surface to the CALLER (back-pressure), never
    convert into unbounded queue depth that starves the other tenants --
    the admission half of the fleet fairness law (DESIGN.md section 17)."""


# Lowercased substrings that identify a transient transport fault in backend
# error text.  UNAVAILABLE is the gRPC status the dead tunnel produces
# (r5_tpu_all_rows.json: every post-crash device_put failed UNAVAILABLE);
# the rest are the dark-probe / dropped-connection shapes seen in stderr.
_TRANSPORT_PATTERNS = (
    "unavailable", "deadline_exceeded", "deadline exceeded",
    "connection reset", "connection refused", "failed to connect",
    "socket closed", "transport is closing", "broken pipe",
)

# Real allocation exhaustion (distinct from transport: retrying the same
# launch cannot help; the fix is a smaller launch).  Anchored regexes, not
# bare substrings: 'oom' must be a standalone word ('headroom'/'zoom' in an
# unrelated traceback must NOT classify a crash as oom -- the taxonomy is
# what retry/quarantine policy keys on).
_OOM_RE = re.compile(
    r"resource[_ ]exhausted|out of memory|\boom\b|allocation failure"
    r"|failed to allocate")

# The input-contract taxonomy's class names as they appear in a traceback /
# stderr tail, plus the canonical phrase.  A worker that dies on illegal
# input classifies 'invalid-input' -- deterministic, never retried.
_INVALID_INPUT_RE = re.compile(
    r"inputcontracterror|invalidshapeerror|nonfiniteinputerror"
    r"|domainboundserror|degenerateextenterror|invalidkerror"
    r"|corruptinputerror|invalidconfigerror|invalidrequesterror"
    r"|unknowntenanterror|overquotaerror"
    r"|input contract|request contract|unknown tenant|over quota")


def classify_fault_text(text: str) -> Optional[str]:
    """Map backend/stderr error text onto the failure taxonomy: 'transport'
    for transient connection loss, 'invalid-input' for a typed contract
    refusal, 'oom' for allocation exhaustion, None when the text matches
    none of them (callers keep their own default kind).
    Transport wins ties: a dark tunnel produces UNAVAILABLE wrapped around
    all sorts of secondary allocator noise, and misclassifying a transient
    fault as oom would wrongly disable retry.  invalid-input beats oom: a
    contract refusal's message may legitimately mention budgets/allocation
    while still being a deterministic input problem."""
    low = (text or "").lower()
    if any(p in low for p in _TRANSPORT_PATTERNS):
        return "transport"
    if _INVALID_INPUT_RE.search(low):
        return "invalid-input"
    if _OOM_RE.search(low):
        return "oom"
    return None


def wrap_device_error(exc: BaseException, context: str) -> DeviceMemoryError:
    """Wrap a backend exception in the taxonomy subclass its text indicates
    (TransportError for UNAVAILABLE/dark-tunnel shapes, DeviceOOMError for
    allocation exhaustion, base DeviceMemoryError otherwise), preserving the
    failing site like the reference's checked helpers do
    (knearests.cu:205-231)."""
    kind = classify_fault_text(f"{type(exc).__name__}: {exc}")
    cls = {"transport": TransportError,
           "oom": DeviceOOMError}.get(kind, DeviceMemoryError)
    return cls(f"{context}: {type(exc).__name__}: {exc}")


def to_device(x: np.ndarray, dtype: Any = jnp.float32,
              sharding: Optional[jax.sharding.Sharding] = None,
              validate: bool = True) -> jax.Array:
    """Validated host->HBM staging (analog of gpuMallocNCopy, knearests.cu:219-226).

    ``validate=False`` skips the finite scan for callers whose input already
    went through io.validate_points (e.g. gridhash.build_grid) -- the checked
    device placement and error reporting still apply."""
    arr = np.asarray(x)
    if validate and not np.isfinite(arr).all():
        # typed refusal: NonFiniteInputError is BOTH taxonomies (input
        # contract + device memory), so legacy DeviceMemoryError catches
        # keep working while the supervisor records kind 'invalid-input'
        raise NonFiniteInputError(
            "refusing to stage non-finite data to device (input contract: "
            "coordinates must be finite; clean the input first)")
    arr = np.ascontiguousarray(arr, dtype=np.dtype(dtype))
    try:
        return jax.device_put(arr, sharding)
    except Exception as e:  # surface the failing site like the reference does
        # classified wrap: a dead-tunnel UNAVAILABLE raises TransportError
        # (retryable by the supervisor), everything else the base class
        raise wrap_device_error(
            e, f"device_put failed for shape={arr.shape} "
               f"dtype={arr.dtype}") from e


def from_device(x: jax.Array) -> np.ndarray:
    """D2H readback (analog of the kn_get_* D2H copies, knearests.cu:406-437)."""
    return np.asarray(jax.device_get(x))


def nbytes(tree: Any) -> int:
    """Total bytes of all arrays in a pytree.

    The correct version of the reference's "GPU memory used" stat
    (knearests.cu:342), whose ``bufsize +=`` bug (knearests.cu:329,333) this
    framework fixes rather than reproduces (SURVEY.md section 2.2).
    """
    leaves = jax.tree.leaves(tree)
    return int(sum(getattr(l, "nbytes", 0) for l in leaves))


def device_nbytes(tree: Any) -> int:
    """Bytes of the DEVICE-resident arrays in a pytree only.

    Plans deliberately carry host-resident numpy leaves (e.g. the adaptive
    plan's query-bucketing maps, hoisted off-device by the one-sync solve,
    DESIGN.md section 12) -- a device-footprint stat must not count them."""
    leaves = jax.tree.leaves(tree)
    return int(sum(l.nbytes for l in leaves if isinstance(l, jax.Array)))
