"""Checked host<->device staging helpers.

Reference parity (C5/C10): the reference wraps every cudaMalloc/Memcpy/Memset in
checked helpers that report the failing site and abort
(/root/reference/knearests.cu:205-231), and tracks total device memory used --
with a ``bufsize +=`` accounting bug that inflates the stat
(/root/reference/knearests.cu:329,333,342).  JAX owns allocation, so the useful
equivalents are: validated H2D staging (`to_device`), D2H extraction
(`from_device`), and *correct* buffer-size accounting for diagnostics.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class DeviceMemoryError(RuntimeError):
    """Raised when staging fails validation (analog of the reference's
    print-and-exit in gpuMalloc*, knearests.cu:205-231 -- but recoverable)."""


def to_device(x: np.ndarray, dtype: Any = jnp.float32,
              sharding: Optional[jax.sharding.Sharding] = None,
              validate: bool = True) -> jax.Array:
    """Validated host->HBM staging (analog of gpuMallocNCopy, knearests.cu:219-226).

    ``validate=False`` skips the finite scan for callers whose input already
    went through io.validate_points (e.g. gridhash.build_grid) -- the checked
    device placement and error reporting still apply."""
    arr = np.asarray(x)
    if validate and not np.isfinite(arr).all():
        raise DeviceMemoryError("refusing to stage non-finite data to device")
    arr = np.ascontiguousarray(arr, dtype=np.dtype(dtype))
    try:
        return jax.device_put(arr, sharding)
    except Exception as e:  # surface the failing site like the reference does
        raise DeviceMemoryError(f"device_put failed for shape={arr.shape} "
                                f"dtype={arr.dtype}: {e}") from e


def from_device(x: jax.Array) -> np.ndarray:
    """D2H readback (analog of the kn_get_* D2H copies, knearests.cu:406-437)."""
    return np.asarray(jax.device_get(x))


def nbytes(tree: Any) -> int:
    """Total bytes of all arrays in a pytree.

    The correct version of the reference's "GPU memory used" stat
    (knearests.cu:342), whose ``bufsize +=`` bug (knearests.cu:329,333) this
    framework fixes rather than reproduces (SURVEY.md section 2.2).
    """
    leaves = jax.tree.leaves(tree)
    return int(sum(getattr(l, "nbytes", 0) for l in leaves))
