"""Device-level tracing hooks.

Reference parity (SURVEY.md section 5 "Tracing / profiling"): the reference
exposes cudaEvent timers around build/solve and compiles with ``-lineinfo`` so
nvprof/nsight can map kernels to source.  The TPU equivalents are (a) the
Stopwatch/timed wall timers (utils/stopwatch.py -- the cudaEvent analog) and
(b) this module: ``jax.profiler`` trace capture producing a Perfetto/
TensorBoard-readable trace of XLA ops, Pallas kernels, and transfers -- the
nsight analog.

Usage:
    from cuda_knearests_tpu.utils.profiling import trace
    with trace("/tmp/knn_trace"):
        problem.solve()
    # then: tensorboard --logdir /tmp/knn_trace  (or load in Perfetto)
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a device trace for the enclosed block (blocks on exit so the
    trailing async work lands inside the trace)."""
    options = None
    try:  # tracer options moved modules across jax versions; both optional
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
    except Exception:  # noqa: BLE001 -- ProfileOptions is version-dependent
        # sugar: on any shape of absence/rejection the trace below still
        # captures, just without the host tracer level tweak
        pass
    if options is not None:
        ctx = jax.profiler.trace(log_dir, profiler_options=options)
    else:
        ctx = jax.profiler.trace(log_dir)
    with ctx:
        yield
        (jax.device_put(0.0) + 0).block_until_ready()


def annotate(name: str):
    """Named region that shows up in profiler traces (and is free outside
    them): ``with annotate("halo-exchange"): ...``"""
    return jax.profiler.TraceAnnotation(name)
