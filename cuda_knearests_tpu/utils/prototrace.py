"""Runtime protocol-action trace recorder.

The declared protocol models (analysis/models.py) are only worth
anything if the REAL code walks the transitions they declare.  Protocol
methods in serve/fleet/{replica,elastic,frontdoor}.py and
pod/reshard.py call :func:`record` at each ``# proto:``-annotated site;
the chaos campaign and the failover drills :func:`enable` the recorder
around a run and reconcile the drained trace with
``models.conform(...)`` -- the runtime twin of syncflow's
``trace_sites`` reconciliation.

Off by default and O(1) when off (one attribute load + truth test), so
the hot serve path pays nothing in production.  The buffer is bounded:
chaos cases are budgeted, but a runaway loop must not turn the recorder
into a leak.  Thread-safe -- the fleet daemon pumps from worker
threads.

Lives in ``utils`` (not ``analysis``) because the recording sites are
inside serve/fleet and pod, which must not import the analysis package
(analysis imports nothing from the runtime, and the runtime must stay
importable without it).
"""

from __future__ import annotations

import threading
from typing import List, Tuple

_MAX_EVENTS = 100_000

_lock = threading.Lock()
_events: List[Tuple[str, str]] = []
_dropped = 0
enabled = False


def enable() -> None:
    """Start recording (clears any previous trace)."""
    global enabled, _dropped
    with _lock:
        _events.clear()
        _dropped = 0
        enabled = True


def disable() -> None:
    global enabled
    with _lock:
        enabled = False


def record(model: str, action: str) -> None:
    """Append one (model, action) event; no-op unless enabled."""
    global _dropped
    if not enabled:
        return
    with _lock:
        if not enabled:
            return
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append((model, action))


def drain() -> List[Tuple[str, str]]:
    """Return and clear the recorded trace (oldest first)."""
    global _dropped
    with _lock:
        out = list(_events)
        _events.clear()
        _dropped = 0
        return out


def dropped() -> int:
    """Events discarded because the bounded buffer was full."""
    with _lock:
        return _dropped
