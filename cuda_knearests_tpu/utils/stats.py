"""Diagnostics: grid occupancy, convergence/certification, memory -- as JSON.

Reference parity (C6, /root/reference/knearests.cu:440-466 kn_print_stats and the
max-ring readback at :378-390): min/max/avg points-per-cell plus a full occupancy
histogram, and a convergence statistic.  Differences: the reference's "Max
visited ring" is computed with a data race and an off-by-one (SURVEY.md section
2.2); here the equivalent quantity is the *certified fraction* -- an exact
per-query completeness guarantee -- and everything is emitted as a
machine-readable dict (BASELINE.md wants machine-readable numbers).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .memory import device_nbytes


def occupancy_stats(cell_counts: np.ndarray) -> Dict[str, Any]:
    """Occupancy histogram over grid cells (reference: knearests.cu:440-466)."""
    counts = np.asarray(cell_counts)
    vals, freq = np.unique(counts, return_counts=True)
    return {
        "num_cells": int(counts.size),
        "num_points": int(counts.sum()),
        "min_per_cell": int(counts.min()) if counts.size else 0,
        "max_per_cell": int(counts.max()) if counts.size else 0,
        "avg_per_cell": float(counts.mean()) if counts.size else 0.0,
        "histogram": {int(v): int(f) for v, f in zip(vals, freq)},
    }


def _margin_sq_np(q: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                  domain: float) -> np.ndarray:
    """Squared margin from each query row to the complement of its dilated
    box (numpy twin of ops.solve._margin_sq, per-row shapes (n, 3))."""
    with np.errstate(invalid="ignore"):
        m_lo = np.where(lo <= 0.0, np.inf, q - lo)
        m_hi = np.where(hi >= domain, np.inf, hi - q)
        m = np.maximum(np.minimum(m_lo, m_hi).min(axis=-1), 0.0)
    return np.where(np.isinf(m), np.inf, m * m)


def margin_summary(kth_sq: np.ndarray, margin_sq: np.ndarray
                   ) -> Dict[str, Any]:
    """Per-query achieved-margin telemetry: ratio = kth_dist / margin.

    The fixed analog of the reference's "Max visited ring" convergence stat
    (/root/reference/knearests.cu:378-390 -- racy and diagnostic-only there):
    ratio r in [0, 1) means the query's k-th neighbor used fraction r of its
    certificate margin; r close to 1 means the planner's radius choice
    (ops/adaptive.py) barely held.  r >= 1 ("decertified") means the EXACT
    k-th distance exceeds the margin, i.e. the grid route could never have
    certified this query.  Note this is computed from final (post-fallback)
    distances, so transient in-kernel decertifications that the fallback
    found to be fine (e.g. blocked-kernel deficits) do not count -- it
    measures the planner's geometry, not the runtime fallback rate (that is
    ``certified_fraction``/``uncertified`` in problem_stats).  An infinite
    margin (box unconstrained on every axis by the domain boundary) can
    never decertify -> ratio 0.
    """
    # Intentional host-side f64: the ratio sqrt(kth/msq) compares two f32
    # squared distances whose quotient approaches 1.0 exactly where the
    # diagnostic matters most (near-decertification); computing it in f32
    # can flip ratio >= 1.0 across the decertified boundary for margins
    # within ~1 ulp of the kth distance.  Host-only telemetry, never staged
    # to a device (pinned by tests/test_analysis.py::test_margin_summary_f64).
    kth = np.asarray(kth_sq, np.float64)    # kntpu-ok: wide-dtype -- f64 certificate telemetry (see above)
    msq = np.asarray(margin_sq, np.float64)  # kntpu-ok: wide-dtype -- f64 certificate telemetry (see above)
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.sqrt(kth / msq)
    ratio = np.where(np.isinf(msq), 0.0, ratio)     # unconstrained: safe
    ratio = np.where(np.isnan(ratio), 1.0, ratio)   # 0/0: exactly at bound
    n = ratio.size
    if n == 0:
        return {"n": 0}
    edges = np.linspace(0.0, 1.0, 11)
    hist = np.histogram(ratio[ratio < 1.0], bins=edges)[0]
    over = int((ratio >= 1.0).sum())
    return {
        "n": int(n),
        "mean": float(np.mean(np.minimum(ratio, 1.0))),
        "p50": float(np.percentile(ratio, 50)),
        "p90": float(np.percentile(ratio, 90)),
        "p99": float(np.percentile(ratio, 99)),
        "max": float(ratio.max()),
        "histogram": {f"{edges[i]:.1f}-{edges[i + 1]:.1f}": int(hist[i])
                      for i in range(10)},
        "decertified": over,
    }


def problem_margins(problem) -> Dict[str, Any] | None:
    """Achieved-margin summary for a solved api.KnnProblem, or None when the
    planner shape keeps no per-query boxes (legacy XLA plan without a pack).
    Boxes come from the same schedule the certificate used: adaptive classes
    (inv_box) or the legacy PallasPack (inv_sc)."""
    if problem.result is None:
        return None
    import jax

    grid = problem.grid
    kth = np.asarray(jax.device_get(problem.result.dists_sq))[:, -1]
    aplan = getattr(problem, "aplan", None)
    pack = getattr(problem, "pack", None)
    if aplan is not None:
        lo = np.concatenate([np.asarray(jax.device_get(cp.lo))
                             for cp in aplan.classes], axis=0)
        hi = np.concatenate([np.asarray(jax.device_get(cp.hi))
                             for cp in aplan.classes], axis=0)
        inv = np.asarray(jax.device_get(aplan.inv_box))
    elif pack is not None:
        lo = np.asarray(jax.device_get(pack.lo))
        hi = np.asarray(jax.device_get(pack.hi))
        inv = np.asarray(jax.device_get(pack.inv_sc))
    else:
        return None
    q = np.asarray(jax.device_get(grid.points))
    msq = _margin_sq_np(q, lo[inv], hi[inv], grid.domain)
    return margin_summary(kth, msq)


def problem_stats(problem) -> Dict[str, Any]:
    """Full stats for an api.KnnProblem (post-solve fields optional).

    Both planner shapes are reported under ``plan``: the legacy global
    schedule as a single (qcap, ccap), and the adaptive schedule as the
    per-class capacity table plus the (max-over-classes) aggregate caps --
    so capacity diagnostics (the reference's convergence half of
    kn_print_stats, knearests.cu:440-466) survive the default config.
    """
    grid = problem.grid
    aplan = getattr(problem, "aplan", None)
    out: Dict[str, Any] = {
        "n_points": grid.n_points,
        "grid_dim": grid.dim,
        "k": problem.config.k,
        "ring_radius": problem.config.resolved_ring_radius(),
        "supercell": problem.config.supercell,
        "occupancy": occupancy_stats(np.asarray(grid.cell_counts)),
        # device-resident leaves only: the adaptive plan's query-bucketing
        # maps are deliberately host numpy (one-sync hoist, DESIGN.md s12)
        "device_bytes": device_nbytes((grid, problem.plan, aplan,
                                       getattr(problem, "pack", None))),
    }
    # aplan wins the report when both schedules exist: solve() routes adaptive
    # whenever an aplan is present, the legacy plan then only serves query()
    if aplan is not None:
        classes = [{"radius": cp.radius, "n_supercells": cp.n_sc,
                    "qcap": cp.qcap, "ccap": cp.ccap,
                    "route": cp.route,
                    "use_pallas": bool(cp.use_pallas)}
                   for cp in aplan.classes]
        out["plan"] = {"adaptive": True, "n_classes": len(classes),
                       "qcap": max(c["qcap"] for c in classes),
                       "ccap": max(c["ccap"] for c in classes),
                       "classes": classes}
    elif problem.plan is not None:
        out["plan"] = {"qcap": problem.plan.qcap, "ccap": problem.plan.ccap,
                       "n_supercell_chunks": problem.plan.n_chunks,
                       "chunk_batch": problem.plan.batch}
    if problem.result is not None:
        cert = np.asarray(problem.result.certified)
        out["certified_fraction"] = float(cert.mean()) if cert.size else 1.0
        out["uncertified"] = int((~cert).sum())
        margins = problem_margins(problem)
        if margins is not None:
            out["margin"] = margins
    return out


def print_stats(problem) -> Dict[str, Any]:
    """Human-readable dump (reference: kn_print_stats, knearests.cu:440-466)."""
    s = problem_stats(problem)
    occ = s["occupancy"]
    print(f"grid {s['grid_dim']}^3, {s['n_points']} points, k={s['k']}, "
          f"ring_radius={s['ring_radius']}, supercell={s['supercell']}^3")
    print(f"points per cell: min {occ['min_per_cell']} / "
          f"avg {occ['avg_per_cell']:.2f} / max {occ['max_per_cell']}")
    hist = occ["histogram"]
    for v in sorted(hist):
        print(f"  cells with {v:3d} points: {hist[v]}")
    plan = s.get("plan")
    if plan is not None and plan.get("adaptive"):
        print(f"adaptive schedule: {plan['n_classes']} capacity classes "
              f"(max qcap {plan['qcap']}, max ccap {plan['ccap']})")
        for c in plan["classes"]:
            print(f"  class r={c['radius']}: {c['n_supercells']} supercells, "
                  f"qcap {c['qcap']}, ccap {c['ccap']} [{c['route']}]")
    elif plan is not None:
        print(f"schedule: qcap {plan['qcap']}, ccap {plan['ccap']}, "
              f"{plan['n_supercell_chunks']} chunks x {plan['chunk_batch']}")
    if "certified_fraction" in s:
        print(f"certified: {100.0 * s['certified_fraction']:.4f}% "
              f"({s['uncertified']} fallback queries)")
    if "margin" in s and s["margin"].get("n"):
        m = s["margin"]
        print(f"achieved margin ratio (kth_dist/margin; 1.0 = decertify): "
              f"p50 {m['p50']:.3f}, p90 {m['p90']:.3f}, p99 {m['p99']:.3f}, "
              f"max {m['max']:.3f}; {m['decertified']} decertified")
    print(f"device memory: {s['device_bytes'] / 1e6:.1f} MB")
    return s
