"""Diagnostics: grid occupancy, convergence/certification, memory -- as JSON.

Reference parity (C6, /root/reference/knearests.cu:440-466 kn_print_stats and the
max-ring readback at :378-390): min/max/avg points-per-cell plus a full occupancy
histogram, and a convergence statistic.  Differences: the reference's "Max
visited ring" is computed with a data race and an off-by-one (SURVEY.md section
2.2); here the equivalent quantity is the *certified fraction* -- an exact
per-query completeness guarantee -- and everything is emitted as a
machine-readable dict (BASELINE.md wants machine-readable numbers).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .memory import nbytes


def occupancy_stats(cell_counts: np.ndarray) -> Dict[str, Any]:
    """Occupancy histogram over grid cells (reference: knearests.cu:440-466)."""
    counts = np.asarray(cell_counts)
    vals, freq = np.unique(counts, return_counts=True)
    return {
        "num_cells": int(counts.size),
        "num_points": int(counts.sum()),
        "min_per_cell": int(counts.min()) if counts.size else 0,
        "max_per_cell": int(counts.max()) if counts.size else 0,
        "avg_per_cell": float(counts.mean()) if counts.size else 0.0,
        "histogram": {int(v): int(f) for v, f in zip(vals, freq)},
    }


def problem_stats(problem) -> Dict[str, Any]:
    """Full stats for an api.KnnProblem (post-solve fields optional).

    Both planner shapes are reported under ``plan``: the legacy global
    schedule as a single (qcap, ccap), and the adaptive schedule as the
    per-class capacity table plus the (max-over-classes) aggregate caps --
    so capacity diagnostics (the reference's convergence half of
    kn_print_stats, knearests.cu:440-466) survive the default config.
    """
    grid = problem.grid
    aplan = getattr(problem, "aplan", None)
    out: Dict[str, Any] = {
        "n_points": grid.n_points,
        "grid_dim": grid.dim,
        "k": problem.config.k,
        "ring_radius": problem.config.resolved_ring_radius(),
        "supercell": problem.config.supercell,
        "occupancy": occupancy_stats(np.asarray(grid.cell_counts)),
        "device_bytes": nbytes((grid, problem.plan, aplan,
                                getattr(problem, "pack", None))),
    }
    # aplan wins the report when both schedules exist: solve() routes adaptive
    # whenever an aplan is present, the legacy plan then only serves query()
    if aplan is not None:
        classes = [{"radius": cp.radius, "n_supercells": cp.n_sc,
                    "qcap": cp.qcap, "ccap": cp.ccap,
                    "route": cp.route,
                    "use_pallas": bool(cp.use_pallas)}
                   for cp in aplan.classes]
        out["plan"] = {"adaptive": True, "n_classes": len(classes),
                       "qcap": max(c["qcap"] for c in classes),
                       "ccap": max(c["ccap"] for c in classes),
                       "classes": classes}
    elif problem.plan is not None:
        out["plan"] = {"qcap": problem.plan.qcap, "ccap": problem.plan.ccap,
                       "n_supercell_chunks": problem.plan.n_chunks,
                       "chunk_batch": problem.plan.batch}
    if problem.result is not None:
        cert = np.asarray(problem.result.certified)
        out["certified_fraction"] = float(cert.mean()) if cert.size else 1.0
        out["uncertified"] = int((~cert).sum())
    return out


def print_stats(problem) -> Dict[str, Any]:
    """Human-readable dump (reference: kn_print_stats, knearests.cu:440-466)."""
    s = problem_stats(problem)
    occ = s["occupancy"]
    print(f"grid {s['grid_dim']}^3, {s['n_points']} points, k={s['k']}, "
          f"ring_radius={s['ring_radius']}, supercell={s['supercell']}^3")
    print(f"points per cell: min {occ['min_per_cell']} / "
          f"avg {occ['avg_per_cell']:.2f} / max {occ['max_per_cell']}")
    hist = occ["histogram"]
    for v in sorted(hist):
        print(f"  cells with {v:3d} points: {hist[v]}")
    plan = s.get("plan")
    if plan is not None and plan.get("adaptive"):
        print(f"adaptive schedule: {plan['n_classes']} capacity classes "
              f"(max qcap {plan['qcap']}, max ccap {plan['ccap']})")
        for c in plan["classes"]:
            print(f"  class r={c['radius']}: {c['n_supercells']} supercells, "
                  f"qcap {c['qcap']}, ccap {c['ccap']} [{c['route']}]")
    elif plan is not None:
        print(f"schedule: qcap {plan['qcap']}, ccap {plan['ccap']}, "
              f"{plan['n_supercell_chunks']} chunks x {plan['chunk_batch']}")
    if "certified_fraction" in s:
        print(f"certified: {100.0 * s['certified_fraction']:.4f}% "
              f"({s['uncertified']} fallback queries)")
    print(f"device memory: {s['device_bytes'] / 1e6:.1f} MB")
    return s
