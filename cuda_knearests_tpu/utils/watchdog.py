"""In-process stall watchdog for unattended accelerated captures.

The tunneled TPU transport's observed failure mode is a *hang*: a backend
RPC (compile or execute) that never returns once the tunnel dies.  The
reference can check-and-exit per CUDA call (its errors are synchronous,
/root/reference/knearests.cu error handling); here the only reliable signal
is the absence of progress.  The outer watcher (scripts/tpu_watch.py) kills
a hung child at its step timeout, but that blinds the probe loop for the
whole timeout and -- worse -- wastes the rest of a healthy window that
returned while the child was pinned to its dead connection.  This watchdog
lets the child detect the stall itself: benches call ``heartbeat()`` after
every completed unit of device work, and a daemon thread exits the process
(rc 3, after printing a machine-readable error line) when no heartbeat
arrives for ``BENCH_STALL_TIMEOUT_S`` seconds (default 300; 0 disables).

Callers ``disable()`` it on CPU hosts: local CPU work cannot hang on the
transport, and a legitimately slow row (e.g. the emulated sharded 10M
config) would trip a 300 s limit.

GIL caveat: the thread only runs if the hung extension call released the
GIL.  jax's blocking waits (compile RPCs, ``block_until_ready``) do, so the
observed hangs are coverable; a hypothetical GIL-holding hang degrades to
the outer watcher's timeout kill -- never worse than without the watchdog.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

_ENV = "BENCH_STALL_TIMEOUT_S"
_lock = threading.Lock()
_state = {"t": 0.0, "enabled": False, "stall_s": 300.0, "tag": ""}
_started = False


def heartbeat() -> None:
    """Record forward progress.  Cheap; safe to call from any thread, and a
    no-op if the watchdog was never started."""
    with _lock:
        _state["t"] = time.monotonic()


def disable() -> None:
    """Stop stall enforcement (the thread stays parked).  Used when the
    acquired platform turns out to be CPU."""
    with _lock:
        _state["enabled"] = False


def start(tag: str = "", default_s: float = 300.0) -> None:
    """Arm the watchdog (idempotent).  ``tag`` names the tool for the error
    line.  BENCH_STALL_TIMEOUT_S overrides the limit; <= 0 disables."""
    global _started
    raw = os.environ.get(_ENV)
    stall_s = default_s
    if raw is not None:
        try:
            stall_s = float(raw)
        except ValueError:
            print(f"ignoring malformed {_ENV}={raw!r}; using {default_s}",
                  file=sys.stderr, flush=True)
    if stall_s <= 0:
        return
    with _lock:
        _state.update(t=time.monotonic(), enabled=True, stall_s=stall_s,
                      tag=tag)
    if _started:
        return
    _started = True
    threading.Thread(target=_watch, daemon=True,
                     name="bench-stall-watchdog").start()


def _watch() -> None:
    while True:
        with _lock:
            stall_s = _state["stall_s"]
        time.sleep(max(1.0, min(15.0, stall_s / 4.0)))
        with _lock:
            if not _state["enabled"]:
                continue
            dt = time.monotonic() - _state["t"]
            tag = _state["tag"]
        if dt > stall_s:
            # one machine-readable line so the rc-stamped artifact records
            # WHY the run died (the watcher's _artifact_good rejects
            # error-bearing lines, so the step is retried, not enshrined)
            print(json.dumps({
                "error": f"stall watchdog ({tag}): no progress for "
                         f"{dt:.0f}s (limit {stall_s:.0f}s); presumed hung "
                         f"on a dead accelerator transport"}), flush=True)
            sys.stderr.flush()
            os._exit(3)
