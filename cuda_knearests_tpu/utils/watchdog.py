"""In-process stall watchdog for unattended accelerated captures.

The tunneled TPU transport's observed failure mode is a *hang*: a backend
RPC (compile or execute) that never returns once the tunnel dies.  The
reference can check-and-exit per CUDA call (its errors are synchronous,
/root/reference/knearests.cu error handling); here the only reliable signal
is the absence of progress.  The outer watcher (scripts/tpu_watch.py) kills
a hung child at its step timeout, but that blinds the probe loop for the
whole timeout and -- worse -- wastes the rest of a healthy window that
returned while the child was pinned to its dead connection.  This watchdog
lets the child detect the stall itself: benches call ``heartbeat()`` after
every completed unit of device work, and a daemon thread exits the process
(rc 3, after printing a machine-readable error line) when no heartbeat
arrives for ``BENCH_STALL_TIMEOUT_S`` seconds (default 300; 0 disables).

Callers ``disable()`` it on CPU hosts: local CPU work cannot hang on the
transport, and a legitimately slow row (e.g. the emulated sharded 10M
config) would trip a 300 s limit.

GIL caveat: the thread only runs if the hung extension call released the
GIL.  jax's blocking waits (compile RPCs, ``block_until_ready``) do, so the
observed hangs are coverable; a hypothetical GIL-holding hang degrades to
the outer watcher's timeout kill -- never worse than without the watchdog.
"""
from __future__ import annotations

import faulthandler
import json
import os
import sys
import tempfile
import threading
import time

_ENV = "BENCH_STALL_TIMEOUT_S"
_FAILURE_DIR_ENV = "KNTPU_FAILURE_DIR"


def _dump_tracebacks(tag: str) -> str | None:
    """Dump all-thread tracebacks (faulthandler) into a failure artifact and
    to stderr, returning the artifact path (None if the write failed).  A
    stall trip without this leaves only a timeout on record; the tracebacks
    show WHERE the process was pinned (which backend RPC, which phase) --
    the evidence a hang postmortem actually needs.  stderr gets a copy too
    so supervised children surface it in their captured stderr tail even
    when the artifact directory is unwritable."""
    path = None
    try:
        d = os.environ.get(_FAILURE_DIR_ENV) or tempfile.gettempdir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"stall_{tag or 'bench'}_{os.getpid()}.tb")
        with open(path, "w") as f:
            f.write(f"stall watchdog trip ({tag}): all-thread tracebacks\n")
            f.flush()
            faulthandler.dump_traceback(file=f, all_threads=True)
            # flight-recorder tail (obs/recorder): the process's recent
            # spans + metric deltas land NEXT TO the tracebacks, so a
            # hang postmortem sees what the process was doing, not just
            # where it was pinned (DESIGN.md section 19)
            try:
                from ..obs.recorder import FLIGHT

                FLIGHT.metric_delta()
                f.write("\n=== flight recorder tail ===\n")
                f.write(json.dumps(FLIGHT.dump()) + "\n")
            except Exception:  # noqa: BLE001 -- the exit path must never raise; tracebacks alone still land
                pass
    except Exception:  # noqa: BLE001 -- the exit path must never raise
        path = None
    try:
        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
    except Exception:  # noqa: BLE001 -- the exit path must never raise
        pass
    return path
_lock = threading.Lock()
_state = {"t": 0.0, "enabled": False, "stall_s": 300.0, "tag": ""}
_started = False


def heartbeat() -> None:
    """Record forward progress.  Cheap; safe to call from any thread, and a
    no-op if the watchdog was never started."""
    with _lock:
        _state["t"] = time.monotonic()


def disable() -> None:
    """Stop stall enforcement (the thread stays parked).  Used when the
    acquired platform turns out to be CPU."""
    with _lock:
        _state["enabled"] = False


def start(tag: str = "", default_s: float = 300.0) -> None:
    """Arm the watchdog (idempotent).  ``tag`` names the tool for the error
    line.  BENCH_STALL_TIMEOUT_S overrides the limit; <= 0 disables."""
    global _started
    raw = os.environ.get(_ENV)
    stall_s = default_s
    if raw is not None:
        try:
            stall_s = float(raw)
        except ValueError:
            print(f"ignoring malformed {_ENV}={raw!r}; using {default_s}",
                  file=sys.stderr, flush=True)
    if stall_s <= 0:
        return
    with _lock:
        _state.update(t=time.monotonic(), enabled=True, stall_s=stall_s,
                      tag=tag)
    if _started:
        return
    _started = True
    threading.Thread(target=_watch, daemon=True,
                     name="bench-stall-watchdog").start()


def _watch() -> None:
    while True:
        with _lock:
            stall_s = _state["stall_s"]
        time.sleep(max(1.0, min(15.0, stall_s / 4.0)))
        with _lock:
            if not _state["enabled"]:
                continue
            dt = time.monotonic() - _state["t"]
            tag = _state["tag"]
        if dt > stall_s:
            # count the trip in the metrics registry (obs/metrics) before
            # dumping -- snapshot consumers see watchdog.stalls move
            try:
                from ..obs.metrics import watchdog_stall_tripped

                watchdog_stall_tripped(tag)
            except Exception:  # noqa: BLE001 -- the exit path must never raise
                pass
            # evidence first: all-thread tracebacks into the failure
            # artifact (and stderr), so a hang leaves more than a timeout
            tb_path = _dump_tracebacks(tag)
            # one machine-readable line so the rc-stamped artifact records
            # WHY the run died (the watcher's _artifact_good rejects
            # error-bearing lines, so the step is retried, not enshrined)
            line = {
                "error": f"stall watchdog ({tag}): no progress for "
                         f"{dt:.0f}s (limit {stall_s:.0f}s); presumed hung "
                         f"on a dead accelerator transport",
                "failure_kind": "timeout"}
            if tb_path:
                line["traceback_file"] = tb_path
            print(json.dumps(line), flush=True)
            sys.stderr.flush()
            os._exit(3)
