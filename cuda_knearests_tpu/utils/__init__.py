from . import devinfo, memory, stats, stopwatch
from .stopwatch import Stopwatch, timed

__all__ = ["devinfo", "memory", "stats", "stopwatch", "Stopwatch", "timed"]
