"""Device discovery and property dump.

Reference parity (C12, /root/reference/test_knearests.cu:83-115 printDevProp):
prints every accelerator visible to JAX with the properties that matter for this
workload (platform, memory, core counts where exposed), plus process/topology info
the multi-chip path cares about.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax


def device_properties() -> List[Dict[str, Any]]:
    props = []
    for d in jax.devices():
        entry: Dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "device_kind": d.device_kind,
            "process_index": d.process_index,
        }
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 -- not all backends expose memory
            # stats (and some raise rather than return None); the dump just
            # omits the memory fields, it must never fail a diagnostics call
            pass
        if "bytes_limit" in stats:
            entry["memory_limit_bytes"] = stats["bytes_limit"]
        if "bytes_in_use" in stats:
            entry["memory_in_use_bytes"] = stats["bytes_in_use"]
        core = getattr(d, "core_on_chip", None)
        if core is not None:
            entry["core_on_chip"] = core
        coords = getattr(d, "coords", None)
        if coords is not None:
            entry["coords"] = tuple(coords)
        props.append(entry)
    return props


def print_device_properties() -> None:
    """Human-readable dump (reference: printDevProp, test_knearests.cu:83-115)."""
    devs = device_properties()
    print(f"There are {len(devs)} JAX devices "
          f"(backend={jax.default_backend()}, processes={jax.process_count()})")
    for p in devs:
        print(f"  device {p['id']}: {p['device_kind']} [{p['platform']}]")
        for key in ("memory_limit_bytes", "memory_in_use_bytes", "coords", "core_on_chip"):
            if key in p:
                print(f"    {key}: {p[key]}")
