"""Device discovery, property dump, and the per-device-kind peaks table.

Reference parity (C12, /root/reference/test_knearests.cu:83-115 printDevProp):
prints every accelerator visible to JAX with the properties that matter for this
workload (platform, memory, core counts where exposed), plus process/topology info
the multi-chip path cares about.

:data:`DEVICE_PEAKS` is the one source of roofline peak constants
(utils/roofline.py used to hand-enter the v5e HBM number inline): public
per-device-kind HBM bandwidth and MXU peak FLOP/s, matched by device-kind
substring with a typed CPU fallback entry.  Every entry carries a
``basis`` string naming where the number comes from -- a bench row's
pct-of-peak claim is only as good as its peak's provenance.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax

#: Public peak table, keyed by a canonical entry name; ``match`` holds
#: device_kind substrings (lowercased) that select the entry.  HBM GB/s
#: and bf16 MXU TFLOP/s from the public chip specs
#: (jax-ml.github.io/scaling-book); the CPU entry is a NOMINAL host
#: memory figure (4-channel DDR4-3200) so fallback rows still render an
#: order-of-magnitude roofline -- its basis string says exactly that.
DEVICE_PEAKS: Dict[str, Dict[str, Any]] = {
    "tpu-v5e": {"match": ("v5e", "v5 lite", "v5lite"),
                "hbm_gbps": 819.0, "peak_tflops": 197.0,
                "flops_precision": "bf16",
                "basis": "public TPU v5e spec"},
    "tpu-v5p": {"match": ("v5p",),
                "hbm_gbps": 2765.0, "peak_tflops": 459.0,
                "flops_precision": "bf16",
                "basis": "public TPU v5p spec"},
    "tpu-v4": {"match": ("v4",),
               "hbm_gbps": 1228.0, "peak_tflops": 275.0,
               "flops_precision": "bf16",
               "basis": "public TPU v4 spec"},
    "tpu-v3": {"match": ("v3",),
               "hbm_gbps": 900.0, "peak_tflops": 123.0,
               "flops_precision": "bf16",
               "basis": "public TPU v3 spec"},
    "tpu-v2": {"match": ("v2",),
               "hbm_gbps": 700.0, "peak_tflops": 46.0,
               "flops_precision": "bf16",
               "basis": "public TPU v2 spec"},
    "cpu": {"match": ("cpu", "host"),
            "hbm_gbps": 51.2, "peak_tflops": None,
            "flops_precision": None,
            "basis": "nominal 4-channel DDR4-3200 host (CPU fallback: "
                     "order-of-magnitude, not a measured claim)"},
}

#: Platform fallback when the device kind matches no entry: an unnamed
#: TPU is assumed v5e (the fleet this repo targets -- stamped
#: ``assumed`` so the provenance is visible), an unnamed CPU-ish host
#: takes the nominal CPU entry.
_PLATFORM_DEFAULT = {"tpu": "tpu-v5e", "cpu": "cpu"}


def device_peaks(device_kind: Optional[str] = None,
                 platform: Optional[str] = None) -> Optional[dict]:
    """The peaks entry for a device kind (substring match), falling back
    by platform; None when neither resolves.  The returned dict carries
    ``entry`` (the table key) and ``assumed=True`` on platform-default
    fallbacks."""
    kind = (device_kind or "").lower()
    if kind:
        for name, ent in DEVICE_PEAKS.items():
            if any(m in kind for m in ent["match"]):
                return {"entry": name,
                        **{k: v for k, v in ent.items() if k != "match"}}
    key = _PLATFORM_DEFAULT.get((platform or "").lower())
    if key is not None:
        ent = DEVICE_PEAKS[key]
        return {"entry": key, "assumed": True,
                **{k: v for k, v in ent.items() if k != "match"}}
    return None


def current_device_kind() -> Tuple[Optional[str], Optional[str]]:
    """(device_kind, platform) of the default device, or (None, None)
    when no backend is reachable -- NEVER initializes a backend that
    is not already safe to touch from the caller's context."""
    try:
        d = jax.devices()[0]
        return str(d.device_kind), str(d.platform)
    except Exception:  # noqa: BLE001 -- a dark transport must not fail a stamp
        return None, None


def device_properties() -> List[Dict[str, Any]]:
    props = []
    for d in jax.devices():
        entry: Dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "device_kind": d.device_kind,
            "process_index": d.process_index,
        }
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 -- not all backends expose memory
            # stats (and some raise rather than return None); the dump just
            # omits the memory fields, it must never fail a diagnostics call
            pass
        if "bytes_limit" in stats:
            entry["memory_limit_bytes"] = stats["bytes_limit"]
        if "bytes_in_use" in stats:
            entry["memory_in_use_bytes"] = stats["bytes_in_use"]
        core = getattr(d, "core_on_chip", None)
        if core is not None:
            entry["core_on_chip"] = core
        coords = getattr(d, "coords", None)
        if coords is not None:
            entry["coords"] = tuple(coords)
        props.append(entry)
    return props


def print_device_properties() -> None:
    """Human-readable dump (reference: printDevProp, test_knearests.cu:83-115)."""
    devs = device_properties()
    print(f"There are {len(devs)} JAX devices "
          f"(backend={jax.default_backend()}, processes={jax.process_count()})")
    for p in devs:
        print(f"  device {p['id']}: {p['device_kind']} [{p['platform']}]")
        for key in ("memory_limit_bytes", "memory_in_use_bytes", "coords", "core_on_chip"):
            if key in p:
                print(f"    {key}: {p[key]}")
