"""Static-shape traffic/FLOP accounting for the solve (VERDICT r4 weak #5).

Every plan the engine builds has fully static shapes, so the bytes a solve
must move and the distance FLOPs it must execute are computable host-side
without instrumenting the kernels.  The bench stamps each row with these
numbers divided by the measured steady-state solve seconds -- achieved GB/s
and GFLOP/s -- and, on TPU hosts, the fraction of the v5e HBM roofline.
That turns DESIGN.md section 2's "VMEM-bandwidth-bound" claim into a
falsifiable number per run.

Traffic model (per steady-state solve call; 4-byte f32/i32 elements):

- HBM: kernel/solver *inputs* are read once (per-axis coordinate lane blocks
  qx/qy/qz + qid and cx/cy/cz + cid -> 4*(qcap+ccap) elements per supercell)
  and *outputs* written once (k dists + k ids per padded query slot), plus
  the epilogue's gather of those outputs into the (n, k) result (read + write
  2*n*k elements each).  This is the unavoidable traffic; XLA may re-fetch,
  so achieved numbers are lower bounds on actual movement.
- VMEM (Pallas routes only): the package's own kernel cost model
  (config.py kernel docs).  Per query row, elements touched are
    kpass:   k * ccap              (k min-and-mask sweeps of the (Q,C) tile)
    blocked: ccap * m + k * g * m  (per-block top-m in registers + k-pass
                                    over the (Q, g*m) survivor pool)
  times 4 bytes, times qcap_pad * n_sc.  The round-5 kernel A/B measures
  whether wall-clock tracks this model (DESIGN.md section 2b).
- Dense/streamed (XLA) routes materialize the distance tile in
  XLA-managed memory: counted as one write + one read of qcap*ccap
  elements per supercell (XLA fuses the top-k extraction over tiles, so
  this is again a documented lower bound).
- FLOPs: 8 per (query, candidate) pair -- 3 subs, 3 muls, 2 adds
  (knearests.cu:125's accumulation, identical here).

Peaks come from the per-device-kind table in ``utils/devinfo.py``
(DEVICE_PEAKS): HBM bandwidth and MXU FLOP/s matched by the measured
device's kind, with a typed nominal CPU fallback entry -- every
pct-of-peak stamp names its peak's provenance (``roofline_peak_source``).
VMEM peak bandwidth is not publicly pinned; vmem numbers are reported as
achieved GB/s only, with no pct-of-peak claim.
"""

from __future__ import annotations

from typing import Optional

from .devinfo import DEVICE_PEAKS, current_device_kind, device_peaks

#: Back-compat alias: the old hand-entered constant, now sourced from
#: the devinfo table (tests and older callers import it from here).
V5E_HBM_GBPS = DEVICE_PEAKS["tpu-v5e"]["hbm_gbps"]

_BYTES = 4  # f32 coords/dists, i32 ids
_FLOPS_PER_PAIR = 8


def _class_counts(n_sc: int, qcap: int, ccap: int, route: str, k: int,
                  kernel: str) -> dict:
    """Static counts for one class-shaped launch (works for the legacy
    single-plan pallas path too: that is one class with route='pallas')."""
    from ..config import blocked_topm, resolve_kernel

    pairs = n_sc * qcap * ccap
    hbm = {
        # inputs: 3 coord axes + 1 id lane block for each side, read once
        "hbm_read": n_sc * 4 * (qcap + ccap) * _BYTES,
        # outputs: k dists + k ids per padded query slot, written once
        "hbm_write": n_sc * qcap * k * 2 * _BYTES,
        "pairs": pairs,
        "flops": pairs * _FLOPS_PER_PAIR,
        "vmem": 0,
    }
    if route == "pallas":
        kern = resolve_kernel(kernel, k, ccap)
        if kern == "blocked":
            m = blocked_topm(k, ccap)
            g = ccap // 128
            per_query = ccap * m + k * g * m
        else:
            per_query = k * ccap
        hbm["vmem"] = n_sc * qcap * per_query * _BYTES
    else:
        # XLA tile materialization: one write + one read of the distance tile
        hbm["hbm_read"] += pairs * _BYTES
        hbm["hbm_write"] += pairs * _BYTES
    return hbm


def _accumulate(rows: list[dict], n_points: int, k: int) -> dict:
    tot = {key: sum(r[key] for r in rows)
           for key in ("hbm_read", "hbm_write", "pairs", "flops", "vmem")}
    # epilogue: gather the raw per-slot outputs into the (n, k) result
    # (read the gathered rows, write neighbors + dists)
    epi = 2 * n_points * k * 2 * _BYTES
    tot["hbm_read"] += epi // 2
    tot["hbm_write"] += epi // 2
    tot["hbm_total"] = tot["hbm_read"] + tot["hbm_write"]
    return tot


def adaptive_traffic(plan, k: int, kernel: str) -> dict:
    """Per-solve static counts for an AdaptivePlan (all classes)."""
    rows = [_class_counts(cp.n_sc, cp.qcap_pad, cp.ccap, cp.route, k, kernel)
            for cp in plan.classes]
    return _accumulate(rows, plan.n_points, k)


def pack_traffic(pack, k: int, kernel: str) -> dict:
    """Per-solve static counts for the legacy single-plan pallas path."""
    n_sc = pack.qx.shape[0]
    n_points = pack.inv_flat.shape[0]
    rows = [_class_counts(n_sc, pack.qx.shape[2], pack.cx.shape[2],
                          "pallas", k, kernel)]
    return _accumulate(rows, n_points, k)


def xla_plan_traffic(plan, n_points: int, k: int) -> dict:
    """Per-solve static counts for the pure-XLA supercell scan."""
    rows = [_class_counts(plan.n_chunks * plan.batch, plan.qcap, plan.ccap,
                          "xla", k, "kpass")]
    return _accumulate(rows, n_points, k)


def problem_traffic(problem) -> Optional[dict]:
    """Static traffic counts for a prepared single-chip KnnProblem, or None
    when the engine has no device plan to account (oracle backend)."""
    cfg = problem.config
    if cfg.backend == "oracle":
        return None
    k, kernel = cfg.k, cfg.effective_kernel()
    if getattr(problem, "aplan", None) is not None:
        return adaptive_traffic(problem.aplan, k, kernel)
    if getattr(problem, "pack", None) is not None:
        return pack_traffic(problem.pack, k, kernel)
    if getattr(problem, "plan", None) is not None:
        return xla_plan_traffic(problem.plan, problem.grid.n_points, k)
    return None


def sharded_traffic(sp) -> Optional[dict]:
    """Static traffic counts summed over a ShardedKnnProblem's chip plans.

    Each chip plan is an adaptive class schedule against the halo-extended
    point set; the per-chip counts simply sum (the halo exchange itself is
    a prepare-time cost, not part of the timed solve)."""
    cfg = sp.config
    k, kernel = cfg.k, cfg.effective_kernel()
    rows = [
        _class_counts(cp.n_sc, cp.qcap_pad, cp.ccap, cp.route, k, kernel)
        for plan in sp.chip_plans for cp in plan.classes]
    if not rows:
        return None
    return _accumulate(rows, sp.n_points, k)


def roofline_fields(traffic: Optional[dict], solve_s: float,
                    platform: str, n_devices: int = 1,
                    device_kind: Optional[str] = None) -> dict:
    """Bench-row fields from static counts + measured steady-state seconds.

    The peak side resolves from the devinfo DEVICE_PEAKS table by the
    measured device's kind (probed from the live backend when the probe's
    platform matches ``platform``; the explicit ``device_kind`` argument
    wins) with a typed CPU fallback -- every pct-of-peak claim stamps the
    peak it compared against and that peak's provenance.  ``n_devices``:
    chips the traffic was spread over concurrently -- a sharded solve's
    aggregate bytes/s compare against n_devices * the single-chip peak,
    not one chip's."""
    if not traffic or solve_s <= 0:
        return {}
    out = {
        "moved_hbm_gb": round(traffic["hbm_total"] / 1e9, 4),
        "achieved_hbm_gbps": round(traffic["hbm_total"] / solve_s / 1e9, 2),
        "dist_gflop": round(traffic["flops"] / 1e9, 3),
        "achieved_gflops": round(traffic["flops"] / solve_s / 1e9, 2),
        "traffic_model": "static-shape lower bound (utils/roofline.py)",
    }
    if traffic.get("vmem"):
        out["modeled_vmem_gb"] = round(traffic["vmem"] / 1e9, 4)
        out["achieved_vmem_gbps"] = round(
            traffic["vmem"] / solve_s / 1e9, 2)
    if device_kind is None:
        probed_kind, probed_platform = current_device_kind()
        # only adopt the live probe when it describes the platform this
        # measurement claims -- a CPU-host probe must not relabel a row
        # computed for a TPU artifact (and vice versa)
        if probed_kind is not None and probed_platform == platform:
            device_kind = probed_kind
    if device_kind:
        out["device_kind"] = device_kind
    peaks = device_peaks(device_kind, platform)
    if peaks and peaks.get("hbm_gbps"):
        out["pct_hbm_roofline"] = round(
            100.0 * out["achieved_hbm_gbps"]
            / (peaks["hbm_gbps"] * max(1, n_devices)), 2)
        out["roofline_peak_gbps"] = peaks["hbm_gbps"]
        out["roofline_peak_source"] = (
            f"{peaks['entry']}"
            + (" (assumed from platform)" if peaks.get("assumed") else "")
            + f": {peaks['basis']}")
        if n_devices > 1:
            out["roofline_basis"] = f"aggregate over {n_devices} chips"
    if peaks and peaks.get("peak_tflops"):
        out["pct_flops_roofline"] = round(
            100.0 * (out["achieved_gflops"] / 1e3)
            / (peaks["peak_tflops"] * max(1, n_devices)), 4)
        out["roofline_peak_tflops"] = peaks["peak_tflops"]
        out["roofline_flops_precision"] = peaks.get("flops_precision")
    return out
