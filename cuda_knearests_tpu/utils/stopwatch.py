"""Wall-clock and device-accurate timing utilities.

Reference parity (C7, /root/reference/stopwatch.h:11-43): an RAII timer printing
elapsed wall time for a named phase.  The reference's version has 10 ms resolution
(``times()``); this one uses ``perf_counter`` (ns resolution) and knows about the
two things a CUDA stopwatch does not need to know about JAX: asynchronous dispatch
(results must be blocked on before stopping the clock) and one-time compilation
cost (the analog of the reference's dummy ``cudaMalloc`` context-warmup at
/root/reference/test_knearests.cu:138-139), which ``timed`` separates out.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Tuple

import jax


class Stopwatch:
    """Context-manager / RAII-style phase timer (reference: stopwatch.h:11-43)."""

    def __init__(self, name: str = "", verbose: bool = True):
        self.name = name
        self.verbose = verbose
        self.start = time.perf_counter()
        self.last = self.start
        self.elapsed = 0.0
        if verbose and name:
            print(f"[{name} start]", flush=True)

    def tick(self) -> float:
        """Seconds since the previous tick (reference: Stopwatch::tick)."""
        now = time.perf_counter()
        dt = now - self.last
        self.last = now
        return dt

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self.start
        if self.verbose and self.name:
            print(f"[{self.name}: {self.elapsed:.6f} s]", flush=True)
        return self.elapsed

    def __enter__(self) -> "Stopwatch":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def block(tree: Any) -> Any:
    """Block until every array in a pytree is computed (async-dispatch barrier)."""
    return jax.block_until_ready(tree)


def timed(fn: Callable[..., Any], *args: Any, warmup: int = 1, iters: int = 3,
          **kwargs: Any) -> Tuple[Any, Dict[str, float]]:
    """Run `fn`, separating compile/warmup time from steady-state time.

    Returns (result, {"warmup_s", "mean_s", "min_s"}).  The warmup split is the
    JAX analog of the reference keeping CUDA context creation outside its inner
    "knn subgpu" timer (test_knearests.cu:136-144).
    """
    t0 = time.perf_counter()
    out = block(fn(*args, **kwargs))
    warmup_s = time.perf_counter() - t0
    for _ in range(max(0, warmup - 1)):
        block(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = block(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return out, {
        "warmup_s": warmup_s,
        "mean_s": sum(times) / len(times),
        "min_s": min(times),
    }
