"""Platform-selection guard.

Some environments install a site hook that registers an accelerator backend and
widens ``jax_platforms`` behind the user's back, which both overrides an
explicit ``JAX_PLATFORMS=cpu`` and can hang backend init when the accelerator
transport is down.  ``honor_jax_platforms_env()`` restores the standard
semantics: if the user set ``JAX_PLATFORMS``, that is what jax uses.  Call it
at entry-point start, before the first backend use.
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
