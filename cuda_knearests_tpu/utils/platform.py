"""Platform selection & safe backend acquisition.

Two related guards live here:

``honor_jax_platforms_env()`` -- some environments install a site hook that
registers an accelerator backend and widens ``jax_platforms`` behind the
user's back, which both overrides an explicit ``JAX_PLATFORMS=cpu`` and can
hang backend init when the accelerator transport is down.  This restores the
standard semantics: if the user set ``JAX_PLATFORMS``, that is what jax uses.
Call it at entry-point start, before the first backend use.

``acquire_backend()`` -- a down accelerator transport makes jax backend init
*hang*, not error (the reference's failure mode is the opposite: every CUDA
call is checked and exits, /root/reference/knearests.cu:205-231).  So any
entry point that must terminate in bounded time (bench, CLI) first probes the
default backend in a subprocess it can time out, retries with backoff, and on
persistent failure pins ``JAX_PLATFORMS=cpu`` before this process ever touches
a backend.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def honor_jax_platforms_env() -> None:
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def enable_compile_cache(default_dir: str | None = None) -> str:
    """Point jax's persistent compilation cache at a stable local directory
    (default: ``<repo>/.jax_cache``, gitignored) and return the path.

    The accelerator here lives behind a remote tunnel whose healthy windows
    can be shorter than one cold capture (~30 s/program remote compiles);
    persisting compiles means a retry after a transport flap -- or the
    driver's own ``bench.py`` run after the watcher warmed the cache --
    resumes nearly compile-free.  An explicit ``JAX_COMPILATION_CACHE_DIR``
    wins; config is applied at jax.config level too because jax only reads
    the env var at import time.
    """
    path = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if path == "":
        return ""  # explicit disable (stock jax semantics): leave cache off
    if path is None:
        path = default_dir or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache")
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    min_s = _env_number("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                        0.5, float)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          str(min_s))
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_s)
    except Exception:  # noqa: BLE001 -- cache is an optimization, never fatal
        pass
    return path


def _probe_default_backend(timeout_s: float) -> str | None:
    """Ask a subprocess whether the default jax backend initializes, and on
    what platform.  A subprocess because a down accelerator transport makes
    backend init *hang*, not error -- the parent must be able to time it out
    without poisoning its own jax state.  The probe applies the same
    JAX_PLATFORMS-restoring semantics as honor_jax_platforms_env, so it
    answers for the platform the parent will actually run on -- not whatever
    a site hook widens the subprocess to."""
    code = ("import os, jax\n"
            "w = os.environ.get('JAX_PLATFORMS')\n"
            "if w: jax.config.update('jax_platforms', w)\n"
            "print('PLATFORM=' + jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return None
    if r.returncode == 0:
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1].strip()
    return None


_CACHE_TTL_ENV = "BENCH_PROBE_CACHE_TTL_S"
_CACHE_TTL_DEFAULT = 60.0


def backoff_schedule(tries: int, base_s: float = 5.0, factor: float = 2.0,
                     max_s: float = 120.0) -> list[float]:
    """Delays (seconds) between retry attempts: ``tries - 1`` entries of
    capped exponential backoff.  The one backoff law shared by every retry
    loop in the engine -- acquire_backend's probe retry below and the
    execution supervisor's transient-transport retry
    (runtime/supervisor.py) -- so changing the policy cannot leave one
    caller on a stale curve."""
    delays = []
    d = max(0.0, base_s)
    for _ in range(max(0, tries - 1)):
        delays.append(min(d, max_s))
        d *= factor
    return delays


def _env_number(name, default, cast):
    """Parse a numeric env knob; a malformed value must not crash every
    entry point -- fall back to the default with a stderr note."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except ValueError:
        print(f"ignoring malformed {name}={raw!r}; using {default}",
              file=sys.stderr, flush=True)
        return default


def _probe_cache_path() -> str:
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(),
                        f"cuda_knearests_tpu_probe_{uid}.json")


def _probe_env_key() -> str:
    """The JAX_PLATFORMS pin the probe answered for.  A cached result is only
    valid for the same pin: a healthy 'tpu' stamped under JAX_PLATFORMS=axon
    says nothing about what an unset-env process would initialize -- serving
    it across pins would skip the probe for a backend that was never checked
    (the unbounded-init hang this module exists to prevent)."""
    return os.environ.get("JAX_PLATFORMS", "")


def _read_healthy_probe_cache(ttl_s: float) -> str | None:
    """A healthy probe result persisted within the last ttl_s seconds for the
    SAME JAX_PLATFORMS pin, or None.  Failures are never written here, so a
    hit always means 'a real backend init succeeded moments ago'.  The file
    must be owned by this uid -- a fixed predictable /tmp path is otherwise
    forgeable by any local user (sticky-bit /tmp keeps our os.replace from
    evicting a planted file)."""
    path = _probe_cache_path()
    try:
        if hasattr(os, "getuid") and os.stat(path).st_uid != os.getuid():
            return None
        with open(path) as f:
            d = json.load(f)
        if (d.get("platform") and d.get("env_key") == _probe_env_key()
                and 0.0 <= time.time() - d["t"] <= ttl_s):
            return str(d["platform"])
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return None


def _write_healthy_probe_cache(platform: str) -> None:
    path = _probe_cache_path()
    try:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        with os.fdopen(fd, "w") as f:
            json.dump({"platform": platform, "env_key": _probe_env_key(),
                       "t": time.time()}, f)
        os.replace(tmp, path)  # atomic vs concurrent readers
    except OSError:
        pass


def acquire_backend(tries: int | None = None, timeout_s: float | None = None,
                    probe=None):
    """Bounded retry-with-backoff around backend acquisition.

    Returns (platform, note): the platform the caller will run on, plus a
    diagnostic note when the default (accelerator) backend was unavailable and
    the caller fell back to CPU.  JAX_PLATFORMS=cpu short-circuits (cpu init
    cannot hang); any other environment -- unset, or an accelerator pin like
    the launcher's JAX_PLATFORMS=axon -- is probed in a subprocess first,
    because a pinned-but-dead accelerator is exactly the hang scenario.
    BENCH_PROBE_TRIES / BENCH_PROBE_TIMEOUT_S override the retry bounds.

    Probe caching: only *healthy* results are cached, in a cross-process tmp
    file, for a short TTL (BENCH_PROBE_CACHE_TTL_S, default 60 s; 0 disables),
    keyed by the JAX_PLATFORMS pin they answered for.  A second entry-point
    run within the TTL skips the subprocess backend init (which costs 10-30 s
    over a remote-tunnel accelerator).  Failures are never cached -- a dead
    transport is always re-probed.

    Tradeoff, stated plainly: a cache hit proceeds straight to in-process
    backend init, so if the transport dies *within the TTL* of a healthy
    probe, the caller hangs unbounded -- the same race that already exists in
    the seconds between any probe and the parent's own init, widened to at
    most TTL seconds.  Interactive entry points accept that for the 2x
    startup saving; unattended automation that needs a hard bound per run
    should set BENCH_PROBE_CACHE_TTL_S=0 (scripts/tpu_watch.py does) or pin
    JAX_PLATFORMS explicitly.
    """
    explicit = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    if explicit == "cpu":
        return "cpu", None
    ttl_s = _env_number(_CACHE_TTL_ENV, _CACHE_TTL_DEFAULT, float)
    if ttl_s > 0:
        cached = _read_healthy_probe_cache(ttl_s)
        if cached:
            return cached, None
    # 2 tries x 75s bounds the dead-transport worst case at ~155s -- inside
    # the bench's end-to-end wall budget -- while the 75s first-try timeout
    # still tolerates a slow healthy accelerator init.
    if tries is None:
        tries = _env_number("BENCH_PROBE_TRIES", 2, int)
    if timeout_s is None:
        timeout_s = _env_number("BENCH_PROBE_TIMEOUT_S", 75.0, float)
    if probe is None:
        probe = _probe_default_backend
    delays = backoff_schedule(tries, base_s=5.0)
    for i in range(tries):
        platform = probe(timeout_s)
        if platform:
            if ttl_s > 0:
                _write_healthy_probe_cache(platform)
            return platform, None
        if i < len(delays):
            time.sleep(delays[i])
    # Persistent failure: pin cpu in the env (for any child process) AND at
    # jax config level -- jax is typically already imported by the package
    # __init__ at this point, so the env var alone would be a no-op here.
    # honor_jax_platforms_env applies the config-level pin; making the
    # fallback self-contained means callers need no ordering contract.
    os.environ["JAX_PLATFORMS"] = "cpu"
    honor_jax_platforms_env()
    note = (f"default jax backend unavailable after {tries} probes "
            f"({timeout_s:.0f}s timeout each); fell back to cpu")
    print(note, file=sys.stderr, flush=True)
    return "cpu", note
