"""Tie-aware differential comparison for FoF labels vs the union-find oracle.

Label equality is the WRONG check at the linking radius: the engine scores
pairs in f32 (with whatever fusion the backend picked) while the oracle
scores in f64, so a pair whose true distance sits within the f32 rounding
band of ``b`` may legally link in one and not the other -- and ONE such
edge can merge two components, relabeling arbitrarily many points.  What
is exactly checkable:

  1. well-formedness: labels are (n,) integers in [0, n), sizes (when
     given) count label multiplicity exactly;
  2. canonicalization: every cluster's label IS its minimum member id;
  3. mandatory links: pairs provably inside the radius (f64 distance below
     the band) must share an engine label -- every oracle *mandatory*
     component carries one engine label;
  4. allowed links: the engine must not link beyond pairs possibly inside
     the radius -- every engine component lies inside one oracle *allowed*
     component.

3 + 4 say the engine partition sits between the oracle's bracketing
partitions in the refinement lattice; with no pairs in the band the two
brackets coincide and the check degenerates to exact partition equality.
Together with 2 this pins the full FoF contract without ever comparing
labels across the f32/f64 boundary.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..fuzz.compare import Mismatch


def fof_band(b: float) -> float:
    """Absolute squared-distance slack bracketing the engine's f32 edge
    predicate around ``b^2``.

    Two error sources: the engine thresholds at ``f32(b)^2`` computed in
    f32 (relative ~2^-23 of b^2, doubled), and the f32 diff-square-sum
    distance itself (for pairs near the radius the per-axis subtraction is
    exact or near-exact -- Sterbenz for nearby coordinates -- leaving the
    squaring/summation rounding, relative ~2^-21 of d2, plus the
    subtraction rounding cross term ~ulp(coord) * b).  A 1e-4 relative
    band plus a coordinate-ulp cross term covers both with two orders of
    magnitude to spare while staying far below any real inter-point
    spacing gap."""
    b2 = float(np.float64(b) ** 2)  # kntpu-ok: wide-dtype -- host threshold arithmetic, never staged
    return 1e-4 * b2 + 4e-3 * float(b) + 1e-9


def _groups_share_one_value(group_of: np.ndarray, value_of: np.ndarray
                            ) -> Optional[int]:
    """First index whose ``value_of`` differs from its group's first
    member's, or None when every group carries one value."""
    order = np.argsort(group_of, kind="stable")
    g = group_of[order]
    v = value_of[order]
    starts = np.concatenate([[True], g[1:] != g[:-1]])
    first_of_group = np.maximum.accumulate(
        np.where(starts, np.arange(g.size), 0))
    bad = v != v[first_of_group]
    if bad.any():
        return int(order[np.nonzero(bad)[0][0]])
    return None


def check_fof_result(points: np.ndarray, b: float, labels: np.ndarray,
                     sizes: Optional[np.ndarray] = None,
                     band: Optional[float] = None) -> Optional[Mismatch]:
    """First tie-aware disagreement between an engine FoF labeling and the
    CPU union-find oracle, or None when the labeling is exact.

    ``band`` overrides the default f32 rounding band (squared-distance
    units); the oracle runs once with it to produce the bracketing
    mandatory/allowed partitions (oracle.fof_oracle)."""
    from ..oracle import fof_oracle

    points = np.asarray(points, np.float32)
    n = points.shape[0]
    labels = np.asarray(labels)
    if labels.shape != (n,) or not np.issubdtype(labels.dtype, np.integer):
        return Mismatch(-1, "shape",
                        f"labels {labels.shape} {labels.dtype}, want ({n},) "
                        f"integer")
    if n == 0:
        return None
    if labels.min() < 0 or labels.max() >= n:
        r = int(np.nonzero((labels < 0) | (labels >= n))[0][0])
        return Mismatch(r, "label-range",
                        f"label {int(labels[r])} outside [0, {n})")
    # canonicalization: each cluster's label is its minimum member id
    mins = np.full(n, n, dtype=np.int64)  # kntpu-ok: wide-dtype -- host index arithmetic, never staged
    np.minimum.at(mins, labels, np.arange(n))
    uniq = np.unique(labels)
    bad = uniq[mins[uniq] != uniq]
    if bad.size:
        lab = int(bad[0])
        return Mismatch(lab, "not-canonical",
                        f"cluster labeled {lab} but its minimum member id "
                        f"is {int(mins[lab])}")
    if sizes is not None:
        sizes = np.asarray(sizes)
        counts = np.bincount(labels, minlength=n)
        if sizes.shape != (n,) or (sizes != counts[labels]).any():
            r = 0 if sizes.shape != (n,) else \
                int(np.nonzero(sizes != counts[labels])[0][0])
            return Mismatch(r, "size-mismatch",
                            f"sizes disagree with label multiplicity at "
                            f"row {r}")
    band = fof_band(b) if band is None else float(band)
    mand, allowed = fof_oracle(points, b, band=band)
    # (3) every mandatory component carries exactly one engine label
    r = _groups_share_one_value(mand, labels)
    if r is not None:
        return Mismatch(r, "mandatory-split",
                        f"point {r} (engine label {int(labels[r])}) is "
                        f"mandatorily linked to oracle component "
                        f"{int(mand[r])} whose members carry another "
                        f"engine label")
    # (4) every engine component lies inside one allowed oracle component
    r = _groups_share_one_value(labels, allowed)
    if r is not None:
        return Mismatch(r, "forbidden-merge",
                        f"engine cluster {int(labels[r])} spans distinct "
                        f"allowed-oracle components (a link beyond the "
                        f"radius band merged them)")
    return None
