"""Friends-of-friends clustering on the grid-hash core.

FoF links every pair of points within a fixed *linking length* ``b`` and
returns the connected components of that graph -- the third query family
on the engine's one spatial index (ROADMAP item 4; JZ-Tree, arXiv
2604.05885, shows neighbour search and FoF share one traversal core).

Structure (all device work, no data-dependent host loops):

1. **Grid reuse.**  The cloud is hashed with the standard CSR build
   (``ops/gridhash.build_grid``) at a dim chosen so the cell width stays
   >= ``b`` (:func:`fof_grid_dim`): then every link is contained in the
   27-cell neighborhood, which is exactly ``ops/rings.ring_schedule(2)``
   -- the same ring schedule the kNN traversal walks, truncated at ring 1.
2. **Pair enumeration on the fly.**  Each propagation round walks the 27
   neighbor-cell segments per point (scalar CSR gathers, candidates
   re-scored in f32 'diff' arithmetic like every other route) instead of
   materializing an (n, pairs) edge table -- peak memory is O(n * m) for
   m = the densest cell, not O(edges).
3. **Iterative union-find.**  Labels start as each point's own sorted
   index; every round takes the min label over the closed linked
   neighborhood (hooking) and then pointer-jumps twice (``L <- L[L]``,
   path doubling).  Labels are monotone non-increasing and always index a
   member of their own component, so the fixed point is the component's
   minimum sorted index, reached in O(log n) rounds (each round at least
   quadruples the distance a minimum has propagated along a chain).
4. **Counted convergence.**  The per-round ``changed`` flag is the ONLY
   mid-solve host traffic, read through ``runtime.dispatch.fetch`` -- one
   counted sync per round, plus one final batched fetch of labels + sizes:
   a whole FoF solve costs ``rounds + 1`` host round trips, stamped on the
   result (and on ``bench.py`` FoF rows) as ``host_syncs``.
5. **Canonical labels.**  Each component's label is the MINIMUM ORIGINAL
   point id among its members (translated through ``grid.permutation`` in
   the same jitted finalize that scatters results back to input order), so
   labels are stable under any storage reordering and directly comparable
   with the CPU union-find oracle (``oracle.fof_oracle``).

Per-round launches ride the AOT :data:`~..runtime.dispatch.EXEC_CACHE`
keyed by the standard signature census, with the densest-cell occupancy
padded to a power of two -- so a serving daemon answering repeated ``fof``
requests (serve/daemon.py) dispatches already-compiled programs in steady
state.  See DESIGN.md section 14.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import DEFAULT_CELL_DENSITY, DOMAIN_SIZE, grid_dim_for
from ..ops.gridhash import build_grid, cell_coords_host
from ..ops.rings import ring_schedule
from ..runtime import dispatch as _dispatch
from ..utils.memory import LaunchBudgetError

# Convergence guard: pointer jumping converges in O(log n) rounds (module
# docstring); 64 rounds covers any n the i32 index space can address many
# times over, so hitting the cap indicates a bug, not a big input.
MAX_ROUNDS = 64

# Candidate-matrix preflight cap: one round materializes O(n * m27) f32/i32
# temporaries (m27 = padded densest-cell occupancy x the unrolled offset
# sweep).  Refusing beyond this bound is the FoF analog of the kNN HBM
# preflight -- a degenerate cloud (everything coincident at scale) fails
# fast with a typed oom-kind error instead of wedging the host allocator.
MAX_PAIR_SLOTS = 1 << 28


@dataclasses.dataclass(frozen=True)
class FofResult:
    """One FoF solve's output, host-resident, rows in INPUT order.

    Attributes:
      labels: (n,) i32 canonical cluster label per point = the minimum
        original point id in its component (two points share a cluster iff
        they share a label; each label names one of its own members).
      sizes: (n,) i32 component size per point (``sizes[i]`` = how many
        points share ``labels[i]``).
      n_clusters: number of distinct components.
      rounds: propagation rounds to convergence (the iteration counter
        bench rows stamp as ``fof_rounds``).
      host_syncs: blocking host round trips the solve consumed (the
        counted convergence reads + the one final batched fetch).
      linking_length: the b this solve linked at.
      dim: grid cells per axis actually used (cell width >= b).
      cell_max: densest-cell occupancy (the m the round kernel padded).
    """

    labels: np.ndarray
    sizes: np.ndarray
    n_clusters: int
    rounds: int
    host_syncs: int
    linking_length: float
    dim: int
    cell_max: int

    def cluster_sizes(self) -> "tuple[np.ndarray, np.ndarray]":
        """(labels, sizes) of each distinct cluster, labels ascending."""
        if self.labels.size == 0:
            return (np.empty((0,), np.int32), np.empty((0,), np.int64))  # kntpu-ok: wide-dtype -- np.unique's native count dtype, host-only
        lab, cnt = np.unique(self.labels, return_counts=True)
        return lab.astype(np.int32), cnt


def fof_grid_dim(n: int, b: float, domain: float = DOMAIN_SIZE,
                 density: float = DEFAULT_CELL_DENSITY) -> int:
    """Cells per axis for a FoF solve: the standard density-targeted dim,
    capped so the cell width stays >= ``b`` -- the invariant that makes
    the 27-cell neighborhood (ring schedule rings 0..1) sufficient for
    pair enumeration.  A linking length wider than the domain simply
    collapses to one cell per axis."""
    dim = grid_dim_for(n, density)
    if b > 0.0:
        dim = max(1, min(dim, int(domain / b)))
    while dim > 1 and domain / dim < b:  # float-division guard
        dim -= 1
    return dim


def _round_pow2(x: int, minimum: int = 8) -> int:
    return max(minimum, 1 << max(0, int(x) - 1).bit_length())


@functools.partial(jax.jit, static_argnames=("m",))
def _fof_round(labels, px, py, pz, starts, counts, nbr_cells, nbr_ok, b2,
               m: int):
    """One propagation round over the 27-cell neighborhoods.

    labels (n,) i32 sorted-index labels; px/py/pz (n,) f32 sorted
    coordinates; starts/counts (ncells,) i32 CSR; nbr_cells/nbr_ok (n, 27)
    the per-point neighbor-cell ids and in-grid mask (host-precomputed from
    the bit-identical coordinate twin); b2 0-d f32 = linking length
    squared.  Returns (new labels, changed flag)."""
    n = labels.shape[0]
    acc = labels
    slot = jnp.arange(m, dtype=jnp.int32)
    for o in range(27):  # static unroll of the ring schedule (rings 0..1)
        cid = nbr_cells[:, o]
        ok_c = nbr_ok[:, o]
        st = jnp.where(ok_c, starts[cid], 0)
        ct = jnp.where(ok_c, counts[cid], 0)
        idx = st[:, None] + slot[None, :]
        valid = slot[None, :] < ct[:, None]
        safe = jnp.where(valid, idx, 0)
        d2 = ((px[:, None] - px[safe]) ** 2
              + (py[:, None] - py[safe]) ** 2
              + (pz[:, None] - pz[safe]) ** 2)
        link = valid & (d2 <= b2)
        cand = jnp.where(link, labels[safe], n)
        acc = jnp.minimum(acc, jnp.min(cand, axis=1))
    # pointer jumping (path doubling): labels always satisfy L[i] <= i, so
    # the label graph is a forest and two hops at least quadruple how far
    # a component minimum has propagated per round
    acc = acc[acc]
    acc = acc[acc]
    return acc, jnp.any(acc != labels)


_I32_MAX = np.iinfo(np.int32).max  # trace-static (hoisted per lint policy)


@jax.jit
def _fof_finalize(labels, perm):
    """Sorted-index root labels -> canonical min-ORIGINAL-id labels plus
    per-point component sizes, scattered back to input order."""
    n = labels.shape[0]
    big = jnp.full((n,), _I32_MAX, jnp.int32)
    canon = big.at[labels].min(perm)          # root -> min original id
    root_sizes = jnp.zeros((n,), jnp.int32).at[labels].add(1)
    out_l = jnp.zeros((n,), jnp.int32).at[perm].set(canon[labels])
    out_s = jnp.zeros((n,), jnp.int32).at[perm].set(root_sizes[labels])
    return out_l, out_s


def _launch_round(args, m: int):
    """One round through the AOT executable cache (the launch_brute idiom:
    same signature census as the recompile-key checker, plain jitted
    fallback when the backend cannot AOT-lower)."""
    key = (("cluster.fof._fof_round",) + _dispatch.signature(args, m))
    exe = _dispatch.EXEC_CACHE.get_or_build(
        key, lambda: _fof_round.lower(*args, m=m).compile())
    if exe is not None:
        return exe(*args)
    return _fof_round(*args, m=m)


def _neighbor_cells_host(points: np.ndarray, order: np.ndarray, dim: int,
                         domain: float):
    """(n, 27) neighbor-cell ids + in-grid mask per SORTED row, pure host
    numpy (cell_coords_host is the bit-identical twin of the device
    mapping, so this costs zero device round trips)."""
    coords = cell_coords_host(points, dim, domain)[order]  # sorted order
    offs = ring_schedule(2).offsets  # rings 0..1 == the 27-cell block
    nc = coords[:, None, :] + offs[None, :, :]             # (n, 27, 3)
    ok = ((nc >= 0) & (nc < dim)).all(axis=2)
    ncc = np.clip(nc, 0, dim - 1)
    cids = ncc[:, :, 0] + dim * (ncc[:, :, 1] + dim * ncc[:, :, 2])
    return cids.astype(np.int32), ok


def fof_labels(points, linking_length: float, *,
               density: float = DEFAULT_CELL_DENSITY,
               domain: float = DOMAIN_SIZE,
               validate: bool = True,
               max_rounds: int = MAX_ROUNDS) -> FofResult:
    """Friends-of-friends connected components of ``points`` at linking
    length ``linking_length``.

    Input goes through the standard front door (``io.validate_or_raise``
    for the points contract, ``io.validate_linking_length`` for ``b``);
    n = 0 and n = 1 are legal degraded modes (empty / singleton labeling).
    Returns a :class:`FofResult` with canonical min-original-id labels.

    Two points at squared distance exactly ``b^2`` in the engine's f32
    arithmetic ARE linked (``<=``); the differential check treats pairs
    within the f32 rounding band of the radius as legally ambiguous
    (cluster/compare.py).
    """
    from ..io import validate_linking_length, validate_or_raise

    b = validate_linking_length(linking_length)
    points = (validate_or_raise(points, domain=domain) if validate
              else np.ascontiguousarray(points, np.float32))
    n = points.shape[0]
    s0 = _dispatch.stats()
    if n == 0:
        return FofResult(labels=np.empty((0,), np.int32),
                         sizes=np.empty((0,), np.int32), n_clusters=0,
                         rounds=0, host_syncs=0, linking_length=b,
                         dim=1, cell_max=0)
    dim = fof_grid_dim(n, b, domain, density)
    grid = build_grid(points, dim=dim, domain=domain)
    # host twins: the stable argsort over the bit-identical host cell ids
    # reproduces the device build's sorted order with no readback
    cids = cell_coords_host(points, dim, domain)
    cids = cids[:, 0] + dim * (cids[:, 1] + dim * cids[:, 2])
    order = np.argsort(cids, kind="stable").astype(np.int32)
    cell_max = int(np.bincount(cids, minlength=dim ** 3).max())
    m = _round_pow2(cell_max, minimum=8)
    if n * m * 27 > MAX_PAIR_SLOTS:
        raise LaunchBudgetError(
            f"FoF round would materialize {n}x{m} candidate slots per "
            f"offset (densest cell holds {cell_max} of {n} points at "
            f"dim={dim}); beyond the {MAX_PAIR_SLOTS} pair-slot budget",
            requested=n * m * 27 * 4, budget=MAX_PAIR_SLOTS * 4,
            site="cluster.fof")
    nbr_cells, nbr_ok = _neighbor_cells_host(points, order, dim, domain)
    b2 = np.float32(b) * np.float32(b)
    args = (
        _dispatch.stage(np.arange(n, dtype=np.int32)),  # syncflow: fof-stage
        grid.points[:, 0], grid.points[:, 1], grid.points[:, 2],
        grid.cell_starts, grid.cell_counts,
        _dispatch.stage(nbr_cells), _dispatch.stage(nbr_ok),  # syncflow: fof-stage
        _dispatch.stage(np.float32(b2)),  # syncflow: fof-stage
    )
    labels = args[0]
    rounds = 0
    changed = n > 1
    while changed and rounds < max_rounds:
        labels, chg = _launch_round((labels,) + args[1:], m)
        rounds += 1
        # the counted convergence read: ONE flag per round through the
        # sanctioned batched-fetch primitive (DESIGN.md sections 12/14)
        changed = bool(_dispatch.fetch(chg))  # syncflow: fof-round
    if changed:
        raise AssertionError(
            f"FoF propagation failed to converge in {max_rounds} rounds "
            f"(n={n}); pointer jumping guarantees O(log n) -- this is a "
            f"bug, not a large input")
    out_l, out_s = _dispatch.fetch(*_fof_finalize(labels, grid.permutation))  # syncflow: fof-final
    out_l = np.asarray(out_l)
    out_s = np.asarray(out_s)
    syncs = _dispatch.stats().host_syncs - s0.host_syncs
    return FofResult(labels=out_l, sizes=out_s,
                     n_clusters=int(np.unique(out_l).size),
                     rounds=rounds, host_syncs=syncs, linking_length=b,
                     dim=dim, cell_max=cell_max)
