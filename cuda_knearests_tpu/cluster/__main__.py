"""``python -m cuda_knearests_tpu.cluster`` -- the clustering CPU smoke.

Two fixed-seed checks in bounded time (wired into scripts/check.sh):

1. **FoF vs the union-find oracle**: friends-of-friends labels on a small
   uniform cloud at three linking regimes (sparse / percolating / dense)
   must pass the tie-aware partition check (cluster/compare.py), and the
   solve's sync accounting must match the rounds+1 contract.
2. **Plane-feed pin**: the bisector planes emitted by the solve epilogue
   and the query surface must be bit-identical to an independent f64
   recompute from the returned neighbor ids (DESIGN.md section 14).

Exit code 0 = both clean, 1 = any violation (one JSON line per check).
"""

from __future__ import annotations

import json
import sys


def _smoke(n: int = 2500) -> int:
    import numpy as np

    from .. import KnnConfig, KnnProblem
    from ..config import DOMAIN_SIZE
    from ..io import generate_uniform
    from .compare import check_fof_result
    from .fof import fof_labels
    from .planes import bisector_planes

    rc = 0
    points = generate_uniform(n, seed=11)
    spacing = DOMAIN_SIZE / float(n) ** (1.0 / 3.0)

    for regime, scale in (("sparse", 0.4), ("percolating", 1.0),
                          ("dense", 2.2)):
        res = fof_labels(points, scale * spacing)
        bad = check_fof_result(points, res.linking_length, res.labels,
                               res.sizes)
        sync_ok = res.host_syncs == res.rounds + 1
        ok = bad is None and sync_ok
        rc |= 0 if ok else 1
        print(json.dumps({
            "check": f"fof-vs-oracle[{regime}]", "ok": ok,
            "n": n, "b": round(res.linking_length, 3),
            "clusters": res.n_clusters, "rounds": res.rounds,
            "host_syncs": res.host_syncs,
            **({} if bad is None else {"mismatch": bad.render()})}),
            flush=True)

    # plane-feed pin: solve epilogue + query surface vs f64 recompute
    k = 8
    problem = KnnProblem.prepare(points, KnnConfig(k=k, plane_feed=True))
    problem.solve()
    queries = generate_uniform(256, seed=12)
    ids_q, _d2, planes_q = problem.query(queries, planes=True)

    def ref_planes(sites, ids):
        q = sites.astype(np.float64)[:, None, :]  # kntpu-ok: wide-dtype -- the independent f64 recompute the pin compares against, host-only
        p = points[np.clip(ids, 0, None)].astype(np.float64)  # kntpu-ok: wide-dtype -- the independent f64 recompute the pin compares against, host-only
        nn = (p - q).astype(np.float32)
        d = (((p * p).sum(-1) - (q * q).sum(-1)) / 2.0).astype(np.float32)
        ok = ids >= 0
        out = np.concatenate(
            [np.where(ok[..., None], nn, np.float32(0.0)),
             np.where(ok, d, np.float32(np.inf))[..., None]], axis=-1)
        return out

    got = problem.get_planes()
    solve_ok = np.array_equal(
        got, ref_planes(points, problem.get_knearests_original()))
    query_ok = np.array_equal(planes_q, ref_planes(queries, ids_q))
    shared = np.array_equal(
        got, bisector_planes(points, points,
                             problem.get_knearests_original()))
    ok = solve_ok and query_ok and shared
    rc |= 0 if ok else 1
    print(json.dumps({"check": "plane-feed-bit-identity", "ok": ok,
                      "solve_ok": bool(solve_ok),
                      "query_ok": bool(query_ok)}), flush=True)
    return rc


if __name__ == "__main__":
    # run the canonical module instance (same -m hygiene as
    # runtime.dispatch.__main__): counters and caches must be the ones the
    # engine increments
    from cuda_knearests_tpu.cluster.__main__ import _smoke as _canonical

    sys.exit(_canonical())
