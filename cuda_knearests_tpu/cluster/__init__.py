"""Clustering subsystem: the third query family on the shared grid core.

The engine's first two query families (kNN, fixed-radius) answer per-query
rows; this package adds workloads whose output is a GLOBAL graph property
of the point cloud, computed on the same grid machinery:

* :mod:`fof` -- friends-of-friends connected components over fixed-radius
  pairs (the cosmology "FoF halo finder" primitive, JZ-Tree arXiv
  2604.05885): pair enumeration rides the existing grid-hash CSR + the
  27-cell ring schedule (``ops/rings.ring_schedule(2)``), and the
  connected-components labeling is an on-device iterative union-find
  (min-label propagation + pointer jumping) whose only host traffic is a
  counted convergence flag per round through ``runtime.dispatch.fetch``.
* :mod:`planes` -- the Voronoi/power-diagram plane feed the reference's
  own ``DEFAULT_NB_PLANES`` naming promises (params.h:4): the per-neighbor
  bisector-plane representation ``(n, d) = (p - q, (|p|^2 - |q|^2) / 2)``
  emitted as an optional epilogue of every kNN surface.
* :mod:`compare` -- the tie-aware differential check for FoF labels
  against the CPU union-find oracle (``oracle.fof_oracle``): pairs within
  the f32 rounding band of the linking radius may legally link either
  way, so the engine partition is checked against the oracle's
  mandatory/allowed partition pair instead of naive label equality.

``python -m cuda_knearests_tpu.cluster`` runs the CPU smoke (FoF vs the
union-find oracle + the plane-feed bit-identity pin) -- wired into
``scripts/check.sh``.  See DESIGN.md section 14.
"""

from __future__ import annotations

from .fof import FofResult, fof_labels  # noqa: F401
from .planes import bisector_planes  # noqa: F401

__all__ = ["FofResult", "fof_labels", "bisector_planes"]
