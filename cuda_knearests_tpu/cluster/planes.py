"""The Voronoi/power-diagram plane feed: per-neighbor bisector planes.

The reference names its k ``DEFAULT_NB_PLANES`` (/root/reference/params.h:4)
because its neighbor tables exist to feed a Voronoi-cell clipping pipeline:
each neighbor q of a site p contributes the half-space of points closer to
p than to q.  This module emits that representation directly from the kNN
result epilogue, so a clipping consumer gets planes WITH the neighbor rows
instead of re-deriving them in a second pass:

    n = p_neighbor - p_site                 (the plane normal)
    d = (|p_neighbor|^2 - |p_site|^2) / 2   (the offset)

and the site's cell is the intersection of half-spaces ``n . x <= d``
(x closer to the site than to the neighbor  <=>  2 x . (p - q) <= |p|^2 -
|q|^2, with p the neighbor and q the site).

Precision contract (the reason this epilogue is HOST-side f64, not another
device pass): the offset ``d`` subtracts two squared norms of magnitude up
to ``3 * domain^2`` that agree in nearly every bit for near neighbors --
exactly the pairs kNN returns -- so f32 arithmetic loses the plane to
catastrophic cancellation, and the engine's own static gate (kntpu-check
trace-dtype) forbids f64 inside device programs.  The feed therefore runs
in f64 on the already-fetched host rows (zero extra device syncs -- every
input is host-resident after the route's one batched fetch) and rounds to
f32 once.  The normal ``n`` is exact either way: the f64 difference of two
f32 values is exact, so its f32 rounding equals the f32 subtraction.
tests/test_cluster.py pins the emitted planes bit-identical to an
independent f64 recompute from the returned neighbor ids on all four solve
routes (DESIGN.md section 14).
"""

from __future__ import annotations

import numpy as np


def bisector_planes(sites: np.ndarray, points: np.ndarray,
                    neighbor_ids: np.ndarray) -> np.ndarray:
    """(m, k, 4) f32 plane feed ``[nx, ny, nz, d]`` for each (site,
    neighbor) pair of a kNN result.

    ``sites`` (m, 3): the query coordinates (for the all-points self-solve,
    the points themselves in original order).  ``points`` (n, 3): the
    stored cloud in ORIGINAL indexing.  ``neighbor_ids`` (m, k): the
    result's neighbor table in original indexing, ``-1`` beyond the
    available neighbors.

    Invalid slots (id < 0) emit the trivially-true half-space ``n = 0,
    d = +inf`` -- a missing neighbor constrains nothing, so a clipping
    consumer can intersect all k rows unconditionally.
    """
    sites = np.asarray(sites, np.float32)
    ids = np.asarray(neighbor_ids)
    points = np.asarray(points, np.float32)
    m, k = ids.shape
    out = np.zeros((m, k, 4), np.float32)
    out[:, :, 3] = np.inf
    if m == 0 or k == 0 or points.shape[0] == 0:
        return out
    valid = ids >= 0
    safe = np.clip(ids, 0, points.shape[0] - 1)
    p = points[safe].astype(np.float64)      # kntpu-ok: wide-dtype -- the plane offset cancels catastrophically in f32 (module docstring); host-only, rounded to f32 once, never staged
    q = sites.astype(np.float64)[:, None, :]  # kntpu-ok: wide-dtype -- same f64 plane-feed contract as above
    normal = (p - q).astype(np.float32)
    d = (((p * p).sum(-1) - (q * q).sum(-1)) / 2.0).astype(np.float32)
    out[:, :, :3] = np.where(valid[:, :, None], normal, np.float32(0.0))
    out[:, :, 3] = np.where(valid, d, np.float32(np.inf))
    return out
