"""Pallas MXU kernel for the brute blocked-matmul top-k (any d).

One program per 128-query block: the query block (128, d_pad) and the full
interleaved candidate set (C, d_pad) live in VMEM; the kernel walks the
candidate axis one 128-lane block at a time, computes each (128, 128)
dot-form score tile ON THE MXU (``jnp.dot`` with f32 accumulation --
pallas_guide.md "Matrix Operations"), reduces it to its ascending top-m by
m min-and-mask passes while it lives in registers, and runs the final
top-k on the (G*m, 128) survivor pool -- the TPU-KNN in-register
approximate top-k (arXiv 2206.14286), structurally the general-d twin of
``pallas_solve._kernel_blocked``.

The kernel emits the SELECTION (ids + dot-form scores + the certification
bit from ``kplus >= t + 2B``, topk.py); the exact diff-arithmetic
rescoring is a shared XLA post-pass (scorer.rescore_sorted), identical to
the XLA twin's, so the two backends differ only in who runs the fold.
``tests/test_mxu.py`` pins kernel-vs-twin selection equality in interpret
mode.

Layouts (all (8, 128)-aligned): queries/candidates pad d to a sublane
multiple and their point axes to 128 lanes; candidate ids ride as a
(G, 128) block so block g is a static-stride sublane slice; the survivor
pool and rem live in VMEM scratch, written at dynamic SUBLANE offsets
(``pl.ds`` -- the documented Mosaic pattern; lane offsets are always
static, so the (128, k) output tiles accumulate through iota masks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .topk import BLOCK, dot_error_bound

_BIG_ID = 2**31 - 1

# Per-program VMEM budget for choosing the kernel path (same constant
# rationale as pallas_solve._VMEM_BUDGET: headroom for Mosaic's own
# double-buffering under the 128 MiB v5e budget).
_VMEM_BUDGET = 32 * 1024 * 1024


def kernel_fits(c_pad: int, d_pad: int, k: int, m: int) -> bool:
    """True when the resident candidate set + survivor pool + tiles fit
    one program's VMEM budget (the brute route falls back to the XLA twin
    otherwise -- same contract as pallas_solve.pick_qsub returning 0)."""
    g = c_pad // BLOCK
    resident = c_pad * max(8, d_pad) * 4          # candidate coords
    scratch = (g * m * BLOCK * 8) + (g * BLOCK * 4)   # pool v+i, rem
    tiles = BLOCK * BLOCK * 4 * 4                 # score tile + temporaries
    outs = 2 * BLOCK * (-(-k // BLOCK) * BLOCK) * 4
    return resident + scratch + tiles + outs <= _VMEM_BUDGET


def _select_kernel(q_ref, qid_ref, p_ref, cid_ref, out_i_ref, out_v_ref,
                   cert_ref, pool_v_ref, pool_i_ref, rem_ref, *, k: int,
                   m: int, d_real: int, exclude_self: bool,
                   precision: str = "f32"):
    """One 128-query block: stage-1 per-block top-m into the VMEM pool,
    stage-2 k-pass selection + the (k+1)-th probe, certification.

    Refs and BlockSpecs stay f32 at every precision tier -- bf16 casts
    happen in-register AFTER the VMEM load (no (16, 128) bf16 tiling in
    the layouts), with f32 accumulation on every reduction; only the
    scoring inputs round, which the widened bf16 certification band
    covers (topk.dot_error_bound)."""
    g_total = cid_ref.shape[0]
    q = q_ref[:, :]                                  # (128, d_pad)
    qn = jnp.sum(q * q, axis=1)                      # (128,) f32 band input
    qs = q.astype(jnp.bfloat16) if precision == "bf16" else q
    qn_s = (jnp.sum(qs * qs, axis=1, dtype=jnp.float32)
            if precision == "bf16" else qn)          # scoring norms
    qid = qid_ref[0, :].reshape(-1, 1) if exclude_self else None

    def s1_body(g, pn_max):
        p_blk = p_ref[pl.ds(g * BLOCK, BLOCK), :]    # (128, d_pad)
        cid = cid_ref[pl.ds(g, 1), :]                # (1, 128)
        pn = jnp.sum(p_blk * p_blk, axis=1)          # (128,) f32 band input
        ps = p_blk.astype(jnp.bfloat16) if precision == "bf16" else p_blk
        pn_s = (jnp.sum(ps * ps, axis=1, dtype=jnp.float32)
                if precision == "bf16" else pn)
        # the MXU contraction: (128, d) x (d, 128) with f32 accumulation
        qp = jax.lax.dot_general(qs, ps, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        s = qn_s[:, None] + pn_s[None, :] - 2.0 * qp  # (128q, 128c)
        drop = cid < 0
        if exclude_self:
            drop = drop | (cid == qid)
        s = jnp.where(drop, jnp.inf, s)

        def m_body(j, s):
            mv = jnp.min(s, axis=1)                  # (128q,)
            sel = s == mv[:, None]
            bid = jnp.min(jnp.where(sel, cid, _BIG_ID), axis=1)
            pool_v_ref[pl.ds(g * m + j, 1), :] = mv.reshape(1, -1)
            pool_i_ref[pl.ds(g * m + j, 1), :] = bid.reshape(1, -1)
            return jnp.where(sel & (cid == bid[:, None]), jnp.inf, s)

        s = jax.lax.fori_loop(0, m, m_body, s)
        # the block's smallest REJECTED score (inf when it kept all)
        rem_ref[pl.ds(g, 1), :] = jnp.min(s, axis=1).reshape(1, -1)
        return jnp.maximum(pn_max,
                           jnp.max(jnp.where(cid[0, :] < 0, -jnp.inf, pn)))

    pn_max = jax.lax.fori_loop(0, g_total, s1_body, jnp.float32(0.0))

    pool_v = pool_v_ref[:, :]                        # (G*m, 128q)
    pool_i = pool_i_ref[:, :]
    lane_j = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, k), 1)

    def s2_body(j, carry):
        pool_v, acc_v, acc_i = carry
        mv = jnp.min(pool_v, axis=0)                 # (128q,)
        sel = pool_v == mv[None, :]
        bid = jnp.min(jnp.where(sel, pool_i, _BIG_ID), axis=0)
        hit = lane_j == j
        acc_v = jnp.where(hit, mv[:, None], acc_v)
        acc_i = jnp.where(hit, bid[:, None], acc_i)
        return (jnp.where(sel & (pool_i == bid[None, :]), jnp.inf, pool_v),
                acc_v, acc_i)

    pool_v, acc_v, acc_i = jax.lax.fori_loop(
        0, k, s2_body,
        (pool_v, jnp.full((BLOCK, k), jnp.inf, jnp.float32),
         jnp.full((BLOCK, k), _BIG_ID, jnp.int32)))
    out_v_ref[:, :] = acc_v
    out_i_ref[:, :] = acc_i
    # certification (topk.py): every non-selected score >= kplus; the row
    # certifies iff kplus clears t by twice the dot-form error bound
    t = jnp.max(jnp.where(jnp.isfinite(acc_v), acc_v, -jnp.inf), axis=1)
    t = jnp.where(jnp.any(jnp.isfinite(acc_v), axis=1), t,
                  jnp.full_like(t, jnp.inf))
    kplus = jnp.minimum(jnp.min(rem_ref[:, :], axis=0),
                        jnp.min(pool_v, axis=0))     # pool's (k+1)-th
    # the ONE certification bound (topk.dot_error_bound, plain arithmetic,
    # traces fine in-kernel): re-deriving it here would let the two
    # engines certify with different bands the moment the bound changes
    err_b = dot_error_bound(qn, pn_max, d_real, precision)
    cert_ref[0, :] = (kplus >= t + 2.0 * err_b).astype(jnp.int32)


def select_pallas(queries, q_ids, pts_il, cid_il, k: int, m: int,
                  d_real: int, exclude_self: bool, interpret: bool,
                  precision: str = "f32"):
    """Launch the selection kernel over 128-query blocks.

    queries (Mp, d_pad) with Mp a 128 multiple; q_ids (Mp,); pts_il
    (C, d_pad) interleaved padded candidates; cid_il (C,) ids (-1 pads).
    Returns (sel_ids (Mp, k) by ascending dot score, sel_scores (Mp, k),
    certified (Mp,) bool) -- same contract as scorer.block_fold, ready for
    the shared rescore_sorted post-pass.
    """
    mp, d_pad = queries.shape
    c_pad = pts_il.shape[0]
    g = c_pad // BLOCK
    n_qblk = mp // BLOCK
    q_spec = pl.BlockSpec((BLOCK, d_pad), lambda b: (b, 0),
                          memory_space=pltpu.VMEM)
    qid_spec = pl.BlockSpec((1, BLOCK), lambda b: (b, 0),
                            memory_space=pltpu.VMEM)
    p_spec = pl.BlockSpec((c_pad, d_pad), lambda b: (0, 0),
                          memory_space=pltpu.VMEM)
    cid_spec = pl.BlockSpec((g, BLOCK), lambda b: (0, 0),
                            memory_space=pltpu.VMEM)
    out_specs = [
        pl.BlockSpec((BLOCK, k), lambda b: (b, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((BLOCK, k), lambda b: (b, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, BLOCK), lambda b: (b, 0), memory_space=pltpu.VMEM),
    ]
    out_i, out_v, cert = pl.pallas_call(
        functools.partial(_select_kernel, k=k, m=m, d_real=d_real,
                          exclude_self=exclude_self, precision=precision),
        grid=(n_qblk,),
        in_specs=[q_spec, qid_spec, p_spec, cid_spec],
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((mp, k), jnp.int32),
            jax.ShapeDtypeStruct((mp, k), jnp.float32),
            jax.ShapeDtypeStruct((n_qblk, BLOCK), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((g * m, BLOCK), jnp.float32),
                        pltpu.VMEM((g * m, BLOCK), jnp.int32),
                        pltpu.VMEM((g, BLOCK), jnp.float32)],
        interpret=interpret,
    )(queries, q_ids.reshape(n_qblk, BLOCK), pts_il,
      cid_il.reshape(g, BLOCK))
    # sanitize like scorer.solve_blocks_xla: an all-inf pool can emit a
    # REAL id with an inf score (min-id over equal-inf slots), so validity
    # keys on the score and ids carry the -1 sentinel for the host epilogue
    invalid = (out_i == _BIG_ID) | ~jnp.isfinite(out_v)
    sel_v = jnp.where(invalid, jnp.inf, out_v)
    sel_i = jnp.where(invalid, -1, out_i)
    return sel_i, sel_v, cert.reshape(-1).astype(bool)
