"""Blocked-matmul distance scoring with the TPU-KNN approximate top-k.

The scoring core recasts candidate distances as ``|q|^2 + |p|^2 - 2 QP^T``
so the O(Q * C * d) work lands on the MXU as a blocked matmul (f32
accumulation via ``preferred_element_type``) instead of the VPU's
elementwise diff path -- the TPU-KNN formulation (arXiv 2206.14286).  The
core is dimension-agnostic by construction: ``d`` is just the contraction
axis, which is what opens the general-d workload (ROADMAP item 4).

Three layers, all sharing one selection/certification fold (topk.py has
the math and the soundness argument):

* :func:`block_fold` -- the in-register approximate top-k: per-128-lane
  block top-m + the block's smallest *rejected* score, folded into a
  ``G * m`` pool, exact top-k over the pool, and the per-row certification
  bit ``kplus >= t + 2B`` that proves the selected id set is a true top-k
  set despite the dot-form's cancellation error.
* :func:`rescore_sorted` -- selected ids re-scored in the engine's exact
  ``diff`` arithmetic (the same subtract-square-accumulate loop, axis
  order 0..d-1, as ops/solve.py) and re-sorted ascending with min-id tie
  break.  This is the DEVICE-side variant the grid-fed class scorer uses
  (its rows feed the margin certificate in-program); the brute route's
  final distances are instead a HOST epilogue over the one fetched
  selection (solve.py ``_host_rescore``), because XLA strips
  ``optimization_barrier`` on CPU and reassociates/FMA-contracts the
  3-term sum SHAPE-DEPENDENTLY -- measured: 3 rows of the 20k fixture
  flip 1 ulp between two shapes of the same program.  Host numpy is
  strict IEEE at every shape, which is what makes ``recall_target=1.0``
  byte-identity with the exact elementwise path pinnable (the same
  host-epilogue precedent as the plane feed, DESIGN.md section 14).
* :func:`solve_blocks_xla` / :func:`grid_class_topk` -- the brute
  (all-candidates, any d) core and the grid-fed (d=3, per-class candidate
  boxes) core.  Both are pure XLA: the batched matmul lowers onto the MXU
  on TPU by itself; the hand-blocked Pallas twin (kernel.py) exists for
  the brute route where the fold can stay in registers.

Precision tiers (DESIGN.md section 21): ``precision='bf16'`` casts the
matmul inputs and the norm squares to bfloat16 while every accumulation
stays f32 (``preferred_element_type`` / explicit f32 sum dtype) -- the
MXU's native reduced-precision mode.  Certification stays sound because
the error band comes from the per-precision family
(topk.dot_error_bound(..., precision)): the wider bf16 band decertifies
boundary rows into the existing exact fallback instead of mis-certifying
them.  The f32 tier is byte-identical to the pre-tier pipeline.

Seeded faults (``KNTPU_MXU_FAULT``, resolved by the solve wrapper and
passed as a static): ``drop-block`` silently discards block 0's pool
contribution AFTER certification (a certified-yet-incomplete row -- the
shape of a broken fold), ``skip-certify`` forces every row certified (a
dead refinement tier), ``narrow-bound`` certifies bf16-scored rows with
the f32-width band (the realistic forgot-to-thread-the-precision bug:
bf16 noise dwarfs the narrow band, so boundary rows wrongly certify).
Each must yield a banked failure in the ``fuzz --approx`` self-test
(scripts/check.sh).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.solve import pack_cells
from ..ops.topk import INVALID_ID
from .topk import BLOCK, dot_error_bound, interleave_slots, per_block_m

#: Score-tile budget (bytes) per chunk: bounds the (qc, C) f32 tile the
#: blocked matmul materializes per step on the XLA path.
_MXU_TILE_BYTES = 64 << 20

FAULTS = ("drop-block", "skip-certify", "narrow-bound")


def cert_band_precision(precision: str, fault: Optional[str] = None) -> str:
    """The precision whose error band certifies rows: the SCORING precision
    -- except under the ``narrow-bound`` seeded fault, which drops the
    precision term and certifies with the f32-width band regardless of the
    arithmetic that actually scored.  Under bf16 scoring that band is
    ~465x too narrow, so boundary rows wrongly certify: the exact unsound
    shape ``fuzz --approx`` exists to bank."""
    return "f32" if fault == "narrow-bound" else precision


def _cast_for(q: jax.Array, precision: str) -> jax.Array:
    """Cast a matmul/norm input to the scoring precision ('f32' is the
    identity -- same array object, so the f32 tier's program is untouched,
    not merely equivalent)."""
    return q.astype(jnp.bfloat16) if precision == "bf16" else q


def _sort_pairs(vals: jax.Array, ids: jax.Array):
    """Ascending lexicographic sort by (value, id) along the last axis --
    the canonical tie rule of this subsystem (min id among equal values,
    matching the Pallas kernels' min-and-mask convention)."""
    return jax.lax.sort((vals, ids), num_keys=2, dimension=-1)


def block_fold(s: jax.Array, ids: jax.Array, k: int, m: int,
               err_b: jax.Array, fault: Optional[str] = None
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The TPU-KNN fold over a scored tile.

    s:     (..., C) dot-form scores, C a BLOCK multiple; masked slots +inf.
    ids:   (..., C) global candidate ids aligned with ``s``.
    err_b: (...,) per-row dot-vs-true error bound B (topk.dot_error_bound).
    Returns (sel_ids (..., k), sel_scores (..., k) ascending dot-form,
    certified (...,)) -- see topk.py for the certification soundness proof.
    """
    lead = s.shape[:-1]
    c = s.shape[-1]
    if c % BLOCK != 0:
        raise ValueError(f"candidate axis {c} is not a {BLOCK} multiple")  # kntpu-ok: bare-valueerror -- internal layout invariant (callers pad), not user input
    g = c // BLOCK
    m = min(m, BLOCK)
    sb = s.reshape(lead + (g, BLOCK))
    mm = min(m + 1, BLOCK)
    neg, slot = jax.lax.top_k(-sb, mm)              # (..., g, mm) ascending
    # barrier before slicing: a consumer that slices top_k's INDEX output
    # defeats XLA CPU's TopK custom-call lowering and falls back to a
    # generic sort (measured 13.8s -> 2.0s for one 8k fold on this host);
    # free where the fast path was already taken
    neg, slot = jax.lax.optimization_barrier((neg, slot))
    vals = -neg
    # block g's smallest REJECTED score: the (m+1)-th smallest, inf when
    # the block kept everything it had (m == BLOCK, or fewer real slots)
    rem = vals[..., m] if mm > m else jnp.full(lead + (g,), jnp.inf,
                                               jnp.float32)
    kept_v = vals[..., :m].reshape(lead + (g * m,))
    flat = (slot[..., :m]
            + (jnp.arange(g, dtype=jnp.int32) * BLOCK)[..., :, None])
    kept_i = jnp.take_along_axis(ids, flat.reshape(lead + (g * m,)),
                                 axis=-1)
    pad = max(0, k + 1 - g * m)
    if pad:
        # tiny pools (few blocks at small m) widen with inf sentinels so
        # the k-th / (k+1)-th reads below are always in range
        kept_v = jnp.concatenate(
            [kept_v, jnp.full(lead + (pad,), jnp.inf, jnp.float32)], axis=-1)
        kept_i = jnp.concatenate(
            [kept_i, jnp.full(lead + (pad,), INVALID_ID, jnp.int32)],
            axis=-1)
    sv, si = _sort_pairs(kept_v, kept_i)
    t = sv[..., k - 1]
    # smallest score the selection EXCLUDED: pool overflow or block reject
    kplus = jnp.minimum(jnp.min(rem, axis=-1), sv[..., k])
    cert = kplus >= t + 2.0 * err_b
    if fault == "skip-certify":
        cert = jnp.ones_like(cert)
    if fault == "drop-block":
        # certification above saw the full pool; the selection below
        # silently loses block 0's survivors -- a certified-yet-incomplete
        # row, the exact shape the fuzz --approx soundness check exists for
        flat_all = flat.reshape(lead + (g * m,))
        if pad:
            flat_all = jnp.concatenate(
                [flat_all, jnp.full(lead + (pad,), BLOCK, jnp.int32)],
                axis=-1)
        from_blk0 = flat_all < BLOCK
        sv, si = _sort_pairs(jnp.where(from_blk0, jnp.inf, kept_v),
                             jnp.where(from_blk0, INVALID_ID, kept_i))
    return si[..., :k], sv[..., :k], cert


def rescore_sorted(points: jax.Array, q: jax.Array, sel_i: jax.Array,
                   sel_s: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Re-score selected ids in the exact diff arithmetic and re-sort.

    points (n, d) storage; q (..., d) query coords; sel_i/sel_s (..., k)
    from block_fold.  Returns ((..., k) i32 ids, INVALID_ID pads; (..., k)
    f32 d2 ascending) -- distances computed as the engine's canonical
    subtract-square-accumulate over axes 0..d-1 (ops/solve.py), so emitted
    values are byte-comparable with the elementwise routes."""
    valid = jnp.isfinite(sel_s)
    safe = jnp.where(valid & (sel_i >= 0), sel_i, 0)
    c = jnp.take(points, safe, axis=0)              # (..., k, d)
    d2 = jnp.zeros(sel_i.shape, jnp.float32)
    for ax in range(points.shape[1]):
        diff = q[..., None, ax] - c[..., ax]
        d2 = d2 + diff * diff
    d2 = jnp.where(valid, d2, jnp.inf)
    ids = jnp.where(valid, sel_i, INVALID_ID).astype(jnp.int32)
    d2s, ids_s = _sort_pairs(d2, ids)
    return ids_s, d2s


def score_tile(q: jax.Array, p: jax.Array,
               precision: str = "f32") -> jax.Array:
    """One (Q, C) dot-form score tile: |q|^2 + |p|^2 - 2 q.p with f32
    accumulation -- the MXU contraction (XLA lowers the matmul onto the
    MXU on TPU; the Pallas twin issues the same jnp.dot in-kernel).

    Under ``precision='bf16'`` the matmul inputs and the per-coordinate
    norm squares round to bfloat16; both reductions still accumulate in
    f32 (explicit sum dtype / preferred_element_type), so the only new
    error is the per-lane cast+product roundoff the widened certification
    band (topk.dot_error_bound's _CAST_SITES term) covers."""
    qs, ps = _cast_for(q, precision), _cast_for(p, precision)
    qn = jnp.sum(qs * qs, axis=-1, dtype=jnp.float32)
    pn = jnp.sum(ps * ps, axis=-1, dtype=jnp.float32)
    qp = jnp.dot(qs, ps.T, preferred_element_type=jnp.float32)
    return qn[:, None] + pn[None, :] - 2.0 * qp


@functools.partial(jax.jit, static_argnames=("k", "m", "exclude_self",
                                             "qc", "fault", "precision"))
def solve_blocks_xla(pts_il: jax.Array, cid_il: jax.Array,
                     queries: jax.Array, q_ids: jax.Array, k: int, m: int,
                     exclude_self: bool, qc: int,
                     fault: Optional[str] = None,
                     precision: str = "f32"):
    """The brute MXU core (any d): every query scored against every stored
    point in BLOCK-wide bins, approximate top-k + certification, chunked
    over the query axis to bound the score tile.

    pts_il/cid_il: (C, d)/(C,) interleaved padded candidates + global ids
      (-1 pads) -- built host-side by the solve wrapper (interleave_slots).
    queries: (M, d), M a ``qc`` multiple (wrapper pads); q_ids (M,) the
      global id each query excludes (-1 = exclude nothing / padded row).
    Returns the SELECTION: (ids (M, k) i32 by ascending dot score, -1
    where fewer than k candidates exist; scores (M, k) f32 dot-form;
    cert (M,) bool).  The exact diff-arithmetic distances and the final
    (d2, id) ordering are the caller's host epilogue
    (solve._host_rescore) -- see rescore_sorted's docstring for why the
    byte-identity contract forces them off-device.  ``precision`` picks
    the scoring tier (score_tile) and, through cert_band_precision, the
    certification band that keeps it sound.
    """
    d = pts_il.shape[1]
    pn = jnp.sum(pts_il * pts_il, axis=1)
    pn_max = jnp.max(jnp.where(cid_il >= 0, pn, -jnp.inf), initial=0.0)

    def chunk(args):
        q_c, qid_c = args
        s = score_tile(q_c, pts_il, precision)
        drop = cid_il[None, :] < 0
        if exclude_self:
            drop = drop | (cid_il[None, :] == qid_c[:, None])
        s = jnp.where(drop, jnp.inf, s)
        # f32 norms for the BAND even when scoring casts down: the band's
        # (qn + pn_max) is an analytic envelope, not a scored quantity
        qn = jnp.sum(q_c * q_c, axis=1)
        err_b = dot_error_bound(qn, pn_max, d,
                                cert_band_precision(precision, fault))
        ids_b = jnp.broadcast_to(cid_il[None, :], s.shape)
        sel_i, sel_s, cert = block_fold(s, ids_b, k, m, err_b, fault)
        # a dropped/pad candidate can ride out of the fold carrying a REAL
        # id with an inf score (min-id over an all-inf pool); sanitize to
        # the -1 sentinel so the host epilogue keys validity on ids alone
        sel_i = jnp.where(jnp.isfinite(sel_s), sel_i, INVALID_ID)
        return sel_i, sel_s, cert

    n_chunks = queries.shape[0] // qc
    ids, scores, cert = jax.lax.map(
        chunk, (queries.reshape(n_chunks, qc, d),
                q_ids.reshape(n_chunks, qc)))
    return (ids.reshape(-1, k), scores.reshape(-1, k), cert.reshape(-1))


# -- grid-fed d=3 class scoring (the adaptive route's 'mxu' scorer) -----------

#: Per-chunk (rows, qcap, ccap) score-tile ceiling for the class scorer --
#: same order as adaptive._DENSE_TILE_BYTES; classes past it at one row
#: per chunk fall back to their elementwise route (exact, never silent).
_CLASS_TILE_BYTES = 64 << 20


def class_eligible(qcap: int, ccap: int) -> bool:
    """True when one class row's (qcap, ccap) score tile fits the chunk
    budget (ccap is a BLOCK multiple by plan construction)."""
    return ccap % BLOCK == 0 and qcap * ccap * 4 <= _CLASS_TILE_BYTES


def grid_class_topk(points: jax.Array, starts: jax.Array,
                    counts: jax.Array, own_cells: jax.Array,
                    cand_cells: jax.Array, qcap: int, k: int, ccap: int,
                    exclude_self: bool, recall_target: float,
                    precision: str = "f32"):
    """One adaptive class's self-solve through the MXU scorer: CSR-packed
    queries x candidate boxes scored as blocked matmuls, the TPU-KNN fold,
    diff-arithmetic rescoring, and NaN-decertification.

    Same flat output contract as adaptive._dense_self -- (Sc * qcap, k)
    row-major dists/ids, ascending -- with one addition: a row whose
    selection did not certify carries NaN at column k-1, which fails the
    downstream margin certificate in every epilogue (the blocked kernel's
    established decertify trick), so it resolves through the standard
    exact fallback.  At recall_target=1.0 the fold is exhaustive and the
    NaN only fires on dot-arithmetic boundary ambiguity (topk.py), keeping
    the finalized result byte-identical to the elementwise path.

    ``precision`` picks the scoring tier: bf16 casts the matmul/norm
    inputs (f32 accumulation throughout) and certifies against the wider
    bf16 band, so uncertified rows still resolve exactly downstream.
    """
    n_sc = own_cells.shape[0]
    g = ccap // BLOCK
    m = per_block_m(recall_target, k, g)
    rows_chunk = max(1, min(n_sc, _CLASS_TILE_BYTES // max(1, qcap * ccap * 4)))
    n_chunks = -(-n_sc // rows_chunk)
    il = jnp.asarray(interleave_slots(ccap))
    d = points.shape[1]

    def pad_rows(a, fill):
        pad = n_chunks * rows_chunk - a.shape[0]
        if pad:
            a = jnp.concatenate(
                [a, jnp.full((pad,) + a.shape[1:], fill, a.dtype)])
        return a.reshape((n_chunks, rows_chunk) + a.shape[1:])

    def step(_, inp):
        own_c, cand_c = inp
        qi_c, qo_c = pack_cells(own_c, starts, counts, qcap)
        ci_c, co_c = pack_cells(cand_c, starts, counts, ccap)
        # round-robin slot interleave (the _pack_inputs trick): CSR packing
        # puts spatially-adjacent candidates in adjacent slots, which would
        # concentrate every query's near neighbors into one or two blocks
        # and rot the per-block top-m's recall bound
        ci_c = jnp.take(ci_c, il, axis=1)
        co_c = jnp.take(co_c, il, axis=1)
        q = jnp.take(points, qi_c, axis=0)           # (rows, qcap, d)
        c = jnp.take(points, ci_c, axis=0)           # (rows, ccap, d)
        qs, cs = _cast_for(q, precision), _cast_for(c, precision)
        qn = jnp.sum(qs * qs, axis=-1, dtype=jnp.float32)
        cn = jnp.sum(cs * cs, axis=-1, dtype=jnp.float32)
        qp = jnp.einsum("rqd,rcd->rqc", qs, cs,
                        preferred_element_type=jnp.float32)
        s = qn[:, :, None] + cn[:, None, :] - 2.0 * qp
        drop = ~co_c[:, None, :]
        if exclude_self:
            drop = drop | (ci_c[:, None, :] == qi_c[:, :, None])
        s = jnp.where(drop, jnp.inf, s)
        # band inputs in f32 regardless of scoring tier (analytic envelope)
        qn_f = jnp.sum(q * q, axis=-1)
        cn_f = jnp.sum(c * c, axis=-1)
        pn_max = jnp.max(jnp.where(co_c, cn_f, -jnp.inf), initial=0.0,
                         axis=(1,), keepdims=True)  # (rows, 1) per-class-row
        err_b = dot_error_bound(qn_f, pn_max, d, precision)
        ids_b = jnp.broadcast_to(ci_c[:, None, :], s.shape)
        sel_i, sel_s, cert = block_fold(s, ids_b, k, m, err_b)
        ids_o, d2_o = rescore_sorted(points, q, sel_i, sel_s)
        # decertify via the NaN trick: NaN <= margin is false even for an
        # infinite margin, so the row fails every downstream certificate
        # and resolves through the exact fallback.  Padded query slots
        # (qo false) are dropped by the epilogue maps either way.
        kth = d2_o[..., k - 1]
        d2_o = d2_o.at[..., k - 1].set(
            jnp.where(cert | ~qo_c, kth, jnp.nan))
        return None, (d2_o, ids_o)

    _, (out_d, out_i) = jax.lax.scan(
        step, None, (pad_rows(own_cells, -1), pad_rows(cand_cells, -1)))
    out_d = out_d.reshape(-1, k)[: n_sc * qcap]
    out_i = out_i.reshape(-1, k)[: n_sc * qcap]
    return out_d, out_i
