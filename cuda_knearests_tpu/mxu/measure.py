"""The ONE tie-aware f64 recall oracle of the MXU subsystem.

Every gate that measures recall -- the CPU smoke (``mxu/__main__``), the
approximate-mode fuzz flavor (``fuzz/approx``), and ``bench.py
--frontier``'s ``recall_ok`` bar -- imports THIS module, so they all
measure the same claim with the same tie discipline; two hand-rolled
copies would let a tie-rule fix in one silently desynchronize the fuzz
comparator from the bench gate (DESIGN.md section 16).

The discipline, in both measures:

* a returned id counts as a hit iff its exact f64 squared distance does
  not exceed the true k-th distance -- any member of a tied boundary
  group is a valid top-k pick (the fuzz campaign's comparator rule);
* **band-free** measurement (``band=None``) additionally accepts a pick
  that TIES the true k-th at f32 resolution: engines select in f32 (the
  refined/exact tier through the f32 diff brute force, the approximate
  tier through the f32 dot form), so two boundary candidates whose f64
  distances differ below one f32 ulp are indistinguishable to any engine
  under the subsystem's own arithmetic contract -- holding the selection
  to strict f64 ordering would fail byte-correct results exactly when
  the measured recall is gated at 1.0;
* the **declared-precision** measurement widens the hit threshold by the
  per-row dot-form rounding band ``2B`` (``declared_band``, the same
  band the certificate reasons with) -- the recall-vs-bound measure for
  unrefined approximate rows, whose selection never claimed f64
  ordering.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def declared_band(points: np.ndarray,
                  queries: Optional[np.ndarray] = None,
                  precision: str = "f32") -> np.ndarray:
    """Per-query scoring-precision band ``2B`` of the dot-form route
    (topk.dot_error_bound -- the same band the certificate reasons
    with): the width within which blocked-matmul scores at the declared
    ``precision`` tier provably cannot order candidates.  Recall measured
    at the route's declared precision widens the hit threshold by this
    band -- bf16 rows measure against the bf16 band, so the measure and
    the certificate always reason with the SAME family (certificate
    soundness itself stays band-free: certified_recall never widens)."""
    from .topk import dot_error_bound

    p64 = points.astype(np.float64)  # kntpu-ok: wide-dtype -- oracle math: the band is a bound on f32 error, computed exactly
    q64 = p64 if queries is None else queries.astype(np.float64)  # kntpu-ok: wide-dtype -- oracle math
    qn = (q64 * q64).sum(axis=1)
    pn_max = float((p64 * p64).sum(axis=1).max()) if p64.size else 0.0
    return 2.0 * dot_error_bound(qn, pn_max, points.shape[1], precision)


def f64_kth(points: np.ndarray, k: int,
            queries: Optional[np.ndarray] = None,
            exclude: Optional[np.ndarray] = None,
            exclude_self: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Per-query (k-th true squared distance, available-neighbor count)
    in exact f64 -- the tie threshold of both recall measures.  Chunked
    brute force; fine to a few 10k points.  ``exclude`` masks one
    candidate column per query (self-exclusion at arbitrary indices);
    the default self-solve (``queries=None, exclude_self=True``) masks
    the diagonal."""
    p64 = points.astype(np.float64)  # kntpu-ok: wide-dtype -- oracle math: tie thresholds in exact f64
    q64 = p64 if queries is None else queries.astype(np.float64)  # kntpu-ok: wide-dtype -- oracle math
    if exclude is None and queries is None and exclude_self:
        exclude = np.arange(p64.shape[0])
    m = q64.shape[0]
    kth = np.empty((m,), np.float64)  # kntpu-ok: wide-dtype -- oracle math
    avail = np.empty((m,), np.int64)  # kntpu-ok: wide-dtype -- oracle math
    chunk = max(1, int(2.0e7) // max(1, p64.shape[0]))
    for s in range(0, m, chunk):
        q = q64[s:s + chunk]
        d2 = ((q[:, None, :] - p64[None, :, :]) ** 2).sum(-1)
        if exclude is not None:
            d2[np.arange(q.shape[0]), exclude[s:s + q.shape[0]]] = np.inf
        a = np.minimum(k, np.isfinite(d2).sum(1))
        avail[s:s + chunk] = a
        kth[s:s + chunk] = np.sort(d2, axis=1)[
            np.arange(q.shape[0]), np.maximum(a, 1) - 1]
    return kth, avail


def row_hits(points: np.ndarray, neighbors: np.ndarray,
             kth: np.ndarray,
             band: Optional[np.ndarray] = None,
             queries: Optional[np.ndarray] = None) -> np.ndarray:
    """Tie-aware per-row hit counts against precomputed ``kth``
    thresholds (module docstring has the full discipline)."""
    p64 = points.astype(np.float64)  # kntpu-ok: wide-dtype -- oracle math
    q64 = p64 if queries is None else queries.astype(np.float64)  # kntpu-ok: wide-dtype -- oracle math
    valid = neighbors >= 0
    c = p64[np.where(valid, neighbors, 0)]
    gd = ((q64[:, None, :] - c) ** 2).sum(-1)
    if band is not None:
        hit = gd <= (kth + band)[:, None]
    else:
        # f32-tie discipline: a pick tying the true kth at f32 resolution
        # is a valid boundary-group member under the engines' own f32
        # arithmetic contract
        hit = ((gd <= kth[:, None])
               | (gd.astype(np.float32) <= kth[:, None].astype(np.float32)))
    return (valid & hit).sum(axis=1)


def measured_recall(points: np.ndarray, neighbors: np.ndarray,
                    k: int, queries: Optional[np.ndarray] = None,
                    exclude_self: bool = True,
                    band: Optional[np.ndarray] = None) -> float:
    """Aggregate tie-aware recall@k vs the exact f64 oracle.  ``band``
    (e.g. ``declared_band``) switches from the band-free f32-tie measure
    to the route's declared-precision measure; an empty/neighborless
    cloud is vacuously 1.0."""
    exclude = (np.arange(points.shape[0])
               if queries is None and exclude_self else None)
    kth, avail = f64_kth(points, k, queries=queries, exclude=exclude,
                         exclude_self=False)
    hits = row_hits(points, neighbors, kth, band=band, queries=queries)
    total = int(avail.sum())
    return float(hits.sum()) / total if total else 1.0


def certified_recall(points: np.ndarray, neighbors: np.ndarray,
                     rows: np.ndarray, k: int) -> float:
    """Band-free recall restricted to ``rows`` (the certified-claim
    audit: a certified row below 1.0 is a SOUNDNESS failure, the exact
    shape the KNTPU_MXU_FAULT=drop-block self-test plants)."""
    q = points[rows]
    kth, avail = f64_kth(points, k, queries=q, exclude=rows,
                         exclude_self=False)
    hits = row_hits(points, neighbors[rows], kth, queries=q)
    total = int(avail.sum())
    return float(hits.sum()) / total if total else 1.0
