"""MXU scoring subsystem: blocked-matmul distances + bounded approximate
top-k + exact-certify refinement (DESIGN.md section 16).

The TPU's peak FLOP/s live in the MXU; this package recasts candidate
scoring as ``|q|^2 + |p|^2 - 2 QP^T`` blocked matmuls (TPU-KNN, arXiv
2206.14286) with the paper's in-register approximate top-k and its
recall-vs-speed bound surfaced as ``KnnConfig.recall_target``.  The core
is dimension-agnostic, which is what opens general-d point sets
(ROADMAP item 4): ``mxu.knn`` / ``mxu.solve_general`` accept ``(n, d)``
for any d; the grid routes keep their d=3 contract and refuse wider input
with a pointer here (io.validate_or_raise).

Exactness stays authoritative: every row carries a certification bit
proving (or declining to prove) that its approximate selection IS a true
top-k set despite dot-form rounding (topk.py has the bound), and
uncertified rows batch into the existing one-extra-sync exact brute
fallback -- at ``recall_target=1.0`` the finalized answer is
byte-identical to the exact elementwise path.

* :mod:`topk`   -- the recall bound, per-block keep counts, error bound,
  slot interleave (host math, no jax).
* :mod:`scorer` -- the shared fold + rescoring, the XLA blocked core, and
  the grid-fed per-class scorer the adaptive route dispatches to under
  ``KnnConfig.scorer='mxu'``.
* :mod:`kernel` -- the Pallas MXU kernel twin of the brute core (TPU /
  interpret; selection equality vs the XLA core is pinned in tier-1).
* :mod:`solve`  -- the brute/MXU route: ``solve_general`` (any d, recall
  knob, counted <= 2-sync finalize) and the ``knn`` convenience.

``python -m cuda_knearests_tpu.mxu`` runs the CPU smoke wired into
scripts/check.sh: the recall_target=1.0 byte-identity pin, a measured
recall-vs-bound check, and a general-d exactness check.
"""

from __future__ import annotations

from .solve import MxuResult, knn, parse_fault, solve_general
from .topk import BLOCK, per_block_m, recall_bound

__all__ = ["BLOCK", "MxuResult", "knn", "parse_fault", "per_block_m",
           "recall_bound", "solve_general"]
