"""MXU subsystem CPU smoke (scripts/check.sh, DESIGN.md section 16).

Three checks, one JSON line each, rc 1 on any failure:

  * **exactness pin** -- ``solve_general(recall_target=1.0, scorer='mxu')``
    must be BYTE-identical (ids and distances) to the exact elementwise
    path (``scorer='elementwise'``) on the 20k fixture
    (``KNTPU_MXU_SMOKE_N`` scales it down for constrained runners; the
    full-size pin also lives in tier-1, tests/test_mxu.py).
  * **recall bound** -- a clustered cloud at a sub-1.0 ``recall_target``
    with ``refine='none'``: the measured tie-aware recall vs the exact
    f64 oracle -- at the route's declared ``2B`` scoring precision, the
    fuzz comparator's discipline -- must meet the configured TPU-KNN
    bound, and every row whose certificate claims exactness must BE
    exact (band-free).
  * **general-d** -- a d=6 cloud at ``recall_target=1.0`` must match a
    host f64 brute-force oracle exactly (tie-aware) end to end.

Run:  JAX_PLATFORMS=cpu python -m cuda_knearests_tpu.mxu
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

# the ONE recall oracle (mxu/measure.py) -- re-exported here because the
# smoke predates the shared module and tests/bench historically imported
# the measures from this entry point
from .measure import certified_recall, declared_band, measured_recall

_certified_recall = certified_recall


def _row(name: str, ok: bool, **fields) -> bool:
    print(json.dumps({"check": name, "ok": bool(ok), **fields}), flush=True)
    return bool(ok)


def main() -> int:
    from ..io import generate_clustered, get_dataset
    from . import solve_general

    rc = 0

    # 1. the exactness pin: byte-identity at recall_target=1.0
    n_pin = int(os.environ.get("KNTPU_MXU_SMOKE_N", "20626"))
    pts = get_dataset("pts20K.xyz")
    if n_pin < pts.shape[0]:
        pts = np.ascontiguousarray(pts[:n_pin])
    k = 10
    a = solve_general(pts, k=k, recall_target=1.0, scorer="mxu")
    b = solve_general(pts, k=k, scorer="elementwise")
    ids_eq = bool(np.array_equal(a.neighbors, b.neighbors))
    d2_eq = bool(np.array_equal(a.dists_sq, b.dists_sq))
    if not _row("byte-identity", ids_eq and d2_eq, n=int(pts.shape[0]),
                k=k, ids_equal=ids_eq, dists_equal=d2_eq,
                uncert_count=int(a.uncert_count),
                backend=a.backend):
        rc = 1

    # 2. measured recall >= the configured TPU-KNN bound (approx mode),
    #    and certified rows are actually exact
    target = 0.75
    cl = generate_clustered(6000, seed=17)
    res = solve_general(cl, k=k, recall_target=target, refine="none")
    rec = measured_recall(cl, res.neighbors, k, band=declared_band(cl))
    cert_rows = np.nonzero(res.certified)[0]
    cert_ok = True
    if cert_rows.size:
        sub_rec = certified_recall(cl, res.neighbors, cert_rows, k)
        cert_ok = sub_rec >= 1.0
    if not _row("recall-bound", rec >= res.bound and cert_ok,
                recall_target=target, bound=round(res.bound, 6),
                measured=round(rec, 6), m=res.m, n_blocks=res.n_blocks,
                certified_fraction=round(float(res.certified.mean()), 4),
                certified_rows_exact=bool(cert_ok)):
        rc = 1

    # 3. general-d end to end (the d != 3 workload, ROADMAP item 4)
    rng = np.random.default_rng(23)
    d6 = (rng.random((2048, 6)) * 100.0).astype(np.float32)
    r6 = solve_general(d6, k=8, recall_target=1.0)
    rec6 = measured_recall(d6, r6.neighbors, 8)
    if not _row("general-d", rec6 >= 1.0, d=6, n=2048, k=8,
                measured=round(rec6, 6),
                certified=bool(r6.certified.all())):
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
