"""TPU-KNN approximate top-k: the bound math and the shared fold.

The selection scheme is the in-register approximate top-k of TPU-KNN
(arXiv 2206.14286, PAPERS.md): the candidate axis is partitioned into
128-lane *blocks* (the MXU/VPU tile width), each block keeps only its
ascending top-``m`` while its distance tile lives in registers, and the
final top-k runs on the surviving ``G * m`` pool.  With candidates spread
uniformly at random across ``L = G * m`` kept slots, the expected recall of
the true top-k is bounded below by

    E[recall@k] >= 1 - k * (k - 1) / (2 * L)

(the paper's bound; ``jax.lax.approx_max_k`` sizes its bins from the same
expression).  :func:`per_block_m` inverts it: the smallest per-block keep
count whose bound meets ``KnnConfig.recall_target``.  Candidate slots are
round-robin interleaved across blocks before scoring (the ``_pack_inputs``
trick) so spatially-adjacent candidates -- the near neighbors -- spread
evenly and the uniform-binning assumption is defensible on clustered data.

Exactness tier: the fold also emits a per-row **certification bit** that is
sound against the *true* (diff-arithmetic) distances, not just the
dot-form scores.  Let ``s(j) = |q|^2 + |p_j|^2 - 2 q.p_j`` be the f32
dot-form score and ``d(j)`` the true squared distance; catastrophic
cancellation bounds their gap by ``|s - d| <= B`` with
``B = O(eps32 * (|q|^2 + max|p|^2))`` (:func:`dot_error_bound`).  The fold
tracks, per row, the k-th selected score ``t`` and the smallest score the
selection *excluded* -- ``kplus = min(min_g rem_g, pool_(k+1))``, where
``rem_g`` is block g's smallest non-kept score (so every non-selected
candidate scores >= kplus).  A row certifies iff

    kplus >= t + 2 * B

which proves every excluded candidate's TRUE distance exceeds every
selected candidate's (d_excl >= kplus - B >= t + B >= d_sel), i.e. the
selected id set IS a true top-k set up to exact-distance ties.  Certified
rows are exact; uncertified rows carry correct-but-unproven approximations
and the refinement tier (api._finalize's batched brute fallback) resolves
them -- at ``recall_target=1.0`` (m = k: the fold is exhaustive) this makes
the final answer byte-identical to the exact elementwise path.

Host-only math here (no jax import): the jnp fold lives in scorer.py.
"""

from __future__ import annotations

import math

import numpy as np

#: Candidate-axis block width: the TPU lane count (one MXU tile edge).
BLOCK = 128

#: f32 unit roundoff.
_EPS32 = float(np.finfo(np.float32).eps)

#: Safety factor on the dot-form error bound: covers the handful of
#: rounding sites (two norms, the dot reduction, two adds) plus headroom
#: for XLA reassociation.  Deliberately generous -- an under-bound would
#: certify rows whose selection a rounding swap corrupted.
_ERR_SAFETY = 4.0

#: Scoring precisions the engines accept, in the order the docs list them.
#: "auto" is a config-layer alias (resolved before any engine sees it).
PRECISIONS = ("f32", "bf16")

#: Extra per-coordinate roundoff the SCORING precision adds on top of the
#: f32 pipeline.  f32 scoring adds nothing (the (d + 8) * eps32 term below
#: already covers it -- keeping the f32 bound bit-identical to its pre-tier
#: value, which the byte-identity pins rely on).  bf16 scoring rounds each
#: matmul input and each norm square to 8 mantissa bits: eps_bf16 = 2^-7.
#: Hardcoded (not np.finfo(bfloat16)) so this module stays numpy-only.
_SCORE_EPS = {"f32": 0.0, "bf16": 2.0 ** -7}

#: Rounding-site count for the reduced-precision terms: two input casts and
#: one product rounding per side of the matmul, plus the two norm squares
#: -- 6 sites, padded to 8 for slack before _ERR_SAFETY even applies.
_CAST_SITES = 8.0


def check_precision(precision: str) -> str:
    """Refuse unknown scoring precisions with a typed error.

    A typo must not silently score (or certify) at the wrong precision --
    the bound family below would pick a KeyError deep in jit tracing
    otherwise, far from the config that caused it.
    """
    if precision not in PRECISIONS:
        raise ValueError(  # kntpu-ok: bare-valueerror -- host-only module; config layer wraps with InvalidConfigError
            f"unknown precision {precision!r}; expected one of {PRECISIONS}")
    return precision


def bins_for(recall_target: float, k: int) -> int:
    """Kept-slot count L whose TPU-KNN bound meets ``recall_target``:
    L = ceil(k(k-1) / (2(1-r))).  Infinite (exhaustive) at r = 1.0."""
    r = float(recall_target)
    if k <= 1 or r >= 1.0:
        return k  # top-1 (or exact) needs no approximation slack
    return max(k, int(math.ceil(k * (k - 1) / (2.0 * (1.0 - r)))))


def per_block_m(recall_target: float, k: int, n_blocks: int) -> int:
    """Per-block keep count m for ``n_blocks`` candidate blocks.

    r = 1.0 keeps min(k, BLOCK) per block -- an m-of-min(k,128) fold is
    EXHAUSTIVE (a block holding more than m of the global top-k would have
    to hold more than min(k, 128) of them, impossible within one 128-lane
    block when m = min(k, 128)), so selection is exact by construction.
    Below 1.0, the smallest m whose L = m * n_blocks meets the bound; the
    floor ceil(k / n_blocks) keeps the pool wide enough to hold k at all.
    """
    n_blocks = max(1, int(n_blocks))
    cap = min(int(k), BLOCK)
    if float(recall_target) >= 1.0:
        return cap
    need = bins_for(recall_target, k)
    m = max(1, -(-need // n_blocks), -(-int(k) // n_blocks))
    return min(m, cap)


def recall_bound(k: int, n_blocks: int, m: int) -> float:
    """The proven expected-recall lower bound of an (n_blocks, m) fold:
    1.0 when the fold is exhaustive (m covers min(k, BLOCK)), else the
    TPU-KNN expression over L = m * n_blocks kept slots."""
    if m >= min(int(k), BLOCK) or k <= 1:
        return 1.0
    loss = k * (k - 1) / (2.0 * m * max(1, n_blocks))
    return max(0.0, 1.0 - loss)


def dot_error_bound(qn, pn_max, d: int, precision: str = "f32"):
    """Per-row upper bound B on |dot-form score - true squared distance|.

    The dot identity subtracts two O(|q|^2 + |p|^2) quantities to produce an
    O(d2) result: each f32 rounding site contributes up to eps32 times the
    LARGE operands, so the absolute error scales with the norms, not the
    distance.  (d + 8) counts the reduction depth (d-term dot product plus
    the norm sums and the final combine); _ERR_SAFETY covers reassociation.
    Works elementwise on arrays (qn per row, pn_max a scalar or row-shaped).

    Per-precision family: reduced-precision scoring keeps f32 ACCUMULATION
    (``preferred_element_type=f32`` on every MXU op), so the reduction-depth
    term stays at eps32 -- only the input casts and per-lane products round
    at the scoring precision.  Each such site errs by at most
    ``eps_prec * |q_i * p_i|`` and Cauchy-Schwarz folds the coordinate sums
    back into the same ``(qn + pn_max)`` envelope (``sum |q_i p_i| <=
    |q||p| <= (qn + pn_max) / 2``), giving the additive ``_CAST_SITES *
    eps_prec`` term.  For f32 the term is exactly 0.0, keeping this bound
    BIT-IDENTICAL to the pre-family value (the byte-identity pins depend on
    it); for bf16 the band widens ~465x at d=3, decertifying rows into the
    existing exact-fallback sync -- soundness is free, only the certified
    fraction moves.
    """
    check_precision(precision)
    return (_ERR_SAFETY * ((d + 8) * _EPS32 + _CAST_SITES * _SCORE_EPS[precision])
            * (qn + pn_max))


def interleave_slots(n_slots: int) -> np.ndarray:
    """Round-robin slot permutation: slot ``r * G + g -> lane g * BLOCK + r``
    (the `_pack_inputs` interleave).  Adjacent input slots -- spatially
    adjacent candidates under CSR packing or storage order -- land in
    DIFFERENT blocks, spreading every query's near neighbors evenly so no
    single block overflows its top-m (the uniform-binning assumption the
    recall bound rests on).  ``n_slots`` must be a BLOCK multiple.
    Returns the (n_slots,) i32 gather map: out[i] = in[perm[i]]."""
    if n_slots % BLOCK != 0:
        raise ValueError(f"n_slots={n_slots} is not a multiple of {BLOCK}")  # kntpu-ok: bare-valueerror -- internal layout invariant (callers pad), not user input
    g = n_slots // BLOCK
    # lane-major inverse of (r, g) -> (g, r): out[g*BLOCK + r] = in[r*g_ + g]
    return np.arange(n_slots, dtype=np.int32).reshape(
        BLOCK, g).T.reshape(-1)
