"""The brute/MXU solve route: general-d all-points kNN with a recall knob.

``solve_general`` is the route ROADMAP item 4 needed: points are an
``(n, d)`` array for ANY d >= 1 (the grid routes stay d=3 until the hash
generalizes -- io.validate_or_raise points general-d callers here).  Every
query scores against every stored point through the blocked-matmul MXU
core (scorer.py / kernel.py) with the TPU-KNN approximate top-k at
``recall_target``, per-row certification bits, and the same finalize
discipline as ``api._finalize``: ONE batched fetch of the selection
(ids + certificates; exact distances are a strict-IEEE host epilogue over
it, ``_host_rescore``), plus at most one more batched fetch when
uncertified rows resolve through the exact brute fallback -- the proven
``1 + fb <= 2`` host-sync window (analysis/syncflow.py, window
'mxu-brute').

``recall_target=1.0`` makes the fold exhaustive and the certificate
strict about dot-form rounding, so the finalized answer is byte-identical
to the exact elementwise path (certified rows re-score in the engine's
diff arithmetic; ambiguous rows take the same brute fallback both paths
share) -- pinned on the 20k fixture by tests/test_mxu.py.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import numpy as np

from ..obs import spans as _spans
from ..runtime import dispatch as _dispatch
from ..utils.memory import InvalidConfigError
from ..utils.profiling import annotate
from .scorer import FAULTS, _MXU_TILE_BYTES, solve_blocks_xla
from .topk import BLOCK, interleave_slots, per_block_m, recall_bound

_FAULT_ENV = "KNTPU_MXU_FAULT"


def parse_fault(spec: Optional[str] = None) -> Optional[str]:
    """The seeded-fault knob
    (``KNTPU_MXU_FAULT=drop-block|skip-certify|narrow-bound``); unknown
    values refuse loudly -- a typo'd fault must never silently run a clean
    campaign that 'proves' the detectors fire."""
    spec = os.environ.get(_FAULT_ENV, "") if spec is None else spec
    spec = (spec or "").strip()
    if not spec:
        return None
    if spec not in FAULTS:
        raise InvalidConfigError(
            f"unknown {_FAULT_ENV} value {spec!r}: expected one of {FAULTS}")
    return spec


@dataclasses.dataclass(frozen=True)
class MxuResult:
    """One brute/MXU solve's finalized answer + its approximation ledger.

    neighbors/dists are in ORIGINAL point indexing (the route has no grid
    permutation), rows ascending by (d2, id), -1/inf beyond the available
    neighbors; every row's distances flow through the one strict-IEEE
    host realization (``_host_rescore``), whichever engine selected it.
    ``certified`` marks rows whose selection was PROVEN a true top-k set
    (topk.py); after refinement every row is certified and
    ``uncert_count`` records how many needed the fallback.  ``bound`` is
    the proven expected-recall lower bound of the (n_blocks, m) fold the
    solve ran -- the number bench frontier rows stamp and the fuzz
    campaign asserts measured recall against."""

    neighbors: np.ndarray
    dists_sq: np.ndarray
    certified: np.ndarray
    uncert_count: int
    bound: float
    m: int
    n_blocks: int
    backend: str  # 'pallas' | 'xla' | 'elementwise'
    # scoring tier the selection ran at ('f32' | 'bf16'); certified rows
    # are exact at EVERY tier (the per-precision band, topk.py), the tier
    # only moves speed and the certified fraction.  Bench rows stamp it.
    precision: str = "f32"


def _pick_qc(c_pad: int) -> int:
    """Query-chunk width for the XLA core: bounds the (qc, C) score tile,
    8-aligned (sublane floor)."""
    qc = max(8, min(1024, _MXU_TILE_BYTES // max(1, 4 * c_pad)))
    return (qc // 8) * 8


def _host_rescore(points: np.ndarray, queries: np.ndarray,
                  sel_i: np.ndarray):
    """Exact diff-arithmetic distances + final (d2, id) ordering of a
    fetched selection -- the brute route's host epilogue.

    numpy elementwise ops are strict IEEE f32 at EVERY shape, unlike the
    XLA arithmetic they replace (the compiler strips optimization_barrier
    on CPU and reassociates the d-term accumulation shape-dependently --
    scorer.rescore_sorted's docstring has the measured case), so the
    rescored values land bit-for-bit on the engine's canonical
    subtract-square-accumulate sequence and the ``recall_target=1.0``
    byte-identity pin against ops.solve.brute_force_by_index holds.  Same
    zero-extra-sync pattern as the plane feed (DESIGN.md section 14):
    pure host work over the one already-fetched selection.

    Returns ((m, k) i32 ids ascending by (d2, id), INVALID_ID pads;
    (m, k) f32 d2, inf pads)."""
    valid = sel_i >= 0
    c = points[np.where(valid, sel_i, 0)]           # (m, k, d)
    d2 = np.zeros(sel_i.shape, np.float32)
    for ax in range(points.shape[1]):
        diff = queries[:, None, ax] - c[..., ax]
        d2 += diff * diff
    d2 = np.where(valid, d2, np.float32(np.inf)).astype(np.float32)
    ids = np.where(valid, sel_i, -1).astype(np.int32)
    # ascending (d2, id) per row -- the subsystem's canonical tie rule
    # (scorer._sort_pairs), which for the brute route coincides with the
    # elementwise path's first-seen storage order
    order = np.lexsort((ids, d2), axis=1)
    return (np.take_along_axis(ids, order, axis=1),
            np.take_along_axis(d2, order, axis=1))


def _use_kernel(c_pad: int, d_pad: int, k: int, m: int,
                interpret: bool) -> bool:
    from .kernel import kernel_fits

    on_tpu = jax.devices()[0].platform == "tpu"
    return (on_tpu or interpret) and kernel_fits(c_pad, d_pad, k, m)


def solve_general(points, k: int = 10, recall_target: float = 1.0,
                  exclude_self: bool = True, refine: str = "brute",
                  queries=None, interpret: bool = False,
                  scorer: str = "mxu", precision: str = "auto",
                  query_chunk: Optional[int] = None) -> MxuResult:
    """All-points (or external-``queries``) kNN through the brute/MXU route.

    ``scorer`` picks the selection engine: ``'mxu'`` (default -- the
    blocked-matmul core this route exists for), ``'elementwise'`` (the
    exact diff-arithmetic brute selection, ops/solve.brute_force_by_index
    -- the byte-identity baseline), or ``'auto'`` (config.resolve_scorer's
    rule).  EVERY output row, whichever engine selected it, realizes its
    distances and (d2, id) ordering through the one strict-IEEE host
    epilogue (``_host_rescore``), so ``scorer='mxu', recall_target=1.0``
    is byte-identical to ``scorer='elementwise'`` by construction -- the
    scorer knob changes selection only, never realization.

    ``refine='brute'`` (default) resolves uncertified rows exactly through
    the batched diff-arithmetic fallback (ops/solve.brute_force_by_index
    for the self-solve, the coords twin for external queries) -- one extra
    batched fetch, never a sync storm.  ``refine='none'`` returns the raw
    approximation with its certification bits -- what the fuzz --approx
    campaign measures recall bounds against and what ``bench.py
    --frontier`` times as the approximate serving mode.

    ``precision`` picks the MXU scoring tier (``'f32'`` | ``'bf16'`` |
    ``'auto'`` -> f32, config.resolve_precision): bf16 casts the matmul
    inputs with f32 accumulation and certifies against the wider
    per-precision band (topk.dot_error_bound), so certified rows stay
    exact and boundary rows decertify into the same fallback.
    ``query_chunk`` overrides the XLA core's auto-sized query chunk (the
    tuner's knob; 8-aligned, clamped to the tile budget); None keeps
    ``_pick_qc``'s sizing.
    """
    from ..config import resolve_precision, resolve_scorer
    from ..io import validate_or_raise

    if refine not in ("brute", "none"):
        raise InvalidConfigError(
            f"unknown refine {refine!r}: 'brute' or 'none'")
    scorer = resolve_scorer(scorer, recall_target, precision)
    try:
        precision = resolve_precision(precision, scorer)
    except ValueError as e:
        raise InvalidConfigError(str(e)) from e
    points = validate_or_raise(points, k=k, dims=None)
    n, d = points.shape
    self_solve = queries is None
    if self_solve:
        queries_v = points
    else:
        queries_v = validate_or_raise(queries, k=k, dims=None,
                                      what="queries")
        if queries_v.shape[1] != d:
            from ..utils.memory import InvalidShapeError

            raise InvalidShapeError(
                f"queries are (m, {queries_v.shape[1]}) but the stored "
                f"points are (n, {d}) (input contract: one d per problem)")
        exclude_self = False
    m_q = queries_v.shape[0]
    if n == 0 or m_q == 0:
        return MxuResult(
            neighbors=np.full((m_q, k), -1, np.int32),
            dists_sq=np.full((m_q, k), np.inf, np.float32),
            certified=np.ones((m_q,), bool), uncert_count=0, bound=1.0,
            m=0, n_blocks=0, backend="xla", precision=precision)

    if scorer == "elementwise":
        # the exact elementwise selection (THE baseline the MXU engine's
        # recall_target=1.0 byte-identity is pinned against): one brute
        # launch, ids fetched in ONE sync, distances realized by the same
        # host epilogue as every other row of this route
        from ..ops.query import brute_force_by_coords
        from ..ops.solve import brute_force_by_index

        pts_dev = _dispatch.stage(points)  # syncflow: mxu-stage
        if self_solve:
            b_i, _b_d = brute_force_by_index(
                pts_dev, _dispatch.stage(np.arange(n, dtype=np.int32)),  # syncflow: mxu-stage
                k, exclude_self)
        else:
            b_i, _b_d = brute_force_by_coords(
                pts_dev, _dispatch.stage(queries_v), k)  # syncflow: mxu-stage
        b_i = np.asarray(_dispatch.fetch(b_i))  # syncflow: mxu-final
        ids, d2 = _host_rescore(points, queries_v, b_i)
        return MxuResult(neighbors=ids, dists_sq=d2,
                         certified=np.ones((m_q,), bool), uncert_count=0,
                         bound=1.0, m=0, n_blocks=0, backend="elementwise",
                         precision="f32")  # exact diff arithmetic: f32 tier

    fault = parse_fault()
    c_pad = -(-n // BLOCK) * BLOCK
    g = c_pad // BLOCK
    m = per_block_m(recall_target, k, g)
    bound = recall_bound(k, g, m)

    # host-side interleave + padding: adjacent storage slots spread across
    # blocks (topk.interleave_slots) so the recall bound's uniform-binning
    # assumption survives spatially-sorted inputs; pads carry id -1 and
    # zero coords (masked by id inside the fold -- FAR coords would
    # overflow the dot form to inf - inf = NaN)
    il = interleave_slots(c_pad)
    pts_pad = np.zeros((c_pad, d), np.float32)
    pts_pad[:n] = points
    cid = np.full((c_pad,), -1, np.int32)
    cid[:n] = np.arange(n, dtype=np.int32)
    pts_il, cid_il = pts_pad[il], cid[il]

    # the VMEM gate sees the PADDED width: the kernel stages (c_pad, d_pad)
    # candidate arrays, so judging fit at the raw d under-counts the
    # resident set for d > 8 off the 8-sublane lattice
    d_pad = -(-d // 8) * 8
    use_kernel = fault is None and _use_kernel(
        c_pad, d_pad, k, m, interpret)
    if use_kernel:
        from .kernel import select_pallas

        qp = np.zeros((-(-m_q // BLOCK) * BLOCK, d_pad), np.float32)
        qp[:m_q, :d] = queries_v
        pil = np.zeros((c_pad, d_pad), np.float32)
        pil[:, :d] = pts_il
        qid = np.full((qp.shape[0],), -1, np.int32)
        if exclude_self:
            qid[:m_q] = np.arange(m_q, dtype=np.int32)
        # named profiler scope: the blocked-matmul selection shows as
        # 'kntpu:mxu-select' in jax.profiler traces (and as a phase span
        # in the kntpu-trace timeline) instead of anonymous jit regions
        with _spans.span("solve.mxu.select", backend="pallas",
                         n_blocks=g, m=m), annotate("kntpu:mxu-select"):
            sel_i, sel_s, cert_d = select_pallas(
                _dispatch.stage(qp), _dispatch.stage(qid),  # syncflow: mxu-stage
                _dispatch.stage(pil), _dispatch.stage(cid_il),  # syncflow: mxu-stage
                k, m, d, exclude_self, interpret, precision)
        sel_i, cert_d = sel_i[:m_q], cert_d[:m_q]
        backend = "pallas"
    else:
        if query_chunk is not None and int(query_chunk) > 0:
            # tuner override: 8-aligned (sublane floor), capped at the
            # auto-sizer's tile-budget chunk so a stale plan can't blow
            # the score-tile budget on a larger problem
            qc = max(8, min((int(query_chunk) // 8) * 8, _pick_qc(c_pad)))
        else:
            qc = _pick_qc(c_pad)
        mq_pad = -(-m_q // qc) * qc
        qpad = np.zeros((mq_pad, d), np.float32)
        qpad[:m_q] = queries_v
        qid = np.full((mq_pad,), -1, np.int32)
        if exclude_self:
            qid[:m_q] = np.arange(m_q, dtype=np.int32)
        with _spans.span("solve.mxu.select", backend="xla",
                         n_blocks=g, m=m), annotate("kntpu:mxu-select"):
            sel_i, _sel_s, cert_d = solve_blocks_xla(
                _dispatch.stage(pts_il), _dispatch.stage(cid_il),  # syncflow: mxu-stage
                _dispatch.stage(qpad), _dispatch.stage(qid),  # syncflow: mxu-stage
                k, m, exclude_self, qc, fault, precision)
        sel_i, cert_d = sel_i[:m_q], cert_d[:m_q]
        backend = "xla"

    # ONE batched readback of the selection -- the mxu-brute window's
    # single sync; the exact distances are a host epilogue over it
    ids_sel, cert = _dispatch.fetch(sel_i, cert_d)  # syncflow: mxu-final
    with _spans.span("solve.mxu.rescore", rows=m_q):
        ids, d2 = _host_rescore(points, queries_v, np.asarray(ids_sel))
    cert = np.array(cert)
    n_unc = int((~cert).sum())
    if refine == "brute" and n_unc:
        from ..api import _pad_pow2
        from ..ops.query import brute_force_by_coords
        from ..ops.solve import brute_force_by_index

        with _spans.span("solve.mxu.refine", rows=n_unc), \
                annotate("kntpu:mxu-refine"):
            bad = np.nonzero(~cert)[0].astype(np.int32)
            pts_dev = _dispatch.stage(points)  # syncflow: mxu-fallback-stage
            if self_solve:
                q_idx = _pad_pow2(bad, fill=-1)
                b_i, _b_d = brute_force_by_index(
                    pts_dev, _dispatch.stage(q_idx), k, exclude_self)  # syncflow: mxu-fallback-stage
                b_i = np.asarray(_dispatch.fetch(b_i))  # syncflow: mxu-fallback
                sel = q_idx >= 0
                rows = q_idx[sel]
                r_ids, r_d2 = _host_rescore(points, queries_v[rows],
                                            b_i[sel])
            else:
                b_i, _b_d = brute_force_by_coords(
                    pts_dev, _dispatch.stage(queries_v[bad]), k)  # syncflow: mxu-fallback-stage
                b_i = np.asarray(_dispatch.fetch(b_i))  # syncflow: mxu-fallback
                rows = bad
                r_ids, r_d2 = _host_rescore(points, queries_v[rows], b_i)
            # fallback rows land through the SAME realization as
            # certified rows -- one canonical (d2, id) form for every row
            ids[rows] = r_ids
            d2[rows] = r_d2
            cert[bad] = True
    return MxuResult(neighbors=ids, dists_sq=d2, certified=cert,
                     uncert_count=n_unc, bound=bound, m=m, n_blocks=g,
                     backend=backend, precision=precision)


def knn(points, k: int = 10, recall_target: float = 1.0) -> np.ndarray:
    """One-call convenience (the general-d twin of api.knn): exact (or
    recall-bounded approximate, with uncertified rows refined exactly)
    all-points kNN in original indexing."""
    return solve_general(points, k=k,
                         recall_target=recall_target).neighbors
