"""ctypes binding to the native C++ kd-tree oracle (oracle/kd_tree.cpp).

Reference parity (C9): the exact CPU oracle the differential tests compare the
TPU engine against, playing the role the reference's KdTree plays in its test
(/root/reference/test_knearests.cu:194-232).  Builds the shared library on
demand via ``make -C oracle``; if no C++ toolchain is available, a pure-numpy
brute-force fallback keeps the differential tests runnable (slower, same
semantics).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ORACLE_DIR = os.path.join(_REPO_ROOT, "oracle")
_LIB_PATH = os.path.join(_ORACLE_DIR, "liboracle.so")
_lock = threading.Lock()
# None = not attempted; False = attempted and failed (don't re-run make);
# CDLL = loaded.
_lib: "ctypes.CDLL | bool | None" = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib or None
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(["make", "-C", _ORACLE_DIR, "-s"], check=True,  # kntpu-ok: blocking-under-lock -- once-only build: concurrent loaders MUST wait here (releasing would race parallel makes on the same .so); the False cache makes it once-ever
                               capture_output=True)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.kdt_build.restype = ctypes.c_void_p
            lib.kdt_build.argtypes = [ctypes.POINTER(ctypes.c_float),
                                      ctypes.c_int64]
            lib.kdt_free.argtypes = [ctypes.c_void_p]
            lib.kdt_num_nodes.restype = ctypes.c_int64
            lib.kdt_num_nodes.argtypes = [ctypes.c_void_p]
            lib.kdt_knn.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float)]
            if hasattr(lib, "kdt_knn_all"):  # absent in a stale pre-r5 .so
                lib.kdt_knn_all.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_float)]
        except Exception:  # noqa: BLE001 -- any load failure (no toolchain,
            # stale/wrong-arch .so, missing symbol) downgrades to the numpy
            # brute fallback: same semantics, slower -- never an error.  The
            # False is cached so a failing `make` isn't re-spawned per oracle.
            _lib = False
            return None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class KdTreeOracle:
    """Exact k-NN oracle over a fixed point set.

    Query semantics match the reference oracle: the query point is not excluded
    unless an exclude id is given (the reference test queries k+1 and drops the
    self hit, test_knearests.cu:205-211).
    """

    def __init__(self, points: np.ndarray):
        self.points = np.ascontiguousarray(points, dtype=np.float32)
        if self.points.ndim != 2 or self.points.shape[1] != 3:
            raise ValueError("points must be (n, 3)")
        self._lib = _load()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.kdt_build(
                self.points.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self.points.shape[0])

    def __del__(self):
        if getattr(self, "_handle", None) and self._lib is not None:
            self._lib.kdt_free(self._handle)
            self._handle = None

    def knn(self, queries: np.ndarray, k: int,
            exclude_ids: Optional[np.ndarray] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
        """(nq, k) nearest ids + squared distances, ascending; -1/inf padding."""
        queries = np.ascontiguousarray(queries, dtype=np.float32)
        nq = queries.shape[0]
        out_ids = np.empty((nq, k), dtype=np.int32)
        out_d2 = np.empty((nq, k), dtype=np.float32)
        if self._handle is not None:
            excl = None
            if exclude_ids is not None:
                excl = np.ascontiguousarray(exclude_ids, dtype=np.int32)
                ep = excl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            else:
                ep = None
            self._lib.kdt_knn(
                self._handle,
                queries.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                nq, k, ep,
                out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_d2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return out_ids, out_d2
        return self._brute(queries, k, exclude_ids)

    def knn_all_points(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """All-points self-query with self excluded by index -- the oracle side
        of the differential test (reference: test_knearests.cu:203-212).

        Uses the native tree-order batch entry point when available:
        iterating queries in tree order keeps nearby queries' shared
        descent paths hot in cache (same results, measured faster than the
        original-order batch)."""
        n = self.points.shape[0]
        if self._handle is not None and hasattr(self._lib, "kdt_knn_all"):
            out_ids = np.empty((n, k), dtype=np.int32)
            out_d2 = np.empty((n, k), dtype=np.float32)
            self._lib.kdt_knn_all(
                self._handle, k,
                out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_d2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return out_ids, out_d2
        excl = np.arange(n, dtype=np.int32)
        return self.knn(self.points, k, exclude_ids=excl)

    def _brute(self, queries, k, exclude_ids, chunk: int = 512):
        n = self.points.shape[0]
        nq = queries.shape[0]
        out_ids = np.full((nq, k), -1, np.int32)
        out_d2 = np.full((nq, k), np.inf, np.float32)
        for s in range(0, nq, chunk):
            e = min(s + chunk, nq)
            q = queries[s:e]
            d2 = ((q[:, None, :] - self.points[None, :, :]) ** 2).sum(-1)
            if exclude_ids is not None:
                rows = np.arange(e - s)
                ex = exclude_ids[s:e]
                ok = ex >= 0
                d2[rows[ok], ex[ok]] = np.inf
            kk = min(k, n)
            part = np.argpartition(d2, kk - 1, axis=1)[:, :kk]
            pd = np.take_along_axis(d2, part, axis=1)
            order = np.argsort(pd, axis=1, kind="stable")
            ids = np.take_along_axis(part, order, axis=1)
            d2s = np.take_along_axis(pd, order, axis=1)
            good = np.isfinite(d2s)
            out_ids[s:e, :kk] = np.where(good, ids, -1)
            out_d2[s:e, :kk] = np.where(good, d2s, np.inf)
        return out_ids, out_d2


# -- friends-of-friends oracle (cluster/, DESIGN.md section 14) ---------------
#
# The CPU reference the FoF differential tests and the fuzz --fof campaign
# compare the grid engine against: a classic path-compressed union-find over
# exact f64 fixed-radius pairs.  Because the engine scores pairs in f32, a
# pair whose true distance sits within the f32 rounding band of the linking
# radius may legally link either way -- so the oracle exposes TWO partitions
# (mandatory = pairs provably inside the radius, allowed = pairs possibly
# inside), and the tie-aware check (cluster/compare.py) requires the engine
# partition to lie between them in the refinement lattice.


class UnionFind:
    """Array union-find with path compression + union by size (host)."""

    def __init__(self, n: int):
        self.parent = np.arange(n, dtype=np.int64)  # kntpu-ok: wide-dtype -- host index arithmetic, never staged
        self.size = np.ones(n, dtype=np.int64)      # kntpu-ok: wide-dtype -- host index arithmetic, never staged

    def find(self, i: int) -> int:
        p = self.parent
        root = i
        while p[root] != root:
            root = p[root]
        while p[i] != root:  # path compression
            p[i], i = root, p[i]
        return int(root)

    def union(self, i: int, j: int) -> None:
        ri, rj = self.find(i), self.find(j)
        if ri == rj:
            return
        if self.size[ri] < self.size[rj]:
            ri, rj = rj, ri
        self.parent[rj] = ri
        self.size[ri] += self.size[rj]

    def canonical_labels(self) -> np.ndarray:
        """(n,) i32 labels: every member carries the MINIMUM member id of
        its component (the engine's canonicalization contract)."""
        n = self.parent.shape[0]
        roots = np.fromiter((self.find(i) for i in range(n)),
                            dtype=np.int64, count=n)  # kntpu-ok: wide-dtype -- host index arithmetic, never staged
        mins = np.full(n, n, dtype=np.int64)          # kntpu-ok: wide-dtype -- host index arithmetic, never staged
        np.minimum.at(mins, roots, np.arange(n))
        return mins[roots].astype(np.int32)


def _fof_thresholds(b: float, band: float):
    """(lo, hi) squared-distance thresholds bracketing the engine's f32
    edge predicate ``d2_f32 <= f32(b)^2``: below ``lo`` a pair MUST link,
    above ``hi`` it MUST NOT, in between it may do either.  ``band`` is
    the absolute slack in squared-distance units (callers derive it from
    the f32 rounding model; 0.0 = the exact radius)."""
    b2 = float(np.float64(b) ** 2)  # kntpu-ok: wide-dtype -- exact host threshold arithmetic, never staged
    return max(b2 - band, 0.0), b2 + band


def _pairs_within(points: np.ndarray, hi: float, chunk: int = 1024):
    """All unique pairs (i < j) with f64 squared distance <= ``hi``.
    Returns (pairs (E, 2) i64, d2 (E,) f64).  Chunked O(n^2) host brute
    force -- the oracle is exact, not fast (fuzz cases are small)."""
    pts = np.asarray(points, np.float64)  # kntpu-ok: wide-dtype -- exact oracle distances, host-only, never staged
    n = pts.shape[0]
    out_p, out_d = [], []
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        d2 = ((pts[s:e, None, :] - pts[None, :, :]) ** 2).sum(-1)
        ii, jj = np.nonzero(d2 <= hi)
        keep = (ii + s) < jj  # unique pairs, no self-pairs
        out_p.append(np.stack([ii[keep] + s, jj[keep]], axis=1))
        out_d.append(d2[ii[keep], jj[keep]])
    if not out_p:
        return (np.empty((0, 2), np.int64), np.empty((0,), np.float64))  # kntpu-ok: wide-dtype -- exact oracle distances, host-only, never staged
    return np.concatenate(out_p), np.concatenate(out_d)


def fof_oracle(points: np.ndarray, b: float, band: float = 0.0):
    """(mandatory_labels, allowed_labels): canonical min-id FoF labelings
    under the two bracketing edge sets (see _fof_thresholds).  With
    ``band=0`` the two coincide: the exact-f64 FoF partition at radius b.

    ``allowed`` unions EVERY pair the f32 engine could have linked, so any
    engine component must lie inside one allowed component; ``mandatory``
    unions only pairs the engine must have linked, so every mandatory
    component must carry one engine label.  cluster/compare.py checks both
    inclusions plus the canonicalization contract."""
    points = np.asarray(points, np.float32)
    n = points.shape[0]
    uf_m, uf_a = UnionFind(n), UnionFind(n)
    if n == 0:
        return (np.empty((0,), np.int32), np.empty((0,), np.int32))
    lo, hi = _fof_thresholds(b, band)
    pairs, d2 = _pairs_within(points, hi)
    for (i, j), d in zip(pairs, d2):
        uf_a.union(int(i), int(j))
        if d <= lo:
            uf_m.union(int(i), int(j))
    return uf_m.canonical_labels(), uf_a.canonical_labels()
