"""ctypes binding to the native C++ kd-tree oracle (oracle/kd_tree.cpp).

Reference parity (C9): the exact CPU oracle the differential tests compare the
TPU engine against, playing the role the reference's KdTree plays in its test
(/root/reference/test_knearests.cu:194-232).  Builds the shared library on
demand via ``make -C oracle``; if no C++ toolchain is available, a pure-numpy
brute-force fallback keeps the differential tests runnable (slower, same
semantics).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ORACLE_DIR = os.path.join(_REPO_ROOT, "oracle")
_LIB_PATH = os.path.join(_ORACLE_DIR, "liboracle.so")
_lock = threading.Lock()
# None = not attempted; False = attempted and failed (don't re-run make);
# CDLL = loaded.
_lib: "ctypes.CDLL | bool | None" = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib or None
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(["make", "-C", _ORACLE_DIR, "-s"], check=True,
                               capture_output=True)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.kdt_build.restype = ctypes.c_void_p
            lib.kdt_build.argtypes = [ctypes.POINTER(ctypes.c_float),
                                      ctypes.c_int64]
            lib.kdt_free.argtypes = [ctypes.c_void_p]
            lib.kdt_num_nodes.restype = ctypes.c_int64
            lib.kdt_num_nodes.argtypes = [ctypes.c_void_p]
            lib.kdt_knn.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float)]
            if hasattr(lib, "kdt_knn_all"):  # absent in a stale pre-r5 .so
                lib.kdt_knn_all.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_float)]
        except Exception:  # noqa: BLE001 -- any load failure (no toolchain,
            # stale/wrong-arch .so, missing symbol) downgrades to the numpy
            # brute fallback: same semantics, slower -- never an error.  The
            # False is cached so a failing `make` isn't re-spawned per oracle.
            _lib = False
            return None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class KdTreeOracle:
    """Exact k-NN oracle over a fixed point set.

    Query semantics match the reference oracle: the query point is not excluded
    unless an exclude id is given (the reference test queries k+1 and drops the
    self hit, test_knearests.cu:205-211).
    """

    def __init__(self, points: np.ndarray):
        self.points = np.ascontiguousarray(points, dtype=np.float32)
        if self.points.ndim != 2 or self.points.shape[1] != 3:
            raise ValueError("points must be (n, 3)")
        self._lib = _load()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.kdt_build(
                self.points.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self.points.shape[0])

    def __del__(self):
        if getattr(self, "_handle", None) and self._lib is not None:
            self._lib.kdt_free(self._handle)
            self._handle = None

    def knn(self, queries: np.ndarray, k: int,
            exclude_ids: Optional[np.ndarray] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
        """(nq, k) nearest ids + squared distances, ascending; -1/inf padding."""
        queries = np.ascontiguousarray(queries, dtype=np.float32)
        nq = queries.shape[0]
        out_ids = np.empty((nq, k), dtype=np.int32)
        out_d2 = np.empty((nq, k), dtype=np.float32)
        if self._handle is not None:
            excl = None
            if exclude_ids is not None:
                excl = np.ascontiguousarray(exclude_ids, dtype=np.int32)
                ep = excl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            else:
                ep = None
            self._lib.kdt_knn(
                self._handle,
                queries.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                nq, k, ep,
                out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_d2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return out_ids, out_d2
        return self._brute(queries, k, exclude_ids)

    def knn_all_points(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """All-points self-query with self excluded by index -- the oracle side
        of the differential test (reference: test_knearests.cu:203-212).

        Uses the native tree-order batch entry point when available:
        iterating queries in tree order keeps nearby queries' shared
        descent paths hot in cache (same results, measured faster than the
        original-order batch)."""
        n = self.points.shape[0]
        if self._handle is not None and hasattr(self._lib, "kdt_knn_all"):
            out_ids = np.empty((n, k), dtype=np.int32)
            out_d2 = np.empty((n, k), dtype=np.float32)
            self._lib.kdt_knn_all(
                self._handle, k,
                out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_d2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return out_ids, out_d2
        excl = np.arange(n, dtype=np.int32)
        return self.knn(self.points, k, exclude_ids=excl)

    def _brute(self, queries, k, exclude_ids, chunk: int = 512):
        n = self.points.shape[0]
        nq = queries.shape[0]
        out_ids = np.full((nq, k), -1, np.int32)
        out_d2 = np.full((nq, k), np.inf, np.float32)
        for s in range(0, nq, chunk):
            e = min(s + chunk, nq)
            q = queries[s:e]
            d2 = ((q[:, None, :] - self.points[None, :, :]) ** 2).sum(-1)
            if exclude_ids is not None:
                rows = np.arange(e - s)
                ex = exclude_ids[s:e]
                ok = ex >= 0
                d2[rows[ok], ex[ok]] = np.inf
            kk = min(k, n)
            part = np.argpartition(d2, kk - 1, axis=1)[:, :kk]
            pd = np.take_along_axis(d2, part, axis=1)
            order = np.argsort(pd, axis=1, kind="stable")
            ids = np.take_along_axis(part, order, axis=1)
            d2s = np.take_along_axis(pd, order, axis=1)
            good = np.isfinite(d2s)
            out_ids[s:e, :kk] = np.where(good, ids, -1)
            out_d2[s:e, :kk] = np.where(good, d2s, np.inf)
        return out_ids, out_d2
