"""Runtime-first configuration for the TPU kNN engine.

The reference keeps its whole configuration in compile-time macros
(``/root/reference/params.h:3-6``: ``DEFAULT_NB_PLANES 50`` = k baked into kernel
shared-memory shapes, ``POINTS_PER_BLOCK 32``) plus hard-coded grid constants inside
``kn_prepare`` (``/root/reference/knearests.cu:249,254``: density target 3.1 points
per cell, ring budget ``KN_global_stack_size = 16``).  Here every one of those knobs
is a first-class runtime parameter; ``k`` and the tile sizes are *static for a given
compile* (XLA needs static shapes) but freely chosen per problem.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


# The reference's domain contract: all points must lie in [0, 1000]^3
# (/root/reference/knearests.cu:21 "it is supposed that all points fit in range
# [0,1000]^3").  We keep the same contract; io.normalize_points enforces it.
DOMAIN_SIZE = 1000.0

# Average points-per-cell target used to size the grid, same constant as the
# reference (/root/reference/knearests.cu:249: dim = (N/3.1)^(1/3)).
DEFAULT_CELL_DENSITY = 3.1

# Default k matches the reference's DEFAULT_NB_PLANES (/root/reference/params.h:4).
DEFAULT_K = 50

# Default entry cap of the process-wide executable cache
# (runtime.dispatch.EXEC_CACHE).  A long-lived serving daemon compiles one
# executable per (route, capacity-bucket, k) signature; the cap bounds the
# cache's footprint with LRU eviction and the KNTPU_EXEC_CACHE_CAP env knob
# overrides it (DESIGN.md section 13).
DEFAULT_EXEC_CACHE_ENTRIES = 64

# Default entry cap of the tuned-plan store (tune/store.py), the
# ExecutableCache's disk-persisted sibling: one entry per
# (device kind, problem signature) the autotuner has searched.  LRU-bounded
# for the same reason the exec cache is; KNTPU_TUNE_CACHE_CAP overrides
# (DESIGN.md section 21).
DEFAULT_TUNE_CACHE_ENTRIES = 64


def grid_dim_for(n_points: int, density: float = DEFAULT_CELL_DENSITY) -> int:
    """Cells per axis for a cubic grid with ~`density` points per cell.

    Mirrors /root/reference/knearests.cu:249-252 (``round((N/3.1)^(1/3))``) but
    without the reference's hard ``dim >= 16`` exit (knearests.cu:254-258): small
    point sets simply get a small grid (min 1 cell per axis).
    """
    return max(1, int(round((n_points / density) ** (1.0 / 3.0))))


def default_ring_radius(k: int, density: float = DEFAULT_CELL_DENSITY) -> int:
    """Ring radius (in cells) expected to certify most queries for a given k.

    The expected k-th neighbor radius for a uniform point process with `density`
    points per cell of width w is ``w * (3k / (4 pi density))^(1/3)``.  A query in
    the interior of a supercell dilated by R cells is certified once its k-th
    distance is below its margin to the dilated box, which is at least R cell
    widths.  One extra cell of slack keeps the uncertified-fallback fraction tiny.
    """
    r_expect = (3.0 * k / (4.0 * math.pi * density)) ** (1.0 / 3.0)
    return max(1, int(math.ceil(r_expect)) + 1)


@dataclasses.dataclass(frozen=True)
class KnnConfig:
    """All tunables of the engine in one place (reference analog: params.h).

    Attributes:
      k: neighbors per query (reference: DEFAULT_NB_PLANES=50, compile-time only).
      density: grid sizing target, avg points/cell (reference: 3.1 hard-coded).
      ring_radius: candidate dilation radius in cells around each supercell; the
        functional analog of the reference's ring-expanding traversal budget
        (knearests.cu:254 Nmax=16).  None -> derived from k via
        default_ring_radius().
      supercell: query-tile side length in cells.  Queries in the same supercell
        share one gathered candidate set -- this is the TPU replacement for the
        reference's one-thread-per-point divergent traversal (knearests.cu:93-148).
      sc_batch: how many supercells one jitted chunk processes on the XLA
        backend's lax.scan (bounds that path's peak memory).  The pallas
        backend instead packs the whole schedule into one kernel launch whose
        per-program footprint is the VMEM tile -- there sc_batch only shapes
        the schedule arrays, and peak HBM for the gathered pack grows with the
        problem, gated by pallas_fits, not by sc_batch.
      dist_method: 'diff' = sum((a-b)^2), identical arithmetic to the oracle and to
        the reference (knearests.cu:125) so single-chip results match exactly;
        'dot' = |a|^2+|b|^2-2ab via batched matmul (XLA backend only -- with a
        3-wide contraction the MXU is ~2% utilized and measured slower than
        the VPU diff path; the Pallas kernel always uses 'diff').
      exclude_self: drop the query point itself *by storage index*, matching the
        reference's ``if (ptr == point_in) continue`` (knearests.cu:123) --
        coordinate duplicates of the query are still reported.
      fallback: resolve uncertified queries exactly by tiled brute force ('brute'),
        or leave them best-effort ('none').  With 'none', kernel='blocked'/'auto'
        is forced to 'kpass' (see effective_kernel): a blocked-kernel deficit
        row loses its trailing entries outright (INVALID_ID/inf) where kpass
        returns a near-correct best-effort neighbor, so without the exact
        fallback to resolve deficits the blocked body would be a silent
        per-row quality regression.
      backend: 'pallas' = fused VMEM kernel (ops/pallas_solve.py), 'xla' = pure
        XLA supercell scan (ops/solve.py), 'auto' = pallas on TPU when the tile
        fits VMEM, else xla.  'oracle' = answer through the native C++ kd-tree
        (the reference's own CPU path promoted to a first-class engine): exact
        by construction, all rows certified, and the fastest exact CPU route
        (3-5x the grid's dense route on the 900k north star; ~5x after the
        round-5 tree-order layout) -- the right
        choice on accelerator-less hosts; no accelerator involvement at all.
      interpret: run Pallas kernels in interpreter mode (CPU testing).
      adaptive: partition supercells into per-radius capacity classes sized
        from local ring occupancy (ops/adaptive.py) -- the planner analog of
        the reference's per-query ring walk (knearests.cu:113-136).  Applies
        to the single-chip solve when backend is 'auto'/'pallas' and
        dist_method is 'diff'; dense classes that exceed VMEM stream through
        a memory-bounded merge instead of demoting the whole solve.
      max_classes: cap on adaptive capacity classes (one compiled launch each).
      stream_tile: candidate-axis tile of the streamed (non-kernel) class
        solver; bounds its peak memory independently of ccap.
      epilogue: how raw per-class solver outputs become the final per-query
        (n, k) rows.  'gather' = the round-5 path: per-class transpose of the
        raw (Sc, k, qcap) kernel layout to row-major, one concatenation, one
        contiguous per-point row gather (AdaptivePlan.inv_row).  'scatter' =
        the kernel itself emits row-major (qsub, k) blocks at data-dependent
        output offsets (scalar-prefetched block maps,
        pallas_solve._pallas_topk_rows), and each class's rows scatter
        straight into the preallocated final buffer through its prepare-time
        forward row map (ClassPlan.tgt) -- no transpose pass, no row-major
        concatenation, no separate gather program; the epilogue stops
        existing as a standalone phase (DESIGN.md section 2c; the r5 phase
        table put the standalone epilogue at 51.5% of the on-chip solve).
        'auto' = scatter on kernel platforms (TPU / interpret, where the
        scalar-prefetch kernel runs), gather elsewhere -- the host routes
        keep the round-5 measured path unless scatter is requested
        explicitly.  Both modes are byte-identical by differential test
        (tests/test_epilogue.py); resolve through resolved_epilogue(),
        never the raw field.
      hbm_budget_bytes: HBM budget (bytes) one kernel launch may commit to,
        consumed by the preflight (ops/pallas_solve.preflight_launch /
        hbm_fits).  None -> resolve from the KNTPU_HBM_BUDGET_BYTES env knob,
        else 80% of the device's reported bytes_limit, else unbounded; <= 0
        forces unbounded.  Over-budget launches are DEMOTED where a cheaper
        route exists (adaptive classes stream) and otherwise REFUSED with a
        structured oom-kind LaunchBudgetError before any grid is built --
        never left to crash the worker mid-launch (the r5 clustered-input
        failure mode; see DESIGN.md section 9).
      kernel: top-k extraction strategy inside the Pallas kernel.  'kpass' =
        k min-and-mask sweeps of the full (Q, C) distance tile (the
        shared-memory-heap analog, knearests.cu:127-133).  'blocked' =
        two-stage reduce: per-128-lane-block ascending top-m computed from
        coordinates in registers (the distance tile is never materialized),
        then the k-pass runs on the (Q, G*m) survivor pool -- O(C*m + k*G*m)
        VMEM traffic instead of O(k*C).  Exactness holds via a per-query
        deficit certificate (a block whose m-th kept value could hide a
        better candidate decertifies the row, which then resolves through
        the standard exact fallback); candidate slots are interleaved across
        blocks at pack time so the spatially-clustered near candidates
        spread evenly and deficits stay rare.  'auto' = 'kpass': the
        on-chip A/B (bench_runs/r5_tpu_kernel_ab.json) measured blocked
        slower at every compiling shape and Mosaic-rejected at supercell
        >= 4, so blocked is kept explicit-request-only (see resolve_kernel).
      query_chunk: external-query pipeline chunk size (queries per chunk),
        LEGACY (non-adaptive) query route only.  When set, ops/query.py
        splits large query batches into fixed-size
        chunks dispatched back-to-back -- chunk i+1's H2D staging overlaps
        chunk i's compute (async dispatch is the double buffer) -- and reads
        all results back in ONE batched fetch, so the sync count does not
        grow with the chunk count (DESIGN.md section 12).  None = single
        shot.  The adaptive query route ignores it: its per-class launches
        already dispatch back-to-back against one batched readback, so
        there is no monolithic upload to split.  Solvers read
        resolved_query_chunk(), not this field.
      precision: MXU scoring precision tier (DESIGN.md section 21).  'f32'
        = the pipeline today, byte-for-byte.  'bf16' = the norms and the
        -2*QP^T matmul cast their inputs to bfloat16 while EVERY
        accumulation stays f32 (preferred_element_type) -- the MXU's native
        reduced-precision mode, the peak-FLOP/s tier of TPU-KNN (arXiv
        2206.14286).  Certification stays SOUND at every tier: the
        per-precision bound family (mxu.topk.dot_error_bound) widens the
        certification band to cover the cast/product roundoff, so bf16
        decertifies more rows into the existing exact fallback -- only the
        certified fraction moves, never correctness of a certified row.
        'auto' = 'f32' (reduced precision is an opt-in speed knob, never a
        silent accuracy change) unless a tuned plan resolves it (see
        resolve_tuned).  Only the MXU scorer honors it; 'bf16' with the
        elementwise scorer is refused.  Solvers read resolved_precision(),
        not this field.
    """

    k: int = DEFAULT_K
    density: float = DEFAULT_CELL_DENSITY
    # MXU scoring subsystem (cuda_knearests_tpu/mxu/, DESIGN.md section 16):
    # 'mxu' recasts candidate scoring as |q|^2 + |p|^2 - 2*QP^T blocked
    # matmuls with the TPU-KNN in-register approximate top-k (arXiv
    # 2206.14286); 'elementwise' is the exact diff-arithmetic path every
    # route has always run; 'auto' resolves from recall_target ('mxu' when
    # a sub-1.0 target asks for the approximate engine, 'elementwise' at
    # 1.0 -- the measured-fast exact path on d=3).  Solvers read
    # resolved_scorer(), never this field.
    scorer: str = "auto"
    # TPU-KNN recall/speed knob: the approximate top-k keeps enough
    # per-block candidates that expected recall@k >= recall_target
    # (mxu.topk.recall_bound has the derivation).  1.0 = exact selection;
    # per-row certification bits route any row whose selection is not
    # PROVABLY exact through the existing one-extra-sync brute fallback,
    # so the final answer at 1.0 is byte-identical to the elementwise path.
    recall_target: float = 1.0
    ring_radius: Optional[int] = None
    supercell: int = 3  # best measured tile shape on v5e across k=10..50
    sc_batch: int = 64
    dist_method: str = "diff"
    exclude_self: bool = True
    fallback: str = "brute"
    backend: str = "auto"
    interpret: bool = False
    adaptive: bool = True
    max_classes: int = 4
    stream_tile: int = 2048
    hbm_budget_bytes: Optional[int] = None
    kernel: str = "kpass"  # solvers read effective_kernel(), not this field
    epilogue: str = "auto"  # solvers read resolved_epilogue(), not this field
    query_chunk: Optional[int] = None  # solvers read resolved_query_chunk()
    precision: str = "auto"  # solvers read resolved_precision(), not this field
    # Voronoi plane feed (cluster/planes.py, DESIGN.md section 14): when
    # True, solve() emits the per-neighbor bisector-plane representation
    # (n, d) = (p - q, (|p|^2 - |q|^2)/2) as result.planes -- the clipping
    # input the reference's DEFAULT_NB_PLANES naming promises (params.h:4)
    # -- with no second kNN pass and no extra host sync (the f64 host
    # epilogue runs on the already-fetched rows; f32 would lose the offset
    # to catastrophic cancellation and device traces forbid f64).
    plane_feed: bool = False

    def resolved_ring_radius(self) -> int:
        if self.ring_radius is not None:
            return max(1, int(self.ring_radius))
        return default_ring_radius(self.k, self.density)

    def effective_kernel(self) -> str:
        """The kernel string solvers should resolve from (every solver call
        site reads this, never the raw ``kernel`` field).  fallback='none'
        pins blocked/auto to 'kpass': blocked deficit rows resolve through
        the exact fallback; without one they'd silently lose their trailing
        entries (INVALID_ID/inf) where kpass keeps a near-correct
        best-effort neighbor (see the fallback field docs).  Unknown kernel
        strings pass through unchanged so resolve_kernel's typo guard still
        fires."""
        if self.fallback == "none" and self.kernel in ("blocked", "auto"):
            return "kpass"
        return self.kernel

    def resolved_epilogue(self) -> str:
        """resolve_epilogue() against THIS process's default backend: every
        solver call site reads this, never the raw ``epilogue`` field, so
        the kernel-platform predicate (TPU, or interpret mode standing in
        for one) lives in exactly one place -- same single-source rule as
        effective_kernel()."""
        import jax  # deferred: config must import without a backend

        on_kernel = jax.devices()[0].platform == "tpu" or self.interpret
        return resolve_epilogue(self.epilogue, on_kernel)

    def resolved_scorer(self) -> str:
        """resolve_scorer() against this config -- every solver call site
        reads this, never the raw ``scorer`` field (same single-source rule
        as effective_kernel / resolved_epilogue)."""
        return resolve_scorer(self.scorer, self.recall_target, self.precision)

    def resolved_query_chunk(self) -> Optional[int]:
        """Chunk size of the external-query double-buffered pipeline
        (ops/query.py, the LEGACY query route -- the adaptive route's
        per-class launches already pipeline, see the field docs): queries
        split into fixed-size chunks whose uploads
        and launches are dispatched back-to-back (chunk i+1 stages while
        chunk i computes) with ONE batched readback at the end -- the same
        one-sync contract as the unchunked path, byte-identical by test
        (tests/test_dispatch.py).  None or <= 0 means single-shot."""
        q = self.query_chunk
        return int(q) if q is not None and int(q) > 0 else None

    def resolved_precision(self) -> str:
        """resolve_precision() against this config -- every solver call site
        reads this, never the raw ``precision`` field (same single-source
        rule as resolved_scorer / resolved_epilogue)."""
        return resolve_precision(self.precision, self.resolved_scorer())


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Tunables of the persistent serving daemon (serve/, DESIGN.md s13).

    Attributes:
      max_batch: largest dynamic-batch capacity (queries per flushed batch).
        Also the size-trigger: the batcher flushes as soon as admitting the
        next request would exceed it.  A single request larger than
        max_batch is REFUSED at admission (typed InvalidRequestError) --
        capacity buckets must be bounded for the zero-recompile law to hold.
      max_delay_s: deadline trigger -- a pending request older than this
        forces a flush even when the batch is not full (bounds queueing
        latency at low arrival rates).
      min_bucket: smallest capacity bucket.  Flushed batches pad up to the
        next power-of-two bucket in [min_bucket, max_batch], so the set of
        batch shapes -- and therefore of executable signatures -- is fixed
        and finite: after one warmup pass per bucket the steady-state loop
        performs ZERO recompiles (asserted by tests/test_serve.py).
      compact_threshold: mutations (inserts + deletes) absorbed by the
        delta overlay before it compacts into a full re-prepare of the
        mutated cloud (serve/delta.py).  Compaction changes the stored-point
        count, so the next batch per bucket recompiles once; between
        compactions the signature set is stable.
      warmup: pre-execute one sentinel batch per capacity bucket at daemon
        start (and after compaction) so steady state begins hot.
      k: neighbors per served query (None -> the problem's prepared k).
        Every batch executes at THIS k regardless of per-request k (one
        signature); per-request k <= k truncates columns on the way out.
    """

    max_batch: int = 256
    max_delay_s: float = 0.01
    min_bucket: int = 8
    compact_threshold: int = 512
    warmup: bool = True
    k: Optional[int] = None

    def __post_init__(self):
        if self.min_bucket < 1 or self.max_batch < self.min_bucket:
            raise ValueError(
                f"serve buckets need 1 <= min_bucket <= max_batch, got "
                f"min_bucket={self.min_bucket} max_batch={self.max_batch}")
        if self.max_delay_s < 0:
            raise ValueError(f"max_delay_s must be >= 0, "
                             f"got {self.max_delay_s}")
        if self.compact_threshold < 1:
            raise ValueError(f"compact_threshold must be >= 1, "
                             f"got {self.compact_threshold}")
        if self.k is not None and self.k < 1:
            # k=0 must refuse loudly, not silently coerce to the prepared k
            raise ValueError(f"serving k must be >= 1 (or None for the "
                             f"prepared k), got {self.k}")

    def buckets(self) -> tuple:
        """The fixed capacity-bucket ladder: powers of two from min_bucket
        up to (and including) a bucket covering max_batch."""
        out = []
        b = 1 << (self.min_bucket - 1).bit_length()
        while b < self.max_batch:
            out.append(b)
            b <<= 1
        out.append(b)
        return tuple(out)

    def bucket_for(self, m: int) -> int:
        """Smallest bucket covering an m-query batch (m <= max_batch)."""
        for b in self.buckets():
            if m <= b:
                return b
        # internal invariant: the batcher never forms an over-cap batch
        # (admission refuses oversized requests with the typed taxonomy)
        raise ValueError(f"batch of {m} queries exceeds max_batch="
                         f"{self.max_batch}")


@dataclasses.dataclass(frozen=True)
class SloClass:
    """One service-level-objective tier of the serving fleet (serve/fleet,
    DESIGN.md section 17).  An SLO class parameterizes the EXISTING
    batching law -- it introduces no new trigger kinds, it just picks the
    deadline (latency tier: short ``max_delay_s``, small batches flush
    fast) or the depth (throughput tier: long deadline, deep batches ride
    the big capacity buckets) per tenant.  "Bigger Buffer k-d Trees"
    (arXiv 1512.02831) is the motivation for the throughput tier's deep
    per-tenant buffering.

    Attributes:
      name: the class's wire name ('latency' / 'throughput').
      max_delay_s: deadline flush trigger for tenants of this class.
      max_batch: batch depth cap for tenants of this class (clamped to the
        fleet ladder's global max_batch so every batch shape stays on the
        shared bucket ladder).
      p99_budget_ms: the class's latency promise -- stamped on fleet bench
        rows as ``slo_ok`` (p99 <= budget) so the "latency tier holds while
        a throughput tenant floods" law is machine-checkable."""

    name: str
    max_delay_s: float
    max_batch: int
    p99_budget_ms: float


# The fleet's SLO-class table.  Tenants name a class; the front door builds
# each tenant's ServeConfig from it plus the shared ladder (min_bucket and
# the global max_batch come from ServeFleetConfig, so tenants of equal
# problem signature share executable-cache entries bucket for bucket).
SLO_CLASSES = {
    "latency": SloClass("latency", max_delay_s=0.002, max_batch=64,
                        p99_budget_ms=250.0),
    "throughput": SloClass("throughput", max_delay_s=0.05, max_batch=256,
                           p99_budget_ms=4000.0),
}


@dataclasses.dataclass(frozen=True)
class ServeFleetConfig:
    """Tunables of the multi-tenant serving fleet (serve/fleet/,
    DESIGN.md section 17).

    Attributes:
      min_bucket: smallest capacity bucket of the SHARED ladder.  Every
        tenant's batches pad to this one power-of-two ladder, so tenants
        whose prepared problems carry equal executable signatures share
        ExecutableCache entries -- the second such tenant warms with ZERO
        new compiles (asserted in tests/test_fleet.py).
      max_batch: the ladder's global cap; per-class max_batch clamps to it.
      compact_threshold: per-tenant delta-overlay compaction threshold
        (serve/delta.py semantics, unchanged).
      warmup: pre-execute one sentinel batch per bucket per DENSE tenant at
        fleet start (sidecar tenants mint no executables, nothing to warm).
      sidecar_threshold: tenants whose cloud is smaller than this (or
        degenerate: n < k) route to the brute CPU sidecar
        (serve/fleet/sidecar.py) instead of the dense batching ladder --
        the Hybrid KNN-Join split (arXiv 1810.04758): tiny tenants must
        not mint executable signatures or ride capacity buckets.
      quota_qps: default token-bucket refill rate (query rows/sec) for
        tenants that do not set their own; None = unmetered.
      quota_burst: default token-bucket depth (rows) -- the burst a tenant
        may spend above its sustained rate.
      drr_quantum: deficit-round-robin quantum (query rows added to each
        active tenant's deficit per scheduling round).  The fairness law:
        over any window in which tenants stay backlogged, served rows per
        tenant differ by at most one quantum plus one batch -- a hot
        tenant provably cannot starve the rest (DESIGN.md section 17).
      pod_threshold: tenants whose cloud is at least this large serve
        from an ELASTIC pod-partitioned index (pod/reshard.ElasticIndex:
        Morton-range shards with live boundary migration, DESIGN.md
        section 22) instead of one dense daemon.  None (default) disables
        the pod rung of the placement ladder sidecar -> dense -> pod.
      pod_shards: initial Morton-range shard count for pod tenants.
      pod_skew_threshold: population skew (max shard / mean) past which a
        pod tenant's mutation stream triggers a live rebalance.
    """

    min_bucket: int = 8
    max_batch: int = 256
    compact_threshold: int = 512
    warmup: bool = True
    sidecar_threshold: int = 192
    quota_qps: Optional[float] = None
    quota_burst: float = 4096.0
    drr_quantum: int = 64
    pod_threshold: Optional[int] = None
    pod_shards: int = 2
    pod_skew_threshold: float = 3.0

    def __post_init__(self):
        if self.min_bucket < 1 or self.max_batch < self.min_bucket:
            raise ValueError(
                f"fleet ladder needs 1 <= min_bucket <= max_batch, got "
                f"min_bucket={self.min_bucket} max_batch={self.max_batch}")
        if self.sidecar_threshold < 0:
            raise ValueError(f"sidecar_threshold must be >= 0, got "
                             f"{self.sidecar_threshold}")
        if self.drr_quantum < 1:
            raise ValueError(f"drr_quantum must be >= 1, got "
                             f"{self.drr_quantum}")
        if self.quota_qps is not None and self.quota_qps <= 0:
            raise ValueError(f"quota_qps must be > 0 (or None for "
                             f"unmetered), got {self.quota_qps}")
        if self.pod_threshold is not None \
                and self.pod_threshold <= self.sidecar_threshold:
            raise ValueError(
                f"pod_threshold must exceed sidecar_threshold (the "
                f"placement ladder is sidecar -> dense -> pod), got "
                f"pod_threshold={self.pod_threshold} <= "
                f"sidecar_threshold={self.sidecar_threshold}")
        if self.pod_shards < 1:
            raise ValueError(f"pod_shards must be >= 1, got "
                             f"{self.pod_shards}")
        if self.pod_skew_threshold <= 1.0:
            raise ValueError(f"pod_skew_threshold must be > 1.0, got "
                             f"{self.pod_skew_threshold}")

    def serve_config_for(self, slo: SloClass,
                         k: Optional[int] = None) -> ServeConfig:
        """The per-tenant ServeConfig an SLO class induces on the shared
        ladder: class deadline/depth, fleet ladder floor/cap.  Built here
        so every tenant's bucket set is a prefix of one ladder."""
        return ServeConfig(
            max_batch=min(int(slo.max_batch), self.max_batch),
            max_delay_s=float(slo.max_delay_s),
            min_bucket=self.min_bucket,
            compact_threshold=self.compact_threshold,
            warmup=self.warmup, k=k)


def resolve_epilogue(epilogue: str, on_kernel_platform: bool) -> str:
    """'auto' -> 'scatter' on kernel platforms, 'gather' elsewhere.

    Kernel platforms (TPU, or interpret mode standing in for one) run the
    scalar-prefetch row-major kernel (pallas_solve._pallas_topk_rows), so the
    per-class transpose + row-major concat + row gather of the gather
    epilogue collapse into the kernel launch plus one forward-map scatter --
    the r5 phase table charged the standalone epilogue 51.5% of the on-chip
    solve (bench_runs/r5_tpu_phases.json).  Host platforms default to the
    measured round-5 gather path (dense/streamed solvers already emit
    row-major rows there, so scatter only swaps the final gather for an XLA
    scatter -- available explicitly, not assumed faster).  Both modes are
    byte-identical by differential test."""
    if epilogue not in ("auto", "scatter", "gather"):
        raise ValueError(
            f"unknown epilogue {epilogue!r}: expected 'auto', 'scatter' or "
            f"'gather'")  # a typo must not silently benchmark the wrong path
    if epilogue == "auto":
        return "scatter" if on_kernel_platform else "gather"
    return epilogue


def resolve_scorer(scorer: str, recall_target: float,
                   precision: str = "auto") -> str:
    """'auto' -> 'mxu' below a 1.0 recall target (only the MXU engine has an
    approximate mode) or under a reduced scoring precision (only the MXU
    engine has one of those, too), 'elementwise' at exactly 1.0/f32 (the
    measured-fast exact arithmetic on d=3 -- a 3-wide contraction leaves
    the MXU ~2% utilized, see the dist_method docs).  Explicit scorers pass
    through; an 'elementwise' scorer with a sub-1.0 target is refused
    loudly -- the exact path cannot honor an approximation budget, and
    silently ignoring the knob would benchmark the wrong engine.  (The
    elementwise-x-bf16 refusal lives in resolve_precision: scorer
    resolution must stay total so the precision check can consult it.)"""
    if scorer not in ("auto", "mxu", "elementwise"):
        raise ValueError(
            f"unknown scorer {scorer!r}: expected 'auto', 'mxu' or "
            f"'elementwise'")  # a typo must not silently benchmark the wrong engine
    r = float(recall_target)
    if not (0.0 < r <= 1.0):
        raise ValueError(
            f"recall_target must lie in (0, 1], got {recall_target!r} "
            f"(1.0 = exact; the TPU-KNN bound is meaningless outside)")
    if scorer == "elementwise" and r < 1.0:
        raise ValueError(
            f"scorer='elementwise' computes exact top-k only; "
            f"recall_target={r} needs scorer='mxu' (or 'auto')")
    if scorer == "auto":
        return "mxu" if (r < 1.0 or precision == "bf16") else "elementwise"
    return scorer


def resolve_precision(precision: str, scorer_resolved: str = "mxu") -> str:
    """'auto' -> 'f32': reduced precision is an opt-in speed knob, never a
    silent accuracy change (the tuned-plan seam is the one place that fills
    'auto' differently, and only from a plan the tuner measured on this
    hardware).  Explicit tiers pass through mxu.topk.PRECISIONS validation;
    'bf16' with the elementwise scorer is refused loudly -- that path
    scores in exact diff arithmetic with no reduced-precision mode, and
    silently ignoring the knob would benchmark the wrong arithmetic."""
    from .mxu import topk as _topk  # host-only numpy module; cheap import

    if precision == "auto":
        return "f32"
    _topk.check_precision(precision)
    if precision != "f32" and scorer_resolved == "elementwise":
        raise ValueError(
            f"precision={precision!r} needs the MXU scorer; the elementwise "
            f"path has no reduced-precision mode (set scorer='mxu' or leave "
            f"it 'auto')")  # a typo must not silently benchmark the wrong arithmetic
    return precision


def resolve_tuned(cfg: "KnnConfig", signature, device_kind=None) -> "KnnConfig":
    """Fill a config's still-default knobs from the tuned-plan store.

    The ONE resolution seam between the autotuner (tune/, DESIGN.md
    section 21) and the solvers: api.prepare, the sharded and pod prepares,
    and bench --frontier all pass their config through here before
    planning.  Law of the seam:

      * only knobs still at their 'auto'/None defaults are filled -- an
        explicit user choice ALWAYS wins over a tuned plan;
      * the store is consulted only when one is active (KNTPU_TUNE_STORE
        set, or a process store registered via tune.store.set_default_store)
        -- with no store this returns ``cfg`` unchanged without importing
        the tuner, so untouched deployments keep byte-identical behavior;
      * ``signature`` is the problem shape key (tune.store.plan_signature)
        or an ``(n, d)`` tuple converted AFTER the activation check (so
        callers never import the tuner just to build a key);
        ``device_kind`` defaults to this process's accelerator
        (utils.devinfo.current_device_kind).

    Because plans only fill 'auto' slots and certification is sound at
    every precision tier, a tuned resolve can change SPEED and the
    certified fraction but never the correctness contract -- and at
    recall_target=1.0 with epilogue/scorer defaults the tuned and untuned
    answers are byte-identical by test (tests/test_tune.py).
    """
    import os

    if "KNTPU_TUNE_STORE" not in os.environ:
        import sys
        tune_store = sys.modules.get(__package__ + ".tune.store")
        if tune_store is None or tune_store.get_default_store() is None:
            return cfg  # no store active: zero behavior (and import) change
    from .tune import store as _store

    if isinstance(signature, tuple):
        n, d = signature
        signature = _store.plan_signature(n, d, cfg.k, cfg.recall_target)
    plan = _store.lookup_plan(signature, device_kind)
    if not plan:
        return cfg
    updates = {}
    if cfg.precision == "auto" and plan.get("precision"):
        updates["precision"] = str(plan["precision"])
    if cfg.scorer == "auto" and plan.get("scorer"):
        updates["scorer"] = str(plan["scorer"])
    if cfg.epilogue == "auto" and plan.get("epilogue"):
        updates["epilogue"] = str(plan["epilogue"])
    if cfg.query_chunk is None and plan.get("query_chunk"):
        updates["query_chunk"] = int(plan["query_chunk"])
    return dataclasses.replace(cfg, **updates) if updates else cfg


def blocked_topm(k: int, ccap: int) -> int:
    """Per-block kept count m for the 'blocked' kernel, or 0 when the blocked
    route is ineligible for this (k, ccap).

    m barely affects the kernel's VMEM traffic (stage-1 extraction passes
    run on in-register blocks; coordinates are read once per block either
    way), so it is chosen for deficit rate, not bandwidth: measured on
    15k blue noise with G=9 blocks, m=4 flagged 1.8% of queries at k=10 and
    9.7% at k=20, while ceil(k/G)+4 flagged 0.00% / 0.05%.  Eligibility
    requires the survivor pool (m*G entries) to cover k three times over --
    a pool close to k puts the selected k-th near the pool maximum and
    flags almost every block -- and at least 2 blocks (else blocked IS
    kpass with overhead)."""
    g = ccap // 128
    if ccap % 128 != 0 or g < 2:
        return 0
    m = min(max(-(-k // g) + 4, -(-3 * k // g)), 16)
    return m if m * g >= 3 * k else 0


def resolve_kernel(kernel: str, k: int, ccap: int) -> str:
    """'auto' -> 'kpass'; 'blocked' stays explicit-request-only.

    Decided by the on-chip A/B (bench_runs/r5_tpu_kernel_ab.json): at every
    shape where blocked compiles it measured slower than kpass (k=10:
    1.29M vs 2.17M q/s; k=20: 0.88M vs 1.57M), and at supercell >= 4 its
    dynamic-offset VMEM scratch store fails Mosaic ('index in dimension 0
    not provably a multiple of 8').  The traffic model that motivated it
    (O(C*m + k*G*m) vs O(k*C) VMEM bytes) is real but does not pay on v5e,
    where the kpass sweeps pipeline better than the per-block gather/store
    traffic of the two-stage reduce."""
    if kernel not in ("auto", "blocked", "kpass"):
        raise ValueError(
            f"unknown kernel {kernel!r}: expected 'auto', 'blocked' or "
            f"'kpass'")  # a typo must not silently benchmark the wrong body
    if kernel == "auto":
        return "kpass"
    if kernel == "blocked" and not blocked_topm(k, ccap):
        return "kpass"  # ineligible shape: degrade to exact-anyway kpass
    return kernel
