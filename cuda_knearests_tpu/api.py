"""Engine API: the idiomatic equivalent of the reference's ``kn_*`` C surface.

Reference parity (C1, /root/reference/knearests.h:21-29, impl knearests.cu:235-466):

  =======================  ==============================================
  reference                this framework
  =======================  ==============================================
  ``kn_prepare(pts, n)``   ``KnnProblem.prepare(points, config)``
  ``kn_solve(kn)``         ``problem.solve()``
  ``kn_get_points``        ``problem.get_points()``
  ``kn_get_knearests``     ``problem.get_knearests()`` (sorted indexing)
  ``kn_get_permutation``   ``problem.get_permutation()``
  ``kn_print_stats``       ``problem.print_stats()``
  ``kn_free``              (garbage collection -- no manual lifetime)
  =======================  ==============================================

Beyond parity: ``k`` is a runtime argument instead of a compile-time macro
(params.h:4), results carry per-query completeness certificates, and uncertified
queries are resolved exactly by a brute-force fallback pass, so the final answer
is exact -- not "exact assuming the ring budget sufficed" like the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np

from .config import KnnConfig
from .obs import spans as _obs_spans
from .ops.gridhash import GridHash, build_grid
from .ops.solve import (KnnResult, SolvePlan, brute_force_by_index, build_plan,
                        solve)
from .runtime import dispatch as _dispatch
from .utils import stats as _stats
from .utils.memory import InvalidKError, from_device


def radius_mask_from_knn(ids: np.ndarray, d2: np.ndarray, radius: float,
                         cap: int):
    """Shared tail of the query_radius surfaces (single-chip and sharded):
    mask exact k-NN rows beyond ``radius``.  The k-NN rows are globally exact,
    so the mask is exact for any radius; the only possible incompleteness is
    the cap itself, flagged per query via ``truncated``.  Returns (ids with
    -1 beyond count, d2 with inf beyond, counts, truncated)."""
    in_range = d2 <= np.float32(radius) ** 2
    counts = in_range.sum(axis=1).astype(np.int32)
    truncated = counts >= cap
    return (np.where(in_range, ids, -1), np.where(in_range, d2, np.inf),
            counts, truncated)


def edges_from_neighbors(nbrs: np.ndarray, symmetric: bool = False
                         ) -> np.ndarray:
    """(n, k) neighbor table (original ids, -1 = none) -> COO edge list
    (E, 2).  ``symmetric`` adds reverse edges and deduplicates.  Shared by
    the single-chip and sharded get_edges surfaces."""
    n, k = nbrs.shape
    src = np.repeat(np.arange(n, dtype=np.int32), k)
    dst = nbrs.reshape(-1)
    keep = dst >= 0
    edges = np.stack([src[keep], dst[keep]], axis=1)
    if symmetric:
        und = np.concatenate([edges, edges[:, ::-1]])
        edges = np.unique(und, axis=0)
    return edges


def _config_adaptive_eligible(cfg, per_chip: bool = False) -> bool:
    """THE adaptive-route predicate: prepare's fail-fast scorer guard and
    solve-time routing must agree on it, or a scorer='mxu' config that
    passes the refusal can still route legacy and silently score
    elementwise (the exact case the guard exists to prevent).

    ``per_chip=True`` is the sharded/pod form of the same agreement: the
    per-chip solves ALWAYS run the adaptive class machinery (build_class_
    specs routes eligible classes to the MXU scorer under
    ``resolved_scorer() == 'mxu'``, with the per-chip recall_target pools
    of DESIGN.md section 18), so only the arithmetic contract matters --
    the class scorers realize distances in 'diff' arithmetic, and the
    single-chip routing knobs (adaptive, backend) are not consulted by
    the per-chip route.  Both prepare-time guards (ShardedKnnProblem /
    PodKnnProblem) and build_class_specs' routing read THIS predicate, so
    they cannot disagree."""
    if per_chip:
        return cfg.dist_method == "diff"
    if not (cfg.adaptive and cfg.dist_method == "diff"):
        return False
    if cfg.backend == "auto":
        return True
    # explicit 'pallas' only routes here where the kernel can actually
    # run -- off-TPU without interpret it falls through to the legacy
    # path, which fails loudly instead of silently streaming XLA
    return (cfg.backend == "pallas"
            and (jax.devices()[0].platform == "tpu" or cfg.interpret))


def _resolve_tuned_for(cfg, points) -> "KnnConfig":
    """THE tuned-plan seam of every prepare (config.resolve_tuned over
    this problem's shape signature): fills only still-default knobs, and
    with no active store (KNTPU_TUNE_STORE unset, nothing registered) it
    is an exact no-op -- the single-chip, sharded, and pod prepares all
    pass through here so a plan tuned once applies everywhere.  Shape
    probing is deliberately forgiving (a prepare on unvalidated input must
    refuse through the io front door, not here)."""
    from .config import resolve_tuned

    shape = getattr(points, "shape", None)
    if shape is None:
        try:
            shape = np.asarray(points).shape
        except Exception:  # noqa: BLE001 -- malformed input: validate_or_raise owns the refusal
            return cfg
    if len(shape) != 2:
        return cfg
    return resolve_tuned(cfg, (int(shape[0]), int(shape[1])))


def _pad_pow2(x: np.ndarray, fill: int, minimum: int = 8) -> np.ndarray:
    m = max(minimum, 1 << (int(x.size) - 1).bit_length()) if x.size else minimum
    out = np.full((m,), fill, x.dtype)
    out[: x.size] = x
    return out


@dataclasses.dataclass
class KnnProblem:
    """One prepared all-points kNN problem (reference analog: struct kn_problem,
    /root/reference/knearests.h:3-16)."""

    grid: GridHash
    config: KnnConfig
    plan: Optional[SolvePlan] = None
    result: Optional[KnnResult] = None
    # Host-resident original-order input points (the validated array
    # prepare() staged from).  Kept by reference, never copied: the plane
    # feed (cluster/planes.py) and other host epilogues read coordinates
    # from here at zero device round trips.  None on problems resumed from
    # a checkpoint -- _host_original() then reconstructs via one counted
    # fetch and caches the result here.
    host_points: Optional[np.ndarray] = None
    pack: Optional[object] = None  # cached PallasPack (pallas backend only)
    aplan: Optional[object] = None  # cached AdaptivePlan (adaptive solve)
    _oracle: Optional[object] = None  # KdTreeOracle (oracle backend only)
    # prepare-time executable-signature census (runtime.dispatch.signature
    # over the built plan): the problem half of the executable-cache key, so
    # repeated problems with the same class-shape signature reuse compiled
    # query-launch executables (DESIGN.md section 12)
    _exec_key: Optional[tuple] = None

    @classmethod
    def prepare(cls, points, config: KnnConfig | None = None,
                dim: int | None = None, validate: bool = True) -> "KnnProblem":
        """Stage points, build the spatial hash and the supercell schedule.

        Like kn_prepare (knearests.cu:235-344), input points must satisfy the
        [0, domain]^3 contract (io.normalize_points enforces it) -- but where
        the reference silently clamps out-of-range points into boundary cells
        (knearests.cu:26-28), this fails fast with a fix pointer: the
        io.validate_or_raise front door raises the typed input taxonomy
        (kind 'invalid-input').  n = 0 and k > n are legal degraded modes
        (empty results / -1-inf-padded rows), not errors.
        """
        with _obs_spans.span("knn.prepare",
                             k=int((config or KnnConfig()).k)):
            return cls._prepare_impl(points, config, dim, validate)

    @classmethod
    def _prepare_impl(cls, points, config: KnnConfig | None = None,
                      dim: int | None = None,
                      validate: bool = True) -> "KnnProblem":
        from .io import validate_or_raise

        config = config or KnnConfig()
        config = _resolve_tuned_for(config, points)
        # fail-fast scorer resolution (DESIGN.md section 16): an illegal
        # scorer x recall_target combination refuses HERE, not at solve
        # time -- and the MXU scorer only has a grid-route implementation
        # on the adaptive class schedule, so configs that would silently
        # run elementwise under an explicit approximation budget refuse
        # with a pointer at the route that honors it
        scorer = config.resolved_scorer()
        if scorer == "mxu" and not _config_adaptive_eligible(config):
            from .utils.memory import InvalidConfigError

            raise InvalidConfigError(
                f"scorer='mxu' (recall_target={config.recall_target}) "
                f"needs the adaptive grid route (adaptive=True, "
                f"dist_method='diff', backend 'auto' -- or 'pallas' on "
                f"TPU/interpret); this config would route to the legacy "
                f"path and silently score elementwise -- use the "
                f"brute/MXU route (cuda_knearests_tpu.mxu.solve_general) "
                f"for plan-free scoring")
        points = (validate_or_raise(points, k=config.k) if validate
                  else np.asarray(points, np.float32))
        grid = build_grid(points, dim=dim, density=config.density)
        problem = cls(grid=grid, config=config,
                      host_points=points if isinstance(points, np.ndarray)
                      else None)
        if grid.n_points == 0:
            # empty cloud: nothing to plan -- solve()/query() short-circuit
            # to empty / all-invalid results (degraded mode, DESIGN.md s11)
            return problem
        # one planning pass: adaptive problems use the aplan for both solve()
        # and query(); the legacy plan/pack exist only for non-adaptive
        # configs; the oracle backend plans nothing (the kd-tree IS the
        # engine) and builds its tree here, mirroring the grid-build-at-
        # prepare convention (timing contract: solve() measures queries)
        if config.backend == "oracle":
            from .oracle import KdTreeOracle

            problem._oracle = KdTreeOracle(from_device(grid.points))
        elif problem._adaptive_eligible():
            from .ops.adaptive import build_adaptive_plan

            problem.aplan = build_adaptive_plan(grid, config)
        else:
            problem.plan = build_plan(grid, config)
        problem._seal()
        return problem

    def _seal(self) -> None:
        """Stamp the prepare-time executable-signature census: the
        recompile key (runtime.dispatch.signature -- the same census the
        kntpu-check contract engine computes) of everything planning
        produced.  Two problems with equal keys dispatch shape-identical
        programs, so the query chunk pipeline's executable cache can reuse
        one compiled launch across them."""
        self._exec_key = _dispatch.signature(
            (self.plan, self.aplan), self.config.k, self.config.supercell,
            self.grid.dim, self.grid.n_points)

    def with_points(self, points, validate: bool = True) -> "KnnProblem":
        """A fresh problem over ``points`` under THIS problem's config --
        the rebuild-from-scratch primitive of the serving delta overlay
        (serve/delta.py compacts through it, and the mutation fuzz uses it
        as the oracle the overlay is pinned byte-identical against)."""
        return KnnProblem.prepare(points, self.config, validate=validate)

    def _adaptive_eligible(self) -> bool:
        return _config_adaptive_eligible(self.config)

    def solve(self) -> KnnResult:
        """Run the grid solve, then resolve uncertified queries exactly
        (reference analog: kn_solve, knearests.cu:348-392).

        backend='oracle' answers through the native C++ kd-tree instead of
        the grid engine (exact by construction, all rows certified) -- the
        reference's own CPU path (its kd-tree solve phase,
        /root/reference/test_knearests.cu:194-214) promoted to a first-class
        engine, and the fastest exact CPU route (measured 3-5x the grid's
        dense route on the 900k north star, DESIGN.md section 5)."""
        with _obs_spans.span("knn.solve", n=int(self.grid.n_points),
                             k=int(self.config.k),
                             route=self._route_name()):
            return self._solve_impl()

    def _route_name(self) -> str:
        if self.config.backend == "oracle":
            return "oracle"
        return "adaptive" if self._adaptive_eligible() else "legacy"

    def _solve_impl(self) -> KnnResult:
        if self.grid.n_points == 0:
            # degraded mode: an empty cloud solves to empty, fully-certified
            # results (there is nothing a neighbor table could miss)
            k = self.config.k
            self.result = KnnResult(
                neighbors=np.empty((0, k), np.int32),
                dists_sq=np.empty((0, k), np.float32),
                certified=np.empty((0,), bool))
            return self._with_plane_feed()
        if self.config.backend == "oracle":
            ids, d2 = self._oracle.knn_all_points(self.config.k) \
                if self.config.exclude_self else self._oracle.knn(
                    self._oracle.points, self.config.k)
            # host-native result: the kd-tree answers on the host, so no
            # device round trip ever enters this route (the one-sync
            # contract's zero-sync case)
            self.result = KnnResult(
                neighbors=np.asarray(ids, np.int32),
                dists_sq=np.asarray(d2, np.float32),
                certified=np.ones((self.grid.n_points,), bool),
                uncert_count=np.int32(0))
            return self._with_plane_feed()
        if self._adaptive_eligible():
            from .ops.adaptive import build_adaptive_plan, solve_adaptive

            if self.aplan is None:
                self.aplan = build_adaptive_plan(self.grid, self.config)
            res = solve_adaptive(self.grid, self.config, self.aplan)
        else:
            from .ops.solve import prepare_pack

            if self.plan is None:
                self.plan = build_plan(self.grid, self.config)
            if self.pack is None:
                self.pack = prepare_pack(self.grid, self.config, self.plan)
            res = solve(self.grid, self.config, self.plan, self.pack)
        self.result = self._finalize(res)
        return self._with_plane_feed()

    def _finalize(self, res: KnnResult) -> KnnResult:
        """One-sync completion (DESIGN.md section 12): a single batched D2H
        of the assembled tree -- ids, d2, certificate mask, and uncertified
        count TOGETHER (the count readback at the old fallback gate rode its
        own eager sync) -- then, only when uncertified rows exist and the
        brute fallback is on, ONE more batched fetch of their exact
        resolution.  <= 2 host round trips per solve on every route, pinned
        by tests/test_dispatch.py."""
        cnt = (res.uncert_count if res.uncert_count is not None
               else jax.numpy.sum(~res.certified, dtype=jax.numpy.int32))
        nbr, d2, cert, n_unc = _dispatch.fetch(  # syncflow: solve-final
            res.neighbors, res.dists_sq, res.certified, cnt)
        nbr = np.asarray(nbr)
        d2 = np.asarray(d2)
        cert = np.asarray(cert)
        if int(n_unc) == 0 or self.config.fallback != "brute":
            return KnnResult(neighbors=nbr, dists_sq=d2, certified=cert,
                             uncert_count=np.int32(int(n_unc)))
        # writable copies only on the (rare) resolution branch: device_get
        # hands back read-only zero-copy views on the CPU backend
        nbr, d2, cert = np.array(nbr), np.array(d2), np.array(cert)
        bad = np.nonzero(~cert)[0].astype(np.int32)
        # Pad to a power of two so repeated solves reuse a handful of compiles.
        q_idx = _pad_pow2(bad, fill=-1)
        b_ids, b_d2 = brute_force_by_index(
            self.grid.points, _dispatch.stage(q_idx), self.config.k,  # syncflow: solve-fallback-stage
            self.config.exclude_self)
        # the SAME batched fetch primitive as the main readback: an
        # uncertified row costs one more round trip total, never a second
        # sync storm of eager per-array readbacks
        b_ids, b_d2 = _dispatch.fetch(b_ids, b_d2)  # syncflow: solve-fallback
        sel = q_idx >= 0
        nbr[q_idx[sel]] = np.asarray(b_ids)[sel]
        d2[q_idx[sel]] = np.asarray(b_d2)[sel]
        cert[bad] = True
        # uncert_count = rows that NEEDED resolution (all resolved now):
        # populated on every path, so consumers never special-case None
        return KnnResult(neighbors=nbr, dists_sq=d2, certified=cert,
                         uncert_count=np.int32(int(n_unc)))

    def _with_plane_feed(self) -> KnnResult:
        """solve()'s one exit: when ``config.plane_feed`` is on, attach the
        Voronoi plane feed (cluster/planes.py) to the finalized result --
        a pure-host f64 epilogue over the already-fetched rows, zero extra
        device syncs (DESIGN.md section 14)."""
        if self.config.plane_feed and self.result.planes is None:
            self.result = dataclasses.replace(
                self.result, planes=self._compute_planes())
        return self.result

    def _host_original(self) -> np.ndarray:
        """Original-order host coordinates of the stored cloud.  Free on
        prepared problems (the validated input array is kept by
        reference); checkpoint-resumed problems pay one counted fetch and
        cache it."""
        if self.host_points is None:
            pts, perm = _dispatch.fetch(self.grid.points,  # syncflow: host-original
                                        self.grid.permutation)
            out = np.empty_like(np.asarray(pts))
            out[np.asarray(perm)] = np.asarray(pts)
            self.host_points = out
        return self.host_points

    def _compute_planes(self) -> np.ndarray:
        from .cluster.planes import bisector_planes

        pts = self._host_original()
        return bisector_planes(pts, pts, self.get_knearests_original())

    def get_planes(self) -> np.ndarray:
        """(n, k, 4) f32 bisector-plane feed of the solved all-points kNN:
        rows in ORIGINAL point order, ``[nx, ny, nz, d]`` per neighbor
        with the half-space ``n . x <= d`` containing the site (pad slots
        are the trivially-true ``n=0, d=inf``).  The explicit form of what
        the reference's DEFAULT_NB_PLANES k feeds its clipping pipeline
        (params.h:4); see cluster/planes.py for the precision contract.
        Computed once and cached on the result."""
        self._require_solved()
        if self.result.planes is None:
            self.result = dataclasses.replace(
                self.result, planes=self._compute_planes())
        return self.result.planes

    def query(self, queries, k: int | None = None, planes: bool = False):
        """Exact kNN of arbitrary query coordinates against the stored points.

        The reference's GPU engine only answers the all-points self-query; its
        CPU oracle takes arbitrary queries (kd_tree.cpp:168-205) -- this closes
        that asymmetry.  Queries must lie in the engine domain; the query point
        set is independent of the stored set (no self-exclusion).  ``k``
        defaults to (and may not exceed) the prepared config's k, which sized
        the candidate dilation the completeness certificate relies on.

        Returns ((m, k) neighbor ids in original indexing, ascending by
        distance; (m, k) squared distances) -- plus, with ``planes=True``,
        the (m, k, 4) Voronoi bisector-plane feed of the rows
        (cluster/planes.py: ``[nx, ny, nz, d]``, half-space ``n . x <= d``
        containing the query; a pure-host f64 epilogue over the fetched
        rows, zero extra device syncs).
        """
        with _obs_spans.span("knn.query", k=int(k or self.config.k),
                             route=self._route_name()) as sp:
            return self._query_impl(queries, k, planes, sp)

    def _query_impl(self, queries, k, planes, sp):
        from .io import validate_or_raise

        k = self.config.k if k is None else k
        queries = validate_or_raise(queries, k=k, what="queries")
        sp.set(m=int(queries.shape[0]))
        k = int(k)
        if k > self.config.k:
            raise InvalidKError(
                f"k={k} exceeds the prepared k={self.config.k}; re-prepare "
                f"with a larger config.k (it sizes the candidate dilation)")
        ids, d2 = self._query_ids(queries, k)
        if not planes:
            return ids, d2
        from .cluster.planes import bisector_planes

        return ids, d2, bisector_planes(queries, self._host_original(), ids)

    def _query_ids(self, queries: np.ndarray, k: int):
        """query()'s route dispatch (validated inputs): ((m, k) ids in
        original indexing, (m, k) d2)."""
        if self.grid.n_points == 0:
            # degraded mode: no stored points -> every row is all -1/inf
            return (np.full((queries.shape[0], k), -1, np.int32),
                    np.full((queries.shape[0], k), np.inf, np.float32))
        if self.config.backend == "oracle":
            # sorted-index results from the tree over sorted storage ->
            # original ids via the permutation (the query contract)
            ids, d2 = self._oracle.knn(
                np.ascontiguousarray(queries, np.float32), k)
            perm = from_device(self.grid.permutation)
            return np.where(ids >= 0, perm[np.clip(ids, 0, None)],
                            ids).astype(np.int32), d2
        # One planning pass per problem: adaptive problems route external
        # queries through the class schedule prepare() already built, never
        # materializing the legacy SolvePlan/PallasPack alongside it.
        if self.aplan is not None:
            from .ops.adaptive import query_adaptive

            return query_adaptive(self.grid, self.config, self.aplan,
                                  queries, k, self.config.fallback)
        from .ops.query import query_knn
        from .ops.solve import prepare_pack

        if self.plan is None:
            self.plan = build_plan(self.grid, self.config)
        # Same backend policy as solve(): prepare_pack builds the kernel pack
        # only when pick_backend resolves to pallas (TPU, or interpret mode,
        # and the tile fits VMEM); otherwise it returns None and query_knn
        # routes to the exact tiled brute-force path.
        if self.pack is None:
            self.pack = prepare_pack(self.grid, self.config, self.plan)
        pack = self.pack
        interpret = (self.config.interpret
                     or jax.devices()[0].platform == "cpu")
        return query_knn(self.grid, self.plan, pack, queries, k,
                         self.config.supercell, interpret,
                         self.config.fallback,
                         self.config.resolved_epilogue(),
                         chunk=self.config.resolved_query_chunk(),
                         exec_key=self._exec_key)

    def query_radius(self, queries, radius: float,
                     max_neighbors: int | None = None):
        """All stored points within ``radius`` of each query (capped).

        Fixed-radius search on the same grid machinery: runs the k-NN kernel
        with k=``max_neighbors`` and masks results beyond the radius.  The
        k-NN results are *globally* exact (completeness certificate or brute
        fallback), so the mask is exact for any radius -- the only possible
        incompleteness is the cap itself, flagged per query via ``truncated``.

        Returns (ids (m, cap) original indexing, -1 beyond count;
        d2 (m, cap) ascending, inf beyond; counts (m,); truncated (m,) --
        True where exactly ``max_neighbors`` landed in range, i.e. more
        neighbors may exist beyond the cap).
        """
        cap = self.config.k if max_neighbors is None else int(max_neighbors)
        if cap > self.config.k:
            raise InvalidKError(
                f"max_neighbors={cap} exceeds the prepared k={self.config.k}")
        ids, d2 = self.query(queries, k=cap)
        return radius_mask_from_knn(ids, d2, radius, cap)

    # -- result extraction (reference: kn_get_*, knearests.cu:406-437) ----------

    def get_points(self) -> np.ndarray:
        """Points in sorted (grid) order, like kn_get_points (knearests.cu:406)."""
        return from_device(self.grid.points)

    def get_permutation(self) -> np.ndarray:
        """sorted position -> original index, like kn_get_permutation
        (knearests.cu:430)."""
        return from_device(self.grid.permutation)

    def get_knearests(self) -> np.ndarray:
        """(n, k) neighbor ids in *sorted* indexing, ascending by distance --
        the reference's output contract (knearests.cu:141-147,420)."""
        self._require_solved()
        return from_device(self.result.neighbors)

    def get_knearests_original(self) -> np.ndarray:
        """(n, k) neighbor table re-expressed in original point ids -- the
        un-permute step the reference leaves to its caller
        (test_knearests.cu:155-160).

        Pure host numpy after one batched fetch: the finalized result is
        already host-resident, so re-uploading it for a device unpermute
        (gridhash.unpermute_neighbors -- still the device-side API) would
        cost H2D + eager dispatches + D2H on the serving path for nothing."""
        self._require_solved()
        nbrs, perm = _dispatch.fetch(self.result.neighbors,  # syncflow: extract-original
                                     self.grid.permutation)
        if self.grid.n_points == 0:
            return np.asarray(nbrs)
        nbrs = np.asarray(nbrs)
        perm = np.asarray(perm)
        # same contract as unpermute_neighbors (fill = -1):
        # out[perm[r]][j] = perm[nbrs[r][j]], sentinels preserved
        mapped = np.where(nbrs >= 0,
                          perm[np.clip(nbrs, 0, self.grid.n_points - 1)], -1)
        out = np.empty_like(mapped)
        out[perm] = mapped
        return out

    def get_dists_sq(self) -> np.ndarray:
        self._require_solved()
        return from_device(self.result.dists_sq)

    def get_edges(self, symmetric: bool = False) -> np.ndarray:
        """kNN graph as a COO edge list (E, 2) of original point ids.

        The reference's neighbor tables feed a clipping-plane pipeline (its k
        is literally named DEFAULT_NB_PLANES, params.h:4); an explicit edge
        list is the graph-consumer form of the same product.  ``symmetric``
        adds reverse edges and deduplicates (an undirected graph).
        """
        self._require_solved()
        return edges_from_neighbors(self.get_knearests_original(), symmetric)

    def print_stats(self):
        """Occupancy histogram + certification + memory (reference:
        kn_print_stats, knearests.cu:440-466)."""
        return _stats.print_stats(self)

    def stats(self):
        return _stats.problem_stats(self)

    def _require_solved(self) -> None:
        if self.result is None:
            raise RuntimeError("call solve() first")


def knn(points, k: int = 10, config: KnnConfig | None = None) -> np.ndarray:
    """One-call convenience: exact all-points kNN in original indexing."""
    cfg = dataclasses.replace(config or KnnConfig(), k=k)
    problem = KnnProblem.prepare(points, cfg)
    problem.solve()
    return problem.get_knearests_original()


def _npz_path(path: str) -> str:
    """np.savez appends '.npz' to bare paths; normalize so save/load agree."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def save_problem(problem: KnnProblem, path: str) -> None:
    """Checkpoint a prepared problem (grid + config) to one ``.npz``
    ('.npz' is appended when missing, and load_problem does the same).

    The reference has no persistence at all (SURVEY.md section 5
    "Checkpoint / resume: Absent"); here a prepared spatial hash -- the
    expensive part of prepare() at 10M+ points -- can be saved and resumed.
    Solved results are not checkpointed (re-solving is cheap and the solve is
    deterministic)."""
    path = _npz_path(path)
    g = problem.grid
    cfg = dataclasses.asdict(problem.config)
    np.savez_compressed(
        path,
        points=from_device(g.points),
        permutation=from_device(g.permutation),
        cell_starts=from_device(g.cell_starts),
        cell_counts=from_device(g.cell_counts),
        dim=np.int64(g.dim), domain=np.float64(g.domain),  # kntpu-ok: wide-dtype -- on-disk checkpoint schema, never staged to a device
        config_json=np.bytes_(
            __import__("json").dumps(
                {k: v for k, v in cfg.items() if v is not None}).encode()),
    )


def load_problem(path: str) -> KnnProblem:
    """Resume a checkpointed problem: stages the saved grid back onto the
    device and rebuilds the (cheap, deterministic) supercell plan."""
    import json

    from .ops.gridhash import GridHash

    with np.load(_npz_path(path)) as z:
        cfg = KnnConfig(**json.loads(bytes(z["config_json"]).decode()))
        counts = z["cell_counts"].astype(np.int32)
        grid = GridHash(
            points=jax.numpy.asarray(z["points"]),
            permutation=jax.numpy.asarray(z["permutation"].astype(np.int32)),
            cell_starts=jax.numpy.asarray(z["cell_starts"].astype(np.int32)),
            cell_counts=jax.numpy.asarray(counts),
            dim=int(z["dim"]), domain=float(z["domain"]))
    problem = KnnProblem(grid=grid, config=cfg)
    if cfg.backend == "oracle":
        from .oracle import KdTreeOracle

        problem._oracle = KdTreeOracle(from_device(grid.points))
    elif problem._adaptive_eligible():
        from .ops.adaptive import build_adaptive_plan

        problem.aplan = build_adaptive_plan(grid, cfg, cell_counts_host=counts)
    else:
        problem.plan = build_plan(grid, cfg, cell_counts_host=counts)
    problem._seal()
    return problem
