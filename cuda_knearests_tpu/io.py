"""Point-cloud I/O, normalization, and synthetic dataset generators.

Reference parity (component C11/C15 in SURVEY.md):
  * ``.xyz`` text format: line 1 = point count, then ``x y z`` per line
    (/root/reference/test_knearests.cu:40-62).
  * Normalization into the engine's ``[0, 1000]^3`` domain contract, preserving
    aspect ratio and padding the bbox slightly so no point sits exactly on the
    boundary (/root/reference/test_knearests.cu:15-38,65-78).
  * Synthetic generators regenerate the datasets the reference references but does
    not ship (``pts300K.xyz``, ``300k_blue_cube.xyz``, ``900k_blue_cube.xyz`` --
    /root/reference/.MISSING_LARGE_BLOBS:1-3): uniform random and blue-noise
    (dart-throwing via grid-jitter) samplers.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .config import DOMAIN_SIZE
from .utils.memory import (CorruptInputError, DegenerateExtentError,
                           DomainBoundsError, InvalidConfigError,
                           InvalidKError, InvalidRequestError,
                           InvalidShapeError, NonFiniteInputError,
                           OverQuotaError, UnknownTenantError)


def load_xyz(path: str) -> np.ndarray:
    """Parse an .xyz file -> float32 array (n, 3).

    Format per /root/reference/test_knearests.cu:48-62: first line is the point
    count, each following line has three floats.  Raises on count mismatch (the
    reference uses ``assert``, test_knearests.cu:62).
    """
    with open(path, "r") as f:
        first = f.readline().split()
        n = int(first[0])
        data = np.loadtxt(f, dtype=np.float32)
    data = np.atleast_2d(data)[:, :3].astype(np.float32)
    if data.shape[0] != n:
        raise CorruptInputError(
            f"{path}: header says {n} points, found {data.shape[0]}")
    return np.ascontiguousarray(data)


def save_xyz(path: str, points: np.ndarray) -> None:
    """Write points in the reference's .xyz format (count header + rows)."""
    points = np.asarray(points, dtype=np.float32)
    with open(path, "w") as f:
        f.write(f"{points.shape[0]}\n")
        np.savetxt(f, points, fmt="%.9g")


def bbox(points: np.ndarray, pad_fraction: float = 0.001) -> Tuple[np.ndarray, np.ndarray]:
    """Axis-aligned bounding box padded by `pad_fraction` of its max side.

    Mirrors get_bbox (/root/reference/test_knearests.cu:15-38), which pads by
    0.1% of the largest side so normalized points land strictly inside the domain.
    """
    points = np.asarray(points)
    if points.size == 0:
        raise DegenerateExtentError(
            "cannot take a bounding box of an empty point set (input "
            "contract: normalization needs at least one point; an empty "
            "set is legal input to prepare/solve, which skip normalization)")
    lo = points.min(axis=0).astype(np.float64)
    hi = points.max(axis=0).astype(np.float64)
    pad = float((hi - lo).max()) * pad_fraction
    return lo - pad, hi + pad


def normalize_points(points: np.ndarray, domain: float = DOMAIN_SIZE) -> np.ndarray:
    """Rescale so the longest bbox side maps to [0, domain], preserving aspect.

    Engine-domain contract enforcement, mirroring
    /root/reference/test_knearests.cu:65-78.
    """
    points = np.asarray(points, dtype=np.float32)
    lo, hi = bbox(points)
    extent = float((hi - lo).max())
    if extent <= 0.0:
        # degenerate cloud (single point / all identical): center it instead of
        # dividing by zero -- the engine handles identical points fine
        out = points.astype(np.float64) - lo + domain / 2.0
        return np.ascontiguousarray(out.astype(np.float32))
    scale = domain / extent
    out = (points.astype(np.float64) - lo) * scale
    return np.ascontiguousarray(out.astype(np.float32))


def validate_or_raise(points: np.ndarray, k: Optional[int] = None,
                      domain: float = DOMAIN_SIZE,
                      what: str = "points",
                      dims: Optional[Tuple[int, ...]] = (3,)) -> np.ndarray:
    """THE input front door: every solve route funnels its inputs through
    here (KnnProblem.prepare, the external-query surface, the sharded
    prepare/query, the brute/MXU route, and the CLI), so "what inputs are
    legal, and what happens to the rest" is one tested contract rather than
    scattered checks.

    Legal input (DESIGN.md section 11 has the full table):
      * ``points``: a (n, d) array of finite float coordinates with d drawn
        from ``dims``.  The default ``dims=(3,)`` is the GRID contract: the
        spatial hash linearizes exactly three axes (gridhash.linearize), so
        grid routes refuse other widths with an actionable pointer at the
        dimension-agnostic brute/MXU route (``cuda_knearests_tpu.mxu``,
        DESIGN.md section 16).  ``dims=None`` accepts any d >= 1 -- the
        brute/MXU route's contract, which also skips the domain-bounds
        check below (no grid, no domain; finiteness still holds).
        Coordinates must lie inside ``[0, domain]^d`` when a grid is in
        play (the reference's own contract, knearests.cu:21); n = 0 is
        legal (empty results downstream).
      * ``k`` (when given): a positive integer.  ``k > n`` is legal degraded
        mode -- result rows pad -1/inf beyond the available neighbors, with
        certificates intact -- so it is deliberately NOT rejected here.

    Raises the typed taxonomy (utils/memory.py; every class subclasses
    ValueError, kind='invalid-input'): InvalidShapeError /
    NonFiniteInputError / DomainBoundsError / InvalidKError.  Returns the
    validated (n, d) contiguous float32 array.

    Where the reference silently clamps out-of-range points into boundary
    cells (knearests.cu:26-28) -- quietly corrupting results -- this fails
    fast with a fix pointer.
    """
    if k is not None:
        # bool is an int subclass; k=True sizing a kernel is never intended
        if isinstance(k, bool) or not isinstance(k, (int, np.integer)):
            raise InvalidKError(
                f"k must be a positive integer, got {k!r} (input contract)")
        if k < 1:
            raise InvalidKError(
                f"k must be >= 1, got {k} (input contract; note k > n is "
                f"legal: rows pad -1/inf beyond the available neighbors)")
    try:
        points = np.asarray(points, np.float32)
    except (TypeError, ValueError) as e:
        raise InvalidShapeError(
            f"{what} are not a numeric array: {e} (input contract: "
            f"(n, d) finite float coordinates)") from e
    if points.ndim != 2 or points.shape[1] < 1:
        raise InvalidShapeError(
            f"{what} must be a 2-d (n, d) array, got shape {points.shape} "
            f"(input contract)")
    if dims is not None and points.shape[1] not in dims:
        want = dims[0] if len(dims) == 1 else f"one of {dims}"
        raise InvalidShapeError(
            f"{what} are (n, {points.shape[1]}) but the grid-route input "
            f"contract is (n, {want}) -- the spatial hash linearizes "
            f"exactly that many axes; general-d point sets run on the "
            f"dimension-agnostic brute/MXU route instead "
            f"(cuda_knearests_tpu.mxu.knn / mxu.solve_general, DESIGN.md "
            f"section 16) until the grid hash generalizes")
    if points.size:
        if not np.isfinite(points).all():
            bad = int((~np.isfinite(points)).sum())
            raise NonFiniteInputError(
                f"{what} contain {bad} NaN/inf coordinate(s); clean the "
                f"input first (input contract: finite f32)")
        lo, hi = float(points.min()), float(points.max())
        if dims is not None and (lo < 0.0 or hi > domain):
            raise DomainBoundsError(
                f"{what} span [{lo:.3g}, {hi:.3g}] but the engine domain "
                f"contract is [0, {domain:g}]^3 -- run io.normalize_points "
                f"first (the reference hard-codes the same contract, "
                f"knearests.cu:21)")
    return np.ascontiguousarray(points)


def validate_linking_length(b) -> float:
    """The linking-length half of the FoF front door (cluster/fof.py, the
    serving daemon's ``fof`` requests, and the fuzz --fof campaign all
    funnel through here): ``b`` must be a finite positive real.
    Deliberately domain-independent: a ``b`` wider than the domain
    diagonal is legal degraded mode -- every point links into one
    cluster -- not an error.  Returns float(b)."""
    if isinstance(b, bool) or isinstance(b, (str, bytes)):
        # bool is an int subclass and float('12') would "work": neither is
        # ever an intended linking length
        raise InvalidConfigError(
            f"linking length must be a positive real number, got {b!r} "
            f"(FoF input contract, DESIGN.md section 14)")
    try:
        out = float(b)
    except (TypeError, ValueError) as e:
        raise InvalidConfigError(
            f"linking length must be a positive real number, got {b!r} "
            f"(FoF input contract)") from e
    if not np.isfinite(out) or out <= 0.0:
        raise InvalidConfigError(
            f"linking length must be finite and > 0, got {out!r} (FoF "
            f"input contract; note b > the domain diagonal is legal: "
            f"everything joins one cluster)")
    return out


# Legal request-stream operation kinds (the serving daemon's wire surface).
# 'fof' is the clustering query family (DESIGN.md section 14): payload =
# the linking length, answered against the CURRENT mutated cloud.
REQUEST_KINDS = ("query", "insert", "delete", "fof")


def validate_request(kind: str, payload, *, k=None, k_max: Optional[int] = None,
                     n_current: Optional[int] = None,
                     max_batch: Optional[int] = None,
                     domain: float = DOMAIN_SIZE,
                     tenant: Optional[str] = None,
                     tenants: Optional[Tuple[str, ...]] = None,
                     quota_ok: Optional[bool] = None):
    """The request-stream front door: the per-request twin of
    :func:`validate_or_raise`, enforced by the serving daemon at admission
    (serve/daemon.py) so a malformed request is REFUSED with the typed
    ``InputContractError`` taxonomy instead of crashing the batch it would
    have ridden.

    Legal requests (DESIGN.md section 13):
      * ``('query', (m, 3) coords)`` -- the points contract of
        validate_or_raise against the PREPARED domain bounds, plus
        ``k`` (when given) a positive integer <= ``k_max`` (the serving k
        that sized the hot executables), plus ``m <= max_batch`` (a request
        wider than the largest capacity bucket can never flush).
      * ``('insert', (m, 3) coords)`` -- same points contract (delta
        inserts must land inside the prepared grid's domain; points that
        need normalization are the CALLER's job, exactly as at prepare).
      * ``('delete', (m,) integer ids)`` -- ids must index the CURRENT
        mutated cloud: integer dtype, unique, within [0, n_current).
      * ``('fof', linking_length)`` -- the clustering query family: the
        payload is one finite positive real (validate_linking_length);
        labels are computed over the current mutated cloud.

    Fleet extension (serve/fleet, DESIGN.md section 17) -- the wire
    contract gains a TENANT field: when ``tenants`` (the front door's
    registry) is given, ``tenant`` must name one of them, refused typed
    (UnknownTenantError) otherwise -- never routed to a 'nearest' tenant,
    which would silently answer against the wrong cloud.  ``quota_ok``
    carries the admission controller's token-bucket verdict for THIS
    request (serve/fleet/admission.py computes it; this front door owns
    the refusal's type and text): ``False`` refuses typed
    (OverQuotaError).  Per-tenant k/dims mismatches surface through the
    same ``k_max``/points-contract checks below, with the tenant named in
    the message when one is in play.

    Raises InvalidRequestError (unknown kind / oversized / bad ids),
    UnknownTenantError, OverQuotaError, InvalidKError, InvalidConfigError
    (bad linking length), or the points-contract taxonomy.  Returns the
    validated payload (f32 (m, 3) for query/insert, i64->i32-safe (m,)
    int array for delete, float for fof)."""
    if tenants is not None and tenant not in tenants:
        raise UnknownTenantError(
            f"unknown tenant {tenant!r}: this front door serves "
            f"{tuple(tenants)} (request contract; the tenant field is "
            f"mandatory on fleet wires)")
    if quota_ok is False:
        raise OverQuotaError(
            f"tenant {tenant!r} is over quota: the token-bucket admission "
            f"rate for this tenant is exhausted -- retry after backoff "
            f"(request contract; see ServeFleetConfig quotas)")
    if kind not in REQUEST_KINDS:
        raise InvalidRequestError(
            f"unknown request kind {kind!r}: expected one of "
            f"{REQUEST_KINDS} (request contract)")
    if kind == "fof":
        return validate_linking_length(payload)
    if kind in ("query", "insert"):
        what = "request queries" if kind == "query" else "request inserts"
        out = validate_or_raise(payload, k=k if kind == "query" else None,
                                domain=domain, what=what)
        if kind == "query" and k is not None and k_max is not None \
                and int(k) > int(k_max):
            who = f"tenant {tenant!r}'s" if tenant is not None \
                else "the"
            raise InvalidKError(
                f"request k={int(k)} exceeds {who} serving k={int(k_max)} "
                f"that sized the hot executables (request contract)")
        if max_batch is not None and out.shape[0] > int(max_batch):
            raise InvalidRequestError(
                f"{what} carry {out.shape[0]} rows but the daemon's largest "
                f"capacity bucket is max_batch={int(max_batch)}; split the "
                f"request (request contract)")
        return out
    try:
        ids = np.asarray(payload)
    except (TypeError, ValueError) as e:
        raise InvalidRequestError(
            f"delete ids are not an array: {e} (request contract)") from e
    if ids.ndim != 1 or not np.issubdtype(ids.dtype, np.integer):
        raise InvalidRequestError(
            f"delete ids must be a 1-d integer array, got shape "
            f"{ids.shape} dtype {ids.dtype} (request contract)")
    if ids.size and np.unique(ids).size != ids.size:
        raise InvalidRequestError(
            "delete ids contain duplicates (request contract: each id "
            "deletes one point of the current cloud)")
    if n_current is not None and ids.size:
        lo, hi = int(ids.min()), int(ids.max())
        if lo < 0 or hi >= int(n_current):
            raise InvalidRequestError(
                f"delete ids span [{lo}, {hi}] but the current cloud has "
                f"{int(n_current)} points (request contract: ids index the "
                f"mutated cloud at admission time)")
    return ids


def validate_points(points: np.ndarray,
                    domain: float = DOMAIN_SIZE) -> np.ndarray:
    """Back-compat alias for the points half of :func:`validate_or_raise`
    (the historical name; new code should call the front door directly)."""
    return validate_or_raise(points, domain=domain)


def generate_uniform(n: int, seed: int = 0, domain: float = DOMAIN_SIZE) -> np.ndarray:
    """n i.i.d. uniform points in [0, domain]^3 (regenerates pts300K-style sets)."""
    rng = np.random.default_rng(seed)
    pts = rng.random((n, 3), dtype=np.float64) * domain
    return pts.astype(np.float32)


def generate_clustered(n: int, seed: int = 0, domain: float = DOMAIN_SIZE,
                       blob_fraction: float = 0.6, n_blobs: int = 12,
                       sigma_fraction: float = 0.012) -> np.ndarray:
    """n points with heavy density skew: tight gaussian blobs over a uniform
    background (scaled-up twin of tests/test_adaptive.py's clustered_points).

    This is the workload the adaptive capacity-class planner exists for
    (ops/adaptive.py): a single global candidate capacity must be sized for
    the densest blob and drags every sparse supercell with it, while
    per-class capacities keep the background cheap.  The bench's clustered
    row measures that claim (VERDICT r4 next #8)."""
    rng = np.random.default_rng(seed)
    n_blob_pts = int(n * blob_fraction)
    n_bg = n - n_blob_pts
    centers = rng.uniform(0.15 * domain, 0.85 * domain, (n_blobs, 3))
    sizes = np.full(n_blobs, n_blob_pts // n_blobs, np.int64)
    sizes[: n_blob_pts - int(sizes.sum())] += 1
    blobs = [rng.normal(c, sigma_fraction * domain, (int(m), 3))
             for c, m in zip(centers, sizes)]
    bg = rng.uniform(0, domain, (n_bg, 3))
    pts = np.concatenate(blobs + [bg])
    return np.clip(pts, 0, np.nextafter(domain, 0)).astype(np.float32)


def generate_blue_noise(n: int, seed: int = 0, domain: float = DOMAIN_SIZE) -> np.ndarray:
    """~n blue-noise points in [0, domain]^3 (regenerates *_blue_cube.xyz-style sets).

    Grid-jitter stratified sampling: one sample per cell of an m^3 grid
    (m = ceil(n^(1/3))), uniformly jittered within its cell, then a random subset
    of exactly n.  This has the blue-noise property that matters for the kNN
    workload -- near-uniform local density with a minimum-distance tendency, i.e.
    the grid occupancy histogram is tightly concentrated (cf. SURVEY.md section 5
    "Statistical sanity").
    """
    rng = np.random.default_rng(seed)
    m = int(np.ceil(n ** (1.0 / 3.0)))
    cells = m * m * m
    ijk = np.stack(np.meshgrid(np.arange(m), np.arange(m), np.arange(m), indexing="ij"), axis=-1)
    ijk = ijk.reshape(cells, 3).astype(np.float64)
    jitter = rng.random((cells, 3))
    pts = (ijk + jitter) * (domain / m)
    keep = rng.permutation(cells)[:n]
    keep.sort()
    return pts[keep].astype(np.float32)


_GENERATORS = {
    "pts20K.xyz": lambda: generate_uniform(20626, seed=20),
    "pts300K.xyz": lambda: generate_uniform(300_000, seed=300),
    "300k_blue_cube.xyz": lambda: generate_blue_noise(300_000, seed=301),
    "900k_blue_cube.xyz": lambda: generate_blue_noise(900_000, seed=900),
}

_REFERENCE_FIXTURES = "/root/reference"


def get_dataset(name: str, data_dir: str = "data") -> np.ndarray:
    """Fetch a named dataset, normalized into the engine domain.

    Resolution order: reference checkout (only pts20K.xyz survives there) ->
    cached regenerated copy in `data_dir` -> regenerate via _GENERATORS and cache.
    """
    ref = os.path.join(_REFERENCE_FIXTURES, name)
    if os.path.exists(ref):
        return normalize_points(load_xyz(ref))
    cached = os.path.join(data_dir, name)
    if os.path.exists(cached):
        return normalize_points(load_xyz(cached))
    if name not in _GENERATORS:
        raise FileNotFoundError(f"unknown dataset {name!r}")
    pts = _GENERATORS[name]()
    os.makedirs(data_dir, exist_ok=True)
    save_xyz(cached, pts)
    return normalize_points(pts)
