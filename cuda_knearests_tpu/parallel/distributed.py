"""Multi-host bring-up helpers for the sharded path.

The reference has no inter-device backend at all (single GPU; SURVEY.md
section 2.3).  This framework's communication backend is XLA's own fabric:
``shard_map`` + collectives ride ICI within a host's chips and DCN across
hosts -- there is no NCCL/MPI analog to manage, only process bring-up and a
mesh whose device order keeps neighboring z-slabs on neighboring chips.

Single-host multi-chip needs none of this (``ShardedKnnProblem.prepare``
builds its own mesh).  For a multi-host pod:

    from cuda_knearests_tpu.parallel.distributed import init_distributed, z_mesh
    init_distributed()                  # once per process, before first jax use
    sp = ShardedKnnProblem.prepare(points, mesh=z_mesh())
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize JAX's multi-process runtime (idempotent).

    With no arguments, relies on the cluster environment (TPU pods
    auto-discover); arguments pass through to ``jax.distributed.initialize``
    for manual bring-up.  Safe to call on a single process with no cluster
    environment (a no-op) and safe to call twice (already-initialized is a
    no-op).  Must run before the first JAX computation -- calling it later
    raises rather than silently degrading to independent single-host jobs.
    """
    defaults = (coordinator_address is None and num_processes is None
                and process_id is None)
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg:
            return  # second call: fine
        if not defaults:
            # an explicit cluster spec that failed must always surface
            raise
        if "before" in msg:
            # called after first jax use: harmless on a single process, but
            # on a pod it would silently degrade to independent jobs -- warn
            import warnings

            warnings.warn(
                "init_distributed() called after JAX was already in use; "
                "multi-host bring-up skipped (call it first on pods)",
                RuntimeWarning, stacklevel=2)
        return  # defaults + no cluster environment: single-process run
    except ValueError:
        if defaults:
            return  # no cluster environment to join: single-process run
        raise


def z_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D ("z",) mesh over all (global) devices, ordered so that mesh
    neighbors are physical neighbors where the platform exposes coordinates.

    The sharded solver's only collective is a z-neighbor ``ppermute``
    (parallel/sharded.py); ordering by (process, coords) keeps those exchanges
    on ICI within a host and crosses DCN only at host seams.
    """
    devs = list(devices if devices is not None else jax.devices())

    def key(d):
        coords = getattr(d, "coords", None)
        if coords is not None:
            return (d.process_index, *coords)
        return (d.process_index, d.id)

    devs.sort(key=key)
    return Mesh(np.array(devs), ("z",))
