from . import distributed, sharded
from .distributed import init_distributed, z_mesh
from .sharded import ShardedKnnProblem

__all__ = ["sharded", "distributed", "ShardedKnnProblem", "init_distributed",
           "z_mesh"]
