__all__ = ["sharded"]
