from . import distributed, sharded
from .distributed import init_distributed, z_mesh
from .sharded import ShardedKnnProblem, load_sharded, save_sharded

__all__ = ["sharded", "distributed", "ShardedKnnProblem", "save_sharded",
           "load_sharded", "init_distributed", "z_mesh"]
