from . import sharded

__all__ = ["sharded"]
