"""Multi-chip kNN: per-chip grid slabs over a device mesh with ICI halo exchange.

The reference is strictly single-GPU -- its only "communication" is cudaMemcpy
H2D/D2H (SURVEY.md section 2.3).  This module is the framework's scaling
capability, per the BASELINE.json north star: point sets beyond single-chip
HBM, sharded as contiguous z-slabs across a 1-D ``jax.sharding.Mesh``.

Pipeline (three phases, no global device-resident array at any point):

  1. **Host partition** (numpy): each point's z-cell decides its chip; points
     bucket per chip, padded to the max slab population.  The host never sorts
     globally and never round-trips device arrays -- its working set is the
     input plus O(n/ndev)-sized per-chip buckets.
  2. **Device build + halo exchange** (one ``shard_map`` program): every chip
     sorts its own slab by local cell id (deterministic stable sort -- the
     per-chip counting-sort analog of ops/gridhash.py), builds its local CSR,
     and exchanges fixed-size boundary blocks (points + original ids + counts)
     with its z-neighbors via ``lax.ppermute`` over ICI.
  3. **Per-chip adaptive solve**: each chip plans its own capacity classes
     from its *local* ring occupancy (ops/adaptive machinery over the chip's
     halo-extended window) and solves with per-class kernels -- chip schedules
     are static per chip index, so a dense blob on one chip never inflates
     another chip's tiles (the multi-chip completion of the reference's
     per-query adaptivity, /root/reference/knearests.cu:116).

Correctness: halo depth equals the per-chip planner's maximum dilation radius,
so every candidate box fits the local window and the single-chip completeness
certificates hold verbatim; uncertified stragglers resolve exactly against the
host-side kd-tree oracle (the only place the full point set is touched, and
only on the host).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# jax >= 0.5 exposes shard_map at the top level; 0.4.x keeps it experimental.
# One alias so the build program below works on both.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

from ..config import DOMAIN_SIZE, KnnConfig, default_ring_radius
from ..obs import spans as _spans
from ..runtime import dispatch as _dispatch
from ..utils.memory import InvalidConfigError, InvalidKError
from ..utils.profiling import annotate
from ..ops.adaptive import (ClassPlan, _class_flat, _prepack_kernel_inputs,
                            _rows2d, build_class_specs, select_radii)
from ..ops.gridhash import cell_coords
from ..ops.rings import box_sums, summed_area_table
from ..ops.solve import _FAR, _margin_sq, _round_up, pack_cells
from ..ops.topk import INVALID_ID


def _slab_bounds(dim: int, supercell: int, ndev: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Supercell-aligned z-cell ranges per chip: [zc0[d], zc1[d])."""
    n_sc_z = -(-dim // supercell)
    layers = -(-n_sc_z // ndev)
    zcap = layers * supercell
    zc0 = np.arange(ndev) * zcap
    zc1 = np.minimum(zc0 + zcap, dim)
    zc1 = np.maximum(zc1, np.minimum(zc0, dim))  # empty slabs: zc1 == zc0
    return zc0, zc1, zcap


@dataclasses.dataclass(frozen=True)
class ShardMeta:
    """Host-side static decomposition metadata."""

    ndev: int
    dim: int
    zcap: int
    radius: int     # halo depth == max per-chip dilation radius
    pcap: int       # per-chip point capacity (max slab population, padded)
    hcap: int       # halo block capacity (max boundary-layer population)
    domain: float


def _measured_halo_depth(points: np.ndarray, dim: int, zcap: int,
                         cfg: KnnConfig) -> int:
    """The largest dilation radius any nonempty supercell will select, from
    global cell occupancy (O(cells) host work, no device involvement).

    Per-chip planners later re-derive radii from the identical occupancy
    boxes (window slices of the same counts), so every per-supercell choice
    is <= this depth by construction and candidate boxes always fit the
    halo-extended window.  Capped at the slab thickness: supercells whose
    sparse neighborhood wants more stay uncertified and resolve through the
    exact host fallback."""
    from ..ops.rings import ring_occupancy
    from ..ops.solve import _boxes_grid

    s = cfg.supercell
    rmax = min(zcap, int(min(dim, max(6, 2 * default_ring_radius(
        cfg.k, cfg.density)))))
    # i64 coords so the dim^2 linearization product below cannot wrap (i32
    # passes at dim ~1290, inside the roadmap's scale) -- host-only
    coords = np.clip((points * (dim / DOMAIN_SIZE)).astype(np.int64),  # kntpu-ok: wide-dtype -- linearization headroom (see above)
                     0, dim - 1)
    lin = coords[:, 0] + dim * coords[:, 1] + dim * dim * coords[:, 2]
    counts3 = np.bincount(lin, minlength=dim ** 3).reshape(dim, dim, dim)
    n_sc = -(-dim // s)
    sc = _boxes_grid(n_sc)
    pts_cum, cells_cum = ring_occupancy(counts3, sc, s, rmax)
    radii = select_radii(pts_cum, cells_cum, cfg.k, rmax)
    nonempty = pts_cum[:, 0] > 0
    return max(1, int(radii[nonempty].max()) if nonempty.any() else rmax)


def _partition_host(points: np.ndarray, dim: int, zcap: int, radius: int,
                    ndev: int, domain: float):
    """Bucket points by owning chip (z-cell // zcap).  Pure numpy; the only
    O(n) host work in prepare.  Returns (bucket_pts (ndev, pcap, 3) FAR-pad,
    bucket_ids (ndev, pcap) i32 original index -1-pad, n_local (ndev,),
    pcap, hcap)."""
    n = points.shape[0]
    # i32 on purpose (kntpu-check wide-dtype audit): single-axis z-cell and
    # chip indices stay far below i32 -- the i64 width the first version
    # carried here was accidental, unlike the linearization products above
    cz = np.clip((points[:, 2] * (dim / domain)).astype(np.int32), 0, dim - 1)
    chip = np.minimum(cz // zcap, ndev - 1).astype(np.int32)
    order = np.argsort(chip, kind="stable")
    chip_sorted = chip[order]
    # counts/starts stay i64: per-chip populations cumsum to n, which the
    # roadmap's >2B-point ambition puts past i32 -- host-only bookkeeping
    counts = np.bincount(chip_sorted, minlength=ndev).astype(np.int64)  # kntpu-ok: wide-dtype -- population sums (see above)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pcap = _round_up(int(counts.max()) if n else 1, 8)

    bucket_pts = np.full((ndev, pcap, 3), _FAR, np.float32)
    bucket_ids = np.full((ndev, pcap), -1, np.int32)
    for d in range(ndev):
        rows = order[starts[d]: starts[d] + counts[d]]
        bucket_pts[d, : counts[d]] = points[rows]
        bucket_ids[d, : counts[d]] = rows.astype(np.int32)

    # halo capacity: max points in any chip's R bottom / top z-cell layers --
    # O(n + dim) via one z-layer histogram (chip ownership is a pure function
    # of the z-cell, so per-chip boundary populations are layer-range sums)
    zhist = np.bincount(cz, minlength=dim)
    hmax = 1
    for d in range(ndev):
        zc0 = d * zcap
        hmax = max(hmax,
                   int(zhist[zc0: zc0 + radius].sum()),
                   int(zhist[max(zc0 + zcap - radius, 0): zc0 + zcap].sum()))
    hcap = _round_up(hmax, 8)
    return bucket_pts, bucket_ids, counts.astype(np.int32), pcap, hcap


@functools.lru_cache(maxsize=32)
def _build_program(meta: ShardMeta, mesh: Mesh):
    """Jitted shard_map build program, cached by the (hashable) decomposition
    metadata + mesh so repeated prepares with the same shapes reuse one
    compile."""
    spec = P("z")
    return jax.jit(_shard_map(
        _make_build_fn(meta), mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=(spec,) * 9))


def _make_build_fn(meta: ShardMeta):
    """The shard_map phase-2 program: per-chip sort/CSR + halo ppermute."""
    ndev, dim, zcap, R = meta.ndev, meta.dim, meta.zcap, meta.radius
    A = dim * dim
    ncell = zcap * A
    hcap = meta.hcap
    fwd = [(i, i + 1) for i in range(ndev - 1)]   # chip d -> d+1
    bwd = [(i + 1, i) for i in range(ndev - 1)]   # chip d -> d-1

    def build_fn(bucket_pts, bucket_ids, n_local):
        pts, ids = bucket_pts[0], bucket_ids[0]
        nloc = n_local[0, 0]
        d = jax.lax.axis_index("z")
        zc0 = d * zcap
        pcap = pts.shape[0]
        slots = jnp.arange(pcap, dtype=jnp.int32)
        valid = slots < nloc
        cc = cell_coords(pts, dim, meta.domain)
        lid = cc[:, 0] + dim * cc[:, 1] + A * (cc[:, 2] - zc0)
        lid = jnp.where(valid, lid, ncell)        # pad rows sort last
        order = jnp.argsort(lid, stable=True).astype(jnp.int32)
        spts = jnp.take(pts, order, axis=0)
        sids = jnp.take(ids, order, axis=0)
        slid = jnp.take(lid, order)
        counts = jnp.zeros((ncell,), jnp.int32).at[slid].add(1, mode="drop")

        # boundary blocks: the sorted array is cell-ascending, so the bottom
        # R layers are a prefix and the top R layers are the suffix of the
        # valid region.  Suffix extraction pads by hcap first so
        # dynamic_slice never clamp-shifts the block (which would silently
        # misalign the receiver's CSR).
        tcount = jnp.sum(counts[(zcap - R) * A:])
        spts_ext = jnp.concatenate(
            [spts, jnp.full((hcap, 3), _FAR, spts.dtype)], axis=0)
        sids_ext = jnp.concatenate(
            [sids, jnp.full((hcap,), -1, sids.dtype)], axis=0)
        bot_pts, bot_ids = spts[:hcap], sids[:hcap]
        tstart = jnp.maximum(nloc - tcount, 0)
        top_pts = jax.lax.dynamic_slice_in_dim(spts_ext, tstart, hcap, 0)
        top_ids = jax.lax.dynamic_slice_in_dim(sids_ext, tstart, hcap, 0)
        bot_counts = counts[: R * A]
        top_counts = counts[(zcap - R) * A:]

        if ndev > 1:
            # halo exchange over ICI: my top block becomes my upper
            # neighbor's lower halo and vice versa; edge chips receive zeros
            # (zero counts -> the empty halo is never gathered from).
            lo_pts = jax.lax.ppermute(top_pts, "z", fwd)
            lo_ids = jax.lax.ppermute(top_ids, "z", fwd)
            lo_counts = jax.lax.ppermute(top_counts, "z", fwd)
            hi_pts = jax.lax.ppermute(bot_pts, "z", bwd)
            hi_ids = jax.lax.ppermute(bot_ids, "z", bwd)
            hi_counts = jax.lax.ppermute(bot_counts, "z", bwd)
        else:
            lo_pts = jnp.full_like(top_pts, _FAR)
            lo_ids = jnp.full_like(top_ids, -1)
            lo_counts = jnp.zeros_like(top_counts)
            hi_pts = jnp.full_like(bot_pts, _FAR)
            hi_ids = jnp.full_like(bot_ids, -1)
            hi_counts = jnp.zeros_like(bot_counts)

        pack = (spts, sids, counts, lo_pts, lo_ids, lo_counts,
                hi_pts, hi_ids, hi_counts)
        return tuple(a[None] for a in pack)

    return build_fn


def _window_occupancy(win3: np.ndarray, sc: np.ndarray, s: int, R: int,
                      dim: int, zc0: int, rmax: int):
    """Per-supercell cumulative points/in-grid cells over the chip's
    halo-extended window (the z-slab twin of rings.ring_occupancy).

    win3: (2R+zcap, dim, dim) [z,y,x] counts; sc: (m, 3) chip-local supercell
    coords (z in layers of the local slab).  Boxes are expressed in window
    cell coordinates (z offset +R); in-grid cell counts clip z against the
    *global* grid through the window mapping zw -> zc0 - R + zw."""
    zwin = win3.shape[0]
    base_lo = sc * s + np.array([0, 0, R])
    base_hi = base_lo + s
    sat = summed_area_table(win3)
    z_valid_lo = max(0, R - zc0)
    z_valid_hi = min(zwin, dim + R - zc0)
    # i64 population sums, same contract as rings.ring_occupancy (host-only)
    pts = np.empty((sc.shape[0], rmax + 1), np.int64)    # kntpu-ok: wide-dtype -- population sums (see above)
    cells = np.empty((sc.shape[0], rmax + 1), np.int64)  # kntpu-ok: wide-dtype -- population sums (see above)
    for r in range(rmax + 1):
        lo = base_lo - r
        hi = base_hi + r
        pts[:, r] = box_sums(win3, lo, hi, sat=sat)
        cx = (np.clip(hi[:, 0], 0, dim) - np.clip(lo[:, 0], 0, dim))
        cy = (np.clip(hi[:, 1], 0, dim) - np.clip(lo[:, 1], 0, dim))
        cz = (np.clip(hi[:, 2], z_valid_lo, z_valid_hi)
              - np.clip(lo[:, 2], z_valid_lo, z_valid_hi))
        cells[:, r] = cx * cy * np.maximum(cz, 0)
    return pts, cells


def _window_box_cells(sc: np.ndarray, lo_off: int, hi_off: int, s: int,
                      dim: int, R: int, zc0: int, zwin: int) -> np.ndarray:
    """Linear window-cell ids of [sc*s+lo_off, sc*s+s+hi_off) per supercell,
    -1 where outside the grid (x/y) or outside the global z range (z).
    Window linearization: x + dim*y + dim^2*zw with zw = local z + R."""
    side = s + hi_off - lo_off
    # i64 intermediates so the dim^2 window linearization below cannot wrap
    # before its terminal i32 cast (the output cell ids are i32 by contract,
    # which bounds dim^2*zwin < 2^31 -- the headroom covers the arithmetic,
    # not the result)
    offs = np.arange(lo_off, s + hi_off, dtype=np.int64)             # kntpu-ok: wide-dtype -- linearization headroom (see above)
    ax = sc[:, :, None].astype(np.int64) * s + offs[None, None, :]   # kntpu-ok: wide-dtype -- linearization headroom (see above)
    x, y, z = ax[:, 0], ax[:, 1], ax[:, 2] + R       # z into window coords
    okx = (x >= 0) & (x < dim)
    oky = (y >= 0) & (y < dim)
    # window z must be inside the window AND map to a real global layer
    gz = z + zc0 - R
    okz = (z >= 0) & (z < zwin) & (gz >= 0) & (gz < dim)
    xc = np.clip(x, 0, dim - 1)
    yc = np.clip(y, 0, dim - 1)
    zc = np.clip(z, 0, zwin - 1)
    lin = (xc[:, None, None, :] + dim * yc[:, None, :, None]
           + dim * dim * zc[:, :, None, None])
    valid = (okx[:, None, None, :] & oky[:, None, :, None]
             & okz[:, :, None, None])
    return np.where(valid, lin, -1).reshape(sc.shape[0], side ** 3).astype(np.int32)


@dataclasses.dataclass(frozen=True)
class ChipPlan:
    """One chip's static adaptive schedule (classes over its local window).

    class_of / row_of: (n_sc_local,) host arrays mapping every chip-local
    supercell to its class (-1 = dropped/empty) and row within that class --
    external queries bucket through these (query()).
    """

    classes: Tuple[ClassPlan, ...]
    class_of: np.ndarray
    row_of: np.ndarray


def _plan_chip(counts_all: np.ndarray, d: int, meta: ShardMeta,
               cfg: KnnConfig, on_kernel_platform: bool) -> ChipPlan:
    """Adaptive class partition from chip d's local ring occupancy.

    counts_all: (ndev, zcap*A) host copies of every chip's cell counts (the
    only per-point-scale data the host reads back, at 4 bytes/cell)."""
    dim, zcap, R, s = meta.dim, meta.zcap, meta.radius, cfg.supercell
    A = dim * dim
    mk3 = lambda c: c.reshape(zcap, dim, dim)
    # i64 cell counts: the window feeds summed_area_table, whose prefix
    # sums reach the total population (see rings.summed_area_table)
    zeros = np.zeros((R, dim, dim), np.int64)                                # kntpu-ok: wide-dtype -- population sums (see above)
    lo3 = (mk3(counts_all[d - 1])[-R:] if d > 0 else zeros)
    hi3 = (mk3(counts_all[d + 1])[:R] if d + 1 < meta.ndev else zeros)
    win3 = np.concatenate([lo3, mk3(counts_all[d]).astype(np.int64), hi3])   # kntpu-ok: wide-dtype -- population sums (see above)

    n_sc_xy = -(-dim // s)
    layers = zcap // s
    r = np.arange(n_sc_xy, dtype=np.int32)
    lz = np.arange(layers, dtype=np.int32)
    zz, yy, xx = np.meshgrid(lz, r, r, indexing="ij")
    sc = np.stack([xx.ravel(), yy.ravel(), zz.ravel()], axis=1)

    zc0 = d * meta.zcap
    if cfg.ring_radius is not None:
        rmax = min(R, max(1, int(cfg.ring_radius)))
        pts_cum, _ = _window_occupancy(win3, sc, s, R, dim, zc0, rmax)
        radii_all = np.full((sc.shape[0],), rmax, np.int32)
    else:
        rmax = R
        pts_cum, cells_cum = _window_occupancy(win3, sc, s, R, dim, zc0, rmax)
        radii_all = select_radii(pts_cum, cells_cum, cfg.k, rmax)

    own_n = pts_cum[:, 0]
    specs = build_class_specs(own_n, pts_cum, radii_all, cfg,
                              on_kernel_platform)
    w = meta.domain / dim
    zwin = win3.shape[0]
    classes = []
    class_of = np.full((sc.shape[0],), -1, np.int32)
    row_of = np.zeros((sc.shape[0],), np.int32)
    for ci, spec in enumerate(specs):
        class_of[spec.rows] = ci
        row_of[spec.rows] = np.arange(spec.rows.size, dtype=np.int32)
    for spec in specs:
        sc_c = sc[spec.rows]
        own = _window_box_cells(sc_c, 0, 0, s, dim, R, zc0, zwin)
        cand = _window_box_cells(sc_c, -spec.radius, spec.radius, s, dim, R,
                                 zc0, zwin)
        # certificate boxes in GLOBAL domain coordinates (z offset by zc0)
        gsc = sc_c + np.array([0, 0, zc0 // s])
        lo = ((gsc * s - spec.radius) * w).astype(np.float32)
        hi = ((gsc * s + s + spec.radius) * w).astype(np.float32)
        classes.append(ClassPlan(
            own=jnp.asarray(own), cand=jnp.asarray(cand),  # kntpu-ok: jnp-in-loop -- prepare-time, <= max_classes tables per chip
            lo=jnp.asarray(lo), hi=jnp.asarray(hi),        # kntpu-ok: jnp-in-loop -- prepare-time, <= max_classes tables per chip
            radius=spec.radius, qcap=spec.qcap, qcap_pad=spec.qcap_pad,
            ccap=spec.ccap, route=spec.route))
    return ChipPlan(classes=tuple(classes), class_of=class_of, row_of=row_of)


def _assemble_ext(spts, sids, counts, lo_pts, lo_ids, lo_counts,
                  hi_pts, hi_ids, hi_counts, hcap: int):
    """Halo-extended point/id/CSR arrays: lower halo | local | upper halo."""
    pcap = spts.shape[0]
    ext_pts = jnp.concatenate([lo_pts, spts, hi_pts], axis=0)
    ext_ids = jnp.concatenate([lo_ids, sids, hi_ids], axis=0)
    mk_starts = lambda c: jnp.cumsum(c) - c
    ext_starts = jnp.concatenate([
        mk_starts(lo_counts),
        mk_starts(counts) + hcap,
        mk_starts(hi_counts) + hcap + pcap]).astype(jnp.int32)
    ext_counts = jnp.concatenate([lo_counts, counts, hi_counts])
    return ext_pts, ext_ids, ext_starts, ext_counts


@functools.partial(jax.jit, static_argnames=("hcap", "k"))
def _chip_ready_state(spts, sids, counts, lo_pts, lo_ids, lo_counts,
                      hi_pts, hi_ids, hi_counts,
                      classes: Tuple[ClassPlan, ...], hcap: int, k: int):
    """One chip's static solve state, built once per problem (the sharded
    analog of the single-chip plan-time prepack).

    Assembles the halo-extended point/CSR arrays (lower halo | local | upper
    halo), prepacks each pallas-routed class's kernel inputs against them,
    and inverts the slot partition for the LOCAL rows only -- steady-state
    solves are then per-class launches + one row gather, with no per-solve
    packing or scatter (measured 3.3x on the single-chip path, DESIGN.md).

    Returns (spts, ext arrays, classes-with-pk,
    inv_loc = (pcap,) output-row index map for the local rows (see
    AdaptivePlan.inv_row), lo_rows/hi_rows (pcap, 3) certificate boxes per
    local row).
    """
    pcap = spts.shape[0]
    ext_pts, ext_ids, ext_starts, ext_counts = _assemble_ext(
        spts, sids, counts, lo_pts, lo_ids, lo_counts, hi_pts, hi_ids,
        hi_counts, hcap)

    from ..ops.adaptive import _class_inverse_update

    n_ext = ext_pts.shape[0]
    inv_row = jnp.zeros((n_ext,), jnp.int32)
    inv_box = jnp.zeros((n_ext,), jnp.int32)
    row_off = box_off = 0
    packed = []
    for cp in classes:
        if cp.route == "pallas":
            cp = dataclasses.replace(cp, pk=_prepack_kernel_inputs(
                ext_pts, ext_starts, ext_counts, cp.own, cp.cand,
                cp.qcap_pad, cp.ccap))
        # invert this class's slot partition (local rows only own slots
        # here: own cells never cover halo layers) via the shared layout
        # encoder -- one source of truth for the output-row index maps
        inv_row, inv_box, row_off, box_off, tgt = (
            _class_inverse_update(inv_row, inv_box, cp,
                                  ext_starts, ext_counts, n_ext,
                                  row_off, box_off))
        # forward map for the scatter epilogue, in LOCAL row units: valid
        # slots hold ext indices in [hcap, hcap + pcap) (own cells never
        # cover halo layers), the n_ext sentinel lands at pcap + hcap and
        # is dropped by the (pcap, k) scatter.  mode='drop' only protects
        # the high side: if the own-cells-never-cover-halo invariant ever
        # broke, an ext index below hcap would go negative and JAX scatter
        # indexing wraps negatives into arbitrary local rows -- silently
        # wrong yet certifiable.  Route such slots to the dropped sentinel
        # instead (trace-safe; this runs under jit): the starved local row
        # then keeps its init values and fails its certificate -- loud,
        # never wrong.
        tgt_loc = tgt - hcap
        packed.append(dataclasses.replace(
            cp, tgt=jnp.where(tgt_loc < 0, n_ext - hcap, tgt_loc)))

    loc = slice(hcap, hcap + pcap)
    box_loc = inv_box[loc]
    lo_rows = jnp.take(jnp.concatenate([cp.lo for cp in classes], axis=0),
                       box_loc, axis=0)
    hi_rows = jnp.take(jnp.concatenate([cp.hi for cp in classes], axis=0),
                       box_loc, axis=0)
    return (spts, ext_pts, ext_ids, ext_starts, ext_counts, tuple(packed),
            inv_row[loc], lo_rows, hi_rows)


@functools.partial(jax.jit, static_argnames=("k", "exclude_self", "domain",
                                             "interpret", "tile", "kernel",
                                             "epilogue", "recall_target"))
def _chip_solve(spts, ext_pts, ext_ids, ext_starts, ext_counts,
                classes: Tuple[ClassPlan, ...], inv_loc, lo_rows, hi_rows,
                k: int, exclude_self: bool, domain: float, interpret: bool,
                tile: int, kernel: str = "kpass", epilogue: str = "gather",
                recall_target: float = 1.0):
    """One chip's steady-state solve over its prepared state: per-class
    launches (prepacked kernel inputs for pallas routes), the local-row
    un-pad (epilogue='gather': row-major concat + one gather through
    inv_loc; 'scatter': row-major kernel output placed directly through the
    per-class forward maps -- see adaptive._scatter_classes), original-id
    translation through the exchanged id blocks, and the completeness
    certificate.  Returns ((pcap, k) original-id neighbors, (pcap, k) d2
    ascending, (pcap,) certified), rows in local sorted order; pad rows
    (beyond the slab population) carry unread filler either way.
    """
    pcap = spts.shape[0]
    if epilogue == "scatter":
        from ..ops.adaptive import _scatter_classes

        row_d, row_i = _scatter_classes(
            ext_pts, ext_starts, ext_counts, classes, pcap, k, exclude_self,
            tile, interpret, kernel, recall_target)
    else:
        flats_d, flats_i = [], []
        for cp in classes:
            fd, fi = _class_flat(ext_pts, ext_starts, ext_counts, cp, k,
                                 exclude_self, tile, interpret, kernel,
                                 recall_target)
            flats_d.append(fd)
            flats_i.append(fi)
        all_d, all_i = _rows2d(flats_d, flats_i, classes, k)
        row_d = jnp.take(all_d, inv_loc, axis=0)             # (pcap, k)
        row_i = jnp.take(all_i, inv_loc, axis=0)
    # raw k-th BEFORE sanitization (blocked-kernel deficit rows carry NaN)
    raw_kth = row_d[:, k - 1]
    ok = jnp.isfinite(row_d)
    row_i = jnp.where(ok, row_i, INVALID_ID)
    row_d = jnp.where(ok, row_d, jnp.inf)
    # extended index -> original id, via the exchanged id blocks
    n_ext = ext_pts.shape[0]
    nbr_orig = jnp.where(
        row_i >= 0,
        jnp.take(ext_ids, jnp.clip(row_i, 0, n_ext - 1), axis=0),
        INVALID_ID)
    cert = raw_kth <= _margin_sq(spts[:, None, :], lo_rows, hi_rows,
                                 domain)[:, 0]
    return nbr_orig, row_d, cert


def save_sharded(problem: "ShardedKnnProblem", path: str) -> None:
    """Checkpoint a sharded problem to one ``.npz`` ('.npz' appended when
    missing), the multi-chip counterpart of api.save_problem.

    What persists is the *input contract* -- points, config, grid dim --
    not per-chip device state: the decomposition, build, and planning are
    deterministic, so resume = re-prepare, which also re-binds the problem
    to whatever mesh the resuming process has (checkpoints move freely
    between mesh sizes and hosts)."""
    import json

    from ..api import _npz_path

    path = _npz_path(path)
    cfg = dataclasses.asdict(problem.config)
    np.savez_compressed(
        path,
        points=problem._points_host,
        dim=np.int64(problem.meta.dim),        # kntpu-ok: wide-dtype -- on-disk checkpoint schema (api.save_problem parity)
        n_devices=np.int64(problem.meta.ndev),  # kntpu-ok: wide-dtype -- on-disk checkpoint schema (api.save_problem parity)
        config_json=np.bytes_(json.dumps(
            {k: v for k, v in cfg.items() if v is not None}).encode()))


def load_sharded(path: str, n_devices: Optional[int] = None,
                 mesh: Optional[Mesh] = None) -> "ShardedKnnProblem":
    """Resume a checkpointed sharded problem (see save_sharded).  The mesh
    defaults to the checkpoint's device count; pass ``n_devices``/``mesh``
    to re-shard onto a different topology."""
    import json

    from ..api import _npz_path

    with np.load(_npz_path(path)) as z:
        cfg = KnnConfig(**json.loads(bytes(z["config_json"]).decode()))
        points = z["points"]
        dim = int(z["dim"])
        if n_devices is None and mesh is None:
            n_devices = int(z["n_devices"])
    return ShardedKnnProblem.prepare(points, n_devices=n_devices,
                                     config=cfg, mesh=mesh, dim=dim)


@dataclasses.dataclass
class ShardedKnnProblem:
    """Multi-chip analog of api.KnnProblem: one prepared problem over a mesh.

    The reference has no counterpart -- this is the "sharded 10M points over
    v5e-8 ICI" capability from BASELINE.json.  Unlike rounds 1-2, prepare
    never builds a global device grid: each chip builds and owns its slab.
    """

    config: KnnConfig
    mesh: Mesh
    meta: ShardMeta
    n_points: int
    chip_plans: List[ChipPlan]
    # device state (sharded over the mesh, leading axis = chip)
    dev: Dict[str, jax.Array] = dataclasses.field(default_factory=dict,
                                                  repr=False)
    _points_host: Optional[np.ndarray] = dataclasses.field(default=None,
                                                           repr=False)
    _oracle_cache: Optional[object] = dataclasses.field(default=None,
                                                        repr=False)
    _ready_cache: Dict[int, tuple] = dataclasses.field(default_factory=dict,
                                                       repr=False)
    _solved_cache: Optional[tuple] = dataclasses.field(default=None,
                                                       repr=False)
    _device_out_cache: Optional[dict] = dataclasses.field(default=None,
                                                          repr=False)

    def _oracle(self):
        """Host kd-tree over the full set, built once per problem (the exact
        resolver for uncertified rows; _points_host is immutable)."""
        if self._oracle_cache is None:
            from ..oracle import KdTreeOracle

            self._oracle_cache = KdTreeOracle(self._points_host)
        return self._oracle_cache

    @classmethod
    def prepare(cls, points, n_devices: Optional[int] = None,
                config: Optional[KnnConfig] = None,
                mesh: Optional[Mesh] = None,
                dim: Optional[int] = None) -> "ShardedKnnProblem":
        from ..api import _resolve_tuned_for
        from ..config import grid_dim_for
        from ..io import validate_or_raise

        config = _resolve_tuned_for(config or KnnConfig(), points)
        if config.backend == "oracle":
            raise InvalidConfigError(
                "backend='oracle' is a single-chip host engine; the sharded "
                "path runs grid engines only ('auto'/'pallas'/'xla')")
        if config.resolved_scorer() == "mxu":
            # the PR 9 typed refusal is LIFTED (ISSUE 12): per-chip class
            # solves now thread recall_target into the shared class
            # machinery (build_class_specs routes eligible classes to the
            # MXU scorer; _chip_solve passes the per-chip G*m pool budget
            # through _class_flat/_scatter_classes), so the approximate
            # frontier and pod scale multiply.  Only the arithmetic
            # contract still gates, via the SAME shared predicate the
            # single-chip guard reads -- prepare-time guard and solve-time
            # routing cannot disagree.
            from ..api import _config_adaptive_eligible

            if not _config_adaptive_eligible(config, per_chip=True):
                raise InvalidConfigError(
                    f"scorer='mxu' (recall_target={config.recall_target}) "
                    f"composes with the per-chip class solves only under "
                    f"dist_method='diff' (got {config.dist_method!r}): "
                    f"the class scorers realize distances in diff "
                    f"arithmetic")
        if mesh is None:
            n_devices = n_devices or len(jax.devices())
            mesh = jax.make_mesh((n_devices,), ("z",))
        ndev = mesh.devices.size
        points = validate_or_raise(points, k=config.k)
        n = points.shape[0]
        if dim is None:
            dim = grid_dim_for(n, config.density)
        dim = int(dim)
        zc0, zc1, zcap = _slab_bounds(dim, config.supercell, ndev)

        # Halo depth = the max dilation radius any nonempty supercell will
        # actually select -- measured on the host from O(cells) occupancy,
        # not assumed.  Thin slabs with a worst-case halo would otherwise
        # carry boundary blocks rivaling the slab itself (uniform data only
        # needs radius ~2 where the planner's ceiling is 6).
        if config.ring_radius is not None:
            radius = max(1, int(config.ring_radius))
            if zcap < radius:
                raise InvalidConfigError(
                    f"slab thickness {zcap} cells < halo depth {radius}: "
                    f"halo would span multiple chips. Use fewer devices, a "
                    f"larger supercell, or a smaller ring radius "
                    f"(dim={dim}, ndev={ndev}).")
        else:
            radius = _measured_halo_depth(points, dim, zcap, config)

        meta_pts, meta_ids, n_local, pcap, hcap = _partition_host(
            points, dim, zcap, radius, ndev, DOMAIN_SIZE)
        meta = ShardMeta(ndev=ndev, dim=dim, zcap=zcap, radius=radius,
                         pcap=pcap, hcap=hcap, domain=DOMAIN_SIZE)

        spec = P("z")
        build = _build_program(meta, mesh)
        out = build(
            jax.device_put(meta_pts,
                           jax.sharding.NamedSharding(mesh, spec)),
            jax.device_put(meta_ids,
                           jax.sharding.NamedSharding(mesh, spec)),
            jax.device_put(n_local.reshape(ndev, 1),
                           jax.sharding.NamedSharding(mesh, spec)))
        names = ("spts", "sids", "counts", "lo_pts", "lo_ids", "lo_counts",
                 "hi_pts", "hi_ids", "hi_counts")
        dev = dict(zip(names, out))

        # per-chip adaptive planning from the (small) cell-count readback.
        # Multi-host: device_get needs a fully-addressable array, and chips at
        # process seams need their DCN-neighbor's counts for halo sizing
        # (_plan_chip reads counts_all[d-1]/[d+1]) -- allgather the per-chip
        # count blocks (4 bytes/cell) so every process plans every chip.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            local = sorted(
                (int(sh.index[0].start or 0),
                 np.asarray(sh.data).reshape(sh.data.shape[1:]))
                for sh in dev["counts"].addressable_shards)
            # the (nproc, local, ...) -> (ndev, ...) reshape below is only
            # valid when the mesh is process-major (process p owns the
            # contiguous chips [p*local, (p+1)*local), as z_mesh guarantees);
            # anything else would silently plan every chip from another
            # chip's occupancy
            nloc = len(local)
            expect0 = jax.process_index() * nloc
            got = [idx for idx, _ in local]
            # raise *collectively*: allgather a per-process ok flag first so a
            # bad mesh fails fast on every process with the message, instead
            # of the passing processes entering the counts allgather and
            # hanging until the distributed timeout
            ok = got == list(range(expect0, expect0 + nloc))
            all_ok = np.asarray(multihost_utils.process_allgather(
                np.asarray([ok], dtype=np.bool_))).reshape(-1)
            if not all_ok.all():
                bad = [p for p, o in enumerate(all_ok) if not o]
                mine = ("" if ok else
                        f"; this process owns mesh positions {got}, expected "
                        f"{list(range(expect0, expect0 + nloc))}")
                raise ValueError(  # kntpu-ok: bare-valueerror -- mesh-topology/runtime contract, not point-input validation
                    f"multi-host mesh is not process-major on process(es) "
                    f"{bad}{mine}; build the mesh with "
                    f"parallel.distributed.z_mesh()")
            loc_block = np.stack([blk for _, blk in local])
            counts_all = np.asarray(
                multihost_utils.process_allgather(loc_block)).reshape(
                    ndev, *loc_block.shape[1:])
        else:
            counts_all = np.asarray(jax.device_get(dev["counts"]))
        # explicit backend='xla' pins every class to the streamed route, like
        # the single-chip pick_backend policy
        on_kernel = (config.backend != "xla"
                     and (jax.devices()[0].platform == "tpu"
                          or config.interpret))
        chip_plans = [_plan_chip(counts_all, d, meta, config, on_kernel)
                      for d in range(ndev)]
        return cls(config=config, mesh=mesh, meta=meta, n_points=n,
                   chip_plans=chip_plans, dev=dev, _points_host=points)

    # -- per-chip shard access ------------------------------------------------

    def local_chips(self) -> List[int]:
        """Global mesh positions of the chips THIS process can address.
        Single-process (and the emulated CPU mesh): all of them.  On a
        multi-host mesh each process sees only its own chips -- the build
        phase (shard_map + ppermute) is SPMD across hosts, and each host then
        drives the solve for its local slabs."""
        arr = next(iter(self.dev.values()))
        return sorted(int(sh.index[0].start or 0)
                      for sh in arr.addressable_shards)

    def _chip_inputs(self, d: int):
        """Device-resident shard of chip (mesh position) d for every build
        output -- no cross-device copies: addressable_shards hands back the
        block already living on that chip."""
        out = {}
        for name, arr in self.dev.items():
            shard = next(sh for sh in arr.addressable_shards
                         if int(sh.index[0].start or 0) == d)
            out[name] = shard.data.reshape(shard.data.shape[1:])
        return out

    def _chip_ready(self, d: int):
        """Chip d's static solve state (halo-extended arrays, prepacked
        classes, local-row inversion), built once per problem and cached --
        the sharded analog of the single-chip plan-time prepack.

        Footprint: the cache pins roughly an extra copy of the chip's
        halo-extended point set plus the per-class prepacked coordinate/id
        blocks in that chip's HBM for the problem's lifetime (both
        ``solve_device()`` and ``query()`` build it).  That is the price of
        the 3.3x prepack win (DESIGN.md section 4b); memory-tight or
        query-heavy workloads can release it between batches with
        :meth:`drop_ready`."""
        if not self.chip_plans[d].classes:
            raise ValueError(  # kntpu-ok: bare-valueerror -- internal invariant (callers skip empty slabs), not input validation
                f"chip {d} has an empty class schedule")
        if d not in self._ready_cache:
            inp = self._chip_inputs(d)
            self._ready_cache[d] = _chip_ready_state(
                inp["spts"], inp["sids"], inp["counts"],
                inp["lo_pts"], inp["lo_ids"], inp["lo_counts"],
                inp["hi_pts"], inp["hi_ids"], inp["hi_counts"],
                self.chip_plans[d].classes, hcap=self.meta.hcap,
                k=self.config.k)
        return self._ready_cache[d]

    def drop_ready(self, chip: Optional[int] = None) -> None:
        """Release the cached per-chip solve state (see _chip_ready's
        footprint note) -- all chips, or one mesh position.  The next
        solve/query rebuilds it (one extend + prepack program per chip; the
        underlying build outputs in ``self.dev`` are untouched)."""
        if chip is None:
            self._ready_cache.clear()
            self._device_out_cache = None
        else:
            self._ready_cache.pop(chip, None)
            if self._device_out_cache is not None:
                self._device_out_cache.pop(chip, None)

    def solve_device(self):
        """Run every process-local chip's adaptive solve, results
        device-resident.

        Returns {mesh position: (orig_ids (pcap, k), d2 (pcap, k),
        cert (pcap,)) or None for empty slabs}, each value resident on its
        chip.  Dispatch is a host loop but execution overlaps: jit dispatch
        is asynchronous and each chip's program runs on its own device.  Chip
        schedules are static per chip index (per-chip capacity classes), so
        one chip's dense blob never sizes another chip's tiles.  On a
        multi-host mesh each process solves its own slabs (local_chips());
        host assembly (solve()) is single-controller.
        """
        cfg, meta = self.config, self.meta
        epilogue = cfg.resolved_epilogue()
        outs = {}
        with _spans.span("solve.sharded.chips",
                         chips=len(self.local_chips())), \
                annotate("kntpu:sharded-chip-solves"):
            for d in self.local_chips():
                if not self.chip_plans[d].classes:  # empty slab: no work
                    outs[d] = None
                    continue
                (spts, ext_pts, ext_ids, ext_starts, ext_counts, classes,
                 inv_loc, lo_rows, hi_rows) = self._chip_ready(d)
                outs[d] = _chip_solve(
                    spts, ext_pts, ext_ids, ext_starts,
                    ext_counts, classes, inv_loc, lo_rows, hi_rows,
                    cfg.k, cfg.exclude_self, meta.domain, cfg.interpret,
                    cfg.stream_tile, cfg.effective_kernel(), epilogue,
                    float(cfg.recall_target))
        # memoized for stats() margin telemetry (released by drop_ready)
        self._device_out_cache = outs
        return outs

    def get_planes(self, solved=None, device_out=None) -> np.ndarray:
        """(n, k, 4) f32 Voronoi bisector-plane feed of the sharded
        all-points solve -- the multi-chip twin of
        api.KnnProblem.get_planes() (cluster/planes.py has the [n, d]
        contract and the f64 precision rationale).  Pass ``solved`` (a
        previous ``solve()`` tuple) or ``device_out`` to reuse results;
        single-controller, like solve()."""
        from ..cluster.planes import bisector_planes

        neighbors = (solved[0] if solved is not None
                     else self.solve(device_out=device_out)[0])
        return bisector_planes(self._points_host, self._points_host,
                               neighbors)

    def query(self, queries, k: Optional[int] = None, planes: bool = False
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact kNN of arbitrary query coordinates against the sharded set.

        The multi-chip twin of api.KnnProblem.query(): each query routes to
        the chip owning its z-slab and rides that chip's class schedule over
        the halo-extended window (a query inside a slab has its whole
        candidate box inside that chip's window, so certificates hold
        verbatim).  No self-exclusion; classless and uncertified rows resolve
        exactly against the host oracle.  Single-controller, like solve().

        Returns ((m, k) ids in ORIGINAL indexing, ascending; (m, k) squared
        distances), rows in query order -- plus, with ``planes=True``, the
        (m, k, 4) Voronoi bisector-plane feed (cluster/planes.py), same
        contract as api.KnnProblem.query(planes=True).
        """
        from ..ops.adaptive import launch_class_query

        from ..io import validate_or_raise

        cfg, meta = self.config, self.meta
        k = cfg.k if k is None else k
        queries = validate_or_raise(queries, k=k, what="queries")
        k = int(k)
        if k > cfg.k:
            raise InvalidKError(
                f"k={k} exceeds the prepared k={cfg.k} (it sized the "
                f"candidate dilation)")
        chips = self.local_chips()
        if len(chips) < meta.ndev:
            raise RuntimeError(
                f"query() needs all {meta.ndev} slabs addressable; this "
                f"process sees chips {chips}")
        queries = np.ascontiguousarray(queries, np.float32)
        m = queries.shape[0]
        if m == 0:
            empty = (np.empty((0, k), np.int32),
                     np.empty((0, k), np.float32))
            if planes:
                return empty + (np.zeros((0, k, 4), np.float32),)
            return empty
        dim, s = meta.dim, cfg.supercell
        # i64 coords: the per-chip scidx linearization below multiplies by
        # n_sc_xy^2 (same wrap-before-cast headroom as _measured_halo_depth)
        coords = np.clip((queries * (dim / meta.domain)).astype(np.int64),  # kntpu-ok: wide-dtype -- linearization headroom (see above)
                         0, dim - 1)
        owner = np.minimum(coords[:, 2] // meta.zcap, meta.ndev - 1)
        n_sc_xy = -(-dim // s)

        out_i = np.full((m, k), INVALID_ID, np.int32)
        out_d = np.full((m, k), np.inf, np.float32)
        cert = np.zeros((m,), bool)
        pending = []  # (dest rows, device r_i, r_d, r_c) per class launch
        for d in chips:
            on_d = np.nonzero(owner == d)[0]
            if on_d.size == 0:
                continue
            plan = self.chip_plans[d]
            if not plan.classes:
                # empty slab: no grid route for these queries; leave them
                # uncertified so the exact oracle pass below resolves them
                continue
            # the prepared chip state: ext arrays + classes with prepacked
            # kernel inputs (their candidate halves are reused per class)
            (_, ext_pts, ext_ids, ext_starts, ext_counts, classes,
             _, _, _) = self._chip_ready(d)
            cc = coords[on_d]
            scidx = ((cc[:, 2] - d * meta.zcap) // s * (n_sc_xy ** 2)
                     + (cc[:, 1] // s) * n_sc_xy + (cc[:, 0] // s))
            qcls = plan.class_of[scidx]
            qrow = plan.row_of[scidx]
            for ci, cp in enumerate(classes):
                sel = on_d[qcls == ci]
                if sel.size == 0:
                    continue
                # ids_map=ext_ids translates ext indices to ORIGINAL ids on
                # device, so readback is O(m*k) -- not the whole id block.
                # No readback happens HERE: every chip's every class launch
                # dispatches back-to-back and the results collect below in
                # one batched fetch (the one-sync contract, DESIGN.md s12)
                order, r_i, r_d, r_c = launch_class_query(
                    ext_pts, ext_starts, ext_counts, cp, queries[sel],
                    qrow[qcls == ci], k, cfg, meta.domain, ids_map=ext_ids)
                pending.append((sel[order], r_i, r_d, r_c))

        # the one sync: a single batched readback across every chip's
        # per-class results (device_get batches across devices), then the
        # host placement is pure numpy
        for rows, h_i, h_d, h_c in _dispatch.fetch(pending):  # syncflow: sharded-query-final
            out_i[rows] = h_i  # fetch() already landed host numpy
            out_d[rows] = h_d
            cert[rows] = h_c

        if not cert.all():
            bad = np.nonzero(~cert)[0].astype(np.int32)
            b_i, b_d = self._oracle().knn(queries[bad], k)  # no self-exclusion
            out_i[bad] = b_i
            out_d[bad] = b_d
        if planes:
            from ..cluster.planes import bisector_planes

            return out_i, out_d, bisector_planes(queries, self._points_host,
                                                 out_i)
        return out_i, out_d

    def query_radius(self, queries, radius: float,
                     max_neighbors: Optional[int] = None):
        """All stored points within ``radius`` of each query (capped) -- the
        sharded twin of api.KnnProblem.query_radius, thin over query().

        The k-NN rows are globally exact (certificate or oracle resolution),
        so the radius mask is exact for any radius; the only possible
        incompleteness is the cap, flagged per query via ``truncated``.
        Returns (ids (m, cap) original indexing, -1 beyond count; d2 (m, cap)
        ascending, inf beyond; counts (m,); truncated (m,))."""
        from ..api import radius_mask_from_knn

        cap = self.config.k if max_neighbors is None else int(max_neighbors)
        if cap > self.config.k:
            raise InvalidKError(
                f"max_neighbors={cap} exceeds the prepared k={self.config.k}")
        ids, d2 = self.query(queries, k=cap)
        return radius_mask_from_knn(ids, d2, radius, cap)

    def get_edges(self, symmetric: bool = False, device_out=None,
                  solved=None) -> np.ndarray:
        """kNN graph as a COO edge list (E, 2) of original point ids -- the
        sharded twin of api.KnnProblem.get_edges, thin over solve().

        Like the single-chip twin after ``solve()``, the no-arg call is a
        cheap readback: solve() memoizes its assembled triple on the problem,
        so only the first call (on a never-solved problem) pays a full solve.
        Pass ``solved`` (a ``solve()`` triple) or ``device_out`` (a
        ``solve_device()`` dict) to use other results explicitly."""
        from ..api import edges_from_neighbors

        if solved is None:
            if device_out is not None:
                solved = self.solve(device_out=device_out)
            else:
                solved = self._solved_cache or self.solve()
        neighbors = solved[0]
        return edges_from_neighbors(neighbors, symmetric)

    def stats(self) -> dict:
        """Decomposition + per-chip schedule diagnostics, machine-readable --
        the multi-chip extension of api.KnnProblem.stats() (C6 parity,
        /root/reference/knearests.cu:440-466)."""
        from ..utils.stats import occupancy_stats

        from ..utils.stats import _margin_sq_np, margin_summary

        meta = self.meta
        chips = []
        for d in self.local_chips():
            inp = self._chip_inputs(d)
            # diagnostics path: per-chip readbacks are the product here,
            # and the loop is bounded by the (small) local chip count
            counts = np.asarray(jax.device_get(inp["counts"]))  # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
            plan = self.chip_plans[d]
            row = {
                "chip": d,
                "n_points": int(counts.sum()),
                "occupancy": occupancy_stats(counts),
                "classes": [{"radius": cp.radius, "n_supercells": cp.n_sc,
                             "qcap": cp.qcap, "ccap": cp.ccap,
                             "route": cp.route} for cp in plan.classes],
            }
            # Per-chip achieved-margin telemetry (the fixed max-visited-ring
            # analog, knearests.cu:378-390) when a solve has run and the
            # chip's prepared state is still cached.  margin_summary's
            # contract is post-fallback ("measures the planner's geometry"):
            # prefer the assembled solve() rows; before assembly, only a
            # fully-certified chip can report (pre-fallback outputs would
            # count resolvable in-kernel decertifications, e.g. blocked-
            # kernel deficits, as geometric failures).
            out = (self._device_out_cache or {}).get(d)
            if out is not None and d in self._ready_cache:
                (spts, *_rest, lo_rows, hi_rows) = self._ready_cache[d]
                sids = np.asarray(jax.device_get(inp["sids"]))  # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
                real = sids >= 0
                kth = None
                if self._solved_cache is not None:
                    kth = np.asarray(                       # kntpu-ok: host-sync-loop -- _solved_cache is host numpy, no device round trip
                        self._solved_cache[1])[sids[real], -1]
                else:
                    cert = np.asarray(jax.device_get(out[2]))[real]  # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
                    if cert.all():
                        kth = np.asarray(jax.device_get(out[1]))[real, -1]  # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
                    else:
                        row["margin_pending_fallback"] = int((~cert).sum())
                if kth is not None:
                    msq = _margin_sq_np(
                        np.asarray(jax.device_get(spts))[real],     # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
                        np.asarray(jax.device_get(lo_rows))[real],  # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
                        np.asarray(jax.device_get(hi_rows))[real],  # kntpu-ok: host-sync-loop -- per-chip diagnostics readback
                        meta.domain)
                    row["margin"] = margin_summary(kth, msq)
            chips.append(row)
        return {
            "n_points": self.n_points,
            "n_devices": meta.ndev,
            "grid_dim": meta.dim,
            "slab_cells_z": meta.zcap,
            "halo_depth": meta.radius,
            "pcap": meta.pcap,
            "hcap": meta.hcap,
            "k": self.config.k,
            "chips": chips,
        }

    def print_stats(self) -> dict:
        """Human-readable decomposition dump (kn_print_stats analog)."""
        s = self.stats()
        print(f"grid {s['grid_dim']}^3, {s['n_points']} points over "
              f"{s['n_devices']} chips; z-slab {s['slab_cells_z']} cells, "
              f"halo {s['halo_depth']} cells, pcap {s['pcap']}, "
              f"hcap {s['hcap']}")
        for c in s["chips"]:
            occ = c["occupancy"]
            print(f"chip {c['chip']}: {c['n_points']} points, "
                  f"max {occ['max_per_cell']}/cell")
            for cl in c["classes"]:
                print(f"  class r={cl['radius']}: {cl['n_supercells']} "
                      f"supercells, qcap {cl['qcap']}, ccap {cl['ccap']} "
                      f"[{cl['route']}]")
            if c.get("margin", {}).get("n"):
                m = c["margin"]
                print(f"  margin ratio: p50 {m['p50']:.3f}, "
                      f"p99 {m['p99']:.3f}, max {m['max']:.3f}; "
                      f"{m['decertified']} decertified")
        return s

    def permutation(self) -> np.ndarray:
        """Original index per storage row, concatenated chip-major -- the
        multi-chip analog of kn_get_permutation (a bijection over [0, n);
        single-controller, like solve())."""
        chips = self.local_chips()
        if len(chips) < self.meta.ndev:
            raise RuntimeError(
                f"permutation() covers all {self.meta.ndev} slabs but this "
                f"process addresses only chips {chips}; on a multi-host mesh "
                f"read per-chip sids from solve_device() inputs instead")
        ids = [np.asarray(jax.device_get(self._chip_inputs(d)["sids"]))
               for d in chips]
        flat = np.concatenate(ids)
        return flat[flat >= 0]

    def solve(self, device_out=None) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the sharded solve and assemble host results in ORIGINAL
        indexing.  Returns (neighbors (n, k), dists_sq (n, k),
        certified (n,)); uncertified rows are resolved exactly against the
        host kd-tree oracle (the one place the full set is touched, host-side
        only -- no chip ever holds the global array).  Pass ``device_out`` (a
        previous ``solve_device()`` result) to skip re-running the solve."""
        cfg, meta = self.config, self.meta
        outs = device_out if device_out is not None else self.solve_device()
        if len(outs) < meta.ndev:
            raise RuntimeError(
                f"solve() assembles all {meta.ndev} slabs but this process "
                f"addresses only chips {sorted(outs)}; on a multi-host mesh "
                f"use solve_device() per process and aggregate externally")
        n, k = self.n_points, cfg.k
        neighbors = np.full((n, k), INVALID_ID, np.int32)
        d2 = np.full((n, k), np.inf, np.float32)
        cert = np.zeros((n,), bool)
        # assembly is ONE batched readback across every chip slab
        # (device_get batches across devices), then pure-numpy placement --
        # the per-chip readback loop this replaces serialized the assembly
        # on ndev round trips (DESIGN.md section 12)
        live = [d for d in sorted(outs) if outs[d] is not None]
        fetched = _dispatch.fetch(  # syncflow: sharded-solve-final
            [(self._chip_inputs(d)["sids"],) + tuple(outs[d]) for d in live])
        for sids, o_i, o_d, o_c in fetched:
            rows = sids >= 0  # fetch() already landed host numpy
            neighbors[sids[rows]] = o_i[rows]
            d2[sids[rows]] = o_d[rows]
            cert[sids[rows]] = o_c[rows]

        if cfg.fallback == "brute" and not cert.all():
            bad = np.nonzero(~cert)[0].astype(np.int32)
            b_ids, b_d2 = self._oracle().knn(
                self._points_host[bad], k,
                exclude_ids=bad if cfg.exclude_self else None)
            neighbors[bad] = b_ids
            d2[bad] = b_d2
            cert[bad] = True
        # memoize for readback-style consumers (get_edges); arrays are
        # returned by reference -- treat them as immutable, like the
        # single-chip result object
        self._solved_cache = (neighbors, d2, cert)
        return neighbors, d2, cert
