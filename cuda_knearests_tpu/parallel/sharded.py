"""Multi-chip kNN: grid-slab sharding over a device mesh with ICI halo exchange.

The reference is strictly single-GPU -- its only "communication" is cudaMemcpy
H2D/D2H (SURVEY.md section 2.3).  This module is the framework's new scaling
capability, per the BASELINE.json north star: for point sets beyond single-chip
HBM, shard the uniform grid into contiguous z-slabs across a 1-D
``jax.sharding.Mesh``; each chip owns its slab's points and CSR, and queries
near slab faces need candidates from the neighboring chips' boundary cells --
exchanged as fixed-size halo buffers with ``lax.ppermute`` over ICI inside a
``jax.shard_map``.  DCN is crossed only at multi-host slab seams, by the same
collective.

Decomposition invariants:
  * The global grid is built once (ops/gridhash.py); its x-fastest/z-slowest
    cell order makes every z-slab a *contiguous* range of the sorted point
    array, so slabbing is slicing, not reshuffling.
  * Slab boundaries are supercell-aligned (z cell extent per chip = Zcap =
    layers * supercell), so every chip reuses the single-chip supercell
    schedule unchanged -- the candidate boxes of a chip's supercells always fit
    inside [slab - halo, slab + halo].
  * Halo depth equals the ring radius R, so boundary queries get exactly the
    candidate set the single-chip solver would gather; certificates remain
    valid verbatim.  Queries whose k-th distance exceeds their margin (rare)
    are resolved exactly on the host against the global array.

All shapes are static and identical across chips (capacities are global
maxima), which is what lets one ``shard_map`` program serve every chip.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..config import KnnConfig
from ..ops.gridhash import GridHash, build_grid
from ..ops.solve import (_FAR, _round_up, brute_force_by_index, chunk_best,
                         global_schedule)
from ..ops.topk import INVALID_ID


@dataclasses.dataclass(frozen=True)
class ShardedPlan:
    """Host-built static schedule + device-stacked inputs (leading axis = chip)."""

    # per-chip point slabs and CSR (stacked on axis 0, sharded over the mesh)
    local_pts: np.ndarray     # (ndev, Pcap, 3) f32, FAR-padded
    local_counts: np.ndarray  # (ndev, Zcap*A) i32
    local_base: np.ndarray    # (ndev, 1) i32 global sorted index of slab start
    n_local: np.ndarray       # (ndev, 1) i32
    # halo send buffers (bottom goes to chip-1, top goes to chip+1)
    bot_pts: np.ndarray       # (ndev, Hcap, 3) f32
    bot_counts: np.ndarray    # (ndev, R*A) i32
    bot_base: np.ndarray      # (ndev, 1) i32
    top_pts: np.ndarray       # (ndev, Hcap, 3) f32
    top_counts: np.ndarray    # (ndev, R*A) i32
    top_base: np.ndarray      # (ndev, 1) i32
    # supercell schedule in halo-extended local cell coordinates
    own_cells: np.ndarray     # (ndev, nchunks, B, s^3) i32, -1 padded
    cand_cells: np.ndarray    # (ndev, nchunks, B, (s+2R)^3) i32
    box_lo: np.ndarray        # (ndev, nchunks, B, 3) f32
    box_hi: np.ndarray        # (ndev, nchunks, B, 3) f32
    # static meta
    ndev: int
    qcap: int
    ccap: int
    pcap: int
    hcap: int


def _slab_bounds(dim: int, supercell: int, ndev: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Supercell-aligned z-cell ranges per chip: [zc0[d], zc1[d])."""
    n_sc_z = -(-dim // supercell)
    layers = -(-n_sc_z // ndev)
    zcap = layers * supercell
    zc0 = np.arange(ndev) * zcap
    zc1 = np.minimum(zc0 + zcap, dim)
    zc1 = np.maximum(zc1, np.minimum(zc0, dim))  # empty slabs: zc1 == zc0
    return zc0, zc1, zcap


def build_sharded_plan(grid: GridHash, cfg: KnnConfig, ndev: int,
                       cell_counts_host: Optional[np.ndarray] = None) -> ShardedPlan:
    dim, s = grid.dim, cfg.supercell
    radius = cfg.resolved_ring_radius()
    domain = grid.domain
    w = domain / dim
    A = dim * dim
    n = grid.n_points

    zc0, zc1, zcap = _slab_bounds(dim, s, ndev)
    if zcap < radius:
        raise ValueError(
            f"slab thickness {zcap} cells < halo depth {radius}: halo would "
            f"span multiple chips. Use fewer devices, a larger supercell, or a "
            f"smaller ring radius (dim={dim}, ndev={ndev}).")

    counts = (np.asarray(cell_counts_host) if cell_counts_host is not None
              else np.asarray(jax.device_get(grid.cell_counts)))
    starts = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)

    def pts_at(zcell: int) -> int:
        """Global sorted index of the first point at z-layer `zcell` (clamped)."""
        c = int(np.clip(zcell, 0, dim)) * A
        return int(starts[c])

    # ---- global supercell schedule (shared with the single-chip planner) ----
    own_g, cand_g, box_lo_g, box_hi_g, qcap, ccap = global_schedule(
        grid, cfg, counts)
    n_sc = -(-dim // s)

    # ---- per-chip slicing ----------------------------------------------------
    nxy = n_sc * n_sc                       # supercells per z-layer of supercells
    layers = zcap // s
    sc_per_dev = layers * nxy
    batch = max(1, int(cfg.sc_batch))
    nchunks = -(-sc_per_dev // batch)
    sc_pad = nchunks * batch

    p0 = np.array([pts_at(z) for z in zc0])
    p1 = np.array([pts_at(z) for z in zc1])
    pcap = _round_up(int((p1 - p0).max()) if ndev else 1, 8)

    # halo regions: bottom R layers [zc0, zc0+R), top R layers [zc0+zcap-R, zc0+zcap)
    b0, b1 = p0, np.array([pts_at(z) for z in zc0 + radius])
    t0 = np.array([pts_at(z) for z in zc0 + zcap - radius])
    t1 = np.array([pts_at(z) for z in zc0 + zcap])
    hcap = _round_up(int(max((b1 - b0).max(), (t1 - t0).max())) if ndev else 1, 8)

    pts_sorted = np.asarray(jax.device_get(grid.points))

    def pad_pts(lo: int, hi: int, cap: int) -> np.ndarray:
        out = np.full((cap, 3), _FAR, np.float32)
        out[: hi - lo] = pts_sorted[lo:hi]
        return out

    def counts_slice(z_from: int, z_to: int) -> np.ndarray:
        """Per-cell counts for z-layers [z_from, z_to), zero-padded beyond grid."""
        out = np.zeros(((z_to - z_from) * A,), np.int32)
        lo, hi = np.clip([z_from, z_to], 0, dim)
        if hi > lo:
            out[(lo - z_from) * A:(hi - z_from) * A] = counts[lo * A:hi * A]
        return out

    local_pts = np.stack([pad_pts(p0[d], p1[d], pcap) for d in range(ndev)])
    local_counts = np.stack([counts_slice(zc0[d], zc0[d] + zcap)
                             for d in range(ndev)])
    bot_pts = np.stack([pad_pts(b0[d], b1[d], hcap) for d in range(ndev)])
    bot_counts = np.stack([counts_slice(zc0[d], zc0[d] + radius)
                           for d in range(ndev)])
    top_pts = np.stack([pad_pts(t0[d], t1[d], hcap) for d in range(ndev)])
    top_counts = np.stack([counts_slice(zc0[d] + zcap - radius, zc0[d] + zcap)
                           for d in range(ndev)])

    def per_dev_plan(d: int):
        r0, r1 = d * sc_per_dev, min((d + 1) * sc_per_dev, own_g.shape[0])
        rows = slice(r0, r1)
        nrows = r1 - r0 if r1 > r0 else 0

        def pad_rows(a: np.ndarray, fill) -> np.ndarray:
            out = np.full((sc_pad,) + a.shape[1:], fill, a.dtype)
            if nrows > 0:
                out[:nrows] = a[rows]
            return out

        # global linear cell id -> halo-extended local id: subtract the window
        # origin (zc0 - R) * A; -1 mask passes through
        shift = A * (radius - int(zc0[d]))
        own = pad_rows(own_g, -1)
        own = np.where(own >= 0, own + shift, -1).astype(np.int32)
        cand = pad_rows(cand_g, -1)
        cand = np.where(cand >= 0, cand + shift, -1).astype(np.int32)
        lo = pad_rows(box_lo_g, 0.0)
        hi = pad_rows(box_hi_g, 0.0)
        rs = lambda a: a.reshape(nchunks, batch, *a.shape[1:])
        return rs(own), rs(cand), rs(lo), rs(hi)

    per_dev = [per_dev_plan(d) for d in range(ndev)]
    own_cells = np.stack([p[0] for p in per_dev])
    cand_cells = np.stack([p[1] for p in per_dev])
    box_lo = np.stack([p[2] for p in per_dev])
    box_hi = np.stack([p[3] for p in per_dev])

    as_col = lambda a: a.astype(np.int32).reshape(ndev, 1)
    return ShardedPlan(
        local_pts=local_pts, local_counts=local_counts,
        local_base=as_col(p0), n_local=as_col(p1 - p0),
        bot_pts=bot_pts, bot_counts=bot_counts, bot_base=as_col(b0),
        top_pts=top_pts, top_counts=top_counts, top_base=as_col(t0),
        own_cells=own_cells, cand_cells=cand_cells,
        box_lo=box_lo.astype(np.float32), box_hi=box_hi.astype(np.float32),
        ndev=ndev, qcap=int(qcap), ccap=int(ccap), pcap=int(pcap),
        hcap=int(hcap))


def _use_pallas(cfg: KnnConfig, qcap: int, ccap: int) -> bool:
    from ..ops.solve import pick_backend

    return pick_backend(cfg, qcap, ccap) == "pallas"


def _make_device_solve(plan: ShardedPlan, cfg: KnnConfig, domain: float,
                       use_pallas: bool):
    """The per-chip program run under shard_map: halo exchange + local solve
    (fused Pallas kernel on TPU, chunked XLA scan otherwise)."""
    ndev, k = plan.ndev, cfg.k
    hcap, pcap = plan.hcap, plan.pcap
    fwd = [(i, i + 1) for i in range(ndev - 1)]   # chip d -> d+1
    bwd = [(i + 1, i) for i in range(ndev - 1)]   # chip d -> d-1

    def device_fn(local_pts, local_counts, local_base, bot_pts, bot_counts,
                  bot_base, top_pts, top_counts, top_base, own, cand, blo, bhi):
        # shard_map blocks carry the leading mesh axis of size 1
        sq = lambda a: a[0]
        local_pts, local_counts = sq(local_pts), sq(local_counts)
        local_base = sq(local_base)[0]
        own, cand, blo, bhi = sq(own), sq(cand), sq(blo), sq(bhi)

        if ndev > 1:
            # halo exchange over ICI: my top region becomes my upper neighbor's
            # lower halo and vice versa.  Edge chips receive zeros -- zero
            # counts, so the empty halos are never gathered from.
            lo_pts = jax.lax.ppermute(sq(top_pts), "z", fwd)
            lo_counts = jax.lax.ppermute(sq(top_counts), "z", fwd)
            lo_base = jax.lax.ppermute(sq(top_base), "z", fwd)[0]
            hi_pts = jax.lax.ppermute(sq(bot_pts), "z", bwd)
            hi_counts = jax.lax.ppermute(sq(bot_counts), "z", bwd)
            hi_base = jax.lax.ppermute(sq(bot_base), "z", bwd)[0]
        else:
            lo_pts = jnp.full_like(sq(top_pts), _FAR)
            lo_counts = jnp.zeros_like(sq(top_counts))
            lo_base = jnp.int32(0)
            hi_pts = jnp.full_like(sq(bot_pts), _FAR)
            hi_counts = jnp.zeros_like(sq(bot_counts))
            hi_base = jnp.int32(0)

        # halo-extended point array + CSR over the z-window [zc0-R, zc0+Zcap+R)
        ext_pts = jnp.concatenate([lo_pts, local_pts, hi_pts], axis=0)
        mk_starts = lambda c: jnp.cumsum(c) - c
        ext_starts = jnp.concatenate([
            mk_starts(lo_counts),
            mk_starts(local_counts) + hcap,
            mk_starts(hi_counts) + hcap + pcap]).astype(jnp.int32)
        ext_counts = jnp.concatenate([lo_counts, local_counts, hi_counts])

        # mark the carry as device-varying over the mesh axis (each chip
        # accumulates its own slab's outputs); moot when the vma checker is
        # off (pallas branch)
        vary = ((lambda a: a) if use_pallas
                else (lambda a: jax.lax.pcast(a, ("z",), to="varying")))
        out_d = vary(jnp.full((pcap, k), jnp.inf, jnp.float32))
        out_i = vary(jnp.full((pcap, k), INVALID_ID, jnp.int32))
        out_cert = vary(jnp.zeros((pcap,), bool))

        def to_global_and_scatter(carry, q_idx, q_valid, best_d, best_i, cert):
            out_d, out_i, out_cert = carry
            # extended index -> global sorted index
            in_lo = best_i < hcap
            in_loc = best_i < hcap + pcap
            gl = jnp.where(in_lo, lo_base + best_i,
                           jnp.where(in_loc, local_base + best_i - hcap,
                                     hi_base + best_i - hcap - pcap))
            gl = jnp.where(best_i == INVALID_ID, INVALID_ID, gl).astype(jnp.int32)
            row = q_idx - hcap  # queries always live in the local section
            safe = jnp.where(q_valid & (row >= 0) & (row < pcap), row, pcap)
            out_d = out_d.at[safe].set(best_d, mode="drop")
            out_i = out_i.at[safe].set(gl, mode="drop")
            out_cert = out_cert.at[safe].set(cert, mode="drop")
            return out_d, out_i, out_cert

        if use_pallas:
            from ..ops.pallas_solve import packed_best

            flat = lambda a: a.reshape((-1,) + a.shape[2:])
            q_idx, q_valid, best_d, best_i, cert = packed_best(
                ext_pts, ext_starts, ext_counts, flat(own), flat(cand),
                flat(blo), flat(bhi), plan.qcap, plan.ccap, k,
                cfg.exclude_self, domain, cfg.interpret)
            out_d, out_i, out_cert = to_global_and_scatter(
                (out_d, out_i, out_cert), q_idx, q_valid, best_d, best_i, cert)
        else:
            def step(carry, chunk):
                own_c, cand_c, lo_c, hi_c = chunk
                q_idx, q_valid, best_d, best_i, cert = chunk_best(
                    ext_pts, ext_starts, ext_counts, own_c, cand_c, lo_c, hi_c,
                    plan.qcap, plan.ccap, k, cfg.dist_method, cfg.exclude_self,
                    domain)
                return to_global_and_scatter(carry, q_idx, q_valid, best_d,
                                             best_i, cert), None

            (out_d, out_i, out_cert), _ = jax.lax.scan(
                step, (out_d, out_i, out_cert), (own, cand, blo, bhi))
        return out_i[None], out_d[None], out_cert[None]

    return device_fn


@dataclasses.dataclass
class ShardedKnnProblem:
    """Multi-chip analog of api.KnnProblem: one prepared problem over a mesh.

    The reference has no counterpart -- this is the "sharded 10M points over
    v5e-8 ICI" capability from BASELINE.json.configs.
    """

    grid: GridHash
    config: KnnConfig
    plan: ShardedPlan
    mesh: Mesh
    _fn: Optional[object] = dataclasses.field(default=None, repr=False)

    @classmethod
    def prepare(cls, points, n_devices: Optional[int] = None,
                config: Optional[KnnConfig] = None,
                mesh: Optional[Mesh] = None,
                dim: Optional[int] = None) -> "ShardedKnnProblem":
        from ..io import validate_points

        config = config or KnnConfig()
        if mesh is None:
            n_devices = n_devices or len(jax.devices())
            mesh = jax.make_mesh((n_devices,), ("z",))
        ndev = mesh.devices.size
        grid = build_grid(validate_points(points), dim=dim,
                          density=config.density)
        plan = build_sharded_plan(grid, config, ndev)
        return cls(grid=grid, config=config, plan=plan, mesh=mesh)

    def solve_device(self):
        """Run the sharded solve on the mesh, leaving results device-resident.

        Returns (out_i, out_d, out_cert) sharded over the mesh, shaped
        (ndev, pcap, ...): per-chip slab rows in *global sorted* neighbor
        indexing, pad rows beyond each chip's n_local undefined.  This is the
        steady-state hot path -- host assembly (solve()) is a separate,
        optional phase, like the reference's kn_get_* readback
        (/root/reference/knearests.cu:406-437).
        """
        plan, cfg = self.plan, self.config
        if self._fn is None:
            # built once per problem so repeated solves reuse the compile cache
            use_pallas = _use_pallas(cfg, plan.qcap, plan.ccap)
            spec_tree = (P("z"),) * 13
            self._fn = jax.jit(jax.shard_map(
                _make_device_solve(plan, cfg, self.grid.domain, use_pallas),
                mesh=self.mesh, in_specs=spec_tree,
                out_specs=(P("z"), P("z"), P("z")),
                # pallas_call's block machinery trips the vma checker (its
                # internal dynamic_slice mixes varying/invariant operands)
                check_vma=not use_pallas))
        return self._fn(
            plan.local_pts, plan.local_counts, plan.local_base,
            plan.bot_pts, plan.bot_counts, plan.bot_base,
            plan.top_pts, plan.top_counts, plan.top_base,
            plan.own_cells, plan.cand_cells, plan.box_lo, plan.box_hi)

    def solve(self, device_out=None) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the sharded solve.  Returns (neighbors_original_ids (n, k),
        dists_sq (n, k), certified (n,)) on the host, exact (uncertified
        queries resolved against the global array).  Pass ``device_out`` (a
        previous ``solve_device()`` result) to assemble without re-running the
        mesh solve."""
        plan, cfg = self.plan, self.config
        out_i, out_d, out_cert = (device_out if device_out is not None
                                  else self.solve_device())
        out_i = np.asarray(jax.device_get(out_i))
        out_d = np.asarray(jax.device_get(out_d))
        out_cert = np.asarray(jax.device_get(out_cert))

        n, k = self.grid.n_points, cfg.k
        nbr_sorted = np.full((n, k), INVALID_ID, np.int32)
        d2 = np.full((n, k), np.inf, np.float32)
        cert = np.zeros((n,), bool)
        base = plan.local_base.ravel()
        nloc = plan.n_local.ravel()
        for d in range(plan.ndev):
            m = int(nloc[d])
            if m == 0:
                continue
            rows = slice(int(base[d]), int(base[d]) + m)
            nbr_sorted[rows] = out_i[d, :m]
            d2[rows] = out_d[d, :m]
            cert[rows] = out_cert[d, :m]

        if cfg.fallback == "brute" and not cert.all():
            from ..api import _pad_pow2
            bad = np.nonzero(~cert)[0].astype(np.int32)
            q_idx = _pad_pow2(bad, fill=-1)
            b_ids, b_d2 = brute_force_by_index(
                self.grid.points, jnp.asarray(q_idx), k, cfg.exclude_self)
            b_ids, b_d2 = np.asarray(b_ids), np.asarray(b_d2)
            nbr_sorted[bad] = b_ids[: bad.size]
            d2[bad] = b_d2[: bad.size]
            cert[bad] = True

        perm = np.asarray(jax.device_get(self.grid.permutation))
        valid = nbr_sorted >= 0
        nbr_orig_vals = np.where(valid, perm[np.clip(nbr_sorted, 0, n - 1)],
                                 INVALID_ID)
        neighbors = np.empty_like(nbr_orig_vals)
        neighbors[perm] = nbr_orig_vals
        d2_out = np.empty_like(d2)
        d2_out[perm] = d2
        cert_out = np.empty_like(cert)
        cert_out[perm] = cert
        return neighbors, d2_out, cert_out
