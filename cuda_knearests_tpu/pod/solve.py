"""PodKnnProblem: the cell-sharded kNN index (prepare / solve / query).

The pod analog of ``api.KnnProblem`` / ``parallel.sharded``: one prepared
problem whose grid cells are partitioned across a chip mesh as contiguous
Morton ranges (partition.py), whose boundary candidates move over ICI
(halo.py), and whose per-chip HBM is the only memory limit (stream.py).

Solve shape (the pod-solve syncflow window, analysis/syncflow.py):

* prepare  -- host planning + slab-by-slab counted staging (each chip's
  bucket rides its own ``dispatch.stage``; the full cloud never rides one
  transfer).  Zero host syncs: planning reads the host census, not the
  device.
* exchange -- one ``shard_map`` program of ``ppermute`` ring steps, run
  lazily at the first solve and cached; its exact wire volume is recorded
  as ``ici_bytes`` (a counter, never a host sync).
* solve    -- per-chip adaptive class solves (the SAME ``_chip_solve``
  program the z-slab route runs, including MXU-routed classes with
  per-chip ``recall_target`` pools), then ONE batched fetch assembles
  every chip's rows; uncertified rows resolve against the host kd-tree
  (zero further syncs).  ``host_syncs <= 2`` proven and reconciled.

Results are pinned tie-aware-identical to the single-chip adaptive route
(tests/test_pod.py, fuzz ``--pod``): certificates + exact resolution make
both routes exact, so they may differ only among equal-distance ties.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import KnnConfig
from ..obs import spans as _spans
from ..ops.adaptive import (ClassPlan, _class_inverse_update,
                            _prepack_kernel_inputs, launch_class_query)
from ..ops.topk import INVALID_ID
from ..parallel.sharded import _chip_solve
from ..runtime import dispatch as _dispatch
from ..utils.memory import (InvalidConfigError, InvalidKError,
                            LaunchBudgetError)
from ..utils.profiling import annotate
from . import halo as _halo
from .partition import (PodChipPlan, PodDirectory, PodMeta, PodPlan,
                        build_pod_plan, route_queries)
from .stream import preflight_pod


@functools.partial(jax.jit, static_argnames=("k",))
def _pod_ready_state(spts, sids, halo_pts, halo_ids, ext_starts, ext_counts,
                     classes: Tuple[ClassPlan, ...], k: int):
    """One chip's static solve state over its halo-extended window.

    Assembles ext arrays ([own slab | ring blocks in slot order] -- the
    exact layout partition.py's ext_starts address), prepacks pallas-routed
    classes, and inverts the slot partition for the LOCAL rows (the first
    pcap).  Returns the same 9-tuple ``parallel.sharded._chip_solve``
    consumes -- the pod route launches THE shared per-chip solve program,
    not a twin (the equivalence engine pins this: analysis/equiv.py)."""
    pcap = spts.shape[0]
    ext_pts = jnp.concatenate([spts, halo_pts.reshape(-1, 3)], axis=0)
    ext_ids = jnp.concatenate([sids, halo_ids.reshape(-1)], axis=0)
    n_ext = ext_pts.shape[0]
    inv_row = jnp.zeros((n_ext,), jnp.int32)
    inv_box = jnp.zeros((n_ext,), jnp.int32)
    row_off = box_off = 0
    packed = []
    for cp in classes:
        if cp.route == "pallas":
            cp = dataclasses.replace(cp, pk=_prepack_kernel_inputs(
                ext_pts, ext_starts, ext_counts, cp.own, cp.cand,
                cp.qcap_pad, cp.ccap))
        # own cells live in the own region ([0, pcap)) by construction --
        # supercells partition cells, a chip owns whole supercells -- so
        # tgt needs no base shift; the n_ext sentinel lands past pcap and
        # the (pcap, k) scatter drops it
        inv_row, inv_box, row_off, box_off, tgt = (
            _class_inverse_update(inv_row, inv_box, cp,
                                  ext_starts, ext_counts, n_ext,
                                  row_off, box_off))
        packed.append(dataclasses.replace(cp, tgt=tgt))
    loc = slice(0, pcap)
    box_loc = inv_box[loc]
    lo_rows = jnp.take(jnp.concatenate([cp.lo for cp in classes], axis=0),
                       box_loc, axis=0)
    hi_rows = jnp.take(jnp.concatenate([cp.hi for cp in classes], axis=0),
                       box_loc, axis=0)
    return (spts, ext_pts, ext_ids, ext_starts, ext_counts, tuple(packed),
            inv_row[loc], lo_rows, hi_rows)


@dataclasses.dataclass
class PodKnnProblem:
    """One prepared cell-sharded kNN problem over a chip mesh."""

    config: KnnConfig
    mesh: Mesh
    meta: PodMeta
    directory: PodDirectory
    n_points: int
    chip_plans: List[PodChipPlan]
    hbm: dict
    # device state: per-chip buckets (sharded, leading axis = chip) + the
    # replicated directory bounds; halo blocks appear after the exchange
    dev: Dict[str, jax.Array] = dataclasses.field(default_factory=dict,
                                                  repr=False)
    _points_host: Optional[np.ndarray] = dataclasses.field(default=None,
                                                           repr=False)
    _bucket_ids_host: Optional[np.ndarray] = dataclasses.field(default=None,
                                                               repr=False)
    _chip_of_point: Optional[np.ndarray] = dataclasses.field(default=None,
                                                             repr=False)
    _oracle_cache: Optional[object] = dataclasses.field(default=None,
                                                        repr=False)
    _ready_cache: Dict[int, tuple] = dataclasses.field(default_factory=dict,
                                                       repr=False)
    _exchanged: bool = dataclasses.field(default=False, repr=False)

    # -- prepare ----------------------------------------------------------

    @classmethod
    def prepare(cls, points, n_devices: Optional[int] = None,
                config: Optional[KnnConfig] = None,
                mesh: Optional[Mesh] = None,
                dim: Optional[int] = None) -> "PodKnnProblem":
        from ..api import _config_adaptive_eligible, _resolve_tuned_for
        from ..config import grid_dim_for
        from ..io import validate_or_raise
        from .stream import auto_devices

        config = _resolve_tuned_for(config or KnnConfig(), points)
        if config.backend == "oracle":
            raise InvalidConfigError(
                "backend='oracle' is a single-chip host engine; the pod "
                "path runs grid engines only ('auto'/'pallas'/'xla')")
        if config.resolved_scorer() == "mxu" \
                and not _config_adaptive_eligible(config, per_chip=True):
            # same shared predicate as the single-chip guard and the
            # (lifted) sharded refusal: the per-chip class solves score in
            # 'diff' arithmetic, so an mxu config that overrides it would
            # silently benchmark the wrong arithmetic
            raise InvalidConfigError(
                f"scorer='mxu' (recall_target={config.recall_target}) "
                f"composes with the per-chip class solves only under "
                f"dist_method='diff' (got {config.dist_method!r}): the "
                f"class scorers realize distances in diff arithmetic")
        points = validate_or_raise(points, k=config.k)
        n = points.shape[0]
        requested = n_devices
        if mesh is None:
            if n_devices is None:
                n_devices = (auto_devices(n, config.k, config,
                                          len(jax.devices()))
                             or len(jax.devices()))
            n_devices = max(1, min(int(n_devices), len(jax.devices())))
            mesh = jax.make_mesh((n_devices,), (_halo.AXIS,))
        ndev = mesh.devices.size
        if dim is None:
            dim = grid_dim_for(n, config.density)
        dim = int(dim)

        if n == 0:
            # degraded mode: nothing to partition; solve()/query() short-
            # circuit to empty / all-invalid results (DESIGN.md s11)
            meta = PodMeta(ndev=ndev, dim=dim, supercell=config.supercell,
                           pcap=8, hcap=8, steps=0, domain=1000.0)
            return cls(config=config, mesh=mesh, meta=meta,
                       directory=PodDirectory(
                           order=np.empty(0, np.int32),
                           rank_of=np.empty(0, np.int32),
                           bounds=np.zeros(ndev + 1, np.int32)),
                       n_points=0, chip_plans=[], hbm={}, dev={},
                       _points_host=points)

        on_kernel = (config.backend != "xla"
                     and (jax.devices()[0].platform == "tpu"
                          or config.interpret))
        auto = requested is None and ndev < len(jax.devices())
        while True:
            plan: PodPlan = build_pod_plan(points, ndev, config, dim,
                                           on_kernel)
            try:
                hbm = preflight_pod(plan.meta, plan.chips, config.k,
                                    config, n)
                break
            except LaunchBudgetError:
                # the auto-splitter's widening arm: the pre-partition
                # estimate (stream.auto_devices) is optimistic -- halo
                # blocks and class outputs only exist after planning -- so
                # a failed per-chip preflight splits across more chips and
                # replans, refusing only when the widest split still
                # cannot fit one chip
                if not auto or ndev >= len(jax.devices()):
                    raise
                ndev = min(ndev * 2, len(jax.devices()))
                mesh = jax.make_mesh((ndev,), (_halo.AXIS,))

        # streamed staging: each chip's slab rides its own counted H2D
        # transfer (halo.stage_sharded) -- the full cloud exists on device
        # only as the sharded assembly of per-chip blocks
        def stage_one(block, device):
            return _dispatch.stage(block, device=device)  # syncflow: pod-prepare-stage

        bucket_pts, bucket_ids, export_idx = _halo.stage_sharded(
            (plan.bucket_pts, plan.bucket_ids,
             np.stack([c.export_idx for c in plan.chips])),
            mesh, stage_one)
        # the replicated cell->chip directory: every chip carries the same
        # (ndev+1,) Morton-rank bounds -- the authoritative owner map a
        # future device-side router would consult; every CURRENT routing
        # decision reads the host twin (route_queries).  Staged through
        # the counted primitive like every other prepare transfer.
        bounds_dev = _dispatch.stage(  # syncflow: pod-prepare-stage
            plan.directory.bounds.astype(np.int32),
            device=NamedSharding(mesh, P()))
        dev = {"bucket_pts": bucket_pts, "bucket_ids": bucket_ids,
               "export_idx": export_idx, "directory": bounds_dev}
        return cls(config=config, mesh=mesh, meta=plan.meta,
                   directory=plan.directory, n_points=n,
                   chip_plans=plan.chips, hbm=hbm, dev=dev,
                   _points_host=points,
                   _bucket_ids_host=plan.bucket_ids,
                   _chip_of_point=plan.chip_of_point)

    # -- internals --------------------------------------------------------

    def _oracle(self):
        if self._oracle_cache is None:
            from ..oracle import KdTreeOracle

            self._oracle_cache = KdTreeOracle(self._points_host)
        return self._oracle_cache

    def _exchange(self) -> None:
        """Run the ICI halo exchange once (cached): ppermute ring steps
        ship every export block ``steps`` chips in each direction.  The
        exact wire volume is recorded as ici_bytes -- interconnect
        traffic, not a host sync (the pod-solve window's central claim)."""
        if self._exchanged:
            return
        # named profiler scope: the ppermute ring shows as
        # 'kntpu:halo-exchange' in jax.profiler traces; the obs span puts
        # the same phase (with its modeled wire volume) on the timeline
        with _spans.span("solve.pod.halo", steps=self.meta.steps,
                         ici_bytes=self.meta.halo_bytes()), \
                annotate("kntpu:halo-exchange"):
            program = _halo.exchange_program(self.meta, self.mesh)
            halo_pts, halo_ids = program(self.dev["bucket_pts"],
                                         self.dev["bucket_ids"],
                                         self.dev["export_idx"])
        self.dev["halo_pts"] = halo_pts
        self.dev["halo_ids"] = halo_ids
        if self.meta.steps and self.meta.ndev > 1:
            _dispatch.ici(self.meta.halo_bytes())  # syncflow: pod-ici
        self._exchanged = True

    def _chip_inputs(self, d: int):
        out = {}
        for name in ("bucket_pts", "bucket_ids", "halo_pts", "halo_ids"):
            arr = self.dev[name]
            shard = next(sh for sh in arr.addressable_shards
                         if int(sh.index[0].start or 0) == d)
            out[name] = shard.data.reshape(shard.data.shape[1:])
        return out

    def _chip_ready(self, d: int):
        if d not in self._ready_cache:
            self._exchange()
            inp = self._chip_inputs(d)
            plan = self.chip_plans[d]
            self._ready_cache[d] = _pod_ready_state(
                inp["bucket_pts"], inp["bucket_ids"],
                inp["halo_pts"], inp["halo_ids"],
                plan.ext_starts, plan.ext_counts, plan.classes,
                k=self.config.k)
        return self._ready_cache[d]

    # -- solve ------------------------------------------------------------

    def solve_device(self) -> Dict[int, Optional[tuple]]:
        """Per-chip adaptive solves over the halo-extended windows, results
        device-resident ({chip: (orig_ids (pcap, k), d2 (pcap, k),
        cert (pcap,)) or None for empty slabs}).  Dispatch is a host loop
        but execution overlaps (async jit dispatch, one program per chip);
        no host sync happens here."""
        cfg = self.config
        outs: Dict[int, Optional[tuple]] = {}
        with _spans.span("solve.pod.chips", ndev=self.meta.ndev), \
                annotate("kntpu:pod-chip-solves"):
            for d in range(self.meta.ndev):
                if not self.chip_plans[d].classes:
                    outs[d] = None
                    continue
                state = self._chip_ready(d)
                outs[d] = _chip_solve(
                    *state, cfg.k, cfg.exclude_self, self.meta.domain,
                    cfg.interpret, cfg.stream_tile,
                    cfg.effective_kernel(), cfg.resolved_epilogue(),
                    float(cfg.recall_target))
        return outs

    def solve(self, device_out=None
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The partitioned all-points solve, assembled in ORIGINAL
        indexing: (neighbors (n, k), dists_sq (n, k), certified (n,)).

        ONE batched fetch collects every chip's rows (the host already
        knows each slab's original ids -- the partitioner built the
        buckets); uncertified rows resolve exactly against the host
        kd-tree (zero further syncs).  host_syncs <= 2 proven by the
        pod-solve window and reconciled by tests/test_pod.py."""
        cfg = self.config
        n, k = self.n_points, cfg.k
        neighbors = np.full((n, k), INVALID_ID, np.int32)
        d2 = np.full((n, k), np.inf, np.float32)
        cert = np.zeros((n,), bool)
        if n == 0:
            return (np.empty((0, k), np.int32),
                    np.empty((0, k), np.float32), np.empty((0,), bool))
        outs = device_out if device_out is not None else self.solve_device()
        live = [d for d in sorted(outs) if outs[d] is not None]
        fetched = _dispatch.fetch(  # syncflow: pod-solve-final
            [tuple(outs[d]) for d in live])
        for d, (o_i, o_d, o_c) in zip(live, fetched):
            sids = self._bucket_ids_host[d]
            rows = sids >= 0
            neighbors[sids[rows]] = o_i[rows]
            d2[sids[rows]] = o_d[rows]
            cert[sids[rows]] = o_c[rows]
        if cfg.fallback == "brute" and not cert.all():
            bad = np.nonzero(~cert)[0].astype(np.int32)
            b_ids, b_d2 = self._oracle().knn(
                self._points_host[bad], k,
                exclude_ids=bad if cfg.exclude_self else None)
            neighbors[bad] = b_ids
            d2[bad] = b_d2
            cert[bad] = True
        return neighbors, d2, cert

    # -- external queries -------------------------------------------------

    def query(self, queries, k: Optional[int] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact kNN of arbitrary coordinates against the partitioned set.

        Each query routes through the directory to the chip owning its
        supercell and rides that chip's class schedule over its
        halo-extended window -- a boundary-straddling query's whole
        candidate box is inside its owner's window by construction, so the
        single-chip certificates hold verbatim.  One batched fetch;
        classless and uncertified rows resolve against the host oracle.
        Returns ((m, k) ids in ORIGINAL indexing, ascending; (m, k) d2)."""
        from ..io import validate_or_raise

        cfg, meta = self.config, self.meta
        k = cfg.k if k is None else k
        queries = validate_or_raise(queries, k=k, what="queries")
        k = int(k)
        if k > cfg.k:
            raise InvalidKError(
                f"k={k} exceeds the prepared k={cfg.k} (it sized the "
                f"candidate dilation)")
        queries = np.ascontiguousarray(queries, np.float32)
        m = queries.shape[0]
        out_i = np.full((m, k), INVALID_ID, np.int32)
        out_d = np.full((m, k), np.inf, np.float32)
        if m == 0 or self.n_points == 0:
            return out_i, out_d
        chip, local_rank = route_queries(self.directory, meta, queries)
        cert = np.zeros((m,), bool)
        pending = []
        for d in range(meta.ndev):
            on_d = np.nonzero(chip == d)[0]
            if on_d.size == 0:
                continue
            plan = self.chip_plans[d]
            if not plan.classes:
                continue  # empty slab: the oracle pass below resolves them
            (_, ext_pts, ext_ids, ext_starts, ext_counts, classes,
             _, _, _) = self._chip_ready(d)
            qcls = plan.class_of[local_rank[on_d]]
            qrow = plan.row_of[local_rank[on_d]]
            for ci, cp in enumerate(classes):
                sel = on_d[qcls == ci]
                if sel.size == 0:
                    continue
                order, r_i, r_d, r_c = launch_class_query(
                    ext_pts, ext_starts, ext_counts, cp, queries[sel],
                    qrow[qcls == ci], k, cfg, meta.domain, ids_map=ext_ids)
                pending.append((sel[order], r_i, r_d, r_c))
        for rows, h_i, h_d, h_c in _dispatch.fetch(pending):  # syncflow: pod-query-final
            out_i[rows] = h_i
            out_d[rows] = h_d
            cert[rows] = h_c
        if not cert.all():
            bad = np.nonzero(~cert)[0].astype(np.int32)
            b_i, b_d = self._oracle().knn(queries[bad], k)
            out_i[bad] = b_i
            out_d[bad] = b_d
        return out_i, out_d

    # -- diagnostics ------------------------------------------------------

    def stats(self) -> dict:
        """Decomposition + exchange + budget diagnostics (all host state:
        zero device round trips)."""
        meta = self.meta
        return {
            "n_points": self.n_points,
            "n_devices": meta.ndev,
            "grid_dim": meta.dim,
            "supercell": meta.supercell,
            "pcap": meta.pcap,
            "hcap": meta.hcap,
            "ring_depth": meta.steps,
            "halo_bytes": meta.halo_bytes(),
            **self.hbm,
            "chips": [{
                "chip": d,
                "n_points": c.n_local,
                "n_supercells": int(c.sc_ids.size),
                "remote_cells": c.remote_cells,
                "max_owner_dist": c.max_owner_dist,
                "classes": [{"radius": cp.radius, "n_supercells": cp.n_sc,
                             "qcap": cp.qcap, "ccap": cp.ccap,
                             "route": cp.route}
                            for cp in c.classes],
            } for d, c in enumerate(self.chip_plans)],
        }
