"""Pod smoke + weak-scaling bench child.

Smoke (default; wired into scripts/check.sh, CPU-only on forced host
devices):

  1. **Partition pin** -- the partitioned solve on the 20k fixture must be
     tie-aware-identical to both the exact oracle and the single-chip
     adaptive route, including ``scorer='mxu'`` at recall_target < 1.0
     and = 1.0, and boundary-straddling external queries.
  2. **Streamed prepare** -- under a budget between the per-chip high
     water and the full-cloud model, prepare must stream (not refuse),
     the per-chip model must stay under the budget while the full cloud
     exceeds it, and the result must stay exact; a budget below any slab
     must refuse with the typed oom taxonomy.
  3. **Sync/ICI reconciliation** -- one solve window: host_syncs <= the
     proven pod-solve bound, and the recorded ici_bytes must EQUAL the
     decomposition's halo-byte model (the syncflow window's expression).

``--bench`` runs one weak-scaling measurement (fixed points per chip on
THIS process's device count -- the parent ``bench.py --pod-scaling``
forces the device count per child via XLA_FLAGS) and emits one JSON row.

Exit codes: 0 = all checks passed, 1 = a check failed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _force_devices(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}").strip()


def _sync_proof(route: str, host_syncs: int, env=None) -> dict:
    from cuda_knearests_tpu.analysis.syncflow import (ROUTE_WINDOWS,
                                                      WINDOWS,
                                                      worst_case_env)

    win = WINDOWS[ROUTE_WINDOWS[route]]
    bound = win.syncs_bound({**worst_case_env(), **(env or {})})
    return {"sync_bound_proved": bound, "sync_bound_expr": win.syncs,
            "sync_bound_ok": host_syncs <= bound}


def _smoke(n: int) -> int:
    import numpy as np

    from cuda_knearests_tpu import KnnConfig, KnnProblem
    from cuda_knearests_tpu.fuzz.compare import check_route_result
    from cuda_knearests_tpu.io import get_dataset, generate_uniform
    from cuda_knearests_tpu.pod import PodKnnProblem
    from cuda_knearests_tpu.runtime import dispatch as _dispatch
    from cuda_knearests_tpu.utils.memory import LaunchBudgetError

    import jax

    ndev = len(jax.devices())
    rc = 0

    def row(check: str, ok: bool, **extra) -> None:
        nonlocal rc
        rc |= 0 if ok else 1
        print(json.dumps({"check": check, "ok": bool(ok), **extra}),
              flush=True)

    try:
        points = get_dataset("pts20K.xyz")
    except Exception:  # noqa: BLE001 -- fixture-less checkout: synthesize
        points = generate_uniform(20_000, seed=20)
    if n and n < points.shape[0]:
        points = np.ascontiguousarray(points[:n])
    k = 10

    # 1a. partitioned == oracle == single-chip (tie-aware)
    _dispatch.reset_stats()
    pp = PodKnnProblem.prepare(points, n_devices=ndev,
                               config=KnnConfig(k=k))
    ids, d2, _cert = pp.solve()
    stats = _dispatch.stats()
    ref_i, ref_d = pp._oracle().knn_all_points(k)
    mm = check_route_result(points, points, ids, d2, ref_d, k)
    sp = KnnProblem.prepare(points, KnnConfig(k=k))
    sp.solve()
    sd2 = np.empty_like(sp.get_dists_sq())
    sd2[sp.get_permutation()] = sp.get_dists_sq()
    mm2 = check_route_result(points, points, ids, d2, sd2, k)
    row("pod-vs-single-chip-pin", mm is None and mm2 is None,
        n=int(points.shape[0]), n_devices=ndev,
        ring_depth=pp.meta.steps,
        mismatch=None if mm is None else mm.render(),
        single_chip_mismatch=None if mm2 is None else mm2.render())

    # 1b. boundary-straddling external queries (jittered stored points:
    # dense near every range boundary by construction)
    rng = np.random.default_rng(3)
    q = np.clip(points[rng.integers(0, points.shape[0], 512)]
                + rng.normal(0, 1.0, (512, 3)).astype(np.float32),
                0.0, 1000.0).astype(np.float32)
    qi, qd = pp.query(q)
    _qri, qrd = pp._oracle().knn(q, k)
    mmq = check_route_result(points, q, qi, qd, qrd, k)
    row("pod-query-pin", mmq is None,
        mismatch=None if mmq is None else mmq.render())

    # 1c. MXU composition: per-chip recall_target pools, both tiers
    sub = np.ascontiguousarray(points[:4000])
    sref_d = None
    for rt in (0.9, 1.0):
        pm = PodKnnProblem.prepare(sub, n_devices=ndev,
                                   config=KnnConfig(k=k, scorer="mxu",
                                                    recall_target=rt))
        mi, md, _mc = pm.solve()
        if sref_d is None:
            o_i, sref_d = pm._oracle().knn_all_points(k)
        mmm = check_route_result(sub, sub, mi, md, sref_d, k)
        n_mxu = sum(cp.route == "mxu" for c in pm.chip_plans
                    for cp in c.classes)
        row(f"pod-mxu-rt{rt:g}", mmm is None and n_mxu > 0,
            mxu_classes=n_mxu,
            mismatch=None if mmm is None else mmm.render())

    # 2. streamed prepare under a budget the full cloud exceeds
    high = pp.hbm["hbm_high_water_bytes"]
    full = pp.hbm["hbm_full_cloud_bytes"]
    budget = (high + full) // 2
    try:
        ps = PodKnnProblem.prepare(points, n_devices=ndev,
                                   config=KnnConfig(
                                       k=k, hbm_budget_bytes=budget))
        si, s_d2, _sc = ps.solve()
        mms = check_route_result(points, points, si, s_d2, ref_d, k)
        ok = (ps.hbm["streamed_prepare"]
              and ps.hbm["hbm_high_water_bytes"] <= budget < full
              and mms is None)
        row("pod-streamed-prepare", ok, **ps.hbm)
    except LaunchBudgetError as e:
        row("pod-streamed-prepare", False, error=str(e))
    try:
        PodKnnProblem.prepare(points, n_devices=max(1, ndev // 2),
                              config=KnnConfig(k=k,
                                               hbm_budget_bytes=high // 8))
        row("pod-budget-refusal", False,
            error="undersized budget was not refused")
    except LaunchBudgetError as e:
        row("pod-budget-refusal", e.kind == "oom", kind=e.kind)

    # 3. sync budget + ICI reconciliation (window around prepare+solve:
    # prepare stages asynchronously and the exchange is ICI, so the only
    # host syncs are the solve's)
    proof = _sync_proof("pod-solve", stats.host_syncs)
    ici_ok = stats.ici_bytes == pp.meta.halo_bytes()
    row("pod-sync-ici", proof["sync_bound_ok"] and ici_ok,
        host_syncs=stats.host_syncs, ici_bytes=stats.ici_bytes,
        ici_model=pp.meta.halo_bytes(), halo_hcap=pp.meta.hcap, **proof)
    return rc


def _bench(points_per_chip: int, k: int) -> int:
    import numpy as np

    import jax

    from cuda_knearests_tpu import KnnConfig
    from cuda_knearests_tpu.cli import set_recall
    from cuda_knearests_tpu.io import generate_uniform
    from cuda_knearests_tpu.pod import PodKnnProblem
    from cuda_knearests_tpu.runtime import dispatch as _dispatch

    ndev = len(jax.devices())
    n = points_per_chip * ndev
    points = generate_uniform(n, seed=12)
    _dispatch.reset_stats()
    pp = PodKnnProblem.prepare(points, n_devices=ndev,
                               config=KnnConfig(k=k))

    def run():
        jax.block_until_ready(
            [o for o in pp.solve_device().values() if o is not None])

    run()  # compile + warmup (runs the cached exchange too)
    # the exchange fires once (cached after): its recorded wire volume
    # lives in the prepare+warmup counter window
    ici_bytes = _dispatch.stats().ici_bytes
    iters = 2
    t0 = time.perf_counter()
    for _ in range(iters):
        run()
    s = (time.perf_counter() - t0) / iters
    _dispatch.reset_stats()
    neighbors, _d2, cert = pp.solve()
    sync = _dispatch.stats()
    sample = np.random.default_rng(8).permutation(n)[
        : min(2000, n)].astype(np.int32)
    ref_ids, _ = pp._oracle().knn(points[sample], k, exclude_ids=sample)
    recall = set_recall(neighbors[sample], ref_ids)
    # kntpu-scope stamps (DESIGN.md section 20): one extra captured
    # solve -- device-time attribution + the measured-HBM verdict against
    # the pod's own per-chip model (chip_hbm_model high water)
    from cuda_knearests_tpu.obs import device as _obsdev

    # the shared enabled/skip contract (skips stamped, never silent)
    cap_fields = _obsdev.bench_capture_or_skip(
        run, hbm_model_bytes=pp.hbm["hbm_high_water_bytes"],
        tag=f"pod{ndev}", solve_s=s)
    # roofline achieved-vs-peak (utils/roofline.py): the pod chip plans
    # are adaptive class schedules, so the sharded traffic accounting
    # applies chip-by-chip unchanged
    from cuda_knearests_tpu.utils.roofline import (roofline_fields,
                                                   sharded_traffic)

    cap_fields.update(roofline_fields(
        sharded_traffic(pp), s, jax.devices()[0].platform,
        n_devices=ndev))
    row = {
        "config": f"pod weak-scaling: {points_per_chip} points/chip over "
                  f"{ndev} chip(s) (k={k}, cell-partitioned)",
        "pod_scaling": True,
        "value": round(n / s / ndev, 1), "unit": "queries/sec/chip",
        "total_qps": round(n / s, 1), "n_devices": ndev,
        "points_per_chip": points_per_chip, "n_points": n,
        "solve_s": round(s, 4),
        "recall": round(recall, 6),
        "precision": pp.config.resolved_precision(),
        "backend": pp.config.backend,
        "ring_depth": pp.meta.steps,
        "halo_bytes": pp.meta.halo_bytes(),
        "ici_bytes": ici_bytes,
        "certified_fraction": float(np.asarray(cert).mean()),
        **pp.hbm,
        "host_syncs": sync.host_syncs,
        "d2h_bytes": sync.d2h_bytes,
        **_sync_proof("pod-solve", sync.host_syncs),
        **cap_fields,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(row), flush=True)
    return 0 if row["sync_bound_ok"] and recall >= 0.999 else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuda_knearests_tpu.pod",
        description="Pod-partitioned grid smoke / weak-scaling bench "
                    "child (DESIGN.md section 18).")
    ap.add_argument("--bench", action="store_true",
                    help="emit one weak-scaling JSON row instead of the "
                         "smoke (the bench.py --pod-scaling child)")
    ap.add_argument("--points-per-chip", type=int,
                    default=int(os.environ.get("BENCH_POD_PPC", "20000")))
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--devices", type=int, default=4,
                    help="forced host device count when no accelerator "
                         "is attached (must be set before jax init)")
    ap.add_argument("--smoke-n", type=int,
                    default=int(os.environ.get("KNTPU_POD_SMOKE_N", "0")),
                    help="cap the smoke fixture size (0 = full 20k)")
    args = ap.parse_args(argv)
    _force_devices(max(1, args.devices))
    # whole-run tracing (KNTPU_TRACE_DIR): this child's host spans spill
    # beside the device lanes its captures mount, so the merged export
    # shows pod children as their own (pid, job) process rows
    from cuda_knearests_tpu.obs import spans as _spans

    _spans.set_process_tag(f"pod:{max(1, args.devices)}dev")
    _spans.start_file_trace_from_env(f"pod{max(1, args.devices)}")
    if args.bench:
        return _bench(max(1, args.points_per_chip), max(1, args.k))
    return _smoke(args.smoke_n)


if __name__ == "__main__":
    sys.exit(main())
