"""ICI halo exchange: ``lax.ppermute`` ring steps over the chip chain.

The partitioner (partition.py) already decided, on the host, which cells
cross chip boundaries and where every remote cell's points land inside
each receiver's window -- so the device side of the exchange is pure data
movement: each chip gathers its export block (the points of its cells
that ANY other chip's candidate boxes reach) and the block rides the ring
``steps`` times in each direction.  After step ``s`` of the forward ring
a chip holds the export block of the chip ``s`` ranks below it; the
backward ring mirrors it.  ``steps`` is the measured maximum ring
distance any candidate box reaches (partition.py) -- queries whose rings
stay chip-local are converged before the first step, and each additional
step exists only because some still-unconverged query's ring crosses
another range boundary (the widening rule; DESIGN.md section 18 has the
convergence argument: after ``steps`` rounds every candidate cell of
every query is resident, so the single-chip certificates apply verbatim).

Everything here is ICI traffic: ``ppermute`` moves blocks chip-to-chip
without touching the host.  The exchange's exact wire volume
(``PodMeta.halo_bytes``) is recorded through ``runtime.dispatch.ici`` by
the solve wrapper -- counted as ``ici_bytes``, never as a host sync,
which is what keeps the pod-solve window inside the <= 2 host-round-trip
budget (analysis/syncflow.py window 'pod-solve').
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.solve import _FAR
from .partition import PodMeta

_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

AXIS = "pod"


def _make_exchange_fn(meta: PodMeta):
    ndev, steps, hcap = meta.ndev, meta.steps, meta.hcap
    fwd = [(i, i + 1) for i in range(ndev - 1)]   # block of d lands on d+1
    bwd = [(i + 1, i) for i in range(ndev - 1)]   # block of d lands on d-1

    def exchange(bucket_pts, bucket_ids, export_idx):
        pts, ids, idx = bucket_pts[0], bucket_ids[0], export_idx[0]
        ok = idx >= 0
        safe = jnp.clip(idx, 0, pts.shape[0] - 1)
        blk_p = jnp.where(ok[:, None], jnp.take(pts, safe, axis=0), _FAR)
        blk_i = jnp.where(ok, jnp.take(ids, safe), -1)
        halo_p, halo_i = [], []
        cur_p, cur_i = blk_p, blk_i
        for _ in range(steps):
            # forward ring: after s steps this chip holds chip (d-s)'s
            # block; edge chips with no left neighbor receive zeros, whose
            # rows no ext cell ever references (the directory knows there
            # is no owner below chip 0)
            cur_p = jax.lax.ppermute(cur_p, AXIS, fwd)
            cur_i = jax.lax.ppermute(cur_i, AXIS, fwd)
            halo_p.append(cur_p)
            halo_i.append(cur_i)
        cur_p, cur_i = blk_p, blk_i
        for _ in range(steps):
            cur_p = jax.lax.ppermute(cur_p, AXIS, bwd)
            cur_i = jax.lax.ppermute(cur_i, AXIS, bwd)
            halo_p.append(cur_p)
            halo_i.append(cur_i)
        if halo_p:
            hp = jnp.stack(halo_p)                      # (2*steps, hcap, 3)
            hi = jnp.stack(halo_i)                      # (2*steps, hcap)
        else:  # single chip / fully local: an empty halo region
            hp = jnp.zeros((0, hcap, 3), jnp.float32)
            hi = jnp.zeros((0, hcap), jnp.int32)
        return hp[None], hi[None]

    return exchange


@functools.lru_cache(maxsize=32)
def exchange_program(meta: PodMeta, mesh: Mesh):
    """Jitted shard_map exchange, cached by the (hashable) decomposition."""
    spec = P(AXIS)
    return jax.jit(_shard_map(
        _make_exchange_fn(meta), mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=(spec, spec)))


def stage_sharded(host_arrays, mesh: Mesh, stage_one):
    """Stage a (ndev, ...) host array slab by slab: each chip's block rides
    its own counted H2D transfer (``stage_one`` = the dispatch.stage
    closure the caller annotates), and the full array exists on device only
    as the sharded assembly of per-chip blocks -- the streamed-prepare
    contract (stream.py): no monolithic upload, per-chip HBM the limit."""
    devices = list(mesh.devices.ravel())
    sharding = NamedSharding(mesh, P(AXIS))
    out = []
    for arr in host_arrays:
        shards = [stage_one(arr[d: d + 1], devices[d])
                  for d in range(len(devices))]
        out.append(jax.make_array_from_single_device_arrays(
            arr.shape, sharding, shards))
    return tuple(out)
