"""Pod-partitioned grid subsystem: the cell-sharded index (DESIGN.md s18).

Every distributed surface before this one (parallel/sharded.py) splits the
cloud into z-slabs whose halo is a fixed +-1 layer; this package is the
SNIPPETS.md target statement done literally: grid cells are partitioned
across chips as contiguous **z-order (Morton) ranges** balanced by point
population, each chip builds and owns only its range's CSR, and only
**boundary-cell candidates** move between chips -- over ICI, via
``jax.lax.ppermute`` ring steps that widen exactly as far as the
unconverged queries' candidate rings demand.  A 100M-point cloud never
materializes on any single chip: per-chip (not per-pod) HBM is the limit,
and the ``hbm_bytes_estimate`` preflight acts as the automatic splitter
(clouds beyond one chip's budget stream through the partitioner in
slab-sized host-to-device stages instead of refusing -- "Memory Safe
Computations with XLA", arXiv 2206.14148).

Layout:

* :mod:`.partition` -- prepare-time planning, all host numpy: Morton cell
  ranges, the replicated cell->chip directory, per-chip CSR layouts,
  per-chip adaptive classes (the shared ops/adaptive machinery), export
  blocks, and the measured ring depth.
* :mod:`.halo`      -- the ICI exchange: one ``shard_map`` program whose
  only communication is ``lax.ppermute`` ring steps; halo bytes and ring
  depth are stamped as counters (``runtime.dispatch.ici``).
* :mod:`.stream`    -- HBM auto-splitting: the per-chip footprint model
  the preflight gates, and the streamed slab-by-slab staging.
* :mod:`.solve`     -- :class:`PodKnnProblem`: prepare / solve / query,
  composing with the PR 9 MXU scorer (``KnnConfig.scorer='mxu'`` with
  per-chip ``recall_target`` pools).
* :mod:`.reshard`   -- mutation under partitioning (DESIGN.md s22):
  :class:`PodOverlay` (solve-time halo re-exchange for mutating clouds --
  dirty-cell deltas restage only the affected chips and re-run the cached
  ppermute program only when an export block changed) and
  :class:`ElasticIndex` (the serving-tier Morton-range shards with live
  boundary migration, behind the fleet front door).

``python -m cuda_knearests_tpu.pod`` runs the CPU smoke (forced host
devices): partitioned == single-chip pin, the streamed-prepare budget
case, and the sync/ICI counter reconciliation -- wired into
``scripts/check.sh``.
"""

from .reshard import ElasticIndex, PodOverlay
from .solve import PodKnnProblem

__all__ = ["PodKnnProblem", "PodOverlay", "ElasticIndex"]
