"""Prepare-time grid partitioner: Morton cell ranges, directory, CSR layout.

All host numpy (the same planning-on-host / solving-on-device split as
every other planner in this tree): the partitioner reads one global cell
histogram -- O(cells) host work, the exact census ``ops/rings`` already
computes for the single-chip adaptive planner -- and from it derives

* the **z-order partition**: supercells sorted by Morton code, split into
  ``ndev`` contiguous rank ranges balanced by point population (a chip
  owns every cell of every supercell in its range -- supercells partition
  cells, so this IS a contiguous z-order cell-range partition at
  supercell granularity, and the per-query adaptive machinery applies per
  chip unchanged);
* the **directory**: the (ndev+1,) Morton-rank bounds array -- the small
  replicated cell->chip map (owner of a cell = ``searchsorted(bounds,
  rank_of[supercell_of(cell)])``), staged replicated onto every chip and
  kept as the host twin that routes external queries;
* each chip's **ext window layout**: its own cells' CSR (starts/counts
  over the chip-local sorted point array) followed by every remote cell
  any of its candidate boxes reaches, each remote cell resolving to a
  fixed offset inside the owning chip's export block -- the whole
  exchange schedule is static, so the device-side halo exchange is
  nothing but ``ppermute`` of fixed-size blocks (halo.py);
* each chip's **adaptive classes** over that window (the shared
  ``ops/adaptive.build_class_specs``), including MXU-routed classes when
  ``cfg.resolved_scorer() == 'mxu'`` -- the per-chip recall_target
  composition ISSUE 12 lifts the sharded refusal for.

Ring depth is MEASURED, not assumed: ``steps`` = the maximum Morton-rank
ring distance between any chip and the owner of any cell its queries'
candidate boxes reach.  Queries whose rings stay chip-local cost zero
exchange; the widening steps exist exactly for the boundary-crossing
(statically "unconverged") queries -- see DESIGN.md section 18 for the
convergence argument.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import DOMAIN_SIZE, KnnConfig
from ..ops.adaptive import ClassPlan, build_class_specs, select_radii
from ..ops.rings import ring_occupancy
from ..ops.solve import _FAR, _round_up


def morton3(coords: np.ndarray) -> np.ndarray:
    """Morton (z-order) codes of (m, 3) integer coords, host i64.

    Bits of x/y/z interleave x-minor; 21 bits per axis of headroom (the
    supercell grid tops out near 10^3 per axis at the roadmap's scale, so
    the interleave can never collide).  Host-only: the codes exist to sort
    and split the supercell list; nothing i64 is ever staged."""
    c = coords.astype(np.int64)  # kntpu-ok: wide-dtype -- 3x21-bit interleave headroom, host-only
    out = np.zeros(c.shape[0], dtype=np.int64)  # kntpu-ok: wide-dtype -- 3x21-bit interleave headroom, host-only
    for bit in range(21):
        for ax in range(3):
            out |= ((c[:, ax] >> bit) & 1) << (3 * bit + ax)
    return out


@dataclasses.dataclass(frozen=True)
class PodDirectory:
    """The replicated cell->chip ownership map (host twin).

    ``order``  -- (n_sc_total,) global supercell id per Morton rank.
    ``rank_of`` -- (n_sc_total,) Morton rank per global supercell id.
    ``bounds`` -- (ndev+1,) i32 rank boundaries: chip d owns Morton ranks
    [bounds[d], bounds[d+1]).  This tiny array IS the directory -- it is
    what prepare stages replicated onto every chip (solve.PodKnnProblem's
    ``dev['directory']``), and what routes every external query to its
    owning chip on the host."""

    order: np.ndarray
    rank_of: np.ndarray
    bounds: np.ndarray

    def chip_of_rank(self, rank: np.ndarray) -> np.ndarray:
        return (np.searchsorted(self.bounds, rank, side="right") - 1) \
            .astype(np.int32)

    def chip_of_sc(self, sc_id: np.ndarray) -> np.ndarray:
        return self.chip_of_rank(self.rank_of[sc_id])


@dataclasses.dataclass(frozen=True)
class PodMeta:
    """Hashable static decomposition metadata (keys the exchange program
    cache, halo.py)."""

    ndev: int
    dim: int
    supercell: int
    pcap: int       # per-chip own-point capacity (max population, 8-padded)
    hcap: int       # export-block capacity (max export population, 8-padded)
    steps: int      # measured ring depth (ppermute rounds per direction)
    domain: float

    @property
    def n_ext(self) -> int:
        """Rows of one chip's halo-extended point window."""
        return self.pcap + 2 * self.steps * self.hcap

    def halo_base(self, receiver: int, owner: int) -> int:
        """Ext-row offset of ``owner``'s export block inside ``receiver``'s
        window: forward-ring blocks (owners below) at slots 0..steps-1,
        backward-ring blocks (owners above) at slots steps..2*steps-1 --
        the exact landing order halo.py's ppermute pipeline produces."""
        if owner < receiver:
            slot = receiver - owner - 1
        else:
            slot = self.steps + (owner - receiver - 1)
        return self.pcap + slot * self.hcap

    def halo_bytes(self) -> int:
        """Exact wire volume of the exchange: per ring step and direction,
        every link of the (non-wrapping) chip chain ships one export block
        -- hcap points (12 B) + ids (4 B).  The same expression the
        pod-solve syncflow window declares; dispatch.ici records exactly
        this, and tests/test_pod.py reconciles the two."""
        return 32 * self.hcap * self.steps * (self.ndev - 1)


@dataclasses.dataclass
class PodChipPlan:
    """One chip's static schedule: classes over its ext window + layout."""

    classes: Tuple[ClassPlan, ...]
    class_of: np.ndarray    # (n_sc_local,) class per owned supercell (-1)
    row_of: np.ndarray      # (n_sc_local,) row within the class's tables
    sc_ids: np.ndarray      # (n_sc_local,) global supercell ids (Morton order)
    ext_starts: np.ndarray  # (n_ext_cells,) i32 ext-row start per ext cell
    ext_counts: np.ndarray  # (n_ext_cells,) i32 points per ext cell
    export_idx: np.ndarray  # (hcap,) i32 own-region rows to export, -1 pad
    export_cells: np.ndarray  # sorted global cell ids behind export_idx
    n_local: int            # real points on this chip
    remote_cells: int       # halo cells this chip's boxes reach
    max_owner_dist: int     # ring distance to the farthest needed owner


@dataclasses.dataclass
class PodPlan:
    """Everything prepare computed on the host, pre-staging."""

    meta: PodMeta
    directory: PodDirectory
    chips: List[PodChipPlan]
    bucket_pts: np.ndarray   # (ndev, pcap, 3) f32, FAR-pad, local cell order
    bucket_ids: np.ndarray   # (ndev, pcap) i32 original index, -1 pad
    chip_of_point: np.ndarray  # (n,) i32 owning chip per original point


def _sc_cells(sc: np.ndarray, s: int, dim: int) -> np.ndarray:
    """(m, s^3) global cell ids of each supercell's own cells, -1 where the
    cell falls outside the grid (edge supercells)."""
    offs = np.arange(s, dtype=np.int64)  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
    ax = sc[:, :, None].astype(np.int64) * s + offs[None, None, :]  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
    x, y, z = ax[:, 0], ax[:, 1], ax[:, 2]
    ok = ((x[:, None, None, :] < dim) & (y[:, None, :, None] < dim)
          & (z[:, :, None, None] < dim))
    lin = (np.clip(x, 0, dim - 1)[:, None, None, :]
           + dim * np.clip(y, 0, dim - 1)[:, None, :, None]
           + dim * dim * np.clip(z, 0, dim - 1)[:, :, None, None])
    return np.where(ok, lin, -1).reshape(sc.shape[0], s ** 3).astype(np.int64)  # kntpu-ok: wide-dtype -- cell ids reach dim^3, host-only


def _box_cells(sc: np.ndarray, radius: int, s: int, dim: int) -> np.ndarray:
    """(m, (s+2r)^3) global cell ids of each supercell's dilated candidate
    box clamped to the grid, -1 outside -- same geometry as the single-chip
    planner's candidate tables (ops/adaptive), in global cell ids."""
    side = s + 2 * radius
    offs = np.arange(-radius, s + radius, dtype=np.int64)  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
    ax = sc[:, :, None].astype(np.int64) * s + offs[None, None, :]  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
    x, y, z = ax[:, 0], ax[:, 1], ax[:, 2]
    ok = ((x[:, None, None, :] >= 0) & (x[:, None, None, :] < dim)
          & (y[:, None, :, None] >= 0) & (y[:, None, :, None] < dim)
          & (z[:, :, None, None] >= 0) & (z[:, :, None, None] < dim))
    lin = (np.clip(x, 0, dim - 1)[:, None, None, :]
           + dim * np.clip(y, 0, dim - 1)[:, None, :, None]
           + dim * dim * np.clip(z, 0, dim - 1)[:, :, None, None])
    return np.where(ok, lin, -1).reshape(sc.shape[0], side ** 3).astype(np.int64)  # kntpu-ok: wide-dtype -- cell ids reach dim^3, host-only


def build_directory(counts_sc: np.ndarray, sc_coords: np.ndarray,
                    ndev: int) -> PodDirectory:
    """Morton-sort the supercells and split into ndev contiguous rank
    ranges balanced by point population (prefix split on the cumulative
    counts; degenerate clouds may leave trailing chips empty -- an empty
    range is a legal slab, like the sharded route's empty z-slabs)."""
    codes = morton3(sc_coords)
    order = np.argsort(codes, kind="stable").astype(np.int32)
    rank_of = np.empty_like(order)
    rank_of[order] = np.arange(order.size, dtype=np.int32)
    cum = np.cumsum(counts_sc[order])  # i64 population prefix sums
    total = int(cum[-1]) if cum.size else 0
    targets = [total * d // ndev for d in range(1, ndev)]
    inner = np.searchsorted(cum, targets, side="left") + 1
    inner = np.minimum(np.maximum.accumulate(inner), order.size)
    bounds = np.concatenate([[0], inner, [order.size]]).astype(np.int32)
    return PodDirectory(order=order, rank_of=rank_of, bounds=bounds)


def build_pod_plan(points: np.ndarray, ndev: int, cfg: KnnConfig, dim: int,
                   on_kernel_platform: bool) -> PodPlan:
    """The whole prepare-time decomposition (see module docstring)."""
    n = points.shape[0]
    s = cfg.supercell
    n_sc_side = -(-dim // s)
    w = DOMAIN_SIZE / dim

    coords = np.clip((points * (dim / DOMAIN_SIZE)).astype(np.int64),  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
                     0, dim - 1)
    cell_of = coords[:, 0] + dim * coords[:, 1] + dim * dim * coords[:, 2]
    counts3 = np.bincount(cell_of, minlength=dim ** 3) \
        .reshape(dim, dim, dim)
    scc = coords // s
    sc_of = (scc[:, 0] + n_sc_side * scc[:, 1]
             + n_sc_side * n_sc_side * scc[:, 2])
    counts_sc = np.bincount(sc_of, minlength=n_sc_side ** 3)

    r = np.arange(n_sc_side, dtype=np.int32)
    zz, yy, xx = np.meshgrid(r, r, r, indexing="ij")
    sc_all = np.stack([xx.ravel(), yy.ravel(), zz.ravel()], axis=1)

    directory = build_directory(counts_sc, sc_all, ndev)
    chip_of_sc_all = directory.chip_of_rank(directory.rank_of)

    # global ring occupancy + radii: the identical signal the single-chip
    # planner reads, computed once and sliced per chip, so per-supercell
    # radii (and therefore halo reach) agree with single-chip planning
    if cfg.ring_radius is not None:
        rmax = max(1, int(cfg.ring_radius))
    else:
        from ..config import default_ring_radius

        rmax = int(min(dim, max(6, 2 * default_ring_radius(cfg.k,
                                                           cfg.density))))
    pts_cum, cells_cum = ring_occupancy(counts3, sc_all, s, rmax)
    if cfg.ring_radius is not None:
        radii_all = np.full((sc_all.shape[0],), rmax, np.int32)
    else:
        radii_all = select_radii(pts_cum, cells_cum, cfg.k, rmax)

    # -- pass A: per-chip supercells, classes, candidate boxes, halo needs --
    cnt_flat = counts3.reshape(-1)
    per_chip: List[dict] = []
    needed: Dict[int, set] = {o: set() for o in range(ndev)}  # owner -> cells
    for d in range(ndev):
        ranks = np.arange(directory.bounds[d], directory.bounds[d + 1])
        sc_ids = directory.order[ranks]
        sc_d = sc_all[sc_ids]
        own_n = counts_sc[sc_ids]
        if own_n.sum() == 0:
            per_chip.append(dict(sc_ids=sc_ids, specs=(), boxes={},
                                 box_reach={},
                                 own_cells=np.empty((0,), np.int64),  # kntpu-ok: wide-dtype -- cell-id table, host-only
                                 own_tab=np.empty((0, s ** 3), np.int64)))  # kntpu-ok: wide-dtype -- cell-id table, host-only
            continue
        specs = build_class_specs(own_n, pts_cum[sc_ids], radii_all[sc_ids],
                                  cfg, on_kernel_platform)
        own_tab = _sc_cells(sc_d, s, dim)          # (n_sc_local, s^3)
        flat = own_tab.reshape(-1)
        own_cells = flat[flat >= 0]                # chip-local cell order
        def owners_of(cells: np.ndarray) -> np.ndarray:
            """cell id -> owning chip, via the directory (ONE home for the
            cell -> supercell -> owner mapping: the partition, ring depth,
            and ext layout all read the pairs computed here)."""
            return chip_of_sc_all[
                (cells // (dim * dim)) // s * (n_sc_side ** 2)
                + ((cells // dim) % dim) // s * n_sc_side
                + (cells % dim) // s]

        boxes = {}
        box_reach = {}  # ci -> (occupied box cells, their owner chips)
        for ci, spec in enumerate(specs):
            box = _box_cells(sc_d[spec.rows], spec.radius, s, dim)
            boxes[ci] = box
            cells = np.unique(box[box >= 0])
            # empty cells never ride the exchange: a zero-population cell
            # contributes no candidates, so receivers record (start=0,
            # count=0) for it and the owner exports nothing
            cells = cells[cnt_flat[cells] > 0]
            owners = owners_of(cells)
            box_reach[ci] = (cells, owners)
            for o in np.unique(owners):
                if int(o) != d:
                    needed[int(o)].update(
                        cells[owners == o].tolist())
        per_chip.append(dict(sc_ids=sc_ids, specs=specs, boxes=boxes,
                             box_reach=box_reach,
                             own_cells=own_cells, own_tab=own_tab))

    # -- pass B: export blocks + ring depth + capacities --
    exports: List[np.ndarray] = []
    export_prefix: List[Dict[int, int]] = []
    hmax = 1
    for o in range(ndev):
        cells_o = np.array(sorted(needed[o]), dtype=np.int64)  # kntpu-ok: wide-dtype -- cell-id table, host-only
        exports.append(cells_o)
        pref: Dict[int, int] = {}
        off = 0
        for c in cells_o.tolist():
            pref[c] = off
            off += int(cnt_flat[c])
        export_prefix.append(pref)
        hmax = max(hmax, off)
    hcap = _round_up(hmax, 8)

    steps = 0
    for d in range(ndev):
        for _cells, owners in per_chip[d]["box_reach"].values():
            if owners.size == 0:
                continue
            far = np.abs(owners.astype(np.int64) - d)  # kntpu-ok: wide-dtype -- ring-distance arithmetic, host-only
            steps = max(steps, int(far.max()))

    chip_of_point = chip_of_sc_all[sc_of].astype(np.int32)
    pop = np.bincount(chip_of_point, minlength=ndev)
    pcap = _round_up(int(pop.max()) if n else 1, 8)
    meta = PodMeta(ndev=ndev, dim=dim, supercell=s, pcap=pcap, hcap=hcap,
                   steps=steps, domain=DOMAIN_SIZE)

    # -- point buckets in (chip, own-cell slot, original id) order --
    # own-cell slot per point: rank of its cell within its chip's own-cell
    # list; the bucket IS the chip-local counting sort, staged slab by slab
    slot_map = np.full(dim ** 3, -1, np.int32)
    own_starts_by_chip: List[np.ndarray] = []
    for d in range(ndev):
        oc = per_chip[d]["own_cells"]
        slot_map[oc] = np.arange(oc.size, dtype=np.int32)
        own_starts_by_chip.append(
            np.concatenate([[0], np.cumsum(cnt_flat[oc])[:-1]])
            .astype(np.int32) if oc.size else np.empty((0,), np.int32))
    slot_of_point = slot_map[cell_of]
    order = np.lexsort((np.arange(n), slot_of_point, chip_of_point))
    bucket_pts = np.full((ndev, pcap, 3), _FAR, np.float32)
    bucket_ids = np.full((ndev, pcap), -1, np.int32)
    starts_pt = np.concatenate([[0], np.cumsum(pop)[:-1]])
    for d in range(ndev):
        rows = order[starts_pt[d]: starts_pt[d] + pop[d]]
        bucket_pts[d, : pop[d]] = points[rows]
        bucket_ids[d, : pop[d]] = rows.astype(np.int32)

    # -- pass C: per-chip ext layout + class tables --
    # clear the scratch wholesale first: the bucketing fill above left
    # every chip's own-slot values in place, and a stale entry (another
    # chip's slot, or a zero-count cell skipped by the exchange filter)
    # would alias a cand-table cell onto the wrong own-region slot --
    # duplicated candidates that still certify (the dev-found
    # pod-uniform-s10 corpus case pins this)
    slot_map[:] = -1
    chips: List[PodChipPlan] = []
    for d in range(ndev):
        info = per_chip[d]
        oc = info["own_cells"]
        own_starts = own_starts_by_chip[d]
        # remote cells this chip's boxes reach, each resolving into the
        # owner's export block at its host-known offset
        remote: Dict[int, Tuple[int, int]] = {}  # cell -> (start, count)
        max_dist = 0
        # zero-population remote cells were filtered from box_reach in
        # pass A, so they stay unmapped here and their cand slots resolve
        # to -1 pads -- one filter, three consumers
        for cells, owners in info["box_reach"].values():
            for c, o in zip(cells.tolist(), owners.tolist()):
                if o == d or c in remote:
                    continue
                remote[c] = (meta.halo_base(d, o) + export_prefix[o][c],
                             int(cnt_flat[c]))
                max_dist = max(max_dist, abs(o - d))
        remote_cells = np.array(sorted(remote), dtype=np.int64)  # kntpu-ok: wide-dtype -- cell-id table, host-only

        # cell -> ext slot map (own slots first, then remote), built in the
        # shared slot_map scratch and reset after use
        slot_map[oc] = np.arange(oc.size, dtype=np.int32)
        slot_map[remote_cells] = (oc.size
                                  + np.arange(remote_cells.size,
                                              dtype=np.int32))
        ext_starts = np.empty(oc.size + remote_cells.size, np.int32)
        ext_counts = np.empty_like(ext_starts)
        ext_starts[: oc.size] = own_starts
        ext_counts[: oc.size] = cnt_flat[oc].astype(np.int32)
        for c in remote_cells.tolist():
            slot = slot_map[c]
            ext_starts[slot], ext_counts[slot] = remote[c]

        export_idx = np.full((hcap,), -1, np.int32)
        off = 0
        for c in exports[d].tolist():
            cc = int(cnt_flat[c])
            export_idx[off: off + cc] = (own_starts[slot_map[c]]
                                         + np.arange(cc, dtype=np.int32))
            off += cc

        classes: List[ClassPlan] = []
        class_of = np.full((info["sc_ids"].size,), -1, np.int32)
        row_of = np.zeros_like(class_of)
        specs = info["specs"]
        for ci, spec in enumerate(specs):
            class_of[spec.rows] = ci
            row_of[spec.rows] = np.arange(spec.rows.size, dtype=np.int32)
        import jax.numpy as jnp

        for ci, spec in enumerate(specs):
            own_g = info["own_tab"][spec.rows]
            box = info["boxes"][ci]
            own_slots = np.where(own_g >= 0,
                                 slot_map[np.clip(own_g, 0, None)],
                                 -1).astype(np.int32)
            cand_slots = np.where(box >= 0,
                                  slot_map[np.clip(box, 0, None)],
                                  -1).astype(np.int32)
            gsc = sc_all[info["sc_ids"][spec.rows]]
            lo = ((gsc * s - spec.radius) * w).astype(np.float32)
            hi = ((gsc * s + s + spec.radius) * w).astype(np.float32)
            classes.append(ClassPlan(
                own=jnp.asarray(own_slots), cand=jnp.asarray(cand_slots),  # kntpu-ok: jnp-in-loop -- prepare-time, <= max_classes tables per chip
                lo=jnp.asarray(lo), hi=jnp.asarray(hi),                    # kntpu-ok: jnp-in-loop -- prepare-time, <= max_classes tables per chip
                radius=spec.radius, qcap=spec.qcap, qcap_pad=spec.qcap_pad,
                ccap=spec.ccap, route=spec.route))

        # reset the shared scratch for the next chip
        slot_map[oc] = -1
        if remote_cells.size:
            slot_map[remote_cells] = -1

        chips.append(PodChipPlan(
            classes=tuple(classes), class_of=class_of, row_of=row_of,
            sc_ids=info["sc_ids"], ext_starts=ext_starts,
            ext_counts=ext_counts, export_idx=export_idx,
            export_cells=exports[d],
            n_local=int(pop[d]), remote_cells=int(remote_cells.size),
            max_owner_dist=max_dist))

    return PodPlan(meta=meta, directory=directory, chips=chips,
                   bucket_pts=bucket_pts, bucket_ids=bucket_ids,
                   chip_of_point=chip_of_point)


def route_queries(directory: PodDirectory, meta: PodMeta,
                  queries: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(owning chip, local supercell rank) per query, via the directory --
    the host twin of the replicated device directory.  A query routed to
    chip d has its whole candidate box inside d's ext window (the window
    was sized from exactly these boxes), so single-chip certificates hold
    verbatim for boundary-straddling queries too."""
    dim, s = meta.dim, meta.supercell
    n_sc_side = -(-dim // s)
    coords = np.clip((queries * (dim / meta.domain)).astype(np.int64),  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
                     0, dim - 1)
    scc = coords // s
    sc_id = (scc[:, 0] + n_sc_side * scc[:, 1]
             + n_sc_side * n_sc_side * scc[:, 2])
    rank = directory.rank_of[sc_id]
    chip = directory.chip_of_rank(rank)
    local = (rank - directory.bounds[chip]).astype(np.int32)
    return chip, local
