"""Mutating pod indexes: halo re-exchange + live Morton resharding.

Two layers, both answering the same question -- what happens to a
partitioned index when the cloud refuses to hold still:

* :class:`PodOverlay` -- the ROADMAP item-1 remainder: a mutating view
  over a prepared :class:`~.solve.PodKnnProblem`.  Deletes tombstone rows
  of the per-chip device buckets IN PLACE: only the dirty chips' slabs
  restage (each rides its own counted H2D transfer, the streamed-prepare
  contract), and the ``ppermute`` halo exchange re-runs ONLY when a dirty
  cell sits in its owner's export block (some other chip imports it) --
  the dirty-cell overlay invalidating exactly the affected export blocks.
  The re-exchange rides the SAME cached exchange program as prepare
  (halo.exchange_program), its wire volume counted as ``ici_bytes``, and
  its host-sync budget (zero: staging and ICI never sync) is proven by
  the ``pod-reexchange`` syncflow window.  Inserts ride a host-side delta
  merged through the one bit-stable brute HLO
  (ops/query.brute_force_by_coords) with dirty-cell pruning -- the same
  machinery as serve/delta, over the pod's cell geometry.

* :class:`ElasticIndex` -- the serving-tier pod-partitioned index behind
  the fleet front door (serve/fleet/elastic.py): the cloud splits into
  contiguous **Morton-code ranges** (:class:`RangeShard`), each served by
  its own base problem + :class:`~..serve.delta.DeltaOverlay`; queries
  scatter to every shard and gather through one deterministic
  pure-comparison merge, so the serve-tier byte-identity pin (overlay ==
  rebuild-from-scratch on the mutated cloud) lifts to the partitioned
  index shard by shard.  When the mutation stream skews population across
  ranges past a threshold, :class:`Migration` moves the range boundary
  and ships the affected slab between shards UNDER traffic with no
  stop-the-world: committed records ship per the PR 10 replication
  protocol (dense 1-based seq, only-committed-acked), queries keep
  answering from the OLD owner until the handover seq is fully applied,
  and the post-migration index answers byte-identical to a per-shard
  rebuild oracle (:meth:`ElasticIndex.rebuild_oracle_query`).

The chaos campaign (fuzz/chaos.py) drives both layers through seeded
fault schedules -- torn migration steps, lost ranges, wedged receivers,
delayed handovers, chip loss -- against those oracles.

Protocol table (model ``migration-handover``, analysis/models.py):

========  ======================================================
action    site
========  ======================================================
start     ``ElasticIndex.maybe_rebalance`` / ``force_rebalance``
ship      ``Migration._append`` (commit) + ``_ship`` (deliver)
insert    ``Migration.on_insert`` / ``on_delete`` (mid-migration
          mutations entering the committed stream)
pump      ``ElasticIndex.pump`` (bounded work per call)
handover  ``Migration.handover`` (the atomic ownership flip)
abort     ``Migration.abort`` (wedge bound / chip loss)
========  ======================================================

The ``# proto:`` annotations at those sites bind them to the model;
exhaustive exploration (crash at every state) proves exactly-one
authoritative owner per uid at all times, no torn handover (the flip
requires acked == committed), and the wedge/abort pump bound.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import KnnConfig
from ..obs import spans as _spans
from ..ops.gridhash import cell_min_d2_host, delta_csr_host
from ..ops.query import launch_brute
from ..ops.topk import INVALID_ID
from ..runtime import dispatch as _dispatch
from ..serve.delta import _FAR, DeltaOverlay, _merge_rows, _round_pow2
from ..utils import prototrace
from ..utils.profiling import annotate
from . import halo as _halo
from .partition import morton3
from .solve import PodKnnProblem

__all__ = ["PodOverlay", "RangeShard", "ElasticIndex", "Migration",
           "morton_codes"]

_MORTON_BITS = 21
_MAX_CODE = np.iinfo(np.int64).max  # kntpu-ok: wide-dtype -- Morton code space bound, host-only constant


def morton_codes(points: np.ndarray, domain: float = 1000.0) -> np.ndarray:
    """Morton (z-order) code of each point at full 21-bit resolution --
    the elastic tier's range key (finer than the supercell directory so a
    range boundary can land between any two points)."""
    pts = np.asarray(points, np.float64).reshape(-1, 3)  # kntpu-ok: wide-dtype -- 21-bit quantization needs f64 mantissa headroom, host-only
    scale = float(1 << _MORTON_BITS) / float(domain)
    c = np.clip((pts * scale).astype(np.int64),  # kntpu-ok: wide-dtype -- 3x21-bit interleave headroom, host-only
                0, (1 << _MORTON_BITS) - 1)
    return morton3(c)


# =============================================================================
# Layer 1: PodOverlay -- solve-time halo re-exchange for mutating clouds
# =============================================================================

class PodOverlay:
    """A mutable point cloud served from a prepared pod decomposition.

    Ids are stable: base points keep their ORIGINAL index (0..n0-1);
    inserts get ``n0 + arrival_index`` and keep it for life (a deleted
    insert tombstones in place, so later inserts never shift).  Deletes
    accept both ranges.  ``solve()`` covers the original rows (deleted
    rows come back invalid: id -1 / d2 inf / cert False); inserts appear
    as neighbor CANDIDATES everywhere and get their own rows via
    ``query``.

    Thread-unsafe by design, same as the serve overlay (the fleet event
    loop is single-threaded).
    """

    def __init__(self, problem: PodKnnProblem):
        pp = self.pp = problem
        meta = pp.meta
        # own mutable copies of the host twins: prepare's arrays are shared
        # with the caller and the plan; the overlay must never mutate them
        if pp._points_host is not None:
            pp._points_host = np.array(pp._points_host, np.float32)
        if pp._bucket_ids_host is not None:
            pp._bucket_ids_host = np.array(pp._bucket_ids_host)
        self.n0 = int(pp.n_points)
        self.alive = np.ones((self.n0,), bool)
        self.n_deleted = 0
        # (chip, bucket row) of every original point: the per-chip bucket
        # id table is the inverse permutation, inverted once here
        self._chip_of = (np.asarray(pp._chip_of_point, np.int32)
                         if pp._chip_of_point is not None
                         else np.zeros((self.n0,), np.int32))
        self._row_of = np.full((self.n0,), -1, np.int32)
        # host twin of the device buckets, rebuilt from the id tables (the
        # plan's bucket_pts array is not retained by the problem)
        self._bkt_pts = np.full((meta.ndev, meta.pcap, 3), _FAR, np.float32)
        self._bkt_ids = (pp._bucket_ids_host
                         if pp._bucket_ids_host is not None
                         else np.full((meta.ndev, meta.pcap), -1, np.int32))
        for d in range(meta.ndev):
            ids = self._bkt_ids[d]
            rows = np.nonzero(ids >= 0)[0]
            if rows.size:
                self._row_of[ids[rows]] = rows.astype(np.int32)
                self._bkt_pts[d, rows] = pp._points_host[ids[rows]]
        # per-owner export-cell sets: the dirty-cell -> export-block
        # invalidation test ("does any other chip import this cell?")
        self._exported = [set(np.asarray(c.export_cells).tolist())
                          for c in pp.chip_plans]
        # insert delta (host side): arrival-order rows, tombstoned in place
        self.delta = np.empty((0, 3), np.float32)
        self._delta_alive = np.empty((0,), bool)
        self._delta_rows = np.empty((0,), np.int32)
        self._delta_csr: Optional[Tuple] = None
        self.dirty_cells = np.empty((0,), np.int32)
        self.stats = {"inserts": 0, "deletes": 0, "restaged_chips": 0,
                      "reexchanges": 0, "reexchanges_skipped": 0,
                      "delta_launches": 0, "delta_skips": 0}

    # -- state ----------------------------------------------------------------

    @property
    def n_points(self) -> int:
        return (self.n0 - self.n_deleted) + int(self._delta_alive.sum())

    def _cells_of(self, pts: np.ndarray) -> np.ndarray:
        dim = self.pp.meta.dim
        c = np.clip((np.asarray(pts, np.float32)
                     * (dim / self.pp.meta.domain)).astype(np.int64),  # kntpu-ok: wide-dtype -- dim^2 linearization headroom, host-only
                    0, dim - 1)
        return c[:, 0] + dim * c[:, 1] + dim * dim * c[:, 2]

    def mutated_points(self) -> np.ndarray:
        """The current cloud (alive base originals + alive inserts), the
        rebuild oracle's input."""
        base = self.pp._points_host[self.alive] if self.n0 else \
            np.empty((0, 3), np.float32)
        return np.ascontiguousarray(
            np.concatenate([base, self.delta[self._delta_alive]]),
            dtype=np.float32)

    # -- mutations ------------------------------------------------------------

    def insert(self, points: np.ndarray) -> np.ndarray:
        """Append points; returns their assigned (stable) ids."""
        pts = np.ascontiguousarray(
            np.asarray(points, np.float32).reshape(-1, 3))
        start = self.n0 + self.delta.shape[0]
        if pts.shape[0] == 0:
            return np.empty((0,), np.int32)
        self.delta = np.concatenate([self.delta, pts])
        self._delta_alive = np.concatenate(
            [self._delta_alive, np.ones((pts.shape[0],), bool)])
        self._invalidate_delta()
        self.stats["inserts"] += pts.shape[0]
        return np.arange(start, start + pts.shape[0], dtype=np.int32)

    def delete(self, ids: np.ndarray) -> None:
        """Remove points by stable id: base rows tombstone on device (dirty
        chips restage; the halo re-exchanges iff an exported cell went
        dirty), insert rows tombstone in the host delta."""
        ids = np.unique(np.asarray(ids, np.int64).reshape(-1))  # kntpu-ok: wide-dtype -- host id arithmetic headroom, never staged
        ins = ids[ids >= self.n0] - self.n0
        if ins.size:
            live = ins[self._delta_alive[ins]]
            self._delta_alive[live] = False
            self.delta[live] = _FAR
            self._invalidate_delta()
            self.stats["deletes"] += int(live.size)
        base = ids[(ids >= 0) & (ids < self.n0)]
        base = base[self.alive[base]]
        if base.size == 0:
            return
        pp = self.pp
        # cells BEFORE tombstoning (the coords are about to go to _FAR)
        cells = self._cells_of(pp._points_host[base])
        chips = self._chip_of[base]
        rows = self._row_of[base]
        self.alive[base] = False
        self.n_deleted += int(base.size)
        # tombstone every host twin: FAR coords keep the kd-tree oracle
        # from ever preferring a deleted point, -1 bucket ids drop the rows
        # from solve writeback AND from every exchange gather
        pp._points_host[base] = _FAR
        pp._oracle_cache = None
        self._bkt_pts[chips, rows] = _FAR
        self._bkt_ids[chips, rows] = -1
        dirty = sorted(int(d) for d in np.unique(chips))
        self._restage(dirty)
        # export-block invalidation: re-exchange iff some dirty cell is in
        # its owner's export block (its points ride the halo)
        exported = any(int(c) in self._exported[int(d)]
                       for d, c in zip(chips, cells))
        if (exported and pp._exchanged and pp.meta.steps
                and pp.meta.ndev > 1):
            self._reexchange()
            pp._ready_cache.clear()
        else:
            if exported:
                # not exchanged yet: the lazy first exchange reads the
                # restaged buckets, nothing to invalidate
                pass
            else:
                self.stats["reexchanges_skipped"] += 1
            for d in dirty:
                pp._ready_cache.pop(d, None)
        self.stats["deletes"] += int(base.size)

    def _restage(self, dirty: Sequence[int]) -> None:
        """Restage ONLY the dirty chips' slabs; clean chips' device blocks
        are reused as-is (their single-device shards re-assemble into the
        new sharded array without moving)."""
        pp = self.pp
        devices = list(pp.mesh.devices.ravel())
        sharding = NamedSharding(pp.mesh, P(_halo.AXIS))
        dirty_set = set(int(d) for d in dirty)
        for name, host in (("bucket_pts", self._bkt_pts),
                           ("bucket_ids", self._bkt_ids)):
            arr = pp.dev[name]
            old = {int(sh.index[0].start or 0): sh.data
                   for sh in arr.addressable_shards}
            shards = []
            for d in range(len(devices)):
                if d in dirty_set:
                    shards.append(_dispatch.stage(  # syncflow: pod-reexchange-stage
                        host[d: d + 1], device=devices[d]))
                else:
                    shards.append(old[d])
            pp.dev[name] = jax.make_array_from_single_device_arrays(
                host.shape, sharding, shards)
        self.stats["restaged_chips"] += len(dirty_set)

    def _reexchange(self) -> None:
        """Re-run the cached ppermute exchange over the restaged buckets:
        same program, same counted wire volume, zero host syncs (the
        pod-reexchange window's claim)."""
        pp = self.pp
        meta = pp.meta
        with _spans.span("solve.pod.rehalo", steps=meta.steps,
                         ici_bytes=meta.halo_bytes()), \
                annotate("kntpu:halo-reexchange"):
            program = _halo.exchange_program(meta, pp.mesh)
            halo_pts, halo_ids = program(pp.dev["bucket_pts"],
                                         pp.dev["bucket_ids"],
                                         pp.dev["export_idx"])
        pp.dev["halo_pts"] = halo_pts
        pp.dev["halo_ids"] = halo_ids
        _dispatch.ici(meta.halo_bytes())  # syncflow: pod-reexchange-ici
        self.stats["reexchanges"] += 1

    def _invalidate_delta(self) -> None:
        rows = np.nonzero(self._delta_alive)[0].astype(np.int32)
        self._delta_rows = rows
        if rows.size:
            order, dirty, starts, counts = delta_csr_host(
                self.delta[rows], self.pp.meta.dim, self.pp.meta.domain)
            self._delta_csr = (order, starts, counts)
            self.dirty_cells = dirty
        else:
            self._delta_csr = None
            self.dirty_cells = np.empty((0,), np.int32)

    # -- result paths ---------------------------------------------------------

    def _filter_deleted(self, ids: np.ndarray, d2: np.ndarray):
        """Drop tombstoned ids from result rows.  Only the host-oracle
        resolution path can surface one (the device buckets are FAR'd),
        and then only at a huge distance -- i.e. when fewer than k alive
        candidates exist -- so masked slots are always the row tail and
        the ascending -1/inf pad contract is preserved."""
        dead = np.nonzero(~self.alive)[0]
        bad = (ids >= 0) & np.isin(ids, dead)
        return (np.where(bad, -1, ids).astype(np.int32),
                np.where(bad, np.inf, d2).astype(np.float32))

    def _delta_merge(self, queries: np.ndarray, ids: np.ndarray,
                     d2: np.ndarray, k: int):
        """Merge the alive insert-delta into per-row results: dirty-cell
        pruning, one capacity-bucketed brute launch through the exec
        cache, pure-comparison merge (bit-stable, same as serve/delta)."""
        rows = self._delta_rows
        if rows.size == 0:
            return ids, d2
        kth = np.where(np.isfinite(d2[:, k - 1]), d2[:, k - 1], np.inf)
        bound = cell_min_d2_host(queries, self.dirty_cells,
                                 self.pp.meta.dim, self.pp.meta.domain)
        need = (bound <= kth[:, None]).any(axis=0)
        if not need.any():
            self.stats["delta_skips"] += 1
            return ids, d2
        order, starts, counts = self._delta_csr
        sel = np.concatenate([order[s: s + c] for s, c
                              in zip(starts[need], counts[need])])
        cap = _round_pow2(int(sel.size))
        pts = np.full((cap, 3), _FAR, np.float32)
        pts[: sel.size] = self.delta[rows[sel]]
        dids = np.full((cap,), -1, np.int32)
        dids[: sel.size] = self.n0 + rows[sel].astype(np.int32)
        m = queries.shape[0]
        qcap = _round_pow2(m)
        qs = np.zeros((qcap, 3), np.float32)
        qs[:m] = queries
        d_pts = _dispatch.stage(pts)  # syncflow: reshard-delta-stage
        d_ids = _dispatch.stage(dids)  # syncflow: reshard-delta-stage
        kd = min(k, cap)
        g_i, g_d = launch_brute(
            d_pts, _dispatch.stage(qs), kd, ids_map=d_ids,  # syncflow: reshard-delta-query-stage
            base_key=("pod-reshard-delta", self.pp.meta))
        g_i, g_d = _dispatch.fetch(g_i, g_d)  # syncflow: reshard-delta-final
        g_i = np.asarray(g_i)[:m]
        g_d = np.where(g_i >= 0, np.asarray(g_d)[:m], np.inf)
        self.stats["delta_launches"] += 1
        return _merge_rows(ids, d2, g_i, np.asarray(g_d, np.float32), k)

    def query(self, queries: np.ndarray, k: Optional[int] = None):
        """Exact kNN against the CURRENT mutated cloud (stable ids)."""
        k = self.pp.config.k if k is None else int(k)
        ids, d2 = self.pp.query(queries, k)
        ids = np.array(ids)
        d2 = np.array(d2)
        queries = np.ascontiguousarray(queries, np.float32).reshape(-1, 3)
        if self.n_deleted:
            ids, d2 = self._filter_deleted(ids, d2)
        return self._delta_merge(queries, ids, d2, k)

    def solve(self):
        """All-points solve over the ORIGINAL rows against the mutated
        cloud: deleted rows come back invalid; alive rows see inserts as
        candidates through the same pruned delta merge."""
        nb, d2, cert = self.pp.solve()
        nb = np.array(nb)
        d2 = np.array(d2)
        cert = np.array(cert)
        if self.n_deleted:
            nb, d2 = self._filter_deleted(nb, d2)
            dead = ~self.alive
            nb[dead] = INVALID_ID
            d2[dead] = np.inf
            cert[dead] = False
        if self._delta_rows.size and self.n0:
            alive_rows = np.nonzero(self.alive)[0]
            if alive_rows.size:
                q = self.pp._points_host[alive_rows]
                m_i, m_d = self._delta_merge(q, nb[alive_rows],
                                             d2[alive_rows],
                                             self.pp.config.k)
                nb[alive_rows] = m_i
                d2[alive_rows] = m_d
        return nb, d2, cert

    def stats_dict(self) -> dict:
        return {**self.stats, "n_points": self.n_points,
                "n_deleted": self.n_deleted,
                "delta_pending": int(self._delta_alive.sum())}


# =============================================================================
# Layer 2: the elastic serving index -- Morton-range shards + live resharding
# =============================================================================

class RangeShard:
    """One contiguous Morton range: a base problem + delta overlay, with a
    uid ledger parallel to the overlay's canonical order.  Every answer
    and every migration speaks uids -- stable for a point's whole life, no
    matter how many shards it crosses."""

    def __init__(self, shard_id: int, points: np.ndarray, uids: np.ndarray,
                 k: int, compact_threshold: int = 512):
        from ..api import KnnProblem

        self.shard_id = int(shard_id)
        self.k = int(k)
        self.compact_threshold = int(compact_threshold)
        pts = np.ascontiguousarray(
            np.asarray(points, np.float32).reshape(-1, 3))
        problem = KnnProblem.prepare(pts, KnnConfig(k=self.k,
                                                    adaptive=False))
        self.overlay = DeltaOverlay(problem,
                                    compact_threshold=compact_threshold)
        self.uids = np.asarray(uids, np.int64).reshape(-1).copy()  # kntpu-ok: wide-dtype -- uid ledger, host-only bookkeeping
        self.migrations_in = 0
        self.migrations_out = 0

    @property
    def n_points(self) -> int:
        return self.overlay.n_points

    def points(self) -> np.ndarray:
        """Canonical-order cloud, parallel to ``self.uids``."""
        return self.overlay.mutated_points()

    def insert(self, points: np.ndarray, uids: np.ndarray) -> None:
        pts = np.asarray(points, np.float32).reshape(-1, 3)
        if pts.shape[0] == 0:
            return
        self.overlay.insert(pts)
        self.uids = np.concatenate(
            [self.uids, np.asarray(uids, np.int64).reshape(-1)])  # kntpu-ok: wide-dtype -- uid ledger, host-only bookkeeping

    def delete_uids(self, uids: np.ndarray) -> int:
        """Delete by uid; returns how many were present (idempotent)."""
        sel = np.nonzero(np.isin(self.uids, np.asarray(uids)))[0]
        if sel.size == 0:
            return 0
        self.overlay.delete(sel)
        self.uids = np.delete(self.uids, sel)
        return int(sel.size)

    def query(self, queries: np.ndarray, k: int):
        """((m, k) uids, -1 pad; (m, k) d2) -- the overlay's canonical ids
        translated through the ledger."""
        m = np.asarray(queries).shape[0]
        if self.n_points == 0:
            return (np.full((m, k), -1, np.int64),  # kntpu-ok: wide-dtype -- uid rows, host-only
                    np.full((m, k), np.inf, np.float32))
        li, ld = self.overlay.query(queries, k)
        li = np.asarray(li)
        safe = np.clip(li, 0, max(0, self.uids.size - 1))
        out = np.where(li >= 0, self.uids[safe], np.int64(-1))  # kntpu-ok: wide-dtype -- uid rows, host-only
        return out, np.asarray(ld, np.float32)


@dataclasses.dataclass
class ShipRecord:
    """One committed migration record, per the PR 10 replication protocol:
    dense 1-based seq, only-committed-acked (the receiver acks each record
    in order; the handover requires acked == committed)."""

    seq: int
    kind: str                      # 'insert' | 'delete'
    uids: np.ndarray               # (m,) i64
    points: Optional[np.ndarray]   # (m, 3) f32 for inserts


class Migration:
    """One live range-boundary move: donor shard -> receiver shard.

    Shipping is chunked and pumped (``step``) so queries interleave: the
    index keeps routing the moving range to the DONOR until the handover,
    and the receiver holds shipped records in a pending set it does not
    serve -- no row is ever answerable from two shards, so the merge needs
    no dedup and the byte-identity pin survives the whole migration.
    Mid-migration mutations in the moving range apply to the donor (the
    serving truth) AND append to the stream, exactly like the PR 10
    replication log tail."""

    def __init__(self, index: "ElasticIndex", donor: int, receiver: int,
                 new_cuts: np.ndarray, chunk: int = 64):
        self.index = index
        self.donor = int(donor)
        self.receiver = int(receiver)
        self.new_cuts = np.asarray(new_cuts, np.int64)  # kntpu-ok: wide-dtype -- Morton cut table, host-only
        self.chunk = max(1, int(chunk))
        d = index.shards[self.donor]
        pts = d.points()
        codes = morton_codes(pts, index.domain)
        moving_mask = index._route(codes, self.new_cuts) != self.donor
        self.moving = set(int(u) for u in d.uids[moving_mask])
        self._coords: Dict[int, np.ndarray] = {
            int(u): pts[i] for i, u in enumerate(d.uids) if moving_mask[i]}
        self.queue: List[int] = [int(u) for u in d.uids[moving_mask]]
        self._qpos = 0
        self.records: List[ShipRecord] = []
        self.committed_seq = 0
        self.acked_seq = 0
        # receiver-side pending set (insertion-ordered): applied records
        # the receiver holds but does NOT serve until the handover
        self.pending: Dict[int, np.ndarray] = {}
        self.state = "shipping"
        self.wedged = False          # chaos: receiver stops acking
        self.handover_delay = 0      # chaos: pumps to sit ready before flip
        self.pumps = 0

    # -- the committed stream -------------------------------------------------

    def _append(self, kind: str, uids: np.ndarray,
                points: Optional[np.ndarray]) -> ShipRecord:
        # proto: migration-handover.ship
        prototrace.record("migration-handover", "ship")
        rec = ShipRecord(seq=self.committed_seq + 1, kind=kind,
                         uids=np.asarray(uids, np.int64).reshape(-1),  # kntpu-ok: wide-dtype -- uid payload, host-only
                         points=points)
        self.records.append(rec)
        self.committed_seq = rec.seq
        self._ship(rec)
        return rec

    def _ship(self, rec: ShipRecord) -> None:
        """Deliver one record to the receiver's pending set.  A wedged
        receiver drops the delivery AND the ack -- the handover gate
        (acked == committed) then holds the flip forever, which is what
        makes wedging safe: the donor keeps serving."""
        # proto: migration-handover.ship
        if self.wedged:
            return
        if rec.seq != self.acked_seq + 1:
            raise RuntimeError(  # kntpu-ok: bare-valueerror -- internal protocol invariant, not input validation
                f"migration sequence gap: receiver acked {self.acked_seq},"
                f" record carries seq {rec.seq}")
        if rec.kind == "insert":
            for i, u in enumerate(rec.uids.tolist()):
                self.pending[u] = np.asarray(rec.points[i], np.float32)  # kntpu-ok: host-sync-loop -- committed migration record (host numpy), no device array rides this loop
        else:
            for u in rec.uids.tolist():
                self.pending.pop(u, None)
        self.acked_seq = rec.seq

    # -- mid-migration mutations ---------------------------------------------

    def on_insert(self, points: np.ndarray, uids: np.ndarray) -> None:
        """New points that routed to the donor but live in the MOVING
        range: the donor serves them (old owner answers until handover)
        and the stream ships them."""
        # proto: migration-handover.insert
        prototrace.record("migration-handover", "insert")
        for u in np.asarray(uids).tolist():
            self.moving.add(int(u))
        self._append("insert", uids, np.asarray(points, np.float32))

    def on_delete(self, uids: np.ndarray) -> None:
        """Deletes of moving uids: already applied to the donor by the
        index; unshipped ones silently leave the queue, shipped ones ship
        a delete record so the receiver's pending set drops them."""
        # proto: migration-handover.insert -- mid-migration mutation, same action
        prototrace.record("migration-handover", "insert")
        dead = set(int(u) for u in np.asarray(uids).tolist()) & self.moving
        if not dead:
            return
        shipped = [u for u in dead
                   if u in self.pending or (self.wedged and u not in
                                            self.queue[self._qpos:])]
        unshipped = dead - set(shipped)
        for u in dead:
            self.moving.discard(u)
            self._coords.pop(u, None)
        if unshipped:
            rest = self.queue[self._qpos:]
            keep = [u for u in rest if u not in unshipped]
            self.queue = self.queue[: self._qpos] + keep
        if shipped:
            self._append("delete", np.asarray(sorted(shipped), np.int64),  # kntpu-ok: wide-dtype -- uid payload, host-only
                         None)

    # -- pumping --------------------------------------------------------------

    @property
    def shipping_done(self) -> bool:
        return self._qpos >= len(self.queue)

    @property
    def ready(self) -> bool:
        return (self.shipping_done
                and self.acked_seq == self.committed_seq
                and self.handover_delay <= 0)

    def step(self) -> None:
        """One pump: ship the next chunk, or burn a handover delay."""
        self.pumps += 1
        if not self.shipping_done:
            take = self.queue[self._qpos: self._qpos + self.chunk]
            self._qpos += len(take)
            take = [u for u in take if u in self.moving]
            if take:
                pts = np.stack([self._coords[u] for u in take])
                self._append("insert", np.asarray(take, np.int64), pts)  # kntpu-ok: wide-dtype -- uid payload, host-only
            return
        if self.handover_delay > 0:
            self.handover_delay -= 1

    def abort(self) -> None:
        """Abandon the move: the receiver discards its pending set, the
        cuts never flip, the donor never deleted -- zero data loss by
        construction (the donor stayed the serving truth throughout)."""
        # proto: migration-handover.abort
        prototrace.record("migration-handover", "abort")
        self.pending.clear()
        self.state = "aborted"

    def handover(self, fault: Optional[str] = None) -> dict:
        """Flip ownership: apply the pending set to the receiver, move the
        cut, delete the moved uids from the donor.

        ``fault`` forges a broken flip for the chaos/fault harness:
        'torn-migration' drops the stream's tail record at the flip (the
        receiver misses committed data it acked), 'lost-range' flips the
        cut and deletes from the donor while the receiver applies NOTHING
        -- both provably detectable by the rebuild/differential oracles."""
        # proto: migration-handover.handover
        prototrace.record("migration-handover", "handover")
        index = self.index
        pend = dict(self.pending)
        if fault == "torn-migration" and pend:
            torn = next(reversed(pend))
            del pend[torn]
        elif fault == "lost-range":
            pend = {}
        landed = np.asarray(list(pend.keys()), np.int64)  # kntpu-ok: wide-dtype -- uid payload, host-only
        if landed.size:
            pts = np.stack([pend[int(u)] for u in landed])
            index.shards[self.receiver].insert(pts, landed)
        index.cuts = self.new_cuts
        moved = np.asarray(sorted(self.moving), np.int64)  # kntpu-ok: wide-dtype -- uid payload, host-only
        deleted = index.shards[self.donor].delete_uids(moved)
        for u in landed.tolist():
            index._shard_of_uid[int(u)] = self.receiver
        index.shards[self.donor].migrations_out += 1
        index.shards[self.receiver].migrations_in += 1
        self.state = "done"
        return {"moved": int(moved.size), "landed": int(landed.size),
                "deleted_from_donor": int(deleted),
                "records": self.committed_seq, "fault": fault}


class ElasticIndex:
    """The pod-partitioned serving index: Morton-range shards, scatter-
    gather queries, live resharding under traffic.

    Public ids are CANONICAL current ids with ``np.delete`` +
    ``np.concatenate`` semantics -- byte-compatible with the dense
    tenant's DeltaOverlay contract, so the fleet front door serves a pod
    tenant through the exact same admission/commit path.  Internally
    every point carries a stable uid; the canonical <-> uid translation
    is two host arrays maintained per mutation.
    """

    def __init__(self, points: np.ndarray, k: int, nshards: int = 2,
                 compact_threshold: int = 512, skew_threshold: float = 3.0,
                 migration_chunk: int = 64, domain: float = 1000.0,
                 abort_after_pumps: int = 256):
        pts = np.ascontiguousarray(
            np.asarray(points, np.float32).reshape(-1, 3))
        n = pts.shape[0]
        self.k = int(k)
        self.domain = float(domain)
        self.compact_threshold = int(compact_threshold)
        self.skew_threshold = float(skew_threshold)
        self.migration_chunk = int(migration_chunk)
        self.abort_after_pumps = int(abort_after_pumps)
        self.fault: Optional[str] = None   # seeded: torn-migration|lost-range
        self.migration: Optional[Migration] = None
        self.migrations_done = 0
        self.migrations_aborted = 0
        self.elastic_recompiles = 0   # exec-cache misses attributed to
        #                               migration/rebuild work (the
        #                               --assert-steady carve-out)
        codes = morton_codes(pts, self.domain)
        nshards = max(1, min(int(nshards), max(1, n)))
        order = np.argsort(codes, kind="stable")
        cuts = [np.int64(0)]  # kntpu-ok: wide-dtype -- Morton cut table, host-only
        for j in range(1, nshards):
            cuts.append(codes[order[j * n // nshards]])
        cuts.append(np.int64(_MAX_CODE))  # kntpu-ok: wide-dtype -- Morton cut table, host-only
        self.cuts = np.asarray(cuts, np.int64)  # kntpu-ok: wide-dtype -- Morton cut table, host-only
        # duplicate-heavy clouds can collapse a cut; drop empty ranges
        # rather than preparing empty shards
        route = self._route(codes, self.cuts)
        keep = np.asarray([j for j in range(nshards)
                           if (route == j).any()])
        if keep.size < nshards:
            self.cuts = np.concatenate(
                [self.cuts[keep], self.cuts[-1:]])
            route = self._route(codes, self.cuts)
            nshards = keep.size
        self.nshards = int(nshards)
        uids = np.arange(n, dtype=np.int64)  # kntpu-ok: wide-dtype -- uid ledger, host-only
        self.uids_canonical = uids.copy()
        self.next_uid = n
        with self._attributed():
            self.shards = [RangeShard(j, pts[route == j], uids[route == j],
                                      self.k, compact_threshold)
                           for j in range(self.nshards)]
        self._shard_of_uid: Dict[int, int] = {
            int(u): int(s) for u, s in zip(uids, route)}
        self._canon_of_uid: Optional[np.ndarray] = None
        # query batch shapes served so far: a handover/chip-loss rebuild
        # re-warms these under _attributed(), so index maintenance never
        # leaks first-query compiles into the serving steady state
        self._seen_batches: set = set()

    # -- routing / bookkeeping ------------------------------------------------

    def _route(self, codes: np.ndarray,
               cuts: Optional[np.ndarray] = None) -> np.ndarray:
        c = self.cuts if cuts is None else cuts
        return np.clip(np.searchsorted(c, codes, side="right") - 1,
                       0, c.size - 2).astype(np.int32)

    @contextlib.contextmanager
    def _attributed(self):
        """Attribute exec-cache misses inside the block to elastic work
        (migration handovers, shard rebuilds): the loadgen steady-state
        gate subtracts these from its recompile count, so a live
        migration never trips ``--assert-steady`` while a genuine serving
        recompile still does."""
        m0 = _dispatch.EXEC_CACHE.misses
        try:
            yield
        finally:
            self.elastic_recompiles += _dispatch.EXEC_CACHE.misses - m0

    @property
    def n_points(self) -> int:
        return int(self.uids_canonical.size)

    @property
    def mutations_pending(self) -> int:
        return sum(s.overlay.mutations_pending for s in self.shards)

    def _canon_map(self) -> np.ndarray:
        if self._canon_of_uid is None:
            m = np.full((max(1, self.next_uid),), -1, np.int32)
            m[self.uids_canonical] = np.arange(
                self.uids_canonical.size, dtype=np.int32)
            self._canon_of_uid = m
        return self._canon_of_uid

    def mutated_points(self) -> np.ndarray:
        """The canonical cloud (the rebuild/replay oracle's input)."""
        pos: Dict[int, np.ndarray] = {}
        for s in self.shards:
            pts = s.points()
            for i, u in enumerate(s.uids.tolist()):
                pos[u] = pts[i]
        if self.migration is not None and self.migration.state == "done":
            pass  # done migrations detach in pump()
        out = np.empty((self.uids_canonical.size, 3), np.float32)
        for i, u in enumerate(self.uids_canonical.tolist()):
            out[i] = pos[u]
        return np.ascontiguousarray(out)

    # -- mutations (canonical-id contract, same as DeltaOverlay) --------------

    def insert(self, points: np.ndarray) -> None:
        pts = np.ascontiguousarray(
            np.asarray(points, np.float32).reshape(-1, 3))
        if pts.shape[0] == 0:
            return
        uids = np.arange(self.next_uid, self.next_uid + pts.shape[0],
                         dtype=np.int64)  # kntpu-ok: wide-dtype -- uid ledger, host-only
        self.next_uid += pts.shape[0]
        self.uids_canonical = np.concatenate([self.uids_canonical, uids])
        self._canon_of_uid = None
        codes = morton_codes(pts, self.domain)
        route = self._route(codes)
        mig = self.migration
        for j in np.unique(route):
            sel = route == j
            with self._attributed():
                # overlay compaction past compact_threshold re-prepares
                # the shard base: index maintenance, not serving work
                self.shards[int(j)].insert(pts[sel], uids[sel])
            for u in uids[sel].tolist():
                self._shard_of_uid[int(u)] = int(j)
            if (mig is not None and mig.state == "shipping"
                    and int(j) == mig.donor):
                new_route = self._route(codes[sel], mig.new_cuts)
                mv = new_route != mig.donor
                if mv.any():
                    mig.on_insert(pts[sel][mv], uids[sel][mv])

    def delete(self, ids: np.ndarray) -> None:
        """Delete by canonical CURRENT id (np.delete semantics)."""
        ids = np.unique(np.asarray(ids, np.int64).reshape(-1))  # kntpu-ok: wide-dtype -- host id arithmetic, never staged
        if ids.size == 0:
            return
        uids = self.uids_canonical[ids]
        self.uids_canonical = np.delete(self.uids_canonical, ids)
        self._canon_of_uid = None
        shard_of = np.asarray([self._shard_of_uid[int(u)] for u in uids],
                              np.int32)
        for j in np.unique(shard_of):
            batch = uids[shard_of == j]
            with self._attributed():
                self.shards[int(j)].delete_uids(batch)
        for u in uids.tolist():
            self._shard_of_uid.pop(int(u), None)
        mig = self.migration
        if mig is not None and mig.state == "shipping":
            mig.on_delete(uids)

    # -- queries --------------------------------------------------------------

    @staticmethod
    def _merge_uid_rows(per_shard: List[Tuple[np.ndarray, np.ndarray]],
                        k: int):
        """Deterministic scatter-gather merge: pure comparisons over
        (d2, uid), invalid slots (uid < 0) last via inf, ties by lower
        uid -- the same discipline as serve/delta._merge_rows, lifted to
        uid rows."""
        ids = np.concatenate([p[0] for p in per_shard], axis=1)
        d2 = np.concatenate([p[1] for p in per_shard], axis=1)
        d2 = np.where(ids >= 0, d2, np.inf)
        order = np.lexsort((ids, d2), axis=1)[:, :k]
        rows = np.arange(ids.shape[0])[:, None]
        out_i, out_d = ids[rows, order], d2[rows, order]
        out_i = np.where(np.isfinite(out_d), out_i, np.int64(-1))  # kntpu-ok: wide-dtype -- uid rows, host-only
        return out_i, np.ascontiguousarray(out_d, np.float32)

    def query(self, queries: np.ndarray, k: int):
        """((m, k) canonical ids, -1 pad; (m, k) d2) against the CURRENT
        cloud: every shard answers its exact local top-k (the old owner
        keeps answering for ranges mid-migration), one deterministic
        merge, uid -> canonical translation at the boundary."""
        queries = np.ascontiguousarray(queries, np.float32).reshape(-1, 3)
        m = queries.shape[0]
        if m == 0 or self.n_points == 0:
            return (np.full((m, k), -1, np.int32),
                    np.full((m, k), np.inf, np.float32))
        self._seen_batches.add((m, int(k)))
        per_shard = [s.query(queries, k) for s in self.shards]
        u_i, out_d = self._merge_uid_rows(per_shard, k)
        cmap = self._canon_map()
        safe = np.clip(u_i, 0, cmap.size - 1)
        out_i = np.where(u_i >= 0, cmap[safe.astype(np.int64)],  # kntpu-ok: wide-dtype -- uid indexing, host-only
                         np.int32(-1)).astype(np.int32)
        return out_i, out_d

    def rebuild_oracle_query(self, queries: np.ndarray, k: int):
        """The byte-identity oracle: a fresh from-scratch problem per
        shard over that shard's EXACT canonical-order cloud, queried and
        merged with the identical deterministic merge.  The serve-tier
        pin (DeltaOverlay == rebuild on the mutated cloud) makes each
        shard's answers byte-identical, and the merge is pure
        comparisons, so the whole index's answers must match this oracle
        byte for byte -- including mid- and post-migration."""
        from ..api import KnnProblem

        queries = np.ascontiguousarray(queries, np.float32).reshape(-1, 3)
        m = queries.shape[0]
        if m == 0 or self.n_points == 0:
            return (np.full((m, k), -1, np.int32),
                    np.full((m, k), np.inf, np.float32))
        per_shard = []
        for s in self.shards:
            if s.n_points == 0:
                per_shard.append(
                    (np.full((m, k), -1, np.int64),  # kntpu-ok: wide-dtype -- uid rows, host-only
                     np.full((m, k), np.inf, np.float32)))
                continue
            fresh = KnnProblem.prepare(s.points(),
                                       KnnConfig(k=self.k, adaptive=False))
            li, ld = fresh.query(queries, k)
            li = np.asarray(li)  # kntpu-ok: host-sync-loop -- rebuild ORACLE path: one bounded fetch per shard by design, never the serving route
            safe = np.clip(li, 0, max(0, s.uids.size - 1))
            per_shard.append((np.where(li >= 0, s.uids[safe],
                                       np.int64(-1)),  # kntpu-ok: wide-dtype -- uid rows, host-only
                              np.asarray(ld, np.float32)))  # kntpu-ok: host-sync-loop -- rebuild ORACLE path: one bounded fetch per shard by design, never the serving route
        u_i, out_d = self._merge_uid_rows(per_shard, k)
        cmap = self._canon_map()
        safe = np.clip(u_i, 0, cmap.size - 1)
        out_i = np.where(u_i >= 0, cmap[safe.astype(np.int64)],  # kntpu-ok: wide-dtype -- uid indexing, host-only
                         np.int32(-1)).astype(np.int32)
        return out_i, out_d

    # -- resharding -----------------------------------------------------------

    def _skew(self) -> Tuple[float, int]:
        pops = np.asarray([s.n_points for s in self.shards], np.float64)  # kntpu-ok: wide-dtype -- host skew statistic
        mean = max(1.0, float(pops.mean()))
        hot = int(pops.argmax())
        return float(pops[hot]) / mean, hot

    def _plan_rebalance(self, donor: int) -> Optional[Migration]:
        """Move the boundary between the donor and its lighter adjacent
        neighbor so the pair's population equalizes: a range split on the
        donor side, merged into the receiver's range -- one cut moves,
        one slab migrates."""
        if self.nshards < 2:
            return None
        cands = [j for j in (donor - 1, donor + 1)
                 if 0 <= j < self.nshards]
        receiver = min(cands, key=lambda j: self.shards[j].n_points)
        d = self.shards[donor]
        if d.n_points <= 1:
            return None
        excess = (d.n_points - self.shards[receiver].n_points) // 2
        if excess <= 0:
            return None
        codes = np.sort(morton_codes(d.points(), self.domain))
        new_cuts = self.cuts.copy()
        if receiver < donor:
            # donate the donor's LOW end: raise the receiver/donor cut
            new_cuts[donor] = codes[min(excess, codes.size - 1)]
        else:
            # donate the donor's HIGH end: lower the donor/receiver cut
            new_cuts[donor + 1] = codes[max(0, codes.size - excess)]
        if np.array_equal(new_cuts, self.cuts):
            return None
        mig = Migration(self, donor, receiver, new_cuts,
                        chunk=self.migration_chunk)
        if not mig.moving:
            return None
        return mig

    def maybe_rebalance(self) -> bool:
        """Start a migration when the population skew crosses the
        threshold (deterministic: same stream -> same trigger)."""
        # proto: migration-handover.start
        if self.migration is not None or self.nshards < 2:
            return False
        skew, hot = self._skew()
        if skew < self.skew_threshold:
            return False
        self.migration = self._plan_rebalance(hot)
        if self.migration is not None:
            prototrace.record("migration-handover", "start")
        return self.migration is not None

    def force_rebalance(self) -> bool:
        """Start a boundary move off the hottest shard regardless of the
        threshold (the bench/chaos trigger)."""
        # proto: migration-handover.start
        if self.migration is not None or self.nshards < 2:
            return False
        _, hot = self._skew()
        self.migration = self._plan_rebalance(hot)
        if self.migration is not None:
            prototrace.record("migration-handover", "start")
        return self.migration is not None

    def pump(self) -> Optional[dict]:
        """Advance the live migration one step; returns the handover
        summary on the pump that completes it.  Called between batches by
        the fleet front door -- resharding progresses UNDER traffic, and
        no single pump does unbounded work (no stop-the-world)."""
        # proto: migration-handover.pump
        mig = self.migration
        if mig is None:
            return None
        prototrace.record("migration-handover", "pump")
        if mig.state != "shipping":
            self.migration = None
            return None
        if (mig.wedged and mig.pumps >= self.abort_after_pumps):
            mig.abort()
            self.migration = None
            self.migrations_aborted += 1
            return {"aborted": True, "records": mig.committed_seq}
        mig.step()
        if mig.ready:
            with self._attributed():
                info = mig.handover(fault=self.fault)
                # fold the shipped delta (receiver) and the tombstoned
                # moved range (donor) into fresh bases NOW, as index-
                # maintenance cost: post-handover serving queries must not
                # pay per-query delta launches against a slab-sized
                # pending delta (compaction is byte-identity-preserving)
                for j in (mig.donor, mig.receiver):
                    self.shards[j].overlay.compact()
                self._rewarm()
            self.migration = None
            self.migrations_done += 1
            return info
        return None

    def _rewarm(self) -> None:
        """Replay every query batch shape served so far against the
        post-rebuild shards (results discarded).  Runs INSIDE an
        ``_attributed()`` block: any executable the rebuild invalidated
        compiles here, as index-maintenance cost, instead of on the first
        serving query after the handover."""
        for m, k in sorted(self._seen_batches):
            self.query(np.zeros((m, 3), np.float32), k)

    # -- chaos surfaces -------------------------------------------------------

    def lose_shard(self, j: int, canonical_points: np.ndarray) -> dict:
        """Chip loss: shard ``j``'s in-memory state is gone; rebuild it
        from the committed log's replayed cloud (the caller supplies the
        canonical replay -- replication is the durability story, exactly
        as in PR 10).  An in-flight migration touching the shard aborts:
        the donor keeps (or regains) the truth, nothing committed is
        lost."""
        j = int(j) % max(1, self.nshards)
        mig = self.migration
        if mig is not None and j in (mig.donor, mig.receiver):
            mig.abort()
            self.migration = None
            self.migrations_aborted += 1
        pts = np.ascontiguousarray(
            np.asarray(canonical_points, np.float32).reshape(-1, 3))
        codes = morton_codes(pts, self.domain)
        route = self._route(codes)
        sel = route == j
        with self._attributed():
            self.shards[j] = RangeShard(j, pts[sel],
                                        self.uids_canonical[sel],
                                        self.k, self.compact_threshold)
            self._rewarm()
        for u in self.uids_canonical[sel].tolist():
            self._shard_of_uid[int(u)] = j
        return {"shard": j, "rebuilt_points": int(sel.sum())}

    def wedge_migration(self) -> bool:
        if self.migration is not None:
            self.migration.wedged = True
            return True
        return False

    def delay_handover(self, pumps: int) -> bool:
        if self.migration is not None:
            self.migration.handover_delay += max(0, int(pumps))
            return True
        return False

    # -- diagnostics ----------------------------------------------------------

    def stats_dict(self) -> dict:
        skew, hot = self._skew()
        return {
            "elastic_shards": self.nshards,
            "elastic_points": self.n_points,
            "elastic_skew": round(skew, 3),
            "elastic_hot_shard": hot,
            "elastic_migrations_done": self.migrations_done,
            "elastic_migrations_aborted": self.migrations_aborted,
            "elastic_migration_active": self.migration is not None,
            "elastic_recompiles": self.elastic_recompiles,
            "shard_points": [s.n_points for s in self.shards],
            "shard_migrations": [
                {"in": s.migrations_in, "out": s.migrations_out}
                for s in self.shards],
        }
