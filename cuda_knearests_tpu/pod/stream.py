"""HBM auto-splitting: the per-chip footprint model and its budget gate.

PR 2's ``hbm_bytes_estimate``/``preflight_launch`` REFUSED would-OOM
launches; this module turns the same preflight into the automatic
partitioner ("Memory Safe Computations with XLA", arXiv 2206.14148): a
cloud whose single-chip footprint exceeds the budget is not refused -- it
streams through the pod partitioner in slab-sized host-to-device stages
(halo.stage_sharded), and the budget is enforced against the PER-CHIP
model instead.  Only a cloud whose *slab* cannot fit a chip refuses, with
the same typed ``LaunchBudgetError`` taxonomy and a pointer at the knob
that helps (more chips).

Like every HBM model in this tree the estimate is deliberately a slight
overestimate (pads counted, tables at full width): the preflight must
refuse marginal fits, never bless them.  The model is what bench rows
stamp as ``hbm_high_water_bytes`` and what tests/test_pod.py proves stays
under the configured budget while the full cloud exceeds it.
"""

from __future__ import annotations

from typing import List, Optional

from ..config import KnnConfig
from ..ops.pallas_solve import hbm_budget_bytes
from ..utils.memory import LaunchBudgetError
from .partition import PodChipPlan, PodMeta


def chip_hbm_model(meta: PodMeta, chip: PodChipPlan, k: int) -> int:
    """Modeled peak HBM (bytes) one chip commits to this problem: its
    staged slab (points + ids), the halo-extended window the exchange
    assembles, the ext CSR, every class's cell tables, and the per-class
    solver outputs (row-major (Sc*qcap, k) dists + ids)."""
    n_ext = meta.n_ext
    bucket = meta.pcap * (12 + 4)                 # staged slab: pts + ids
    window = n_ext * (12 + 4)                     # ext pts + ext ids
    csr = 2 * 4 * max(1, chip.ext_starts.size)    # ext starts + counts
    tables = 0
    outputs = 0
    for cp in chip.classes:
        tables += 4 * cp.own.size + 4 * cp.cand.size + 2 * 12 * cp.n_sc
        outputs += 2 * 4 * cp.n_sc * cp.qcap_pad * k
    final = meta.pcap * (8 * k + 1)               # (pcap, k) ids+d2 + cert
    return bucket + window + csr + tables + outputs + final


def full_cloud_model(n: int, k: int) -> int:
    """Modeled single-chip footprint of the UNSPLIT cloud: staged points +
    permutation + CSR-scale tables + the (n, k) result buffers -- the
    quantity the auto-splitter compares against the budget to decide that
    splitting is mandatory (not just profitable)."""
    return n * (12 + 4) + n * (12 + 4) + n * (8 * k + 1)


def preflight_pod(meta: PodMeta, chips: List[PodChipPlan], k: int,
                  cfg: KnnConfig, n_points: int) -> dict:
    """The auto-splitter's gate: per-chip models must fit the budget.

    Returns the stamp dict bench rows and stats() carry --
    ``hbm_budget_bytes`` (None = unbounded), ``hbm_high_water_bytes`` (max
    per-chip model), ``hbm_full_cloud_bytes``, and ``streamed_prepare``
    (True when the full cloud exceeds the budget, i.e. the split was
    mandatory and the slab staging IS what made the problem admissible).
    Raises the typed oom-kind :class:`LaunchBudgetError` when even one
    chip's slab cannot fit -- the refusal arm that survives, now per chip
    rather than per cloud."""
    budget = hbm_budget_bytes(cfg)
    per_chip = [chip_hbm_model(meta, c, k) for c in chips]
    high = max(per_chip) if per_chip else 0
    full = full_cloud_model(n_points, k)
    if budget is not None and high > budget:
        worst = int(per_chip.index(high))
        raise LaunchBudgetError(
            f"pod-prepare: chip {worst}'s modeled slab footprint {high} "
            f"bytes (pcap={meta.pcap}, halo={2 * meta.steps}x{meta.hcap}, "
            f"k={k}) exceeds the {budget} byte per-chip HBM budget even "
            f"after cell-range splitting across {meta.ndev} chip(s); use "
            f"more devices, a coarser grid (config.density), or raise "
            f"config.hbm_budget_bytes / KNTPU_HBM_BUDGET_BYTES",
            requested=high, budget=budget, site="pod-prepare")
    return {
        "hbm_budget_bytes": budget,
        "hbm_high_water_bytes": high,
        "hbm_full_cloud_bytes": full,
        "streamed_prepare": bool(budget is not None and full > budget),
    }


def auto_devices(n_points: int, k: int, cfg: KnnConfig,
                 available: int) -> Optional[int]:
    """The splitter's device-count chooser for ``n_devices=None``: the
    smallest chip count whose EVEN slab share of the staged cloud fits the
    budget (a pre-partition estimate; the real per-chip model is gated by
    :func:`preflight_pod` after planning).  None = no budget configured --
    the caller keeps its default (all devices)."""
    budget = hbm_budget_bytes(cfg)
    if budget is None:
        return None
    for ndev in range(1, available + 1):
        if full_cloud_model(-(-n_points // ndev), k) * 2 <= budget:
            return ndev
    return available
