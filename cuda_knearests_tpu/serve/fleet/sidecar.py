"""The brute CPU sidecar tier: tiny/degenerate tenants off the dense ladder.

"Hybrid KNN-Join" (arXiv 1810.04758) splits work between the accelerator
and the CPU by density; the fleet applies the same split by TENANT: a
tenant whose cloud is under ``ServeFleetConfig.sidecar_threshold`` (or
degenerate, n < k) is served by this pure-host brute worker instead of the
dense batching ladder.  What that buys the fleet:

* **No executable signatures.**  A 40-point tenant would otherwise mint
  its own prepare plan and per-bucket launch signatures -- cache entries
  that evict the dense tenants' hot executables while serving microscopic
  work.  The sidecar touches neither the ExecutableCache nor the dispatch
  layer (the ``fleet-sidecar`` syncflow window proves host_syncs = 0).
* **No batching latency.**  Tiny tenants answer synchronously at
  admission; the bucket ladder, deadline triggers, and DRR scheduling all
  apply only to tenants whose work is worth batching.

Semantics match the dense path's contracts: canonical CURRENT ids with
``np.delete``/``np.concatenate`` mutation indexing (the same rebuild-oracle
indexing as serve/delta.py), -1/inf row padding beyond the available
neighbors, ascending distances with lower-id tie-break, f32 'diff'
arithmetic.  Distances are host-numpy f32, NOT the XLA launch's bits --
the sidecar's answers are exact under the TIE-AWARE comparison contract
(fuzz/compare.check_route_result), which is the fleet fuzz oracle's
discipline; byte-identity to XLA is a dense-path promise only
(DESIGN.md section 17).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from ...oracle import UnionFind


@dataclasses.dataclass
class SidecarFof:
    """FoF answer over a sidecar tenant's cloud (daemon-compatible shape)."""

    labels: np.ndarray
    n_clusters: int


class CpuSidecar:
    """One tiny tenant's serving state: a host point array + brute answers.

    Thread-unsafe by design, same as the dense overlay (the fleet front
    door is single-threaded).
    """

    def __init__(self, points: np.ndarray, k: int):
        self.points = np.ascontiguousarray(points, np.float32).reshape(-1, 3)
        self.k_serve = int(k)
        self.queries_served = 0
        self.inserts = 0
        self.deletes = 0

    # -- state ----------------------------------------------------------------

    @property
    def n_points(self) -> int:
        return int(self.points.shape[0])

    def mutated_points(self) -> np.ndarray:
        """The current cloud in canonical order (the rebuild oracle's
        input) -- the sidecar stores exactly that order, so this is the
        identity view."""
        return self.points

    # -- mutations (np.delete / np.concatenate canonical indexing) -----------

    def insert(self, points: np.ndarray) -> None:
        pts = np.asarray(points, np.float32).reshape(-1, 3)
        if pts.shape[0]:
            self.points = np.ascontiguousarray(
                np.concatenate([self.points, pts]))
            self.inserts += pts.shape[0]

    def delete(self, ids: np.ndarray) -> None:
        ids = np.asarray(ids).reshape(-1)
        if ids.size:
            self.points = np.ascontiguousarray(
                np.delete(self.points, ids, axis=0))
            self.deletes += ids.size

    # -- queries --------------------------------------------------------------

    def query(self, queries: np.ndarray, k: int) \
            -> Tuple[np.ndarray, np.ndarray]:
        """Exact brute kNN rows: (m, k) i32 ids (-1 pads) and (m, k) f32 d2
        ascending (inf pads), lower-id tie-break -- the dense path's row
        contract, computed entirely on the host."""
        queries = np.asarray(queries, np.float32).reshape(-1, 3)
        m, n = queries.shape[0], self.n_points
        ids = np.full((m, k), -1, np.int32)
        d2 = np.full((m, k), np.inf, np.float32)
        self.queries_served += m
        if m == 0 or n == 0:
            return ids, d2
        diff = queries[:, None, :] - self.points[None, :, :]
        dd = (diff * diff).sum(axis=2)
        kk = min(k, n)
        # stable sort on distance keeps storage order within ties -> the
        # lower-id tie-break of serve/delta._merge_rows for free
        order = np.argsort(dd, axis=1, kind="stable")[:, :kk]
        ids[:, :kk] = order.astype(np.int32)
        d2[:, :kk] = np.take_along_axis(dd, order, axis=1)
        return ids, d2

    def fof(self, b: float) -> SidecarFof:
        """Friends-of-friends labels under the engine's f32 'diff' edge
        predicate (d2_f32 <= f32(b)^2), canonical min-member-id labels --
        the same canonicalization contract as cluster/fof.py, via the
        oracle's host union-find."""
        n = self.n_points
        uf = UnionFind(n)
        b2 = np.float32(b) * np.float32(b)
        for i in range(n - 1):
            diff = self.points[i + 1:] - self.points[i]
            dd = (diff * diff).sum(axis=1)
            for j in np.nonzero(dd <= b2)[0]:
                uf.union(i, i + 1 + int(j))
        labels = uf.canonical_labels()
        return SidecarFof(labels=labels,
                          n_clusters=int(np.unique(labels).size) if n else 0)

    def stats_dict(self) -> dict:
        return {"sidecar": True, "n_points": self.n_points,
                "queries_served": self.queries_served,
                "inserts": self.inserts, "deletes": self.deletes}
