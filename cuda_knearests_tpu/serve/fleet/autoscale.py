"""Traffic-driven autoscale + brownout ladder (DESIGN.md section 24).

The fleet's elastic actuators all exist -- replica pools with a
replication log (serve/fleet/tenants.py), live Morton resharding with
``force_rebalance`` boundary moves (pod/reshard.py), and the
sidecar -> dense -> pod placement ladder -- but until this module nothing
closed the sensor -> policy -> actuator loop: under genuine overload the
fleet's only move was admission refusal.  :class:`Autoscaler` closes it
with a DETERMINISTIC, tick-driven control loop (injected clock, ticked
from ``FleetDaemon.poll``), and adds the graceful middle between "serve
exactly" and "serve nothing": a declared **brownout ladder** built on the
PR 14 precision/recall tiers -- serve *approximately but certified*
before shedding, and shed with a typed retry-after hint before dropping.

Sensor set (per SLO class, sampled once per tick):

* **queue depth** -- queued batch rows + batcher-pending rows across the
  class's dense tenants;
* **occupancy EWMA** -- over the batches the fleet executed since the
  last tick (``FleetDaemon.batch_log``);
* **p999** -- per-class total latency over the responses executed SINCE
  THE LAST TICK against the class's ``SloClass.p99_budget_ms`` budget
  (a windowed sensor, deliberately: a cumulative histogram would pin
  the breach forever after one flood and recovery would never fire;
  the cumulative histogram still backs the metrics provider);
* **admission refusal rate** -- the per-tick delta of typed refusals.

Policy law: a class must breach for ``breach_streak`` CONSECUTIVE ticks
(hysteresis) before any actuation, every actuation opens a
``cooldown_ticks`` cooldown, and at most ONE actuation fires per class
per tick -- so oscillation is structurally bounded (the ``autoscale``
model in analysis/models.py proves the anti-flap invariant
exhaustively; its mutants are this module's seeded faults).

Breach ladder (first rung with headroom fires):

1. **scale up** -- one more in-process replica on the busiest dense
   tenant of the class (``Tenant.add_replica``: snapshot bootstrap,
   then the existing replication log ships the tail);
2. **widen** -- a ``force_rebalance`` boundary move on a skewed pod
   tenant (capacity moves toward the hot range);
3. **promote** -- measured-load-driven dense -> pod promotion
   (``maybe_promote_to_pod(force=True)``): sustained served rows, not
   just the static ``pod_threshold``, now triggers the pod rung;
4. **brown down** (brownout classes only, default 'throughput') -- step
   every dense tenant of the class one rung: exact f32 -> bf16 scoring
   (brute-refined, ids still exact) -> bf16 + lowered ``recall_target``
   (certified-approximate).  Replies carry the tier on the wire
   (``Response.degraded``);
5. **shed** -- admission refuses the class's QUERIES with a typed
   ``retry_after_ms`` hint (mutations are never shed: zero lost
   committed mutations is a law, not a best effort).

Clear ladder (the inverse, recovery first): brown UP back to exact
before any de-provisioning, then scale down (victim = least-caught-up
replica, log compacted only to the remaining pool's applied floor --
the no-drop-tail invariant), then a narrowing boundary move.

Seeded faults (``KNTPU_FLEET_FAULT``, the runtime twins of the model
mutants): ``stuck-sensor`` freezes the sensor snapshot after the first
sample, ``flap-policy`` bypasses hysteresis + cooldown,
``scale-drop-tail`` compacts the log to the committed head on
scale-down.  check.sh proves each one rc != 0 through the autoscale
smoke.

Every actuation is recorded to ``prototrace`` under the ``autoscale``
model and the per-class sensor gauges are exported through
``obs.metrics.metrics_snapshot()`` (provider ``fleet_autoscale``); an
actuator that RAISES has the flight-recorder tail harvested into
``failures`` before the error propagates.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ...obs import metrics as _metrics
from ...obs import recorder as _recorder
from ...utils import prototrace

# wire names of the brownout rungs, in ladder order (Tenant.degraded_tier
# indexes this tuple; tier 0 answers carry no stamp)
TIER_NAMES = ("exact", "bf16", "recall")


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Knobs of the control loop (all deterministic given the clock).

    Attributes:
      period_s: tick period on the fleet's injected clock.
      breach_streak / clear_streak: hysteresis -- consecutive agreeing
        ticks required before a breach/clear actuation.
      cooldown_ticks: ticks after ANY actuation before the next
        (bounded oscillation; the model's anti-flap invariant).
      max_extra_replicas: per-tenant cap on autoscaler-added replicas
        (scale-down only ever removes what scale-up added).
      queue_high_rows / queue_low_rows: queued-rows breach/clear bands.
      refusal_high: per-tick typed-refusal delta that counts as breach.
      occupancy_high: batch-occupancy EWMA breach threshold.
      p999_factor: budget multiplier on SloClass.p99_budget_ms.
      promote_min_points / promote_load_rows: measured-load dense->pod
        promotion gate (cloud size floor + served rows since last tick).
      brownout_classes: SLO classes allowed down the ladder.
      recall_target: the certified band of the deepest rung.
      max_tier: ladder depth (2 = exact -> bf16 -> recall).
      shed_retry_after_s / shed_window_s: the typed defer hint and how
        long a shed episode lasts.
    """

    period_s: float = 0.02
    breach_streak: int = 2
    clear_streak: int = 3
    cooldown_ticks: int = 2
    max_extra_replicas: int = 1
    queue_high_rows: int = 192
    queue_low_rows: int = 16
    refusal_high: int = 4
    occupancy_high: float = 0.97
    p999_factor: float = 1.0
    promote_min_points: int = 1024
    promote_load_rows: int = 512
    brownout_classes: Tuple[str, ...] = ("throughput",)
    recall_target: float = 0.9
    max_tier: int = 2
    shed_retry_after_s: float = 0.05
    shed_window_s: float = 0.1


class _ClassState:
    """Per-SLO-class policy state (streaks, cooldown, ladder position)."""

    __slots__ = ("breach_streak", "clear_streak", "cooldown", "tier",
                 "actions", "last_refused", "last_served", "occ_ewma")

    def __init__(self) -> None:
        self.breach_streak = 0
        self.clear_streak = 0
        self.cooldown = 0
        self.tier = 0
        self.actions = 0
        self.last_refused = 0
        self.last_served = 0
        self.occ_ewma = 0.0


class Autoscaler:
    """The control loop.  Owned by :class:`~.frontdoor.FleetDaemon` when
    constructed with an ``autoscale=`` config; ``tick(now)`` is called
    from every ``poll``/``pump`` pass and is a no-op until the period
    elapses, so existing event loops drive the policy for free."""

    def __init__(self, fleet, config: Optional[AutoscaleConfig] = None):
        self.fleet = fleet
        self.config = config or AutoscaleConfig()
        self.classes: Dict[str, _ClassState] = {}
        self.counters = {k: 0 for k in (
            "ticks", "scale_up", "scale_down", "widen", "narrow",
            "promote", "brown_down", "brown_up", "shed",
            "actuation_failures")}
        self.events: Deque[dict] = deque(maxlen=1024)
        self.failures: List[dict] = []
        self.added: Dict[str, int] = {}      # replicas added per tenant
        self.shed_until: Dict[str, float] = {}
        self.last_sensors: Dict[str, dict] = {}
        self._next_tick: Optional[float] = None
        self._log_seen = 0
        self._frozen: Optional[Dict[str, dict]] = None  # stuck-sensor
        self.class_hist: Dict[str, _metrics.Histogram] = {}
        self._window: Dict[str, List[float]] = {}  # latencies since tick
        _metrics.REGISTRY.register_provider("fleet_autoscale",
                                            self._provider)

    # -- sensors --------------------------------------------------------------

    def observe(self, slo: str, responses) -> None:
        """Front-door hook: bin every executed query response's total
        latency into the class histogram (the p999 sensor's source)."""
        hist = self.class_hist.get(slo)
        if hist is None:
            hist = self.class_hist[slo] = _metrics.Histogram(
                f"fleet.{slo}.total_ms")
        win = self._window.setdefault(slo, [])
        for r in responses:
            if r.ok and r.ids is not None:
                hist.observe(r.latency_s * 1e3)
                win.append(r.latency_s * 1e3)

    def _provider(self) -> dict:
        """The ``fleet_autoscale`` metrics provider: per-class sensor
        gauges (queue depth, occupancy EWMA, refusal rate, p999) plus
        the ladder position -- the policy's full input, inspectable over
        the ``metrics`` wire op."""
        out = {}
        for cls in sorted(self.classes):
            st = self.classes[cls]
            s = self.last_sensors.get(cls, {})
            hist = self.class_hist.get(cls)
            out[cls] = {
                "queue_rows": s.get("queue_rows", 0),
                "occupancy_ewma": round(st.occ_ewma, 4),
                "refusal_delta": s.get("refused_delta", 0),
                "p999_ms": (hist.percentile(0.999)
                            if hist is not None else None),
                "tier": st.tier,
                "tier_name": TIER_NAMES[min(st.tier,
                                            len(TIER_NAMES) - 1)],
                "breach_streak": st.breach_streak,
                "cooldown": st.cooldown,
                "actions": st.actions,
            }
        return out

    def _class_tenants(self, cls: str):
        return [t for t in self.fleet.tenants.values()
                if t.spec.slo == cls]

    def _state(self, cls: str) -> _ClassState:
        st = self.classes.get(cls)
        if st is None:
            st = self.classes[cls] = _ClassState()
        return st

    def _sense(self, now: float) -> Dict[str, dict]:
        """One sensor sample per SLO class.  The seeded ``stuck-sensor``
        fault freezes the FIRST sample forever -- the policy then reads
        stale truth and provably never reacts (check.sh's liveness
        assertion catches it)."""
        if self.fleet._fault == "stuck-sensor" and self._frozen is not None:
            return self._frozen
        fresh = list(self.fleet.batch_log)[
            max(0, len(self.fleet.batch_log)
                - (self.fleet.n_batches - self._log_seen)):]
        self._log_seen = self.fleet.n_batches
        out: Dict[str, dict] = {}
        for cls in sorted({t.spec.slo for t in
                           self.fleet.tenants.values()}):
            st = self._state(cls)
            tenants = self._class_tenants(cls)
            queue_rows = sum(
                sum(b.total for b in t.ready)
                + t.daemon.batcher.pending_queries
                for t in tenants if t.daemon is not None)
            refused = sum(self.fleet.refused.get(t.spec.name, 0)
                          for t in tenants)
            served = sum(self.fleet.served_rows.get(t.spec.name, 0)
                         for t in tenants)
            occs = [e["rows"] / e["capacity"] for e in fresh
                    if e["slo"] == cls and e["capacity"]]
            if occs:
                st.occ_ewma = (0.8 * st.occ_ewma
                               + 0.2 * sum(occs) / len(occs))
            # windowed p999: only the latencies observed since the last
            # tick vote -- an idle/recovered class reads None and can
            # clear (recovery liveness; the cumulative class_hist keeps
            # the whole-session tail for the metrics provider)
            win = self._window.pop(cls, None)
            p999 = (sorted(win)[int(0.999 * (len(win) - 1))]
                    if win else None)
            budget = (tenants[0].spec.slo_class.p99_budget_ms
                      * self.config.p999_factor)
            refused_delta = refused - st.last_refused
            served_delta = served - st.last_served
            st.last_refused, st.last_served = refused, served
            breach = (queue_rows >= self.config.queue_high_rows
                      or refused_delta >= self.config.refusal_high
                      or st.occ_ewma >= self.config.occupancy_high
                      or (p999 is not None and p999 > budget))
            clear = (queue_rows <= self.config.queue_low_rows
                     and refused_delta == 0
                     and (p999 is None or p999 <= budget))
            out[cls] = {"queue_rows": queue_rows,
                        "refused_delta": refused_delta,
                        "served_delta": served_delta,
                        "p999_ms": p999, "breach": breach,
                        "clear": clear}
        self.last_sensors = out
        if self.fleet._fault == "stuck-sensor":
            self._frozen = out
        return out

    # -- the loop -------------------------------------------------------------

    def tick(self, now: float) -> List[dict]:
        """One pass of the control loop; returns the actuation events it
        fired (empty until the period elapses)."""
        if self._next_tick is None:
            self._next_tick = now + self.config.period_s
            return []
        if now < self._next_tick:
            return []
        self._next_tick = now + self.config.period_s
        self.counters["ticks"] += 1
        prototrace.record("autoscale", "tick")  # proto: autoscale.tick
        flap = self.fleet._fault == "flap-policy"
        need_b = 1 if flap else self.config.breach_streak
        need_c = 1 if flap else self.config.clear_streak
        fired: List[dict] = []
        sensors = self._sense(now)
        for cls, s in sensors.items():
            st = self._state(cls)
            if s["breach"]:
                st.breach_streak += 1
                st.clear_streak = 0
            elif s["clear"]:
                st.clear_streak += 1
                st.breach_streak = 0
            else:
                st.breach_streak = 0
                st.clear_streak = 0
            ready = flap or st.cooldown == 0
            ev = None
            if ready and st.breach_streak >= need_b:
                ev = self._act_breach(cls, st, s, now)
            elif ready and st.clear_streak >= need_c:
                ev = self._act_clear(cls, st, now)
            if ev is not None:
                st.cooldown = self.config.cooldown_ticks
                st.breach_streak = 0
                st.clear_streak = 0
                st.actions += 1
                ev.update({"class": cls, "at": round(now, 6),
                           "tick": self.counters["ticks"]})
                self.events.append(ev)
                fired.append(ev)
            elif st.cooldown > 0:
                st.cooldown -= 1
        return fired

    def _fire(self, action: str, cls: str, tenant: Optional[str],
              thunk) -> bool:
        """Run one actuator with the failure-forensics contract: a raise
        harvests the flight-recorder tail into ``failures`` (the
        post-mortem of a policy-actuated migration/scale failure), then
        propagates -- a policy bug must surface, never vanish."""
        try:
            ok = bool(thunk())
        except Exception as e:  # noqa: BLE001 -- harvest-and-reraise, not a swallow
            self.counters["actuation_failures"] += 1
            self.failures.append({
                "action": action, "class": cls, "tenant": tenant,
                "error": str(e),
                "flight_tail": _recorder.FLIGHT.tail(32)})
            raise
        if ok:
            self.counters[action] += 1
            if action == "shed":
                # the other model actions trace at their tenant-level
                # sites (tenants.add_replica/remove_replica/brown_*);
                # widen/narrow/promote walk the migration-handover model
                # inside pod/reshard.py, not this one
                prototrace.record("autoscale", "shed")
        return ok

    def _act_breach(self, cls: str, st: _ClassState, sensors: dict,
                    now: float) -> Optional[dict]:
        """The breach ladder: provision first, degrade second, shed
        last.  One rung per tick."""
        cfg = self.config
        dense = [t for t in self._class_tenants(cls)
                 if t.daemon is not None]
        dense.sort(key=lambda t: self.fleet.served_rows.get(
            t.spec.name, 0), reverse=True)
        # 1. replica scale-up
        for t in dense:
            name = t.spec.name
            if self.added.get(name, 0) >= cfg.max_extra_replicas:
                continue
            if self._fire("scale_up", cls, name, t.add_replica):  # proto: autoscale.scale_up
                self.added[name] = self.added.get(name, 0) + 1
                return {"action": "scale_up", "tenant": name,
                        "replicas": len(t.replica_pool)}
        # 2. pod shard widening: a boundary move toward the hot range
        for t in self._class_tenants(cls):
            if not t.is_pod or t.elastic.migration is not None:
                continue
            if self._fire("widen", cls, t.spec.name,
                          t.elastic.force_rebalance):
                return {"action": "widen", "tenant": t.spec.name}
        # 3. measured-load dense -> pod promotion
        for t in dense:
            if (t.n_points >= cfg.promote_min_points
                    and sensors["served_delta"] >= cfg.promote_load_rows):
                name = t.spec.name
                if self._fire("promote", cls, name,
                              lambda t=t: self._promote(t, now)):
                    self.added.pop(name, None)
                    return {"action": "promote", "tenant": name,
                            "n_points": t.n_points}
        # 4. brownout: step the class one rung down the ladder
        if cls in cfg.brownout_classes and st.tier < cfg.max_tier \
                and dense:
            for t in dense:
                self._fire("brown_down", cls, t.spec.name,
                           lambda t=t: t.brown_down(  # proto: autoscale.brown_down
                               recall_target=cfg.recall_target,
                               max_tier=cfg.max_tier) > 0)
            st.tier = min(st.tier + 1, cfg.max_tier)
            return {"action": "brown_down", "tier": st.tier,
                    "tier_name": TIER_NAMES[st.tier]}
        # 5. shed with a typed retry-after hint
        self.shed_until[cls] = now + cfg.shed_window_s
        self._fire("shed", cls, None, lambda: True)  # proto: autoscale.shed
        return {"action": "shed",
                "retry_after_ms": round(cfg.shed_retry_after_s * 1e3, 3)}

    def _act_clear(self, cls: str, st: _ClassState,
                   now: float) -> Optional[dict]:
        """The clear ladder: ALWAYS recover the exact tier before
        de-provisioning (the model's bounded-recovery invariant)."""
        self.shed_until.pop(cls, None)
        dense = [t for t in self._class_tenants(cls)
                 if t.daemon is not None]
        # 1. brown up toward exact
        if st.tier > 0:
            for t in dense:
                if t.degraded_tier > 0:
                    self._fire("brown_up", cls, t.spec.name,
                               lambda t=t: t.brown_up() >= 0)  # proto: autoscale.brown_up
            st.tier -= 1
            return {"action": "brown_up", "tier": st.tier,
                    "tier_name": TIER_NAMES[st.tier]}
        # 2. scale down what scale-up added (safe log compaction)
        for t in dense:
            name = t.spec.name
            if self.added.get(name, 0) <= 0:
                continue
            res: List[dict] = []
            if self._fire(
                    "scale_down", cls, name,
                    lambda t=t, res=res: res.append(  # proto: autoscale.scale_down
                        t.remove_replica(
                            unsafe_compact=self.fleet._fault
                            == "scale-drop-tail")) or res[-1] is not None):
                self.added[name] -= 1
                if self.added[name] <= 0:
                    self.added.pop(name, None)
                return {"action": "scale_down", "tenant": name, **res[-1]}
        # 3. narrowing boundary move on a still-skewed pod tenant
        for t in self._class_tenants(cls):
            if not t.is_pod or t.elastic.migration is not None:
                continue
            if self._fire("narrow", cls, t.spec.name,
                          t.elastic.force_rebalance):
                return {"action": "narrow", "tenant": t.spec.name}
        return None

    def _promote(self, t, now: float) -> bool:
        """Promotion actuator: drain the tenant's queued work first (the
        batches reference the dense daemon this promotion retires), then
        force the pod rung.  A promoted tenant re-provisions at the
        exact tier -- the pod placement serves exact scatter-gather, so
        carrying a stale brownout stamp would misreport it."""
        self.fleet._drain_tenant(t, now)
        ok = t.maybe_promote_to_pod(force=True)
        if ok:
            t.degraded_tier = 0
            t.degraded_recall = 1.0
        return ok

    # -- admission hook -------------------------------------------------------

    def shed_hint(self, t, now: float) -> Optional[float]:
        """None, or the retry-after seconds a QUERY for this tenant's
        class should be refused with right now (the ladder's floor)."""
        until = self.shed_until.get(t.spec.slo)
        if until is not None and now < until:
            return self.config.shed_retry_after_s
        return None

    # -- introspection --------------------------------------------------------

    def stats_dict(self) -> dict:
        return {
            **{k: v for k, v in self.counters.items()},
            "classes": self._provider(),
            "added": dict(self.added),
            "events": list(self.events),
            "failures": list(self.failures),
        }
