"""Replication + failover: the delta log as the fleet's durability story.

The PR 6 delta overlay made mutations O(delta) and pinned overlay answers
byte-identical to rebuild-from-scratch.  This module turns that same delta
stream into a REPLICATION LOG (DESIGN.md section 17):

* :class:`DeltaRecord` -- one committed mutation: a sequence number plus
  the validated insert points / delete ids, exactly the payload
  ``DeltaOverlay.insert``/``delete`` consumes.  Replicas apply records
  through the SAME overlay machinery as the primary, so the byte-identity
  pin (overlay == rebuild on the mutated cloud) transfers to replicas for
  free -- there is no second apply path to diverge.
* :class:`ReplicationLog` -- the authoritative ordered record of COMMITTED
  mutations.  The commit law: a mutation is committed once the primary has
  applied it AND its record is appended here; only committed mutations are
  ever acked to the client.  Failover re-ships ``since(acked)`` from this
  log, which is what makes "zero lost committed mutations" a structural
  property rather than a race.
* :class:`Replica` -- an in-process replica: its own ``DeltaOverlay`` over
  the SHARED immutable base problem (prepare is not repeated; the overlay
  is the only per-replica state), applying records strictly in sequence --
  a gap or replay raises, never silently reorders.
* :class:`ReplicaProcess` -- a replica in a CHILD PROCESS on the PR 2
  supervisor transport (the framed one-line JSON protocol,
  ``runtime.supervisor.RESULT_PREFIX``): ``python -m
  cuda_knearests_tpu.serve.fleet.replica <spec.npz>`` builds the problem
  from a banked spec and serves apply/query/seq/promote over stdio.
* :class:`FailoverController` -- primary + replicas as ReplicaProcess
  children.  Mutations commit through the primary, then ship to every
  replica (per-replica acked sequence tracked).  ``kill_primary()`` is a
  real SIGKILL; ``failover()`` promotes the most-caught-up replica after
  re-shipping its log tail.  ``expected_points()`` replays the log on the
  host (same np.delete/np.concatenate canonical indexing as the overlay),
  so callers can machine-check both halves of the failover law: the
  promoted replica's cloud equals the committed log's cloud exactly, and
  its query answers are byte-identical to a rebuild oracle on it.

Protocol table (model ``replication-commit``, analysis/models.py; the
``# proto:`` annotations below bind each action to its site and the
proto engine proves the binding complete in both directions):

========  ====================================================
action    site
========  ====================================================
apply     ``Replica.apply`` / ``FailoverController.mutate``
append    ``ReplicationLog.append`` / the ``# COMMIT`` line
ship      per-replica ``rep.apply`` fan-out after commit
failover  ``FailoverController.failover`` (re-ship + promote)
========  ====================================================

Invariants proven by exhaustive exploration (crash enabled at every
state): only committed mutations acked, zero lost committed mutations
across failover, dense sequence numbers.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...obs import metrics as _metrics
from ...obs import spans as _spans
from ...runtime.supervisor import _REPO_ROOT, RESULT_PREFIX
from ...utils import prototrace
from ...utils.memory import TransportError


@dataclasses.dataclass(frozen=True)
class DeltaRecord:
    """One committed mutation of one tenant's cloud."""

    seq: int                  # 1-based, dense: record i has seq == i + 1
    kind: str                 # 'insert' | 'delete'
    payload: np.ndarray       # (m, 3) f32 points | (m,) int ids

    def to_json(self) -> dict:
        return {"seq": self.seq, "kind": self.kind,
                "payload": np.asarray(self.payload).tolist()}

    @classmethod
    def from_json(cls, d: dict) -> "DeltaRecord":
        dtype = np.float32 if d["kind"] == "insert" else np.int64  # kntpu-ok: wide-dtype -- host id payload, validated then used on host
        return cls(seq=int(d["seq"]), kind=str(d["kind"]),
                   payload=np.asarray(d["payload"], dtype))


class ReplicationLog:
    """The ordered committed-mutation record (one per tenant).

    ``compact(upto)`` drops the prefix every surviving consumer has
    already applied (the autoscaler's scale-down path calls it with the
    remaining pool's applied floor).  ``base_seq`` records how much was
    dropped; asking for a tail that starts inside the compacted prefix
    raises LOUDLY -- a silent empty tail here is exactly the
    lost-committed-mutation corruption the replication model forbids.
    """

    def __init__(self) -> None:
        self.records: List[DeltaRecord] = []
        self.base_seq = 0

    @property
    def committed_seq(self) -> int:
        return self.base_seq + len(self.records)

    def append(self, kind: str, payload: np.ndarray) -> DeltaRecord:
        # proto: replication-commit.append
        rec = DeltaRecord(seq=self.committed_seq + 1, kind=kind,
                          payload=np.asarray(payload))
        self.records.append(rec)
        return rec

    def compact(self, upto: int) -> int:
        """Drop records with seq <= ``upto``; returns how many were
        dropped.  The caller owns the safety argument (every surviving
        consumer has applied past ``upto``) -- see Tenant.remove_replica."""
        upto = min(int(upto), self.committed_seq)
        drop = max(0, upto - self.base_seq)
        if drop:
            self.records = self.records[drop:]
            self.base_seq += drop
        return drop

    def since(self, seq: int) -> List[DeltaRecord]:
        """Records with sequence number > ``seq`` (the re-ship tail)."""
        seq = max(0, int(seq))
        if seq < self.base_seq:
            raise RuntimeError(
                f"replication log compacted past seq {seq}: records "
                f"<= {self.base_seq} were dropped, the re-ship tail is "
                f"unrecoverable (scale-down compacted a tail a live "
                f"consumer still needed)")
        return self.records[seq - self.base_seq:]


def replay_on_host(points: np.ndarray,
                   records: List[DeltaRecord]) -> np.ndarray:
    """The committed log's cloud, replayed with the overlay's canonical
    indexing (np.delete + np.concatenate) -- the zero-lost-mutations
    oracle."""
    out = np.ascontiguousarray(points, np.float32).reshape(-1, 3)
    for rec in records:
        if rec.kind == "insert":
            out = np.concatenate(
                [out, np.asarray(rec.payload, np.float32).reshape(-1, 3)])  # kntpu-ok: host-sync-loop -- DeltaRecord payloads are host numpy by construction, no device array rides this loop
        else:
            out = np.delete(out, np.asarray(rec.payload).reshape(-1), axis=0)  # kntpu-ok: host-sync-loop -- DeltaRecord payloads are host numpy by construction, no device array rides this loop
    return np.ascontiguousarray(out, dtype=np.float32)


class Replica:
    """In-process replica: one DeltaOverlay applying records in sequence."""

    def __init__(self, problem, compact_threshold: int = 512):
        from ..delta import DeltaOverlay

        self.overlay = DeltaOverlay(problem,
                                    compact_threshold=compact_threshold)
        self.applied_seq = 0

    def apply(self, record: DeltaRecord) -> int:
        """Apply one record; strict sequencing (a gap means the shipper
        lost a committed delta -- corrupting silently is the one
        unacceptable outcome)."""
        # proto: replication-commit.apply -- primary-side; as the replica
        # receive path this same method is the ship target:
        # proto: replication-commit.ship
        if record.seq != self.applied_seq + 1:
            raise RuntimeError(
                f"replication sequence gap: replica at seq "
                f"{self.applied_seq}, record carries seq {record.seq} "
                f"(committed deltas must apply densely in order)")
        if record.kind == "insert":
            self.overlay.insert(np.asarray(record.payload, np.float32))
        else:
            self.overlay.delete(np.asarray(record.payload))
        self.applied_seq = record.seq
        return self.applied_seq

    def query(self, queries: np.ndarray, k: int):
        return self.overlay.query(np.asarray(queries, np.float32), k)


# -- the child-process replica (PR 2 framed-JSON transport) -------------------

def _encode_rows(ids: np.ndarray, d2: np.ndarray) -> Tuple[list, list]:
    """Wire form of result rows: pad slots (id -1) carry d2 null -- the
    same RFC 8259 discipline as serve Response.to_wire."""
    return (np.asarray(ids).tolist(),
            [[float(v) if np.isfinite(v) else None for v in row]
             for row in np.asarray(d2)])


def _decode_d2(rows: list) -> np.ndarray:
    arr = np.asarray([[np.inf if v is None else v for v in row]
                      for row in rows], np.float32)
    return arr.reshape(len(rows), -1) if rows else arr.reshape(0, 0)


class ReplicaProcess:
    """Parent-side handle of one replica child process.

    The transport is the supervisor's framed protocol: one JSON request
    line down stdin, one ``RESULT_PREFIX``-framed JSON reply line up
    stdout (library chatter on stdout can never be mistaken for a reply).
    A dead or wedged child surfaces as :class:`TransportError` -- the
    taxonomy kind ('transport') the failover path keys on.
    """

    def __init__(self, spec_path: str, timeout_s: float = 120.0):
        self.spec_path = spec_path
        self.timeout_s = float(timeout_s)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cuda_knearests_tpu.serve.fleet.replica",
             spec_path],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env)
        self._buf = ""      # our own stdout line buffer (see _recv)
        self.acked_seq = 0
        self.promoted = False
        self.last_timing: dict = {}
        ready = self._recv()          # startup handshake
        self.n_points = int(ready.get("n_points", 0))

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def _recv(self) -> dict:
        """Read the next RESULT_PREFIX frame.  Reads raw chunks off the
        pipe fd into OUR line buffer (never the TextIOWrapper's readline:
        a frame that arrived in the same chunk as library chatter would
        sit invisibly in Python's stdio buffer while select() blocks on
        an empty OS pipe -- a false 'wedged child').  timeout_s <= 0
        waits indefinitely."""
        deadline = (None if self.timeout_s <= 0
                    else time.monotonic() + self.timeout_s)
        fd = self.proc.stdout.fileno()
        while True:
            while "\n" in self._buf:
                line, self._buf = self._buf.split("\n", 1)
                if not line.startswith(RESULT_PREFIX):
                    continue          # library chatter on stdout
                frame = json.loads(line[len(RESULT_PREFIX):])
                if not frame.get("ok", False):
                    raise TransportError(
                        f"replica pid {self.pid} error frame: "
                        f"{frame.get('error')}")
                return frame
            wait = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            ready, _, _ = select.select([fd], [], [], wait)
            if not ready:
                raise TransportError(
                    f"replica pid {self.pid}: no reply within "
                    f"{self.timeout_s:.0f}s (wedged child)")
            chunk = os.read(fd, 65536)
            if not chunk:
                raise TransportError(
                    f"replica pid {self.pid}: stdout closed "
                    f"(child exited rc {self.proc.poll()})")
            self._buf += chunk.decode("utf-8", errors="replace")

    def _call(self, req: dict) -> dict:
        try:
            self.proc.stdin.write(json.dumps(req) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise TransportError(
                f"replica pid {self.pid}: send failed ({e}) -- "
                f"child dead") from e
        return self._recv()

    def apply(self, record: DeltaRecord) -> int:
        frame = self._call({"op": "apply", **record.to_json()})
        self.acked_seq = int(frame["seq"])
        return self.acked_seq

    def query(self, queries: np.ndarray, k: int,
              trace_id=None):
        t0 = _spans.now()
        frame = self._call({"op": "query",
                            "queries": np.asarray(queries,
                                                  np.float32).tolist(),
                            "k": int(k), "trace_id": trace_id})
        e2e_ms = (_spans.now() - t0) * 1e3
        # wire-level latency decomposition: the child frames how long the
        # whole op and the device launch took; queue here is transport +
        # child stdin wait (everything outside the child's op window)
        op_ms = float(frame.get("op_ms") or 0.0)
        dev_ms = float(frame.get("device_ms") or 0.0)
        self.last_timing = {
            "total_ms": round(e2e_ms, 4),
            "queue_ms": round(max(e2e_ms - op_ms, 0.0), 4),
            "dispatch_ms": round(max(op_ms - dev_ms, 0.0), 4),
            "device_ms": round(dev_ms, 4)}
        ids = np.asarray(frame["ids"], np.int32).reshape(
            len(frame["ids"]), -1)
        return ids, _decode_d2(frame["d2"])

    def metrics(self) -> dict:
        """The child's unified obs metrics snapshot (the fleet wire's
        `metrics` command over the framed transport)."""
        return self._call({"op": "metrics"})["metrics"]

    def seq(self) -> int:
        return int(self._call({"op": "seq"})["seq"])

    def promote(self) -> None:
        self._call({"op": "promote"})
        self.promoted = True

    def kill(self) -> None:
        if self.alive:
            os.kill(self.pid, signal.SIGKILL)
        self.proc.wait()

    def close(self) -> None:
        if self.alive:
            try:
                self.proc.stdin.write(json.dumps({"op": "shutdown"}) + "\n")
                self.proc.stdin.flush()
                self.proc.wait(timeout=10)
            except (BrokenPipeError, OSError,
                    subprocess.TimeoutExpired):
                self.proc.kill()
                self.proc.wait()


def bank_replica_spec(points: np.ndarray, k: int,
                      compact_threshold: int = 512,
                      path: Optional[str] = None) -> str:
    """Write the replica-process bootstrap spec (the base cloud + config)
    to an .npz the child rebuilds its problem from."""
    if path is None:
        fd, path = tempfile.mkstemp(prefix="kntpu-replica-", suffix=".npz")
        os.close(fd)
    np.savez_compressed(path,
                        points=np.asarray(points, np.float32),
                        k=np.int32(k),
                        compact_threshold=np.int32(compact_threshold))
    return path


class FailoverController:
    """Primary + N replicas as child processes; the failover protocol.

    One controller serves one tenant's replicated stream.  Mutations
    commit through the primary (apply + ack) before the record enters the
    log and ships to replicas; queries route to the primary.  On primary
    death (detected as TransportError, or forced by :meth:`kill_primary`'s
    real SIGKILL), :meth:`failover` promotes the replica with the highest
    acked sequence after re-shipping its tail from the log -- so every
    COMMITTED mutation survives, and an in-flight uncommitted one was
    never acked to the caller (retry-after-failover is the client
    contract, exactly once-committed)."""

    def __init__(self, points: np.ndarray, k: int, n_replicas: int = 1,
                 compact_threshold: int = 512, timeout_s: float = 120.0):
        self.initial_points = np.ascontiguousarray(points, np.float32)
        self.k = int(k)
        self.log = ReplicationLog()
        self.spec_path = bank_replica_spec(points, k, compact_threshold)
        self.procs = [ReplicaProcess(self.spec_path, timeout_s=timeout_s)
                      for _ in range(1 + max(0, int(n_replicas)))]
        self.primary = self.procs[0]
        self.primary.promote()
        self.failovers = 0

    @property
    def replicas(self) -> List[ReplicaProcess]:
        return [p for p in self.procs if p is not self.primary]

    def mutate(self, kind: str, payload: np.ndarray) -> DeltaRecord:
        """One committed mutation: primary applies (ack = commit point),
        the record enters the log, then ships to every live replica."""
        rec = DeltaRecord(seq=self.log.committed_seq + 1, kind=kind,
                          payload=np.asarray(payload))
        self.primary.apply(rec)          # raises TransportError if dead
        prototrace.record("replication-commit", "apply")  # proto: replication-commit.apply
        self.log.records.append(rec)     # COMMIT  # proto: replication-commit.append
        prototrace.record("replication-commit", "append")
        for rep in self.replicas:
            if not rep.alive:
                continue
            try:
                rep.apply(rec)           # proto: replication-commit.ship
                prototrace.record("replication-commit", "ship")
            except TransportError:
                pass  # a dead replica just stops being a failover target
        return rec

    def query(self, queries: np.ndarray, k: Optional[int] = None):
        return self.primary.query(queries, self.k if k is None else k)

    def kill_primary(self) -> int:
        """A real SIGKILL -- the bench failover scenario's hammer."""
        pid = self.primary.pid
        self.primary.kill()
        return pid

    def failover(self) -> Dict[str, int]:
        """Promote the most-caught-up replica: re-ship its committed tail,
        then route to it.  Raises TransportError when no live replica
        remains (total fleet loss is not silently absorbed)."""
        live = [p for p in self.replicas if p.alive]
        if not live:
            raise TransportError(
                "failover impossible: no live replica (committed log "
                f"retains {self.log.committed_seq} mutation(s) for a "
                f"future replica)")
        # proto: replication-commit.failover
        target = max(live, key=lambda p: p.acked_seq)
        replayed = 0
        for rec in self.log.since(target.acked_seq):
            target.apply(rec)            # proto: replication-commit.ship
            prototrace.record("replication-commit", "ship")
            replayed += 1
        target.promote()
        self.primary = target
        self.failovers += 1
        prototrace.record("replication-commit", "failover")
        return {"promoted_pid": target.pid, "replayed": replayed,
                "committed_seq": self.log.committed_seq}

    def expected_points(self) -> np.ndarray:
        """The committed log's cloud (host replay) -- what the promoted
        primary must hold exactly."""
        return replay_on_host(self.initial_points, self.log.records)

    def close(self) -> None:
        for p in self.procs:
            p.close()
        try:
            os.unlink(self.spec_path)
        except OSError:
            pass


def failover_drill(n: int = 1500, k: int = 8, ops: int = 24,
                   seed: int = 0, log=None) -> dict:
    """The process-level failover proof, as one machine-checkable summary
    (shared by ``python -m cuda_knearests_tpu.serve.fleet
    --failover-smoke`` and the ``fleet_failover`` bench row).

    A primary and one replica run as real child processes; a seeded
    mutation+query stream commits through the primary; mid-stream the
    primary takes a genuine SIGKILL; the controller fails over and the
    stream finishes.  ``failover_ok`` requires (a) >= 1 failover happened,
    (b) ZERO lost committed mutations -- the promoted replica's applied
    sequence and cloud size equal the committed log's host replay exactly
    -- and (c) post-failover query results BYTE-IDENTICAL to a
    rebuild-from-scratch oracle on that cloud."""
    from ... import KnnConfig, KnnProblem
    from ...io import generate_uniform

    log = log or (lambda s: None)
    rng = np.random.default_rng(seed)
    points = generate_uniform(n, seed=seed)
    ctl = FailoverController(points, k, n_replicas=1)
    killed_at = None
    killed_pid = None
    commits_acked = 0
    # per-request latency decomposition across the wire (DESIGN.md
    # section 19): queue (transport) / dispatch (child host work) /
    # device, binned into bounded histograms, stamped on the bench row
    lat_hist = {name: _metrics.Histogram(f"failover.{name}")
                for name in ("total_ms", "queue_ms", "dispatch_ms",
                             "device_ms")}

    def _absorb_timing() -> None:
        for key, hist in lat_hist.items():
            v = ctl.primary.last_timing.get(key)
            if v is not None:
                hist.observe(v)

    try:
        for i in range(ops):
            if i == ops // 2:
                killed_pid = ctl.kill_primary()
                killed_at = i
            roll = rng.random()
            try:
                if roll < 0.5:
                    pts = (rng.random((4, 3)) * 980.0 + 10.0
                           ).astype(np.float32)
                    ctl.mutate("insert", pts)
                    commits_acked += 1
                elif roll < 0.7 and ctl.log.committed_seq:
                    n_now = ctl.expected_points().shape[0]
                    if n_now > 4:
                        ids = np.sort(rng.choice(n_now, size=2,
                                                 replace=False))
                        ctl.mutate("delete", ids.astype(np.int64))  # kntpu-ok: wide-dtype -- host id payload
                        commits_acked += 1
                else:
                    qs = (rng.random((8, 3)) * 980.0 + 10.0
                          ).astype(np.float32)
                    ctl.query(qs)
                    _absorb_timing()
            except TransportError:
                # the dead primary surfaces here; the op was never
                # committed (no ack), so failing over and moving on loses
                # nothing the client was promised
                info = ctl.failover()
                log(f"failover: {info}")
        expected = ctl.expected_points()
        state = ctl.primary._call({"op": "seq"})
        probe = (np.random.default_rng(seed + 9).random((32, 3))
                 * 980.0 + 10.0).astype(np.float32)
        got_i, got_d = ctl.query(probe)
        _absorb_timing()
        oracle = KnnProblem.prepare(expected,
                                    KnnConfig(k=k, adaptive=False))
        ref_i, ref_d = oracle.query(probe, k)
        zero_lost = (int(state["seq"]) == ctl.log.committed_seq
                     and int(state["n_points"]) == expected.shape[0])
        byte_identical = (np.array_equal(got_i, np.asarray(ref_i))
                          and np.array_equal(
                              got_d, np.asarray(ref_d, np.float32)))
        return {
            "n_points0": n, "k": k, "ops": ops, "seed": seed,
            "killed_at_op": killed_at, "killed_pid": killed_pid,
            "failovers": ctl.failovers,
            "committed_mutations": ctl.log.committed_seq,
            "commits_acked": commits_acked,
            "zero_lost_committed": bool(zero_lost),
            "post_failover_byte_identical": bool(byte_identical),
            "failover_ok": bool(zero_lost and byte_identical
                                and ctl.failovers >= 1),
            "latency_decomposition": {
                name: _metrics.percentile_fields(hist)
                for name, hist in lat_hist.items()},
        }
    finally:
        ctl.close()


# -- child entry: python -m cuda_knearests_tpu.serve.fleet.replica <spec> ----

def _child_emit(obj: dict) -> None:
    print(RESULT_PREFIX + json.dumps(obj), flush=True)


def _child_main(argv) -> int:
    """The replica worker loop (runs in the CHILD process only)."""
    from ...utils.platform import enable_compile_cache, honor_jax_platforms_env

    honor_jax_platforms_env()
    enable_compile_cache()

    from ... import KnnConfig, KnnProblem

    with np.load(argv[0]) as z:
        points = np.asarray(z["points"], np.float32)
        k = int(z["k"])
        compact_threshold = int(z["compact_threshold"])
    problem = KnnProblem.prepare(points, KnnConfig(k=k, adaptive=False))
    replica = Replica(problem, compact_threshold=compact_threshold)
    # cross-process trace stitching: tag this process so merged timelines
    # show 'replica:<pid>', and spill spans when KNTPU_TRACE_DIR is set
    _spans.set_process_tag(f"replica:{os.getpid()}")
    _spans.start_file_trace_from_env(f"replica-{os.getpid()}")
    _child_emit({"ok": True, "ready": True, "n_points": points.shape[0]})
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            op = req.get("op")
            if op == "shutdown":
                _child_emit({"ok": True, "seq": replica.applied_seq})
                return 0
            if op == "apply":
                seq = replica.apply(DeltaRecord.from_json(req))
                _child_emit({"ok": True, "seq": seq,
                             "n_points": replica.overlay.n_points})
            elif op == "query":
                with _spans.span("replica.query", force=True,
                                 trace_id=req.get("trace_id")) as op_sp:
                    with _spans.span("replica.device",
                                     force=True) as dev_sp:
                        ids, d2 = replica.query(
                            np.asarray(req["queries"], np.float32),  # kntpu-ok: host-sync-loop -- JSON-decoded wire payload (host list), no device array rides this loop
                            int(req.get("k") or k))
                    wire_ids, wire_d2 = _encode_rows(ids, d2)
                _child_emit({"ok": True, "ids": wire_ids, "d2": wire_d2,
                             "seq": replica.applied_seq,
                             "trace_id": req.get("trace_id"),
                             "op_ms": round(op_sp.dur_ms, 4),
                             "device_ms": round(dev_sp.dur_ms, 4)})
            elif op == "seq":
                _child_emit({"ok": True, "seq": replica.applied_seq,
                             "n_points": replica.overlay.n_points})
            elif op == "metrics":
                _child_emit({"ok": True,
                             "metrics": _metrics.metrics_snapshot()})
            elif op == "promote":
                _child_emit({"ok": True, "seq": replica.applied_seq})
            else:
                _child_emit({"ok": False,
                             "error": f"unknown replica op {op!r}"})
        except Exception as e:  # noqa: BLE001 -- the transport contract: any per-op failure becomes one typed error frame, the replica loop survives
            _child_emit({"ok": False,
                         "error": f"{type(e).__name__}: {e}"})
    return 0


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1:]))
