"""The fleet front door: many tenants, one wire, one scheduling law.

``FleetDaemon`` multiplexes several prepared indexes (tenants) behind one
request surface (DESIGN.md section 17).  The flow per request:

1. **Admission** -- ONE call to ``io.validate_request`` carrying the
   tenant field: unknown-tenant, over-quota (the token bucket's verdict),
   per-tenant k mismatch, and the whole points/ids contract all refuse
   TYPED here, before anything queues.  A refusal costs nothing but the
   refused request.
2. **Placement** -- sidecar tenants answer synchronously from the brute
   CPU worker; dense tenants enter their OWN dynamic batcher (PR 6
   machinery, SLO-class flush triggers) on the SHARED bucket ladder.
3. **Scheduling** -- flushed batches queue per tenant and execute in
   deficit-round-robin order (serve/fleet/admission.py), each dispatch
   stamped with its fairness accounting.  Mutations and FoF stay
   barriers WITHIN their tenant (stream order per tenant is the PR 6
   daemon's law, unchanged); they do not barrier other tenants.
4. **Replication** -- a mutation the primary applied successfully commits
   to the tenant's replication log and ships to its replicas
   (serve/fleet/tenants.py); ``failover()`` promotes a caught-up replica.

Fault injection (CPU-testable, same convention as KNTPU_SERVE_FAULT):
``KNTPU_FLEET_FAULT=cross-tenant|drop-delta|stale-replica|
torn-migration|lost-range`` seeds the fleet-specific corruptions the
fuzz campaigns must detect (fuzz/fleet.py, fuzz/chaos.py): answering one
tenant's query against another tenant's cloud, dropping a committed
delta from the replication log, promoting a stale replica without the
re-ship, tearing the last committed record out of a pod tenant's
migration handover, and flipping a migration's range cut while the
receiver applies nothing (pod/reshard.Migration.handover).

Protocol binding (analysis/models.py; the ``# proto:`` annotations on
the call sites below are proven complete by ``--engine proto``):
admission's ``try_take`` and the ``t.ready.append`` sites walk
``drr-admission.enqueue``; ``pump``'s ``drr.select`` walks
``drr-admission.rotate`` (the exhaustive exploration proves the deficit
bound and bounded starvation); the commit/ship path delegates to
tenants.py (``replication-commit``) and the live-rebalance pumping to
pod/reshard.py (``migration-handover``).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...config import DOMAIN_SIZE, ServeFleetConfig
from ...io import validate_request
from ...obs import metrics as _metrics
from ...obs import spans as _spans
from ...utils import prototrace
from ...utils.memory import (InputContractError, InvalidConfigError,
                             InvalidRequestError, OverQuotaError)
from ..batching import Batch, Request
from ..daemon import Response
from .admission import DrrScheduler, TokenBucket
from .autoscale import AutoscaleConfig, Autoscaler
from .tenants import Tenant, TenantSpec

FLEET_FAULTS = ("cross-tenant", "drop-delta", "stale-replica",
                "torn-migration", "lost-range",
                # autoscale faults (serve/fleet/autoscale.py, the runtime
                # twins of the analysis/models.py autoscale mutants):
                # frozen sensor snapshot, hysteresis/cooldown bypass,
                # unsafe log compaction on scale-down
                "stuck-sensor", "flap-policy", "scale-drop-tail")


def _parse_fleet_fault() -> Optional[str]:
    fault = os.environ.get("KNTPU_FLEET_FAULT", "")
    if not fault:
        return None
    if fault not in FLEET_FAULTS:
        raise InvalidConfigError(
            f"unknown KNTPU_FLEET_FAULT {fault!r}: expected one of "
            f"{FLEET_FAULTS}")
    return fault


def _rows_estimate(kind: str, payload) -> int:
    """Best-effort admission cost (query/mutation rows) BEFORE validation;
    malformed payloads cost one token and then refuse typed."""
    if kind == "fof":
        return 1
    try:
        return max(1, int(np.asarray(payload).shape[0]))
    except Exception:  # noqa: BLE001 -- unparseable payloads refuse typed downstream; admission just needs a nonzero cost
        return 1


class FleetDaemon:
    """Single-threaded fleet core: admit / poll / pump / drain.

    Same injected-clock design as the single-tenant daemon: the event loop
    lives in the caller (fleet loadgen, the stdio front end), so the
    scheduling and fairness laws are unit-testable with synthetic time.
    """

    def __init__(self, builds: Sequence[Tuple[TenantSpec, np.ndarray]],
                 config: Optional[ServeFleetConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 autoscale: Optional[AutoscaleConfig] = None):
        self.config = config or ServeFleetConfig()
        self.clock = clock
        self.tenants: Dict[str, Tenant] = {}
        self.quota: Dict[str, TokenBucket] = {}
        self.drr = DrrScheduler(self.config.drr_quantum)
        self.refused: Dict[str, int] = {}
        self.served_rows: Dict[str, int] = {}
        # recent-window batch accounting (bounded: the fleet is long-lived
        # by design, a per-batch list would grow without bound) plus the
        # forever counter the stats report
        self.batch_log: Deque[dict] = deque(maxlen=4096)
        self.n_batches = 0
        self._fault = _parse_fleet_fault()
        now = self.clock()
        for spec, points in builds:
            if spec.name in self.tenants:
                raise InvalidConfigError(
                    f"duplicate tenant name {spec.name!r} in the fleet "
                    f"build list")
            t = Tenant(spec, points, self.config, self.clock)
            if t.is_pod and self._fault in ("torn-migration",
                                            "lost-range"):
                t.elastic.fault = self._fault
            self.tenants[spec.name] = t
            self.quota[spec.name] = TokenBucket(
                spec.quota_qps if spec.quota_qps is not None
                else self.config.quota_qps,
                spec.quota_burst if spec.quota_burst is not None
                else self.config.quota_burst, now=now)
            self.drr.register(spec.name)
            self.refused[spec.name] = 0
            self.served_rows[spec.name] = 0
        # the sensor -> policy -> actuator loop (DESIGN.md section 24):
        # None = no autoscaling, zero behavior change for existing fleets
        self.autoscaler: Optional[Autoscaler] = (
            Autoscaler(self, autoscale) if autoscale is not None else None)

    # -- admission + routing --------------------------------------------------

    def _refusal(self, req_id, tenant, e: InputContractError,
                 now: float, trace_id: Optional[str] = None,
                 retry_after_s: Optional[float] = None) -> List[Response]:
        self.refused[tenant] = self.refused.get(tenant, 0) + 1
        if retry_after_s is None and isinstance(e, OverQuotaError):
            # a quota refusal is load-shaped, not malformed: tell the
            # caller WHEN the bucket will admit this cost again so a
            # backoff client defers instead of losing the request
            bucket = self.quota.get(tenant)
            if bucket is not None:
                retry_after_s = bucket.retry_after_s(
                    getattr(e, "rows", 1) or 1, now)
        return [Response(req_id=req_id, ok=False, error=str(e),
                         failure_kind=e.kind, arrived_at=now,
                         completed_at=self.clock(), tenant=tenant,
                         trace_id=trace_id,
                         retry_after_ms=(None if retry_after_s is None
                                         else round(retry_after_s * 1e3,
                                                    4)))]

    def submit(self, req_id: int, tenant: str, kind: str, payload,
               k: Optional[int] = None, now: Optional[float] = None,
               trace_id: Optional[str] = None) -> List[Response]:
        """Admit one tenant-addressed request.  Query responses may
        surface later (poll/pump) or now (size-trigger flush); sidecar
        tenants, mutations, and FoF answer synchronously.  Responses from
        OTHER requests whose batches this submission flushed ride along,
        exactly like the single-tenant daemon."""
        now = self.clock() if now is None else now
        t = self.tenants.get(tenant)
        quota_ok = None
        if t is not None:
            quota_ok = self.quota[tenant].try_take(   # proto: drr-admission.enqueue
                _rows_estimate(kind, payload), now)
        try:
            payload = validate_request(
                kind, payload, k=k,
                k_max=t.spec.k if t is not None else None,
                n_current=t.n_points if t is not None else None,
                max_batch=self._max_batch(t),
                domain=self._domain(t),
                tenant=tenant, tenants=tuple(self.tenants),
                quota_ok=quota_ok)
        except InputContractError as e:
            retry = None
            if isinstance(e, OverQuotaError):
                retry = self.quota[tenant].retry_after_s(
                    _rows_estimate(kind, payload), now)
            return self._refusal(req_id, tenant, e, now, trace_id,
                                 retry_after_s=retry)
        if kind == "query" and self.autoscaler is not None:
            shed = self.autoscaler.shed_hint(t, now)
            if shed is not None:
                # the brownout ladder's floor: admission refuses QUERIES
                # typed with a defer hint (mutations are never shed --
                # zero lost committed mutations stays a law)
                return self._refusal(
                    req_id, tenant,
                    OverQuotaError(
                        f"tenant {tenant!r}: query shed by the autoscale "
                        f"brownout ladder (class "
                        f"{t.spec.slo!r} at ladder floor); retry after "
                        f"{shed * 1e3:.1f} ms"),
                    now, trace_id, retry_after_s=shed)
        if kind == "query" and self._fault == "cross-tenant" \
                and len(self.tenants) > 1:
            return self._cross_tenant_fault(req_id, tenant, payload, k, now)
        if t.is_sidecar:
            return self._submit_sidecar(req_id, t, kind, payload, k, now,
                                        trace_id)
        if t.is_pod:
            return self._submit_pod(req_id, t, kind, payload, k, now,
                                    trace_id)
        return self._submit_dense(req_id, t, kind, payload, k, now,
                                  trace_id)

    def _domain(self, t: Optional[Tenant]) -> float:
        if t is None or t.is_sidecar or t.daemon is None:
            return DOMAIN_SIZE
        return float(t.daemon.overlay.base.grid.domain or DOMAIN_SIZE)

    def _max_batch(self, t: Optional[Tenant]) -> int:
        """The tenant's admittable query-batch cap.  Dense tenants refuse
        at their SLO class's ladder depth -- their batcher's bucket_for
        would raise (untyped) past it -- sidecar tenants at the
        fleet-global cap."""
        if t is None or t.is_sidecar or t.daemon is None:
            return self.config.max_batch
        return int(t.daemon.config.max_batch)

    def _cross_tenant_fault(self, req_id, tenant, payload, k,
                            now) -> List[Response]:
        """Seeded fault: answer against the NEXT tenant's cloud while
        stamping the requested tenant -- the isolation violation the fleet
        fuzz campaign must catch."""
        names = list(self.tenants)
        other = self.tenants[names[(names.index(tenant) + 1) % len(names)]]
        kq = min(int(k) if k else self.tenants[tenant].spec.k,
                 other.spec.k)
        if other.is_sidecar:
            ids, d2 = other.sidecar.query(payload, kq)
        elif other.is_pod:
            ids, d2 = other.elastic.query(payload, kq)
        else:
            ids, d2 = other.daemon.overlay.query(payload, kq)
        want_k = int(k) if k else self.tenants[tenant].spec.k
        m = payload.shape[0]
        out_i = np.full((m, want_k), -1, np.int32)
        out_d = np.full((m, want_k), np.inf, np.float32)
        kk = min(want_k, ids.shape[1])
        out_i[:, :kk] = np.asarray(ids)[:, :kk]
        out_d[:, :kk] = np.asarray(d2)[:, :kk]
        return [Response(req_id=req_id, ok=True, ids=out_i, d2=out_d,
                         arrived_at=now, completed_at=self.clock(),
                         tenant=tenant)]

    def _submit_sidecar(self, req_id, t: Tenant, kind, payload, k,
                        now, trace_id=None) -> List[Response]:
        name = t.spec.name
        if kind == "query":
            kq = int(k) if k else t.spec.k
            # sidecar answers synchronously: no batcher queue and no
            # batch formation, so queue and dispatch are zero BY
            # CONSTRUCTION and the whole wall cost is the CPU worker
            # call (the 'device' of this placement)
            with _spans.span("serve.sidecar", force=True, tenant=name,
                             trace_id=trace_id) as dev_sp:
                ids, d2 = t.sidecar.query(payload, kq)
            self.served_rows[name] += payload.shape[0]
            return [Response(req_id=req_id, ok=True, ids=ids, d2=d2,
                             arrived_at=now, completed_at=self.clock(),
                             tenant=name, trace_id=trace_id,
                             queue_ms=0.0, dispatch_ms=0.0,
                             device_ms=round(dev_sp.dur_ms, 4))]
        if kind == "fof":
            res = t.sidecar.fof(float(payload))
            return [Response(req_id=req_id, ok=True,
                             n_points=t.n_points, labels=res.labels,
                             n_clusters=res.n_clusters, arrived_at=now,
                             completed_at=self.clock(), tenant=name)]
        if kind == "insert":
            t.sidecar.insert(payload)
        else:
            t.sidecar.delete(payload)
        t.maybe_promote_from_sidecar()
        return [Response(req_id=req_id, ok=True, n_points=t.n_points,
                         arrived_at=now, completed_at=self.clock(),
                         tenant=name)]

    def _submit_pod(self, req_id, t: Tenant, kind, payload, k,
                    now, trace_id=None) -> List[Response]:
        """Pod-placement request path: synchronous like the sidecar (the
        elastic index is its own scatter-gather scheduler), with the PR 12
        device-span stamp so the latency decomposition keeps working.
        Mutations commit to the tenant's log (the mesh-durability record)
        and then give the mutation-driven rebalance trigger one look."""
        name = t.spec.name
        if kind == "query":
            kq = int(k) if k else t.spec.k
            with _spans.span("serve.pod", force=True, tenant=name,
                             trace_id=trace_id) as dev_sp:
                ids, d2 = t.elastic.query(payload, kq)
            self.served_rows[name] += payload.shape[0]
            # one migration step rides every query: resharding progresses
            # UNDER traffic, never as a stop-the-world drain
            t.elastic.pump()
            return [Response(req_id=req_id, ok=True, ids=ids, d2=d2,
                             arrived_at=now, completed_at=self.clock(),
                             tenant=name, trace_id=trace_id,
                             queue_ms=0.0, dispatch_ms=0.0,
                             device_ms=round(dev_sp.dur_ms, 4))]
        if kind == "fof":
            return self._refusal(
                req_id, name,
                InvalidRequestError(
                    f"tenant {name!r}: fof is not served from the pod "
                    f"placement (scatter-gather kNN only; run fof "
                    f"against a dense tenant)"),
                now, trace_id)
        with _spans.span("serve.pod.mutate", force=True, tenant=name,
                         kind=kind):
            if kind == "insert":
                t.elastic.insert(payload)
            else:
                t.elastic.delete(payload)
        t.commit_mutation(kind, payload,
                          drop_from_log=self._fault == "drop-delta")
        t.elastic.maybe_rebalance()
        t.elastic.pump()
        return [Response(req_id=req_id, ok=True, n_points=t.n_points,
                         arrived_at=now, completed_at=self.clock(),
                         tenant=name, trace_id=trace_id)]

    def _submit_dense(self, req_id, t: Tenant, kind, payload, k,
                      now, trace_id=None) -> List[Response]:
        name = t.spec.name
        if kind == "query":
            req = Request(req_id=req_id, queries=payload,
                          k=int(k) if k else t.spec.k, arrived_at=now,
                          trace_id=trace_id, t_perf=_spans.now())
            for batch in t.daemon.batcher.admit(req, now):
                t.ready.append(batch)                 # proto: drr-admission.enqueue
                prototrace.record("drr-admission", "enqueue")
            return self.pump(now)
        # mutation / fof barriers: THIS tenant's already-flushed batches
        # execute first (they formed first -- per-tenant stream order),
        # then its still-pending queries flush and execute through the
        # fleet's own accounting (otherwise the daemon's internal barrier
        # flush would run them outside batch_log/served_rows), then the
        # daemon's barrier machinery runs the request with its containment
        # law.  Other tenants are not barriered.
        out = self._execute_ready(t)
        pending = t.daemon.batcher.flush("barrier", now)
        if pending is not None:
            t.ready.append(pending)                   # proto: drr-admission.enqueue
            prototrace.record("drr-admission", "enqueue")
            out.extend(self._execute_ready(t))
        responses = t.daemon.submit(req_id, kind, payload, k=k, now=now,
                                    trace_id=trace_id)
        for r in responses:
            r.tenant = name
        out.extend(responses)
        if kind in ("insert", "delete") and responses \
                and responses[-1].ok:
            t.commit_mutation(kind, payload,
                              drop_from_log=self._fault == "drop-delta")
            # the barrier above drained this tenant's queues, so a dense
            # tenant that grew past pod_threshold can promote here
            t.maybe_promote_to_pod()
        return out

    # -- scheduling -----------------------------------------------------------

    def _run_batch(self, t: Tenant, batch: Batch,
                   accounting: Optional[dict] = None) -> List[Response]:
        if t.degraded_tier > 0:
            responses = self._execute_degraded(t, batch)
        else:
            responses = t.daemon._execute(batch)
        name = t.spec.name
        for r in responses:
            r.tenant = name
            if r.ok and r.ids is not None:
                self.served_rows[name] += r.ids.shape[0]
        self.batch_log.append({
            "tenant": name, "rows": batch.total,
            "capacity": batch.capacity, "reason": batch.reason,
            "slo": t.spec.slo,
            **(accounting or {})})
        self.n_batches += 1
        if self.autoscaler is not None:
            self.autoscaler.observe(t.spec.slo, responses)
        return responses

    def _execute_degraded(self, t: Tenant, batch: Batch) -> List[Response]:
        """Serve one batch at the tenant's brownout tier (DESIGN.md
        section 24): tier 1 scores in bf16 with brute refinement (ids
        still exact -- the MXU solver's refined path), tier 2 lowers the
        recall target (certified-approximate).  The mxu route does not
        ride the serving ExecutableCache, so degraded batches add ZERO
        counted recompiles and the steady-state law keeps holding
        through a brownout episode.  Mutations never reach this path
        (they are barriers through the daemon), so the overlay state --
        and with it the post-recovery byte-identity pin -- is
        tier-independent.  Same containment law as the dense executor:
        a raise costs this batch's riders typed failures, nothing more."""
        from ...mxu.solve import solve_general

        tier_name = t.degraded_tier_name
        kmax = max(r.k for r in batch.requests)
        failed: Optional[BaseException] = None
        res = None
        with _spans.span("serve.degraded", force=True,
                         tenant=t.spec.name, tier=tier_name,
                         rows=batch.total) as ex:
            try:
                res = solve_general(
                    t.daemon.overlay.mutated_points(), k=kmax,
                    recall_target=t.degraded_recall,
                    refine="brute" if t.degraded_tier == 1 else "none",
                    queries=batch.queries, scorer="mxu",
                    precision="bf16")
            except Exception as e:  # noqa: BLE001 -- containment IS the contract, same as ServeDaemon._execute
                failed = e
        done = self.clock()
        if failed is not None:
            kind = t.daemon._classify(failed)
            t.daemon.failed_batches += 1
            t.daemon.failure_kinds[kind] = \
                t.daemon.failure_kinds.get(kind, 0) + 1
            return [Response(req_id=r.req_id, ok=False,
                             error=f"degraded batch failed: "
                                   f"{type(failed).__name__}: {failed}",
                             failure_kind=kind, arrived_at=r.arrived_at,
                             completed_at=done, trace_id=r.trace_id)
                    for r in batch.requests]
        t.daemon.batches_executed += 1
        t.daemon.occupancies.append(batch.occupancy)
        out = []
        for req, a, b in batch.slices():
            out.append(Response(
                req_id=req.req_id, ok=True,
                ids=np.ascontiguousarray(res.neighbors[a:b, :req.k]),
                d2=np.ascontiguousarray(res.dists_sq[a:b, :req.k]),
                arrived_at=req.arrived_at, completed_at=done,
                trace_id=req.trace_id,
                queue_ms=t.daemon._queue_ms(req, ex.t0),
                dispatch_ms=0.0, device_ms=round(ex.dur_ms, 4),
                degraded=tier_name))
        return out

    def _drain_tenant(self, t: Tenant, now: float) -> List[Response]:
        """Drain ONE dense tenant completely (ready queue + pending
        batcher work) through the fleet's own accounting -- the
        autoscaler's promotion actuator needs the dense daemon idle
        before it swaps the placement out from under it."""
        out = self._execute_ready(t)
        if t.daemon is not None:
            batch = t.daemon.batcher.flush("drain", now)
            if batch is not None:
                t.ready.append(batch)             # proto: drr-admission.enqueue
                prototrace.record("drr-admission", "enqueue")
                out.extend(self._execute_ready(t))
        return out

    def _execute_ready(self, t: Tenant) -> List[Response]:
        """Drain ONE tenant's ready queue in FIFO order (the mutation
        barrier path -- DRR does not reorder within a tenant anyway)."""
        out: List[Response] = []
        while t.ready:
            out.extend(self._run_batch(t, t.ready.popleft(),
                                       {"barrier": True}))
        return out

    def pump(self, now: Optional[float] = None) -> List[Response]:
        """Execute every ready batch in deficit-round-robin order; each
        dispatch's fairness accounting (deficit after, backlog snapshot)
        is stamped into the per-batch stats.  The autoscaler (when
        configured) ticks here as well as in poll: a saturated open
        loop spends its passes in submit -> pump, and the policy must
        keep sensing exactly when the fleet is busiest (period-gated,
        so the extra call sites cost one comparison)."""
        if self.autoscaler is not None and now is not None:
            self.autoscaler.tick(now)
        ready = {name: t.ready for name, t in self.tenants.items()
                 if t.daemon is not None}
        if any(q for q in ready.values()):
            prototrace.record("drr-admission", "rotate")
        out: List[Response] = []
        for name, batch, disp in self.drr.select(ready):  # proto: drr-admission.rotate
            out.extend(self._run_batch(
                self.tenants[name], batch,
                {"deficit_after": disp.deficit_after,
                 "backlog": list(disp.backlog)}))
        for t in self.tenants.values():
            if t.is_pod:
                t.elastic.pump()
        return out

    def poll(self, now: Optional[float] = None) -> List[Response]:
        """Deadline-trigger check across every dense tenant, then pump.
        The autoscaler (when configured) ticks here -- the same injected
        clock that drives the batching law drives the policy."""
        now = self.clock() if now is None else now
        if self.autoscaler is not None:
            self.autoscaler.tick(now)
        for t in self.tenants.values():
            if t.daemon is None:
                continue
            batch = t.daemon.batcher.poll(now)
            if batch is not None:
                t.ready.append(batch)                 # proto: drr-admission.enqueue
                prototrace.record("drr-admission", "enqueue")
        return self.pump(now)

    def drain(self, now: Optional[float] = None) -> List[Response]:
        now = self.clock() if now is None else now
        for t in self.tenants.values():
            if t.daemon is None:
                continue
            batch = t.daemon.batcher.flush("drain", now)
            if batch is not None:
                t.ready.append(batch)                 # proto: drr-admission.enqueue
                prototrace.record("drr-admission", "enqueue")
        return self.pump(now)

    def next_deadline(self) -> Optional[float]:
        deadlines = [t.daemon.next_deadline()
                     for t in self.tenants.values()
                     if t.daemon is not None]
        deadlines = [d for d in deadlines if d is not None]
        return min(deadlines) if deadlines else None

    # -- failover -------------------------------------------------------------

    def failover(self, tenant: str) -> dict:
        """Kill the named tenant's primary overlay state and promote its
        most-caught-up replica (tenants.Tenant.failover; the seeded
        stale-replica fault skips the re-ship)."""
        return self.tenants[tenant].failover(
            skip_reship=self._fault == "stale-replica")

    # -- introspection --------------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """The fleet's ``metrics`` document: the unified obs snapshot
        plus the fleet's own scheduling/fairness/tenant counters and the
        per-tenant latency decomposition (span-sourced, DESIGN.md
        section 19)."""
        return {
            **_metrics.metrics_snapshot(),
            "fleet": self.stats_dict(),
            "latency_decomposition": {
                name: t.daemon.latency_decomposition()
                for name, t in self.tenants.items()
                if not t.is_sidecar and t.daemon is not None},
        }

    def stats_dict(self) -> dict:
        from ...runtime import dispatch as _dispatch

        return {
            "tenants": {name: {**t.stats_dict(),
                               **self.quota[name].stats_dict(),
                               "refused": self.refused[name],
                               "served_rows": self.served_rows[name]}
                        for name, t in self.tenants.items()},
            "fleet_batches": self.n_batches,
            **self.drr.stats_dict(),
            **_dispatch.EXEC_CACHE.stats_dict(),
            **({"autoscale": self.autoscaler.stats_dict()}
               if self.autoscaler is not None else {}),
        }
