"""Mesh failover for pod tenants: snapshot + log replay across meshes.

PR 10 proved ``failover_ok`` for dense tenants: a replica process takes
over after a genuine SIGKILL with zero lost committed mutations and
byte-identical answers.  This module extends that law ACROSS MESHES for
the elastic pod placement (DESIGN.md section 22):

* **Snapshot** -- one tenant's durable state is its canonical cloud (as a
  prepared problem via the existing :func:`~...api.save_problem` schema)
  plus the committed log sequence it reflects.  Snapshots publish
  atomically (tmp + ``os.replace``), carry a schema tag and a sha256 over
  every field, and loading REFUSES corrupt or stale-schema files with the
  typed :class:`~...utils.memory.CorruptInputError` -- a half-written or
  bit-flipped snapshot can never silently seed a standby.
* **MeshProcess** -- one mesh as a child process hosting a REAL
  :class:`~.frontdoor.FleetDaemon` with a single pod tenant, on the same
  framed stdio transport as :class:`~.replica.ReplicaProcess`: every
  mutation and query enters through ``fleet.submit`` (admission, commit
  law, live rebalance pumping included), so the drill exercises the
  production path, not a test double.
* **MeshController** -- primary + standby meshes and the authoritative
  parent-side :class:`~.replica.ReplicationLog`.  The commit law is PR
  10's: a mutation is committed once the primary acked it AND its record
  entered the log; only committed mutations are ever acked upstream.
  ``failover()`` SIGKILLs nothing itself -- after the primary dies (the
  drill kills it mid-migration), the standby restores the latest
  snapshot, the controller re-ships ``log.since(snapshot_seq)``, and the
  standby becomes primary holding every committed mutation.
* **mesh_oracle_query** -- the byte-identity oracle rebuilt in THIS
  process from the standby's shipped shard decomposition (fresh
  per-shard prepares + the identical deterministic uid merge of
  :meth:`~...pod.reshard.ElasticIndex.rebuild_oracle_query`), so the
  promoted mesh is checked against an answer it could not have
  fabricated.
* **mesh_failover_drill** -- the machine-checked proof: hotspot stream
  through the primary's front door, forced live rebalance, snapshot
  UNDER the in-flight migration, more committed mutations, genuine
  mid-migration SIGKILL, standby promotion, and the three-way verdict
  (``zero_lost`` + ``byte_identical`` + ``killed_mid_migration``) that
  becomes the ``mesh_failover`` column of the rebalance bench row.

Protocol table (model ``mesh-snapshot-replay``, analysis/models.py):

========  =======================================================
action    site
========  =======================================================
snapshot  ``write_snapshot`` (atomic publish) / ``snapshot_tenant``
          / ``MeshController.snapshot``
restore   ``load_snapshot`` (checksum refusal) /
          ``MeshProcess.restore`` / the failover restore
replay    ``MeshController.failover``'s ``log.since`` re-ship loop
========  =======================================================

The ``# proto:`` annotations at those sites bind them to the model; the
exhaustive exploration proves snapshot ∘ committed-tail replay
reconstructs exactly the committed state and a torn snapshot can never
seed a promoted standby (crash injected at every state).  The commit
path here additionally walks ``replication-commit.apply/append`` --
same commit law as replica.py, lifted across meshes.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import zipfile
from typing import Dict, List, Optional

import numpy as np

from ...obs import metrics as _metrics
from ...obs import spans as _spans
from ...runtime.supervisor import _REPO_ROOT, RESULT_PREFIX
from ...utils import prototrace
from ...utils.memory import CorruptInputError, TransportError
from .replica import (DeltaRecord, ReplicationLog, _decode_d2, _encode_rows,
                      replay_on_host)

SNAPSHOT_SCHEMA = "kntpu-mesh-snapshot-v1"


# -- snapshots (atomic, checksummed, typed refusal) ---------------------------

def _snapshot_digest(fields: Dict[str, np.ndarray]) -> str:
    """sha256 over a canonical serialization of every field EXCEPT the
    checksum itself: sorted names, each contributing its name, dtype,
    shape, and raw bytes -- so any flipped bit anywhere in the payload
    changes the digest."""
    h = hashlib.sha256()
    for name in sorted(fields):
        if name == "sha256":
            continue
        arr = np.asarray(fields[name])  # kntpu-ok: host-sync-loop -- snapshot envelope fields (host numpy), no device array rides this loop
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _npz_path(path: str) -> str:
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def write_snapshot(path: str, points: np.ndarray, k: int,
                   committed_seq: int, nshards: int) -> dict:
    """Publish one mesh snapshot atomically; returns {path, sha256, ...}.

    The cloud rides the EXISTING save_problem schema (grid fields +
    config json: the expensive prepare is checkpointed, not just raw
    points), extended with the mesh envelope: schema tag, the committed
    log sequence this cloud reflects, serving k, shard count, and the
    sha256 over everything.  The write goes to a same-directory temp file
    and lands via ``os.replace`` -- readers see the old snapshot or the
    new one, never a torn one."""
    # proto: mesh-snapshot-replay.snapshot
    from ... import KnnConfig, KnnProblem
    from ...api import save_problem

    path = _npz_path(path)
    pts = np.ascontiguousarray(np.asarray(points, np.float32).reshape(-1, 3))
    problem = KnnProblem.prepare(pts, KnnConfig(k=int(k), adaptive=False))
    grid_tmp = path + ".grid.tmp.npz"
    save_problem(problem, grid_tmp)
    with np.load(grid_tmp) as z:
        fields = {name: np.asarray(z[name]) for name in z.files}
    os.unlink(grid_tmp)
    fields["schema"] = np.bytes_(SNAPSHOT_SCHEMA.encode())
    fields["committed_seq"] = np.int64(committed_seq)  # kntpu-ok: wide-dtype -- on-disk snapshot schema, never staged to a device
    fields["snap_k"] = np.int64(k)  # kntpu-ok: wide-dtype -- on-disk snapshot schema, never staged to a device
    fields["nshards"] = np.int64(nshards)  # kntpu-ok: wide-dtype -- on-disk snapshot schema, never staged to a device
    digest = _snapshot_digest(fields)
    fields["sha256"] = np.bytes_(digest.encode())
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.",
        suffix=".npz", dir=os.path.dirname(path) or ".")
    os.close(fd)
    try:
        np.savez_compressed(tmp, **fields)
        os.replace(tmp, path)        # the atomic publish
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return {"path": path, "sha256": digest,
            "committed_seq": int(committed_seq),
            "n_points": int(pts.shape[0])}


def snapshot_tenant(tenant, path: str) -> dict:
    """Snapshot one fleet tenant (any placement): canonical cloud +
    committed log seq.  Works mid-migration -- the elastic index's
    ``mutated_points`` is migration-aware, so the snapshot reflects
    exactly the committed state the log sequence promises."""
    nshards = tenant.elastic.nshards if tenant.elastic is not None else 1
    info = write_snapshot(                # proto: mesh-snapshot-replay.snapshot
        path, tenant.mutated_points(), tenant.spec.k,
        tenant.log.committed_seq if tenant.log is not None else 0,
        nshards)
    prototrace.record("mesh-snapshot-replay", "snapshot")
    return info


def load_snapshot(path: str) -> dict:
    """Read + verify one snapshot; typed refusal on anything suspect.

    Refusals are :class:`CorruptInputError` (taxonomy kind 'corrupt'):
    unreadable file, missing envelope, unknown/stale schema tag, or a
    checksum mismatch.  A standby mesh NEVER promotes from a snapshot
    this function refused."""
    # proto: mesh-snapshot-replay.restore
    path = _npz_path(path)
    try:
        with np.load(path) as z:
            fields = {name: np.asarray(z[name]) for name in z.files}
    except (OSError, ValueError, KeyError, EOFError,
            zipfile.BadZipFile) as e:
        raise CorruptInputError(
            f"mesh snapshot {path!r}: unreadable ({type(e).__name__}: {e})"
        ) from e
    if "schema" not in fields or "sha256" not in fields:
        raise CorruptInputError(
            f"mesh snapshot {path!r}: missing schema/checksum envelope "
            f"(fields: {sorted(fields)})")
    schema = bytes(fields["schema"]).decode(errors="replace")
    if schema != SNAPSHOT_SCHEMA:
        raise CorruptInputError(
            f"mesh snapshot {path!r}: stale or unknown schema {schema!r} "
            f"(this build reads {SNAPSHOT_SCHEMA!r}); refusing to promote "
            f"a standby from it")
    want = bytes(fields["sha256"]).decode(errors="replace")
    got = _snapshot_digest(fields)
    if got != want:
        raise CorruptInputError(
            f"mesh snapshot {path!r}: checksum mismatch (stored "
            f"{want[:12]}.., computed {got[:12]}..) -- torn or corrupted "
            f"snapshot refused")
    # canonical-order recovery: save_problem stores Morton-sorted points
    # plus the permutation; orig[perm] = sorted
    perm = np.asarray(fields["permutation"]).astype(np.int64)  # kntpu-ok: wide-dtype -- host index arithmetic on snapshot load
    sorted_pts = np.asarray(fields["points"], np.float32)
    pts = np.empty_like(sorted_pts)
    pts[perm] = sorted_pts
    return {"points": np.ascontiguousarray(pts),
            "committed_seq": int(fields["committed_seq"]),
            "k": int(fields["snap_k"]),
            "nshards": int(fields["nshards"]),
            "sha256": want}


# -- the parent-side byte-identity oracle -------------------------------------

def mesh_oracle_query(state: dict, queries: np.ndarray, k: int):
    """Rebuild-from-scratch oracle over a mesh's shipped shard
    decomposition, computed entirely in THIS process: a fresh problem per
    shard over that shard's exact cloud, the identical deterministic uid
    merge, uid -> canonical translation from the shipped canonical order.
    Mirrors :meth:`ElasticIndex.rebuild_oracle_query` so a promoted
    standby's answers can be checked byte-for-byte without trusting any
    code in the (possibly corrupt) child."""
    from ... import KnnConfig, KnnProblem
    from ...pod.reshard import ElasticIndex

    queries = np.ascontiguousarray(queries, np.float32).reshape(-1, 3)
    m = queries.shape[0]
    uids_canonical = np.asarray(state["uids_canonical"], np.int64)  # kntpu-ok: wide-dtype -- uid ledger, host-only
    serving_k = int(state["k"])
    if m == 0 or uids_canonical.size == 0:
        return (np.full((m, k), -1, np.int32),
                np.full((m, k), np.inf, np.float32))
    per_shard = []
    for sh in state["shards"]:
        uids = np.asarray(sh["uids"], np.int64)  # kntpu-ok: wide-dtype -- uid ledger, host-only  # kntpu-ok: host-sync-loop -- snapshot state (host numpy), no device array rides this loop
        pts = np.asarray(sh["points"], np.float32).reshape(-1, 3)  # kntpu-ok: host-sync-loop -- snapshot state (host numpy), no device array rides this loop
        if uids.size == 0:
            per_shard.append((np.full((m, k), -1, np.int64),  # kntpu-ok: wide-dtype -- uid rows, host-only
                              np.full((m, k), np.inf, np.float32)))
            continue
        fresh = KnnProblem.prepare(
            pts, KnnConfig(k=serving_k, adaptive=False))
        li, ld = fresh.query(queries, k)
        li = np.asarray(li)  # kntpu-ok: host-sync-loop -- failover replay ORACLE: one bounded fetch per shard by design, never the serving route
        safe = np.clip(li, 0, max(0, uids.size - 1))
        per_shard.append((np.where(li >= 0, uids[safe], np.int64(-1)),  # kntpu-ok: wide-dtype -- uid rows, host-only
                          np.asarray(ld, np.float32)))  # kntpu-ok: host-sync-loop -- failover replay ORACLE: one bounded fetch per shard by design, never the serving route
    u_i, out_d = ElasticIndex._merge_uid_rows(per_shard, k)
    cmap = np.full((int(uids_canonical.max()) + 1,), -1, np.int32)
    cmap[uids_canonical] = np.arange(uids_canonical.size, dtype=np.int32)
    safe = np.clip(u_i, 0, cmap.size - 1)
    out_i = np.where(u_i >= 0, cmap[safe.astype(np.int64)],  # kntpu-ok: wide-dtype -- uid indexing, host-only
                     np.int32(-1)).astype(np.int32)
    return out_i, out_d


def state_cloud(state: dict) -> np.ndarray:
    """The canonical cloud reconstructed from a shipped shard
    decomposition (uid -> point over shards, read out in canonical uid
    order) -- the parent-side half of the zero-lost check."""
    pos: Dict[int, np.ndarray] = {}
    for sh in state["shards"]:
        pts = np.asarray(sh["points"], np.float32).reshape(-1, 3)  # kntpu-ok: host-sync-loop -- snapshot state (host numpy), no device array rides this loop
        for i, u in enumerate(np.asarray(sh["uids"]).tolist()):  # kntpu-ok: host-sync-loop -- snapshot state (host numpy), no device array rides this loop
            pos[int(u)] = pts[i]
    uids = np.asarray(state["uids_canonical"]).tolist()
    out = np.empty((len(uids), 3), np.float32)
    for i, u in enumerate(uids):
        out[i] = pos[int(u)]
    return np.ascontiguousarray(out)


# -- mesh bootstrap spec ------------------------------------------------------

def bank_mesh_spec(points: np.ndarray, k: int, nshards: int = 2,
                   compact_threshold: int = 512,
                   skew_threshold: float = 3.0,
                   migration_chunk: int = 64,
                   path: Optional[str] = None) -> str:
    """Write the mesh-process bootstrap spec the child rebuilds its
    single-tenant fleet from."""
    if path is None:
        fd, path = tempfile.mkstemp(prefix="kntpu-mesh-", suffix=".npz")
        os.close(fd)
    np.savez_compressed(path,
                        points=np.asarray(points, np.float32),
                        k=np.int32(k), nshards=np.int32(nshards),
                        compact_threshold=np.int32(compact_threshold),
                        skew_threshold=np.float32(skew_threshold),
                        migration_chunk=np.int32(migration_chunk))
    return path


# -- parent-side handle of one mesh child -------------------------------------

class MeshProcess:
    """One mesh (a single-pod-tenant FleetDaemon) as a child process.

    Same framed transport discipline as :class:`~.replica.ReplicaProcess`:
    one JSON request line down stdin, one ``RESULT_PREFIX``-framed reply
    up stdout, raw-fd select with our own line buffer, TransportError on
    a dead or wedged child."""

    def __init__(self, spec_path: str, timeout_s: float = 180.0):
        self.spec_path = spec_path
        self.timeout_s = float(timeout_s)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "cuda_knearests_tpu.serve.fleet.elastic", spec_path],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env)
        self._buf = ""
        self.acked_seq = 0
        self.last_timing: dict = {}
        ready = self._recv()
        self.n_points = int(ready.get("n_points", 0))

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def _recv(self) -> dict:
        import select

        deadline = (None if self.timeout_s <= 0
                    else time.monotonic() + self.timeout_s)
        fd = self.proc.stdout.fileno()
        while True:
            while "\n" in self._buf:
                line, self._buf = self._buf.split("\n", 1)
                if not line.startswith(RESULT_PREFIX):
                    continue
                frame = json.loads(line[len(RESULT_PREFIX):])
                if not frame.get("ok", False):
                    raise TransportError(
                        f"mesh pid {self.pid} error frame: "
                        f"{frame.get('error')}")
                return frame
            wait = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            ready, _, _ = select.select([fd], [], [], wait)
            if not ready:
                raise TransportError(
                    f"mesh pid {self.pid}: no reply within "
                    f"{self.timeout_s:.0f}s (wedged mesh)")
            chunk = os.read(fd, 65536)
            if not chunk:
                raise TransportError(
                    f"mesh pid {self.pid}: stdout closed "
                    f"(child exited rc {self.proc.poll()})")
            self._buf += chunk.decode("utf-8", errors="replace")

    def _call(self, req: dict) -> dict:
        try:
            self.proc.stdin.write(json.dumps(req) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise TransportError(
                f"mesh pid {self.pid}: send failed ({e}) -- "
                f"mesh dead") from e
        return self._recv()

    def mutate(self, record: DeltaRecord) -> int:
        frame = self._call({"op": "mutate", **record.to_json()})
        self.acked_seq = int(frame["seq"])
        return self.acked_seq

    def query(self, queries: np.ndarray, k: Optional[int] = None,
              trace_id=None):
        t0 = _spans.now()
        frame = self._call({"op": "query",
                            "queries": np.asarray(queries,
                                                  np.float32).tolist(),
                            "k": (None if k is None else int(k)),
                            "trace_id": trace_id})
        e2e_ms = (_spans.now() - t0) * 1e3
        op_ms = float(frame.get("op_ms") or 0.0)
        dev_ms = float(frame.get("device_ms") or 0.0)
        self.last_timing = {
            "total_ms": round(e2e_ms, 4),
            "queue_ms": round(max(e2e_ms - op_ms, 0.0), 4),
            "dispatch_ms": round(max(op_ms - dev_ms, 0.0), 4),
            "device_ms": round(dev_ms, 4)}
        ids = np.asarray(frame["ids"], np.int32).reshape(
            len(frame["ids"]), -1)
        return ids, _decode_d2(frame["d2"])

    def state(self) -> dict:
        """{seq, n_points, migration_active, migrations_done}."""
        return self._call({"op": "state"})

    def rebalance(self) -> dict:
        return self._call({"op": "rebalance"})

    def pump(self, n: int = 1) -> dict:
        return self._call({"op": "pump", "n": int(n)})

    def snapshot(self, path: str) -> dict:
        return self._call({"op": "snapshot", "path": str(path)})

    def restore(self, path: str) -> dict:
        """Promote this standby from a snapshot: the child refuses
        (typed, surfaced as a TransportError error frame) anything
        :func:`load_snapshot` refuses."""
        # proto: mesh-snapshot-replay.restore
        return self._call({"op": "restore", "path": str(path)})

    def shards(self) -> dict:
        return self._call({"op": "shards"})

    def kill(self) -> None:
        if self.alive:
            os.kill(self.pid, signal.SIGKILL)
        self.proc.wait()

    def close(self) -> None:
        if self.alive:
            try:
                self.proc.stdin.write(json.dumps({"op": "shutdown"}) + "\n")
                self.proc.stdin.flush()
                self.proc.wait(timeout=15)
            except (BrokenPipeError, OSError, subprocess.TimeoutExpired):
                self.proc.kill()
                self.proc.wait()


class MeshController:
    """Primary + standby meshes under one authoritative committed log.

    PR 10's commit law, lifted across meshes: the parent acks a mutation
    only after the primary mesh acked it AND the record entered this
    log.  The standby receives NO live stream -- durability is snapshot +
    ``log.since(snapshot_seq)`` replay, which is exactly what
    :meth:`failover` performs after the primary dies."""

    def __init__(self, points: np.ndarray, k: int, nshards: int = 2,
                 compact_threshold: int = 512, skew_threshold: float = 3.0,
                 migration_chunk: int = 16, timeout_s: float = 180.0,
                 snapshot_path: Optional[str] = None):
        self.initial_points = np.ascontiguousarray(
            np.asarray(points, np.float32).reshape(-1, 3))
        self.k = int(k)
        self.log = ReplicationLog()
        self.spec_path = bank_mesh_spec(
            self.initial_points, k, nshards, compact_threshold,
            skew_threshold, migration_chunk)
        if snapshot_path is None:
            fd, snapshot_path = tempfile.mkstemp(
                prefix="kntpu-mesh-snap-", suffix=".npz")
            os.close(fd)
        self.snapshot_path = snapshot_path
        self.primary = MeshProcess(self.spec_path, timeout_s=timeout_s)
        self.standby = MeshProcess(self.spec_path, timeout_s=timeout_s)
        self.snapshot_seq: Optional[int] = None
        self.failovers = 0

    def mutate(self, kind: str, payload: np.ndarray) -> DeltaRecord:
        rec = DeltaRecord(seq=self.log.committed_seq + 1, kind=kind,
                          payload=np.asarray(payload))
        self.primary.mutate(rec)         # raises TransportError if dead
        prototrace.record("replication-commit", "apply")  # proto: replication-commit.apply
        self.log.records.append(rec)     # COMMIT  # proto: replication-commit.append
        prototrace.record("replication-commit", "append")
        return rec

    def query(self, queries: np.ndarray, k: Optional[int] = None):
        return self.primary.query(queries, k)

    def snapshot(self) -> dict:
        info = self.primary.snapshot(self.snapshot_path)  # proto: mesh-snapshot-replay.snapshot
        self.snapshot_seq = int(info["committed_seq"])
        prototrace.record("mesh-snapshot-replay", "snapshot")
        return info

    def kill_primary(self) -> int:
        pid = self.primary.pid
        self.primary.kill()
        return pid

    def failover(self) -> dict:
        """Standby restores the last snapshot, the committed tail
        re-ships, the standby becomes primary.  Raises TransportError
        when there is no snapshot or no live standby (total mesh loss is
        not silently absorbed)."""
        if self.snapshot_seq is None:
            raise TransportError(
                "mesh failover impossible: no snapshot was ever taken "
                f"(committed log retains {self.log.committed_seq} "
                f"mutation(s) for a future mesh)")
        if not self.standby.alive:
            raise TransportError("mesh failover impossible: standby dead")
        restored = self.standby.restore(self.snapshot_path)  # proto: mesh-snapshot-replay.restore
        prototrace.record("mesh-snapshot-replay", "restore")
        base_seq = int(restored["seq"])
        replayed = 0
        for rec in self.log.since(base_seq):
            self.standby.mutate(rec)     # proto: mesh-snapshot-replay.replay
            replayed += 1
        # one replay event: the model's `replay` is the atomic tail
        # composition (restore + replay == committed), not per record
        prototrace.record("mesh-snapshot-replay", "replay")
        self.primary = self.standby
        self.standby = None
        self.failovers += 1
        return {"promoted_pid": self.primary.pid,
                "restored_seq": base_seq, "replayed": replayed,
                "committed_seq": self.log.committed_seq}

    def expected_points(self) -> np.ndarray:
        return replay_on_host(self.initial_points, self.log.records)

    def close(self) -> None:
        for p in (self.primary, self.standby):
            if p is not None:
                p.close()
        for path in (self.spec_path, self.snapshot_path):
            try:
                os.unlink(path)
            except OSError:
                pass


def mesh_failover_drill(n: int = 1200, k: int = 8, ops: int = 30,
                        seed: int = 0, nshards: int = 2,
                        migration_chunk: int = 4, log=None) -> dict:
    """The cross-mesh failover proof (the ``mesh_failover`` half of the
    ``rebalance_under_load`` bench row, and the chaos campaign's
    SIGKILL-drill case).

    One primary and one standby mesh run as real child processes.  A
    seeded hotspot stream commits through the primary's front door and
    skews the Morton ranges; a live rebalance is forced; a snapshot
    publishes UNDER the in-flight migration; more mutations commit; then
    the primary takes a genuine SIGKILL while the migration is still in
    flight.  ``mesh_failover_ok`` requires (a) the kill interrupted a
    live migration, (b) ZERO lost committed mutations -- the promoted
    standby's sequence and exact canonical cloud equal the committed
    log's host replay -- and (c) answers byte-identical to the
    parent-side per-shard rebuild oracle."""
    from ...io import generate_uniform

    log = log or (lambda s: None)
    rng = np.random.default_rng(seed)
    points = generate_uniform(n, seed=seed)
    ctl = MeshController(points, k, nshards=nshards,
                         migration_chunk=migration_chunk)
    lat_hist = {name: _metrics.Histogram(f"mesh_failover.{name}")
                for name in ("total_ms", "queue_ms", "dispatch_ms",
                             "device_ms")}

    def _absorb_timing() -> None:
        for key, hist in lat_hist.items():
            v = ctl.primary.last_timing.get(key)
            if v is not None:
                hist.observe(v)

    rebalance_at = max(2, ops // 2 - 3)
    snapshot_at = rebalance_at + 1
    kill_at = snapshot_at + 3        # a committed tail exists past the snap
    killed_mid_migration = False
    killed_pid = None
    migration_seen = False
    try:
        for i in range(ops):
            if i == rebalance_at:
                info = ctl.primary.rebalance()
                log(f"rebalance forced: {info}")
            if i == snapshot_at:
                snap = ctl.snapshot()
                log(f"snapshot: seq {snap['committed_seq']} "
                    f"sha {snap['sha256'][:12]}")
            if i == kill_at:
                st = ctl.primary.state()
                killed_mid_migration = bool(st["migration_active"])
                migration_seen = migration_seen or killed_mid_migration
                killed_pid = ctl.kill_primary()
                log(f"SIGKILL pid {killed_pid} "
                    f"(mid-migration={killed_mid_migration})")
            roll = rng.random()
            try:
                if roll < 0.55:
                    # hotspot inserts: low-Morton corner, skews shard 0
                    pts = (rng.random((12, 3)) * 110.0 + 5.0
                           ).astype(np.float32)
                    ctl.mutate("insert", pts)
                elif roll < 0.7 and ctl.log.committed_seq:
                    n_now = ctl.expected_points().shape[0]
                    if n_now > 8:
                        ids = np.sort(rng.choice(n_now, size=2,
                                                 replace=False))
                        ctl.mutate("delete", ids.astype(np.int64))  # kntpu-ok: wide-dtype -- host id payload
                else:
                    qs = (rng.random((6, 3)) * 980.0 + 10.0
                          ).astype(np.float32)
                    ctl.query(qs)
                    _absorb_timing()
            except TransportError:
                # the dead primary surfaces here; the op was never acked,
                # so promoting the standby and moving on loses nothing
                info = ctl.failover()
                log(f"mesh failover: {info}")
        expected = ctl.expected_points()
        state = ctl.primary.state()
        zero_lost_seq = int(state["seq"]) == ctl.log.committed_seq
        shards_state = ctl.primary.shards()
        cloud = state_cloud(shards_state)
        zero_lost_cloud = (cloud.shape == expected.shape
                          and np.array_equal(cloud, expected))
        probe = (np.random.default_rng(seed + 9).random((24, 3))
                 * 980.0 + 10.0).astype(np.float32)
        got_i, got_d = ctl.query(probe)
        _absorb_timing()
        ref_i, ref_d = mesh_oracle_query(shards_state, probe, k)
        byte_identical = (np.array_equal(got_i, ref_i)
                          and np.array_equal(got_d, ref_d))
        zero_lost = bool(zero_lost_seq and zero_lost_cloud)
        return {
            "n_points0": n, "k": k, "ops": ops, "seed": seed,
            "nshards": nshards,
            "killed_at_op": kill_at, "killed_pid": killed_pid,
            "killed_mid_migration": bool(killed_mid_migration),
            "mesh_failovers": ctl.failovers,
            "committed_mutations": ctl.log.committed_seq,
            "snapshot_seq": ctl.snapshot_seq,
            "replay_tail": (ctl.log.committed_seq
                            - (ctl.snapshot_seq or 0)),
            "zero_lost_committed": zero_lost,
            "post_failover_byte_identical": bool(byte_identical),
            "mesh_failover_ok": bool(zero_lost and byte_identical
                                     and killed_mid_migration
                                     and ctl.failovers >= 1),
            "latency_decomposition": {
                name: _metrics.percentile_fields(hist)
                for name, hist in lat_hist.items()},
        }
    finally:
        ctl.close()


# -- child entry: python -m cuda_knearests_tpu.serve.fleet.elastic <spec> -----

def _child_emit(obj: dict) -> None:
    print(RESULT_PREFIX + json.dumps(obj), flush=True)


class _MeshState:
    """The child's mutable world: one single-pod-tenant FleetDaemon plus
    the dense-sequence ledger (base snapshot seq + locally committed)."""

    TENANT = "mesh"

    def __init__(self, points: np.ndarray, k: int, nshards: int,
                 compact_threshold: int, skew_threshold: float,
                 migration_chunk: int):
        self.k = int(k)
        self.nshards = int(nshards)
        self.compact_threshold = int(compact_threshold)
        self.skew_threshold = float(skew_threshold)
        self.migration_chunk = int(migration_chunk)
        self.base_seq = 0
        self.req = 0
        self.fleet = None
        self._build(points)

    def _build(self, points: np.ndarray) -> None:
        from ...config import ServeFleetConfig
        from .frontdoor import FleetDaemon
        from .tenants import TenantSpec

        cfg = ServeFleetConfig(
            min_bucket=8, max_batch=64, warmup=False,
            sidecar_threshold=1, pod_threshold=2,
            pod_shards=self.nshards,
            pod_skew_threshold=self.skew_threshold,
            compact_threshold=self.compact_threshold)
        self.fleet = FleetDaemon(
            [(TenantSpec(name=self.TENANT, k=self.k), points)], cfg)
        t = self.tenant
        if t.elastic is not None:
            t.elastic.migration_chunk = self.migration_chunk

    @property
    def tenant(self):
        return self.fleet.tenants[self.TENANT]

    @property
    def applied_seq(self) -> int:
        return self.base_seq + (self.tenant.log.committed_seq
                                if self.tenant.log is not None else 0)

    def submit(self, kind: str, payload, k=None, trace_id=None):
        self.req += 1
        rs = self.fleet.submit(
            req_id=self.req, tenant=self.TENANT, kind=kind,
            payload=payload, k=k, now=time.monotonic(),  # kntpu-ok: bare-timing -- admission clock for the child's front door, not a measurement
            trace_id=trace_id)
        mine = [r for r in rs if r.req_id == self.req]
        resp = mine[-1] if mine else rs[-1]
        if not resp.ok:
            raise RuntimeError(f"front door refused {kind}: {resp.error}")
        return resp


def _child_main(argv) -> int:
    """The mesh worker loop (runs in the CHILD process only)."""
    from ...utils.platform import enable_compile_cache, honor_jax_platforms_env

    honor_jax_platforms_env()
    enable_compile_cache()

    with np.load(argv[0]) as z:
        points = np.asarray(z["points"], np.float32)
        state = _MeshState(
            points, k=int(z["k"]), nshards=int(z["nshards"]),
            compact_threshold=int(z["compact_threshold"]),
            skew_threshold=float(z["skew_threshold"]),
            migration_chunk=int(z["migration_chunk"]))
    _spans.set_process_tag(f"mesh:{os.getpid()}")
    _spans.start_file_trace_from_env(f"mesh-{os.getpid()}")
    _child_emit({"ok": True, "ready": True,
                 "n_points": int(points.shape[0])})
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            op = req.get("op")
            if op == "shutdown":
                _child_emit({"ok": True, "seq": state.applied_seq})
                return 0
            if op == "mutate":
                rec = DeltaRecord.from_json(req)
                if rec.seq != state.applied_seq + 1:
                    raise RuntimeError(
                        f"replication sequence gap: mesh at seq "
                        f"{state.applied_seq}, record carries seq "
                        f"{rec.seq} (committed deltas must apply "
                        f"densely in order)")
                resp = state.submit(rec.kind, rec.payload)
                _child_emit({"ok": True, "seq": state.applied_seq,
                             "n_points": int(resp.n_points or 0)})
            elif op == "query":
                with _spans.span("mesh.query", force=True,
                                 trace_id=req.get("trace_id")) as op_sp:
                    resp = state.submit(
                        "query",
                        np.asarray(req["queries"], np.float32),  # kntpu-ok: host-sync-loop -- JSON-decoded wire payload (host list), no device array rides this loop
                        k=req.get("k"), trace_id=req.get("trace_id"))
                    wire_ids, wire_d2 = _encode_rows(
                        np.asarray(resp.ids), np.asarray(resp.d2))  # kntpu-ok: host-sync-loop -- wire encode of an already-fetched Response (host numpy)
                _child_emit({"ok": True, "ids": wire_ids, "d2": wire_d2,
                             "seq": state.applied_seq,
                             "trace_id": req.get("trace_id"),
                             "op_ms": round(op_sp.dur_ms, 4),
                             "device_ms": float(
                                 getattr(resp, "device_ms", 0.0) or 0.0)})
            elif op == "state":
                el = state.tenant.elastic
                _child_emit({
                    "ok": True, "seq": state.applied_seq,
                    "n_points": int(state.tenant.n_points),
                    "migration_active": bool(
                        el is not None and el.migration is not None),
                    "migrations_done": int(
                        el.migrations_done if el is not None else 0)})
            elif op == "rebalance":
                el = state.tenant.elastic
                planned = bool(el is not None and el.force_rebalance())
                _child_emit({"ok": True, "planned": planned,
                             "migration_active": bool(
                                 el is not None
                                 and el.migration is not None)})
            elif op == "pump":
                el = state.tenant.elastic
                for _ in range(max(1, int(req.get("n") or 1))):
                    if el is None or el.migration is None:
                        break
                    el.pump()
                _child_emit({"ok": True, "migration_active": bool(
                    el is not None and el.migration is not None)})
            elif op == "snapshot":
                info = snapshot_tenant(state.tenant, req["path"])
                info["committed_seq"] = state.applied_seq
                _child_emit({"ok": True, **info})
            elif op == "restore":
                snap = load_snapshot(req["path"])   # typed refusal here
                state.base_seq = snap["committed_seq"]
                state._build(snap["points"])
                _child_emit({"ok": True, "seq": state.applied_seq,
                             "n_points": int(snap["points"].shape[0]),
                             "sha256": snap["sha256"]})
            elif op == "shards":
                el = state.tenant.elastic
                if el is None:
                    raise RuntimeError("mesh tenant is not on the pod "
                                       "placement; no shard state")
                _child_emit({
                    "ok": True,
                    "k": el.k,
                    "uids_canonical": el.uids_canonical.tolist(),
                    "shards": [{"uids": s.uids.tolist(),
                                "points": s.points().tolist()}
                               for s in el.shards]})
            else:
                _child_emit({"ok": False,
                             "error": f"unknown mesh op {op!r}"})
        except Exception as e:  # noqa: BLE001 -- the transport contract: any per-op failure becomes one typed error frame, the mesh loop survives
            _child_emit({"ok": False,
                         "error": f"{type(e).__name__}: {e}"})
    return 0


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1:]))
