"""Tenant model: one prepared index + serving state per named tenant.

A TENANT is the fleet's unit of isolation and accounting (DESIGN.md
section 17): its own point cloud, its own serving k, its own SLO class,
quota, and replication factor -- multiplexed with every other tenant onto
ONE process (shared capacity-bucket ladder, shared ExecutableCache,
shared DRR scheduler).  Two placements:

* **Dense** (default): a prepared ``KnnProblem`` behind the PR 6
  ``ServeDaemon`` (mutation overlay + dynamic batcher + containment),
  whose ServeConfig is derived from the tenant's SLO class on the fleet's
  shared ladder (config.ServeFleetConfig.serve_config_for).  Because the
  executable-cache key is a pure shape census (problem signature x bucket
  x k), tenants with equal signatures share compiled launches: the second
  such tenant's warmup takes ZERO new compiles (tests/test_fleet.py).
* **Sidecar**: clouds under ``sidecar_threshold`` (or degenerate, n < k)
  serve from the brute CPU worker (serve/fleet/sidecar.py) -- no
  executables, no batching, synchronous answers.  A sidecar tenant whose
  cloud GROWS past the threshold promotes to a dense placement at the
  mutation that crossed it (one prepare, the same cloud).
* **Pod** (``ServeFleetConfig.pod_threshold``): clouds at or above the
  threshold serve from an elastic pod-partitioned index
  (pod/reshard.ElasticIndex, DESIGN.md section 22): Morton-range shards,
  scatter-gather queries, live boundary migration when the mutation
  stream skews the range populations.  Same canonical-id mutation
  contract as the dense overlay, so the front door's admission and
  commit paths are shared; the committed log (always present for pod
  tenants) is the mesh-failover durability story
  (serve/fleet/elastic.py).  A dense tenant that grows past the
  threshold promotes at the mutation that crossed it.

Replication (dense tenants with ``replicas > 0``): committed mutations
append to the tenant's :class:`~.replica.ReplicationLog` and ship to
in-process :class:`~.replica.Replica` overlays over the SAME base problem.
``ship_mode='sync'`` applies each record as it commits;
``'lazy'`` defers everything to failover's re-ship -- both end at the same
byte-identical state, and the fuzz campaign drives both.  ``failover()``
promotes the most-caught-up replica (re-shipping its committed tail) into
the primary slot; the daemon's FoF memo is invalidated because the
overlay identity changed.

Protocol binding (model ``replication-commit``, analysis/models.py --
the in-process twin of replica.py's process-level table): ``apply`` is
the caller's successful primary mutation, ``append`` =
``commit_mutation``'s log append (the commit point), ``ship`` = the
sync-mode replica fan-out and failover's re-ship, ``failover`` =
:meth:`Tenant.failover`.  The exhaustive exploration proves ack-only-
after-commit and zero-lost-committed-mutations over every interleaving
the chaos campaign samples.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, List, Optional

import numpy as np

from ...api import KnnProblem
from ...config import (SLO_CLASSES, KnnConfig, ServeFleetConfig, SloClass)
from ...pod.reshard import ElasticIndex
from ...utils import prototrace
from ...utils.memory import InvalidConfigError, TransportError
from ..daemon import ServeDaemon
from .replica import Replica, ReplicationLog
from .sidecar import CpuSidecar


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """Regenerable identity of one tenant (the fuzz/bench spec unit).

    Attributes:
      name: wire name (the request 'tenant' field).
      k: the tenant's serving k (per-request k <= k truncates columns).
      slo: SLO class name (config.SLO_CLASSES): 'latency' | 'throughput'.
      quota_qps / quota_burst: token-bucket admission overrides (None ->
        the fleet defaults; quota_qps None there = unmetered).
      replicas: in-process replica count (0 = unreplicated).
      ship_mode: 'sync' ships each committed record immediately; 'lazy'
        defers to failover's re-ship (both converge; fuzz drives both).
    """

    name: str
    k: int = 10
    slo: str = "throughput"
    quota_qps: Optional[float] = None
    quota_burst: Optional[float] = None
    replicas: int = 0
    ship_mode: str = "sync"

    def __post_init__(self):
        if self.slo not in SLO_CLASSES:
            raise InvalidConfigError(
                f"tenant {self.name!r}: unknown SLO class {self.slo!r} "
                f"(expected one of {tuple(SLO_CLASSES)})")
        if self.ship_mode not in ("sync", "lazy"):
            raise InvalidConfigError(
                f"tenant {self.name!r}: unknown ship_mode "
                f"{self.ship_mode!r} (expected 'sync' or 'lazy')")
        if self.k < 1:
            raise InvalidConfigError(
                f"tenant {self.name!r}: serving k must be >= 1, "
                f"got {self.k}")

    @property
    def slo_class(self) -> SloClass:
        return SLO_CLASSES[self.slo]

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "TenantSpec":
        return cls(**d)


class Tenant:
    """One tenant's runtime state inside the fleet front door."""

    def __init__(self, spec: TenantSpec, points: np.ndarray,
                 fleet: ServeFleetConfig, clock):
        self.spec = spec
        self.fleet = fleet
        self.clock = clock
        self.ready: "Deque" = deque()    # flushed batches awaiting DRR
        self.daemon: Optional[ServeDaemon] = None
        self.sidecar: Optional[CpuSidecar] = None
        self.elastic: Optional[ElasticIndex] = None
        self.log: Optional[ReplicationLog] = None
        self.replica_pool: List[Replica] = []
        self.promotions = 0
        self.failovers = 0
        # brownout ladder position (serve/fleet/autoscale.py): 0 exact
        # f32, 1 bf16 scoring (refined => still byte-sound ids), 2 bf16 +
        # lowered recall_target (certified-approximate).  Queries answered
        # above tier 0 carry the tier name on the wire ('degraded').
        self.degraded_tier = 0
        self.degraded_recall = 1.0
        points = np.ascontiguousarray(points, np.float32).reshape(-1, 3)
        if self._wants_sidecar(points.shape[0]):
            self.sidecar = CpuSidecar(points, spec.k)
        elif self._wants_pod(points.shape[0]):
            self._build_elastic(points)
        else:
            self._build_dense(points)

    # -- placement ------------------------------------------------------------

    def _wants_sidecar(self, n: int) -> bool:
        return n < self.fleet.sidecar_threshold or n < self.spec.k

    def _wants_pod(self, n: int) -> bool:
        return (self.fleet.pod_threshold is not None
                and n >= self.fleet.pod_threshold)

    def _build_dense(self, points: np.ndarray) -> None:
        problem = KnnProblem.prepare(
            points, KnnConfig(k=self.spec.k, adaptive=False))
        self.daemon = ServeDaemon(
            problem, self.fleet.serve_config_for(self.spec.slo_class),
            clock=self.clock)
        if self.spec.replicas > 0:
            self.log = ReplicationLog()
            self.replica_pool = [
                Replica(problem,
                        compact_threshold=self.fleet.compact_threshold)
                for _ in range(self.spec.replicas)]

    def _build_elastic(self, points: np.ndarray) -> None:
        """The pod rung: Morton-range shards behind the shared front
        door.  Pod tenants ALWAYS keep a replication log -- the committed
        seq is what a mesh snapshot stamps and what a standby mesh
        replays past it (serve/fleet/elastic.py).  The shard builds'
        exec-cache misses are attributed to the new index's
        ``elastic_recompiles``: a mid-session promotion (the
        autoscaler's measured-load actuator) is index work, not a
        serving-path recompile, so the steady-state carve-out must
        cover it."""
        from ...runtime import dispatch as _dispatch

        m0 = _dispatch.EXEC_CACHE.misses
        self.elastic = ElasticIndex(
            points, k=self.spec.k, nshards=self.fleet.pod_shards,
            compact_threshold=self.fleet.compact_threshold,
            skew_threshold=self.fleet.pod_skew_threshold)
        self.elastic.elastic_recompiles += \
            _dispatch.EXEC_CACHE.misses - m0
        self.log = ReplicationLog()

    def maybe_promote_from_sidecar(self) -> bool:
        """Promote a grown sidecar tenant to a dense placement (one
        prepare of the same cloud; canonical ids are preserved because
        both placements use the identical np.delete/concatenate
        indexing).  Returns True when a promotion happened."""
        if self.sidecar is None or self._wants_sidecar(
                self.sidecar.n_points):
            return False
        points = self.sidecar.mutated_points()
        self.sidecar = None
        if self._wants_pod(points.shape[0]):
            self._build_elastic(points)
        else:
            self._build_dense(points)
        self.promotions += 1
        return True

    def maybe_promote_to_pod(self, *, force: bool = False) -> bool:
        """Promote a dense tenant whose cloud grew past ``pod_threshold``
        to the elastic placement (same canonical cloud, same canonical
        ids -- both placements use np.delete/concatenate indexing).
        The replication log carries over: committed seq is placement-
        independent.  ``force=True`` is the autoscaler's measured-load
        trigger (ISSUE 19): promotion driven by sustained served rows,
        not just the static size threshold -- the caller owns draining
        this tenant's queued batches first."""
        if self.daemon is None or (not force
                                   and not self._wants_pod(self.n_points)):
            return False
        points = self.daemon.overlay.mutated_points()
        log = self.log
        self.daemon = None
        self.replica_pool = []
        self._build_elastic(points)
        if log is not None:
            self.log = log
        self.promotions += 1
        return True

    # -- state ----------------------------------------------------------------

    @property
    def is_sidecar(self) -> bool:
        return self.sidecar is not None

    @property
    def is_pod(self) -> bool:
        return self.elastic is not None

    @property
    def n_points(self) -> int:
        if self.sidecar is not None:
            return self.sidecar.n_points
        if self.elastic is not None:
            return self.elastic.n_points
        return self.daemon.overlay.n_points

    def mutated_points(self) -> np.ndarray:
        """The tenant's CURRENT cloud in canonical order (the per-tenant
        rebuild oracle's input)."""
        if self.sidecar is not None:
            return self.sidecar.mutated_points()
        if self.elastic is not None:
            return self.elastic.mutated_points()
        return self.daemon.overlay.mutated_points()

    # -- replication ----------------------------------------------------------

    def commit_mutation(self, kind: str, payload, *,
                        drop_from_log: bool = False) -> None:
        """Record one mutation the primary ALREADY applied successfully.
        The record enters the log (the commit), then ships to replicas
        under ship_mode='sync'.  ``drop_from_log`` is the seeded
        drop-delta fault's hook (fuzz/fleet.py): a committed delta that
        never reaches the log is exactly the corruption the campaign must
        detect."""
        if self.log is None:
            return
        if drop_from_log:
            return
        prototrace.record("replication-commit", "apply")  # the caller's successful primary apply
        rec = self.log.append(kind, np.asarray(payload))  # proto: replication-commit.append
        prototrace.record("replication-commit", "append")
        if self.spec.ship_mode == "sync":
            for rep in self.replica_pool:
                rep.apply(rec)                            # proto: replication-commit.ship
                prototrace.record("replication-commit", "ship")

    # -- elastic replication + brownout (serve/fleet/autoscale.py) ------------

    def add_replica(self) -> bool:
        """Provision ONE more in-process replica (the autoscaler's
        scale-up actuator).  The newcomer bootstraps from a snapshot of
        the CURRENT cloud and is stamped caught-up at today's committed
        seq -- unconditionally correct even when the tenant never logged
        (replicas=0 history) or the primary overlay compacted its base.
        From then on it rides the existing replication machinery: the
        committed tail ships per record under ship_mode='sync', or
        lazily at failover's re-ship.  The snapshot prepare shares
        compiled launches through the executable cache's shape census,
        so a same-signature scale-up costs zero new compiles."""
        # proto: autoscale.scale_up
        if self.daemon is None:
            return False
        if self.log is None:
            self.log = ReplicationLog()
        problem = KnnProblem.prepare(
            self.daemon.overlay.mutated_points(),
            KnnConfig(k=self.spec.k, adaptive=False))
        rep = Replica(problem,
                      compact_threshold=self.fleet.compact_threshold)
        rep.applied_seq = self.log.committed_seq
        self.replica_pool.append(rep)
        prototrace.record("autoscale", "scale_up")
        return True

    def remove_replica(self, *, unsafe_compact: bool = False
                       ) -> Optional[dict]:
        """De-provision ONE replica (the autoscaler's scale-down
        actuator).  Refuses -- returns None -- at or below the spec's
        provisioned baseline: the policy only removes what it added.
        The victim is the LEAST caught-up replica, so no unique progress
        is dropped, and the log then compacts ONLY to the remaining
        pool's applied floor (the model's no-drop-tail invariant: a
        compaction past a survivor's applied seq would make the next
        failover's re-ship tail unrecoverable).  ``unsafe_compact`` is
        the seeded scale-drop-tail fault's hook: compact to the
        committed head regardless -- the corruption check.sh must prove
        detectable."""
        # proto: autoscale.scale_down
        if self.daemon is None \
                or len(self.replica_pool) <= self.spec.replicas:
            return None
        target = min(self.replica_pool, key=lambda r: r.applied_seq)
        self.replica_pool.remove(target)
        floor = min((r.applied_seq for r in self.replica_pool),
                    default=0)
        dropped = 0
        if self.log is not None:
            dropped = self.log.compact(
                self.log.committed_seq if unsafe_compact else floor)
        prototrace.record("autoscale", "scale_down")
        return {"tenant": self.spec.name,
                "victim_seq": target.applied_seq,
                "compacted": dropped,
                "remaining_replicas": len(self.replica_pool)}

    @property
    def degraded_tier_name(self) -> Optional[str]:
        """Wire name of the current brownout rung (None at exact)."""
        if self.degraded_tier <= 0:
            return None
        return "bf16" if self.degraded_tier == 1 else "recall"

    def brown_down(self, *, recall_target: float = 0.9,
                   max_tier: int = 2) -> int:
        """Step one rung DOWN the declared ladder: exact f32 -> bf16
        scoring (brute-refined, ids still exact) -> bf16 + lowered
        recall_target (certified-approximate).  Monotone within the
        episode by construction: this method only ever steps down."""
        # proto: autoscale.brown_down
        if self.degraded_tier < max_tier:
            self.degraded_tier += 1
            self.degraded_recall = (1.0 if self.degraded_tier == 1
                                    else float(recall_target))
            prototrace.record("autoscale", "brown_down")
        return self.degraded_tier

    def brown_up(self) -> int:
        """Step one rung back UP; at tier 0 the tenant serves exactly as
        one that was never degraded (the byte-identity pin in
        tests/test_autoscale.py)."""
        # proto: autoscale.brown_up
        if self.degraded_tier > 0:
            self.degraded_tier -= 1
            self.degraded_recall = (1.0 if self.degraded_tier <= 1
                                    else self.degraded_recall)
            prototrace.record("autoscale", "brown_up")
        return self.degraded_tier

    def failover(self, *, skip_reship: bool = False) -> dict:
        """Kill the primary overlay and promote the most-caught-up
        replica: re-ship its committed tail from the log, swap its overlay
        into the daemon, invalidate the FoF memo (the overlay identity
        changed).  ``skip_reship`` is the seeded stale-replica fault's
        hook.  Raises TransportError when the tenant has no replica to
        promote."""
        if self.daemon is None or not self.replica_pool:
            raise TransportError(
                f"tenant {self.spec.name!r}: failover impossible "
                f"(replicas={len(self.replica_pool)})")
        # proto: replication-commit.failover
        target = max(self.replica_pool, key=lambda r: r.applied_seq)
        replayed = 0
        if not skip_reship:
            for rec in self.log.since(target.applied_seq):
                target.apply(rec)           # proto: replication-commit.ship
                prototrace.record("replication-commit", "ship")
                replayed += 1
        self.replica_pool.remove(target)
        self.daemon.overlay = target.overlay
        self.daemon.invalidate_fof_memo()   # memo keyed on the old overlay
        self.failovers += 1
        prototrace.record("replication-commit", "failover")
        return {"tenant": self.spec.name, "replayed": replayed,
                "committed_seq": self.log.committed_seq,
                "remaining_replicas": len(self.replica_pool)}

    # -- introspection --------------------------------------------------------

    def stats_dict(self) -> dict:
        base = {"slo": self.spec.slo, "k": self.spec.k,
                "n_points": self.n_points,
                "replicas": len(self.replica_pool),
                "committed_seq": (self.log.committed_seq
                                  if self.log is not None else 0),
                "failovers": self.failovers,
                "promotions": self.promotions,
                "degraded_tier": self.degraded_tier}
        if self.sidecar is not None:
            base.update(self.sidecar.stats_dict())
        elif self.elastic is not None:
            base["sidecar"] = False
            base["pod"] = True
            base.update(self.elastic.stats_dict())
        else:
            base["sidecar"] = False
            base["batches"] = self.daemon.batches_executed
            base["failed_batches"] = self.daemon.failed_batches
            base["occupancies"] = len(self.daemon.occupancies)
        return base
