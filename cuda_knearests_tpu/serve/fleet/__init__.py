"""Serving fleet: multi-tenant, multi-index, replicated serving tier.

One daemon owning one prepared cloud (serve/) is a demo; the north star --
heavy traffic from millions of users -- means many indexes behind one
front door (ROADMAP item 3).  This package is that tier:

* :mod:`tenants` -- the tenant model: per-tenant prepared problem, SLO
  class, quota, replication factor; dense tenants on the shared bucket
  ladder, tiny/degenerate tenants on the CPU sidecar.
* :mod:`admission` -- token-bucket admission (typed over-quota refusals)
  and deficit-round-robin scheduling with per-dispatch fairness stamps.
* :mod:`replica` -- the replication log (PR 6 delta payloads + sequence
  numbers), in-process and child-process replicas, and the
  SIGKILL-tolerant failover controller.
* :mod:`sidecar` -- the brute CPU worker absorbing tiny tenants
  ("Hybrid KNN-Join", arXiv 1810.04758).
* :mod:`frontdoor` -- the FleetDaemon multiplexing all of it behind one
  wire surface.
* :mod:`autoscale` -- the traffic-driven sensor -> policy -> actuator
  loop and the brownout ladder (exact -> bf16 -> lowered recall) with
  hysteresis + cooldown (DESIGN.md section 24).
* :mod:`loadgen` -- the multi-tenant open-loop harness (per-tenant
  percentiles, Jain fairness, SLO verdicts) behind ``bench.py --serve``'s
  fleet rows.

``python -m cuda_knearests_tpu.serve.fleet --loadgen`` runs a mixed-SLO
synthetic fleet session; ``--failover-smoke`` runs the process-level
SIGKILL failover proof.  DESIGN.md section 17 has the tenant model, the
admission/fairness law, the replication-log sequencing, and the failover
protocol.
"""

from __future__ import annotations

from ...config import SLO_CLASSES, ServeFleetConfig, SloClass
from .admission import DrrScheduler, TokenBucket, jain_index
from .autoscale import TIER_NAMES, AutoscaleConfig, Autoscaler
from .frontdoor import FLEET_FAULTS, FleetDaemon
from .loadgen import (TenantLoad, build_fleet_schedule,
                      default_fleet_builds, run_fleet_session)
from .replica import (DeltaRecord, FailoverController, Replica,
                      ReplicaProcess, ReplicationLog, failover_drill,
                      replay_on_host)
from .sidecar import CpuSidecar
from .tenants import Tenant, TenantSpec

__all__ = ["SLO_CLASSES", "ServeFleetConfig", "SloClass", "DrrScheduler",
           "TokenBucket", "jain_index", "TIER_NAMES", "AutoscaleConfig",
           "Autoscaler", "FLEET_FAULTS", "FleetDaemon",
           "TenantLoad", "build_fleet_schedule", "default_fleet_builds",
           "run_fleet_session", "DeltaRecord", "FailoverController",
           "Replica", "ReplicaProcess", "ReplicationLog", "failover_drill",
           "replay_on_host", "CpuSidecar", "Tenant", "TenantSpec"]
