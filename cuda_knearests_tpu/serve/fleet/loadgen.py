"""Multi-tenant open-loop load harness: the fleet's measurement surface.

Same open-loop discipline as serve/loadgen.py (arrivals pre-scheduled by
seeded Poisson processes, never gated on completions), extended across
tenants: each tenant contributes its own arrival process and batch mix,
the merged schedule drives the ONE front door, and the summary reports
per-tenant latency percentiles, sustained QPS, refusals, the Jain
fairness index over per-tenant completion ratios, SLO verdicts
(p99 <= the tenant's class budget), and the fleet-wide recompile count --
the numbers that become ``bench.py --serve`` fleet rows.
"""

from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...config import DOMAIN_SIZE, SLO_CLASSES
from ...runtime import dispatch as _dispatch
from ..daemon import Response
from ..loadgen import SessionAggregate, _percentiles
from .admission import jain_index
from .frontdoor import FleetDaemon
from .tenants import TenantSpec


@dataclasses.dataclass(frozen=True)
class TenantLoad:
    """One tenant's offered load (regenerable from the seed).

    ``hotspot`` concentrates this tenant's scheduled INSERTS into the
    axis-aligned sub-cube ``[lo*domain, hi*domain]^3`` -- a contiguous
    low-Morton range when ``lo`` is near 0 -- so a pod tenant's
    population skews deterministically and the live-rebalance trigger
    (pod/reshard.ElasticIndex.maybe_rebalance) fires reproducibly in
    tier-1 and bench.  Queries and deletes are unaffected.

    ``diurnal`` (ISSUE 19 satellite) sine-modulates the Poisson
    intensity: it is the peak/trough ratio of ``rate(t) = rate * (1 + a
    sin(2 pi t / P))`` with ``a = (diurnal - 1) / (diurnal + 1)`` and
    period ``P = diurnal_period_s`` (default: the load's nominal
    duration, one full cycle).  Arrivals come from inverting the
    cumulative intensity on a unit-rate seeded Poisson stream, so the
    pattern is exactly regenerable and the MEAN rate stays ``rate``.

    ``backoff`` opts this tenant's client into honoring typed
    ``retry_after_ms`` hints: a refused request that carries one is
    RE-OFFERED after the hinted delay (up to ``max_retries`` times)
    instead of being lost -- shed load becomes measurable as
    ``deferred_requests`` in the session summary."""

    tenant: str
    rate: float = 200.0
    requests: int = 100
    batch_mix: Tuple[Tuple[int, float], ...] = (
        (1, 0.45), (4, 0.25), (16, 0.2), (64, 0.1))
    mutation_ratio: float = 0.0
    mutation_size: int = 8
    k: Optional[int] = None
    seed: int = 0
    hotspot: Optional[Tuple[float, float]] = None
    diurnal: Optional[float] = None
    diurnal_period_s: Optional[float] = None
    backoff: bool = False
    max_retries: int = 3

    def arrivals(self) -> np.ndarray:
        """This load's seeded arrival times (flat or diurnal).  The flat
        path is bit-identical to the pre-diurnal harness (same rng, same
        expression), so every existing pinned schedule is unchanged."""
        rate = max(self.rate, 1e-9)
        if self.diurnal is None or self.diurnal <= 1.0:
            return np.cumsum(np.random.default_rng(self.seed).exponential(
                1.0 / rate, self.requests))
        unit = np.cumsum(np.random.default_rng(self.seed).exponential(
            1.0, self.requests))
        a = (self.diurnal - 1.0) / (self.diurnal + 1.0)
        period = (self.diurnal_period_s if self.diurnal_period_s
                  else self.requests / rate)
        return _invert_diurnal(unit, rate, a, period)


def _invert_diurnal(unit: np.ndarray, rate: float, a: float,
                    period: float) -> np.ndarray:
    """Arrival times of an inhomogeneous Poisson process by numeric
    inversion of the cumulative intensity ``L(t) = rate * (t + a P /
    (2 pi) * (1 - cos(2 pi t / P)))`` (monotone: |a| < 1) applied to a
    unit-rate stream -- bisection, fully vectorized, deterministic."""
    u = np.asarray(unit, np.float64)  # kntpu-ok: wide-dtype -- host-side schedule synthesis, never staged
    lo = np.zeros_like(u)
    hi = np.full_like(u, float(u[-1]) / (rate * (1.0 - a)) + period)
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        val = rate * (mid + a * period / (2 * np.pi)
                      * (1.0 - np.cos(2 * np.pi * mid / period)))
        take = val < u
        lo = np.where(take, mid, lo)
        hi = np.where(take, hi, mid)
    return hi


def build_fleet_schedule(loads: List[TenantLoad],
                         n_current: Dict[str, int],
                         domain: float = DOMAIN_SIZE) -> List[dict]:
    """The merged arrival-ordered schedule: [{t, tenant, kind, payload,
    k}].  Per-tenant delete ids track that tenant's running cloud size, so
    every scheduled mutation is legal at its arrival time (hostile streams
    are the fuzz campaign's job)."""
    out: List[dict] = []
    for load in loads:
        rng = np.random.default_rng(load.seed + 1)
        arrivals = load.arrivals()
        sizes = np.asarray([s for s, _ in load.batch_mix])
        weights = np.asarray([w for _, w in load.batch_mix], np.float64)  # kntpu-ok: wide-dtype -- host-side sampling weights, never staged
        weights = weights / weights.sum()
        n = int(n_current[load.tenant])
        for t in arrivals:
            if load.mutation_ratio > 0 \
                    and rng.random() < load.mutation_ratio:
                if rng.random() < 0.5 or n <= load.mutation_size:
                    if load.hotspot is not None:
                        lo, hi = load.hotspot
                        span = max(hi - lo, 1e-6) * domain
                        pts = (rng.random((load.mutation_size, 3)) * span
                               + lo * domain).astype(np.float32)
                    else:
                        pts = (rng.random((load.mutation_size, 3))
                               * (domain * 0.98)
                               + domain * 0.01).astype(np.float32)
                    out.append({"t": float(t), "tenant": load.tenant,
                                "kind": "insert", "payload": pts})
                    n += load.mutation_size
                else:
                    ids = rng.choice(n, size=load.mutation_size,
                                     replace=False)
                    out.append({"t": float(t), "tenant": load.tenant,
                                "kind": "delete",
                                "payload": np.sort(ids).astype(np.int64)})  # kntpu-ok: wide-dtype -- host id payload, validated then used on host
                    n -= load.mutation_size
            else:
                m = int(rng.choice(sizes, p=weights))
                qs = (rng.random((m, 3)) * (domain * 0.98)
                      + domain * 0.01).astype(np.float32)
                out.append({"t": float(t), "tenant": load.tenant,
                            "kind": "query", "payload": qs, "k": load.k})
    out.sort(key=lambda item: item["t"])
    return out


def run_fleet_session(fleet: FleetDaemon, loads: List[TenantLoad],
                      clock=time.monotonic, sleep=time.sleep) -> dict:
    """Drive one merged open-loop session; returns the fleet summary.

    The recompile count is the fleet-wide ExecutableCache miss delta
    across the measured window: every dense tenant warmed its buckets at
    construction, so a mutation-free session must measure ZERO -- the
    fleet steady-state law the __main__ --assert-steady gate and the
    check.sh smoke enforce across >= 2 tenants at once."""
    schedule = build_fleet_schedule(
        loads, {name: t.n_points for name, t in fleet.tenants.items()},
        domain=DOMAIN_SIZE)
    cache0 = dict(_dispatch.EXEC_CACHE.stats_dict())
    elastic0 = sum(t.elastic.elastic_recompiles
                   for t in fleet.tenants.values() if t.is_pod)
    _dispatch.reset_stats()
    # streaming per-tenant aggregation (ISSUE 13 satellite): every
    # response is absorbed -- counted + binned into BOUNDED histograms
    # (query responses only: the fleet's SLO-gate semantics) -- the
    # moment it surfaces; nothing is retained, so a sustained-QPS fleet
    # session's memory is O(1) in the request count
    aggs: Dict[str, SessionAggregate] = {
        load.tenant: SessionAggregate(query_only=True) for load in loads}
    fleet_agg = SessionAggregate(query_only=True)
    degraded_rows: Dict[str, int] = {}

    def absorb(rs: List[Response]) -> None:
        fleet_agg.absorb(rs)
        for r in rs:
            if r.tenant in aggs:
                aggs[r.tenant].absorb([r])
            if r.degraded is not None and r.ids is not None:
                degraded_rows[r.degraded] = (
                    degraded_rows.get(r.degraded, 0)
                    + int(r.ids.shape[0]))

    # client-side backoff (ISSUE 19 satellite): tenants with
    # TenantLoad.backoff re-offer a refusal that carries a typed
    # retry_after_ms hint -- shed load is DEFERRED, not lost
    backoff = {load.tenant: load for load in loads if load.backoff}
    reoffer: List[tuple] = []        # (due, seq, tries, item) min-heap
    deferred = 0
    rid = 0

    def offer(item: dict, now: float, tries: int) -> None:
        nonlocal rid, deferred
        rid += 1
        rs = fleet.submit(
            req_id=rid, tenant=item["tenant"], kind=item["kind"],
            payload=item["payload"], k=item.get("k"), now=now,
            trace_id=f"{item['tenant']}-{rid}")
        load = backoff.get(item["tenant"])
        if load is not None and tries < load.max_retries:
            mine = next((r for r in rs if r.req_id == rid), None)
            if mine is not None and not mine.ok \
                    and mine.retry_after_ms is not None:
                deferred += 1
                heapq.heappush(reoffer,
                               (now + mine.retry_after_ms / 1e3 + 1e-3,
                                rid, tries + 1, item))
        absorb(rs)

    t0 = clock()
    i = 0
    pending = (lambda: any(t.ready or (t.daemon is not None
                                       and t.daemon.batcher.pending_queries)
                           for t in fleet.tenants.values()))
    while i < len(schedule) or reoffer or pending():
        now = clock()
        if reoffer and reoffer[0][0] <= now:
            _, _, tries, item = heapq.heappop(reoffer)
            offer(item, now, tries)
            continue
        if i < len(schedule) and t0 + schedule[i]["t"] <= now:
            item = schedule[i]
            i += 1
            offer(item, t0 + item["t"], 0)
            continue
        absorb(fleet.poll(now))
        next_events = []
        if i < len(schedule):
            next_events.append(t0 + schedule[i]["t"])
        if reoffer:
            next_events.append(reoffer[0][0])
        deadline = fleet.next_deadline()
        if deadline is not None:
            next_events.append(deadline)
        if not next_events:
            break
        wait = min(next_events) - clock()
        if wait > 0:
            sleep(min(wait, 0.005))
    absorb(fleet.drain(clock()))
    # a pod tenant may still hold an in-flight migration: pump it dry so
    # the session's summary reflects the post-handover state (bounded:
    # each pump ships one chunk)
    for t in fleet.tenants.values():
        guard = 0
        while t.is_pod and t.elastic.migration is not None \
                and guard < 10_000:
            t.elastic.pump()
            guard += 1
    elapsed = max(clock() - t0, 1e-9)
    cache1 = _dispatch.EXEC_CACHE.stats_dict()
    # exec-cache misses attributed to elastic index maintenance
    # (migration handovers, shard rebuilds, mutation-side compaction) are
    # carved out of the steady-state recompile gate: a live rebalance is
    # index work, not a serving-path recompile (DESIGN.md section 22)
    elastic1 = sum(t.elastic.elastic_recompiles
                   for t in fleet.tenants.values() if t.is_pod)
    elastic_recompiles = int(elastic1 - elastic0)

    per_tenant: Dict[str, dict] = {}
    offered: Dict[str, int] = {load.tenant: 0 for load in loads}
    for item in schedule:
        if item["kind"] == "query":
            offered[item["tenant"]] += item["payload"].shape[0]
    completion = []
    for load in loads:
        name = load.tenant
        agg = aggs[name]
        served = agg.completed_queries
        # percentiles over QUERY responses only: mutation acks are
        # near-instant and would dilute the p99 the slo_ok gate checks
        slo = SLO_CLASSES[fleet.tenants[name].spec.slo]
        pct = _percentiles(agg.hist["total_ms"])
        ratio = served / offered[name] if offered[name] else None
        completion.append(ratio)
        per_tenant[name] = {
            "slo": slo.name,
            "offered_rows": offered[name],
            "served_rows": served,
            "completion": (round(ratio, 6) if ratio is not None else None),
            "refused": fleet.refused[name],
            "failed": agg.failed,
            "sustained_qps": round(served / elapsed, 1),
            "sidecar": fleet.tenants[name].is_sidecar,
            "pod": fleet.tenants[name].is_pod,
            **pct,
            "decomposition": agg.decomposition(),
            "slo_p99_budget_ms": slo.p99_budget_ms,
            "slo_ok": (pct["p99_ms"] is not None
                       and pct["p99_ms"] <= slo.p99_budget_ms),
        }
    total_served = fleet_agg.completed_queries
    occ = [b["rows"] / b["capacity"] for b in fleet.batch_log]
    summary = {
        "requests": len(schedule),
        "responses": fleet_agg.responses,
        "completed_queries": total_served,
        "failed_requests": fleet_agg.failed,
        "refused_requests": int(sum(fleet.refused.values())),
        "deferred_requests": deferred,
        "degraded_rows": dict(degraded_rows),
        "elapsed_s": round(elapsed, 4),
        "sustained_qps": round(total_served / elapsed, 1),
        "recompiles": int(cache1["exec_cache_misses"]
                          - cache0["exec_cache_misses"]
                          - elastic_recompiles),
        "elastic_recompiles": elastic_recompiles,
        "migrations_done": sum(t.elastic.migrations_done
                               for t in fleet.tenants.values()
                               if t.is_pod),
        "exec_cache_enabled": _dispatch.EXEC_CACHE.enabled,
        "occupancy_mean": (round(float(np.mean(occ)), 4) if occ else None),
        # fleet-wide per-request latency decomposition (span-sourced:
        # queue wait -> host dispatch -> device), p50/p99 -- the stamp
        # the fleet bench rows carry (DESIGN.md section 19)
        "latency_decomposition": fleet_agg.decomposition(),
        "jain_fairness": jain_index(completion),
        "n_tenants": len(fleet.tenants),
        "slo_ok_all": all(per_tenant[n]["slo_ok"] or not offered[n]
                          for n in per_tenant),
        "per_tenant": per_tenant,
        **{k: v for k, v in cache1.items()
           if k != "exec_cache_disabled_by"},
        **_dispatch.stats_dict(),
        **{k: v for k, v in fleet.stats_dict().items()
           if k not in ("tenants",)},
    }
    return summary


def default_fleet_builds(n_tenants: int = 4, base_n: int = 6000,
                         k: int = 8, seed: int = 0,
                         sidecar_tenant: bool = True,
                         replicas: int = 0):
    """A mixed-SLO fleet build list for the smokes and bench rows:
    tenants alternate latency/throughput classes; the LAST tenant (when
    ``sidecar_tenant``) is tiny so it lands on the CPU sidecar; the first
    two tenants share one cloud size so their executable signatures are
    equal (the cross-tenant cache-sharing case is always present)."""
    from ...io import generate_uniform

    builds = []
    for i in range(n_tenants):
        tiny = sidecar_tenant and i == n_tenants - 1 and n_tenants > 1
        n = 48 if tiny else base_n  # tenants 0 and 1 share a size
        if not tiny and i >= 2:
            n = base_n + 1024 * i
        spec = TenantSpec(
            name=f"t{i}", k=k,
            slo="latency" if i % 2 == 0 else "throughput",
            replicas=replicas if not tiny else 0)
        builds.append((spec, generate_uniform(n, seed=seed + 17 * i)))
    return builds
