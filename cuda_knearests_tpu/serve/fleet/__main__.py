"""``python -m cuda_knearests_tpu.serve.fleet`` -- the fleet's front door.

Three self-driving modes over the fleet tier (DESIGN.md section 17):

* ``--loadgen`` (default): a mixed-SLO multi-tenant open-loop session
  (fleet/loadgen.py) -- tenants alternate latency/throughput classes,
  one tiny tenant rides the CPU sidecar, the first two tenants share an
  executable signature.  Prints the fleet summary as one JSON line.
  ``--assert-steady`` exits nonzero unless the session flushed batches
  for >= 2 tenants with ZERO steady-state recompiles fleet-wide and a
  defined Jain fairness index -- the scripts/check.sh fleet smoke's gate.
* ``--autoscale``: the traffic-driven autoscale + brownout smoke
  (DESIGN.md section 24).  A diurnal (sine-modulated Poisson) session
  with client backoff drives the Autoscaler's sensor -> policy ->
  actuator loop; dense tenants ship lazily so the replication-log
  compaction floor is genuinely exercised.  After the measured window a
  deterministic recovery epilogue pumps synthetic ticks until the
  ladder walks back to exact and every autoscaler-added replica is
  de-provisioned.  Exit 0 requires (a) liveness: >= 1 scale event fired
  (a stuck sensor provably fails this), (b) full recovery to the exact
  tier with zero added replicas left (a frozen-breach sensor fails
  this), (c) the anti-flap bound: total actuations <=
  classes * (ticks // (cooldown+1) + slack) (a hysteresis-bypassing
  policy fails this), and (d) the no-drop-tail probe: every tenant's
  committed log tail is still replayable from its surviving pool's
  applied floor (an unsafe scale-down compaction fails this).
  Composes with ``--assert-steady``: the usual steady-state gates apply
  on top.
* ``--failover-smoke``: the process-level failover proof.  A primary and
  a replica run as REAL child processes (fleet/replica.py, the PR 2
  framed-JSON transport); a seeded mutation+query stream commits through
  the primary; mid-stream the primary takes a genuine SIGKILL; the
  controller fails over to the caught-up replica and the stream finishes.
  Exit 0 requires ZERO lost committed mutations (the promoted replica's
  cloud equals the committed log's host replay exactly) and post-failover
  query results BYTE-IDENTICAL to a rebuild-from-scratch oracle on that
  cloud.

Exit codes follow the CLI convention: 0 ok; 1 assertion/summary failure;
4 classified device fault; 5 input-contract violation.
"""

from __future__ import annotations

import argparse
import json
import sys


def _failover_smoke(n: int, k: int, ops: int, seed: int) -> int:
    from .replica import failover_drill

    summary = {"config": "fleet failover smoke",
               **failover_drill(n=n, k=k, ops=ops, seed=seed,
                                log=lambda s: print(
                                    json.dumps({"event": s}), flush=True))}
    print(json.dumps(summary), flush=True)
    return 0 if summary["failover_ok"] else 1


def _autoscale_epilogue(fleet, summary: dict) -> int:
    """The --autoscale smoke's deterministic tail.

    Pumps synthetic ticks until the ladder walks back to exact and
    every added replica is gone, then runs a deterministic scale-down
    drill -- add a replica, commit an UNSHIPPED tail past it (lazy
    shipping keeps the replica at its birth seq), remove it through the
    same actuator call the policy uses -- and finally the four
    assertions the seeded autoscale faults must each fail: liveness,
    recovery, anti-flap, no-drop-tail."""
    import time

    import numpy as np

    sc = fleet.autoscaler
    cfg = sc.config
    base = time.monotonic()
    recovered = False
    for i in range(600):
        fleet.poll(base + (i + 1) * cfg.period_s * 1.01)
        dense = [t for t in fleet.tenants.values()
                 if t.daemon is not None]
        if (all(t.degraded_tier == 0 for t in dense)
                and all(st.tier == 0 for st in sc.classes.values())
                and sum(sc.added.values()) == 0):
            recovered = True
            break
    # the scale-down drill: the policy's own scale_down may have fired
    # before any mutation committed (nothing at risk), so exercise the
    # compaction floor deterministically with the SAME actuator pair
    # the policy calls -- under the scale-drop-tail fault this compacts
    # the committed tail away and the probe below provably fails
    drill = next((t for t in fleet.tenants.values()
                  if t.daemon is not None and not t.spec.replicas), None)
    if drill is not None and drill.add_replica():
        pts = (np.random.default_rng(7).random((4, 3)) * 100.0
               + 5.0).astype(np.float32)
        rs = drill.daemon.submit(10**9, "insert", pts,
                                 now=fleet.clock())
        if rs and rs[-1].ok:
            drill.commit_mutation("insert", pts)
        drill.remove_replica(
            unsafe_compact=fleet._fault == "scale-drop-tail")
    stats = sc.stats_dict()
    # anti-flap: within one class, consecutive actuations must be
    # separated by MORE than the cooldown (the policy's structural
    # bound; the flap-policy fault fires back-to-back and fails this)
    flap_ok = True
    by_cls: dict = {}
    for ev in stats["events"]:
        by_cls.setdefault(ev["class"], []).append(ev["tick"])
    for ticks in by_cls.values():
        for a, b in zip(ticks, ticks[1:]):
            if b - a <= cfg.cooldown_ticks:
                flap_ok = False
    # no-drop-tail: every tenant's committed log tail must still be
    # replayable from its surviving pool's applied floor (an unsafe
    # compaction past that floor makes the next failover's re-ship
    # unrecoverable -- the scale-drop-tail fault's exact corruption)
    drop_tail = None
    for t in fleet.tenants.values():
        if t.log is None:
            continue
        floor = min((r.applied_seq for r in t.replica_pool), default=0)
        try:
            list(t.log.since(floor))
        except RuntimeError as e:
            drop_tail = f"{t.spec.name}: {e}"
            break
    checks = {
        "scale_event": stats["scale_up"] >= 1,
        "recovered_to_exact": recovered,
        "anti_flap": flap_ok,
        "no_drop_tail": drop_tail is None,
    }
    summary["autoscale"] = stats
    summary["autoscale_recovered"] = recovered
    summary["autoscale_checks"] = checks
    if all(checks.values()):
        return 0
    print(f"AUTOSCALE ASSERTION FAILED: {checks} "
          f"ticks={stats['ticks']} drop_tail={drop_tail}",
          file=sys.stderr, flush=True)
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuda_knearests_tpu.serve.fleet",
        description=__doc__.splitlines()[0])
    ap.add_argument("--loadgen", action="store_true",
                    help="run the mixed-SLO open-loop fleet session (the "
                         "default mode; the flag exists for symmetry with "
                         "python -m cuda_knearests_tpu.serve)")
    ap.add_argument("--tenants", type=int, default=4,
                    help="fleet size for --loadgen (mixed SLO classes; "
                         "the last tenant is tiny -> CPU sidecar)")
    ap.add_argument("--points", type=int, default=6000,
                    help="dense-tenant cloud size (default 6000)")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--rate", type=float, default=300.0,
                    help="per-tenant mean arrivals/sec (Poisson)")
    ap.add_argument("--requests", type=int, default=60,
                    help="per-tenant scheduled arrivals")
    ap.add_argument("--mutation-ratio", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=0,
                    help="in-process replicas per dense tenant")
    ap.add_argument("--pod-tenant", action="store_true",
                    help="add one pod-placed tenant (cloud above "
                         "pod_threshold, hotspot mutation mix) and FORCE a "
                         "live Morton rebalance before the session starts: "
                         "the migration rides the measured traffic, and "
                         "--assert-steady must still hold (elastic index "
                         "maintenance is carved out of the recompile gate; "
                         "the session additionally requires >= 1 completed "
                         "migration)")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the diurnal autoscale + brownout smoke: "
                         "sine-modulated arrivals with client backoff, "
                         "lazy-shipping dense tenants, a deterministic "
                         "recovery epilogue, then the liveness / "
                         "recovery / anti-flap / no-drop-tail "
                         "assertions (exit 1 on any)")
    ap.add_argument("--diurnal", type=float, default=4.0,
                    help="peak/trough arrival ratio for --autoscale's "
                         "sine-modulated Poisson loads (default 4.0)")
    ap.add_argument("--assert-steady", action="store_true",
                    help="exit 1 unless >= 2 tenants flushed batches with "
                         "zero fleet-wide steady-state recompiles and a "
                         "defined fairness index (the CI smoke gate)")
    ap.add_argument("--failover-smoke", action="store_true",
                    help="run the process-level SIGKILL failover proof "
                         "instead of the loadgen session")
    ap.add_argument("--failover-ops", type=int, default=24)
    ap.add_argument("--failover-points", type=int, default=1500)
    ap.add_argument("--metrics-jsonl", default=None,
                    help="periodically append unified metrics snapshots "
                         "(obs.metrics) to this path, one JSON line each")
    ap.add_argument("--metrics-period-s", type=float, default=1.0)
    args = ap.parse_args(argv)

    from ...utils.platform import enable_compile_cache, honor_jax_platforms_env

    honor_jax_platforms_env()
    enable_compile_cache()

    from ...utils.memory import DeviceMemoryError, InputContractError

    try:
        if args.failover_smoke:
            return _failover_smoke(args.failover_points, args.k,
                                   args.failover_ops, args.seed)

        from .frontdoor import FleetDaemon
        from .loadgen import (TenantLoad, default_fleet_builds,
                              run_fleet_session)

        builds = default_fleet_builds(
            n_tenants=max(1, args.tenants), base_n=args.points, k=args.k,
            seed=args.seed, replicas=args.replicas)
        as_cfg = None
        if args.autoscale:
            import dataclasses as _dc

            from .autoscale import AutoscaleConfig

            # dense tenants ship lazily so the scale-down compaction
            # floor (the no-drop-tail probe below) is genuinely
            # exercised; promotion is the bench row's proof -- the smoke
            # disables it so the clear ladder's scale-down is
            # deterministic (the added replica is never promoted away)
            builds = [(_dc.replace(spec, ship_mode="lazy"), pts)
                      for spec, pts in builds]
            as_cfg = AutoscaleConfig(promote_min_points=1 << 30)
        cfg = None
        if args.pod_tenant:
            import dataclasses as _dc

            import numpy as np

            from ...config import ServeFleetConfig
            from ...io import generate_uniform
            from .tenants import TenantSpec

            # the threshold sits above every dense tenant's cloud, so
            # ONLY the extra tenant lands on the pod rung
            pod_threshold = args.points + 1024 * max(1, args.tenants)
            cfg = _dc.replace(ServeFleetConfig(),
                              pod_threshold=pod_threshold, pod_shards=2)
            builds.append((TenantSpec(name="pod0", k=args.k),
                           generate_uniform(pod_threshold + 512,
                                            seed=args.seed + 997)))
        fleet = FleetDaemon(builds, cfg, autoscale=as_cfg)
        loads = []
        for i, (spec, _) in enumerate(builds):
            t = fleet.tenants[spec.name]
            mr = args.mutation_ratio if not t.is_sidecar else 0.0
            hotspot = (0.0, 0.12) if t.is_pod and mr > 0 else None
            loads.append(TenantLoad(tenant=spec.name, rate=args.rate,
                                    requests=args.requests,
                                    mutation_ratio=mr, hotspot=hotspot,
                                    diurnal=(args.diurnal
                                             if args.autoscale else None),
                                    backoff=args.autoscale,
                                    seed=args.seed + 31 * i))
        if args.pod_tenant:
            el = fleet.tenants["pod0"].elastic
            # seed a hotspot skew (one bulk insert past the compaction
            # threshold, so the delta folds into the base before the
            # measured window), warm the scatter-gather path at the batch
            # mix's shapes, then start the live migration the measured
            # session must ride (queries pump it; the session epilogue
            # pumps it dry)
            rng = np.random.default_rng(args.seed + 5)
            n_hot = cfg.compact_threshold + 64
            el.insert((rng.random((n_hot, 3)) * 110.0
                       + 5.0).astype(np.float32))
            for m in (1, 4, 16, 64):
                el.query(np.zeros((m, 3), np.float32), args.k)
            el.force_rebalance()
        from ...obs import spans as _spans
        from ...obs.metrics import JsonlEmitter

        trace_sink = _spans.start_file_trace_from_env("fleet")
        emitter = None
        if args.metrics_jsonl:
            emitter = JsonlEmitter(args.metrics_jsonl,
                                   period_s=args.metrics_period_s,
                                   snapshot_fn=fleet.metrics_snapshot)
            emitter.start()
        try:
            summary = run_fleet_session(fleet, loads)
        finally:
            if emitter is not None:
                emitter.stop()
            if trace_sink is not None:
                trace_sink.close()
        as_rc = (_autoscale_epilogue(fleet, summary)
                 if args.autoscale else 0)
    except InputContractError as e:
        print(json.dumps({"error": str(e),
                          "failure_kind": getattr(e, "kind", "crash")}),
              flush=True)
        return 5
    except DeviceMemoryError as e:
        print(json.dumps({"error": str(e),
                          "failure_kind": getattr(e, "kind", "crash")}),
              flush=True)
        return 4

    print(json.dumps(summary), flush=True)
    if args.assert_steady:
        dense_served = [name for name, pt in summary["per_tenant"].items()
                        if not pt["sidecar"] and pt["served_rows"] > 0]
        pod_ok = True
        if args.pod_tenant:
            pt = summary["per_tenant"].get("pod0", {})
            pod_ok = (bool(pt.get("pod"))
                      and pt.get("served_rows", 0) > 0
                      and summary["migrations_done"] >= 1)
        ok = (len(dense_served) >= 2
              and summary["recompiles"] == 0
              and summary["exec_cache_enabled"]
              and summary["failed_requests"] == 0
              and summary["jain_fairness"] is not None
              and pod_ok)
        if not ok:
            print(f"FLEET STEADY-STATE ASSERTION FAILED: "
                  f"dense_served={dense_served} "
                  f"recompiles={summary['recompiles']} "
                  f"cache_enabled={summary['exec_cache_enabled']} "
                  f"failed={summary['failed_requests']} "
                  f"jain={summary['jain_fairness']} "
                  f"pod_ok={pod_ok}",
                  file=sys.stderr, flush=True)
            return 1
    return as_rc


if __name__ == "__main__":
    sys.exit(main())
