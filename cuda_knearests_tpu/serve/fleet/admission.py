"""Admission control + fairness scheduling for the serving fleet.

Two mechanisms, two time scales (DESIGN.md section 17):

* **Token-bucket admission** (:class:`TokenBucket`) refuses over-quota
  load at the FRONT DOOR, per tenant, before anything queues: a tenant's
  bucket refills at ``rate`` query rows/sec up to ``burst``; a request
  whose row count the bucket cannot cover is refused TYPED
  (utils.memory.OverQuotaError via io.validate_request -- the front door
  owns the refusal's type and text).  Refusal, not queueing: converting
  over-quota load into queue depth would let one tenant consume the
  fleet's latency budget invisibly.

* **Deficit round robin** (:class:`DrrScheduler`) arbitrates between
  tenants whose flushed batches are READY: each scheduling round adds one
  ``quantum`` of query rows to every backlogged tenant's deficit and
  dispatches that tenant's batches while the deficit covers them.  The
  fairness law this buys (the classic DRR bound): over any window in
  which a set of tenants stays backlogged, the rows served to any two of
  them differ by at most one quantum plus one max-batch -- so a hot
  throughput-tier tenant provably cannot starve a latency-tier tenant's
  flushed batches, no matter the arrival ratio.  Every dispatch is
  stamped with the tenant, its deficit after dispatch, and the queue
  depths it was scheduled against (the per-batch fairness accounting the
  bench rows aggregate).

Pure host bookkeeping: no jax, no clocks of its own (callers inject
``now``), unit-testable with synthetic time like serve/batching.py.

Protocol binding (model ``drr-admission``, analysis/models.py):
``enqueue`` = admission (``try_take`` / the front door's ready-queue
appends), ``rotate`` = one :meth:`DrrScheduler.select` drain.  The
exhaustive exploration proves the deficit stays bounded by quantum +
max-batch and every enqueued batch dispatches within the starvation
bound -- the state-space twin of the in-source rotation-bound argument
in :meth:`DrrScheduler.select`.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


class TokenBucket:
    """Classic token bucket over query rows; ``rate=None`` = unmetered."""

    def __init__(self, rate: Optional[float], burst: float,
                 now: float = 0.0):
        self.rate = None if rate is None else float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = float(now)
        self.refusals = 0
        self.admitted_rows = 0

    def _refill(self, now: float) -> None:
        if self.rate is None:
            return
        dt = max(0.0, now - self._last)
        self._last = now
        self.tokens = min(self.burst, self.tokens + dt * self.rate)

    def try_take(self, rows: int, now: float) -> bool:
        """Spend ``rows`` tokens if available; False = over quota (the
        caller refuses typed).  Unmetered buckets always admit."""
        # proto: drr-admission.enqueue -- admission is where work enters a queue
        if self.rate is None:
            self.admitted_rows += int(rows)
            return True
        self._refill(now)
        if self.tokens >= rows:
            self.tokens -= rows
            self.admitted_rows += int(rows)
            return True
        self.refusals += 1
        return False

    def retry_after_s(self, rows: int, now: float) -> Optional[float]:
        """How long until ``rows`` tokens will be available -- the typed
        retry-after hint a refusal carries on the wire so a backoff
        client can defer instead of losing the request.  None on an
        unmetered bucket (a refusal there is not quota-shaped)."""
        if self.rate is None:
            return None
        self._refill(now)
        deficit = min(float(rows), self.burst) - self.tokens
        return max(0.0, deficit / self.rate)

    def stats_dict(self) -> dict:
        return {"quota_qps": self.rate, "quota_burst": self.burst,
                "quota_refusals": self.refusals,
                "admitted_rows": self.admitted_rows}


@dataclasses.dataclass(frozen=True)
class DrrDispatch:
    """One scheduling decision: which tenant's batch ran, and the fairness
    accounting at the moment of dispatch (stamped into per-batch stats)."""

    tenant: str
    rows: int
    deficit_after: float
    backlog: Tuple[Tuple[str, int], ...]   # (tenant, queued rows) snapshot


RECENT_DISPATCH_CAP = 4096   # bounded introspection window (long-lived tier)


class DrrScheduler:
    """Deficit round robin over per-tenant ready-batch queues.

    The scheduler owns the deficits and the rotation pointer; the front
    door owns the queues (it enqueues flushed batches and executes what
    :meth:`select` hands back, in order).  Deficits persist only while a
    tenant stays backlogged -- an emptied queue resets its deficit to
    zero, the standard DRR rule that stops an idle tenant banking
    unbounded credit.  ``dispatches`` keeps only the recent window
    (RECENT_DISPATCH_CAP) so a long-lived fleet's accounting stays O(1);
    ``n_dispatches`` counts forever.
    """

    def __init__(self, quantum: int):
        self.quantum = max(1, int(quantum))
        self.deficit: Dict[str, float] = {}
        self._order: List[str] = []
        self._next = 0
        self.dispatches: Deque[DrrDispatch] = deque(
            maxlen=RECENT_DISPATCH_CAP)
        self.n_dispatches = 0
        self.served_rows: Dict[str, int] = {}

    def register(self, tenant: str) -> None:
        if tenant not in self.deficit:
            self.deficit[tenant] = 0.0
            self.served_rows[tenant] = 0
            self._order.append(tenant)

    def select(self, ready: Dict[str, "Deque"]
               ) -> List[Tuple[str, object, DrrDispatch]]:
        """Drain the ready queues completely, in DRR order: repeatedly
        rotate over backlogged tenants, topping deficits by one quantum
        per visit and dispatching head batches the deficit covers.  The
        returned (tenant, batch, fairness-accounting) order IS the
        execution order; because every batch is bounded by the ladder's
        max_batch, every tenant's head batch is dispatchable within
        ceil(max_batch / quantum) visits, so the drain terminates and no
        batch starves."""
        # proto: drr-admission.rotate
        out: List[Tuple[str, object, DrrDispatch]] = []
        if not self._order:
            return out
        # every rotation adds one quantum to each backlogged tenant, so a
        # head batch of B rows dispatches within ceil(B / quantum)
        # rotations of first becoming head -- rotations are bounded by
        # batches * ceil(biggest / quantum), and the guard below only
        # exists to turn a future invariant break into a loud error.  The
        # bound uses the biggest batch ANYWHERE in the queues: a deep
        # batch behind a cheap head needs its own full rotation budget
        # once it surfaces.
        biggest = max((b.total for q in ready.values() for b in q),
                      default=1)
        max_rotations = 2 + sum(len(q) for q in ready.values()) * (
            1 + biggest // self.quantum + 1)
        rotations = 0
        while any(q for q in ready.values()):
            rotations += 1
            if rotations > max_rotations:
                raise RuntimeError(
                    f"DRR failed to drain in {max_rotations} rotations "
                    f"(quantum={self.quantum}): scheduler invariant broken")
            start = self._next
            for off in range(len(self._order)):
                idx = (start + off) % len(self._order)
                name = self._order[idx]
                queue = ready.get(name)
                if not queue:
                    self.deficit[name] = 0.0
                    continue
                self.deficit[name] += self.quantum
                while queue and queue[0].total <= self.deficit[name]:
                    batch = queue.popleft()
                    self.deficit[name] -= batch.total
                    self.served_rows[name] += batch.total
                    disp = DrrDispatch(
                        tenant=name, rows=batch.total,
                        deficit_after=self.deficit[name],
                        backlog=tuple(
                            (t, sum(b.total for b in q))
                            for t, q in sorted(ready.items()) if q))
                    self.dispatches.append(disp)
                    self.n_dispatches += 1
                    out.append((name, batch, disp))
                if not queue:
                    self.deficit[name] = 0.0
                self._next = (idx + 1) % len(self._order)
        return out

    def stats_dict(self) -> dict:
        return {"drr_quantum": self.quantum,
                "drr_dispatches": self.n_dispatches,
                "served_rows": dict(self.served_rows)}


def jain_index(values: List[float]) -> Optional[float]:
    """Jain's fairness index over per-tenant normalized throughput:
    (sum x)^2 / (n * sum x^2), 1.0 = perfectly fair, 1/n = one tenant took
    everything.  None when there is nothing to measure."""
    xs = [float(v) for v in values if v is not None]
    if not xs or all(x == 0.0 for x in xs):
        return None
    s, s2 = sum(xs), sum(x * x for x in xs)
    return round((s * s) / (len(xs) * s2), 6)
