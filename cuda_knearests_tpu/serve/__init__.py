"""Persistent serving subsystem: the long-lived daemon over the kNN engine.

The reference is a one-shot batch executable; the ROADMAP north star is a
server under heavy streaming traffic.  This package is that server mode
(ROADMAP item 3), built on the substrate the earlier PRs laid down:

* :mod:`batching` -- dynamic batching: requests coalesce into
  capacity-bucketed batches (size- and deadline-triggered flush) whose
  shapes come from a FIXED power-of-two bucket ladder, so the executable
  signatures a session dispatches are finite, warmable, and served hot by
  the PR 5 ``ExecutableCache`` (zero recompiles in steady state, asserted).
* :mod:`delta` -- incremental point insert/delete: grid-hash delta updates
  (count/reserve/scatter over the delta alone, a dirty-cell overlay for
  pruning, threshold-triggered compaction into a full re-prepare), with
  query results pinned byte-identical to a rebuild-from-scratch on the
  mutated cloud.
* :mod:`daemon` -- the serving core: typed admission (io.validate_request,
  the request-stream front door), per-batch failure containment mapped
  onto the supervisor's ``FAILURE_KINDS`` taxonomy (a crashed or refused
  request costs one batch, never the daemon), injected-clock event-loop
  surface.
* :mod:`loadgen` -- the open-loop Poisson load harness whose summaries
  become ``bench.py --serve`` rows: sustained QPS, p50/p99/p999 latency,
  batch occupancy, recompile count.

``python -m cuda_knearests_tpu.serve`` runs the daemon: ``--loadgen`` for
a self-driving synthetic session (the CI smoke), default mode reads
JSON-lines requests on stdin.  Everything runs on CPU, so tier-1 and
``scripts/check.sh`` exercise the whole loop.  DESIGN.md section 13 has
the batching law, the delta-overlay invariants, and the failure model.
"""

from __future__ import annotations

from ..config import ServeConfig
from .batching import Batch, DynamicBatcher, Request
from .daemon import Response, ServeDaemon
from .delta import DeltaOverlay
from .loadgen import LoadSpec, build_schedule, run_session

__all__ = ["ServeConfig", "ServeDaemon", "Response", "DeltaOverlay",
           "DynamicBatcher", "Batch", "Request", "LoadSpec",
           "build_schedule", "run_session"]
