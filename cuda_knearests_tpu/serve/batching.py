"""Dynamic batching: coalesce a request stream into capacity-bucketed
batches.

The batching law (DESIGN.md section 13): a flush happens when EITHER

  * admitting the next request would exceed ``max_batch`` queries
    (size trigger -- throughput side), or
  * the oldest pending request has waited ``max_delay_s``
    (deadline trigger -- latency side),

and the flushed batch pads to the next power-of-two capacity bucket in
``[min_bucket, max_batch]``.  The bucket ladder is FIXED, so the set of
executable signatures a serving session can dispatch is finite and fully
warmable: after one pass per bucket, steady state performs zero recompiles
(the ExecutableCache-counter assertion in tests/test_serve.py).

Mutations are NOT batched: a mutation request acts as a barrier (the
daemon flushes pending queries first, then applies it), so every query is
answered against the cloud state at its batch's flush -- a total order the
rebuild-from-scratch oracle can replay.

This module is pure host bookkeeping -- no jax, no clocks of its own (the
daemon injects ``now``), so the flush law is unit-testable with synthetic
time.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ..config import ServeConfig


@dataclasses.dataclass
class Request:
    """One admitted (already validated) query request."""

    req_id: int
    queries: np.ndarray          # (m, 3) f32, validated
    k: int                       # <= serving k; columns truncate on reply
    arrived_at: float            # open-loop arrival time (latency anchor)
    # observability (DESIGN.md section 19): the wire-carried trace id
    # (echoed on the reply, stamped on the request's spans) and the real-
    # clock admission timestamp (obs.spans.now()) the queue-wait component
    # of the latency decomposition is measured from -- arrived_at may be
    # synthetic (injected clocks), t_perf never is
    trace_id: Optional[str] = None
    t_perf: float = 0.0


@dataclasses.dataclass
class Batch:
    """One flushed batch, ready for the executor."""

    requests: List[Request]
    queries: np.ndarray          # (total, 3) concatenated in arrival order
    capacity: int                # the bucket the executor pads to
    reason: str                  # 'size' | 'deadline' | 'barrier' | 'drain'
    formed_at: float

    @property
    def total(self) -> int:
        return int(self.queries.shape[0])

    @property
    def occupancy(self) -> float:
        return self.total / self.capacity

    def slices(self):
        """(request, row_start, row_stop) per rider, in arrival order."""
        at = 0
        for r in self.requests:
            yield r, at, at + r.queries.shape[0]
            at += r.queries.shape[0]


class DynamicBatcher:
    """Accumulates admitted requests until a flush trigger fires."""

    def __init__(self, config: ServeConfig):
        self.config = config
        self._pending: List[Request] = []
        self._total = 0
        self.flushes = {"size": 0, "deadline": 0, "barrier": 0, "drain": 0}

    @property
    def pending_queries(self) -> int:
        return self._total

    def admit(self, request: Request, now: float) -> List[Batch]:
        """Queue one request; returns the batches the size trigger flushed
        (0, 1, or -- when a max-width request lands on a non-empty queue --
        2).  A flushed batch never exceeds max_batch queries."""
        out = []
        if self._total + request.queries.shape[0] > self.config.max_batch:
            b = self.flush("size", now)
            if b is not None:
                out.append(b)
        self._pending.append(request)
        self._total += request.queries.shape[0]
        if self._total >= self.config.max_batch:
            # exactly full (or a single max-width request): flush eagerly
            b = self.flush("size", now)
            if b is not None:
                out.append(b)
        return out

    def poll(self, now: float) -> Optional[Batch]:
        """Deadline trigger: flush when the oldest rider has waited out
        max_delay_s."""
        if self._pending and \
                now - self._pending[0].arrived_at >= self.config.max_delay_s:
            return self.flush("deadline", now)
        return None

    def next_deadline(self) -> Optional[float]:
        """Absolute time the deadline trigger will fire, or None when
        empty (the daemon sleeps until min(next arrival, this))."""
        if not self._pending:
            return None
        return self._pending[0].arrived_at + self.config.max_delay_s

    def flush(self, reason: str, now: float) -> Optional[Batch]:
        """Unconditional flush (mutation barriers and final drain call this
        directly)."""
        if not self._pending:
            return None
        reqs, self._pending = self._pending, []
        total, self._total = self._total, 0
        self.flushes[reason] = self.flushes.get(reason, 0) + 1
        return Batch(requests=reqs,
                     queries=np.concatenate([r.queries for r in reqs]),
                     capacity=self.config.bucket_for(total),
                     reason=reason, formed_at=now)
