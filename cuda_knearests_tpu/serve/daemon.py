"""The persistent serving daemon: request admission, batched execution,
typed failure containment.

One ``ServeDaemon`` owns a prepared problem wrapped in the mutation
overlay (serve/delta.py), a dynamic batcher (serve/batching.py), and the
execution counters.  The wire-level error model IS the engine's existing
typed taxonomy: a malformed request is REFUSED at admission with an
``InputContractError`` subclass (kind 'invalid-input', the CLI's rc-5
class), and a batch whose execution dies is contained -- every rider gets
a typed failure response whose ``failure_kind`` comes from
``runtime.supervisor.FAILURE_KINDS`` exactly as a supervised worker death
would, and the daemon keeps serving (the acceptance law: a crashed or
refused request costs one batch, never the daemon).  Whole-process deaths
are the PR 2 supervisor's layer: ``bench.py --serve`` runs each serving
session in a supervised worker, so even a SIGKILL costs one typed row.

Execution: every batch pads to its capacity bucket with sentinel
queries (domain center -- legal input, rows discarded on reply) and runs
at the SERVING k regardless of per-request k, so steady state always
dispatches an already-cached executable signature (zero recompiles after
warmup, asserted in tests/test_serve.py via the ExecutableCache
counters).  Batches execute through the runtime/dispatch machinery; the
per-session host-sync counters ride the summary.

Fault injection (CPU-testable): ``KNTPU_SERVE_FAULT=batch:<n>[:kind]``
raises a synthetic failure on the n-th executed batch (kind 'oom' raises
a LaunchBudgetError, anything else a RuntimeError classified 'crash') --
how tests prove containment without real hardware faults.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..api import KnnProblem
from ..config import DOMAIN_SIZE, ServeConfig
from ..io import validate_request
from ..obs import metrics as _metrics
from ..obs import spans as _spans
from ..runtime import dispatch as _dispatch
from ..runtime.supervisor import FAILURE_KINDS
from ..utils.memory import (InputContractError, InvalidConfigError,
                            LaunchBudgetError, classify_fault_text)
from .batching import Batch, DynamicBatcher, Request
from .delta import DeltaOverlay


@dataclasses.dataclass
class Response:
    """One request's outcome (the wire reply, minus serialization)."""

    req_id: int
    ok: bool
    ids: Optional[np.ndarray] = None      # (m, k_req) canonical CURRENT ids
    d2: Optional[np.ndarray] = None
    n_points: Optional[int] = None        # mutations: cloud size after
    error: Optional[str] = None
    failure_kind: Optional[str] = None    # FAILURE_KINDS member when not ok
    arrived_at: float = 0.0
    completed_at: float = 0.0
    # fof requests (DESIGN.md section 14): canonical per-point cluster
    # labels over the CURRENT mutated cloud + the distinct-cluster count
    labels: Optional[np.ndarray] = None
    n_clusters: Optional[int] = None
    # fleet wires (serve/fleet, DESIGN.md section 17) stamp the tenant the
    # response belongs to; single-tenant daemons leave it None
    tenant: Optional[str] = None
    # observability (DESIGN.md section 19): the echoed wire trace_id and
    # the span-sourced latency decomposition -- where this request's wall
    # time went (admission -> flush = queue, host batch work = dispatch,
    # device execution = device).  Query responses only; mutation/FoF
    # acks leave them None.
    trace_id: Optional[str] = None
    queue_ms: Optional[float] = None
    dispatch_ms: Optional[float] = None
    device_ms: Optional[float] = None
    # brownout (serve/fleet/autoscale.py, DESIGN.md section 24): the
    # ladder tier this answer was served at ('bf16' | 'recall'; None =
    # exact), and the typed defer hint a shed/over-quota refusal carries
    # so a backoff client re-offers instead of losing the request
    degraded: Optional[str] = None
    retry_after_ms: Optional[float] = None

    @property
    def latency_s(self) -> float:
        return self.completed_at - self.arrived_at

    def to_wire(self) -> dict:
        out: dict = {"id": self.req_id, "ok": self.ok}
        if self.ok and self.ids is not None:
            out["ids"] = self.ids.tolist()
            # RFC 8259 has no Infinity token (json.dumps would emit one a
            # strict parser rejects): pad slots -- id -1 -- carry d2 null
            # on the wire
            out["d2"] = [[float(v) if np.isfinite(v) else None
                          for v in row] for row in self.d2]
        if self.n_points is not None:
            out["n_points"] = self.n_points
        if self.labels is not None:
            out["labels"] = np.asarray(self.labels).tolist()
            out["n_clusters"] = self.n_clusters
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.queue_ms is not None:
            out["timing"] = {"queue_ms": self.queue_ms,
                             "dispatch_ms": self.dispatch_ms,
                             "device_ms": self.device_ms}
        if self.degraded is not None:
            out["degraded"] = self.degraded
        if self.retry_after_ms is not None:
            out["retry_after_ms"] = self.retry_after_ms
        if not self.ok:
            out["error"] = self.error
            out["failure_kind"] = self.failure_kind
        return out


def _parse_serve_fault() -> Optional[tuple]:
    spec = os.environ.get("KNTPU_SERVE_FAULT", "")
    if not spec.startswith("batch:"):
        return None
    parts = spec.split(":")
    return int(parts[1]), (parts[2] if len(parts) > 2 else "crash")


class ServeDaemon:
    """Single-threaded serving core: admit / poll / drain.

    The event loop lives in the CALLER (serve/loadgen.py's session runner,
    or the stdio front end in serve/__main__.py): the daemon exposes pure
    state transitions driven by an injected clock, which is what makes the
    batching law unit-testable with synthetic time.
    """

    def __init__(self, problem: KnnProblem,
                 config: Optional[ServeConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or ServeConfig()
        self.clock = clock
        k_max = int(problem.config.k)
        self.k_serve = (int(self.config.k) if self.config.k is not None
                        else k_max)
        if self.k_serve > k_max:
            raise InvalidConfigError(
                f"serving k={self.k_serve} exceeds the prepared "
                f"k={k_max} that sized the candidate dilation")
        self.overlay = DeltaOverlay(
            problem, compact_threshold=self.config.compact_threshold)
        self.batcher = DynamicBatcher(self.config)
        self.batches_executed = 0
        self.failed_batches = 0
        self.failed_mutations = 0
        self.fof_requests = 0
        self.fof_memo_hits = 0
        self._fof_cache: Optional[tuple] = None  # (version key, FofResult)
        self.refused = 0
        self.failure_kinds: Dict[str, int] = {}
        self.occupancies: List[float] = []
        # bounded latency accounting (obs.metrics.Histogram): total plus
        # the span-sourced queue/dispatch/device decomposition, O(1)
        # memory at any request count (DESIGN.md section 19)
        self.lat_hist = {name: _metrics.Histogram(f"serve.{name}")
                         for name in ("total_ms", "queue_ms",
                                      "dispatch_ms", "device_ms")}
        self._fault = _parse_serve_fault()
        self._compactions_seen = 0
        if self.config.warmup:
            self.warmup()

    # -- warmup ---------------------------------------------------------------

    def warmup(self) -> None:
        """Pre-execute one sentinel batch per capacity bucket so every
        steady-state signature is compiled (and cached) before the first
        real request.  Re-run after compaction (the point count changed,
        so each bucket's signature is new)."""
        dom = float(self.overlay.base.grid.domain or DOMAIN_SIZE)
        for cap in self.config.buckets():
            sentinel = np.full((cap, 3), dom / 2.0, np.float32)
            self.overlay.query(sentinel, self.k_serve)

    # -- admission ------------------------------------------------------------

    def submit(self, req_id: int, kind: str, payload, k: Optional[int] = None,
               now: Optional[float] = None,
               trace_id: Optional[str] = None) -> List[Response]:
        """Admit one request.  Queries queue into the batcher (responses
        surface later via poll/drain); mutations are barriers -- the
        pending batch flushes first, then the mutation applies and answers
        immediately.  A contract violation refuses THIS request (typed,
        kind 'invalid-input') and nothing else.  ``trace_id`` is the wire-
        carried correlation id: echoed on the reply, stamped on the
        request's spans (DESIGN.md section 19)."""
        now = self.clock() if now is None else now
        t_admit = _spans.now()
        _spans.event("serve.admit", trace_id=trace_id, kind=kind,
                     req=req_id)
        try:
            payload = validate_request(
                kind, payload, k=k, k_max=self.k_serve,
                n_current=self.overlay.n_points,
                max_batch=self.config.max_batch,
                domain=float(self.overlay.base.grid.domain or DOMAIN_SIZE))
        except InputContractError as e:
            self.refused += 1
            return [Response(req_id=req_id, ok=False, error=str(e),
                             failure_kind=e.kind, arrived_at=now,
                             completed_at=self.clock(),
                             trace_id=trace_id)]
        if kind == "query":
            req = Request(req_id=req_id, queries=payload,
                          k=int(k) if k else self.k_serve, arrived_at=now,
                          trace_id=trace_id, t_perf=t_admit)
            out = []
            for batch in self.batcher.admit(req, now):
                out.extend(self._execute(batch))
            return out
        if kind == "fof":
            # clustering query family (DESIGN.md section 14): flush the
            # pending batch first (stream-order consistency with the
            # mutation barrier), then label the CURRENT mutated cloud.
            # Same containment law as batches: a FoF death costs THIS
            # request a typed failure, never the daemon.
            out = []
            barrier = self.batcher.flush("barrier", now)
            if barrier is not None:
                out.extend(self._execute(barrier))
            self.fof_requests += 1
            try:
                res = self._run_fof(float(payload))
            except Exception as e:  # noqa: BLE001 -- containment IS the contract: a FoF solve death becomes one typed failure response, the daemon survives
                fkind = self._classify(e)
                self.failure_kinds[fkind] = \
                    self.failure_kinds.get(fkind, 0) + 1
                out.append(Response(
                    req_id=req_id, ok=False,
                    error=f"fof failed: {type(e).__name__}: {e}",
                    failure_kind=fkind, arrived_at=now,
                    completed_at=self.clock(), trace_id=trace_id))
                return out
            out.append(Response(
                req_id=req_id, ok=True, n_points=self.overlay.n_points,
                labels=res.labels, n_clusters=res.n_clusters,
                arrived_at=now, completed_at=self.clock(),
                trace_id=trace_id))
            return out
        # mutation barrier: queries already pending answer against the
        # pre-mutation cloud (their batch formed first)
        out = []
        barrier = self.batcher.flush("barrier", now)
        if barrier is not None:
            out.extend(self._execute(barrier))
        # same containment law as batches: a mutation whose apply dies
        # (compaction's re-prepare, the post-compaction re-warm) costs THIS
        # request a typed failure, never the daemon.  Overlay state stays
        # consistent either way: compact() swaps its base atomically after
        # the re-prepare succeeds, so a failed apply leaves the previous
        # overlay intact and serving.
        try:
            if kind == "insert":
                self.overlay.insert(payload)
            else:
                self.overlay.delete(payload)
            if self.overlay.stats.compactions and self.config.warmup \
                    and self.overlay.mutations_pending == 0 \
                    and self._compactions_seen \
                    != self.overlay.stats.compactions:
                self._compactions_seen = self.overlay.stats.compactions
                self.warmup()
        except Exception as e:  # noqa: BLE001 -- containment IS the contract: a mutation-apply death becomes one typed failure response, the daemon survives
            fkind = self._classify(e)
            self.failed_mutations += 1
            self.failure_kinds[fkind] = self.failure_kinds.get(fkind, 0) + 1
            out.append(Response(
                req_id=req_id, ok=False,
                error=f"mutation failed: {type(e).__name__}: {e}",
                failure_kind=fkind, arrived_at=now,
                completed_at=self.clock(), trace_id=trace_id))
            return out
        out.append(Response(req_id=req_id, ok=True,
                            n_points=self.overlay.n_points,
                            arrived_at=now, completed_at=self.clock(),
                            trace_id=trace_id))
        return out

    def poll(self, now: Optional[float] = None) -> List[Response]:
        """Deadline-trigger check; the event loop calls this between
        arrivals."""
        now = self.clock() if now is None else now
        batch = self.batcher.poll(now)
        return self._execute(batch) if batch is not None else []

    def drain(self, now: Optional[float] = None) -> List[Response]:
        """Flush whatever is pending (end of stream / EOF)."""
        now = self.clock() if now is None else now
        batch = self.batcher.flush("drain", now)
        return self._execute(batch) if batch is not None else []

    def next_deadline(self) -> Optional[float]:
        return self.batcher.next_deadline()

    # -- execution ------------------------------------------------------------

    @staticmethod
    def _classify(e: BaseException) -> str:
        """Taxonomy kind of a contained failure: the exception's own kind
        stamp when it carries one, else text classification, else
        'crash' -- the same ladder the supervisor's workers use."""
        kind = getattr(e, "kind", None)
        if kind in FAILURE_KINDS:
            return kind
        return classify_fault_text(f"{type(e).__name__}: {e}") or "crash"

    def _run_fof(self, b: float):
        """FoF labels of the CURRENT mutated cloud (cluster/fof.py),
        memoized until the next mutation: repeated fof requests at the
        same linking length between mutations answer from cache, and the
        per-round launches behind a cache miss dispatch through the same
        AOT executable cache as the batched queries."""
        from ..cluster.fof import fof_labels

        st = self.overlay.stats
        version = (b, st.inserts, st.deletes, st.compactions)
        # NOTE the version key is per-OVERLAY: anything that swaps the
        # overlay object itself (fleet failover) must call
        # invalidate_fof_memo(), because the new overlay's counters can
        # legally collide with the old one's
        if self._fof_cache is not None and self._fof_cache[0] == version:
            # NOTE the memo is daemon-owned host state, deliberately NOT
            # keyed through the executable cache: an ExecutableCache LRU
            # eviction (capacity pressure from query buckets) must only
            # ever cost a recompile on the next MISS, never invalidate or
            # corrupt an already-computed answer (tests/test_serve.py pins
            # the eviction-mid-session interaction)
            self.fof_memo_hits += 1
            return self._fof_cache[1]
        # overlay points are already inside the prepared domain (inserts
        # were validated at admission): skip the O(n) re-scan
        res = fof_labels(self.overlay.mutated_points(), b, validate=False)
        self._fof_cache = (version, res)
        return res

    def invalidate_fof_memo(self) -> None:
        """Drop the FoF memo.  Mutations invalidate it implicitly through
        the overlay-stats version key; callers that swap the overlay
        OBJECT (fleet failover promotes a replica's overlay) must call
        this, since the new overlay's counters can collide with the old
        key."""
        self._fof_cache = None

    def _run_batch(self, batch: Batch, idx: int):
        """One padded bucket-capacity launch at the serving k.  Returns
        (ids, d2, device_ms): the device span wraps ONLY the overlay
        launch, so the decomposition's device component excludes the
        host-side padding/slicing work (which lands in dispatch_ms)."""
        if self._fault is not None and idx == self._fault[0]:
            if self._fault[1] == "oom":
                raise LaunchBudgetError(
                    "injected synthetic over-budget serving batch",
                    requested=1 << 40, budget=1 << 30, site="serve-fault")
            raise RuntimeError("injected serving batch fault")
        cap = batch.capacity
        dom = float(self.overlay.base.grid.domain or DOMAIN_SIZE)
        padded = np.full((cap, 3), dom / 2.0, np.float32)
        padded[: batch.total] = batch.queries
        with _spans.span("serve.device", force=True, batch=idx) as dev:
            ids, d2 = self.overlay.query(padded, self.k_serve)
        return ids[: batch.total], d2[: batch.total], round(dev.dur_ms, 4)

    def _queue_ms(self, req: Request, t_exec0: float) -> Optional[float]:
        """Span-sourced queue-wait of one rider: admission (t_perf) to
        batch execution start, on the tracer's real clock."""
        if not req.t_perf:
            return None
        return round(max((t_exec0 - req.t_perf) * 1e3, 0.0), 4)

    def _execute(self, batch: Batch) -> List[Response]:
        """Run one batch with containment: a raise costs every rider of
        THIS batch a typed failure response (kind from the supervisor
        taxonomy) and nothing more -- the daemon's loop state stays
        consistent and the next batch runs fresh.

        Observability (DESIGN.md section 19): the execute window and the
        device launch are ALWAYS timed (forced spans -- the decomposition
        is a product, not a debug mode); each rider's reply carries
        queue_ms (admission -> execute start), dispatch_ms (host batch
        work around the device call), and device_ms, and when tracing is
        enabled a retrospective ``serve.queue`` span per rider puts the
        wait on the timeline under its trace_id."""
        idx = self.batches_executed
        self.batches_executed += 1
        failed: Optional[BaseException] = None
        ids = d2 = None
        device_ms = 0.0
        with _spans.span("serve.execute", force=True, batch=idx,
                         capacity=batch.capacity, rows=batch.total,
                         reason=batch.reason) as ex:
            try:
                ids, d2, device_ms = self._run_batch(batch, idx)
            except Exception as e:  # noqa: BLE001 -- containment IS the contract: any batch death becomes typed per-request failures, the daemon survives
                failed = e
        dispatch_ms = round(max(ex.dur_ms - device_ms, 0.0), 4)
        if _spans.enabled():
            for r in batch.requests:
                if r.t_perf:
                    _spans.emit("serve.queue", r.t_perf, ex.t0,
                                trace_id=r.trace_id, req=r.req_id,
                                batch=idx)
        if failed is not None:
            kind = self._classify(failed)
            self.failed_batches += 1
            self.failure_kinds[kind] = self.failure_kinds.get(kind, 0) + 1
            done = self.clock()
            return [Response(req_id=r.req_id, ok=False,
                             error=f"batch {idx} failed: "
                                   f"{type(failed).__name__}: {failed}",
                             failure_kind=kind, arrived_at=r.arrived_at,
                             completed_at=done, trace_id=r.trace_id,
                             queue_ms=self._queue_ms(r, ex.t0),
                             dispatch_ms=dispatch_ms,
                             device_ms=device_ms)
                    for r in batch.requests]
        self.occupancies.append(batch.occupancy)
        done = self.clock()
        out = []
        for req, a, b in batch.slices():
            queue_ms = self._queue_ms(req, ex.t0)
            resp = Response(
                req_id=req.req_id, ok=True,
                ids=np.ascontiguousarray(ids[a:b, : req.k]),
                d2=np.ascontiguousarray(d2[a:b, : req.k]),
                arrived_at=req.arrived_at, completed_at=done,
                trace_id=req.trace_id, queue_ms=queue_ms,
                dispatch_ms=dispatch_ms, device_ms=device_ms)
            self.lat_hist["total_ms"].observe(resp.latency_s * 1e3)
            if queue_ms is not None:
                self.lat_hist["queue_ms"].observe(queue_ms)
                self.lat_hist["dispatch_ms"].observe(dispatch_ms)
                self.lat_hist["device_ms"].observe(device_ms)
            out.append(resp)
        return out

    # -- introspection --------------------------------------------------------

    def latency_decomposition(self) -> dict:
        """Per-request latency decomposition at p50/p99 (span-sourced,
        histogram-bounded): where the daemon's wall time goes, the
        queue-depth/latency trade-off of arXiv 1512.02831 made a stamp."""
        return {name: _metrics.percentile_fields(hist)
                for name, hist in self.lat_hist.items()}

    def metrics_snapshot(self) -> dict:
        """The ``metrics`` wire command's document: the unified obs
        snapshot (registry + dispatch + executable cache) plus this
        daemon's own serving counters and latency decomposition."""
        return {**_metrics.metrics_snapshot(),
                "serve": self.stats_dict()}

    def stats_dict(self) -> dict:
        occ = self.occupancies
        return {
            "batches": self.batches_executed,
            "failed_batches": self.failed_batches,
            "failed_mutations": self.failed_mutations,
            "fof_requests": self.fof_requests,
            "fof_memo_hits": self.fof_memo_hits,
            "refused": self.refused,
            # executable-cache pressure (hits/misses/evictions/cap) plus
            # compile observability (exec_cache_compiled /
            # exec_cache_compile_s, kntpu-scope): the zero-recompile
            # steady state, eviction thrashing, AND where compile wall
            # time went are all visible per session, not just process-wide
            **_dispatch.EXEC_CACHE.stats_dict(),
            "failure_kinds": dict(self.failure_kinds),
            "flushes": dict(self.batcher.flushes),
            "occupancy_mean": (float(np.mean(occ)) if occ else None),
            "latency_decomposition": self.latency_decomposition(),
            "k_serve": self.k_serve,
            "n_points": self.overlay.n_points,
            **{f"overlay_{k}": v
               for k, v in self.overlay.stats.as_dict().items()},
        }
