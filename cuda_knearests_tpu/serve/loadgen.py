"""Open-loop synthetic load generator + session runner.

OPEN loop means arrivals are scheduled in advance by a Poisson process and
never wait for completions -- the generator models independent users, so
when the daemon falls behind, queueing delay shows up as latency instead of
silently throttling the offered load (the closed-loop failure mode that
flatters slow servers).  The whole schedule is drawn up front from one
seeded RNG, so a session is replayable from (spec, seed).

A request is a query burst (size drawn from ``batch_mix``) or, with
probability ``mutation_ratio``, a mutation (insert of fresh in-domain
points, or delete of currently-live ids, 50/50).  The session runner
drives the daemon's admit/poll/drain surface against real wall time and
reports the serving metrics that become ``bench.py --serve`` rows:
sustained QPS, p50/p99/p999 latency, batch occupancy, flush-trigger
counts, recompile count (ExecutableCache misses inside the measured
window), and the dispatch-layer host-sync counters.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Tuple

import numpy as np

from ..config import DOMAIN_SIZE, ServeConfig
from ..obs import metrics as _metrics
from ..runtime import dispatch as _dispatch
from .daemon import Response, ServeDaemon


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """Regenerable identity of one open-loop load session."""

    rate: float = 200.0                 # mean arrivals per second (Poisson)
    requests: int = 200                 # total scheduled arrivals
    batch_mix: Tuple[Tuple[int, float], ...] = (
        (1, 0.45), (4, 0.25), (16, 0.2), (64, 0.1))  # (queries, weight)
    mutation_ratio: float = 0.0         # fraction of arrivals that mutate
    mutation_size: int = 8              # points per insert / ids per delete
    k: Optional[int] = None             # per-request k (None = serving k)
    seed: int = 0

    def arrivals(self) -> np.ndarray:
        """Relative arrival times: cumulative sum of Exp(1/rate) gaps --
        the Poisson process, drawn once (open loop)."""
        rng = np.random.default_rng(self.seed)
        gaps = rng.exponential(1.0 / max(self.rate, 1e-9), self.requests)
        return np.cumsum(gaps)


def build_schedule(spec: LoadSpec, n_current: int,
                   domain: float = DOMAIN_SIZE) -> List[dict]:
    """The full request schedule: [{t, kind, payload, k}] in arrival order.

    Delete ids are drawn against a TRACKED running cloud size, so every
    scheduled delete is legal at its arrival time (the fuzz layer owns
    hostile streams; the load harness offers legal load)."""
    rng = np.random.default_rng(spec.seed + 1)
    sizes = np.asarray([s for s, _ in spec.batch_mix])
    weights = np.asarray([w for _, w in spec.batch_mix], np.float64)  # kntpu-ok: wide-dtype -- host-side sampling weights, never staged
    weights = weights / weights.sum()
    out = []
    n = int(n_current)
    for t in spec.arrivals():
        if spec.mutation_ratio > 0 and rng.random() < spec.mutation_ratio:
            if rng.random() < 0.5 or n <= spec.mutation_size:
                pts = (rng.random((spec.mutation_size, 3))
                       * (domain * 0.98) + domain * 0.01).astype(np.float32)
                out.append({"t": float(t), "kind": "insert", "payload": pts})
                n += spec.mutation_size
            else:
                ids = rng.choice(n, size=spec.mutation_size, replace=False)
                out.append({"t": float(t), "kind": "delete",
                            "payload": np.sort(ids).astype(np.int64)})  # kntpu-ok: wide-dtype -- host id payload, validated then used on host
                n -= spec.mutation_size
        else:
            m = int(rng.choice(sizes, p=weights))
            qs = (rng.random((m, 3)) * (domain * 0.98)
                  + domain * 0.01).astype(np.float32)
            out.append({"t": float(t), "kind": "query", "payload": qs,
                        "k": spec.k})
    return out


def _percentiles(hist: "_metrics.Histogram") -> dict:
    """p50/p99/p999 (ms) from a BOUNDED histogram -- O(1) memory at any
    sustained QPS, replacing the unbounded per-latency Python lists the
    open-loop runner used to grow (ISSUE 13 satellite)."""
    out = {}
    for label, q in (("p50_ms", 0.5), ("p99_ms", 0.99),
                     ("p999_ms", 0.999)):
        p = hist.percentile(q)
        out[label] = round(p, 3) if p is not None else None
    return out


class SessionAggregate:
    """Streaming response accounting for one open-loop session: counts
    plus bounded latency histograms (total + the span-sourced
    queue/dispatch/device decomposition).  Responses are absorbed as they
    surface and never retained, so a sustained-QPS run's memory is O(1)
    in the request count."""

    def __init__(self, query_only: bool = False) -> None:
        # query_only: bin latency for QUERY responses only (the fleet's
        # SLO gate semantics -- mutation acks are near-instant and would
        # dilute the p99 the per-class budget checks)
        self.query_only = query_only
        self.responses = 0
        self.ok_query_requests = 0
        self.completed_queries = 0
        self.failed = 0
        self.hist = {name: _metrics.Histogram(f"loadgen.{name}")
                     for name in ("total_ms", "queue_ms", "dispatch_ms",
                                  "device_ms")}

    def absorb(self, rs: List[Response]) -> None:
        for r in rs:
            self.responses += 1
            if r.ok:
                if r.ids is not None:
                    self.ok_query_requests += 1
                    self.completed_queries += int(r.ids.shape[0])
                    self.hist["total_ms"].observe(r.latency_s * 1e3)
                    if r.queue_ms is not None:
                        self.hist["queue_ms"].observe(r.queue_ms)
                        self.hist["dispatch_ms"].observe(r.dispatch_ms)
                        self.hist["device_ms"].observe(r.device_ms)
                elif not self.query_only:
                    self.hist["total_ms"].observe(r.latency_s * 1e3)
            elif r.failure_kind != "invalid-input":
                self.failed += 1

    def decomposition(self) -> dict:
        return {name: _metrics.percentile_fields(h)
                for name, h in self.hist.items()}


def run_session(daemon: ServeDaemon, spec: LoadSpec,
                clock=time.monotonic, sleep=time.sleep) -> dict:
    """Drive one open-loop session against a (warmed) daemon; returns the
    serving summary.

    The recompile count is the ExecutableCache miss delta across the
    measured window -- the daemon warmed every capacity bucket at
    construction, so in a mutation-free session this MUST be zero (the
    steady-state law tests and the check.sh smoke assert it)."""
    schedule = build_schedule(spec, daemon.overlay.n_points,
                              domain=float(daemon.overlay.base.grid.domain
                                           or DOMAIN_SIZE))
    cache0 = dict(_dispatch.EXEC_CACHE.stats_dict())
    _dispatch.reset_stats()
    # streaming aggregation: responses are absorbed (counted + binned into
    # bounded histograms) the moment they surface, never accumulated --
    # the open-loop runner's memory no longer grows with the request count
    agg = SessionAggregate()
    t0 = clock()
    i = 0
    while i < len(schedule) or daemon.batcher.pending_queries:
        now = clock()
        if i < len(schedule) and t0 + schedule[i]["t"] <= now:
            item = schedule[i]
            i += 1
            agg.absorb(daemon.submit(
                req_id=i, kind=item["kind"], payload=item["payload"],
                k=item.get("k"), now=t0 + item["t"],
                trace_id=f"s{spec.seed}-{i}"))
            continue
        agg.absorb(daemon.poll(now))
        next_events = []
        if i < len(schedule):
            next_events.append(t0 + schedule[i]["t"])
        deadline = daemon.next_deadline()
        if deadline is not None:
            next_events.append(deadline)
        if not next_events:
            break
        wait = min(next_events) - clock()
        if wait > 0:
            sleep(min(wait, 0.005))
    agg.absorb(daemon.drain(clock()))
    elapsed = max(clock() - t0, 1e-9)

    cache1 = _dispatch.EXEC_CACHE.stats_dict()
    summary = {
        "requests": len(schedule),
        "responses": agg.responses,
        "completed_query_requests": agg.ok_query_requests,
        "completed_queries": agg.completed_queries,
        "failed_requests": agg.failed,
        "elapsed_s": round(elapsed, 4),
        "sustained_qps": round(agg.completed_queries / elapsed, 1),
        "offered_rate": spec.rate,
        "mutation_ratio": spec.mutation_ratio,
        "seed": spec.seed,
        **_percentiles(agg.hist["total_ms"]),
        "latency_decomposition": agg.decomposition(),
        "recompiles": int(cache1["exec_cache_misses"]
                          - cache0["exec_cache_misses"]),
        "exec_cache_enabled": _dispatch.EXEC_CACHE.enabled,
        **{k: v for k, v in cache1.items() if k != "exec_cache_disabled_by"},
        **_dispatch.stats_dict(),   # host_syncs / d2h_bytes / h2d_bytes
        # the session-window decomposition above wins over the daemon's
        # lifetime one (identical on a fresh daemon; the window is exact)
        **{k: v for k, v in daemon.stats_dict().items()
           if k != "latency_decomposition"},
    }
    if not _dispatch.EXEC_CACHE.enabled:
        summary["exec_cache_disabled_by"] = cache1.get(
            "exec_cache_disabled_by")
    return summary
