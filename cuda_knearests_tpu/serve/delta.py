"""Incremental point insert/delete: the grid-hash delta overlay.

The base engine pays a full ``prepare`` (O(n log n) sort + plan + device
restage) for ANY change to the point cloud.  A serving daemon fronting a
moving cloud cannot: mutations arrive continuously and each one is tiny.
This module makes mutations O(delta):

* **Inserts** accumulate in a host-side delta set organized by the SAME
  cell partition as the base grid via ``gridhash.delta_csr_host`` -- the
  deterministic count/reserve/scatter idiom run over the delta alone --
  with the touched cells tracked as the **dirty-cell overlay**.
* **Deletes** tombstone base points (a host boolean mask) -- no cell
  tracking needed: a tombstone only matters when it intrudes into a base
  result row, which is detected by id.
* **Queries** stay exact AND byte-identical to a rebuild-from-scratch on
  the mutated cloud (tests/test_serve.py pins both the overlay and the
  post-compaction state): the base problem answers as prepared; rows whose
  base top-k touches a tombstone re-resolve against the alive base set;
  delta candidates merge in through one extra launch.  Every distance on
  the result path comes from the ONE brute launch HLO
  (ops/query.brute_force_by_coords -- measured bit-stable across point
  count, tile, and query count), because host numpy accumulation does NOT
  bit-match XLA's fused multiply-adds.
* **Compaction**: once absorbed mutations cross ``compact_threshold`` the
  overlay folds into a full re-prepare of the mutated cloud
  (api.KnnProblem.with_points) and the delta empties.

Canonical indexing: the mutated cloud is ``[surviving base points in
original order] + [inserts in arrival order]`` -- exactly
``np.delete`` + ``np.concatenate`` semantics, so the rebuild oracle is one
line.  Result ids are canonical CURRENT ids; delete requests address the
same indexing (validated by io.validate_request at admission).

Dirty-cell pruning: before launching the delta pass the overlay bounds
each query's distance to every dirty cell (gridhash.cell_min_d2_host,
exact f64 cell-box geometry).  A cell no query's bound can reach is
dropped; its delta rows never enter the launch (the CSR gathers only
surviving cells' rows), and when EVERY cell drops the launch is skipped
outright -- a mutation in one corner of the domain costs queries
elsewhere nothing.  The bound is conservative, so pruning never changes
the answer.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from ..api import KnnProblem
from ..ops.gridhash import cell_min_d2_host, delta_csr_host
from ..ops.query import launch_brute
from ..runtime import dispatch as _dispatch

# Far-away sentinel for delta-capacity padding rows: any real candidate in
# the [0, 1000]^3 domain (d2 <= 3e6) beats a pad (d2 ~ 1e60), and pads map
# to id -1 so they drop out of the merge as invalid.
_FAR = np.float32(1.0e30)


def _round_pow2(x: int, minimum: int = 8) -> int:
    return max(minimum, 1 << max(0, int(x) - 1).bit_length())


@dataclasses.dataclass
class OverlayStats:
    """Counters of one overlay's life (serving summaries stamp these)."""

    inserts: int = 0
    deletes: int = 0
    compactions: int = 0
    delta_launches: int = 0
    delta_skips: int = 0        # dirty-cell bound pruned the whole launch
    delta_candidates: int = 0   # CSR-gathered rows the launches scored
    resolved_rows: int = 0      # rows re-resolved for tombstone intrusions

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class DeltaOverlay:
    """A mutable point cloud served as base problem + delta, exact always.

    Thread-unsafe by design (the daemon's event loop is single-threaded);
    every public method runs on the host except the query launches.
    """

    def __init__(self, problem: KnnProblem, compact_threshold: int = 512):
        self.base = problem
        self.compact_threshold = max(1, int(compact_threshold))
        self.stats = OverlayStats()
        self._reset_delta()

    # -- state ---------------------------------------------------------------

    def _reset_delta(self) -> None:
        n = self.base.grid.n_points
        base_pts = np.asarray(self.base.get_points())  # sorted order
        perm = np.asarray(self.base.get_permutation())
        # original order view of the base cloud (canonical ids 0..n-1 before
        # any mutation): base_orig[perm[r]] = sorted row r.  The sorted-order
        # copy is NOT retained -- one resident host copy per overlay, not two
        self._base_orig = np.empty_like(base_pts)
        if n:
            self._base_orig[perm] = base_pts
        self.alive = np.ones((n,), bool)
        self.n_deleted = 0
        self.delta = np.empty((0, 3), np.float32)
        self.dirty_cells = np.empty((0,), np.int32)
        self._delta_csr: Optional[Tuple] = None  # (order, starts, counts)
        self._alive_cache: Optional[Tuple] = None  # (pts_dev, ids_dev)
        self._old2new: Optional[np.ndarray] = None

    @property
    def n_points(self) -> int:
        """Size of the CURRENT mutated cloud."""
        return int(self.alive.sum()) + self.delta.shape[0]

    @property
    def mutations_pending(self) -> int:
        return self.n_deleted + self.delta.shape[0]

    def mutated_points(self) -> np.ndarray:
        """The mutated cloud in canonical order (the rebuild oracle's
        input): surviving base originals, then inserts in arrival order."""
        return np.ascontiguousarray(
            np.concatenate([self._base_orig[self.alive], self.delta]),
            dtype=np.float32)

    def _invalidate(self, alive_changed: bool) -> None:
        """Recompute the delta CSR + dirty-cell overlay after a mutation:
        O(d log d) in the CURRENT delta (bounded by compact_threshold),
        never in the base cloud.  Deletes need no cell tracking at all --
        tombstone intrusions are detected by id against the base result
        rows -- so the dirty set is exactly the cells the delta occupies.
        The alive-set caches (the staged resolution arrays and the
        old->new id map) depend only on the tombstone mask, so inserts
        leave them intact -- an insert must never restage the O(n) base."""
        if alive_changed:
            self._alive_cache = None
            self._old2new = None
        if self.delta.shape[0]:
            order, dirty, starts, counts = delta_csr_host(
                self.delta, self.base.grid.dim, self.base.grid.domain)
            self._delta_csr = (order, starts, counts)
            self.dirty_cells = dirty
        else:
            self._delta_csr = None
            self.dirty_cells = np.empty((0,), np.int32)

    def _map_old2new(self) -> np.ndarray:
        """base original id -> canonical CURRENT id (-1 for deleted)."""
        if self._old2new is None:
            m = np.cumsum(self.alive) - 1
            self._old2new = np.where(self.alive, m, -1).astype(np.int32)
        return self._old2new

    # -- mutations -----------------------------------------------------------

    def insert(self, points: np.ndarray) -> None:
        """Append validated points (the daemon validates at admission; this
        layer trusts its caller, same as the ops layer)."""
        points = np.asarray(points, np.float32).reshape(-1, 3)
        if points.shape[0] == 0:
            return
        self.delta = np.concatenate([self.delta, points])
        self.stats.inserts += points.shape[0]
        self._invalidate(alive_changed=False)
        self._maybe_compact()

    def delete(self, ids: np.ndarray) -> None:
        """Remove points by canonical CURRENT id (np.delete semantics)."""
        ids = np.asarray(ids, np.int64).reshape(-1)  # kntpu-ok: wide-dtype -- host id arithmetic headroom, never staged
        if ids.size == 0:
            return
        n_alive = int(self.alive.sum())
        base_ids = ids[ids < n_alive]
        delta_ids = ids[ids >= n_alive] - n_alive
        if base_ids.size:
            orig = np.nonzero(self.alive)[0][base_ids]
            self.alive[orig] = False
            self.n_deleted += base_ids.size
        if delta_ids.size:
            keep = np.ones((self.delta.shape[0],), bool)
            keep[delta_ids] = False
            self.delta = self.delta[keep]
        self.stats.deletes += ids.size
        self._invalidate(alive_changed=True)
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        if self.mutations_pending >= self.compact_threshold:
            self.compact()

    def compact(self) -> None:
        """Fold the overlay into a full re-prepare of the mutated cloud.
        The one O(n) step, amortized over compact_threshold mutations; the
        post-compaction answers stay byte-identical (the new base IS a
        rebuild-from-scratch)."""
        self.base = self.base.with_points(self.mutated_points(),
                                          validate=False)
        self.stats.compactions += 1
        self._reset_delta()

    # -- queries -------------------------------------------------------------

    def _alive_launch_arrays(self):
        """Device (points, canonical-ids) of the alive base set, cached
        until the next mutation -- the tombstone-resolution launch's
        inputs.  Padded to a power-of-two row count (pads at _FAR, id -1)
        so a trickle of deletes does not mint a fresh executable signature
        per mutation: the padded shape is stable until alive count crosses
        a power of two."""
        if self._alive_cache is None:
            n_alive = int(self.alive.sum())
            cap = _round_pow2(n_alive, minimum=128)
            pts = np.full((cap, 3), _FAR, np.float32)
            pts[:n_alive] = self._base_orig[self.alive]
            ids = np.full((cap,), -1, np.int32)
            ids[:n_alive] = np.arange(n_alive, dtype=np.int32)
            self._alive_cache = (_dispatch.stage(pts), _dispatch.stage(ids))  # syncflow: overlay-alive-stage
        return self._alive_cache

    def _delta_launch_arrays(self, sel: np.ndarray, cap: int):
        """Device (points, canonical-ids) of the SELECTED delta rows padded
        to ``cap`` (pad points sit at _FAR with id -1, so they lose every
        merge) -- power-of-two capacity keeps the launch signature
        bucketed.  ``sel`` comes out of the delta CSR: only rows in cells
        some query's bound could not prune."""
        pts = np.full((cap, 3), _FAR, np.float32)
        pts[: sel.size] = self.delta[sel]
        n_alive = int(self.alive.sum())
        ids = np.full((cap,), -1, np.int32)
        ids[: sel.size] = n_alive + sel.astype(np.int32)
        return _dispatch.stage(pts), _dispatch.stage(ids)  # syncflow: overlay-delta-stage

    def query(self, queries: np.ndarray, k: int):
        """Exact kNN of ``queries`` against the CURRENT mutated cloud.

        Returns ((m, k) canonical ids, -1 padded; (m, k) d2 ascending, inf
        padded) -- byte-identical to
        ``base.with_points(mutated_points()).query(queries, k)`` under the
        serving config (the legacy/brute route; tests/test_serve.py pins
        it).  Host round trips: the base query's own (<= 2), plus one for
        tombstone resolution only when a row touched a deleted point, plus
        one for the delta merge only when the dirty-cell bound could not
        prune it."""
        queries = np.ascontiguousarray(queries, np.float32)
        m = queries.shape[0]
        if m == 0:
            return (np.empty((0, k), np.int32),
                    np.empty((0, k), np.float32))
        ids, d2 = self.base.query(queries, k)
        ids = np.array(ids)  # writable (fetch may hand back views)
        d2 = np.array(d2)
        # base ORIGINAL ids -> canonical ids; tombstone intrusions resolve
        # against the alive set (the certify-then-fallback idiom: the rare
        # row pays one extra launch, the batch never pays per-row syncs)
        if self.n_deleted:
            deleted = np.nonzero(~self.alive)[0]
            bad = np.isin(ids, deleted).any(axis=1)
            o2n = self._map_old2new()
            ids = np.where(ids >= 0, o2n[np.clip(ids, 0, None)], -1)
            if bad.any():
                a_pts, a_ids = self._alive_launch_arrays()
                # bad-row count buckets to a power of two as well (sentinel
                # query pads, discarded), for the same signature-stability
                # reason as the batch capacities
                nb = int(bad.sum())
                bcap = _round_pow2(nb)
                bq = np.full((bcap, 3), np.float32(0.0), np.float32)
                bq[:nb] = queries[bad]
                r_i, r_d = launch_brute(
                    a_pts, _dispatch.stage(bq), k, ids_map=a_ids,  # syncflow: overlay-resolve-stage
                    base_key=(self.base._exec_key, "overlay-resolve"))
                r_i, r_d = _dispatch.fetch(r_i, r_d)  # syncflow: overlay-resolve
                r_i = np.asarray(r_i)[:nb]
                r_d = np.asarray(r_d)[:nb]
                # alive-set pads carry id -1 at a huge-but-finite distance;
                # restore the -1/inf pad contract (only reachable when the
                # alive set has fewer than k points)
                r_d = np.where(r_i >= 0, r_d, np.inf)
                ids[bad] = r_i
                d2[bad] = r_d
                self.stats.resolved_rows += nb
        if self.delta.shape[0] == 0:
            return ids, d2
        # dirty-cell pruning: a dirty cell survives only when SOME query's
        # exact cell-box bound beats that query's current k-th distance
        # (rows with fewer than k neighbors have inf there, which no bound
        # exceeds -- they keep every cell).  Conservative, so dropping a
        # pruned cell's points can never change an answer.
        kth = np.where(np.isfinite(d2[:, k - 1]), d2[:, k - 1], np.inf)
        bound = cell_min_d2_host(queries, self.dirty_cells,
                                 self.base.grid.dim, self.base.grid.domain)
        need = (bound <= kth[:, None]).any(axis=0)
        if not need.any():
            self.stats.delta_skips += 1
            return ids, d2
        # gather the surviving cells' delta rows through the CSR (the
        # count/reserve/scatter layout _invalidate built)
        order, starts, counts = self._delta_csr
        sel = np.concatenate([order[s: s + c] for s, c
                              in zip(starts[need], counts[need])])
        cap = _round_pow2(int(sel.size))
        d_pts, d_ids = self._delta_launch_arrays(sel, cap)
        kd = min(k, cap)
        g_i, g_d = launch_brute(
            d_pts, _dispatch.stage(queries), kd, ids_map=d_ids,  # syncflow: overlay-delta-query-stage
            base_key=(self.base._exec_key, "overlay-delta"))
        g_i, g_d = _dispatch.fetch(g_i, g_d)  # syncflow: overlay-delta-final
        self.stats.delta_launches += 1
        self.stats.delta_candidates += int(sel.size)
        return _merge_rows(ids, d2, np.asarray(g_i), np.asarray(g_d), k)


def _merge_rows(a_i: np.ndarray, a_d: np.ndarray, b_i: np.ndarray,
                b_d: np.ndarray, k: int):
    """Merge two ascending per-row candidate lists into the final top-k.

    Pure comparisons -- no arithmetic -- so merged distances carry the
    launch's exact bits.  Invalid slots (id < 0, which covers the delta
    pad rows) sort last via inf; ties break by lower canonical id, which
    is only reachable on exactly-tied f32 distances (the tie-aware fuzz
    comparison owns that regime)."""
    ids = np.concatenate([a_i, b_i], axis=1)
    d2 = np.concatenate([a_d, b_d], axis=1)
    d2 = np.where(ids >= 0, d2, np.inf)
    order = np.lexsort((ids, d2), axis=1)[:, :k]
    rows = np.arange(ids.shape[0])[:, None]
    out_i, out_d = ids[rows, order], d2[rows, order]
    out_i = np.where(np.isfinite(out_d), out_i, -1)
    return np.ascontiguousarray(out_i), np.ascontiguousarray(out_d)
