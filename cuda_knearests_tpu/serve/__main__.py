"""``python -m cuda_knearests_tpu.serve`` -- the daemon's front door.

Two modes over one ServeDaemon:

* ``--loadgen``: self-driving open-loop synthetic session (serve/loadgen);
  prints the serving summary as one JSON line.  ``--assert-steady``
  additionally exits nonzero unless the session flushed at least one
  batch with ZERO steady-state recompiles -- the scripts/check.sh CPU
  smoke's acceptance gate.
* default (stdio): JSON-lines requests on stdin, JSON-lines responses on
  stdout.  Request: ``{"id": 1, "op": "query"|"insert"|"delete"|"fof",
  "data": [[x,y,z],...] | [id,...] | linking_length, "k": 8}`` (``fof``
  answers friends-of-friends cluster labels over the current mutated
  cloud, DESIGN.md section 14).  Responses carry ``ok``
  plus results (pad slots -- fewer than k neighbors -- are id -1 with d2
  null; the wire is strict RFC 8259, never an Infinity token), or the
  typed refusal (``failure_kind`` from the engine taxonomy).  Batching is
  live: responses surface on flush (size, deadline via idle polling,
  mutation barrier, EOF drain).

Exit codes follow the CLI convention: 0 ok; 1 assertion/summary failure;
4 classified device fault; 5 input-contract violation (bad dataset /
illegal serve config).
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_points(spec: str):
    """'uniform:N' / 'blue:N' synthetic clouds, or a dataset name / .xyz
    path through the standard loaders."""
    import os

    from ..io import (generate_blue_noise, generate_uniform, get_dataset,
                      load_xyz, normalize_points)

    if spec.startswith("uniform:"):
        return generate_uniform(int(spec.split(":")[1]), seed=5)
    if spec.startswith("blue:"):
        return generate_blue_noise(int(spec.split(":")[1]), seed=5)
    if os.path.exists(spec):
        return normalize_points(load_xyz(spec))
    return get_dataset(spec)


def _stdio_loop(daemon) -> int:
    """JSON-lines serving over stdin/stdout; deadline flushes ride an idle
    select() poll so a half-full batch never waits for the next request.

    stdin is consumed UNBUFFERED (os.read on the raw fd with our own line
    splitting): mixing select() with Python's buffered readline() would
    strand any requests a client wrote in one burst inside the
    TextIOWrapper buffer -- select() sees no kernel bytes and the daemon
    would block with admitted-but-unread requests pending."""
    import os
    import select

    def emit(responses):
        for r in responses:
            print(json.dumps(r.to_wire()), flush=True)

    def handle(raw: bytes):
        line = raw.strip()
        if not line:
            return
        try:
            req = json.loads(line.decode("utf-8"))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            print(json.dumps({"id": None, "ok": False,
                              "failure_kind": "invalid-input",
                              "error": f"unparseable request line: {e}"}),
                  flush=True)
            return
        if req.get("op") == "metrics":
            # the metrics wire command (DESIGN.md section 19): one
            # snapshot reply -- registry + dispatch + exec-cache counters
            # + the daemon's serving stats and latency decomposition
            print(json.dumps({"id": req.get("id"), "ok": True,
                              "metrics": daemon.metrics_snapshot()}),
                  flush=True)
            return
        emit(daemon.submit(req_id=req.get("id"),
                           kind=req.get("op", "query"),
                           payload=req.get("data"), k=req.get("k"),
                           trace_id=req.get("trace_id")))

    fd = sys.stdin.fileno()
    buf = b""
    while True:
        while b"\n" in buf:
            raw, buf = buf.split(b"\n", 1)
            handle(raw)
        timeout = daemon.config.max_delay_s / 2 if daemon.next_deadline() \
            else None
        ready, _, _ = select.select([fd], [], [], timeout)
        if not ready:
            emit(daemon.poll())
            continue
        chunk = os.read(fd, 1 << 16)
        if not chunk:
            handle(buf)          # trailing unterminated line, if any
            emit(daemon.drain())
            return 0
        buf += chunk


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cuda_knearests_tpu.serve",
        description=__doc__.splitlines()[0])
    ap.add_argument("--points", default="uniform:20000",
                    help="dataset name, .xyz path, or uniform:N / blue:N "
                         "(default uniform:20000)")
    ap.add_argument("--k", type=int, default=10, help="serving k")
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--max-delay-ms", type=float, default=10.0)
    ap.add_argument("--compact-threshold", type=int, default=512)
    ap.add_argument("--loadgen", action="store_true",
                    help="run the open-loop synthetic session instead of "
                         "serving stdin")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="loadgen: mean arrivals/sec (Poisson)")
    ap.add_argument("--requests", type=int, default=200,
                    help="loadgen: scheduled arrivals")
    ap.add_argument("--mutation-ratio", type=float, default=0.0,
                    help="loadgen: fraction of arrivals that insert/delete")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--assert-steady", action="store_true",
                    help="loadgen: exit 1 unless >= 1 batch flushed with "
                         "zero steady-state recompiles (the CI smoke gate)")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="periodically append unified metrics snapshots "
                         "(obs.metrics) to this path, one JSON line each; "
                         "a final snapshot lands on exit")
    ap.add_argument("--metrics-period-s", type=float, default=1.0,
                    help="snapshot period for --metrics-jsonl "
                         "(default 1.0)")
    args = ap.parse_args(argv)

    from ..utils.platform import enable_compile_cache, honor_jax_platforms_env

    honor_jax_platforms_env()
    enable_compile_cache()

    from .. import KnnConfig, KnnProblem
    from ..config import ServeConfig
    from ..utils.memory import DeviceMemoryError, InputContractError
    from .daemon import ServeDaemon
    from .loadgen import LoadSpec, run_session

    def _refuse(e, rc: int) -> int:
        print(json.dumps({"error": str(e),
                          "failure_kind": getattr(e, "kind", "crash")}),
              flush=True)
        return rc

    try:
        points = _load_points(args.points)
        # the serving problem pins the legacy external-query route: its
        # launches ride the executable cache (ops/query.launch_brute /
        # _launch_packed), which is what makes the zero-recompile law
        # countable (DESIGN.md section 13)
        problem = KnnProblem.prepare(points, KnnConfig(k=args.k,
                                                       adaptive=False))
        daemon = ServeDaemon(problem, ServeConfig(
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1000.0,
            compact_threshold=args.compact_threshold))
    except InputContractError as e:
        return _refuse(e, 5)
    except DeviceMemoryError as e:
        return _refuse(e, 4)

    from ..obs import spans as _spans
    from ..obs.metrics import JsonlEmitter

    trace_sink = _spans.start_file_trace_from_env("serve")
    emitter = None
    if args.metrics_jsonl:
        emitter = JsonlEmitter(args.metrics_jsonl,
                               period_s=args.metrics_period_s,
                               snapshot_fn=daemon.metrics_snapshot)
        emitter.start()
    try:
        if not args.loadgen:
            return _stdio_loop(daemon)

        spec = LoadSpec(rate=args.rate, requests=args.requests,
                        mutation_ratio=args.mutation_ratio, seed=args.seed)
        summary = run_session(daemon, spec)
        print(json.dumps(summary), flush=True)
        if args.assert_steady:
            ok = (summary["batches"] >= 1 and summary["recompiles"] == 0
                  and summary["exec_cache_enabled"]
                  and summary["failed_requests"] == 0)
            if not ok:
                print(f"STEADY-STATE ASSERTION FAILED: batches="
                      f"{summary['batches']} recompiles="
                      f"{summary['recompiles']} "
                      f"cache_enabled={summary['exec_cache_enabled']} "
                      f"failed={summary['failed_requests']}",
                      file=sys.stderr, flush=True)
                return 1
        return 0
    finally:
        if emitter is not None:
            emitter.stop()
        if trace_sink is not None:
            trace_sink.close()


if __name__ == "__main__":
    sys.exit(main())
