"""End-to-end differential driver: the framework's `test_knearests` equivalent.

Reference parity (C13 + C12, /root/reference/test_knearests.cu:117-235): load an
``.xyz`` point cloud (normalizing into the engine domain), dump device
properties, run the accelerated all-points kNN (timed, compile split out),
sanity-check the permutation and duplicate invariants, run the exact CPU oracle
(timed), and compare the two per point.  Differences by design: k and every
other knob are CLI flags instead of compile-time macros, comparison is
tie-aware (exact f32 ties accept either id), a recall@k summary is printed for
machine consumption, and ``--sharded N`` exercises the multi-chip slab path the
reference does not have.

Usage:
    python -m cuda_knearests_tpu.cli pts20K.xyz --k 10
    python -m cuda_knearests_tpu.cli 900k_blue_cube.xyz --k 20 --sharded 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def set_recall(got: np.ndarray, ref_ids: np.ndarray) -> float:
    """Order-insensitive recall@k: mean fraction of oracle ids recovered."""
    got_s = np.sort(got, axis=1)
    ref_s = np.sort(ref_ids, axis=1)
    n, k = got_s.shape
    hits = (got_s == ref_s).sum(axis=1).astype(np.float64)
    for i in np.nonzero((got_s != ref_s).any(axis=1))[0]:
        hits[i] = len(set(got_s[i].tolist()) & set(ref_s[i].tolist()))
    return float(hits.sum() / (n * k))


def _tie_aware_mismatches(points: np.ndarray, got: np.ndarray, ref_ids: np.ndarray,
                          ref_d2: np.ndarray) -> tuple[int, int]:
    """Count per-point neighbor-set disagreements, splitting exact-tie flips
    (acceptable) from hard mismatches (bugs).  Returns (ties, hard)."""
    got_s = np.sort(got, axis=1)
    ref_s = np.sort(ref_ids, axis=1)
    rows = np.nonzero((got_s != ref_s).any(axis=1))[0]
    ties = hard = 0
    for i in rows:
        diff = np.array(sorted(set(got_s[i].tolist()) ^ set(ref_s[i].tolist())))
        kth = float(ref_d2[i, -1])
        d2 = ((points[diff].astype(np.float64)
               - points[i].astype(np.float64)) ** 2).sum(-1)
        if np.allclose(d2, kth, rtol=2e-6, atol=0.0):
            ties += 1
        else:
            hard += 1
    return ties, hard


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="All-points kNN: TPU engine vs exact kd-tree oracle "
                    "(the reference test_knearests, rebuilt)")
    ap.add_argument("points", help=".xyz file path or known dataset name "
                    "(e.g. pts20K.xyz, 900k_blue_cube.xyz)")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--density", type=float, default=3.1)
    ap.add_argument("--ring-radius", type=int, default=None)
    ap.add_argument("--supercell", type=int, default=None,
                    help="query-tile side in cells (default: KnnConfig default)")
    ap.add_argument("--dist", choices=("diff", "dot"), default="diff")
    ap.add_argument("--sharded", type=int, default=0, metavar="N",
                    help="solve over an N-chip mesh (slab + halo exchange)")
    ap.add_argument("--no-oracle", action="store_true",
                    help="skip the CPU oracle comparison (benchmark mode)")
    ap.add_argument("--json", action="store_true", help="emit a JSON summary line")
    ap.add_argument("--serve", type=float, default=None, metavar="RATE",
                    help="serve the loaded cloud instead of solving it: "
                         "run the open-loop load harness (Poisson arrivals "
                         "at RATE/sec through the dynamic-batching daemon, "
                         "serve/) and print the serving summary JSON.  "
                         "rc 0 iff every request completed (typed "
                         "invalid-input refusals excluded; a failed batch "
                         "fails its riders and therefore the rc)")
    ap.add_argument("--serve-requests", type=int, default=200,
                    help="with --serve: scheduled arrivals (default 200)")
    args = ap.parse_args(argv)

    # Bounded-time backend acquisition BEFORE the first jax touch: with the
    # accelerator transport down, backend init hangs instead of erroring (the
    # exact environment failure mode bench.py guards against) -- probe in a
    # subprocess and pin cpu on persistent failure, so the driver always
    # terminates.  JAX_PLATFORMS=cpu short-circuits the probe entirely.
    from .utils.platform import (acquire_backend, enable_compile_cache,
                                 honor_jax_platforms_env)
    from .utils import watchdog
    from .utils.platform import _probe_default_backend

    def _probe(timeout_s):
        # each bounded probe return is forward progress; without this a
        # legitimately long probe-and-backoff acquisition (overridden tries
        # or timeouts) would trip the stall limit mid-acquisition
        res = _probe_default_backend(timeout_s)
        watchdog.heartbeat()
        return res

    watchdog.start(tag="cli")  # a dead-tunnel hang must exit, not pin
    platform, backend_note = acquire_backend(probe=_probe)
    watchdog.heartbeat()  # bounded acquisition completed
    if platform == "cpu":
        watchdog.disable()  # local work cannot hang on the transport
    honor_jax_platforms_env()
    enable_compile_cache()  # remote-tunnel compiles persist across runs

    from . import KnnConfig, KnnProblem
    from .io import get_dataset, load_xyz, normalize_points
    from .utils.devinfo import print_device_properties
    from .utils.stopwatch import Stopwatch, timed

    print_device_properties()

    # Classified refusal containment, one machine-readable shape for both
    # exits: rc 5 for input-contract violations (io.validate_or_raise and
    # friends -- bad file, NaN coordinates, illegal k; deterministic,
    # caller must fix the input) and rc 4 for classified device errors
    # (preflight refusals, transport death -- the PR 2 path).
    from .utils.memory import InputContractError

    def _refuse(e, summary: dict, rc: int) -> int:
        summary.update(error=str(e), failure_kind=e.kind)
        print(json.dumps(summary), flush=True)
        print(f"REFUSED [{e.kind}]: {e}", file=sys.stderr, flush=True)
        return rc

    try:
        if os.path.exists(args.points):
            points = normalize_points(load_xyz(args.points))
        else:
            points = get_dataset(args.points)
    except InputContractError as e:
        return _refuse(e, {"k": args.k, "platform": platform}, 5)
    n = points.shape[0]
    print(f"loaded {n} points -> [0,1000]^3")

    cfg_kw = {} if args.supercell is None else {"supercell": args.supercell}
    cfg = KnnConfig(k=args.k, density=args.density, ring_radius=args.ring_radius,
                    dist_method=args.dist, **cfg_kw)
    summary = {"n": n, "k": args.k,
               "mode": ("serve" if args.serve is not None else
                        "sharded" if args.sharded else "single"),
               "platform": platform}
    if backend_note:
        summary["backend_note"] = backend_note

    if args.serve is not None:
        # serving mode: the daemon + open-loop harness instead of the
        # one-shot differential solve; same typed rc 4/5 containment
        from .utils.memory import DeviceMemoryError, InputContractError
        from .config import ServeConfig
        from .serve import LoadSpec, ServeDaemon, run_session
        import dataclasses as _dc

        try:
            # the serving route is the legacy external-query path (its
            # launches ride the executable cache; serve/__main__.py has
            # the same pin)
            problem = KnnProblem.prepare(
                points, _dc.replace(cfg, adaptive=False))
            daemon = ServeDaemon(problem, ServeConfig())
        except InputContractError as e:
            return _refuse(e, summary, 5)
        except DeviceMemoryError as e:
            return _refuse(e, summary, 4)
        watchdog.disable()  # open-loop pacing, not a stall
        result = run_session(daemon, LoadSpec(rate=args.serve,
                                              requests=args.serve_requests))
        summary.update(result)
        print(json.dumps(summary), flush=True)
        return 0 if result["failed_requests"] == 0 else 1

    # --- accelerated solve (reference "knn gpu" phase, test_knearests.cu:136) ---
    # Classified failure containment: a preflight refusal (LaunchBudgetError,
    # kind 'oom') or a transient tunnel death (TransportError, kind
    # 'transport') exits rc 4 with a machine-readable line carrying
    # failure_kind, so the supervisor/watcher can classify the run without
    # parsing a traceback -- instead of the stack trace + rc 1 a crash gives.
    from .utils.memory import DeviceMemoryError
    try:
        if args.sharded:
            from .parallel.sharded import ShardedKnnProblem
            with Stopwatch("prepare (grid + slab plan)"):
                sp = ShardedKnnProblem.prepare(points, n_devices=args.sharded,
                                               config=cfg)
            watchdog.heartbeat()
            # device-side steady state, compile split out -- same convention
            # (and the same JSON summary schema) as the single-chip branch
            dev_out, t = timed(lambda: sp.solve_device(), warmup=1, iters=1)
            watchdog.heartbeat()
            print(f"solve (sharded): compile+first {t['warmup_s']:.3f}s, "
                  f"steady {t['min_s']:.3f}s "
                  f"({n / t['min_s']:.0f} queries/sec)")
            summary["solve_s"] = t["min_s"]
            summary["qps"] = n / t["min_s"]
            with Stopwatch("assemble (host readback)"):
                neighbors, d2, cert = sp.solve(device_out=dev_out)
            perm = sp.permutation()
        else:
            with Stopwatch("prepare (grid + plan)"):
                problem = KnnProblem.prepare(points, cfg)
            watchdog.heartbeat()
            _, t = timed(lambda: problem.solve(), warmup=1, iters=1)
            watchdog.heartbeat()
            print(f"solve: compile+first {t['warmup_s']:.3f}s, "
                  f"steady {t['min_s']:.3f}s "
                  f"({n / t['min_s']:.0f} queries/sec)")
            summary["solve_s"] = t["min_s"]
            summary["qps"] = n / t["min_s"]
            problem.print_stats()
            neighbors = problem.get_knearests_original()
            perm = problem.get_permutation()
    except InputContractError as e:
        # before DeviceMemoryError: NonFiniteInputError is both taxonomies,
        # and the input-contract reading (rc 5, caller must fix the input)
        # is the actionable one
        return _refuse(e, summary, 5)
    except DeviceMemoryError as e:
        return _refuse(e, summary, 4)

    # device work done; the remaining phases (oracle, tie analysis) are
    # local CPU and may legitimately exceed the stall limit at k=50
    watchdog.heartbeat()
    watchdog.disable()

    # --- sanity: permutation bijection (test_knearests.cu:162-168) -------------
    assert np.array_equal(np.sort(perm), np.arange(n)), "permutation not a bijection"
    # --- sanity: no duplicate neighbor ids (test_knearests.cu:174-191) ---------
    valid = neighbors >= 0
    srt = np.sort(np.where(valid, neighbors, np.arange(n)[:, None] + n), axis=1)
    dupes = int(((np.diff(srt, axis=1) == 0) & valid[:, 1:]).sum())
    print(f"duplicate-neighbor check: {dupes} duplicates")
    assert dupes == 0, "duplicate neighbor ids found"

    # --- exact oracle comparison (test_knearests.cu:194-232) -------------------
    if not args.no_oracle:
        from .oracle import KdTreeOracle
        with Stopwatch("knn cpu (kd-tree oracle)"):
            oracle = KdTreeOracle(points)
            ref_ids, ref_d2 = oracle.knn_all_points(k=args.k)
        ties, hard = _tie_aware_mismatches(points, neighbors, ref_ids, ref_d2)
        matched = n - ties - hard
        recall = set_recall(neighbors, ref_ids)
        print(f"oracle comparison: {matched}/{n} exact, {ties} tie flips, "
              f"{hard} hard mismatches; recall@{args.k} = {recall:.6f}")
        summary.update(exact=matched, ties=ties, hard=hard,
                       recall=float(recall))
        if hard:
            print("FAILED", file=sys.stderr)
            return 1
    print("OK")
    if args.json:
        print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
